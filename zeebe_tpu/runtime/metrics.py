"""Metrics: registry + prometheus text rendering + periodic file writer.

Reference parity: ``util/.../metrics/MetricsManager.java`` (allocate
counters with name + labels, ``dump`` renders Prometheus text format) and
``broker-core/.../system/metrics/MetricsFileWriter.java:34-90`` (an actor
flushes the registry to ``metrics/zeebe.prom`` every 5s; scraped via node
exporter). Counters are used throughout the broker: records processed /
skipped / written per stream processor (``StreamProcessorMetrics``),
workflow-instance counts (``WorkflowInstanceMetrics``), transport and
scheduler internals.
"""

from __future__ import annotations

import logging
import os
import threading
import time
from typing import Dict, Iterable, List, Optional, Tuple

from zeebe_tpu.runtime.actors import Actor, ActorScheduler


class Metric:
    """A counter/gauge with fixed labels. Increment-only use makes it a
    counter; ``set`` makes it a gauge — prometheus typing is emitted from
    ``kind``."""

    __slots__ = ("name", "labels", "kind", "_value", "_lock")

    def __init__(self, name: str, labels: Tuple[Tuple[str, str], ...], kind: str):
        self.name = name
        self.labels = labels
        self.kind = kind
        self._value = 0.0
        self._lock = threading.Lock()

    def inc(self, delta: float = 1.0) -> None:
        with self._lock:
            self._value += delta

    def set(self, value: float) -> None:
        with self._lock:
            self._value = value

    @property
    def value(self) -> float:
        return self._value


class Histogram:
    """A cumulative-bucket histogram with fixed labels (prometheus
    `histogram` type: `_bucket{le=...}`, `_sum`, `_count` series). Used by
    the metrics exporter for per-ValueType/intent export latencies."""

    DEFAULT_BUCKETS = (1, 5, 10, 25, 50, 100, 250, 500, 1000, 5000, 30000)

    __slots__ = ("name", "labels", "buckets", "_counts", "_sum", "_count", "_lock")

    def __init__(
        self,
        name: str,
        labels: Tuple[Tuple[str, str], ...],
        buckets: Tuple[float, ...] = DEFAULT_BUCKETS,
    ):
        self.name = name
        self.labels = labels
        self.buckets = tuple(sorted(buckets))
        self._counts = [0] * len(self.buckets)
        self._sum = 0.0
        self._count = 0
        self._lock = threading.Lock()

    def observe(self, value: float) -> None:
        with self._lock:
            self._sum += value
            self._count += 1
            for i, le in enumerate(self.buckets):
                if value <= le:
                    self._counts[i] += 1

    @property
    def count(self) -> int:
        return self._count

    @property
    def sum(self) -> float:
        return self._sum

    def render(self, prefix: str, ts: int) -> List[str]:
        base = ",".join(f'{k}="{v}"' for k, v in self.labels)
        sep = "," if base else ""
        lines = []
        with self._lock:
            # _counts are cumulative already (observe bumps every bucket
            # whose bound covers the value) — prometheus `le` semantics
            for le, n in zip(self.buckets, self._counts):
                lines.append(
                    f'{prefix}{self.name}_bucket{{{base}{sep}le="{le:g}"}} '
                    f"{n} {ts}"
                )
            lines.append(
                f'{prefix}{self.name}_bucket{{{base}{sep}le="+Inf"}} '
                f"{self._count} {ts}"
            )
            suffix = f"{{{base}}}" if base else ""
            lines.append(f"{prefix}{self.name}_sum{suffix} {self._sum:g} {ts}")
            lines.append(f"{prefix}{self.name}_count{suffix} {self._count} {ts}")
        return lines


class MetricsRegistry:
    """Reference MetricsManager: allocate once, render many."""

    def __init__(self, prefix: str = "zb_"):
        self.prefix = prefix
        self._metrics: Dict[Tuple[str, Tuple[Tuple[str, str], ...]], Metric] = {}
        self._histograms: Dict[Tuple[str, Tuple[Tuple[str, str], ...]], Histogram] = {}
        self._help: Dict[str, str] = {}
        self._lock = threading.Lock()

    def counter(self, name: str, help_text: str = "", **labels: str) -> Metric:
        return self._allocate(name, "counter", help_text, labels)

    def gauge(self, name: str, help_text: str = "", **labels: str) -> Metric:
        return self._allocate(name, "gauge", help_text, labels)

    def histogram(
        self,
        name: str,
        help_text: str = "",
        buckets: Tuple[float, ...] = Histogram.DEFAULT_BUCKETS,
        **labels: str,
    ) -> Histogram:
        key = (name, tuple(sorted(labels.items())))
        with self._lock:
            h = self._histograms.get(key)
            if h is None:
                h = Histogram(name, key[1], buckets)
                self._histograms[key] = h
            elif h.buckets != tuple(sorted(buckets)):
                # allocate-once semantics: the first caller's buckets win
                # for the process lifetime (observations already landed in
                # them) — silently dropping a DIFFERENT buckets arg would
                # let an operator believe a changed latency_buckets config
                # took effect when it did not
                logging.getLogger(__name__).warning(
                    "histogram %r already allocated with buckets %s; "
                    "ignoring different buckets %s (restart the process "
                    "to change histogram buckets)",
                    name, h.buckets, tuple(sorted(buckets)),
                )
            if help_text:
                self._help[name] = help_text
            return h

    def _allocate(self, name: str, kind: str, help_text: str, labels: Dict[str, str]) -> Metric:
        key = (name, tuple(sorted(labels.items())))
        with self._lock:
            metric = self._metrics.get(key)
            if metric is None:
                metric = Metric(name, key[1], kind)
                self._metrics[key] = metric
            if help_text:
                self._help[name] = help_text
            return metric

    def dump(self, now_ms: Optional[int] = None) -> str:
        """Prometheus text format (reference MetricsManager.dump renders
        `name{label="v",...} value timestamp`)."""
        ts = now_ms if now_ms is not None else int(time.time() * 1000)
        by_name: Dict[str, List[Metric]] = {}
        hists_by_name: Dict[str, List[Histogram]] = {}
        with self._lock:
            for metric in self._metrics.values():
                by_name.setdefault(metric.name, []).append(metric)
            for hist in self._histograms.values():
                hists_by_name.setdefault(hist.name, []).append(hist)
        lines: List[str] = []
        for name in sorted(by_name):
            full = self.prefix + name
            if name in self._help:
                lines.append(f"# HELP {full} {self._help[name]}")
            lines.append(f"# TYPE {full} {by_name[name][0].kind}")
            for metric in by_name[name]:
                if metric.labels:
                    label_str = ",".join(f'{k}="{v}"' for k, v in metric.labels)
                    lines.append(f"{full}{{{label_str}}} {metric.value:g} {ts}")
                else:
                    lines.append(f"{full} {metric.value:g} {ts}")
        for name in sorted(hists_by_name):
            full = self.prefix + name
            if name in self._help:
                lines.append(f"# HELP {full} {self._help[name]}")
            lines.append(f"# TYPE {full} histogram")
            for hist in hists_by_name[name]:
                lines.extend(hist.render(self.prefix, ts))
        return "\n".join(lines) + "\n"


# -- process-global event counters ------------------------------------------
# Low-level components (transport, log storage, snapshot storage, raft) have
# no broker registry in reach — they are constructed in many places, some
# (raft's own ClientTransport) several layers away from the broker. Chaos-
# relevant events from those layers count into one process-global registry
# instead, merged into every /metrics dump and metrics-file flush via
# ``render_with_global``. Names used today: raft_elections_started,
# raft_elections_won, transport_reconnects, transport_pending_expired,
# log_torn_tail_truncations, snapshot_salvage_events; exporter plane:
# exporter_lag (gauge, per exporter/partition), exporter_records_exported,
# exporter_export_failures, exporter_floor_stalls, exporter_open_failures,
# exporter_skipped_compacted; snapshot lifecycle (docs/STATE.md):
# snapshot_last_new_bytes / snapshot_last_total_bytes /
# snapshot_take_seconds / snapshot_capture_pause_seconds /
# snapshot_restore_seconds (gauges), snapshot_full_takes,
# snapshot_delta_takes, snapshot_take_failures, snapshot_skipped_inflight,
# snapshot_recover_skipped; columnar record plane (docs/SERVING.md):
# serving_rows_materialized_total — Record objects lazily materialized from
# columnar batch views (protocol/columnar.py); 0 on the pure host wave
# path, where every row is an engine-built Record already; tracing plane
# (docs/operations/tracing.md): raft_commit_stalls,
# raft_appends_truncated, serving_commit_stalls, serving_slow_waves,
# flight_recorder_dumps.
GLOBAL_REGISTRY = MetricsRegistry()


def global_counter(name: str, help_text: str = "", **labels: str) -> Metric:
    return GLOBAL_REGISTRY.counter(name, help_text, **labels)


def global_gauge(name: str, help_text: str = "", **labels: str) -> Metric:
    """Labeled process-global gauge (exporter lag per exporter/partition,
    etc.) — merged into every /metrics dump via ``render_with_global``."""
    return GLOBAL_REGISTRY.gauge(name, help_text, **labels)


def count_event(name: str, help_text: str = "", delta: float = 1.0) -> None:
    """Bump a process-global event counter (allocate-on-first-use)."""
    GLOBAL_REGISTRY.counter(name, help_text).inc(delta)


def event_count(name: str) -> float:
    """Current value of a global event counter (0 if never bumped)."""
    return GLOBAL_REGISTRY.counter(name).value


# -- serving-plane wave instrumentation --------------------------------------
# The pipelined batched drain (runtime/broker.run_until_idle waves,
# cluster_broker.PartitionServer._process_committed chunks) reports each
# dispatched wave here: fill + occupancy gauges localize "the pipeline is
# running empty" vs "the device is the bottleneck" without a profiler, and
# the host/device second counters give the time split the serving bench
# prints. Handles are cached — this sits on the drain hot loop.
_WAVE_HANDLES: dict = {}


def _wave_handles() -> dict:
    if not _WAVE_HANDLES:
        g = GLOBAL_REGISTRY
        _WAVE_HANDLES.update(
            waves=g.counter(
                "serving_waves_total",
                "Committed-record drain waves dispatched to the engine",
            ),
            records=g.counter(
                "serving_wave_records_total",
                "Committed records drained through waves",
            ),
            fill=g.gauge(
                "serving_wave_fill", "Records in the most recent drain wave"
            ),
            fill_mean=g.gauge(
                "serving_wave_fill_mean",
                "Mean records per drain wave since process start",
            ),
            occupancy=g.gauge(
                "serving_wave_occupancy",
                "Most recent wave's fill fraction of the drain-chunk capacity",
            ),
            host_s=g.counter(
                "serving_host_seconds_total",
                "Serving-path host seconds (staging, host-routed records, "
                "emission materialization)",
            ),
            device_s=g.counter(
                "serving_device_seconds_total",
                "Serving-path seconds blocked on device outputs",
            ),
        )
    return _WAVE_HANDLES


def observe_wave(
    records: int,
    capacity: int,
    host_seconds: float = 0.0,
    device_seconds: float = 0.0,
) -> None:
    """Record one committed-record drain wave (process-global; shows up on
    every /metrics dump and metrics file via ``render_with_global``)."""
    h = _wave_handles()
    h["waves"].inc()
    h["records"].inc(records)
    h["fill"].set(records)
    h["fill_mean"].set(h["records"].value / max(h["waves"].value, 1.0))
    if capacity > 0:
        h["occupancy"].set(records / capacity)
    if host_seconds > 0:
        h["host_s"].inc(host_seconds)
    if device_seconds > 0:
        h["device_s"].inc(device_seconds)


# -- shared-wave scheduler instrumentation -----------------------------------
# The cross-partition wave scheduler (zeebe_tpu/scheduler/) reports each
# SHARED wave here on top of the plain wave series: how many partitions
# contributed (the fill-by-traffic-mix view — high fill with many sources
# is the scheduler doing its job; high fill from one source is just a
# firehose), plus its own backpressure/shed counters (allocated on first
# use via count_event / the admission controller).
_SCHED_HANDLES: dict = {}


def _sched_handles() -> dict:
    if not _SCHED_HANDLES:
        g = GLOBAL_REGISTRY
        _SCHED_HANDLES.update(
            shared_waves=g.counter(
                "scheduler_shared_waves_total",
                "Shared waves packed across partitions by the wave scheduler",
            ),
            sources=g.gauge(
                "serving_wave_sources",
                "Partitions contributing records to the most recent shared "
                "wave",
            ),
            sources_total=g.counter(
                "scheduler_wave_sources_total",
                "Sum of contributing partitions over all shared waves "
                "(mean = this / scheduler_shared_waves_total)",
            ),
            sources_mean=g.gauge(
                "serving_wave_sources_mean",
                "Mean partitions per shared wave since process start",
            ),
        )
    return _SCHED_HANDLES


def observe_shared_wave(
    records: int,
    capacity: int,
    sources: int,
    host_seconds: float = 0.0,
    device_seconds: float = 0.0,
) -> None:
    """Record one SHARED drain wave (scheduler path): the plain wave
    series (fill/occupancy/time split) plus the traffic-mix gauges."""
    observe_wave(records, capacity, host_seconds, device_seconds)
    h = _sched_handles()
    h["shared_waves"].inc()
    h["sources"].set(sources)
    h["sources_total"].inc(sources)
    h["sources_mean"].set(
        h["sources_total"].value / max(h["shared_waves"].value, 1.0)
    )


# -- mesh serving instrumentation --------------------------------------------
# The mesh-sharded serving plane (scheduler/placement.DevicePlan) places
# leader partitions across devices; these series prove the spread is real:
# per-device wave/record/occupancy/time-split (labeled by plan device
# index) and the per-shared-wave distinct-device count — ">1 device active
# per scheduling round" is serving_wave_devices_mean > 1.
_DEVICE_WAVE_HANDLES: dict = {}
_MESH_WAVE_HANDLES: dict = {}


def _device_wave_handles(device: str) -> dict:
    h = _DEVICE_WAVE_HANDLES.get(device)
    if h is None:
        g = GLOBAL_REGISTRY
        h = dict(
            waves=g.counter(
                "serving_device_waves_total",
                "Wave segments dispatched to each mesh device",
                device=device,
            ),
            records=g.counter(
                "serving_device_records_total",
                "Records processed per mesh device",
                device=device,
            ),
            share=g.gauge(
                "serving_device_wave_share",
                "Share of the most recent shared wave's records that "
                "landed on each mesh device (balance view; ~1/active "
                "devices under uniform load)",
                device=device,
            ),
            host_s=g.counter(
                "serving_device_host_seconds_total",
                "Host seconds spent staging/collecting per mesh device",
                device=device,
            ),
            device_s=g.counter(
                "serving_device_device_seconds_total",
                "Seconds blocked on each mesh device's outputs",
                device=device,
            ),
        )
        _DEVICE_WAVE_HANDLES[device] = h
    return h


def observe_device_wave(
    device_index: int,
    records: int,
    wave_total: int,
    host_seconds: float = 0.0,
    device_seconds: float = 0.0,
) -> None:
    """Record one wave segment landing on a mesh device (labeled by the
    DevicePlan index). ``wave_total`` is the WHOLE shared wave's record
    count — the share gauge reads balance across devices, not fill.
    Called by the wave scheduler per dispatched segment; engines without
    a plan placement (index < 0) are skipped."""
    if device_index < 0:
        return
    h = _device_wave_handles(str(device_index))
    h["waves"].inc()
    h["records"].inc(records)
    if wave_total > 0:
        h["share"].set(records / wave_total)
    if host_seconds > 0:
        h["host_s"].inc(host_seconds)
    if device_seconds > 0:
        h["device_s"].inc(device_seconds)


def observe_mesh_wave(devices_active: int) -> None:
    """Distinct mesh devices that received segments of one shared wave."""
    h = _MESH_WAVE_HANDLES
    if not h:
        g = GLOBAL_REGISTRY
        h.update(
            devices=g.gauge(
                "serving_wave_devices",
                "Mesh devices active in the most recent shared wave",
            ),
            devices_total=g.counter(
                "scheduler_wave_devices_total",
                "Sum of active mesh devices over all shared waves "
                "(mean = this / scheduler_shared_waves_total)",
            ),
            waves=g.counter("scheduler_shared_waves_total"),
            devices_mean=g.gauge(
                "serving_wave_devices_mean",
                "Mean mesh devices active per shared wave since process "
                "start (>1 = device compute overlaps across the mesh)",
            ),
        )
    h["devices"].set(devices_active)
    h["devices_total"].inc(devices_active)
    h["devices_mean"].set(
        h["devices_total"].value / max(h["waves"].value, 1.0)
    )


_SHARDED_WAVE_HANDLES: Dict[str, Metric] = {}
_SHARD_ROW_HANDLES: Dict[str, Metric] = {}
_SHARD_FILL_HANDLES: Dict[str, Metric] = {}
# edge-trigger for the skew warn log: one line per skew EPISODE, re-armed
# by the next balanced wave (flooding the log at wave rate would bury the
# signal the warn exists to surface)
_SKEW_WARNED = [False]

# waves skewed beyond this (max/mean routed rows) warn: one shard is
# doing >4x its fair share — the residency router's load-balance signal
SHARD_SKEW_WARN_RATIO = 4.0


def observe_sharded_wave(
    shard_rows, exchange_bytes: int, single_lane: bool = False
) -> None:
    """Record one wave dispatched through a SHARDED-state partition:
    ``shard_rows`` is the per-shard row count of the staged batch (owner
    lane fill under resident routing, advisory key-hash split under
    gathered — the balance signal operators watch for hot shards),
    ``exchange_bytes`` the wave's ACTUAL cross-shard volume (0 for waves
    that dispatched no records — idle/warm steps move nothing worth
    accounting). ``single_lane`` marks a RESIDENT-ROUTED wave: one lane
    holds everything BY DESIGN, so the skew gauge/warn skip it (the
    ratio would read num_shards on every healthy routed wave — skew is a
    key-hash-split signal, scored on gathered and fallback waves)."""
    h = _SHARDED_WAVE_HANDLES
    if not h:
        g = GLOBAL_REGISTRY
        h.update(
            waves=g.counter(
                "serving_sharded_waves_total",
                "Waves dispatched through the mesh-sharded step program",
            ),
            exchange=g.counter(
                "mesh_shard_exchange_bytes_total",
                "Cross-shard collective bytes moved by sharded-state waves "
                "(table gathers over the mesh axis, or boundary psum "
                "volume under resident routing)",
            ),
            skew=g.gauge(
                "mesh_shard_skew_ratio",
                "max/mean routed rows across the shard span for the most "
                "recent non-empty sharded wave (1.0 = perfectly balanced, "
                "num_shards = one shard takes everything)",
            ),
            skew_waves=g.counter(
                "mesh_shard_skewed_waves_total",
                "Sharded waves whose routed-row skew exceeded the 4x "
                "warn threshold (at meaningful fill)",
            ),
        )
    h["waves"].inc()
    if exchange_bytes > 0:
        h["exchange"].inc(exchange_bytes)
    total = 0
    peak = 0
    for i, rows in enumerate(shard_rows):
        rows = int(rows)
        total += rows
        peak = max(peak, rows)
        key = str(i)
        m = _SHARD_ROW_HANDLES.get(key)
        if m is None:
            m = GLOBAL_REGISTRY.gauge(
                "mesh_shard_rows",
                "Rows of the most recent sharded wave routed to each "
                "shard by key hash",
                device=key,
            )
            _SHARD_ROW_HANDLES[key] = m
        m.set(rows)
    nshards = max(len(shard_rows), 1)
    if total > 0 and not single_lane:
        ratio = peak * nshards / total  # max over mean
        h["skew"].set(ratio)
        # the warn gates on meaningful fill (>= 4 rows/shard on average):
        # a 3-record wave on 8 shards is ALWAYS "skewed" and means nothing
        if ratio > SHARD_SKEW_WARN_RATIO and total >= 4 * nshards:
            h["skew_waves"].inc()
            if not _SKEW_WARNED[0]:
                _SKEW_WARNED[0] = True
                logging.getLogger(__name__).warning(
                    "sharded wave skew %.1fx across %d shards (%d rows, "
                    "peak %d): one shard is doing >%gx its fair share — "
                    "resident routing is only as parallel as the "
                    "instance spread",
                    ratio, nshards, total, peak, SHARD_SKEW_WARN_RATIO,
                )
        else:
            _SKEW_WARNED[0] = False


def observe_shard_fill(plan_indices, fill) -> None:
    """Per-shard staged-row fill of one collected sharded-state segment,
    keyed by the PLAN device index each shard occupies (the scheduler's
    view — ``mesh_shard_rows`` above is keyed by shard ordinal, which
    every sharded partition shares)."""
    for d, rows in zip(plan_indices, fill):
        key = str(int(d))
        m = _SHARD_FILL_HANDLES.get(key)
        if m is None:
            m = GLOBAL_REGISTRY.gauge(
                "mesh_shard_wave_fill",
                "Staged rows the most recent collected sharded segment "
                "routed to this plan device",
                device=key,
            )
            _SHARD_FILL_HANDLES[key] = m
        m.set(int(rows))


def render_with_global(registry: MetricsRegistry, now_ms: Optional[int] = None) -> str:
    """A registry's Prometheus dump with the global event counters appended
    (skipped when the registry IS the global one — no duplicate series)."""
    text = registry.dump(now_ms)
    if registry is not GLOBAL_REGISTRY:
        text += GLOBAL_REGISTRY.dump(now_ms)
    return text


class MetricsHttpServer:
    """Serves ``GET /metrics`` with the registry's Prometheus text dump.

    The reference exposes the metrics file through a node exporter
    (prometheus/prometheus.yml + MetricsFileWriter); here the broker
    serves the same text directly so the compose stack needs no exporter
    sidecar."""

    def __init__(self, registry: MetricsRegistry, host: str = "0.0.0.0", port: int = 9600):
        import http.server

        registry_ref = registry

        class Handler(http.server.BaseHTTPRequestHandler):
            def do_GET(self):  # noqa: N802 - stdlib API name
                if self.path.rstrip("/") not in ("", "/metrics", "/healthz"):
                    self.send_response(404)
                    self.end_headers()
                    return
                body = render_with_global(registry_ref).encode("utf-8")
                self.send_response(200)
                self.send_header("Content-Type", "text/plain; version=0.0.4")
                self.send_header("Content-Length", str(len(body)))
                self.end_headers()
                self.wfile.write(body)

            def log_message(self, *args):  # quiet
                pass

        self._server = http.server.ThreadingHTTPServer((host, port), Handler)
        self.port = self._server.server_address[1]
        self._thread = threading.Thread(
            target=self._server.serve_forever, name="zb-metrics-http", daemon=True
        )
        self._thread.start()

    def close(self) -> None:
        self._server.shutdown()
        self._server.server_close()


class MetricsFileWriter(Actor):
    """Periodically dumps the registry to a file (reference
    MetricsFileWriter: temp-write then rename so scrapers never see a torn
    file)."""

    def __init__(
        self,
        registry: MetricsRegistry,
        path: str,
        scheduler: ActorScheduler,
        flush_period_ms: int = 5_000,
    ):
        super().__init__("metrics-file-writer")
        self.registry = registry
        self.path = path
        self.flush_period_ms = flush_period_ms
        scheduler.submit_actor(self, io_bound=True)  # zblint: disable=unobserved-actor-future (boot submit; start failures land in the scheduler failure ring)

    def on_actor_started(self) -> None:
        self.actor.run_at_fixed_rate(self.flush_period_ms, self.flush)

    def flush(self) -> None:
        os.makedirs(os.path.dirname(self.path) or ".", exist_ok=True)
        tmp = self.path + ".tmp"
        with open(tmp, "w") as f:
            f.write(render_with_global(self.registry))
        os.replace(tmp, self.path)
