"""Append-only event-sourced log (reference: ``logstreams/`` module)."""

from zeebe_tpu.log.storage import SegmentedLogStorage
from zeebe_tpu.log.logstream import LogStream, LogStreamReader, LogStreamWriter

__all__ = ["SegmentedLogStorage", "LogStream", "LogStreamReader", "LogStreamWriter"]
