"""Segmented append-only log storage.

Reference parity: ``logstreams/.../impl/log/fs/FsLogStorage.java`` (512 LoC;
segments, addresses = (segmentId, offset), block append, truncate, recovery
scan) and ``FsLogSegment.java``.

This is the pure-Python backend; ``native/log_storage.cc`` provides a C++
mmap backend with the same on-disk format (selected via
``SegmentedLogStorage(native=True)`` once built).
"""

from __future__ import annotations

import logging
import os
import struct
import zlib
from typing import Iterator, List, Optional, Tuple

from zeebe_tpu._events import count_event as _count_event

logger = logging.getLogger(__name__)

SEGMENT_MAGIC = 0x5A4C4F47  # "ZLOG"
SEGMENT_HEADER = struct.Struct("<IIq")  # magic, segment_id, start_offset_unused
SEGMENT_HEADER_SIZE = SEGMENT_HEADER.size

DEFAULT_SEGMENT_SIZE = 64 * 1024 * 1024  # reference default is 512M; smaller here

# Shared record-frame prefix (protocol/codec.py layout): u32 frame_length
# (total, including itself), u32 crc32 over bytes [8:frame_length). The
# storage layer validates this prefix on reopen to find a torn tail; the
# full decode stays the codec's concern.
_FRAME_PREFIX = struct.Struct("<iI")


def _has_resync_frame(data: bytes, start: int) -> bool:
    """Does any byte position after ``start`` begin a valid frame? True
    means the invalid region does not extend to EOF — intact frames follow
    the corruption, which a torn append can never produce (a crash leaves
    at most one partial frame, at the tail)."""
    for pos in range(start + 1, len(data) - _FRAME_PREFIX.size + 1):
        frame_len, crc = _FRAME_PREFIX.unpack_from(data, pos)
        if frame_len < _FRAME_PREFIX.size or pos + frame_len > len(data):
            continue
        if zlib.crc32(data[pos + 8 : pos + frame_len]) == crc:
            return True
    return False


class SegmentedLogStorage:
    """Append-only storage of opaque blocks across size-bounded segment files.

    Addresses are ``(segment_id << 32) | byte_offset`` — the reference packs
    (segmentId, offset) into a long the same way.

    ``native=True`` serves the same on-disk format through the C++ mmap
    backend (``native/log_storage.cc``); it requires the native toolchain
    (``zeebe_tpu.native.available()``) and raises when missing rather than
    silently falling back — an operator asking for the native backend
    should not unknowingly run the Python one.
    """

    def __new__(cls, directory: str, segment_size: int = DEFAULT_SEGMENT_SIZE,
                native: bool = False):
        if native and cls is SegmentedLogStorage:
            from zeebe_tpu import native as native_mod

            if not native_mod.available():
                raise RuntimeError(
                    "native log storage requested but the native layer is "
                    f"unavailable: {native_mod.build_error()}"
                )
            return native_mod.NativeLogStorage(directory, segment_size)
        return object.__new__(cls)

    def __init__(self, directory: str, segment_size: int = DEFAULT_SEGMENT_SIZE,
                 native: bool = False):
        del native  # handled by __new__ (this body only runs for the Python backend)
        self.directory = directory
        self.segment_size = segment_size
        os.makedirs(directory, exist_ok=True)
        self._segments: List[int] = []  # segment ids, sorted
        self._current_file = None
        self._current_id = -1
        self._current_size = 0
        self._open()

    # -- address packing ---------------------------------------------------
    @staticmethod
    def address(segment_id: int, offset: int) -> int:
        return (segment_id << 32) | offset

    @staticmethod
    def segment_of(address: int) -> int:
        return address >> 32

    @staticmethod
    def offset_of(address: int) -> int:
        return address & 0xFFFFFFFF

    # -- lifecycle ---------------------------------------------------------
    def _segment_path(self, segment_id: int) -> str:
        return os.path.join(self.directory, f"segment-{segment_id:06d}.log")

    def _open(self) -> None:
        existing = sorted(
            int(name[len("segment-") : -len(".log")])
            for name in os.listdir(self.directory)
            if name.startswith("segment-") and name.endswith(".log")
        )
        self._segments = existing
        if existing:
            last = existing[-1]
            path = self._segment_path(last)
            self._current_file = open(path, "r+b")
            self._current_file.seek(0, os.SEEK_END)
            self._current_size = self._current_file.tell()
            self._current_id = last
            self._truncate_torn_tail()
        else:
            self._roll_segment(0)

    def _truncate_torn_tail(self) -> None:
        """Crash recovery for the current (last) segment: walk its record
        frames validating the shared length+crc32 prefix and truncate the
        file to the last whole record. Without this, a torn append poisons
        replay — recovery's scan stops at the partial frame, but new appends
        land AFTER it, so every record written post-restart is unreachable.

        Only the last segment can be torn (appends never touch earlier
        ones). Opaque non-record payloads are left alone: if the FIRST frame
        after the header does not validate, the segment is treated as
        opaque and not scanned (raw-block users of this storage)."""
        f = self._current_file
        f.seek(0)
        header = f.read(SEGMENT_HEADER_SIZE)
        if len(header) < SEGMENT_HEADER_SIZE or (
            SEGMENT_HEADER.unpack(header)[0] != SEGMENT_MAGIC
        ):
            # crash during _roll_segment: the header itself is torn — the
            # segment never held a record, rewrite it empty
            logger.warning(
                "segment %s: torn header (%d bytes), rewriting empty",
                self._segment_path(self._current_id), len(header),
            )
            f.seek(0)
            f.truncate(0)
            f.write(SEGMENT_HEADER.pack(SEGMENT_MAGIC, self._current_id, 0))
            f.flush()
            self._current_size = SEGMENT_HEADER_SIZE
            _count_event("log_torn_tail_truncations")
            return
        data = f.read()
        offset = 0
        while offset < len(data):
            if len(data) - offset < _FRAME_PREFIX.size:
                break
            frame_len, crc = _FRAME_PREFIX.unpack_from(data, offset)
            if frame_len < _FRAME_PREFIX.size or offset + frame_len > len(data):
                break
            if zlib.crc32(data[offset + 8 : offset + frame_len]) != crc:
                break
            offset += frame_len
        if offset == 0 and data:
            return  # opaque content: never truncate what we can't parse
        valid_end = SEGMENT_HEADER_SIZE + offset
        if valid_end < SEGMENT_HEADER_SIZE + len(data):
            if _has_resync_frame(data, offset):
                # A later frame validates, so the invalid region does NOT
                # reach EOF: this is mid-file corruption (bitrot, external
                # tampering), not the single partial frame a crashed append
                # leaves. Truncation is still the only state that lets
                # replay and appends proceed — records are positionally
                # sequential, so the suffix is unreachable either way, and
                # raft re-replicates it from the leader — but it discards
                # INTACT frames, so escalate past the benign-tail warning.
                logger.error(
                    "segment %s: CRC failure at %d with valid frames after "
                    "it — mid-file corruption, not a torn tail; discarding "
                    "the suffix (%d bytes) including intact records",
                    self._segment_path(self._current_id), valid_end,
                    len(data) - offset,
                )
                _count_event("log_midfile_corruption")
            else:
                logger.warning(
                    "segment %s: torn tail at %d (%d bytes discarded)",
                    self._segment_path(self._current_id), valid_end,
                    len(data) - offset,
                )
            f.truncate(valid_end)
            f.flush()
            self._current_size = valid_end
            _count_event("log_torn_tail_truncations")

    def _roll_segment(self, segment_id: int) -> None:
        if self._current_file is not None:
            self._current_file.flush()
            self._current_file.close()
        path = self._segment_path(segment_id)
        self._current_file = open(path, "w+b")
        self._current_file.write(SEGMENT_HEADER.pack(SEGMENT_MAGIC, segment_id, 0))
        self._current_size = SEGMENT_HEADER_SIZE
        self._current_id = segment_id
        self._segments.append(segment_id)

    def close(self) -> None:
        if self._current_file is not None:
            self._current_file.flush()
            self._current_file.close()
            self._current_file = None

    def _ensure_open(self) -> None:
        """Reopen the current segment after ``close()``. An append can
        legally arrive after the storage was closed (broker shutdown races
        a late drain; seen as ``AttributeError: 'NoneType' ... 'seek'`` in
        the BENCH_r05 tail) — reopening is cheap and keeps the address
        sequence intact."""
        if self._current_file is None:
            self._current_file = open(self._segment_path(self._current_id), "r+b")
            self._current_file.seek(0, os.SEEK_END)
            self._current_size = self._current_file.tell()

    # -- append / read -----------------------------------------------------
    def append(self, block: bytes) -> int:
        """Append a block; returns its address."""
        self._ensure_open()
        if self._current_size + len(block) > self.segment_size and self._current_size > SEGMENT_HEADER_SIZE:
            self._roll_segment(self._current_id + 1)
        address = self.address(self._current_id, self._current_size)
        self._current_file.seek(self._current_size)
        self._current_file.write(block)
        self._current_size += len(block)
        return address

    def delete_segments_before(self, segment_id: int) -> int:
        """Delete whole segment files with id < ``segment_id`` (log
        compaction floor — reference: the broker deletes segments below the
        committed snapshot position). Never deletes the current segment.
        Returns the number of segments removed."""
        removed = 0
        for sid in list(self._segments):
            if sid >= segment_id or sid == self._current_id:
                break
            try:
                os.remove(self._segment_path(sid))
            except OSError:
                break
            self._segments.remove(sid)
            removed += 1
        return removed

    def flush(self) -> None:
        if self._current_file is not None:
            self._current_file.flush()
            os.fsync(self._current_file.fileno())
            # fsync count vs log_group_commit_coalesced = how well the
            # group-commit plane amortizes the durability round trip
            _count_event("log_fsyncs")

    def read(self, address: int, length: int) -> bytes:
        segment_id = self.segment_of(address)
        offset = self.offset_of(address)
        if segment_id == self._current_id and self._current_file is not None:
            self._current_file.flush()
        with open(self._segment_path(segment_id), "rb") as f:
            f.seek(offset)
            return f.read(length)

    def read_segment(self, segment_id: int) -> bytes:
        if segment_id == self._current_id and self._current_file is not None:
            self._current_file.flush()
        with open(self._segment_path(segment_id), "rb") as f:
            f.seek(SEGMENT_HEADER_SIZE)
            return f.read()

    def iter_blocks(self) -> Iterator[Tuple[int, bytes]]:
        """Recovery scan: yields (address, segment_bytes) per segment; framing
        of records inside the segment is the codec's concern."""
        for segment_id in list(self._segments):
            data = self.read_segment(segment_id)
            yield self.address(segment_id, SEGMENT_HEADER_SIZE), data

    def first_address(self) -> Optional[int]:
        if not self._segments:
            return None
        return self.address(self._segments[0], SEGMENT_HEADER_SIZE)

    # -- truncate (test/failure injection; reference FsLogStorage.truncate) --
    def reset(self) -> None:
        """Delete ALL segments and roll a fresh one (snapshot fast-forward:
        the installed snapshot supersedes everything on disk)."""
        if self._current_file is not None:
            self._current_file.close()
        self._current_file = None
        for sid in list(self._segments):
            try:
                os.unlink(self._segment_path(sid))
            except OSError:
                pass
        self._segments = []
        self._roll_segment(0)

    def truncate(self, address: int) -> None:
        self._ensure_open()
        segment_id = self.segment_of(address)
        offset = self.offset_of(address)
        for sid in [s for s in self._segments if s > segment_id]:
            os.unlink(self._segment_path(sid))
            self._segments.remove(sid)
        if self._current_id != segment_id:
            self._current_file.close()
            self._current_file = open(self._segment_path(segment_id), "r+b")
            self._current_id = segment_id
        self._current_file.truncate(offset)
        self._current_file.seek(offset)
        self._current_size = offset
