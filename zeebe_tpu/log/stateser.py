"""Data-only snapshot codec for engine state (no pickle, ever).

Snapshot payloads are fetched from cluster peers over the snapshot
replication protocol (``runtime/cluster_broker.py``), so they must be
treated as untrusted input. The reference replicates opaque RocksDB files
and never deserializes executable objects from peers
(``broker-core/.../clustering/base/snapshots/SnapshotReplicationService.java``);
this module is the equivalent stance for the host engine: every state
family is explicitly encoded to plain msgpack data
(``zeebe_tpu.protocol.msgpack``) and explicitly reconstructed — decoding
can only ever produce the fixed set of state types below.

Workflows are snapshotted as their deployed source resource (BPMN XML /
YAML) plus (key, version) and re-transformed on restore — the transform is
deterministic, so this both avoids serializing the executable graph and
keeps the snapshot wire format independent of transformer internals.

Numpy arrays (device-engine state) are supported via a tagged
``{dtype, shape, raw bytes}`` envelope, mirroring how the reference treats
RocksDB checkpoints as raw byte streams.
"""

from __future__ import annotations

import zlib
from typing import Any, Dict, List

import numpy as np

from zeebe_tpu.engine import interpreter as eng
from zeebe_tpu.engine.keyspace import KeyGenerator
from zeebe_tpu.protocol import msgpack
from zeebe_tpu.protocol.intents import WorkflowInstanceIntent as WI
from zeebe_tpu.protocol.records import (
    IncidentRecord,
    JobRecord,
    TimerRecord,
    WorkflowInstanceRecord,
)

# compressed-envelope magic: snapshots are mostly sparse fixed-capacity
# tables (device SoA state) — zlib turns multi-MB payloads into ~KBs,
# which matters on the chunked snapshot-replication wire
_ZMAGIC = b"ZBZ1"

FORMAT_HOST_V1 = "zbtpu-host-state-v1"
FORMAT_DEVICE_V1 = "zbtpu-device-state-v1"
FORMAT_RAW_V1 = "zbtpu-raw-state-v1"

# snapshots cross the wire during replication; refuse absurd payloads
# before decoding (the follower also caps what it buffers per transfer)
MAX_SNAPSHOT_BYTES = 1 << 31


class SnapshotFormatError(ValueError):
    """Payload is not a valid snapshot in a known format."""


# ---------------------------------------------------------------------------
# ndarray envelope (device state / bulk columns)
# ---------------------------------------------------------------------------

_ALLOWED_DTYPES = {
    "bool", "int8", "uint8", "int16", "int32", "int64",
    "uint32", "uint64", "float32", "float64",
}


def pack_ndarray(a: np.ndarray) -> dict:
    a = np.ascontiguousarray(a)
    if a.dtype.name not in _ALLOWED_DTYPES:
        raise SnapshotFormatError(f"unsupported dtype {a.dtype.name}")
    return {"__nd": a.dtype.name, "sh": list(a.shape), "b": a.tobytes()}


def unpack_ndarray(d: dict) -> np.ndarray:
    name = d.get("__nd")
    if name not in _ALLOWED_DTYPES:
        raise SnapshotFormatError(f"unsupported dtype {name!r}")
    shape = tuple(int(x) for x in d.get("sh", []))
    raw = d.get("b", b"")
    if not isinstance(raw, (bytes, bytearray)):
        raise SnapshotFormatError("ndarray payload is not bytes")
    a = np.frombuffer(raw, dtype=np.dtype(name))
    expected = int(np.prod(shape)) if shape else 1
    if a.size != expected:
        raise SnapshotFormatError("ndarray size mismatch")
    return a.reshape(shape).copy()


# ---------------------------------------------------------------------------
# host-engine state
# ---------------------------------------------------------------------------


def _enc_keygen(kg: KeyGenerator) -> dict:
    return {"n": kg.peek, "s": kg._step}


def _dec_keygen(d: dict) -> KeyGenerator:
    kg = KeyGenerator(int(d["n"]), int(d["s"]))
    return kg


def _enc_instances(index: eng.ElementInstanceIndex) -> List[dict]:
    # dict preserves insertion order, and a parent is always created before
    # its children, so a flat parent-key list round-trips the scope tree
    # (including children order).
    out = []
    for inst in index.instances.values():
        out.append({
            "k": inst.key,
            "p": inst.parent.key if inst.parent is not None else None,
            "s": int(inst.state) if inst.state is not None else None,
            "v": inst.value.to_document() if inst.value is not None else None,
            "j": inst.job_key,
            "t": inst.active_tokens,
            "a": inst.join_arrivals,
            "mo": inst.mi_outputs,
        })
    return out


def _dec_instances(items: List[Any]) -> eng.ElementInstanceIndex:
    index = eng.ElementInstanceIndex()
    for d in items:
        if not isinstance(d, dict):
            raise SnapshotFormatError("bad element instance entry")
        if d.get("p") is not None:
            parent = index.get(int(d["p"]))
            if parent is None:
                # a child without its parent means the payload is
                # internally inconsistent — fail the restore so
                # SnapshotController.recover falls back to an older
                # snapshot instead of silently promoting it to a root
                raise SnapshotFormatError(
                    f"element instance {d['k']} references missing parent {d['p']}"
                )
        else:
            parent = None
        inst = eng.ElementInstance(int(d["k"]), parent)
        inst.state = WI(int(d["s"])) if d.get("s") is not None else None
        inst.value = (
            WorkflowInstanceRecord.from_document(d["v"])
            if d.get("v") is not None else None
        )
        inst.job_key = int(d.get("j", -1))
        inst.active_tokens = int(d.get("t", 0))
        arrivals = d.get("a") or {}
        inst.join_arrivals = {
            int(gw): {int(fl): dict(payload) for fl, payload in flows.items()}
            for gw, flows in arrivals.items()
        }
        inst.mi_outputs = {int(c): v for c, v in (d.get("mo") or {}).items()}
        index.instances[inst.key] = inst
    return index


def _enc_workflows(workflows) -> List[dict]:
    out = []
    for wf in workflows:
        src = wf.source_resource
        if isinstance(src, str):
            src = src.encode("utf-8")
        out.append({
            "id": wf.id, "k": wf.key, "ver": wf.version,
            "src": src, "st": wf.source_type,
        })
    return out


def _dec_workflows(items: List[Any]):
    from zeebe_tpu.models.bpmn.xml import read_model
    from zeebe_tpu.models.bpmn.yaml_front import read_yaml_workflow
    from zeebe_tpu.models.transform.transformer import transform_model

    out = []
    for d in items:
        if not isinstance(d, dict):
            raise SnapshotFormatError("bad workflow entry")
        data = d.get("src", b"")
        if isinstance(data, str):
            data = data.encode("utf-8")
        if d.get("st") == "YAML_WORKFLOW":
            model = read_yaml_workflow(data.decode("utf-8"))
        else:
            model = read_model(data, strict=False)  # already accepted at deploy
        matched = False
        for wf in transform_model(model):
            if wf.id != d.get("id"):
                continue
            wf.key = int(d["k"])
            wf.version = int(d["ver"])
            wf.source_resource = data
            wf.source_type = d.get("st", "BPMN_XML")
            out.append(wf)
            matched = True
        if not matched:
            # the recorded id must come back out of the re-transform;
            # dropping the workflow silently would restore partial state
            raise SnapshotFormatError(
                f"workflow id {d.get('id')!r} not produced by re-transform"
            )
    return out


def encode_host_state(state: Dict[str, Any]) -> bytes:
    """Encode ``PartitionEngine.snapshot_state()`` output to safe bytes."""
    doc = {
        "fmt": FORMAT_HOST_V1,
        "wf_keys": _enc_keygen(state["wf_keys"]),
        "job_keys": _enc_keygen(state["job_keys"]),
        "incident_keys": _enc_keygen(state["incident_keys"]),
        "deployment_keys": _enc_keygen(state["deployment_keys"]),
        "element_instances": _enc_instances(state["element_instances"]),
        "jobs": {
            k: {"s": js.state, "d": js.deadline, "r": js.record.to_document()}
            for k, js in state["jobs"].items()
        },
        "incidents": {
            k: {"s": i.state, "ie": i.incident_event_position,
                "fe": i.failure_event_position}
            for k, i in state["incidents"].items()
        },
        "incident_by_activity": dict(state["incident_by_activity"]),
        "incident_by_failed_job": dict(state["incident_by_failed_job"]),
        "resolving_events": dict(state["resolving_events"]),
        "incident_records": {
            k: r.to_document() for k, r in state["incident_records"].items()
        },
        "messages": {
            k: {"k": m.key, "n": m.name, "c": m.correlation_key,
                "ttl": m.time_to_live, "p": m.payload, "id": m.message_id,
                "dl": m.deadline}
            for k, m in state["messages"].items()
        },
        "message_subscriptions": [
            {"n": s.message_name, "c": s.correlation_key,
             "pp": s.workflow_instance_partition_id,
             "wk": s.workflow_instance_key, "ak": s.activity_instance_key}
            for s in state["message_subscriptions"]
        ],
        "timers": {
            k: {"d": t.due_date, "a": t.activity_instance_key,
                "r": t.record.to_document()}
            for k, t in state["timers"].items()
        },
        "pending_boundary": {
            k: [bid, dict(payload)]
            for k, (bid, payload) in state.get("pending_boundary", {}).items()
        },
        # jobs that became activatable during a credit drought (the
        # engine's _awaiting_jobs backlog index, Dict[type, ordered key
        # set]); dropping it strands drought-backlogged jobs on a
        # snapshot-restored leader — backlog_activations would never
        # revisit them
        "awaiting_jobs": {
            job_type: list(keys)
            for job_type, keys in state.get("awaiting_jobs", {}).items()
        },
        "topic_sub_acks": dict(state["topic_sub_acks"]),
        # per-exporter acked positions; absent in pre-exporter snapshots
        "exporter_positions": dict(state.get("exporter_positions", {})),
        "topics": {k: dict(v) for k, v in state["topics"].items()},
        "next_partition_id": state["next_partition_id"],
        "last_processed_position": state["last_processed_position"],
        "workflows": _enc_workflows(state["workflows"]),
    }
    return msgpack.pack(doc)


def decode_host_state(payload: bytes) -> Dict[str, Any]:
    """Decode untrusted snapshot bytes back into the restore_state() dict.

    Raises SnapshotFormatError on anything that is not a well-formed v1
    host snapshot; never constructs anything beyond the fixed state types.
    """
    return _decode_host_doc(_unpack_checked(payload, FORMAT_HOST_V1))


def _unpack_checked(payload: bytes, expect_fmt: str) -> dict:
    if len(payload) > MAX_SNAPSHOT_BYTES:
        raise SnapshotFormatError("snapshot payload too large")
    try:
        doc = msgpack.unpack(payload)
    except Exception as e:
        raise SnapshotFormatError(f"undecodable snapshot: {e}") from None
    if not isinstance(doc, dict) or doc.get("fmt") != expect_fmt:
        raise SnapshotFormatError("unknown snapshot format")
    return doc


def _decode_host_doc(doc: dict) -> Dict[str, Any]:
    try:
        return {
            "wf_keys": _dec_keygen(doc["wf_keys"]),
            "job_keys": _dec_keygen(doc["job_keys"]),
            "incident_keys": _dec_keygen(doc["incident_keys"]),
            "deployment_keys": _dec_keygen(doc["deployment_keys"]),
            "element_instances": _dec_instances(doc["element_instances"]),
            "jobs": {
                int(k): eng.JobState(
                    state=int(v["s"]),
                    record=JobRecord.from_document(v["r"]),
                    deadline=int(v["d"]),
                )
                for k, v in doc["jobs"].items()
            },
            "incidents": {
                int(k): eng.IncidentState(
                    state=int(v["s"]),
                    incident_event_position=int(v["ie"]),
                    failure_event_position=int(v["fe"]),
                )
                for k, v in doc["incidents"].items()
            },
            "incident_by_activity": {
                int(k): int(v) for k, v in doc["incident_by_activity"].items()
            },
            "incident_by_failed_job": {
                int(k): int(v) for k, v in doc["incident_by_failed_job"].items()
            },
            "resolving_events": {
                int(k): int(v) for k, v in doc["resolving_events"].items()
            },
            "incident_records": {
                int(k): IncidentRecord.from_document(v)
                for k, v in doc["incident_records"].items()
            },
            "messages": {
                int(k): eng.StoredMessage(
                    key=int(v["k"]), name=str(v["n"]),
                    correlation_key=str(v["c"]), time_to_live=int(v["ttl"]),
                    payload=dict(v["p"]), message_id=str(v["id"]),
                    deadline=int(v["dl"]),
                )
                for k, v in doc["messages"].items()
            },
            "message_subscriptions": [
                eng.StoredSubscription(
                    message_name=str(s["n"]), correlation_key=str(s["c"]),
                    workflow_instance_partition_id=int(s["pp"]),
                    workflow_instance_key=int(s["wk"]),
                    activity_instance_key=int(s["ak"]),
                )
                for s in doc["message_subscriptions"]
            ],
            "timers": {
                int(k): eng.TimerState(
                    due_date=int(v["d"]),
                    activity_instance_key=int(v["a"]),
                    record=TimerRecord.from_document(v["r"]),
                )
                for k, v in doc["timers"].items()
            },
            "pending_boundary": {
                int(k): (str(v[0]), dict(v[1]))
                for k, v in doc.get("pending_boundary", {}).items()
            },
            # ordered key set per type (insertion-ordered dict of key ->
            # None, matching the engine's in-memory form); absent in
            # pre-round-6 snapshots
            "awaiting_jobs": {
                str(job_type): {int(k): None for k in keys}
                for job_type, keys in doc.get("awaiting_jobs", {}).items()
            },
            "topic_sub_acks": {
                str(k): int(v) for k, v in doc["topic_sub_acks"].items()
            },
            "exporter_positions": {
                str(k): int(v)
                for k, v in doc.get("exporter_positions", {}).items()
            },
            "topics": {str(k): dict(v) for k, v in doc["topics"].items()},
            "next_partition_id": int(doc["next_partition_id"]),
            "last_processed_position": int(doc["last_processed_position"]),
            "workflows": _dec_workflows(doc["workflows"]),
        }
    except SnapshotFormatError:
        raise
    except Exception as e:
        # includes parser errors from workflow-source re-transform (XML
        # ParseError, YAML errors): a snapshot that cannot be restored must
        # be SKIPPED by recovery (next older one is tried), never crash it
        raise SnapshotFormatError(f"malformed snapshot: {e}") from None


# ---------------------------------------------------------------------------
# generic entry points used by SnapshotController
# ---------------------------------------------------------------------------


def encode_state(state: Any) -> bytes:
    """Engine-state → bytes (zlib-compressed envelope). Dispatches on
    shape: a device-state envelope (dict with 'fmt' already set by the
    device engine) passes through its own encoder; a dict carrying
    KeyGenerators is host-engine state; any other plain-data value is
    wrapped raw (msgpack.pack rejects non-data objects, so nothing
    executable can sneak through this path either)."""
    if isinstance(state, dict) and state.get("fmt") == FORMAT_DEVICE_V1:
        raw = encode_device_state(state)
    elif isinstance(state, dict) and isinstance(state.get("wf_keys"), KeyGenerator):
        raw = encode_host_state(state)
    else:
        raw = msgpack.pack({"fmt": FORMAT_RAW_V1, "data": state})
    return _ZMAGIC + zlib.compress(raw, level=1)


def decode_state(payload: bytes) -> Any:
    if len(payload) > MAX_SNAPSHOT_BYTES:
        raise SnapshotFormatError("snapshot payload too large")
    if payload[:4] == _ZMAGIC:
        try:
            d = zlib.decompressobj()
            payload = d.decompress(payload[4:], MAX_SNAPSHOT_BYTES)
            if d.unconsumed_tail:
                raise SnapshotFormatError("snapshot decompresses too large")
        except zlib.error as e:
            raise SnapshotFormatError(f"corrupt snapshot: {e}") from None
    try:
        doc = msgpack.unpack(payload)
    except Exception as e:
        raise SnapshotFormatError(f"undecodable snapshot: {e}") from None
    if not isinstance(doc, dict):
        raise SnapshotFormatError("unknown snapshot format")
    fmt = doc.get("fmt")
    if fmt == FORMAT_HOST_V1:
        return _decode_host_doc(doc)
    if fmt == FORMAT_DEVICE_V1:
        return _decode_device_doc(doc)
    if fmt == FORMAT_RAW_V1:
        return doc.get("data")
    raise SnapshotFormatError(f"unknown snapshot format {fmt!r}")


# ---------------------------------------------------------------------------
# device-engine state (SoA tables + intern/varspace sidecars)
# ---------------------------------------------------------------------------


def encode_state_parts(state: Any) -> List[tuple]:
    """Engine-state → named parts for content-addressed checkpointing.

    The snapshot storage hashes each part and only writes segments it has
    not seen in a previous checkpoint (the TPU-native analogue of RocksDB
    checkpoints hard-linking unchanged SST files —
    ``logstreams/.../state/StateSnapshotController.java``). The split is
    chosen so the stable bulk dedupes:
    - device state: one part per SoA table array (fixed-capacity tables
      that did not change between checkpoints hash identically), plus the
      embedded host-oracle state and a small root part;
    - host state: deployed workflow resources (static after deployment)
      split from the mutable remainder;
    - anything else: a single legacy-encoded part.

    Returns ``[(name, bytes), ...]``; decode with ``decode_state_parts``.
    """
    if isinstance(state, dict) and state.get("fmt") == FORMAT_DEVICE_V1:
        parts = [
            (
                "_root",
                msgpack.pack(
                    {
                        "fmt": FORMAT_DEVICE_V1,
                        "meta": state.get("meta", {}),
                        "arrays": sorted(state.get("arrays", {}).keys()),
                    }
                ),
            )
        ]
        for name in sorted(state.get("arrays", {}).keys()):
            parts.append(
                ("a/" + name,
                 msgpack.pack(pack_ndarray(np.asarray(state["arrays"][name]))))
            )
        if state.get("host") is not None:
            parts.extend(
                ("h/" + n, b) for n, b in _host_state_parts(state["host"])
            )
        return parts
    if isinstance(state, dict) and isinstance(state.get("wf_keys"), KeyGenerator):
        return [("_root", msgpack.pack({"fmt": FORMAT_HOST_V1}))] + [
            ("h/" + n, b) for n, b in _host_state_parts(state)
        ]
    return [("state", encode_state(state))]


def _host_state_parts(state: Dict[str, Any]) -> List[tuple]:
    """Host engine state as (workflows, rest) parts: deployed resources are
    immutable after deployment, so the (often large) workflow part dedupes
    across every subsequent checkpoint."""
    doc = msgpack.unpack(encode_host_state(state))
    workflows = doc.pop("workflows", [])
    return [
        ("workflows", msgpack.pack({"workflows": workflows})),
        ("rest", msgpack.pack(doc)),
    ]


def _host_state_from_parts(parts: Dict[str, bytes], prefix: str) -> Dict[str, Any]:
    try:
        doc = msgpack.unpack(parts[prefix + "rest"])
        wf_doc = msgpack.unpack(parts[prefix + "workflows"])
        doc["workflows"] = wf_doc.get("workflows", [])
    except KeyError as e:
        raise SnapshotFormatError(f"snapshot part missing: {e}") from None
    except Exception as e:
        raise SnapshotFormatError(f"malformed snapshot part: {e}") from None
    if not isinstance(doc, dict) or doc.get("fmt") != FORMAT_HOST_V1:
        raise SnapshotFormatError("malformed host snapshot parts")
    return _decode_host_doc(doc)


def decode_state_parts(parts: Dict[str, bytes]) -> Any:
    """Reassemble ``encode_state_parts`` output (untrusted bytes)."""
    if sum(len(b) for b in parts.values()) > MAX_SNAPSHOT_BYTES:
        raise SnapshotFormatError("snapshot parts too large")
    if set(parts) == {"state"}:
        return decode_state(parts["state"])
    try:
        root = msgpack.unpack(parts["_root"])
    except KeyError:
        raise SnapshotFormatError("snapshot root part missing") from None
    except Exception as e:
        raise SnapshotFormatError(f"malformed snapshot root: {e}") from None
    if not isinstance(root, dict):
        raise SnapshotFormatError("malformed snapshot root")
    fmt = root.get("fmt")
    if fmt == FORMAT_HOST_V1:
        return _host_state_from_parts(parts, "h/")
    if fmt == FORMAT_DEVICE_V1:
        arrays: Dict[str, np.ndarray] = {}
        try:
            names = [str(n) for n in root.get("arrays", [])]
            for name in names:
                arrays[name] = unpack_ndarray(msgpack.unpack(parts["a/" + name]))
        except KeyError as e:
            raise SnapshotFormatError(f"snapshot part missing: {e}") from None
        except SnapshotFormatError:
            raise
        except Exception as e:
            raise SnapshotFormatError(f"malformed snapshot part: {e}") from None
        host = None
        if any(n.startswith("h/") for n in parts):
            host = _host_state_from_parts(parts, "h/")
        meta = root.get("meta", {})
        if not isinstance(meta, dict):
            raise SnapshotFormatError("malformed snapshot meta")
        return {
            "fmt": FORMAT_DEVICE_V1,
            "arrays": arrays,
            "meta": meta,
            "host": host,
        }
    raise SnapshotFormatError(f"unknown snapshot parts format {fmt!r}")


def encode_device_state(state: Dict[str, Any]) -> bytes:
    """Device snapshot envelope: {'fmt', 'arrays': {name: ndarray},
    'meta': plain-data dict, 'host': host-engine snapshot dict or None}.

    The embedded host oracle state (device engines keep one for
    device-ineligible records) rides along as its own encoded payload.
    """
    doc = {
        "fmt": FORMAT_DEVICE_V1,
        "arrays": {
            name: pack_ndarray(np.asarray(a))
            for name, a in state.get("arrays", {}).items()
        },
        "meta": state.get("meta", {}),
        "host": (
            encode_host_state(state["host"])
            if state.get("host") is not None else None
        ),
    }
    return msgpack.pack(doc)


def decode_device_state(payload: bytes) -> Dict[str, Any]:
    return _decode_device_doc(_unpack_checked(payload, FORMAT_DEVICE_V1))


def _decode_device_doc(doc: dict) -> Dict[str, Any]:
    try:
        return {
            "fmt": FORMAT_DEVICE_V1,
            "arrays": {
                str(k): unpack_ndarray(v) for k, v in doc["arrays"].items()
            },
            "meta": doc.get("meta", {}),
            "host": (
                decode_host_state(doc["host"])
                if doc.get("host") is not None else None
            ),
        }
    except SnapshotFormatError:
        raise
    except Exception as e:
        raise SnapshotFormatError(f"malformed snapshot: {e}") from None
