"""Data-only snapshot codec for engine state (no pickle, ever).

Snapshot payloads are fetched from cluster peers over the snapshot
replication protocol (``runtime/cluster_broker.py``), so they must be
treated as untrusted input. The reference replicates opaque RocksDB files
and never deserializes executable objects from peers
(``broker-core/.../clustering/base/snapshots/SnapshotReplicationService.java``);
this module is the equivalent stance for the host engine: every state
family is explicitly encoded to plain msgpack data
(``zeebe_tpu.protocol.msgpack``) and explicitly reconstructed — decoding
can only ever produce the fixed set of state types below.

Workflows are snapshotted as their deployed source resource (BPMN XML /
YAML) plus (key, version) and re-transformed on restore — the transform is
deterministic, so this both avoids serializing the executable graph and
keeps the snapshot wire format independent of transformer internals.

Numpy arrays (device-engine state) are supported via a tagged
``{dtype, shape, raw bytes}`` envelope, mirroring how the reference treats
RocksDB checkpoints as raw byte streams.
"""

from __future__ import annotations

import zlib
from typing import Any, Dict, Iterable, List, Optional, Tuple

import numpy as np

from zeebe_tpu.engine import interpreter as eng
from zeebe_tpu.engine.keyspace import KeyGenerator
from zeebe_tpu.protocol import msgpack
from zeebe_tpu.protocol.intents import WorkflowInstanceIntent as WI
from zeebe_tpu.protocol.records import (
    IncidentRecord,
    JobRecord,
    TimerRecord,
    WorkflowInstanceRecord,
)

# compressed-envelope magic: snapshots are mostly sparse fixed-capacity
# tables (device SoA state) — zlib turns multi-MB payloads into ~KBs,
# which matters on the chunked snapshot-replication wire
_ZMAGIC = b"ZBZ1"

FORMAT_HOST_V1 = "zbtpu-host-state-v1"
FORMAT_DEVICE_V1 = "zbtpu-device-state-v1"
FORMAT_RAW_V1 = "zbtpu-raw-state-v1"

# snapshots cross the wire during replication; refuse absurd payloads
# before decoding (the follower also caps what it buffers per transfer)
MAX_SNAPSHOT_BYTES = 1 << 31


class SnapshotFormatError(ValueError):
    """Payload is not a valid snapshot in a known format."""


# ---------------------------------------------------------------------------
# ndarray envelope (device state / bulk columns)
# ---------------------------------------------------------------------------

_ALLOWED_DTYPES = {
    "bool", "int8", "uint8", "int16", "int32", "int64",
    "uint32", "uint64", "float32", "float64",
}


def pack_ndarray(a: np.ndarray) -> dict:
    a = np.ascontiguousarray(a)
    if a.dtype.name not in _ALLOWED_DTYPES:
        raise SnapshotFormatError(f"unsupported dtype {a.dtype.name}")
    return {"__nd": a.dtype.name, "sh": list(a.shape), "b": a.tobytes()}


def unpack_ndarray(d: dict) -> np.ndarray:
    name = d.get("__nd")
    if name not in _ALLOWED_DTYPES:
        raise SnapshotFormatError(f"unsupported dtype {name!r}")
    shape = tuple(int(x) for x in d.get("sh", []))
    raw = d.get("b", b"")
    if not isinstance(raw, (bytes, bytearray)):
        raise SnapshotFormatError("ndarray payload is not bytes")
    a = np.frombuffer(raw, dtype=np.dtype(name))
    expected = int(np.prod(shape)) if shape else 1
    if a.size != expected:
        raise SnapshotFormatError("ndarray size mismatch")
    return a.reshape(shape).copy()


# ---------------------------------------------------------------------------
# host-engine state
# ---------------------------------------------------------------------------


def _enc_keygen(kg: KeyGenerator) -> dict:
    return {"n": kg.peek, "s": kg._step}


def _dec_keygen(d: dict) -> KeyGenerator:
    kg = KeyGenerator(int(d["n"]), int(d["s"]))
    return kg


def _enc_instances(index: eng.ElementInstanceIndex) -> List[dict]:
    # dict preserves insertion order, and a parent is always created before
    # its children, so a flat parent-key list round-trips the scope tree
    # (including children order).
    out = []
    for inst in index.instances.values():
        out.append({
            "k": inst.key,
            "p": inst.parent.key if inst.parent is not None else None,
            "s": int(inst.state) if inst.state is not None else None,
            "v": inst.value.to_document() if inst.value is not None else None,
            "j": inst.job_key,
            "t": inst.active_tokens,
            "a": inst.join_arrivals,
            "mo": inst.mi_outputs,
        })
    return out


def _dec_instances(items: List[Any]) -> eng.ElementInstanceIndex:
    index = eng.ElementInstanceIndex()
    for d in items:
        if not isinstance(d, dict):
            raise SnapshotFormatError("bad element instance entry")
        if d.get("p") is not None:
            parent = index.get(int(d["p"]))
            if parent is None:
                # a child without its parent means the payload is
                # internally inconsistent — fail the restore so
                # SnapshotController.recover falls back to an older
                # snapshot instead of silently promoting it to a root
                raise SnapshotFormatError(
                    f"element instance {d['k']} references missing parent {d['p']}"
                )
        else:
            parent = None
        inst = eng.ElementInstance(int(d["k"]), parent)
        inst.state = WI(int(d["s"])) if d.get("s") is not None else None
        inst.value = (
            WorkflowInstanceRecord.from_document(d["v"])
            if d.get("v") is not None else None
        )
        inst.job_key = int(d.get("j", -1))
        inst.active_tokens = int(d.get("t", 0))
        arrivals = d.get("a") or {}
        inst.join_arrivals = {
            int(gw): {int(fl): dict(payload) for fl, payload in flows.items()}
            for gw, flows in arrivals.items()
        }
        inst.mi_outputs = {int(c): v for c, v in (d.get("mo") or {}).items()}
        index.instances[inst.key] = inst
    return index


def _enc_workflows(workflows) -> List[dict]:
    out = []
    for wf in workflows:
        src = wf.source_resource
        if isinstance(src, str):
            src = src.encode("utf-8")
        out.append({
            "id": wf.id, "k": wf.key, "ver": wf.version,
            "src": src, "st": wf.source_type,
        })
    return out


def _dec_workflows(items: List[Any]):
    from zeebe_tpu.models.bpmn.xml import read_model
    from zeebe_tpu.models.bpmn.yaml_front import read_yaml_workflow
    from zeebe_tpu.models.transform.transformer import transform_model

    out = []
    for d in items:
        if not isinstance(d, dict):
            raise SnapshotFormatError("bad workflow entry")
        data = d.get("src", b"")
        if isinstance(data, str):
            data = data.encode("utf-8")
        if d.get("st") == "YAML_WORKFLOW":
            model = read_yaml_workflow(data.decode("utf-8"))
        else:
            model = read_model(data, strict=False)  # already accepted at deploy
        matched = False
        for wf in transform_model(model):
            if wf.id != d.get("id"):
                continue
            wf.key = int(d["k"])
            wf.version = int(d["ver"])
            wf.source_resource = data
            wf.source_type = d.get("st", "BPMN_XML")
            out.append(wf)
            matched = True
        if not matched:
            # the recorded id must come back out of the re-transform;
            # dropping the workflow silently would restore partial state
            raise SnapshotFormatError(
                f"workflow id {d.get('id')!r} not produced by re-transform"
            )
    return out


# Per-key encoders: each top-level key of the host snapshot doc has one
# explicit encoder so a DELTA take can encode a single state family
# without walking the clean ones (the family split below groups keys the
# engine dirties together).
_HOST_KEY_ENCODERS: Dict[str, Any] = {
    "wf_keys": lambda s: _enc_keygen(s["wf_keys"]),
    "job_keys": lambda s: _enc_keygen(s["job_keys"]),
    "incident_keys": lambda s: _enc_keygen(s["incident_keys"]),
    "deployment_keys": lambda s: _enc_keygen(s["deployment_keys"]),
    "element_instances": lambda s: _enc_instances(s["element_instances"]),
    "jobs": lambda s: {
        k: {"s": js.state, "d": js.deadline, "r": js.record.to_document()}
        for k, js in s["jobs"].items()
    },
    "incidents": lambda s: {
        k: {"s": i.state, "ie": i.incident_event_position,
            "fe": i.failure_event_position}
        for k, i in s["incidents"].items()
    },
    "incident_by_activity": lambda s: dict(s["incident_by_activity"]),
    "incident_by_failed_job": lambda s: dict(s["incident_by_failed_job"]),
    "resolving_events": lambda s: dict(s["resolving_events"]),
    "incident_records": lambda s: {
        k: r.to_document() for k, r in s["incident_records"].items()
    },
    "messages": lambda s: {
        k: {"k": m.key, "n": m.name, "c": m.correlation_key,
            "ttl": m.time_to_live, "p": m.payload, "id": m.message_id,
            "dl": m.deadline}
        for k, m in s["messages"].items()
    },
    "message_subscriptions": lambda s: [
        {"n": sub.message_name, "c": sub.correlation_key,
         "pp": sub.workflow_instance_partition_id,
         "wk": sub.workflow_instance_key, "ak": sub.activity_instance_key}
        for sub in s["message_subscriptions"]
    ],
    "timers": lambda s: {
        k: {"d": t.due_date, "a": t.activity_instance_key,
            "r": t.record.to_document()}
        for k, t in s["timers"].items()
    },
    "pending_boundary": lambda s: {
        k: [bid, dict(payload)]
        for k, (bid, payload) in s.get("pending_boundary", {}).items()
    },
    # jobs that became activatable during a credit drought (the
    # engine's _awaiting_jobs backlog index, Dict[type, ordered key
    # set]); dropping it strands drought-backlogged jobs on a
    # snapshot-restored leader — backlog_activations would never
    # revisit them
    "awaiting_jobs": lambda s: {
        job_type: list(keys)
        for job_type, keys in s.get("awaiting_jobs", {}).items()
    },
    "topic_sub_acks": lambda s: dict(s["topic_sub_acks"]),
    # per-exporter acked positions; absent in pre-exporter snapshots
    "exporter_positions": lambda s: dict(s.get("exporter_positions", {})),
    "topics": lambda s: {k: dict(v) for k, v in s["topics"].items()},
    "next_partition_id": lambda s: s["next_partition_id"],
    "last_processed_position": lambda s: s["last_processed_position"],
    "workflows": lambda s: _enc_workflows(s["workflows"]),
}

# Host state families: the unit of dirty tracking and of per-part delta
# encoding. Each family becomes its own snapshot part ("h/<family>"), so a
# take re-encodes and re-hashes only families the engine marked dirty.
# "control" is small and includes last_processed_position, so it is dirty
# on effectively every take; the bulk families (instances, jobs, messages)
# only pay when their state actually changed.
HOST_FAMILIES: Dict[str, Tuple[str, ...]] = {
    "workflows": ("workflows",),
    "instances": ("element_instances", "pending_boundary"),
    "jobs": ("jobs", "awaiting_jobs"),
    "incidents": ("incidents", "incident_by_activity",
                  "incident_by_failed_job", "resolving_events",
                  "incident_records"),
    "messages": ("messages", "message_subscriptions"),
    "timers": ("timers",),
    "control": ("wf_keys", "job_keys", "incident_keys", "deployment_keys",
                "topic_sub_acks", "exporter_positions", "topics",
                "next_partition_id", "last_processed_position"),
}

# Device SoA arrays group into dtype/table families (the wave staging
# transfer unit); clean families skip the device→host readback entirely.
_DEVICE_FAMILY_PREFIXES: Tuple[Tuple[str, str], ...] = (
    ("free_ei", "ei"), ("free_job", "job"), ("ei", "ei"), ("job", "job"),
    ("join", "join"), ("timer", "timer"), ("msub", "msub"), ("msg", "msg"),
    ("sub", "sub"), ("next", "keys"),
)

DEVICE_ARRAY_FAMILIES = tuple(sorted({f for _, f in _DEVICE_FAMILY_PREFIXES}))


def device_array_family(field: str) -> str:
    """Dirty-tracking family of a device state field (or hashtable
    ``<field>.keys``/``.vals`` part name)."""
    base = field.split(".", 1)[0]
    for prefix, family in _DEVICE_FAMILY_PREFIXES:
        if base == prefix or base.startswith(prefix + "_"):
            return family
    return "other"


def part_family(name: str) -> Optional[str]:
    """Dirty-tracking family of a snapshot part name, or None for parts
    that are re-encoded on every take (the small ``_root``, legacy
    single-blob ``state``)."""
    if name.startswith("h/"):
        return name
    if name.startswith("a/"):
        return "d/" + device_array_family(name[2:])
    return None


def _enc_host_family(state: Dict[str, Any], family: str) -> bytes:
    doc: Dict[str, Any] = {}
    if family == "control":
        # the fmt marker rides in the always-dirty control family so the
        # merged doc of a family-split snapshot still self-identifies
        doc["fmt"] = FORMAT_HOST_V1
    for key in HOST_FAMILIES[family]:
        doc[key] = _HOST_KEY_ENCODERS[key](state)
    return msgpack.pack(doc)


def encode_host_state(state: Dict[str, Any]) -> bytes:
    """Encode ``PartitionEngine.snapshot_state()`` output to safe bytes."""
    doc: Dict[str, Any] = {"fmt": FORMAT_HOST_V1}
    for key, enc in _HOST_KEY_ENCODERS.items():
        doc[key] = enc(state)
    return msgpack.pack(doc)


def decode_host_state(payload: bytes) -> Dict[str, Any]:
    """Decode untrusted snapshot bytes back into the restore_state() dict.

    Raises SnapshotFormatError on anything that is not a well-formed v1
    host snapshot; never constructs anything beyond the fixed state types.
    """
    return _decode_host_doc(_unpack_checked(payload, FORMAT_HOST_V1))


def _unpack_checked(payload: bytes, expect_fmt: str) -> dict:
    if len(payload) > MAX_SNAPSHOT_BYTES:
        raise SnapshotFormatError("snapshot payload too large")
    try:
        doc = msgpack.unpack(payload)
    except Exception as e:
        raise SnapshotFormatError(f"undecodable snapshot: {e}") from None
    if not isinstance(doc, dict) or doc.get("fmt") != expect_fmt:
        raise SnapshotFormatError("unknown snapshot format")
    return doc


def _decode_host_doc(doc: dict) -> Dict[str, Any]:
    try:
        return {
            "wf_keys": _dec_keygen(doc["wf_keys"]),
            "job_keys": _dec_keygen(doc["job_keys"]),
            "incident_keys": _dec_keygen(doc["incident_keys"]),
            "deployment_keys": _dec_keygen(doc["deployment_keys"]),
            "element_instances": _dec_instances(doc["element_instances"]),
            "jobs": {
                int(k): eng.JobState(
                    state=int(v["s"]),
                    record=JobRecord.from_document(v["r"]),
                    deadline=int(v["d"]),
                )
                for k, v in doc["jobs"].items()
            },
            "incidents": {
                int(k): eng.IncidentState(
                    state=int(v["s"]),
                    incident_event_position=int(v["ie"]),
                    failure_event_position=int(v["fe"]),
                )
                for k, v in doc["incidents"].items()
            },
            "incident_by_activity": {
                int(k): int(v) for k, v in doc["incident_by_activity"].items()
            },
            "incident_by_failed_job": {
                int(k): int(v) for k, v in doc["incident_by_failed_job"].items()
            },
            "resolving_events": {
                int(k): int(v) for k, v in doc["resolving_events"].items()
            },
            "incident_records": {
                int(k): IncidentRecord.from_document(v)
                for k, v in doc["incident_records"].items()
            },
            "messages": {
                int(k): eng.StoredMessage(
                    key=int(v["k"]), name=str(v["n"]),
                    correlation_key=str(v["c"]), time_to_live=int(v["ttl"]),
                    payload=dict(v["p"]), message_id=str(v["id"]),
                    deadline=int(v["dl"]),
                )
                for k, v in doc["messages"].items()
            },
            "message_subscriptions": [
                eng.StoredSubscription(
                    message_name=str(s["n"]), correlation_key=str(s["c"]),
                    workflow_instance_partition_id=int(s["pp"]),
                    workflow_instance_key=int(s["wk"]),
                    activity_instance_key=int(s["ak"]),
                )
                for s in doc["message_subscriptions"]
            ],
            "timers": {
                int(k): eng.TimerState(
                    due_date=int(v["d"]),
                    activity_instance_key=int(v["a"]),
                    record=TimerRecord.from_document(v["r"]),
                )
                for k, v in doc["timers"].items()
            },
            "pending_boundary": {
                int(k): (str(v[0]), dict(v[1]))
                for k, v in doc.get("pending_boundary", {}).items()
            },
            # ordered key set per type (insertion-ordered dict of key ->
            # None, matching the engine's in-memory form); absent in
            # pre-round-6 snapshots
            "awaiting_jobs": {
                str(job_type): {int(k): None for k in keys}
                for job_type, keys in doc.get("awaiting_jobs", {}).items()
            },
            "topic_sub_acks": {
                str(k): int(v) for k, v in doc["topic_sub_acks"].items()
            },
            "exporter_positions": {
                str(k): int(v)
                for k, v in doc.get("exporter_positions", {}).items()
            },
            "topics": {str(k): dict(v) for k, v in doc["topics"].items()},
            "next_partition_id": int(doc["next_partition_id"]),
            "last_processed_position": int(doc["last_processed_position"]),
            "workflows": _dec_workflows(doc["workflows"]),
        }
    except SnapshotFormatError:
        raise
    except Exception as e:
        # includes parser errors from workflow-source re-transform (XML
        # ParseError, YAML errors): a snapshot that cannot be restored must
        # be SKIPPED by recovery (next older one is tried), never crash it
        raise SnapshotFormatError(f"malformed snapshot: {e}") from None


# ---------------------------------------------------------------------------
# generic entry points used by SnapshotController
# ---------------------------------------------------------------------------


def encode_state(state: Any) -> bytes:
    """Engine-state → bytes (zlib-compressed envelope). Dispatches on
    shape: a device-state envelope (dict with 'fmt' already set by the
    device engine) passes through its own encoder; a dict carrying
    KeyGenerators is host-engine state; any other plain-data value is
    wrapped raw (msgpack.pack rejects non-data objects, so nothing
    executable can sneak through this path either)."""
    if isinstance(state, dict) and state.get("fmt") == FORMAT_DEVICE_V1:
        raw = encode_device_state(state)
    elif isinstance(state, dict) and isinstance(state.get("wf_keys"), KeyGenerator):
        raw = encode_host_state(state)
    else:
        raw = msgpack.pack({"fmt": FORMAT_RAW_V1, "data": state})
    return _ZMAGIC + zlib.compress(raw, level=1)


def decode_state(payload: bytes) -> Any:
    if len(payload) > MAX_SNAPSHOT_BYTES:
        raise SnapshotFormatError("snapshot payload too large")
    if payload[:4] == _ZMAGIC:
        try:
            d = zlib.decompressobj()
            payload = d.decompress(payload[4:], MAX_SNAPSHOT_BYTES)
            if d.unconsumed_tail:
                raise SnapshotFormatError("snapshot decompresses too large")
        except zlib.error as e:
            raise SnapshotFormatError(f"corrupt snapshot: {e}") from None
    try:
        doc = msgpack.unpack(payload)
    except Exception as e:
        raise SnapshotFormatError(f"undecodable snapshot: {e}") from None
    if not isinstance(doc, dict):
        raise SnapshotFormatError("unknown snapshot format")
    fmt = doc.get("fmt")
    if fmt == FORMAT_HOST_V1:
        return _decode_host_doc(doc)
    if fmt == FORMAT_DEVICE_V1:
        return _decode_device_doc(doc)
    if fmt == FORMAT_RAW_V1:
        return doc.get("data")
    raise SnapshotFormatError(f"unknown snapshot format {fmt!r}")


# ---------------------------------------------------------------------------
# device-engine state (SoA tables + intern/varspace sidecars)
# ---------------------------------------------------------------------------


def encode_state_parts(state: Any) -> List[tuple]:
    """Engine-state → named parts for content-addressed checkpointing.

    The snapshot storage hashes each part and only writes segments it has
    not seen in a previous checkpoint (the TPU-native analogue of RocksDB
    checkpoints hard-linking unchanged SST files —
    ``logstreams/.../state/StateSnapshotController.java``). The split is
    chosen so the stable bulk dedupes:
    - device state: one part per SoA table array (fixed-capacity tables
      that did not change between checkpoints hash identically), plus the
      embedded host-oracle state and a small root part;
    - host state: one part per state family (``HOST_FAMILIES``) so the
      stable bulk — deployed workflow resources, quiescent instance or
      message tables — dedupes across checkpoints;
    - anything else: a single legacy-encoded part.

    Returns ``[(name, bytes), ...]``; decode with ``decode_state_parts``.
    """
    return encode_state_parts_delta(state, None)[0]


def encode_state_parts_delta(
    state: Any, dirty: Optional[Iterable[str]]
) -> Tuple[List[tuple], List[str]]:
    """Delta variant of :func:`encode_state_parts`: with ``dirty`` a set of
    family names (``"h/<family>"`` / ``"d/<family>"``), only parts of dirty
    families are encoded; parts of clean families come back by NAME in the
    second element, for the caller to resolve against the previous take's
    manifest. ``dirty=None`` encodes everything (full take). The tiny
    ``_root`` part is always re-encoded.

    For the device engine, a clean family's array values may be ``None``
    in ``state["arrays"]`` (readback skipped); only names are required.
    """
    dirty_set = None if dirty is None else set(dirty)
    if isinstance(state, dict) and state.get("fmt") == FORMAT_DEVICE_V1:
        names = sorted(state.get("arrays", {}).keys())
        parts = [
            (
                "_root",
                msgpack.pack(
                    {
                        "fmt": FORMAT_DEVICE_V1,
                        "meta": state.get("meta", {}),
                        "arrays": names,
                    }
                ),
            )
        ]
        clean: List[str] = []
        for name in names:
            family = "d/" + device_array_family(name)
            if dirty_set is not None and family not in dirty_set:
                clean.append("a/" + name)
                continue
            value = state["arrays"][name]
            if value is None:
                raise SnapshotFormatError(
                    f"array {name!r} of dirty family {family!r} was not "
                    "materialized by the engine"
                )
            parts.append(
                ("a/" + name, msgpack.pack(pack_ndarray(np.asarray(value))))
            )
        if state.get("host") is not None:
            hp, hc = _host_state_parts(state["host"], dirty_set)
            parts.extend(("h/" + n, b) for n, b in hp)
            clean.extend("h/" + n for n in hc)
        return parts, clean
    if isinstance(state, dict) and isinstance(state.get("wf_keys"), KeyGenerator):
        hp, hc = _host_state_parts(state, dirty_set)
        return (
            [("_root", msgpack.pack({"fmt": FORMAT_HOST_V1}))]
            + [("h/" + n, b) for n, b in hp],
            ["h/" + n for n in hc],
        )
    # legacy raw states have no family structure: always a full take
    return [("state", encode_state(state))], []


def _host_state_parts(
    state: Dict[str, Any], dirty: Optional[set] = None
) -> Tuple[List[tuple], List[str]]:
    """Host engine state as one part per family (``HOST_FAMILIES``); with
    ``dirty``, clean families are skipped and returned by name."""
    parts: List[tuple] = []
    clean: List[str] = []
    for family in HOST_FAMILIES:
        if dirty is not None and ("h/" + family) not in dirty:
            clean.append(family)
            continue
        parts.append((family, _enc_host_family(state, family)))
    return parts, clean


def decode_state_parts(parts: Dict[str, bytes]) -> Any:
    """Reassemble ``encode_state_parts`` output (untrusted bytes)."""
    if sum(len(b) for b in parts.values()) > MAX_SNAPSHOT_BYTES:
        raise SnapshotFormatError("snapshot parts too large")
    if set(parts) == {"state"}:
        return decode_state(parts["state"])
    if "_root" not in parts:
        raise SnapshotFormatError("snapshot root part missing")
    return decode_state_parts_stream(
        [("_root", parts["_root"])]
        + [(n, b) for n, b in parts.items() if n != "_root"]
    )


def decode_state_parts_stream(part_iter: Iterable[tuple]) -> Any:
    """Streaming reassembly of ``encode_state_parts`` output: consumes
    ``(name, bytes)`` pairs in manifest order (``_root`` first — the
    manifest's canonical sort guarantees it) and decodes each part as it
    arrives, so restore memory is bounded by the decoded state plus ONE
    in-flight part instead of all raw part bytes at once (the restore
    analogue of the wave pipeline's per-family columnar readback)."""
    it = iter(part_iter)
    try:
        first_name, first_data = next(it)
    except StopIteration:
        raise SnapshotFormatError("empty snapshot") from None
    if first_name == "state":
        return decode_state(first_data)
    if first_name != "_root":
        raise SnapshotFormatError(
            f"snapshot stream must start with _root, got {first_name!r}"
        )
    try:
        root = msgpack.unpack(first_data)
    except Exception as e:
        raise SnapshotFormatError(f"malformed snapshot root: {e}") from None
    if not isinstance(root, dict):
        raise SnapshotFormatError("malformed snapshot root")
    fmt = root.get("fmt")
    if fmt not in (FORMAT_HOST_V1, FORMAT_DEVICE_V1):
        raise SnapshotFormatError(f"unknown snapshot parts format {fmt!r}")

    total = len(first_data)
    arrays: Dict[str, np.ndarray] = {}
    # host family parts decode AS THEY ARRIVE into one merged doc (the
    # legacy two-part layout merges through the same path: its
    # "workflows" part has the family shape and "rest" is the remainder
    # incl. the fmt marker), so raw part bytes never accumulate
    host_doc: Dict[str, Any] = {}
    saw_host = False
    for name, data in it:
        total += len(data)
        if total > MAX_SNAPSHOT_BYTES:
            raise SnapshotFormatError("snapshot parts too large")
        if name.startswith("a/"):
            try:
                arrays[name[2:]] = unpack_ndarray(msgpack.unpack(data))
            except SnapshotFormatError:
                raise
            except Exception as e:
                raise SnapshotFormatError(
                    f"malformed snapshot part {name!r}: {e}"
                ) from None
        elif name.startswith("h/"):
            saw_host = True
            try:
                sub = msgpack.unpack(data)
            except Exception as e:
                raise SnapshotFormatError(
                    f"malformed snapshot part {name!r}: {e}"
                ) from None
            if not isinstance(sub, dict):
                raise SnapshotFormatError(
                    f"malformed snapshot part {name!r}"
                )
            host_doc.update(sub)
        else:
            raise SnapshotFormatError(f"unexpected snapshot part {name!r}")

    host = None
    if saw_host:
        if host_doc.get("fmt") != FORMAT_HOST_V1:
            raise SnapshotFormatError("malformed host snapshot parts")
        host = _decode_host_doc(host_doc)
    if fmt == FORMAT_HOST_V1:
        if host is None:
            raise SnapshotFormatError("snapshot host parts missing")
        return host
    names = [str(n) for n in root.get("arrays", [])]
    missing = [n for n in names if n not in arrays]
    if missing:
        raise SnapshotFormatError(f"snapshot part missing: 'a/{missing[0]}'")
    meta = root.get("meta", {})
    if not isinstance(meta, dict):
        raise SnapshotFormatError("malformed snapshot meta")
    return {
        "fmt": FORMAT_DEVICE_V1,
        "arrays": {n: arrays[n] for n in names},
        "meta": meta,
        "host": host,
    }


def encode_device_state(state: Dict[str, Any]) -> bytes:
    """Device snapshot envelope: {'fmt', 'arrays': {name: ndarray},
    'meta': plain-data dict, 'host': host-engine snapshot dict or None}.

    The embedded host oracle state (device engines keep one for
    device-ineligible records) rides along as its own encoded payload.
    """
    doc = {
        "fmt": FORMAT_DEVICE_V1,
        "arrays": {
            name: pack_ndarray(np.asarray(a))
            for name, a in state.get("arrays", {}).items()
        },
        "meta": state.get("meta", {}),
        "host": (
            encode_host_state(state["host"])
            if state.get("host") is not None else None
        ),
    }
    return msgpack.pack(doc)


def decode_device_state(payload: bytes) -> Dict[str, Any]:
    return _decode_device_doc(_unpack_checked(payload, FORMAT_DEVICE_V1))


def _decode_device_doc(doc: dict) -> Dict[str, Any]:
    try:
        return {
            "fmt": FORMAT_DEVICE_V1,
            "arrays": {
                str(k): unpack_ndarray(v) for k, v in doc["arrays"].items()
            },
            "meta": doc.get("meta", {}),
            "host": (
                decode_host_state(doc["host"])
                if doc.get("host") is not None else None
            ),
        }
    except SnapshotFormatError:
        raise
    except Exception as e:
        raise SnapshotFormatError(f"malformed snapshot: {e}") from None
