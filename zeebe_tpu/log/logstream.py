"""Log stream: positioned record log with commit position and readers.

Reference parity: ``logstreams/.../log/LogStream.java`` (positions, commit
position), ``LogStreamWriterImpl`` / ``LogStreamBatchWriterImpl`` (atomic
multi-record batches), ``BufferedLogStreamReader`` (seekable iteration via
the sparse ``LogBlockIndex``).

Positions are dense per-partition record sequence numbers (the reference
uses sparse byte positions; density is an implementation choice, the
contract — strictly increasing, stable across replay — is the same).
"""

from __future__ import annotations

import time
from typing import Callable, Iterator, List, Optional

from zeebe_tpu.log.storage import SegmentedLogStorage
from zeebe_tpu.protocol import codec
from zeebe_tpu.protocol.records import Record

BLOCK_INDEX_DENSITY = 256  # record a (position → address) entry every N records


class LogStream:
    """A partition's append-only record log."""

    def __init__(
        self,
        storage: SegmentedLogStorage,
        partition_id: int = 0,
        topic_name: str = "default-topic",
        clock: Optional[Callable[[], int]] = None,
        recover_commit: bool = True,
    ):
        """``recover_commit``: in single-writer mode (True) recovery marks
        the whole recovered log committed. Under raft (False) the commit
        position is the LEADER's to advance — a restarted follower's
        unreplicated tail must not be exposed as committed, or a later
        conflict truncation would rewind the commit position (commit is
        final)."""
        self.storage = storage
        self.partition_id = partition_id
        self.topic_name = topic_name
        self.clock = clock or (lambda: int(time.time() * 1000))
        self.recover_commit = recover_commit

        self._next_position = 0
        self._commit_position = -1
        # sparse block index: (position, address); reference LogBlockIndex.java:44
        self._block_index: List[tuple] = []
        # in-memory tail: records by dense position (the hot read path; disk is
        # the durability path — mirrors the reference's dispatcher write buffer
        # serving readers before/alongside storage)
        self._records: List[Record] = []
        self._commit_listeners: List[Callable[[int], None]] = []
        self._recover()

    # -- recovery scan (reference FsLogStorage recovery + LogBlockIndexWriter)
    def _recover(self) -> None:
        last_position = -1
        torn = False
        for base_address, data in self.storage.iter_blocks():
            if torn:
                break
            offset = 0
            while offset < len(data):
                frame_len = codec.peek_frame_length(data, offset)
                if frame_len is None or offset + frame_len > len(data):
                    torn = True  # torn tail write: discard
                    break
                try:
                    record, next_offset = codec.decode_record(data, offset)
                except ValueError:
                    torn = True  # corrupt tail frame (bad crc): discard
                    break
                if record.position % BLOCK_INDEX_DENSITY == 0:
                    self._block_index.append((record.position, base_address + offset))
                self._records.append(record)
                last_position = record.position
                offset = next_offset
        self._next_position = last_position + 1
        # Single-writer mode: recovered records were durably written, commit
        # resumes at the log end. Raft mode: stay at -1 until the leader
        # advances it (see __init__).
        self._commit_position = last_position if self.recover_commit else -1

    # -- write path --------------------------------------------------------
    @property
    def next_position(self) -> int:
        return self._next_position

    @property
    def commit_position(self) -> int:
        return self._commit_position

    def append(self, records: List[Record], commit: bool = True) -> int:
        """Atomically append a batch (reference LogStreamBatchWriter). Assigns
        positions + timestamps; returns the last assigned position."""
        ts = self.clock()
        frames = []
        for record in records:
            record.position = self._next_position
            if record.timestamp < 0:
                record.timestamp = ts
            frames.append(codec.encode_record(record))
            self._records.append(record)
            self._next_position += 1
        address = self.storage.append(b"".join(frames))
        offset = 0
        for record, frame in zip(records, frames):
            if record.position % BLOCK_INDEX_DENSITY == 0:
                self._block_index.append((record.position, address + offset))
            offset += len(frame)
        if commit:
            self.set_commit_position(self._next_position - 1)
        return self._next_position - 1

    def append_replicated(self, record: Record) -> int:
        """Follower append: the record keeps its leader-assigned position,
        timestamp and raft term (reference: follower writes the
        AppendRequest's serialized entries verbatim). The record's position
        must equal ``next_position``."""
        if record.position != self._next_position:
            raise ValueError(
                f"replicated append at {record.position}, expected {self._next_position}"
            )
        frame = codec.encode_record(record)
        address = self.storage.append(frame)
        self._records.append(record)
        if record.position % BLOCK_INDEX_DENSITY == 0:
            self._block_index.append((record.position, address))
        self._next_position += 1
        return record.position

    def set_commit_position(self, position: int) -> None:
        if position > self._commit_position:
            self._commit_position = position
            for listener in self._commit_listeners:
                listener(position)

    def on_commit(self, listener: Callable[[int], None]) -> None:
        self._commit_listeners.append(listener)

    def flush(self) -> None:
        self.storage.flush()

    def reader(self, position: int = 0) -> "LogStreamReader":
        return LogStreamReader(self, position)

    # -- failure injection (reference StreamProcessorRule.truncateLog) ------
    def truncate(self, position: int) -> None:
        """Discard records with position >= ``position`` (failure injection;
        raft follower conflict resolution). In raft mode committed records
        are final — truncating them is a protocol violation and raises."""
        if not self.recover_commit and position <= self._commit_position:
            raise RuntimeError(
                f"refusing to truncate at {position}: commit position is "
                f"{self._commit_position} (commit is final)"
            )
        address = None
        for record, addr in _iter_disk_frames(self, 0):
            if record.position >= position:
                address = addr
                break
        if address is not None:
            self.storage.truncate(address)
            self._next_position = position
            self._commit_position = min(self._commit_position, position - 1)
            self._block_index = [e for e in self._block_index if e[0] < position]
            del self._records[position:]


def _iter_disk_frames(log: LogStream, target: int) -> Iterator[tuple]:
    """Scan frames from storage, yielding (record, address) for positions >=
    target. Used by truncate and as the cold-read fallback; the hot read path
    serves from the in-memory tail."""
    start_entry = None
    for pos, addr in log._block_index:
        if pos <= target:
            start_entry = (pos, addr)
        else:
            break
    for base_address, data in log.storage.iter_blocks():
        segment_id = log.storage.segment_of(base_address)
        if start_entry is not None and log.storage.segment_of(start_entry[1]) > segment_id:
            continue
        offset = 0
        if start_entry is not None and log.storage.segment_of(start_entry[1]) == segment_id:
            offset = log.storage.offset_of(start_entry[1]) - log.storage.offset_of(base_address)
        while offset < len(data):
            frame_len = codec.peek_frame_length(data, offset)
            if frame_len is None or offset + frame_len > len(data):
                break
            record, next_offset = codec.decode_record(data, offset)
            if record.position >= target:
                yield record, base_address + offset
            offset = next_offset


class LogStreamReader:
    """Sequential reader with seek-by-position, served from the in-memory
    tail (O(1) per record).

    Reference: ``logstreams/.../log/BufferedLogStreamReader.java``.
    """

    def __init__(self, log: LogStream, position: int = 0):
        self.log = log
        self._position = max(position, 0)

    def seek(self, position: int) -> None:
        self._position = max(position, 0)

    def __iter__(self) -> Iterator[Record]:
        while self._position < len(self.log._records):
            record = self.log._records[self._position]
            self._position = record.position + 1
            yield record

    def read_committed(self) -> List[Record]:
        """All records from the current position up to the commit position
        (records past the commit position are not consumed)."""
        commit = self.log.commit_position
        out = []
        while self._position <= commit and self._position < len(self.log._records):
            record = self.log._records[self._position]
            out.append(record)
            self._position = record.position + 1
        return out


class LogStreamWriter:
    """Single-record convenience writer (reference LogStreamWriterImpl)."""

    def __init__(self, log: LogStream):
        self.log = log

    def write(self, record: Record, commit: bool = True) -> int:
        return self.log.append([record], commit=commit)
