"""Log stream: positioned record log with commit position and readers.

Reference parity: ``logstreams/.../log/LogStream.java`` (positions, commit
position), ``LogStreamWriterImpl`` / ``LogStreamBatchWriterImpl`` (atomic
multi-record batches), ``BufferedLogStreamReader`` (seekable iteration via
the sparse ``LogBlockIndex``).

Positions are dense per-partition record sequence numbers (the reference
uses sparse byte positions; density is an implementation choice, the
contract — strictly increasing, stable across replay — is the same).
"""

from __future__ import annotations

import json
import os
import threading
import time
from typing import Callable, Iterator, List, Optional

from zeebe_tpu.log.storage import SegmentedLogStorage
from zeebe_tpu.protocol import codec
from zeebe_tpu.protocol.columnar import ColumnarBatch, RecordsView
from zeebe_tpu.protocol.records import Record

BLOCK_INDEX_DENSITY = 256  # record a (position → address) entry every N records


class LogStream:
    """A partition's append-only record log."""

    def __init__(
        self,
        storage: SegmentedLogStorage,
        partition_id: int = 0,
        topic_name: str = "default-topic",
        clock: Optional[Callable[[], int]] = None,
        recover_commit: bool = True,
    ):
        """``recover_commit``: in single-writer mode (True) recovery marks
        the whole recovered log committed. Under raft (False) the commit
        position is the LEADER's to advance — a restarted follower's
        unreplicated tail must not be exposed as committed, or a later
        conflict truncation would rewind the commit position (commit is
        final)."""
        self.storage = storage
        self.partition_id = partition_id
        self.topic_name = topic_name
        self.clock = clock or (lambda: int(time.time() * 1000))
        self.recover_commit = recover_commit

        self._next_position = 0
        self._commit_position = -1
        # compaction floor: first position still held (in memory AND on
        # disk); everything below is covered by a snapshot
        self._base_position = 0
        self._base_prev_term = -1  # raft term of record base_position-1
        # first record position per storage segment (compaction is
        # segment-aligned: a segment is deleted only when ALL its records
        # fall below the floor, so the in-memory view always matches what
        # recovery rebuilds from the remaining segments)
        self._segment_first_pos: dict = {}
        # sparse block index: (position, address); reference LogBlockIndex.java:44
        self._block_index: List[tuple] = []
        # in-memory tail: records by dense position (the hot read path; disk is
        # the durability path — mirrors the reference's dispatcher write buffer
        # serving readers before/alongside storage)
        self._records: List[Record] = []
        # compaction/truncation mutate (_base_position, _records) as a
        # compound update while readers on other actors index by
        # position - base; the lock makes each record_at read and each
        # compound mutation atomic (list.append alone is atomic under the
        # GIL, so the append hot path stays lock-free)
        self._view_lock = threading.Lock()
        self._commit_listeners: List[Callable[[int], None]] = []
        # floor providers (exporter directors): each returns the first
        # position it still needs; compact() never passes them (reference:
        # segment deletion is bounded by exporter/subscriber positions)
        self._floor_providers: List[Callable[[], int]] = []
        self._load_base_meta()
        self._recover()

    def _base_meta_path(self) -> str:
        return os.path.join(self.storage.directory, "base.meta")

    def _load_base_meta(self) -> None:
        try:
            with open(self._base_meta_path()) as f:
                data = json.load(f)
            self._base_prev_term = int(data.get("base_prev_term", -1))
            self._base_meta_position = int(data.get("base_position", 0))
        except (OSError, ValueError):
            self._base_meta_position = 0

    def _save_base_meta(self) -> None:
        tmp = self._base_meta_path() + ".tmp"
        try:
            with open(tmp, "w") as f:
                json.dump(
                    {
                        "base_position": self._base_position,
                        "base_prev_term": self._base_prev_term,
                    },
                    f,
                )
                f.flush()
                os.fsync(f.fileno())
            os.replace(tmp, self._base_meta_path())
            self._base_meta_position = self._base_position
        except OSError:
            pass

    # -- recovery scan (reference FsLogStorage recovery + LogBlockIndexWriter)
    def _recover(self) -> None:
        last_position = -1
        torn = False
        torn_address = None
        for base_address, data in self.storage.iter_blocks():
            if torn:
                break
            offset = 0
            while offset < len(data):
                frame_len = codec.peek_frame_length(data, offset)
                if frame_len is None or offset + frame_len > len(data):
                    torn = True  # torn tail write: discard
                    torn_address = base_address + offset
                    break
                try:
                    record, next_offset = codec.decode_record(data, offset)
                except ValueError:
                    torn = True  # corrupt tail frame (bad crc): discard
                    torn_address = base_address + offset
                    break
                if record.position % BLOCK_INDEX_DENSITY == 0:
                    self._block_index.append((record.position, base_address + offset))
                seg = self.storage.segment_of(base_address)
                self._segment_first_pos.setdefault(seg, record.position)
                if not self._records:
                    self._base_position = record.position
                self._records.append(record)
                last_position = record.position
                offset = next_offset
        if torn_address is not None:
            # physically cut the torn tail so the next append resumes at the
            # last whole record — an in-memory discard alone would leave new
            # appends stranded AFTER the partial frame, unreachable to every
            # future recovery scan (the storage layer's crc pre-scan catches
            # most of this; this covers a torn FIRST record and frames whose
            # prefix validates but whose body the codec rejects)
            try:
                self.storage.truncate(torn_address)
            except OSError:
                pass  # read-only/odd storage: recovery still discards in memory
        self._next_position = last_position + 1
        if not self._records and self._base_meta_position > 0:
            # empty log after a fast-forward (or compaction that emptied
            # it) followed by a crash: resume at the persisted base — the
            # prev-term of base-1 was loaded with it
            self._base_position = self._base_meta_position
            self._next_position = max(self._next_position, self._base_meta_position)
        # (when base.meta disagrees with the recovered records, term_at
        # consults _base_meta_position directly — no need to discard the
        # persisted prev-term here)
        # Single-writer mode: recovered records were durably written, commit
        # resumes at the log end. Raft mode: stay at -1 until the leader
        # advances it (see __init__).
        self._commit_position = last_position if self.recover_commit else -1

    # -- write path --------------------------------------------------------
    @property
    def next_position(self) -> int:
        return self._next_position

    @property
    def base_position(self) -> int:
        """First retained position (compaction floor)."""
        return self._base_position

    def record_at(self, position: int) -> Optional[Record]:
        """Record by position, None when compacted away or not yet
        appended — the supported random-access API (raft replication and
        readers must not reach into the private list). Columnar-appended
        entries materialize here, once (the backing batch caches the row,
        so every reader sees one object identity per position)."""
        with self._view_lock:
            idx = position - self._base_position
            if idx < 0 or idx >= len(self._records):
                return None
            entry = self._records[idx]
            if type(entry) is tuple:  # lazy (batch, row) columnar ref
                entry = entry[0].row(entry[1])
                self._records[idx] = entry
            return entry

    def slice_records(
        self,
        start: int,
        limit: Optional[int] = None,
        committed_only: bool = False,
    ) -> List[Record]:
        """Materialized records from ``start`` under ONE lock acquisition
        (the drain loops used to pay a lock round-trip per record via
        ``record_at``). Clamps to the live window; ``committed_only``
        bounds at the commit position (the wave-drain read)."""
        with self._view_lock:
            hi = self._next_position - 1
            if committed_only:
                hi = min(hi, self._commit_position)
            lo = max(start, self._base_position)
            if lo > hi:
                return []
            i0 = lo - self._base_position
            i1 = hi - self._base_position + 1
            if limit is not None:
                i1 = min(i1, i0 + limit)
            out = self._records[i0:i1]
            for k, entry in enumerate(out):
                if type(entry) is tuple:
                    entry = entry[0].row(entry[1])
                    self._records[i0 + k] = entry
                    out[k] = entry
            return out

    def committed_view(
        self, start: int, limit: Optional[int] = None
    ) -> RecordsView:
        """Committed records from ``start`` as a :class:`RecordsView` —
        one lock acquisition, NO row materialization (lazy columnar
        entries stay lazy; column reads come from the backing batch).
        The exporter plane's read API."""
        with self._view_lock:
            hi = self._commit_position
            lo = max(start, self._base_position)
            if lo > hi:
                return RecordsView([])
            i0 = lo - self._base_position
            i1 = hi - self._base_position + 1
            if limit is not None:
                i1 = min(i1, i0 + limit)
            return RecordsView(self._records[i0:i1])

    def term_at(self, position: int) -> int:
        """Raft term at ``position``. For the position just below the
        PERSISTED compaction base the term is retained across compaction
        (replication prev-entry checks); live records win when still
        present — this makes the answer correct on both sides of the
        crash window between writing base.meta and deleting segments."""
        record = self.record_at(position)
        if record is not None:
            return record.raft_term
        if position == self._base_meta_position - 1:
            return self._base_prev_term
        if position == self._base_position - 1:
            return self._base_prev_term if (
                self._base_meta_position == self._base_position
            ) else -1
        return -1

    def compact(self, position: int) -> int:
        """Compaction floor: drop records below ``position``, SEGMENT
        aligned — a storage segment is deleted only when every record in
        it falls below the floor, and the in-memory tail drops exactly the
        deleted segments' records. This keeps the live view identical to
        what a restart recovers from the remaining segments. Only
        positions covered by a durable snapshot may be compacted (the
        caller's contract — reference: the broker deletes segments below
        the snapshot position). Registered floor providers (exporter
        directors) additionally bound the floor HERE: records some
        exporter has not acked survive even a caller that forgot them.
        Returns the new base position."""
        for provider in list(self._floor_providers):
            position = min(position, provider())
        position = min(position, self._next_position)
        if position <= self._base_position:
            return self._base_position
        segs = sorted(self._segment_first_pos)
        # a segment is fully below the floor when the NEXT segment starts
        # at or below the floor position
        new_base = self._base_position
        first_kept = None
        for i, seg in enumerate(segs):
            next_first = (
                self._segment_first_pos[segs[i + 1]]
                if i + 1 < len(segs) else self._next_position + 1
            )
            if next_first <= position:
                continue  # fully compactable
            first_kept = seg
            new_base = max(
                self._base_position, self._segment_first_pos[seg]
            )
            break
        if first_kept is None or new_base <= self._base_position:
            return self._base_position
        prev = self.record_at(new_base - 1)
        self._base_prev_term = prev.raft_term if prev is not None else -1
        with self._view_lock:
            del self._records[: new_base - self._base_position]
            self._base_position = new_base
        self._block_index = [e for e in self._block_index if e[0] >= new_base]
        # persist the base metadata BEFORE deleting segments: the prev-term
        # of base-1 must survive a crash anywhere in this sequence (leaders
        # advertise it in replication prev-entry checks; -1 would make
        # followers truncate committed records)
        self._save_base_meta()
        self.storage.delete_segments_before(first_kept)
        self._segment_first_pos = {
            s: p for s, p in self._segment_first_pos.items() if s >= first_kept
        }
        return self._base_position

    def fast_forward(self, position: int, term: int = -1) -> None:
        """Jump an empty-or-behind log to ``position`` (exclusive: next
        append lands there) after installing a snapshot that covers
        everything below — the follower side of snapshot catch-up
        (reference SnapshotReplicationService + follower reset). Refuses
        to rewind."""
        if position <= self._next_position:
            return
        # the snapshot supersedes everything on disk: reset storage so a
        # restart cannot resurrect the pre-gap records
        self.storage.reset()
        with self._view_lock:
            self._records.clear()
            self._base_position = position
        self._block_index = []
        self._segment_first_pos = {}
        self._base_prev_term = term
        self._next_position = position
        self._commit_position = max(self._commit_position, position - 1)
        self._save_base_meta()

    @property
    def commit_position(self) -> int:
        return self._commit_position

    def append(self, records, commit: bool = True) -> int:
        """Atomically append a batch (reference LogStreamBatchWriter).
        Assigns positions + timestamps; returns the last assigned position.

        ``records`` is a list of ``Record`` objects or a
        :class:`ColumnarBatch` — either way the whole wave encodes in ONE
        codec pass into a single buffer, appends as one storage block, and
        the block index derives from the pass's frame offsets (no
        re-walk). A columnar batch's rows stay LAZY: the in-memory tail
        holds ``(batch, row)`` refs that materialize on first read."""
        ts = self.clock()
        first_position = self._next_position
        columnar = isinstance(records, ColumnarBatch)
        if columnar:
            n = len(records)
            records.assign_positions(first_position, ts)
            buf, offsets = codec.encode_columnar(records)
            self._records.extend(records.log_entries())
            # response/push-relevant rows that are already materialized
            # get their just-encoded frame cached, like the list path
            records.cache_frames(buf, offsets)
        else:
            n = len(records)
            for i, record in enumerate(records):
                record.position = first_position + i
                if record.timestamp < 0:
                    record.timestamp = ts
            buf, offsets = codec.encode_records(records)
            self._records.extend(records)
        self._next_position = first_position + n
        address = self.storage.append(buf)
        if n:
            self._segment_first_pos.setdefault(
                self.storage.segment_of(address), first_position
            )
            # sparse block index: only when the appended position range
            # actually crosses a density boundary (group-committed batches
            # are the append hot path)
            last = first_position + n - 1
            if (last // BLOCK_INDEX_DENSITY) * BLOCK_INDEX_DENSITY >= first_position:
                for i, offset in enumerate(offsets):
                    if (first_position + i) % BLOCK_INDEX_DENSITY == 0:
                        self._block_index.append(
                            (first_position + i, address + offset)
                        )
            if not columnar:
                # cache the just-encoded frame on response/push-relevant
                # records: the cluster broker re-encodes exactly these for
                # client response / push marshalling moments later
                total = len(buf)
                for i, record in enumerate(records):
                    md = record.metadata
                    if md.request_id >= 0 or md.request_stream_id >= 0:
                        end = offsets[i + 1] if i + 1 < n else total
                        record._frame = (
                            record.position, bytes(buf[offsets[i]:end]),
                        )
        if commit:
            self.set_commit_position(self._next_position - 1)
        return self._next_position - 1

    def append_replicated(self, record: Record) -> int:
        """Follower append: the record keeps its leader-assigned position,
        timestamp and raft term (reference: follower writes the
        AppendRequest's serialized entries verbatim). The record's position
        must equal ``next_position``."""
        if record.position != self._next_position:
            raise ValueError(
                f"replicated append at {record.position}, expected {self._next_position}"
            )
        frame = codec.encode_record(record)
        address = self.storage.append(frame)
        self._segment_first_pos.setdefault(
            self.storage.segment_of(address), record.position
        )
        self._records.append(record)
        if record.position % BLOCK_INDEX_DENSITY == 0:
            self._block_index.append((record.position, address))
        self._next_position += 1
        return record.position

    def set_commit_position(self, position: int) -> None:
        if position > self._commit_position:
            self._commit_position = position
            for listener in self._commit_listeners:
                listener(position)

    def on_commit(self, listener: Callable[[int], None]) -> None:
        self._commit_listeners.append(listener)

    def remove_commit_listener(self, listener: Callable[[int], None]) -> None:
        """Unhook a commit listener (exporter directors close on leader
        step-down; a stale listener would pump a dead director forever)."""
        if listener in self._commit_listeners:
            self._commit_listeners.remove(listener)

    def add_floor_provider(self, provider: Callable[[], int]) -> None:
        """Register a compaction bound: ``provider()`` returns the first
        position its owner still needs (see ``compact``)."""
        if provider not in self._floor_providers:
            self._floor_providers.append(provider)

    def remove_floor_provider(self, provider: Callable[[], int]) -> None:
        if provider in self._floor_providers:
            self._floor_providers.remove(provider)

    def flush(self) -> None:
        self.storage.flush()

    def reader(self, position: int = 0) -> "LogStreamReader":
        return LogStreamReader(self, position)

    # -- failure injection (reference StreamProcessorRule.truncateLog) ------
    def truncate(self, position: int) -> None:
        """Discard records with position >= ``position`` (failure injection;
        raft follower conflict resolution). In raft mode committed records
        are final — truncating them is a protocol violation and raises."""
        if not self.recover_commit and position <= self._commit_position:
            raise RuntimeError(
                f"refusing to truncate at {position}: commit position is "
                f"{self._commit_position} (commit is final)"
            )
        address = None
        for record, addr in _iter_disk_frames(self, 0):
            if record.position >= position:
                address = addr
                break
        if address is not None:
            self.storage.truncate(address)
            self._next_position = position
            self._commit_position = min(self._commit_position, position - 1)
            self._block_index = [e for e in self._block_index if e[0] < position]
            # purge segment bookkeeping for truncated-away content: a stale
            # too-low first-position would later let compact() delete a
            # segment still holding live records
            self._segment_first_pos = {
                s: p for s, p in self._segment_first_pos.items() if p < position
            }
            with self._view_lock:
                del self._records[position - self._base_position :]


def _iter_disk_frames(log: LogStream, target: int) -> Iterator[tuple]:
    """Scan frames from storage, yielding (record, address) for positions >=
    target. Used by truncate and as the cold-read fallback; the hot read path
    serves from the in-memory tail."""
    start_entry = None
    for pos, addr in log._block_index:
        if pos <= target:
            start_entry = (pos, addr)
        else:
            break
    for base_address, data in log.storage.iter_blocks():
        segment_id = log.storage.segment_of(base_address)
        if start_entry is not None and log.storage.segment_of(start_entry[1]) > segment_id:
            continue
        offset = 0
        if start_entry is not None and log.storage.segment_of(start_entry[1]) == segment_id:
            offset = log.storage.offset_of(start_entry[1]) - log.storage.offset_of(base_address)
        while offset < len(data):
            frame_len = codec.peek_frame_length(data, offset)
            if frame_len is None or offset + frame_len > len(data):
                break
            record, next_offset = codec.decode_record(data, offset)
            if record.position >= target:
                yield record, base_address + offset
            offset = next_offset


class LogStreamReader:
    """Sequential reader with seek-by-position, served from the in-memory
    tail (O(1) per record).

    Reference: ``logstreams/.../log/BufferedLogStreamReader.java``.
    """

    def __init__(self, log: LogStream, position: int = 0):
        self.log = log
        self._position = max(position, 0)

    def seek(self, position: int) -> None:
        self._position = max(position, 0)

    def __iter__(self) -> Iterator[Record]:
        if self._position < self.log.base_position:
            self._position = self.log.base_position
        while True:
            record = self.log.record_at(self._position)
            if record is None:
                return
            self._position = record.position + 1
            yield record

    def read_committed(self, limit: Optional[int] = None) -> List[Record]:
        """All records from the current position up to the commit position
        (records past the commit position are not consumed). One lock
        acquisition for the whole span — the wave drain's read path."""
        out = self.log.slice_records(
            self._position, limit=limit, committed_only=True
        )
        if out:
            self._position = out[-1].position + 1
        elif self._position < self.log.base_position:
            self._position = self.log.base_position
        return out


class LogStreamWriter:
    """Single-record convenience writer (reference LogStreamWriterImpl)."""

    def __init__(self, log: LogStream):
        self.log = log

    def write(self, record: Record, commit: bool = True) -> int:
        return self.log.append([record], commit=commit)
