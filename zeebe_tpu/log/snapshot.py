"""Snapshot storage + controller: checkpoint/recover partition state.

Reference parity:
- ``logstreams/.../impl/snapshot/fs/FsSnapshotStorage.java`` /
  ``FsSnapshotController.java`` — snapshots on disk with checksums,
  temp-write then commit-rename.
- ``logstreams/.../state/StateSnapshotController.java`` /
  ``StateSnapshotMetadata.java`` — checkpoints keyed by
  (lastProcessedPosition, lastWrittenPosition, term); recovery picks the
  newest snapshot *valid against the log* (the written position must still
  exist — guards against a truncated/diverged log, the term check of
  ``StreamProcessorController.validateSnapshot:177-187``).

Resume contract (SURVEY.md §5 checkpoint/resume): recover best valid
snapshot, then REPLAY committed records after ``last_processed_position``
to rebuild state without re-executing side effects.
"""

from __future__ import annotations

import dataclasses
import hashlib
import logging
import os
import re
import shutil
import time
import zlib
from typing import Any, Dict, List, Optional, Tuple

from zeebe_tpu._events import count_event as _count_event, set_gauge as _set_gauge

logger = logging.getLogger(__name__)


class SnapshotPartError(Exception):
    """A committed snapshot's parts cannot be read back (missing/corrupt
    segment or manifest) — recovery skips the snapshot and tries an older
    one."""

_SNAPSHOT_DIR_RE = re.compile(r"^snapshot_(-?\d+)_(-?\d+)_(-?\d+)$")
_STATE_FILE = "state.bin"
_MANIFEST_FILE = "manifest.bin"
_CHECKSUM_FILE = "checksum.crc32"
_SEGMENTS_DIR = "segments"
_HASH_HEX_RE = re.compile(r"^[0-9a-f]{32}$")
# GC grace: segments younger than this are kept even when unreferenced —
# they may belong to a checkpoint/install whose manifest has not committed
# yet (the manifest dir rename is the commit point)
_SEGMENT_GC_GRACE_SEC = 120.0

MANIFEST_FORMAT = "zbtpu-snapshot-manifest-v1"


def part_hash(data: bytes) -> str:
    """Content address of an (uncompressed) snapshot part."""
    return hashlib.blake2b(data, digest_size=16).hexdigest()


@dataclasses.dataclass(frozen=True, order=True)
class SnapshotMetadata:
    """Reference: StateSnapshotMetadata.java (ordering = recency)."""

    last_processed_position: int
    last_written_position: int
    term: int = 0

    @property
    def dirname(self) -> str:
        return (
            f"snapshot_{self.last_processed_position}"
            f"_{self.last_written_position}_{self.term}"
        )

    @staticmethod
    def parse(dirname: str) -> Optional["SnapshotMetadata"]:
        m = _SNAPSHOT_DIR_RE.match(dirname)
        if not m:
            return None
        return SnapshotMetadata(int(m.group(1)), int(m.group(2)), int(m.group(3)))


class SnapshotStorage:
    """Directory of committed snapshots for one partition/processor.

    Layout: ``{root}/snapshot_{processed}_{written}_{term}/state.bin`` with a
    crc32 checksum file; writes go to a ``.tmp`` sibling and are committed by
    atomic rename (reference FsSnapshotStorage temp-write + commit).
    """

    # set-aside suffix used by _swap_in; ".old" is the legacy spelling
    # (pre-chaos-plane dirs) and is swept identically
    _ASIDE_SUFFIXES = (".aside", ".old")

    def __init__(self, root: str):
        self.root = root
        os.makedirs(root, exist_ok=True)
        self._sweep_orphans()

    def _sweep_orphans(self) -> None:
        """Crash-recovery sweep of the snapshot root (runs on open).

        ``.tmp`` dirs are torn writes — DELETED (never just skipped: a
        skipped orphan survives forever and later swap-ins trip over it).
        ``.aside`` set-aside dirs come from a crash between ``_swap_in``'s
        two renames: when the replacement never landed the set-aside IS the
        committed snapshot and is restored; when the final exists the
        set-aside is obsolete and DELETED. Every action logs a salvage
        event and counts into ``snapshot_salvage_events``."""
        for name in sorted(os.listdir(self.root)):
            path = os.path.join(self.root, name)
            if name.endswith(".tmp"):
                shutil.rmtree(path, ignore_errors=True)
                self._salvage("deleted torn temp dir %s", name)
                continue
            suffix = next(
                (s for s in self._ASIDE_SUFFIXES if name.endswith(s)), None
            )
            if suffix is None:
                continue
            final = path[: -len(suffix)]
            if os.path.exists(final):
                shutil.rmtree(path, ignore_errors=True)
                self._salvage(
                    "deleted orphaned set-aside %s (replacement committed)", name
                )
            else:
                os.rename(path, final)
                self._salvage(
                    "restored set-aside snapshot %s (replacement never landed)",
                    name,
                )

    def _salvage(self, fmt: str, *args) -> None:
        logger.warning("snapshot salvage in %s: " + fmt, self.root, *args)
        _count_event("snapshot_salvage_events")

    def _swap_in(self, tmp: str, final: str) -> None:
        """Commit ``tmp`` over ``final`` without ever unlinking a committed
        snapshot before its replacement is durable: move the old dir aside,
        rename the new one in, THEN delete the set-aside — a crash at any
        point leaves either the old or the new snapshot on disk
        (round-4 advisor finding on _commit_manifest)."""
        if os.path.exists(final):
            aside = final + ".aside"
            if os.path.exists(aside):
                shutil.rmtree(aside)
            os.rename(final, aside)
            os.rename(tmp, final)
            shutil.rmtree(aside, ignore_errors=True)
        else:
            os.rename(tmp, final)  # the commit point

    def list(self) -> List[SnapshotMetadata]:
        """Committed snapshots, newest (highest positions) first."""
        out = []
        for name in os.listdir(self.root):
            meta = SnapshotMetadata.parse(name)
            if meta is not None:
                out.append(meta)
        out.sort(reverse=True)
        return out

    @staticmethod
    def populate_blob_dir(tmp: str, payload: bytes) -> None:
        """Write a single-blob snapshot's content (state + checksum, both
        fsync'd) into ``tmp``. Shared with the chaos plane's crash-point
        injector so simulated crashes leave exactly the on-disk layout a
        real one would."""
        if os.path.exists(tmp):
            shutil.rmtree(tmp)
        os.makedirs(tmp)
        with open(os.path.join(tmp, _STATE_FILE), "wb") as f:
            f.write(payload)
            f.flush()
            os.fsync(f.fileno())
        with open(os.path.join(tmp, _CHECKSUM_FILE), "w") as f:
            f.write(str(zlib.crc32(payload)))
            f.flush()
            os.fsync(f.fileno())

    def write(self, metadata: SnapshotMetadata, payload: bytes) -> None:
        tmp = os.path.join(self.root, metadata.dirname + ".tmp")
        final = os.path.join(self.root, metadata.dirname)
        self.populate_blob_dir(tmp, payload)
        self._swap_in(tmp, final)

    def read(self, metadata: SnapshotMetadata) -> Optional[bytes]:
        """Payload, or None if missing/corrupt (checksum mismatch)."""
        path = os.path.join(self.root, metadata.dirname)
        try:
            with open(os.path.join(path, _STATE_FILE), "rb") as f:
                payload = f.read()
            with open(os.path.join(path, _CHECKSUM_FILE)) as f:
                expected = int(f.read().strip())
        except (OSError, ValueError):
            return None
        if zlib.crc32(payload) != expected:
            return None
        return payload

    def delete(self, metadata: SnapshotMetadata) -> None:
        shutil.rmtree(os.path.join(self.root, metadata.dirname), ignore_errors=True)

    def purge_older_than(self, keep: SnapshotMetadata) -> None:
        """Reference: FsSnapshotStorage purges obsolete snapshots on commit."""
        for meta in self.list():
            if meta < keep:
                self.delete(meta)
        self.gc_segments()

    # -- incremental checkpoints: content-addressed segment store ----------
    # A snapshot is a manifest of named parts, each stored once per content
    # hash under segments/. Unchanged parts (fixed-capacity device tables,
    # deployed workflow resources) are shared across checkpoints, so the
    # per-checkpoint write cost tracks the CHANGED state, not total state
    # size — the analogue of RocksDB checkpoints hard-linking unchanged SST
    # files (logstreams/.../state/StateSnapshotController.java).

    def _segments_root(self) -> str:
        path = os.path.join(self.root, _SEGMENTS_DIR)
        os.makedirs(path, exist_ok=True)
        return path

    def _segment_path(self, h: str) -> str:
        if not _HASH_HEX_RE.match(h):
            raise ValueError(f"bad segment hash {h!r}")
        return os.path.join(self._segments_root(), h + ".seg")

    def has_segment(self, h: str) -> bool:
        return os.path.exists(self._segment_path(h))

    def read_segment(self, h: str) -> Optional[bytes]:
        """Compressed segment bytes as stored (the replication wire unit)."""
        try:
            with open(self._segment_path(h), "rb") as f:
                return f.read()
        except OSError:
            return None

    @staticmethod
    def verify_segment(
        h: str, compressed: bytes, length: int, exact: bool = True
    ) -> Optional[bytes]:
        """THE segment verification: bounded decompress + length +
        content-hash check, shared by local reads, follower installs and
        the replication fetch path (one implementation, so a future
        hardening cannot miss a copy). Returns the decompressed bytes or
        None; ``exact=False`` treats ``length`` as an upper bound."""
        try:
            d = zlib.decompressobj()
            data = d.decompress(compressed, length + 1)
            if d.unconsumed_tail or (
                len(data) != length if exact else len(data) > length
            ):
                return None
        except zlib.error:
            return None
        if part_hash(data) != h:
            return None
        return data

    def install_segment(
        self, h: str, compressed: bytes, max_len: int
    ) -> Optional[bytes]:
        """Verify + persist a fetched segment; returns the decompressed
        bytes (so the caller need not decompress again) or None on any
        violation. The content address makes the transfer self-verifying:
        the decompressed bytes must hash to ``h``."""
        data = self.verify_segment(h, compressed, max_len, exact=False)
        if data is None:
            return None
        self._write_segment(h, compressed)
        return data

    def _write_segment(self, h: str, compressed: bytes) -> None:
        path = self._segment_path(h)
        if os.path.exists(path):
            return
        tmp = path + ".tmp"
        with open(tmp, "wb") as f:
            f.write(compressed)
            f.flush()
            os.fsync(f.fileno())
        os.replace(tmp, path)

    def write_parts(
        self, metadata: SnapshotMetadata, parts: List[Tuple[str, bytes]]
    ) -> Dict[str, int]:
        """Commit a manifest snapshot; returns write-cost stats
        (``new_bytes`` is the incremental cost — bytes whose content hash
        was not already in the segment store)."""
        return self.write_parts_delta(metadata, parts, [])[0]

    def write_parts_delta(
        self,
        metadata: SnapshotMetadata,
        parts: List[Tuple[str, bytes]],
        reused: List[dict],
    ) -> Tuple[Dict[str, int], List[dict]]:
        """Commit a manifest snapshot from freshly encoded ``parts`` plus
        ``reused`` manifest entries (``{"n","h","l"}``) carried over from a
        previous take whose families did not change — those parts were
        never re-read, re-encoded or re-hashed; their segments are already
        in the store. Returns ``(stats, entries)`` with the committed
        manifest entries (the next take's delta base)."""
        stats = {"total_bytes": 0, "new_bytes": 0,
                 "parts": len(parts) + len(reused), "new_segments": 0,
                 "reused_parts": len(reused)}
        entries = []
        for name, data in parts:
            h = part_hash(data)
            stats["total_bytes"] += len(data)
            if not self.has_segment(h):
                self._write_segment(h, zlib.compress(data, 1))
                stats["new_bytes"] += len(data)
                stats["new_segments"] += 1
            entries.append({"n": name, "h": h, "l": len(data)})
        for e in reused:
            stats["total_bytes"] += int(e["l"])
            entries.append({"n": str(e["n"]), "h": str(e["h"]), "l": int(e["l"])})
        # canonical manifest order: sorted by part name, which puts the
        # "_root" part first ("_" < "a" < "h") — the streaming restore
        # relies on reading the root before any family part, and a delta
        # take's manifest is byte-identical to a full take's of the same
        # state regardless of which families were re-encoded
        entries.sort(key=lambda e: e["n"])
        self._commit_manifest(metadata, _pack_manifest(entries))
        return stats, entries

    def iter_parts(self, metadata: SnapshotMetadata):
        """Stream a snapshot's ``(name, payload)`` parts in manifest order,
        verifying each segment as it is read (one decompressed part in
        memory at a time — the restore-side analogue of the wave pipeline's
        per-family readback). Raises :class:`SnapshotPartError` on a
        missing/corrupt manifest or segment; legacy single-blob snapshots
        yield one ``("state", payload)`` part."""
        path = os.path.join(self.root, metadata.dirname)
        if os.path.exists(os.path.join(path, _STATE_FILE)):
            payload = self.read(metadata)
            if payload is None:
                raise SnapshotPartError(f"{metadata.dirname}: corrupt state blob")
            yield "state", payload
            return
        entries = self.manifest(metadata)
        if entries is None:
            raise SnapshotPartError(f"{metadata.dirname}: missing/corrupt manifest")
        for e in entries:
            name, h, length = str(e["n"]), str(e["h"]), int(e["l"])
            compressed = self.read_segment(h)
            if compressed is None:
                raise SnapshotPartError(
                    f"{metadata.dirname}: segment {h} of part {name!r} missing"
                )
            data = self.verify_segment(h, compressed, length)
            if data is None:
                raise SnapshotPartError(
                    f"{metadata.dirname}: segment {h} of part {name!r} "
                    "failed verification (corrupt/truncated/hash mismatch)"
                )
            yield name, data

    def _commit_manifest(self, metadata: SnapshotMetadata, manifest: bytes) -> None:
        """Atomic manifest commit: fsync'd tmp dir, rename = commit point."""
        tmp = os.path.join(self.root, metadata.dirname + ".tmp")
        final = os.path.join(self.root, metadata.dirname)
        if os.path.exists(tmp):
            shutil.rmtree(tmp)
        os.makedirs(tmp)
        with open(os.path.join(tmp, _MANIFEST_FILE), "wb") as f:
            f.write(manifest)
            f.flush()
            os.fsync(f.fileno())
        with open(os.path.join(tmp, _CHECKSUM_FILE), "w") as f:
            f.write(str(zlib.crc32(manifest)))
            f.flush()
            os.fsync(f.fileno())
        self._swap_in(tmp, final)

    def manifest(self, metadata: SnapshotMetadata) -> Optional[List[dict]]:
        """Part list ``[{"n", "h", "l"}, ...]`` of a manifest snapshot, or
        None (missing / corrupt / legacy single-blob snapshot)."""
        path = os.path.join(self.root, metadata.dirname)
        try:
            with open(os.path.join(path, _MANIFEST_FILE), "rb") as f:
                raw = f.read()
            with open(os.path.join(path, _CHECKSUM_FILE)) as f:
                expected = int(f.read().strip())
        except (OSError, ValueError):
            return None
        if zlib.crc32(raw) != expected:
            return None
        return _unpack_manifest(raw)

    def install_manifest(
        self, metadata: SnapshotMetadata, entries: List[dict]
    ) -> bool:
        """Follower side: commit a manifest whose segments are already
        installed. Refuses if any referenced segment is missing."""
        for e in entries:
            if not self.has_segment(str(e["h"])):
                return False
        self._commit_manifest(metadata, _pack_manifest(entries))
        return True

    def read_parts(self, metadata: SnapshotMetadata) -> Optional[Dict[str, bytes]]:
        """Named part payloads of a snapshot (legacy single-blob snapshots
        come back as ``{"state": payload}``); None if missing/corrupt."""
        try:
            return dict(self.iter_parts(metadata))
        except SnapshotPartError:
            return None

    def gc_segments(self) -> int:
        """Delete segments referenced by no committed manifest (with a
        grace period for segments of an install in progress). Returns the
        number of files removed."""
        seg_root = os.path.join(self.root, _SEGMENTS_DIR)
        if not os.path.isdir(seg_root):
            return 0
        referenced = set()
        for meta in self.list():
            for e in self.manifest(meta) or []:
                referenced.add(str(e["h"]))
        removed = 0
        cutoff = time.time() - _SEGMENT_GC_GRACE_SEC
        for name in os.listdir(seg_root):
            if name.endswith(".seg"):
                if name[:-4] in referenced:
                    continue
            elif not name.endswith(".seg.tmp"):
                continue  # .tmp = torn write from a crash: GC after grace
            path = os.path.join(seg_root, name)
            try:
                if os.path.getmtime(path) > cutoff:
                    continue
                os.unlink(path)
                removed += 1
            except OSError:
                continue
        return removed


def _pack_manifest(entries: List[dict]) -> bytes:
    from zeebe_tpu.protocol import msgpack

    return msgpack.pack({"fmt": MANIFEST_FORMAT, "parts": entries})


def _unpack_manifest(raw: bytes) -> Optional[List[dict]]:
    from zeebe_tpu.protocol import msgpack

    try:
        doc = msgpack.unpack(raw)
    except Exception:
        return None
    if not isinstance(doc, dict) or doc.get("fmt") != MANIFEST_FORMAT:
        return None
    parts = doc.get("parts")
    if not isinstance(parts, list):
        return None
    out = []
    for e in parts:
        if not isinstance(e, dict):
            return None
        try:
            name, h, length = str(e["n"]), str(e["h"]), int(e["l"])
        except (KeyError, TypeError, ValueError):
            return None
        if not _HASH_HEX_RE.match(h) or length < 0:
            return None
        out.append({"n": name, "h": h, "l": length})
    return out


@dataclasses.dataclass
class PendingSnapshot:
    """A fenced capture awaiting its (possibly off-thread) commit: the
    dirty families' freshly encoded parts plus the previous manifest's
    entries for the clean ones. Produced by ``SnapshotController.capture``
    on the processing thread; ``commit`` does the hash/compress/fsync work
    and may run anywhere (it touches only this object and the storage)."""

    metadata: SnapshotMetadata
    parts: List[Tuple[str, bytes]]
    reused: List[dict]
    # families captured (None = full take); on commit failure the caller
    # re-marks these dirty so the next take re-captures them
    dirty: Optional[frozenset]
    capture_seconds: float = 0.0
    # set by cluster callers at capture time (engine state is unsafe to
    # read off-actor)
    compaction_floor: Optional[int] = None
    engine: Any = None


class SnapshotController:
    """Takes/recovers engine-state snapshots for one stream processor.

    The processor supplies ``snapshot_state() -> state dict`` and
    ``restore_state(obj)`` (the engine's analogue of the reference's
    ``SnapshotSupport`` composition: ComposedSnapshot over ZbMapSnapshotSupport
    / SerializableWrapper, FsSnapshotController.java).

    Engines that track dirty state families (``snapshot_dirty_families`` /
    ``snapshot_mark_clean`` / ``snapshot_mark_dirty``) get DELTA takes:
    ``capture`` encodes only dirty families and reuses the previous
    manifest's entries for clean ones — no device→host readback, no
    re-encode, no re-hash for unchanged state. The first take of a
    controller incarnation is always full (no delta base yet).

    Payloads are encoded with the explicit data-only codec
    (``zeebe_tpu.log.stateser``), never pickle: snapshots are fetched from
    cluster peers during replication and must be safe to decode untrusted
    (the reference replicates opaque RocksDB files; it never deserializes
    executable objects from peers).
    """

    def __init__(self, storage: SnapshotStorage):
        self.storage = storage
        # write-cost stats of the last take(): {"total_bytes", "new_bytes",
        # "parts", "new_segments", "reused_parts"} — new_bytes is the
        # incremental cost
        self.last_take_stats: Optional[Dict[str, int]] = None
        # name → manifest entry of the newest take committed by THIS
        # controller incarnation; the delta base. None forces a full take
        # (fresh boot, failed commit, or legacy-layout predecessor).
        self._delta_base: Optional[Dict[str, dict]] = None

    def take(self, state: Any, metadata: SnapshotMetadata) -> None:
        """Full take from an already-materialized state (legacy entry;
        engines with dirty tracking go through take_engine/capture)."""
        from zeebe_tpu.log import stateser

        parts = stateser.encode_state_parts(state)
        stats, entries = self.storage.write_parts_delta(metadata, parts, [])
        self._finish_take(metadata, stats, entries)

    def take_engine(self, engine: Any, metadata: SnapshotMetadata) -> Dict[str, int]:
        """Capture + commit in one call (single-threaded brokers). Cluster
        brokers split the two so commit runs off the partition actor."""
        pending = self.capture(engine, metadata)
        try:
            return self.commit(pending)
        except BaseException:
            remark = getattr(engine, "snapshot_mark_dirty", None)
            if remark is not None:
                remark(pending.dirty)
            raise

    # -- capture (on the processing thread, at a wave boundary) ------------
    def capture(self, engine: Any, metadata: SnapshotMetadata) -> PendingSnapshot:
        """Fenced capture: grab + encode ONLY the dirty state families
        (full state when the engine has no tracking or no delta base
        exists). Resets the engine's dirty tracking — mutations from the
        moment capture returns belong to the next take. The pause this
        imposes on serving is the capture time, reported as the
        ``snapshot_capture_pause_seconds`` gauge; the expensive
        hash/compress/fsync work happens in :meth:`commit`."""
        from zeebe_tpu.log import stateser

        t0 = time.perf_counter()
        dirty = None
        if self._delta_base is not None:
            dirty = getattr(engine, "snapshot_dirty_families", lambda: None)()
        reused: List[dict] = []
        if dirty is not None:
            reusable = self._reusable_entries(dirty)
            if reusable is None:
                dirty = None  # base segment vanished: full take
            else:
                reused = reusable
        parts: List[Tuple[str, bytes]] = []
        if dirty is not None:
            state = engine.snapshot_state(families=dirty)
            parts, clean = stateser.encode_state_parts_delta(state, dirty)
            if set(clean) != {e["n"] for e in reused}:
                # part layout drifted from the delta base (should not
                # happen mid-run) — take a full snapshot instead
                dirty = None
                reused = []
        if dirty is None:
            state = engine.snapshot_state()
            parts = stateser.encode_state_parts(state)
        mark_clean = getattr(engine, "snapshot_mark_clean", None)
        if mark_clean is not None:
            mark_clean()  # the capture fence: later mutations → next take
        capture_seconds = time.perf_counter() - t0
        _set_gauge(
            "snapshot_capture_pause_seconds", capture_seconds,
            "Serving pause imposed by the last snapshot capture (encode of "
            "dirty families only; commit runs off the serving path)",
        )
        return PendingSnapshot(
            metadata=metadata, parts=parts, reused=reused,
            dirty=dirty, capture_seconds=capture_seconds, engine=engine,
        )

    def _reusable_entries(self, dirty: frozenset) -> Optional[List[dict]]:
        """Delta-base entries of clean families, verified present in the
        segment store; None when any is gone (forces a full take)."""
        from zeebe_tpu.log import stateser

        out: List[dict] = []
        for name, e in self._delta_base.items():
            family = stateser.part_family(name)
            if family is None or family in dirty:
                continue  # re-encoded on every take / captured as dirty
            if not self.storage.has_segment(str(e["h"])):
                return None
            out.append({"n": name, "h": str(e["h"]), "l": int(e["l"])})
        return out

    # -- commit (anywhere; touches only the pending capture + storage) -----
    def commit(self, pending: PendingSnapshot) -> Dict[str, int]:
        t0 = time.perf_counter()
        try:
            stats, entries = self.storage.write_parts_delta(
                pending.metadata, pending.parts, pending.reused
            )
        except BaseException:
            # on-disk state unknown: never build a delta on it
            self._delta_base = None
            raise
        self._finish_take(pending.metadata, stats, entries)
        _count_event(
            "snapshot_delta_takes" if pending.dirty is not None
            else "snapshot_full_takes",
        )
        _set_gauge(
            "snapshot_take_seconds",
            pending.capture_seconds + (time.perf_counter() - t0),
            "Duration of the last snapshot take (capture + commit)",
        )
        return stats

    def _finish_take(
        self, metadata: SnapshotMetadata, stats: Dict[str, int], entries: List[dict]
    ) -> None:
        self._delta_base = {
            str(e["n"]): {"h": str(e["h"]), "l": int(e["l"])} for e in entries
        }
        self.last_take_stats = stats
        _set_gauge(
            "snapshot_last_new_bytes", stats["new_bytes"],
            "Bytes of the last take not already in the segment store (the "
            "delta cost)",
        )
        _set_gauge(
            "snapshot_last_total_bytes", stats["total_bytes"],
            "Total uncompressed state bytes referenced by the last take",
        )
        self.storage.purge_older_than(metadata)

    def recover(self, log_last_position: int):
        """Newest snapshot whose written position is still on the log.

        Returns (state, metadata) or (None, None). Invalid/corrupt/
        unparseable snapshots are skipped (and the next older one is tried),
        mirroring ``StateSnapshotController.recover`` trying metadata
        candidates — each skip logs a warning naming the snapshot and
        counts into ``snapshot_recover_skipped``: every skip moves recovery
        one snapshot closer to a full-log replay, and operators should see
        that drift. Parts stream per family (one decompressed part in
        memory at a time) and the decode time reports as the
        ``snapshot_restore_seconds`` gauge."""
        from zeebe_tpu.log import stateser

        t0 = time.perf_counter()
        for meta in self.storage.list():
            if meta.last_written_position > log_last_position:
                self._skip(meta, f"written position past log end {log_last_position}")
                continue  # log was truncated past this snapshot: stale
            try:
                state = stateser.decode_state_parts_stream(
                    self.storage.iter_parts(meta)
                )
            except (SnapshotPartError, stateser.SnapshotFormatError) as e:
                self._skip(meta, str(e))
                continue
            # zblint: disable=metrics-hot-loop (runs once: the loop returns right after)
            _set_gauge(
                "snapshot_restore_seconds", time.perf_counter() - t0,
                "Duration of the last snapshot recovery (read + streamed "
                "per-family decode; excludes log replay)",
            )
            return state, meta
        return None, None

    def _skip(self, meta: SnapshotMetadata, reason: str) -> None:
        logger.warning(
            "recovery in %s skipped snapshot %s (%s); falling back to an "
            "older snapshot or full-log replay",
            self.storage.root, meta.dirname, reason,
        )
        _count_event(
            "snapshot_recover_skipped",
            "Snapshots skipped during recovery (stale/corrupt/unreadable)",
        )
