"""Snapshot storage + controller: checkpoint/recover partition state.

Reference parity:
- ``logstreams/.../impl/snapshot/fs/FsSnapshotStorage.java`` /
  ``FsSnapshotController.java`` — snapshots on disk with checksums,
  temp-write then commit-rename.
- ``logstreams/.../state/StateSnapshotController.java`` /
  ``StateSnapshotMetadata.java`` — checkpoints keyed by
  (lastProcessedPosition, lastWrittenPosition, term); recovery picks the
  newest snapshot *valid against the log* (the written position must still
  exist — guards against a truncated/diverged log, the term check of
  ``StreamProcessorController.validateSnapshot:177-187``).

Resume contract (SURVEY.md §5 checkpoint/resume): recover best valid
snapshot, then REPLAY committed records after ``last_processed_position``
to rebuild state without re-executing side effects.
"""

from __future__ import annotations

import dataclasses
import os
import re
import shutil
import zlib
from typing import Any, List, Optional

_SNAPSHOT_DIR_RE = re.compile(r"^snapshot_(-?\d+)_(-?\d+)_(-?\d+)$")
_STATE_FILE = "state.bin"
_CHECKSUM_FILE = "checksum.crc32"


@dataclasses.dataclass(frozen=True, order=True)
class SnapshotMetadata:
    """Reference: StateSnapshotMetadata.java (ordering = recency)."""

    last_processed_position: int
    last_written_position: int
    term: int = 0

    @property
    def dirname(self) -> str:
        return (
            f"snapshot_{self.last_processed_position}"
            f"_{self.last_written_position}_{self.term}"
        )

    @staticmethod
    def parse(dirname: str) -> Optional["SnapshotMetadata"]:
        m = _SNAPSHOT_DIR_RE.match(dirname)
        if not m:
            return None
        return SnapshotMetadata(int(m.group(1)), int(m.group(2)), int(m.group(3)))


class SnapshotStorage:
    """Directory of committed snapshots for one partition/processor.

    Layout: ``{root}/snapshot_{processed}_{written}_{term}/state.bin`` with a
    crc32 checksum file; writes go to a ``.tmp`` sibling and are committed by
    atomic rename (reference FsSnapshotStorage temp-write + commit).
    """

    def __init__(self, root: str):
        self.root = root
        os.makedirs(root, exist_ok=True)
        # sweep torn temp dirs from a crash mid-write
        for name in os.listdir(root):
            if name.endswith(".tmp"):
                shutil.rmtree(os.path.join(root, name), ignore_errors=True)

    def list(self) -> List[SnapshotMetadata]:
        """Committed snapshots, newest (highest positions) first."""
        out = []
        for name in os.listdir(self.root):
            meta = SnapshotMetadata.parse(name)
            if meta is not None:
                out.append(meta)
        out.sort(reverse=True)
        return out

    def write(self, metadata: SnapshotMetadata, payload: bytes) -> None:
        tmp = os.path.join(self.root, metadata.dirname + ".tmp")
        final = os.path.join(self.root, metadata.dirname)
        if os.path.exists(tmp):
            shutil.rmtree(tmp)
        os.makedirs(tmp)
        with open(os.path.join(tmp, _STATE_FILE), "wb") as f:
            f.write(payload)
            f.flush()
            os.fsync(f.fileno())
        with open(os.path.join(tmp, _CHECKSUM_FILE), "w") as f:
            f.write(str(zlib.crc32(payload)))
            f.flush()
            os.fsync(f.fileno())
        if os.path.exists(final):
            shutil.rmtree(final)
        os.rename(tmp, final)  # the commit point

    def read(self, metadata: SnapshotMetadata) -> Optional[bytes]:
        """Payload, or None if missing/corrupt (checksum mismatch)."""
        path = os.path.join(self.root, metadata.dirname)
        try:
            with open(os.path.join(path, _STATE_FILE), "rb") as f:
                payload = f.read()
            with open(os.path.join(path, _CHECKSUM_FILE)) as f:
                expected = int(f.read().strip())
        except (OSError, ValueError):
            return None
        if zlib.crc32(payload) != expected:
            return None
        return payload

    def delete(self, metadata: SnapshotMetadata) -> None:
        shutil.rmtree(os.path.join(self.root, metadata.dirname), ignore_errors=True)

    def purge_older_than(self, keep: SnapshotMetadata) -> None:
        """Reference: FsSnapshotStorage purges obsolete snapshots on commit."""
        for meta in self.list():
            if meta < keep:
                self.delete(meta)


class SnapshotController:
    """Takes/recovers engine-state snapshots for one stream processor.

    The processor supplies ``snapshot_state() -> state dict`` and
    ``restore_state(obj)`` (the engine's analogue of the reference's
    ``SnapshotSupport`` composition: ComposedSnapshot over ZbMapSnapshotSupport
    / SerializableWrapper, FsSnapshotController.java).

    Payloads are encoded with the explicit data-only codec
    (``zeebe_tpu.log.stateser``), never pickle: snapshots are fetched from
    cluster peers during replication and must be safe to decode untrusted
    (the reference replicates opaque RocksDB files; it never deserializes
    executable objects from peers).
    """

    def __init__(self, storage: SnapshotStorage):
        self.storage = storage

    def take(self, state: Any, metadata: SnapshotMetadata) -> None:
        from zeebe_tpu.log import stateser

        payload = stateser.encode_state(state)
        self.storage.write(metadata, payload)
        self.storage.purge_older_than(metadata)

    def recover(self, log_last_position: int):
        """Newest snapshot whose written position is still on the log.

        Returns (state, metadata) or (None, None). Invalid/corrupt/
        unparseable snapshots are skipped (and the next older one is tried),
        mirroring ``StateSnapshotController.recover`` trying metadata
        candidates.
        """
        from zeebe_tpu.log import stateser

        for meta in self.storage.list():
            if meta.last_written_position > log_last_position:
                continue  # log was truncated past this snapshot: stale
            payload = self.storage.read(meta)
            if payload is None:
                continue
            try:
                return stateser.decode_state(payload), meta
            except stateser.SnapshotFormatError:
                continue
        return None, None
