"""Payload input/output mapping processor.

Reference parity: ``json-path/.../mapping/MappingProcessor.java`` —
``extract(document, mappings)`` builds a new msgpack document from
source-path → target-path moves; ``merge(source, target, mappings)`` merges
the (mapped) source document into the target document. With no mappings,
merge is a top-level document merge. A mapping whose source path has no
result raises (→ IO_MAPPING_ERROR incident).
"""

from __future__ import annotations

from typing import Any, Dict, List

from zeebe_tpu.models.bpmn.model import Mapping
from zeebe_tpu.models.el.ast import compile_json_path, query_json_path


class MappingError(ValueError):
    """Reference: MappingException → IO_MAPPING_ERROR incident."""


def _set_path(document: Dict[str, Any], path: str, value: Any) -> None:
    try:
        steps = compile_json_path(path)
    except ValueError as e:
        raise MappingError(str(e)) from None
    if not steps:
        raise MappingError("Target mapping '$' must be the only mapping")
    node = document
    for step in steps[:-1]:
        if not isinstance(step, str):
            raise MappingError(f"Unsupported target path step: {step!r}")
        nxt = node.get(step)
        if not isinstance(nxt, dict):
            nxt = {}
            node[step] = nxt
        node = nxt
    last = steps[-1]
    if not isinstance(last, str):
        raise MappingError(f"Unsupported target path step: {last!r}")
    node[last] = value


def _query(document: Dict[str, Any], path: str):
    """Runtime query: any path error becomes a MappingError so the engine
    raises an IO_MAPPING_ERROR incident instead of crashing the step."""
    try:
        return query_json_path(document, path)
    except ValueError as e:
        raise MappingError(str(e)) from None


def extract(document: Dict[str, Any], mappings: List[Mapping]) -> Dict[str, Any]:
    """Build a new document from mappings (reference MappingProcessor.extract)."""
    result: Dict[str, Any] = {}
    for mapping in mappings:
        found, value = _query(document, mapping.source)
        if not found:
            raise MappingError(
                f"No data found for query {mapping.source}."
            )
        if mapping.target == "$":
            if not isinstance(value, dict):
                raise MappingError(
                    "Processing failed, since mapping will result in a non map object (json object)."
                )
            result = dict(value)
        else:
            _set_path(result, mapping.target, value)
    return result


def merge(
    source: Dict[str, Any],
    target: Dict[str, Any],
    mappings: List[Mapping],
) -> Dict[str, Any]:
    """Merge ``source`` into ``target`` (reference MappingProcessor.merge).

    With mappings: each target path is set to the value at the source path
    in ``source``. Without mappings: top-level merge of source into target.
    """
    result = dict(target)
    if not mappings:
        result.update(source)
        return result
    for mapping in mappings:
        found, value = _query(source, mapping.source)
        if not found:
            raise MappingError(f"No data found for query {mapping.source}.")
        if mapping.target == "$":
            if not isinstance(value, dict):
                raise MappingError(
                    "Processing failed, since mapping will result in a non map object (json object)."
                )
            result = dict(value)
        else:
            _set_path(result, mapping.target, value)
    return result
