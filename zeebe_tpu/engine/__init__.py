"""Stream-processing engines.

Two implementations with one contract (event-replay parity):

- ``zeebe_tpu.engine.interpreter`` — the host reference interpreter: exact
  per-record semantics mirroring the reference broker's stream processors.
  It is the correctness oracle in tests and the recovery/replay fallback.
- ``zeebe_tpu.engine.kernel`` + ``zeebe_tpu.engine.processor`` — the TPU
  engine: batched SIMD state transitions over struct-of-arrays state by a
  jitted step kernel, host loop coupling device sweeps to the log.
"""
