"""Host reference engine: exact per-record stream-processing semantics.

This is the correctness oracle for the TPU engine (event-replay parity) and
the recovery fallback. It mirrors, processor by processor, the reference
broker's per-partition stream processors:

- workflow instance processor + BpmnStepProcessor
  (``broker-core/.../workflow/processor/WorkflowInstanceStreamProcessor.java``,
  ``BpmnStepProcessor.java`` + the 16 step handlers),
- job processor + activate-job push processor
  (``broker-core/.../job/processor/JobInstanceStreamProcessor.java``,
  ``ActivateJobStreamProcessor.java``),
- incident processor (``broker-core/.../incident/processor/IncidentStreamProcessor.java``),
- message processors (``broker-core/.../subscription/message/processor/``),
- deployment processor (``broker-core/.../system/workflow/repository/processor/``).

Determinism contract (deviation by design, documented): the reference runs
these processors as independent actors whose interleaving is scheduler
dependent; here each committed record is routed through the sub-processors
in one fixed registration order, which yields a canonical serializable
interleaving. Cross-processor per-entity record order is preserved.

TPU-native extensions beyond the reference engine (per BASELINE.json):
parallel-gateway fork/join with scope token accounting, timer catch events,
receive tasks, and message-subscription close on termination.
"""

from __future__ import annotations

import dataclasses
import logging
from collections import OrderedDict
from typing import Any, Callable, Dict, List, Optional, Tuple

from zeebe_tpu.engine import keyspace
from zeebe_tpu.engine.mappings import MappingError, extract, merge
from zeebe_tpu.models.bpmn.model import ElementType, OutputBehavior
from zeebe_tpu.models.el.ast import query_json_path
from zeebe_tpu.models.el.interpreter import ConditionEvalError, evaluate_condition
from zeebe_tpu.models.transform.executable import (
    ExecutableFlowElement,
    ExecutableWorkflow,
)
from zeebe_tpu.models.transform.steps import BpmnStep
from zeebe_tpu.protocol.enums import ErrorType, RecordType, RejectionType, ValueType
from zeebe_tpu.protocol.intents import (
    IncidentIntent,
    JobIntent,
    MessageIntent,
    MessageSubscriptionIntent,
    SubscriberIntent,
    SubscriptionIntent,
    TimerIntent,
    WorkflowInstanceIntent as WI,
    WorkflowInstanceSubscriptionIntent,
    is_final_state,
    is_initial_state,
    can_terminate,
)
from zeebe_tpu.protocol.metadata import RecordMetadata
from zeebe_tpu.protocol.records import (
    IncidentRecord,
    JobHeaders,
    JobRecord,
    MessageRecord,
    MessageSubscriptionRecord,
    Record,
    TimerRecord,
    WorkflowInstanceRecord,
    WorkflowInstanceSubscriptionRecord,
)

logger = logging.getLogger(__name__)


# ---------------------------------------------------------------------------
# state
# ---------------------------------------------------------------------------


class ElementInstance:
    """Reference: broker-core/.../workflow/index/ElementInstance.java, plus
    ``active_tokens`` (TPU-native scope token counter for parallel flows)."""

    __slots__ = (
        "key", "parent", "state", "value", "children", "job_key",
        "active_tokens", "join_arrivals", "mi_outputs",
    )

    def __init__(self, key: int, parent: Optional["ElementInstance"]):
        self.key = key
        self.parent = parent
        self.state: Optional[WI] = None
        self.value: Optional[WorkflowInstanceRecord] = None
        self.children: List["ElementInstance"] = []
        self.job_key = -1
        self.active_tokens = 0
        # parallel-join arrival payloads: gateway element idx → {flow idx → payload}
        self.join_arrivals: Dict[int, Dict[int, dict]] = {}
        # multi-instance containers: loopCounter → extracted output value
        self.mi_outputs: Dict[int, Any] = {}
        if parent is not None:
            parent.children.append(self)

    def destroy(self):
        if self.parent is not None and self in self.parent.children:
            self.parent.children.remove(self)

    def can_terminate(self) -> bool:
        return can_terminate(self.state)


class ElementInstanceIndex:
    """Reference: broker-core/.../workflow/index/ElementInstanceIndex.java."""

    def __init__(self):
        self.instances: Dict[int, ElementInstance] = {}

    def new_instance(
        self,
        key: int,
        value: WorkflowInstanceRecord,
        state: WI,
        parent: Optional[ElementInstance] = None,
    ) -> ElementInstance:
        instance = ElementInstance(key, parent)
        instance.state = state
        instance.value = value.copy()
        self.instances[key] = instance
        return instance

    def get(self, key: int) -> Optional[ElementInstance]:
        return self.instances.get(key)

    def remove(self, key: int) -> None:
        instance = self.instances.pop(key, None)
        if instance is not None:
            instance.destroy()


@dataclasses.dataclass
class JobState:
    """Reference: JobInstanceStateController short states in RocksDB."""

    state: int  # JobIntent value of the last state event
    record: JobRecord
    deadline: int = -1


@dataclasses.dataclass
class JobSubscription:
    """Reference: broker-core/.../job/processor/JobSubscription.java."""

    subscriber_key: int
    job_type: str
    worker: str
    timeout: int
    credits: int


@dataclasses.dataclass
class StoredMessage:
    key: int
    name: str
    correlation_key: str
    time_to_live: int
    payload: Dict[str, Any]
    message_id: str
    deadline: int


@dataclasses.dataclass
class StoredSubscription:
    message_name: str
    correlation_key: str
    workflow_instance_partition_id: int
    workflow_instance_key: int
    activity_instance_key: int


@dataclasses.dataclass
class IncidentState:
    state: int  # CREATED / RESOLVING / DELETING (int of IncidentIntent-ish)
    incident_event_position: int
    failure_event_position: int


INCIDENT_CREATED = 1
INCIDENT_RESOLVING = 2
INCIDENT_DELETING = 3


@dataclasses.dataclass
class TimerState:
    due_date: int
    activity_instance_key: int
    record: TimerRecord


class WorkflowRepository:
    """Deployed workflow store (reference: WorkflowRepositoryIndex on the
    system partition + per-partition WorkflowCache; here fetches are
    synchronous in-process, so one shared repository serves all partitions)."""

    def __init__(self):
        self.by_key: Dict[int, ExecutableWorkflow] = {}
        self.versions: Dict[str, List[ExecutableWorkflow]] = {}
        # monotonic mutation counter: the repository is SHARED across
        # partitions (and mutated by workflow fetches outside any record),
        # so snapshot dirty tracking compares this instead of guessing
        # from processed value types
        self.version = 0

    def put(self, workflow: ExecutableWorkflow) -> None:
        self.by_key[workflow.key] = workflow
        self.versions.setdefault(workflow.id, []).append(workflow)
        self.version += 1

    def next_version(self, process_id: str) -> int:
        return len(self.versions.get(process_id, [])) + 1

    def latest(self, process_id: str) -> Optional[ExecutableWorkflow]:
        versions = self.versions.get(process_id)
        return versions[-1] if versions else None

    def by_id_and_version(self, process_id: str, version: int) -> Optional[ExecutableWorkflow]:
        for wf in self.versions.get(process_id, []):
            if wf.version == version:
                return wf
        return None

    def merge(self, workflows: List[ExecutableWorkflow]) -> None:
        """Idempotent restore-merge (snapshot recovery): register unknown
        workflows, keeping version lists sorted so ``latest`` stays correct."""
        for wf in workflows:
            if wf.key not in self.by_key:
                self.put(wf)
        for versions in self.versions.values():
            versions.sort(key=lambda w: w.version)


# ---------------------------------------------------------------------------
# processing result plumbing
# ---------------------------------------------------------------------------


class RecordCache:
    """Position → record cache with a bounded in-heap hot window and a
    native keyed cold store behind it.

    The reference keeps keyed processor state in RocksDB
    (``logstreams/.../state/StateController.java:24-50``); this is that
    role for the oracle's position-based reads (incident resolution
    re-reads its failure event by position, reference TypedStreamReader):
    the newest ``hot_capacity`` records stay as Python objects, older ones
    spill to ``native/kvstore.cc`` as encoded frames. Without the native
    toolchain the cache degrades to a plain unbounded dict (the
    round-2 behavior)."""

    def __init__(self, hot_capacity: int = 8192):
        self._hot: "OrderedDict[int, Record]" = OrderedDict()
        self._hot_capacity = hot_capacity
        # position-addressed fallback (the partition's LOG, installed by
        # the brokers): every cached record IS a log record, and the
        # engine's compaction floor pins exactly the positions incident
        # resolution re-reads — so with a log behind the cache, eviction
        # needs NO spill copy at all. The KV spill (encoded frame per
        # evicted record) was ~a third of the serving drain's host CPU.
        self._log_lookup = None
        self._kv = None
        try:
            from zeebe_tpu import native as _native

            if _native.available():
                self._kv = _native.KvStore()
        except Exception:  # noqa: BLE001 - cold store is an optimization
            self._kv = None

    def set_log_lookup(self, lookup) -> None:
        """Install ``lookup(position) -> Optional[Record]`` (the log's
        ``record_at``); eviction stops paying the encode+KV spill."""
        self._log_lookup = lookup

    def __setitem__(self, position: int, record: Record) -> None:
        self._hot[position] = record
        self._hot.move_to_end(position)
        if len(self._hot) <= self._hot_capacity:
            return
        if self._log_lookup is not None:
            self._hot.popitem(last=False)  # the log serves old positions
            return
        if self._kv is not None:
            old_pos, old_rec = self._hot.popitem(last=False)
            try:
                from zeebe_tpu.protocol import codec as _codec

                self._kv.put(
                    old_pos.to_bytes(8, "little", signed=True),
                    _codec.encode_record(old_rec),
                )
            except Exception:  # noqa: BLE001 - keep it hot on encode failure
                self._hot[old_pos] = old_rec
                self._hot.move_to_end(old_pos, last=False)

    def get(self, position: int, default=None):
        record = self._hot.get(position)
        if record is not None:
            return record
        if self._log_lookup is not None:
            record = self._log_lookup(position)
            if record is not None:
                return record
        if self._kv is not None:
            blob = self._kv.get(position.to_bytes(8, "little", signed=True))
            if blob is not None:
                from zeebe_tpu.protocol import codec as _codec

                record, _ = _codec.decode_record(blob, 0)
                return record
        return default

    def __contains__(self, position: int) -> bool:
        return self.get(position) is not None

    def __len__(self) -> int:
        return len(self._hot) + (len(self._kv) if self._kv is not None else 0)


@dataclasses.dataclass
class ProcessingResult:
    """Output of processing one committed record."""

    written: List[Record] = dataclasses.field(default_factory=list)
    responses: List[Record] = dataclasses.field(default_factory=list)
    # cross-partition sends (reference: subscription transport messages):
    # (target_partition_id, record-to-write-as-command)
    sends: List[Tuple[int, Record]] = dataclasses.field(default_factory=list)
    # job pushes to subscribers: (subscriber_key, record)
    pushes: List[Tuple[int, Record]] = dataclasses.field(default_factory=list)

    @classmethod
    def merged(cls, results) -> "ProcessingResult":
        """Record-major merge of per-record results (every output channel;
        the ONE place to extend when ProcessingResult grows a field)."""
        out = cls()
        for res in results:
            out.written.extend(res.written)
            out.responses.extend(res.responses)
            out.sends.extend(res.sends)
            out.pushes.extend(res.pushes)
        return out


def _record(
    record_type: RecordType,
    value,
    intent: int,
    key: int = -1,
    source_position: int = -1,
    metadata_extra: Optional[dict] = None,
) -> Record:
    md = RecordMetadata(
        record_type=record_type,
        value_type=value.VALUE_TYPE,
        intent=int(intent),
    )
    if metadata_extra:
        for k, v in metadata_extra.items():
            setattr(md, k, v)
    return Record(
        key=key,
        source_record_position=source_position,
        metadata=md,
        value=value,
    )


# ---------------------------------------------------------------------------
# the engine
# ---------------------------------------------------------------------------


class PartitionEngine:
    """Reference-semantics stream processor for one partition."""

    def __init__(
        self,
        partition_id: int = 0,
        num_partitions: int = 1,
        repository: Optional[WorkflowRepository] = None,
        clock: Optional[Callable[[], int]] = None,
    ):
        self.partition_id = partition_id
        self.num_partitions = num_partitions
        self.repository = repository if repository is not None else WorkflowRepository()
        self.clock = clock or (lambda: 0)

        # key generators (reference KeyGenerator.create*KeyGenerator)
        self.wf_keys = keyspace.workflow_instance_keys()
        self.job_keys = keyspace.job_keys()
        self.incident_keys = keyspace.incident_keys()
        self.deployment_keys = keyspace.deployment_keys()

        # workflow state
        self.element_instances = ElementInstanceIndex()

        # job state
        self.jobs: Dict[int, JobState] = {}
        self.job_subscriptions: List[JobSubscription] = []
        self._job_rr_cursor = 0
        # jobs that became activatable while every matching subscription
        # was out of credits: type → insertion-ordered key set. The
        # reference never strands these — ActivateJobStreamProcessor
        # pauses its log reader on credit exhaustion and RESUMES where it
        # stopped when credits return; this index is the bounded-memory
        # equivalent (backlog_activations drains it on credit return /
        # broker tick). Entries are verified against live job state on
        # pop, so stale keys (completed/canceled meanwhile) just drop.
        self._awaiting_jobs: Dict[str, Dict[int, None]] = {}

        # incident state (reference IncidentStreamProcessor maps)
        self.incidents: Dict[int, IncidentState] = {}
        self.incident_by_activity: Dict[int, int] = {}
        self.incident_by_failed_job: Dict[int, int] = {}
        self.resolving_events: Dict[int, int] = {}  # failure-event position → incident key
        self.incident_records: Dict[int, IncidentRecord] = {}

        # message state (this partition acting as message partition)
        self.messages: Dict[int, StoredMessage] = {}
        self.message_subscriptions: List[StoredSubscription] = []

        # timers (TPU-native)
        self.timers: Dict[int, TimerState] = {}

        # interrupting-boundary continuations: host instance key →
        # (boundary element id, trigger payload); set when the trigger
        # terminates the host, consumed when ELEMENT_TERMINATED processes
        self._pending_boundary: Dict[int, tuple] = {}

        # topic subscription ack state (reference TopicSubscriberState:
        # per-subscription last acked position, persisted in the log)
        self.topic_sub_acks: Dict[str, int] = {}
        self.topic_sub_keys = keyspace.topic_subscriber_keys()

        # exporter export progress (reference ExportersState: per exporter
        # the last log position it durably exported; replicated through
        # EXPORTER ACKNOWLEDGE records so a new leader resumes without
        # gaps, and pins the compaction floor until exported)
        self.exporter_positions: Dict[str, int] = {}

        # poison-record isolation (reference StreamProcessor onError):
        # (position, error) for records whose handler raised; they are
        # skipped by process_batch, never retried
        self.processing_failures: List[tuple] = []

        # topic orchestration state, system partition only (reference
        # KnownTopics + the IdGenerator stream processor: partition ids are
        # assigned deterministically from replicated state)
        self.topics: Dict[str, dict] = {}
        self.topic_keys = keyspace.topic_keys()
        self.next_partition_id = 1  # 0 is the system partition

        # log access for position-based reads (reference TypedStreamReader,
        # backed by the keyed cold-state store when the native layer is
        # present — the RocksDB StateController analogue; in-heap otherwise)
        self.records_by_position = RecordCache()

        self.last_processed_position = -1

        # delta-snapshot dirty tracking: families ("h/<family>") mutated
        # since the last snapshot_mark_clean(); None = tracking cold
        # (everything assumed dirty — fresh or restored engine)
        self._dirty_families: Optional[set] = None
        self._repo_version_at_clean: Optional[int] = None

    # -- partition routing (reference SubscriptionCommandSender:96-108) ----
    def partition_for_correlation_key(self, correlation_key: str) -> int:
        return _correlation_hash(correlation_key) % self.num_partitions

    # -- snapshot support (reference: ComposedSnapshot of the processor's
    # state resources — ElementInstanceIndex SerializableWrapper, job RocksDB
    # checkpoint, incident/message maps; SURVEY.md §5 checkpoint/resume) ----

    # Dirty-family tracking for delta snapshots: which state families
    # (log/stateser.py HOST_FAMILIES, "h/" namespace) a record of a given
    # value type may mutate. CONSERVATIVE supersets derived from the
    # handler dispatch — over-marking merely re-encodes a clean family;
    # under-marking would silently corrupt delta takes (the chaos
    # delta-vs-full invariant is the regression net). "h/control" appears
    # everywhere because every processed record advances
    # last_processed_position; "h/workflows" is tracked separately via
    # WorkflowRepository.version (the repository is shared and mutated by
    # fetches outside record processing).
    _VT_DIRTY_FAMILIES = {
        int(ValueType.DEPLOYMENT): frozenset({"h/control"}),
        int(ValueType.WORKFLOW_INSTANCE): frozenset(
            {"h/instances", "h/incidents", "h/control"}),
        int(ValueType.JOB): frozenset(
            {"h/jobs", "h/instances", "h/incidents", "h/control"}),
        # RESOLVE re-writes the failure event via _write_wi_followup,
        # which mutates the element-instance index directly
        int(ValueType.INCIDENT): frozenset(
            {"h/incidents", "h/instances", "h/control"}),
        int(ValueType.MESSAGE): frozenset({"h/messages", "h/control"}),
        int(ValueType.MESSAGE_SUBSCRIPTION): frozenset(
            {"h/messages", "h/control"}),
        int(ValueType.WORKFLOW_INSTANCE_SUBSCRIPTION): frozenset(
            {"h/instances", "h/messages", "h/control"}),
        int(ValueType.TIMER): frozenset(
            {"h/timers", "h/instances", "h/control"}),
        int(ValueType.SUBSCRIBER): frozenset({"h/control"}),
        int(ValueType.SUBSCRIPTION): frozenset({"h/control"}),
        int(ValueType.EXPORTER): frozenset({"h/control"}),
        int(ValueType.TOPIC): frozenset({"h/control"}),
        int(ValueType.NOOP): frozenset({"h/control"}),
        int(ValueType.RAFT): frozenset({"h/control"}),
    }

    def snapshot_dirty_families(self):
        """Families mutated since the last ``snapshot_mark_clean`` (the
        ``"h/<family>"`` names of log/stateser.HOST_FAMILIES), or None when
        tracking is cold (fresh/restored engine) — the controller takes a
        full snapshot then."""
        if self._dirty_families is None:
            return None
        dirty = set(self._dirty_families)
        if (
            self._repo_version_at_clean is None
            or self.repository.version != self._repo_version_at_clean
        ):
            dirty.add("h/workflows")
        return frozenset(dirty)

    def snapshot_mark_clean(self) -> None:
        """Reset tracking at a capture fence: mutations from now on belong
        to the NEXT snapshot."""
        self._dirty_families = set()
        self._repo_version_at_clean = self.repository.version

    def snapshot_mark_dirty(self, families=None) -> None:
        """Re-mark families dirty (None = everything) — used when a take
        fails after its capture fence already reset the tracking."""
        if families is None:
            self._dirty_families = None
            self._repo_version_at_clean = None
            return
        if "h/workflows" in families:
            self._repo_version_at_clean = None
        if self._dirty_families is not None:
            self._dirty_families.update(families)

    def _mark_dirty_for_record(self, value_type) -> None:
        if self._dirty_families is None:
            return
        families = self._VT_DIRTY_FAMILIES.get(int(value_type))
        if families is None:
            # unknown value type: assume everything mutated (safety over
            # delta efficiency)
            self._dirty_families = None
            self._repo_version_at_clean = None
            return
        self._dirty_families.update(families)

    def compaction_floor(self) -> int:
        """Highest log position below which records may be compacted away
        (exclusive). Open incidents re-read their failure event from the
        log on resolution (reference TypedStreamReader by position), so
        those positions must survive until the incident is deleted."""
        floor = self.last_processed_position + 1
        for incident in self.incidents.values():
            if incident.failure_event_position >= 0:
                floor = min(floor, incident.failure_event_position)
            if incident.incident_event_position >= 0:
                floor = min(floor, incident.incident_event_position)
        # durable topic subscriptions resume from their logged acks — the
        # records past a subscriber's ack must survive compaction or the
        # subscriber silently loses them (reference: segment deletion is
        # bounded by exporter/subscriber positions)
        for acked in self.topic_sub_acks.values():
            floor = min(floor, acked + 1)
        # exporters bound segment deletion the same way (reference: "the
        # broker deletes segments only up to the lowest exporter
        # position"): a registered exporter with no progress yet (-1)
        # pins the floor at 0
        for acked in self.exporter_positions.values():
            floor = min(floor, acked + 1)
        return floor

    def snapshot_state(self, families=None) -> dict:
        """All log-derived state. Excludes transient client-session state
        (job subscriptions re-register after failover, as in the reference)
        and the position→record cache (rebuilt from the log on recovery).

        ``families`` (a dirty-family set from ``snapshot_dirty_families``)
        is accepted for interface parity with the device engine, where a
        partial capture skips device→host readback; host state is plain
        references, so the dict is cheap either way — the delta filtering
        happens at encode time (``stateser.encode_state_parts_delta``)."""
        return {
            "wf_keys": self.wf_keys,
            "job_keys": self.job_keys,
            "incident_keys": self.incident_keys,
            "deployment_keys": self.deployment_keys,
            "element_instances": self.element_instances,
            "jobs": self.jobs,
            "incidents": self.incidents,
            "incident_by_activity": self.incident_by_activity,
            "incident_by_failed_job": self.incident_by_failed_job,
            "resolving_events": self.resolving_events,
            "incident_records": self.incident_records,
            "messages": self.messages,
            "message_subscriptions": self.message_subscriptions,
            "timers": self.timers,
            "pending_boundary": self._pending_boundary,
            "awaiting_jobs": self._awaiting_jobs,
            "topic_sub_acks": self.topic_sub_acks,
            "exporter_positions": self.exporter_positions,
            "topics": self.topics,
            "next_partition_id": self.next_partition_id,
            "last_processed_position": self.last_processed_position,
            # deployed workflows ride along so a restored partition does not
            # depend on replaying the deployment partition (reference:
            # WorkflowCache refetches; here the repository is restored)
            "workflows": list(self.repository.by_key.values()),
        }

    def restore_state(self, state: dict) -> None:
        # a restored engine's tracking is cold: the next take is full
        self.snapshot_mark_dirty(None)
        self.wf_keys = state["wf_keys"]
        self.job_keys = state["job_keys"]
        self.incident_keys = state["incident_keys"]
        self.deployment_keys = state["deployment_keys"]
        self.element_instances = state["element_instances"]
        self.jobs = state["jobs"]
        self.incidents = state["incidents"]
        self.incident_by_activity = state["incident_by_activity"]
        self.incident_by_failed_job = state["incident_by_failed_job"]
        self.resolving_events = state["resolving_events"]
        self.incident_records = state["incident_records"]
        self.messages = state["messages"]
        self.message_subscriptions = state["message_subscriptions"]
        self.timers = state["timers"]
        self._pending_boundary = state.get("pending_boundary", {})
        self._awaiting_jobs = state.get("awaiting_jobs", {})
        self.topic_sub_acks = state.get("topic_sub_acks", {})
        self.exporter_positions = state.get("exporter_positions", {})
        self.topics = state.get("topics", {})
        self.next_partition_id = state.get("next_partition_id", 1)
        self.last_processed_position = state["last_processed_position"]
        self.repository.merge(state["workflows"])

    # ------------------------------------------------------------------
    # main entry: process one committed record
    # ------------------------------------------------------------------
    def process_batch(self, records: List[Record]) -> ProcessingResult:
        """Batch drain: per-record processing with per-record source
        stamping, merged in log order (the device engine overrides this
        with real SIMD batching).

        Failure containment (reference StreamProcessorController onError →
        skip/blacklist, ``StreamProcessorController.java:296-399``): a record
        whose handler raises is logged, recorded in ``processing_failures``,
        answered with a PROCESSING_ERROR rejection when it was a client
        command, and SKIPPED — a poison record cannot wedge the partition by
        re-raising on every drain (round-3 advisor finding). Determinism
        note: handlers fail deterministically (pure functions of record +
        state), so replay re-raises at the same point and reconverges on the
        same partial mutations; the skip is replay-stable."""
        return ProcessingResult.merged(self.process_wave(records))

    # value types the wave fold handles WITHOUT the full per-record
    # dispatch: pure state-fold records that never produce follow-ups and
    # are never re-read by position (the plane's own admin traffic — on an
    # exporter-heavy partition every dispatched batch appends an ack that
    # flows back through here)
    _FOLD_VTS = frozenset(
        {int(ValueType.NOOP), int(ValueType.RAFT), int(ValueType.EXPORTER)}
    )

    def process_wave(self, records) -> List[ProcessingResult]:
        """One drained wave → PER-RECORD results (source-stamped). The
        in-process broker applies each record's sends/appends in record
        order, so a wave-drained log stays byte-identical to
        record-at-a-time processing even when sends target the local
        partition; the device engine overrides this with one SIMD dispatch
        per wave. Failure containment is per record (see process_batch).

        ``records`` may be a plain list or a columnar view
        (``RecordsView``/``ColumnarBatch``); the wave FOLDS over the
        value-type column for the plane's own admin records (NOOP / RAFT
        no-ops, EXPORTER position acks) — no position-cache insert, no
        handler dispatch — and runs the full per-record path for
        everything else."""
        import time as _time

        from zeebe_tpu.protocol.records import stamp_source_positions

        t0 = _time.perf_counter()
        results: List[ProcessingResult] = []
        fold_vts = self._FOLD_VTS
        command = RecordType.COMMAND
        for record in records:
            md = record.metadata
            if int(md.value_type) in fold_vts:
                # column fold: state-only admin record. EXPORTER acks fold
                # into exporter_positions; NOOP/RAFT only advance the
                # processed position. Both dirty h/control exactly like
                # the dispatched path (_VT_DIRTY_FAMILIES) and emit no
                # follow-ups, so skipping dispatch is byte-invisible.
                out = ProcessingResult()
                if self._dirty_families is not None:
                    self._dirty_families.add("h/control")
                if (
                    int(md.value_type) == int(ValueType.EXPORTER)
                    and md.record_type == command
                ):
                    try:
                        self._process_exporter_ack(record, out)
                    except Exception as e:  # noqa: BLE001 - poison isolation
                        self._contain_processing_failure(record, e, out)
                self.last_processed_position = record.position
                results.append(out)
                continue
            try:
                res = self.process(record)
            except Exception as e:  # noqa: BLE001 - poison-record isolation
                res = ProcessingResult()
                self._contain_processing_failure(record, e, res)
            else:
                stamp_source_positions(res.written, record.position)
            results.append(res)
        # (host_seconds, device_seconds) of the last wave — the serving
        # metrics' time-split source; pure host engine ⇒ device share 0
        self.last_wave_seconds = (_time.perf_counter() - t0, 0.0)
        return results

    def _contain_processing_failure(
        self, record: Record, exc: Exception, merged: ProcessingResult
    ) -> None:
        """Record, log, and (for client commands) answer a record whose
        handler raised, so the client sees a rejection instead of hanging
        to its request timeout."""
        self.processing_failures.append((record.position, repr(exc)[:300]))
        logger.error(
            "record at position %d (valueType=%s intent=%s) poisoned the "
            "engine and was skipped: %r",
            record.position, record.metadata.value_type,
            record.metadata.intent, exc,
        )
        if (
            record.metadata.record_type == RecordType.COMMAND
            and record.metadata.request_id >= 0
        ):
            try:
                rejection = _record(
                    RecordType.COMMAND_REJECTION, record.value.copy(),
                    record.metadata.intent, record.key, record.position,
                    {
                        "rejection_type": RejectionType.PROCESSING_ERROR,
                        "rejection_reason": f"processing failed: {exc!r}"[:200],
                        "request_id": record.metadata.request_id,
                        "request_stream_id": record.metadata.request_stream_id,
                    },
                )
            except Exception:  # noqa: BLE001 - the value itself may be broken
                return
            merged.responses.append(rejection)

    def process(self, record: Record) -> ProcessingResult:
        self.records_by_position[record.position] = record
        self._mark_dirty_for_record(record.metadata.value_type)
        out = ProcessingResult()
        vt = record.metadata.value_type
        rt = record.metadata.record_type
        intent = record.metadata.intent

        if vt == ValueType.DEPLOYMENT and rt == RecordType.COMMAND:
            self._process_deployment(record, out)
        elif vt == ValueType.WORKFLOW_INSTANCE:
            self._process_workflow_instance(record, out)
            self._incident_on_workflow_record(record, out)
        elif vt == ValueType.JOB:
            if rt == RecordType.COMMAND:
                self._process_job_command(record, out)
            else:
                self._workflow_on_job_event(record, out)
                self._activate_jobs_on_event(record, out)
                self._incident_on_job_event(record, out)
        elif vt == ValueType.INCIDENT:
            self._process_incident(record, out)
        elif vt == ValueType.MESSAGE and rt == RecordType.COMMAND:
            self._process_message_command(record, out)
        elif vt == ValueType.MESSAGE_SUBSCRIPTION and rt == RecordType.COMMAND:
            self._process_message_subscription(record, out)
        elif vt == ValueType.WORKFLOW_INSTANCE_SUBSCRIPTION and rt == RecordType.COMMAND:
            self._process_wi_subscription(record, out)
        elif vt == ValueType.TIMER and rt == RecordType.COMMAND:
            self._process_timer(record, out)
        elif vt == ValueType.SUBSCRIBER and rt == RecordType.COMMAND:
            self._process_topic_subscriber(record, out)
        elif vt == ValueType.SUBSCRIPTION and rt == RecordType.COMMAND:
            self._process_topic_subscription_ack(record, out)
        elif vt == ValueType.EXPORTER and rt == RecordType.COMMAND:
            self._process_exporter_ack(record, out)
        elif vt == ValueType.TOPIC and rt == RecordType.COMMAND:
            self._process_topic(record, out)

        self.last_processed_position = record.position
        return out

    # -- topic orchestration, system partition (reference
    # TopicCreateProcessor / TopicCreatedProcessor + IdGenerator) ----------
    def _process_topic(self, record: Record, out: ProcessingResult) -> None:
        from zeebe_tpu.protocol.intents import TopicIntent

        intent = TopicIntent(record.metadata.intent)
        value = record.value
        request_meta = {
            "request_id": record.metadata.request_id,
            "request_stream_id": record.metadata.request_stream_id,
        }
        if intent == TopicIntent.CREATE:
            if not value.name:
                self._topic_rejection(record, "topic name must not be empty", out)
                return
            if value.partitions <= 0:
                self._topic_rejection(record, "partition count must be positive", out)
                return
            if value.name in self.topics:
                self._topic_rejection(record, f"topic '{value.name}' already exists", out)
                return
            created = value.copy()
            # deterministic id assignment from replicated state (reference
            # IdGenerator: ids survive failover because they come from the
            # replicated log, never from local counters)
            created.partition_ids = [
                self.next_partition_id + i for i in range(value.partitions)
            ]
            self.next_partition_id += value.partitions
            key = self.topic_keys.next_key()
            self.topics[created.name] = {"record": created, "state": "CREATING"}
            # CREATING carries the client request metadata: the response is
            # deferred until CREATE_COMPLETE confirms leaders exist
            out.written.append(
                _record(RecordType.EVENT, created.copy(), TopicIntent.CREATING,
                        key, record.position, request_meta)
            )
        elif intent == TopicIntent.CREATE_COMPLETE:
            topic = self.topics.get(value.name)
            if topic is None or topic["state"] == "CREATED":
                return
            topic["state"] = "CREATED"
            done = _record(
                RecordType.EVENT, topic["record"].copy(), TopicIntent.CREATED,
                record.key, record.position, request_meta,
            )
            out.written.append(done)
            out.responses.append(done)

    def _topic_rejection(self, record: Record, reason: str, out: ProcessingResult) -> None:
        rejection = _record(
            RecordType.COMMAND_REJECTION, record.value.copy(),
            record.metadata.intent, record.key, record.position,
            {
                "rejection_type": RejectionType.BAD_VALUE,
                "rejection_reason": reason,
                "request_id": record.metadata.request_id,
                "request_stream_id": record.metadata.request_stream_id,
            },
        )
        out.written.append(rejection)
        out.responses.append(rejection)

    # -- topic subscriptions (reference TopicSubscriptionManagementProcessor)
    def _process_topic_subscriber(self, record: Record, out: ProcessingResult) -> None:
        intent = SubscriberIntent(record.metadata.intent)
        if intent != SubscriberIntent.SUBSCRIBE:
            return
        value = record.value
        key = self.topic_sub_keys.next_key()
        if value.force_start:
            # reference: forceStart resets persisted progress
            self.topic_sub_acks.pop(value.name, None)
        subscribed = _record(
            RecordType.EVENT, value.copy(), SubscriberIntent.SUBSCRIBED, key,
            record.position,
            {
                "request_id": record.metadata.request_id,
                "request_stream_id": record.metadata.request_stream_id,
            },
        )
        out.written.append(subscribed)
        out.responses.append(subscribed)

    def _process_topic_subscription_ack(self, record: Record, out: ProcessingResult) -> None:
        intent = SubscriptionIntent(record.metadata.intent)
        if intent != SubscriptionIntent.ACKNOWLEDGE:
            return
        value = record.value
        prior = self.topic_sub_acks.get(value.name, -1)
        if value.ack_position > prior:
            self.topic_sub_acks[value.name] = value.ack_position
        out.written.append(
            _record(RecordType.EVENT, value.copy(), SubscriptionIntent.ACKNOWLEDGED,
                    record.key, record.position)
        )

    # -- exporter position acks (reference: exporter positions column in
    # broker state, ExporterDirector#updateLastExportedPosition) -----------
    def _process_exporter_ack(self, record: Record, out: ProcessingResult) -> None:
        from zeebe_tpu.protocol.intents import ExporterIntent

        intent = ExporterIntent(record.metadata.intent)
        value = record.value
        if not value.exporter_id:
            return
        if intent == ExporterIntent.REMOVE:
            # deconfigured exporter: drop its entry so a stale position
            # (possibly the -1 registration) stops pinning the compaction
            # floor forever (the director appends REMOVE on open for
            # recovered ids no longer in its configured set)
            self.exporter_positions.pop(value.exporter_id, None)
            return
        if intent != ExporterIntent.ACKNOWLEDGE:
            return
        # monotonic: a late/duplicate ack (director retry after failover)
        # never rewinds export progress. position -1 REGISTERS an exporter
        # before its first ack so compaction is pinned from the start.
        prior = self.exporter_positions.get(value.exporter_id)
        if prior is None or value.position > prior:
            self.exporter_positions[value.exporter_id] = value.position
        # no follow-up event: the ack command itself is the durable,
        # replicated artifact (state-only update, nothing re-processable)

    # ------------------------------------------------------------------
    # writers (reference TypedStreamWriter / ElementInstanceWriter)
    # ------------------------------------------------------------------
    def _write_new_wi_event(
        self, out: ProcessingResult, source: Record, state: WI, value: WorkflowInstanceRecord
    ) -> int:
        """Reference ElementInstanceWriter.writeNewEvent."""
        key = self.wf_keys.next_key()
        out.written.append(
            _record(RecordType.EVENT, value.copy(), state, key, source.position)
        )
        if is_initial_state(state):
            scope_key = value.scope_instance_key
            parent = self.element_instances.get(scope_key) if scope_key >= 0 else None
            self.element_instances.new_instance(key, value, state, parent)
        return key

    def _write_wi_followup(
        self, out: ProcessingResult, source: Record, key: int, state: WI,
        value: WorkflowInstanceRecord, metadata_extra: Optional[dict] = None,
    ) -> None:
        """Reference ElementInstanceWriter.writeFollowUpEvent."""
        out.written.append(
            _record(RecordType.EVENT, value.copy(), state, key, source.position, metadata_extra)
        )
        if is_final_state(state):
            self.element_instances.remove(key)
        else:
            instance = self.element_instances.get(key)
            if instance is not None:
                instance.state = state
                instance.value = value.copy()

    # ------------------------------------------------------------------
    # deployment (reference DeploymentCreateEventProcessor)
    # ------------------------------------------------------------------
    def _process_deployment(self, record: Record, out: ProcessingResult) -> None:
        from zeebe_tpu.models.bpmn.validation import validate_model
        from zeebe_tpu.models.bpmn.xml import read_model
        from zeebe_tpu.models.bpmn.yaml_front import read_yaml_workflow
        from zeebe_tpu.models.transform.transformer import transform_model
        from zeebe_tpu.protocol.intents import DeploymentIntent
        from zeebe_tpu.protocol.records import DeployedWorkflowMeta

        deployment = record.value
        deployed: List[ExecutableWorkflow] = []
        try:
            for resource in deployment.resources:
                data = resource.resource
                if isinstance(data, str):
                    data = data.encode("utf-8")
                if resource.resource_type == "YAML_WORKFLOW":
                    model = read_yaml_workflow(data.decode("utf-8"))
                else:
                    model = read_model(data)
                errors = validate_model(model)
                if errors:
                    raise ValueError("; ".join(str(e) for e in errors))
                for wf in transform_model(model):
                    wf.source_resource = data
                    wf.source_type = resource.resource_type
                    deployed.append(wf)
        except Exception as e:  # malformed resource → rejection
            out.written.append(
                _record(
                    RecordType.COMMAND_REJECTION,
                    deployment,
                    DeploymentIntent.CREATE,
                    record.key,
                    record.position,
                    {
                        "rejection_type": RejectionType.BAD_VALUE,
                        "rejection_reason": str(e),
                        "request_id": record.metadata.request_id,
                        "request_stream_id": record.metadata.request_stream_id,
                    },
                )
            )
            out.responses.append(out.written[-1])
            return

        key = self.deployment_keys.next_key()
        deployment.deployed_workflows = []
        for wf in deployed:
            wf.version = self.repository.next_version(wf.id)
            wf.key = self.deployment_keys.next_key()
            self.repository.put(wf)
            deployment.deployed_workflows.append(
                DeployedWorkflowMeta(
                    bpmn_process_id=wf.id, version=wf.version, key=wf.key
                )
            )
        created = _record(
            RecordType.EVENT,
            deployment,
            DeploymentIntent.CREATED,
            key,
            record.position,
            {
                "request_id": record.metadata.request_id,
                "request_stream_id": record.metadata.request_stream_id,
            },
        )
        out.written.append(created)
        out.responses.append(created)

    # ------------------------------------------------------------------
    # workflow instance records
    # ------------------------------------------------------------------
    def _process_workflow_instance(self, record: Record, out: ProcessingResult) -> None:
        intent = WI(record.metadata.intent)
        rt = record.metadata.record_type
        if rt == RecordType.COMMAND:
            if intent == WI.CREATE:
                self._create_workflow_instance(record, out)
            elif intent == WI.CANCEL:
                self._cancel_workflow_instance(record, out)
            elif intent == WI.UPDATE_PAYLOAD:
                self._update_payload(record, out)
            return
        if rt != RecordType.EVENT:
            return
        if intent == WI.CREATED:
            # reference WorkflowInstanceCreatedEventProcessor
            self.element_instances.new_instance(record.key, record.value, WI.ELEMENT_READY)
            out.responses.append(record)
            return
        if intent in (
            WI.SEQUENCE_FLOW_TAKEN,
            WI.ELEMENT_READY,
            WI.ELEMENT_ACTIVATED,
            WI.ELEMENT_COMPLETING,
            WI.ELEMENT_COMPLETED,
            WI.ELEMENT_TERMINATING,
            WI.ELEMENT_TERMINATED,
            WI.START_EVENT_OCCURRED,
            WI.END_EVENT_OCCURRED,
            WI.GATEWAY_ACTIVATED,
            WI.BOUNDARY_EVENT_OCCURRED,
        ):
            self._bpmn_step(record, intent, out)

    def _create_workflow_instance(self, command: Record, out: ProcessingResult) -> None:
        """Reference CreateWorkflowInstanceEventProcessor (fetches are
        synchronous here; key generated before lookup for replay parity)."""
        value: WorkflowInstanceRecord = command.value.copy()
        wf_instance_key = self.wf_keys.next_key()
        value.workflow_instance_key = wf_instance_key

        workflow = None
        if value.workflow_key > 0:
            workflow = self.repository.by_key.get(value.workflow_key)
        elif value.version > 0:
            workflow = self.repository.by_id_and_version(value.bpmn_process_id, value.version)
        else:
            workflow = self.repository.latest(value.bpmn_process_id)

        md_extra = {
            "request_id": command.metadata.request_id,
            "request_stream_id": command.metadata.request_stream_id,
        }
        if workflow is None:
            out.written.append(
                _record(
                    RecordType.COMMAND_REJECTION,
                    value,
                    WI.CREATE,
                    command.key,
                    command.position,
                    {
                        "rejection_type": RejectionType.BAD_VALUE,
                        "rejection_reason": "Workflow is not deployed",
                        **md_extra,
                    },
                )
            )
            out.responses.append(out.written[-1])
            return

        value.workflow_key = workflow.key
        value.version = workflow.version
        value.bpmn_process_id = workflow.id
        value.activity_id = workflow.id
        # batch: CREATED (with request metadata) + ELEMENT_READY
        out.written.append(
            _record(RecordType.EVENT, value.copy(), WI.CREATED, wf_instance_key,
                    command.position, md_extra)
        )
        out.written.append(
            _record(RecordType.EVENT, value.copy(), WI.ELEMENT_READY, wf_instance_key,
                    command.position)
        )
        # index entry is created when the CREATED event is processed

    def _cancel_workflow_instance(self, command: Record, out: ProcessingResult) -> None:
        """Reference CancelWorkflowInstanceProcessor."""
        instance = self.element_instances.get(command.key)
        if instance is None or not instance.can_terminate():
            rejection = _record(
                RecordType.COMMAND_REJECTION,
                command.value,
                WI.CANCEL,
                command.key,
                command.position,
                {
                    "rejection_type": RejectionType.NOT_APPLICABLE,
                    "rejection_reason": "Workflow instance is not running",
                    "request_id": command.metadata.request_id,
                    "request_stream_id": command.metadata.request_stream_id,
                },
            )
            out.written.append(rejection)
            out.responses.append(rejection)
            return
        value = instance.value.copy()
        value.payload = {}
        out.written.append(
            _record(RecordType.EVENT, value.copy(), WI.CANCELING, command.key,
                    command.position,
                    {
                        "request_id": command.metadata.request_id,
                        "request_stream_id": command.metadata.request_stream_id,
                    })
        )
        out.responses.append(out.written[-1])
        out.written.append(
            _record(RecordType.EVENT, value.copy(), WI.ELEMENT_TERMINATING, command.key,
                    command.position)
        )
        instance.state = WI.ELEMENT_TERMINATING

    def _update_payload(self, command: Record, out: ProcessingResult) -> None:
        """Reference UpdatePayloadProcessor."""
        value: WorkflowInstanceRecord = command.value
        instance = self.element_instances.get(value.workflow_instance_key)
        md_extra = {
            "request_id": command.metadata.request_id,
            "request_stream_id": command.metadata.request_stream_id,
        }
        if instance is None:
            rejection = _record(
                RecordType.COMMAND_REJECTION, value, WI.UPDATE_PAYLOAD,
                command.key, command.position,
                {
                    "rejection_type": RejectionType.NOT_APPLICABLE,
                    "rejection_reason": "Workflow instance is not running",
                    **md_extra,
                },
            )
            out.written.append(rejection)
            out.responses.append(rejection)
            return
        instance.value.payload = dict(value.payload)
        event = _record(
            RecordType.EVENT, instance.value.copy(), WI.PAYLOAD_UPDATED,
            command.key, command.position, md_extra,
        )
        out.written.append(event)
        out.responses.append(event)

    # ------------------------------------------------------------------
    # BPMN step dispatch (reference BpmnStepProcessor)
    # ------------------------------------------------------------------
    def _bpmn_step(self, record: Record, intent: WI, out: ProcessingResult) -> None:
        value: WorkflowInstanceRecord = record.value
        workflow = self.repository.by_key.get(value.workflow_key)
        if workflow is None:
            return

        element = workflow.element_by_id(value.activity_id)
        if element is None:
            return

        instance = self.element_instances.get(record.key)
        scope_instance = self.element_instances.get(value.scope_instance_key)

        # reference shallProcessRecord: skip finished instances
        if instance is None and scope_instance is None:
            return
        if not self._step_guard(intent, record, instance, scope_instance):
            return

        # boundary-event arming/disarming rides the host activity's
        # lifecycle events, independent of its bound step (the reference
        # model defines BoundaryEvent but its engine never executes it;
        # the continuation intent is BOUNDARY_EVENT_OCCURRED)
        if element.boundary_events:
            if intent == WI.ELEMENT_ACTIVATED:
                self._arm_boundary_events(record, element, out)
            elif intent in (WI.ELEMENT_COMPLETING, WI.ELEMENT_TERMINATING):
                self._disarm_boundary_events(record, element, out)

        if intent == WI.ELEMENT_TERMINATED and record.key in self._pending_boundary:
            # interrupting boundary: the host terminated on behalf of the
            # trigger — continue the token at the boundary event instead of
            # propagating the termination. If the SCOPE started terminating
            # in between (a cancel raced the boundary), drop the
            # continuation and let normal termination propagation run.
            boundary_id, payload = self._pending_boundary.pop(record.key)
            if scope_instance is not None and scope_instance.state == WI.ELEMENT_ACTIVATED:
                boundary_el = workflow.element_by_id(boundary_id)
                if boundary_el is not None:
                    new_value = value.copy()
                    new_value.activity_id = boundary_el.id
                    new_value.payload = dict(payload)
                    self._write_new_wi_event(
                        out, record, WI.BOUNDARY_EVENT_OCCURRED, new_value
                    )
                return

        step = element.get_step(intent)
        if step == BpmnStep.NONE:
            return

        handler = self._STEP_HANDLERS[step]
        handler(self, record, element, workflow, instance, scope_instance, out)

    def _step_guard(
        self,
        intent: WI,
        record: Record,
        instance: Optional[ElementInstance],
        scope: Optional[ElementInstance],
    ) -> bool:
        """Reference BpmnStepProcessor stepGuards (BpmnStepProcessor.java:127-151)."""
        if intent in (WI.ELEMENT_READY, WI.ELEMENT_ACTIVATED, WI.ELEMENT_COMPLETING):
            return instance is not None and instance.state == intent
        if intent == WI.ELEMENT_COMPLETED:
            return scope is not None and scope.state == WI.ELEMENT_ACTIVATED
        if intent == WI.ELEMENT_TERMINATING:
            return True
        if intent == WI.ELEMENT_TERMINATED:
            # pending interrupting-boundary continuations are processed
            # while the scope stays ACTIVATED (the token moves to the
            # boundary event, the scope does not terminate); when the
            # scope is itself TERMINATING (boundary raced a cancel) the
            # guard passes so normal termination propagation runs —
            # _bpmn_step discards the stale pending entry
            if record.key in self._pending_boundary:
                return scope is not None and scope.state in (
                    WI.ELEMENT_ACTIVATED,
                    WI.ELEMENT_TERMINATING,
                )
            return scope is not None and scope.state == WI.ELEMENT_TERMINATING
        if intent in (
            WI.END_EVENT_OCCURRED,
            WI.GATEWAY_ACTIVATED,
            WI.START_EVENT_OCCURRED,
            WI.SEQUENCE_FLOW_TAKEN,
            WI.BOUNDARY_EVENT_OCCURRED,
        ):
            return scope is not None and scope.state == WI.ELEMENT_ACTIVATED
        return True

    # -- step handlers ----------------------------------------------------
    def _raise_incident(
        self, record: Record, error_type: ErrorType, message: str, out: ProcessingResult
    ) -> None:
        """Reference BpmnStepContext.raiseIncident."""
        value: WorkflowInstanceRecord = record.value
        incident = IncidentRecord(
            error_type=int(error_type),
            error_message=message,
            failure_event_position=record.position,
            bpmn_process_id=value.bpmn_process_id,
            workflow_instance_key=value.workflow_instance_key,
            activity_id=value.activity_id,
            activity_instance_key=record.key,
            payload=dict(value.payload),
        )
        if record.metadata.incident_key < 0:
            out.written.append(
                _record(RecordType.COMMAND, incident, IncidentIntent.CREATE, -1, record.position)
            )
        else:
            out.written.append(
                _record(
                    RecordType.EVENT, incident, IncidentIntent.RESOLVE_FAILED,
                    record.metadata.incident_key, record.position,
                )
            )

    def _h_take_sequence_flow(self, record, element, workflow, instance, scope, out):
        # reference TakeSequenceFlowHandler: exactly one outgoing flow
        flow = element.outgoing[0]
        value = record.value.copy()
        value.activity_id = flow.id
        self._write_new_wi_event(out, record, WI.SEQUENCE_FLOW_TAKEN, value)

    def _h_consume_token(self, record, element, workflow, instance, scope, out):
        # reference ConsumeTokenHandler, extended with token counting for
        # parallel flows: the scope completes when its last token is consumed
        value: WorkflowInstanceRecord = record.value
        scope_value = scope.value
        scope_el = workflow.element_by_id(scope_value.activity_id)
        is_mi = scope_el is not None and scope_el.is_multi_instance
        if is_mi:
            # multi-instance container: iteration-local variables
            # (loopCounter, the input element) must NOT leak into the
            # container payload; per-iteration outputs are collected in
            # loopCounter order instead
            if scope_el.mi_output_collection:
                # keyed by COMPLETION order (log order — deterministic and
                # replay-stable). loopCounter cannot key the collection: a
                # job result replaces the iteration payload (reference
                # semantics), dropping it for some iterations, and a
                # mixed keyspace would let a surviving loopCounter collide
                # with an order-assigned key and silently drop an output
                try:
                    found, extracted = query_json_path(
                        value.payload, scope_el.mi_output_element
                    )
                except ValueError:
                    # a bad output-element path collects null rather than
                    # escaping the engine loop mid-token-consume
                    found, extracted = False, None
                scope.mi_outputs[len(scope.mi_outputs) + 1] = (
                    extracted if found else None
                )
        else:
            scope_value.payload = dict(value.payload)
        scope.active_tokens -= 1
        if scope.active_tokens <= 0:
            if is_mi and scope_el.mi_output_collection:
                payload = dict(scope_value.payload)
                payload[scope_el.mi_output_collection] = [
                    scope.mi_outputs[c] for c in sorted(scope.mi_outputs)
                ]
                scope_value.payload = payload
                scope.mi_outputs = {}
            self._write_wi_followup(out, record, scope.key, WI.ELEMENT_COMPLETING, scope_value)

    def _h_exclusive_split(self, record, element, workflow, instance, scope, out):
        # reference ExclusiveSplitHandler
        value: WorkflowInstanceRecord = record.value
        try:
            taken = None
            for flow in element.outgoing_with_condition:
                if evaluate_condition(flow.condition, value.payload):
                    taken = flow
                    break
            if taken is None:
                taken = element.default_flow
            if taken is not None:
                new_value = value.copy()
                new_value.activity_id = taken.id
                self._write_new_wi_event(out, record, WI.SEQUENCE_FLOW_TAKEN, new_value)
            else:
                self._raise_incident(
                    record,
                    ErrorType.CONDITION_ERROR,
                    "All conditions evaluated to false and no default flow is set.",
                    out,
                )
        except ConditionEvalError as e:
            self._raise_incident(record, ErrorType.CONDITION_ERROR, str(e), out)

    def _h_create_job(self, record, element, workflow, instance, scope, out):
        # reference CreateJobHandler
        value: WorkflowInstanceRecord = record.value
        job = JobRecord(
            type=element.job_type,
            retries=element.job_retries,
            payload=dict(value.payload),
            custom_headers=dict(element.job_headers),
            headers=JobHeaders(
                bpmn_process_id=value.bpmn_process_id,
                workflow_definition_version=value.version,
                workflow_key=value.workflow_key,
                workflow_instance_key=value.workflow_instance_key,
                activity_id=element.id,
                activity_instance_key=record.key,
            ),
        )
        out.written.append(
            _record(RecordType.COMMAND, job, JobIntent.CREATE, -1, record.position)
        )

    def _h_apply_input_mapping(self, record, element, workflow, instance, scope, out):
        # reference InputMappingHandler
        value = record.value.copy()
        try:
            if element.input_mappings:
                value.payload = extract(value.payload, element.input_mappings)
            self._write_wi_followup(out, record, record.key, WI.ELEMENT_ACTIVATED, value)
        except MappingError as e:
            self._raise_incident(record, ErrorType.IO_MAPPING_ERROR, str(e), out)

    def _h_apply_output_mapping(self, record, element, workflow, instance, scope, out):
        # reference OutputMappingHandler
        value = record.value.copy()
        scope_payload = dict(scope.value.payload) if scope is not None else {}
        try:
            if element.output_behavior == OutputBehavior.NONE:
                value.payload = scope_payload
            else:
                if element.output_behavior == OutputBehavior.OVERWRITE:
                    scope_payload = {}
                value.payload = merge(value.payload, scope_payload, element.output_mappings)
            self._write_wi_followup(out, record, record.key, WI.ELEMENT_COMPLETED, value)
        except MappingError as e:
            self._raise_incident(record, ErrorType.IO_MAPPING_ERROR, str(e), out)

    def _h_activate_gateway(self, record, element, workflow, instance, scope, out):
        # reference ActivateGatewayHandler
        value = record.value.copy()
        value.activity_id = element.target.id
        self._write_new_wi_event(out, record, WI.GATEWAY_ACTIVATED, value)

    def _h_start_stateful_element(self, record, element, workflow, instance, scope, out):
        # reference StartStatefulElementHandler
        value = record.value.copy()
        value.activity_id = element.target.id
        self._write_new_wi_event(out, record, WI.ELEMENT_READY, value)

    def _h_trigger_end_event(self, record, element, workflow, instance, scope, out):
        # reference TriggerEndEventHandler
        value = record.value.copy()
        value.activity_id = element.target.id
        self._write_new_wi_event(out, record, WI.END_EVENT_OCCURRED, value)

    def _h_trigger_start_event(self, record, element, workflow, instance, scope, out):
        # reference TriggerStartEventHandler (+ token accounting)
        start_event = element.start_event
        value = record.value.copy()
        value.activity_id = start_event.id
        value.scope_instance_key = record.key
        container = self.element_instances.get(record.key)
        if container is not None:
            container.active_tokens = 1
        self._write_new_wi_event(out, record, WI.START_EVENT_OCCURRED, value)

    def _h_complete_process(self, record, element, workflow, instance, scope, out):
        # reference CompleteProcessHandler
        self._write_wi_followup(out, record, record.key, WI.ELEMENT_COMPLETED, record.value.copy())

    def _h_terminate_contained(self, record, element, workflow, instance, scope, out):
        # reference TerminateContainedElementsHandler (extended: terminate all
        # children, not just the first — multi-token scopes)
        container = instance
        if container is None:
            return
        if not container.children:
            self._write_wi_followup(out, record, record.key, WI.ELEMENT_TERMINATED, record.value.copy())
        else:
            for child in sorted(container.children, key=lambda c: c.key):
                if child.can_terminate():
                    self._write_wi_followup(
                        out, record, child.key, WI.ELEMENT_TERMINATING, child.value.copy()
                    )

    def _h_terminate_job_task(self, record, element, workflow, instance, scope, out):
        # reference TerminateServiceTaskHandler
        if instance is not None and instance.job_key > 0:
            job_state = self.jobs.get(instance.job_key)
            value: WorkflowInstanceRecord = record.value
            job = JobRecord(
                type=job_state.record.type if job_state else "",
                headers=JobHeaders(
                    bpmn_process_id=value.bpmn_process_id,
                    workflow_definition_version=value.version,
                    workflow_instance_key=value.workflow_instance_key,
                    activity_id=value.activity_id,
                    activity_instance_key=instance.key,
                ),
            )
            out.written.append(
                _record(RecordType.COMMAND, job, JobIntent.CANCEL, instance.job_key, record.position)
            )
        self._write_wi_followup(out, record, record.key, WI.ELEMENT_TERMINATED, record.value.copy())

    def _h_terminate_element(self, record, element, workflow, instance, scope, out):
        # reference TerminateElementHandler
        self._write_wi_followup(out, record, record.key, WI.ELEMENT_TERMINATED, record.value.copy())

    def _h_terminate_catch_event(self, record, element, workflow, instance, scope, out):
        # TPU-native: close message subscription / cancel timer, then terminate
        if element.message_name:
            value: WorkflowInstanceRecord = record.value
            try:
                found, corr_value = query_json_path(
                    value.payload, element.correlation_key_path
                )
            except ValueError:
                found, corr_value = False, None
            if found:
                target = self.partition_for_correlation_key(str(corr_value))
                close = MessageSubscriptionRecord(
                    workflow_instance_partition_id=self.partition_id,
                    workflow_instance_key=value.workflow_instance_key,
                    activity_instance_key=record.key,
                    message_name=element.message_name,
                    correlation_key=str(corr_value),
                )
                out.sends.append(
                    (target, _record(RecordType.COMMAND, close, MessageSubscriptionIntent.CLOSE))
                )
        for timer_key, timer in list(self.timers.items()):
            if timer.activity_instance_key == record.key:
                out.written.append(
                    _record(RecordType.COMMAND, timer.record, TimerIntent.CANCEL,
                            timer_key, record.position)
                )
        self._write_wi_followup(out, record, record.key, WI.ELEMENT_TERMINATED, record.value.copy())

    def _h_propagate_termination(self, record, element, workflow, instance, scope, out):
        # reference PropagateTerminationHandler
        if scope is None:
            return
        if not scope.children:
            self._write_wi_followup(out, record, scope.key, WI.ELEMENT_TERMINATED, scope.value.copy())

    def _h_subscribe_to_message(self, record, element, workflow, instance, scope, out):
        # reference SubscribeMessageHandler: extract correlation key, send
        # OpenMessageSubscription to the message partition
        value: WorkflowInstanceRecord = record.value
        try:
            found, corr_value = query_json_path(
                value.payload, element.correlation_key_path
            )
        except ValueError:
            found, corr_value = False, None
        if not found or not isinstance(corr_value, (str, int)):
            self._raise_incident(
                record,
                ErrorType.IO_MAPPING_ERROR,
                f"Failed to extract the correlation-key by '{element.correlation_key_path}'",
                out,
            )
            return
        correlation_key = str(corr_value)
        target = self.partition_for_correlation_key(correlation_key)
        sub = MessageSubscriptionRecord(
            workflow_instance_partition_id=self.partition_id,
            workflow_instance_key=value.workflow_instance_key,
            activity_instance_key=record.key,
            message_name=element.message_name,
            correlation_key=correlation_key,
        )
        out.sends.append(
            (target, _record(RecordType.COMMAND, sub, MessageSubscriptionIntent.OPEN))
        )

    def _h_parallel_split(self, record, element, workflow, instance, scope, out):
        # TPU-native: fork — one SEQUENCE_FLOW_TAKEN per outgoing flow, scope
        # gains (n-1) tokens
        if scope is not None:
            scope.active_tokens += len(element.outgoing) - 1
        for flow in element.outgoing:
            value = record.value.copy()
            value.activity_id = flow.id
            self._write_new_wi_event(out, record, WI.SEQUENCE_FLOW_TAKEN, value)

    def _h_parallel_merge(self, record, element, workflow, instance, scope, out):
        # TPU-native: join — count arrivals per (scope, gateway); activate
        # when all incoming flows have arrived; payloads merge in flow order
        gateway = element.target
        if scope is None:
            return
        arrivals = scope.join_arrivals.setdefault(gateway.index, {})
        flow_order = [f.index for f in gateway.incoming]
        arrivals[element.index] = dict(record.value.payload)
        if len(arrivals) == len(gateway.incoming):
            merged: Dict[str, Any] = {}
            for flow_idx in flow_order:
                merged.update(arrivals[flow_idx])
            scope.active_tokens -= len(gateway.incoming) - 1
            scope.join_arrivals.pop(gateway.index, None)
            value = record.value.copy()
            value.activity_id = gateway.id
            value.payload = merged
            self._write_new_wi_event(out, record, WI.GATEWAY_ACTIVATED, value)

    def _h_multi_instance_split(self, record, element, workflow, instance, scope, out):
        """Parallel multi-instance activation (reference model
        MultiInstanceLoopCharacteristics.java — the reference engine never
        executes it): spawn one body token per item; the container
        completes when the last body token is consumed (token counting,
        the same mechanism as the parallel join). Each iteration's payload
        carries ``loopCounter`` (1-based) and, with an input collection,
        ``input_element`` = collection[i]."""
        value: WorkflowInstanceRecord = record.value
        container = instance
        items = None
        if element.mi_input_collection:
            try:
                found, coll = query_json_path(
                    value.payload, element.mi_input_collection
                )
            except ValueError:
                # malformed path that slipped past deploy validation must
                # become an incident, not wedge the partition drain loop
                found, coll = False, None
            if not found or not isinstance(coll, list):
                self._raise_incident(
                    record,
                    ErrorType.IO_MAPPING_ERROR,
                    "Multi-instance input collection "
                    f"'{element.mi_input_collection}' is not an array in the payload",
                    out,
                )
                return
            items = coll
            n = len(items)
        else:
            n = int(element.mi_cardinality or 0)
        if n <= 0:
            # empty collection: the multi-instance body never runs and the
            # container completes immediately — with an EMPTY output
            # collection, so downstream readers of the variable see []
            done_value = value
            if element.mi_output_collection:
                done_value = value.copy()
                payload = dict(done_value.payload)
                payload[element.mi_output_collection] = []
                done_value.payload = payload
            self._write_wi_followup(
                out, record, record.key, WI.ELEMENT_COMPLETING, done_value
            )
            return
        if container is not None:
            container.active_tokens = n
        start_event = element.start_event
        for i in range(n):
            child_value = value.copy()
            child_value.activity_id = start_event.id
            child_value.scope_instance_key = record.key
            payload = dict(value.payload)
            payload["loopCounter"] = i + 1
            if items is not None and element.mi_input_element:
                payload[element.mi_input_element] = items[i]
            child_value.payload = payload
            self._write_new_wi_event(out, record, WI.START_EVENT_OCCURRED, child_value)

    def _h_create_timer(self, record, element, workflow, instance, scope, out):
        # TPU-native: timer catch event
        # record.timestamp, not clock(): replay must rebuild identical state
        # (reference reprocessing re-reads due dates from logged records)
        due = record.timestamp + int(element.timer_duration_ms or 0)
        timer = TimerRecord(
            workflow_instance_key=record.value.workflow_instance_key,
            activity_instance_key=record.key,
            due_date=due,
            handler_element_id=element.id,
        )
        out.written.append(
            _record(RecordType.COMMAND, timer, TimerIntent.CREATE, -1, record.position)
        )

    def _h_cancel_process(self, record, element, workflow, instance, scope, out):
        pass  # reference BpmnStep.CANCEL_PROCESS is unused in this version

    # -- boundary events (reference model BoundaryEvent.java +
    # cancelActivity; the continuation intent BOUNDARY_EVENT_OCCURRED is a
    # TPU-native extension — the reference engine never executes boundary
    # events) ----------------------------------------------------------------
    def _arm_boundary_events(self, record: Record, element, out: ProcessingResult) -> None:
        """On host ELEMENT_ACTIVATED: start a timer / open a message
        subscription per attached boundary event."""
        value: WorkflowInstanceRecord = record.value
        for boundary in element.boundary_events:
            if boundary.timer_duration_ms is not None:
                due = record.timestamp + int(boundary.timer_duration_ms)
                timer = TimerRecord(
                    workflow_instance_key=value.workflow_instance_key,
                    activity_instance_key=record.key,
                    due_date=due,
                    handler_element_id=boundary.id,
                )
                out.written.append(
                    _record(RecordType.COMMAND, timer, TimerIntent.CREATE, -1, record.position)
                )
            elif boundary.message_name:
                try:
                    found, corr_value = query_json_path(
                        value.payload, boundary.correlation_key_path
                    )
                except ValueError:
                    found, corr_value = False, None
                if not found or not isinstance(corr_value, (str, int)):
                    self._raise_incident(
                        record,
                        ErrorType.IO_MAPPING_ERROR,
                        "Failed to extract the correlation-key by "
                        f"'{boundary.correlation_key_path}'",
                        out,
                    )
                    continue
                correlation_key = str(corr_value)
                target = self.partition_for_correlation_key(correlation_key)
                sub = MessageSubscriptionRecord(
                    workflow_instance_partition_id=self.partition_id,
                    workflow_instance_key=value.workflow_instance_key,
                    activity_instance_key=record.key,
                    message_name=boundary.message_name,
                    correlation_key=correlation_key,
                )
                out.sends.append(
                    (target, _record(RecordType.COMMAND, sub, MessageSubscriptionIntent.OPEN))
                )

    def _disarm_boundary_events(self, record: Record, element, out: ProcessingResult) -> None:
        """On host COMPLETING/TERMINATING: cancel boundary timers and close
        boundary message subscriptions that did not fire."""
        value: WorkflowInstanceRecord = record.value
        for timer_key, timer in list(self.timers.items()):
            if timer.activity_instance_key == record.key:
                out.written.append(
                    _record(RecordType.COMMAND, timer.record, TimerIntent.CANCEL,
                            timer_key, record.position)
                )
        for boundary in element.boundary_events:
            if not boundary.message_name:
                continue
            try:
                found, corr_value = query_json_path(
                    value.payload, boundary.correlation_key_path
                )
            except ValueError:
                continue
            if not found:
                continue
            target = self.partition_for_correlation_key(str(corr_value))
            close = MessageSubscriptionRecord(
                workflow_instance_partition_id=self.partition_id,
                workflow_instance_key=value.workflow_instance_key,
                activity_instance_key=record.key,
                message_name=boundary.message_name,
                correlation_key=str(corr_value),
            )
            out.sends.append(
                (target, _record(RecordType.COMMAND, close, MessageSubscriptionIntent.CLOSE))
            )

    def _fire_boundary_event(
        self,
        record: Record,
        boundary,
        host: ElementInstance,
        payload: Dict,
        out: ProcessingResult,
    ) -> None:
        """A boundary trigger fired while the host activity is active."""
        host_value = host.value
        new_value = host_value.copy()
        new_value.activity_id = boundary.id
        new_value.payload = dict(payload)
        if boundary.cancel_activity:
            # interrupting: terminate the host; the token continues at the
            # boundary event when ELEMENT_TERMINATED processes
            self._pending_boundary[host.key] = (boundary.id, dict(payload))
            self._write_wi_followup(
                out, record, host.key, WI.ELEMENT_TERMINATING, host_value
            )
        else:
            scope = host.parent
            if scope is not None:
                scope.active_tokens += 1
            self._write_new_wi_event(out, record, WI.BOUNDARY_EVENT_OCCURRED, new_value)

    def _boundary_for(self, instance: ElementInstance, message_name: str = "",
                      handler_element_id: str = ""):
        """Resolve a trigger to the host element's attached boundary event
        (by handler element id for timers, by message name for messages).
        Returns (element, boundary) or (None, None)."""
        if instance is None or instance.value is None:
            return None, None
        workflow = self.repository.by_key.get(instance.value.workflow_key)
        if workflow is None:
            return None, None
        element = workflow.element_by_id(instance.value.activity_id)
        if element is None:
            return None, None
        for boundary in element.boundary_events:
            if handler_element_id and boundary.id == handler_element_id:
                return element, boundary
            if message_name and boundary.message_name == message_name:
                return element, boundary
        return element, None

    _STEP_HANDLERS = {
        BpmnStep.TAKE_SEQUENCE_FLOW: _h_take_sequence_flow,
        BpmnStep.CONSUME_TOKEN: _h_consume_token,
        BpmnStep.EXCLUSIVE_SPLIT: _h_exclusive_split,
        BpmnStep.CREATE_JOB: _h_create_job,
        BpmnStep.APPLY_INPUT_MAPPING: _h_apply_input_mapping,
        BpmnStep.APPLY_OUTPUT_MAPPING: _h_apply_output_mapping,
        BpmnStep.ACTIVATE_GATEWAY: _h_activate_gateway,
        BpmnStep.START_STATEFUL_ELEMENT: _h_start_stateful_element,
        BpmnStep.TRIGGER_END_EVENT: _h_trigger_end_event,
        BpmnStep.SUBSCRIBE_TO_INTERMEDIATE_MESSAGE: _h_subscribe_to_message,
        BpmnStep.TRIGGER_START_EVENT: _h_trigger_start_event,
        BpmnStep.COMPLETE_PROCESS: _h_complete_process,
        BpmnStep.TERMINATE_CONTAINED_INSTANCES: _h_terminate_contained,
        BpmnStep.TERMINATE_JOB_TASK: _h_terminate_job_task,
        BpmnStep.TERMINATE_ELEMENT: _h_terminate_element,
        BpmnStep.PROPAGATE_TERMINATION: _h_propagate_termination,
        BpmnStep.CANCEL_PROCESS: _h_cancel_process,
        BpmnStep.PARALLEL_SPLIT: _h_parallel_split,
        BpmnStep.PARALLEL_MERGE: _h_parallel_merge,
        BpmnStep.CREATE_TIMER: _h_create_timer,
        BpmnStep.TERMINATE_CATCH_EVENT: _h_terminate_catch_event,
        BpmnStep.MULTI_INSTANCE_SPLIT: _h_multi_instance_split,
    }

    # ------------------------------------------------------------------
    # job subsystem (reference JobInstanceStreamProcessor)
    # ------------------------------------------------------------------
    def _job_response(self, command: Record, intent: JobIntent, value: JobRecord,
                      out: ProcessingResult, key: int) -> Record:
        event = _record(
            RecordType.EVENT, value.copy(), intent, key, command.position,
            {
                "request_id": command.metadata.request_id,
                "request_stream_id": command.metadata.request_stream_id,
            },
        )
        out.written.append(event)
        if command.metadata.request_id >= 0:
            out.responses.append(event)
        return event

    def _job_rejection(self, command: Record, reason: str, out: ProcessingResult,
                       rejection_type: RejectionType = RejectionType.NOT_APPLICABLE) -> None:
        rejection = _record(
            RecordType.COMMAND_REJECTION, command.value, command.metadata.intent,
            command.key, command.position,
            {
                "rejection_type": rejection_type,
                "rejection_reason": reason,
                "request_id": command.metadata.request_id,
                "request_stream_id": command.metadata.request_stream_id,
            },
        )
        out.written.append(rejection)
        if command.metadata.request_id >= 0:
            out.responses.append(rejection)

    def _process_job_command(self, command: Record, out: ProcessingResult) -> None:
        intent = JobIntent(command.metadata.intent)
        value: JobRecord = command.value
        job = self.jobs.get(command.key)

        if intent == JobIntent.CREATE:
            key = self.job_keys.next_key()
            self.jobs[key] = JobState(state=int(JobIntent.CREATED), record=value.copy())
            self._job_response(command, JobIntent.CREATED, value, out, key)
        elif intent == JobIntent.ACTIVATE:
            # reference ActivateJobProcessor
            if job is not None and job.state in (
                int(JobIntent.CREATED), int(JobIntent.FAILED), int(JobIntent.TIMED_OUT)
            ):
                job.state = int(JobIntent.ACTIVATED)
                job.record = value.copy()
                job.deadline = value.deadline
                event = _record(RecordType.EVENT, value.copy(), JobIntent.ACTIVATED,
                                command.key, command.position)
                out.written.append(event)
                subscriber_key = command.metadata.request_stream_id
                out.pushes.append((subscriber_key, event))
            else:
                self._job_rejection(
                    command, "Job is not in one of these states: CREATED, FAILED, TIMED_OUT", out
                )
                self._return_job_credit(command.metadata.request_stream_id)
        elif intent == JobIntent.COMPLETE:
            if job is not None and job.state in (int(JobIntent.ACTIVATED), int(JobIntent.TIMED_OUT)):
                # merge the (possibly thin) command value onto the stored job
                # record so the COMPLETED event carries full headers — the
                # workflow processor resolves the activity instance from them
                completed = job.record.copy()
                completed.payload = dict(value.payload)
                del self.jobs[command.key]
                self._job_response(command, JobIntent.COMPLETED, completed, out, command.key)
            else:
                self._job_rejection(command, "Job is not in state: ACTIVATED, TIMED_OUT", out)
        elif intent == JobIntent.FAIL:
            if job is not None and job.state == int(JobIntent.ACTIVATED):
                failed = job.record.copy()
                failed.retries = value.retries
                if value.payload:
                    failed.payload = dict(value.payload)
                job.state = int(JobIntent.FAILED)
                job.record = failed.copy()
                self._job_response(command, JobIntent.FAILED, failed, out, command.key)
            else:
                self._job_rejection(command, "Job is not in state ACTIVATED", out)
        elif intent == JobIntent.TIME_OUT:
            if job is not None and job.state == int(JobIntent.ACTIVATED):
                job.state = int(JobIntent.TIMED_OUT)
                self._job_response(command, JobIntent.TIMED_OUT, value, out, command.key)
            else:
                self._job_rejection(command, "Job is not in state ACTIVATED", out)
        elif intent == JobIntent.UPDATE_RETRIES:
            if job is not None and job.state == int(JobIntent.FAILED):
                if value.retries > 0:
                    # respond with the stored job record (the reference client
                    # echoes the full job record in the command; a thin client
                    # may send only retries)
                    job.record.retries = value.retries
                    self._job_response(
                        command, JobIntent.RETRIES_UPDATED, job.record, out, command.key
                    )
                else:
                    self._job_rejection(
                        command, "Retries must be greater than 0", out, RejectionType.BAD_VALUE
                    )
            else:
                self._job_rejection(command, "Job is not in state FAILED", out)
        elif intent == JobIntent.CANCEL:
            if job is not None:
                del self.jobs[command.key]
                self._job_response(command, JobIntent.CANCELED, value, out, command.key)
            else:
                self._job_rejection(command, "Job does not exist", out)

    def _workflow_on_job_event(self, record: Record, out: ProcessingResult) -> None:
        """Reference JobCreatedProcessor / JobCompletedEventProcessor in the
        workflow instance stream processor."""
        intent = JobIntent(record.metadata.intent)
        value: JobRecord = record.value
        activity_instance_key = value.headers.activity_instance_key
        if intent == JobIntent.CREATED:
            if activity_instance_key > 0:
                instance = self.element_instances.get(activity_instance_key)
                if instance is not None:
                    instance.job_key = record.key
        elif intent == JobIntent.COMPLETED:
            instance = self.element_instances.get(activity_instance_key)
            if instance is not None:
                wi_value = instance.value
                wi_value.payload = dict(value.payload)
                self._write_wi_followup(
                    out, record, activity_instance_key, WI.ELEMENT_COMPLETING, wi_value
                )
                instance.job_key = -1

    def _activate_jobs_on_event(self, record: Record, out: ProcessingResult) -> None:
        """Reference ActivateJobStreamProcessor (push with credits)."""
        intent = JobIntent(record.metadata.intent)
        if intent not in (
            JobIntent.CREATED, JobIntent.TIMED_OUT, JobIntent.FAILED, JobIntent.RETRIES_UPDATED
        ):
            return
        value: JobRecord = record.value
        if value.retries <= 0:
            return
        subscription = self._next_job_subscription(value.type)
        if subscription is None:
            # no credits right now: remember the job so a later credit
            # return can assign it (reference: the paused job stream
            # processor resumes from this position)
            self._awaiting_jobs.setdefault(value.type, {})[record.key] = None
            return
        self._awaiting_jobs.get(value.type, {}).pop(record.key, None)
        activated = value.copy()
        activated.deadline = record.timestamp + subscription.timeout
        activated.worker = subscription.worker
        out.written.append(
            _record(
                RecordType.COMMAND, activated, JobIntent.ACTIVATE, record.key, record.position,
                {"request_stream_id": subscription.subscriber_key},
            )
        )
        subscription.credits -= 1

    def _next_job_subscription(self, job_type: str) -> Optional[JobSubscription]:
        """Round-robin over subscriptions with credits (reference
        getNextAvailableSubscription)."""
        matching = [s for s in self.job_subscriptions if s.job_type == job_type]
        if not matching or sum(s.credits for s in matching) <= 0:
            return None
        for i in range(len(matching)):
            sub = matching[(self._job_rr_cursor + i) % len(matching)]
            if sub.credits > 0:
                self._job_rr_cursor = (self._job_rr_cursor + i + 1) % len(matching)
                return sub
        return None

    def _return_job_credit(self, subscriber_key: int) -> None:
        for sub in self.job_subscriptions:
            if sub.subscriber_key == subscriber_key:
                sub.credits += 1
                return

    def backlog_activations(self) -> List[Record]:
        """ACTIVATE commands pairing available credits with jobs that
        became activatable during a credit drought (``_awaiting_jobs``).
        The broker calls this on credit return and from the periodic
        tick, appending the returned commands to the partition log —
        without it, any job created while all matching subscriptions were
        out of credits is stranded forever (round-5 serving-path finding:
        a 10k-instance run converged at ~34% because returned credits
        never revisited the backlog)."""
        out: List[Record] = []
        if self._dirty_families is not None and self._awaiting_jobs:
            # drains the awaiting index and stamps activation deadlines
            self._dirty_families.add("h/jobs")
        activatable = (
            int(JobIntent.CREATED), int(JobIntent.TIMED_OUT),
            int(JobIntent.FAILED), int(JobIntent.RETRIES_UPDATED),
        )
        for job_type in list(self._awaiting_jobs):
            keys = self._awaiting_jobs.get(job_type) or {}
            while keys:
                key = next(iter(keys))
                job = self.jobs.get(key)
                if (
                    job is None
                    or job.state not in activatable
                    or job.record.retries <= 0
                ):
                    keys.pop(key, None)  # stale: finished/failed meanwhile
                    continue
                subscription = self._next_job_subscription(job_type)
                if subscription is None:
                    break  # credits exhausted; keep the rest queued
                keys.pop(key, None)
                activated = job.record.copy()
                activated.deadline = self.clock() + subscription.timeout
                activated.worker = subscription.worker
                subscription.credits -= 1
                out.append(
                    _record(
                        RecordType.COMMAND, activated, JobIntent.ACTIVATE,
                        key, -1,
                        {"request_stream_id": subscription.subscriber_key},
                    )
                )
            if not keys:
                self._awaiting_jobs.pop(job_type, None)
        return out

    # -- host API: subscriptions + deadline checks ------------------------
    def add_job_subscription(self, subscription: JobSubscription) -> List[Record]:
        """Register a worker subscription and return ACTIVATE commands for the
        backlog of already-created matching jobs.

        Reference: ActivateJobStreamProcessor is installed on first
        subscription and reads the log from the start, so pre-existing
        CREATED (or failed-with-retries / timed-out) jobs get assigned too.
        The returned commands must be appended to the partition log.
        Idempotent per subscriber key: a re-subscribe (client recovering
        from a leader change) replaces the previous registration."""
        self.remove_job_subscription(subscription.subscriber_key)
        self.job_subscriptions.append(subscription)
        backlog = []
        activatable = (
            int(JobIntent.CREATED),
            int(JobIntent.TIMED_OUT),
            int(JobIntent.FAILED),
            int(JobIntent.RETRIES_UPDATED),
        )
        for key, job in sorted(self.jobs.items()):
            if subscription.credits <= 0:
                break
            if job.state not in activatable:
                continue
            if job.record.type != subscription.job_type or job.record.retries <= 0:
                continue
            activated = job.record.copy()
            activated.deadline = self.clock() + subscription.timeout
            activated.worker = subscription.worker
            backlog.append(
                _record(
                    RecordType.COMMAND, activated, JobIntent.ACTIVATE, key, -1,
                    {"request_stream_id": subscription.subscriber_key},
                )
            )
            subscription.credits -= 1
        return backlog

    def remove_job_subscription(self, subscriber_key: int) -> None:
        self.job_subscriptions = [
            s for s in self.job_subscriptions if s.subscriber_key != subscriber_key
        ]

    def increase_job_credits(self, subscriber_key: int, credits: int) -> None:
        for sub in self.job_subscriptions:
            if sub.subscriber_key == subscriber_key:
                sub.credits += credits

    def check_job_deadlines(self) -> List[Record]:
        """Reference JobTimeOutStreamProcessor: TIME_OUT commands for expired
        activated jobs; returned commands must be appended to the log."""
        now = self.clock()
        # filter THEN sort: the sweep runs every broker tick over the
        # whole table — sorting only the due entries keeps the idle tick
        # O(n) with no allocation instead of an O(n log n) sort of
        # thousands of in-flight jobs (output order unchanged: due keys
        # ascending)
        activated = int(JobIntent.ACTIVATED)
        due = [
            (key, job) for key, job in self.jobs.items()
            if job.state == activated and 0 <= job.deadline <= now
        ]
        return [
            _record(RecordType.COMMAND, job.record.copy(), JobIntent.TIME_OUT, key)
            for key, job in sorted(due)
        ]

    def check_timer_deadlines(self) -> List[Record]:
        """TPU-native timer firing: TRIGGER commands for due timers."""
        now = self.clock()
        due = [
            (key, timer) for key, timer in self.timers.items()
            if timer.due_date <= now
        ]
        return [
            _record(RecordType.COMMAND, timer.record.copy(), TimerIntent.TRIGGER, key)
            for key, timer in sorted(due)
        ]

    def check_message_ttls(self) -> List[Record]:
        """Reference MessageTimeToLiveChecker: DELETE commands for expired
        messages."""
        now = self.clock()
        due = [
            (key, message) for key, message in self.messages.items()
            if message.deadline <= now
        ]
        return [
            _record(
                RecordType.COMMAND,
                MessageRecord(
                    name=message.name,
                    correlation_key=message.correlation_key,
                    time_to_live=message.time_to_live,
                    payload=dict(message.payload),
                    message_id=message.message_id,
                ),
                MessageIntent.DELETE,
                key,
            )
            for key, message in sorted(due)
        ]

    # ------------------------------------------------------------------
    # incident subsystem (reference IncidentStreamProcessor)
    # ------------------------------------------------------------------
    def _process_incident(self, record: Record, out: ProcessingResult) -> None:
        intent = IncidentIntent(record.metadata.intent)
        rt = record.metadata.record_type
        value: IncidentRecord = record.value

        if rt == RecordType.COMMAND and intent == IncidentIntent.CREATE:
            is_job_incident = value.job_key > 0
            if is_job_incident and self.incident_by_failed_job.get(value.job_key, -1) != -2:
                self._job_rejection(record, "Job is not failed", out)
                return
            key = self.incident_keys.next_key()
            created = _record(RecordType.EVENT, value.copy(), IncidentIntent.CREATED,
                              key, record.position)
            out.written.append(created)
            if is_job_incident:
                self.incident_by_failed_job[value.job_key] = key
            else:
                self.incident_by_activity[value.activity_instance_key] = key
            self.incidents[key] = IncidentState(
                state=INCIDENT_CREATED,
                incident_event_position=record.position,
                failure_event_position=value.failure_event_position,
            )
            self.incident_records[key] = value.copy()
        elif rt == RecordType.COMMAND and intent == IncidentIntent.RESOLVE:
            incident = self.incidents.get(record.key)
            if incident is not None and incident.state == INCIDENT_CREATED:
                failure = self.records_by_position.get(incident.failure_event_position)
                if failure is not None:
                    new_value = failure.value.copy()
                    new_value.payload = dict(value.payload)
                    self._write_wi_followup(
                        out, record, failure.key, WI(failure.metadata.intent), new_value,
                        {"incident_key": record.key},
                    )
                    incident.state = INCIDENT_RESOLVING
            else:
                self._job_rejection(record, "Incident is not in state CREATED", out)
        elif rt == RecordType.EVENT and intent == IncidentIntent.RESOLVE_FAILED:
            incident = self.incidents.get(record.key)
            if incident is not None and incident.state == INCIDENT_RESOLVING:
                incident.state = INCIDENT_CREATED
        elif rt == RecordType.COMMAND and intent == IncidentIntent.DELETE:
            incident = self.incidents.pop(record.key, None)
            if incident is not None:
                prior = self.incident_records.pop(record.key, None)
                out.written.append(
                    _record(RecordType.EVENT, prior or value, IncidentIntent.DELETED,
                            record.key, record.position)
                )
            else:
                self._job_rejection(record, "Incident does not exist", out)

    def _incident_on_workflow_record(self, record: Record, out: ProcessingResult) -> None:
        if record.metadata.record_type != RecordType.EVENT:
            return
        intent = WI(record.metadata.intent)
        # ActivityRewrittenProcessor: remember re-written failure events
        if intent in (WI.ELEMENT_READY, WI.GATEWAY_ACTIVATED, WI.ELEMENT_COMPLETING):
            if record.metadata.incident_key > 0:
                self.resolving_events[record.position] = record.metadata.incident_key
        # PayloadUpdatedProcessor: trigger RESOLVE
        if intent == WI.PAYLOAD_UPDATED:
            incident_key = self.incident_by_activity.get(record.key, -1)
            if incident_key > 0 and self.incidents.get(incident_key, None) is not None \
                    and self.incidents[incident_key].state == INCIDENT_CREATED:
                resolve_value = IncidentRecord(
                    workflow_instance_key=record.value.workflow_instance_key,
                    activity_instance_key=record.key,
                    payload=dict(record.value.payload),
                )
                out.written.append(
                    _record(RecordType.COMMAND, resolve_value, IncidentIntent.RESOLVE,
                            incident_key, record.position)
                )
        # ActivityIncidentResolvedProcessor: resolution completes on the next
        # lifecycle event produced from the re-written failure event
        if intent in (
            WI.ELEMENT_ACTIVATED, WI.SEQUENCE_FLOW_TAKEN, WI.ELEMENT_COMPLETED,
        ):
            incident_key = self.resolving_events.get(record.source_record_position, -1)
            if incident_key > 0:
                incident = self.incidents.get(incident_key)
                if incident is not None and incident.state == INCIDENT_RESOLVING:
                    prior = self.incident_records.get(incident_key)
                    out.written.append(
                        _record(RecordType.EVENT, prior, IncidentIntent.RESOLVED,
                                incident_key, record.position)
                    )
                    self.incidents.pop(incident_key, None)
                    if prior is not None:
                        self.incident_by_activity.pop(prior.activity_instance_key, None)
                    self.resolving_events.pop(record.source_record_position, None)
        # ActivityTerminatedProcessor: delete incidents of terminated elements
        if intent == WI.ELEMENT_TERMINATED:
            incident_key = self.incident_by_activity.pop(record.key, -1)
            if incident_key > 0:
                incident = self.incidents.get(incident_key)
                if incident is not None and incident.state in (
                    INCIDENT_CREATED, INCIDENT_RESOLVING
                ):
                    incident.state = INCIDENT_DELETING
                    out.written.append(
                        _record(RecordType.COMMAND, IncidentRecord(), IncidentIntent.DELETE,
                                incident_key, record.position)
                    )

    def _incident_on_job_event(self, record: Record, out: ProcessingResult) -> None:
        intent = JobIntent(record.metadata.intent)
        value: JobRecord = record.value
        if intent == JobIntent.FAILED and value.retries <= 0:
            # reference JobFailedProcessor
            headers = value.headers
            incident = IncidentRecord(
                error_type=int(ErrorType.JOB_NO_RETRIES),
                error_message="No more retries left.",
                failure_event_position=record.position,
                bpmn_process_id=headers.bpmn_process_id,
                workflow_instance_key=headers.workflow_instance_key,
                activity_id=headers.activity_id,
                activity_instance_key=headers.activity_instance_key,
                job_key=record.key,
                payload=dict(value.payload),
            )
            self.incident_by_failed_job[record.key] = -2  # NON_PERSISTENT_INCIDENT
            if record.metadata.incident_key < 0:
                out.written.append(
                    _record(RecordType.COMMAND, incident, IncidentIntent.CREATE, -1, record.position)
                )
            else:
                out.written.append(
                    _record(RecordType.EVENT, incident, IncidentIntent.RESOLVE_FAILED,
                            record.metadata.incident_key, record.position)
                )
        elif intent in (JobIntent.RETRIES_UPDATED, JobIntent.CANCELED):
            # reference JobIncidentResolvedProcessor
            incident_key = self.incident_by_failed_job.pop(record.key, -1)
            if incident_key > 0:
                incident = self.incidents.get(incident_key)
                if incident is not None and incident.state == INCIDENT_CREATED:
                    if intent == JobIntent.RETRIES_UPDATED:
                        # re-activate by re-writing the failure event: the job
                        # goes back to the activation pool
                        prior = self.incident_records.get(incident_key)
                        out.written.append(
                            _record(RecordType.EVENT, prior, IncidentIntent.RESOLVED,
                                    incident_key, record.position)
                        )
                    else:
                        prior = self.incident_records.get(incident_key)
                        out.written.append(
                            _record(RecordType.COMMAND, prior or IncidentRecord(),
                                    IncidentIntent.DELETE, incident_key, record.position)
                        )
                    self.incidents.pop(incident_key, None)
                    self.incident_records.pop(incident_key, None)

    # ------------------------------------------------------------------
    # message subsystem (reference subscription/message/processor/*)
    # ------------------------------------------------------------------
    def _process_message_command(self, record: Record, out: ProcessingResult) -> None:
        intent = MessageIntent(record.metadata.intent)
        value: MessageRecord = record.value
        if intent == MessageIntent.PUBLISH:
            if value.message_id and any(
                m.name == value.name
                and m.correlation_key == value.correlation_key
                and m.message_id == value.message_id
                for m in self.messages.values()
            ):
                reason = f"message with id '{value.message_id}' is already published"
                self._job_rejection(record, reason, out, RejectionType.BAD_VALUE)
                return
            key = self.wf_keys.next_key()
            published = _record(
                RecordType.EVENT, value.copy(), MessageIntent.PUBLISHED, key, record.position,
                {
                    "request_id": record.metadata.request_id,
                    "request_stream_id": record.metadata.request_stream_id,
                },
            )
            out.written.append(published)
            if record.metadata.request_id >= 0:
                out.responses.append(published)
            # correlate to open subscriptions
            for sub in self.message_subscriptions:
                if sub.message_name == value.name and sub.correlation_key == value.correlation_key:
                    out.sends.append(
                        (
                            sub.workflow_instance_partition_id,
                            _record(
                                RecordType.COMMAND,
                                WorkflowInstanceSubscriptionRecord(
                                    workflow_instance_key=sub.workflow_instance_key,
                                    activity_instance_key=sub.activity_instance_key,
                                    message_name=value.name,
                                    payload=dict(value.payload),
                                    message_partition_id=self.partition_id,
                                    correlation_key=sub.correlation_key,
                                ),
                                WorkflowInstanceSubscriptionIntent.CORRELATE,
                            ),
                        )
                    )
            if value.time_to_live > 0:
                self.messages[key] = StoredMessage(
                    key=key,
                    name=value.name,
                    correlation_key=value.correlation_key,
                    time_to_live=value.time_to_live,
                    payload=dict(value.payload),
                    message_id=value.message_id,
                    deadline=record.timestamp + value.time_to_live,
                )
            else:
                out.written.append(
                    _record(RecordType.EVENT, value.copy(), MessageIntent.DELETED,
                            key, record.position)
                )
        elif intent == MessageIntent.DELETE:
            if record.key in self.messages:
                del self.messages[record.key]
                out.written.append(
                    _record(RecordType.EVENT, value.copy(), MessageIntent.DELETED,
                            record.key, record.position)
                )

    def _process_message_subscription(self, record: Record, out: ProcessingResult) -> None:
        intent = MessageSubscriptionIntent(record.metadata.intent)
        value: MessageSubscriptionRecord = record.value
        if intent == MessageSubscriptionIntent.OPEN:
            # reference OpenMessageSubscriptionProcessor
            key = self.wf_keys.next_key()
            out.written.append(
                _record(RecordType.EVENT, value.copy(), MessageSubscriptionIntent.OPENED,
                        key, record.position)
            )
            self.message_subscriptions.append(
                StoredSubscription(
                    message_name=value.message_name,
                    correlation_key=value.correlation_key,
                    workflow_instance_partition_id=value.workflow_instance_partition_id,
                    workflow_instance_key=value.workflow_instance_key,
                    activity_instance_key=value.activity_instance_key,
                )
            )
            for message in sorted(self.messages.values(), key=lambda m: m.key):
                if message.name == value.message_name and message.correlation_key == value.correlation_key:
                    out.sends.append(
                        (
                            value.workflow_instance_partition_id,
                            _record(
                                RecordType.COMMAND,
                                WorkflowInstanceSubscriptionRecord(
                                    workflow_instance_key=value.workflow_instance_key,
                                    activity_instance_key=value.activity_instance_key,
                                    message_name=value.message_name,
                                    payload=dict(message.payload),
                                    message_partition_id=self.partition_id,
                                    correlation_key=value.correlation_key,
                                ),
                                WorkflowInstanceSubscriptionIntent.CORRELATE,
                            ),
                        )
                    )
                    break
        elif intent == MessageSubscriptionIntent.CLOSE:
            before = len(self.message_subscriptions)
            self.message_subscriptions = [
                s
                for s in self.message_subscriptions
                if not (
                    s.activity_instance_key == value.activity_instance_key
                    and s.workflow_instance_key == value.workflow_instance_key
                    # name-scoped: an activity instance holds one
                    # subscription per message (own catch + message
                    # boundaries); each CLOSE names the one it consumes
                    and (
                        not value.message_name
                        or s.message_name == value.message_name
                    )
                )
            ]
            if len(self.message_subscriptions) != before:
                out.written.append(
                    _record(RecordType.EVENT, value.copy(), MessageSubscriptionIntent.CLOSED,
                            record.key, record.position)
                )

    def _process_wi_subscription(self, record: Record, out: ProcessingResult) -> None:
        """Reference CorrelateWorkflowInstanceSubscription."""
        value: WorkflowInstanceSubscriptionRecord = record.value
        instance = self.element_instances.get(value.activity_instance_key)
        if instance is None:
            self._job_rejection(record, "activity is not active anymore", out)
            return
        out.written.append(
            _record(RecordType.EVENT, value.copy(),
                    WorkflowInstanceSubscriptionIntent.CORRELATED,
                    record.key, record.position)
        )
        _, boundary = self._boundary_for(instance, message_name=value.message_name)
        if boundary is not None:
            self._fire_boundary_event(
                record, boundary, instance, dict(value.payload), out
            )
            if not boundary.cancel_activity:
                # non-interrupting: the subscription stays open so the
                # boundary can fire again for further messages
                return
        else:
            wi_value = instance.value
            wi_value.payload = dict(value.payload)
            self._write_wi_followup(
                out, record, value.activity_instance_key, WI.ELEMENT_COMPLETING, wi_value
            )
        # close the now-consumed subscription on the message partition (the
        # reference leaks it in this version; see MessageSubscriptionIntent)
        if value.message_partition_id >= 0:
            close = MessageSubscriptionRecord(
                workflow_instance_partition_id=self.partition_id,
                workflow_instance_key=value.workflow_instance_key,
                activity_instance_key=value.activity_instance_key,
                message_name=value.message_name,
                correlation_key=value.correlation_key,
            )
            out.sends.append(
                (
                    value.message_partition_id,
                    _record(RecordType.COMMAND, close, MessageSubscriptionIntent.CLOSE),
                )
            )

    # ------------------------------------------------------------------
    # timers (TPU-native)
    # ------------------------------------------------------------------
    def _process_timer(self, record: Record, out: ProcessingResult) -> None:
        intent = TimerIntent(record.metadata.intent)
        value: TimerRecord = record.value
        if intent == TimerIntent.CREATE:
            key = self.wf_keys.next_key()
            self.timers[key] = TimerState(
                due_date=value.due_date,
                activity_instance_key=value.activity_instance_key,
                record=value.copy(),
            )
            out.written.append(
                _record(RecordType.EVENT, value.copy(), TimerIntent.CREATED, key, record.position)
            )
        elif intent == TimerIntent.TRIGGER:
            timer = self.timers.pop(record.key, None)
            if timer is None:
                self._job_rejection(record, "timer does not exist", out)
                return
            out.written.append(
                _record(RecordType.EVENT, value.copy(), TimerIntent.TRIGGERED,
                        record.key, record.position)
            )
            instance = self.element_instances.get(value.activity_instance_key)
            if instance is not None and instance.state == WI.ELEMENT_ACTIVATED:
                _, boundary = self._boundary_for(
                    instance, handler_element_id=value.handler_element_id
                )
                if boundary is not None:
                    self._fire_boundary_event(
                        record, boundary, instance,
                        dict(instance.value.payload), out,
                    )
                else:
                    self._write_wi_followup(
                        out, record, instance.key, WI.ELEMENT_COMPLETING, instance.value
                    )
        elif intent == TimerIntent.CANCEL:
            timer = self.timers.pop(record.key, None)
            if timer is not None:
                out.written.append(
                    _record(RecordType.EVENT, value.copy(), TimerIntent.CANCELED,
                            record.key, record.position)
                )


def _correlation_hash(key: str) -> int:
    """Deterministic correlation-key hash (reference uses String.hashCode-style
    routing in SubscriptionCommandSender; any stable hash works as long as
    every node agrees)."""
    h = 0
    for ch in key:
        h = (h * 31 + ord(ch)) & 0x7FFFFFFF
    return h


