"""Per-partition key generation.

Reference parity: ``broker-core/.../logstreams/processor/KeyGenerator.java``
— strided counters so entity families get disjoint keys on one partition:
workflow keys ≡ 1 (mod 5), job ≡ 2, incident ≡ 3, deployment ≡ 4, topic ≡ 0.
"""

from __future__ import annotations

STEP_SIZE = 5
WF_OFFSET = 1
JOB_OFFSET = 2
INCIDENT_OFFSET = 3
DEPLOYMENT_OFFSET = 4
TOPIC_OFFSET = 5


class KeyGenerator:
    def __init__(self, initial_value: int, step_size: int = STEP_SIZE):
        self._next = initial_value
        self._step = step_size

    def next_key(self) -> int:
        key = self._next
        self._next += self._step
        return key

    def set_key(self, key: int) -> None:
        """Resume after ``key`` (recovery: reference stateController.recoverLatestJobKey)."""
        if key + self._step > self._next:
            self._next = key + self._step

    @property
    def peek(self) -> int:
        return self._next


def workflow_instance_keys() -> KeyGenerator:
    return KeyGenerator(WF_OFFSET)


def job_keys() -> KeyGenerator:
    return KeyGenerator(JOB_OFFSET)


def incident_keys() -> KeyGenerator:
    return KeyGenerator(INCIDENT_OFFSET)


def deployment_keys() -> KeyGenerator:
    return KeyGenerator(DEPLOYMENT_OFFSET)


def topic_keys() -> KeyGenerator:
    return KeyGenerator(TOPIC_OFFSET)


def topic_subscriber_keys() -> KeyGenerator:
    """Reference: TopicSubscriptionManagementProcessor's own key space —
    per-processor generators may overlap numerically across entity families
    (keys are unique per (partition, processor), KeyGenerator.java:23)."""
    return KeyGenerator(TOPIC_OFFSET)
