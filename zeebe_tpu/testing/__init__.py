"""Test utilities shipped with the framework (reference parity:
``protocol-test-util`` — the stub broker, record asserts, controlled
clocks are product surface, not private test code)."""

from zeebe_tpu.testing.stub_broker import StubBroker  # noqa: F401
from zeebe_tpu.testing.chaos import (  # noqa: F401
    ChaosHarness,
    DiskFaults,
    FaultPlane,
    oracle_state_bytes,
    replay_oracle,
)
