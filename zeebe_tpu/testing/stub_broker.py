"""Scriptable stub broker speaking the native client protocol.

Reference parity: ``protocol-test-util/.../brokerapi/StubBrokerRule.java``
— a fake broker for CLIENT-side unit tests: every request is recorded,
responses are scripted per request type, and failure modes (timeouts,
rejections, disconnects, redirects) are injected deterministically. Works
for any native-protocol client: the Python ``ClusterClient`` and the C++
``clients/cpp/zbclient`` speak to it unchanged.

    stub = StubBroker()
    stub.reject_next("command", reason="boom")     # one scripted rejection
    stub.drop_next("command")                      # swallow → client timeout
    stub.on("command", fn)                         # custom responder
    ...
    stub.requests  ->  [(type, decoded msg), ...]  # everything recorded
"""

from __future__ import annotations

import itertools
import threading
import time
from typing import Callable, Dict, List, Optional, Tuple

from zeebe_tpu.protocol import codec, msgpack
from zeebe_tpu.protocol.enums import RecordType, RejectionType, ValueType
from zeebe_tpu.protocol.records import Record
from zeebe_tpu.transport import ServerTransport


class StubBroker:
    """A fake single-partition broker with scripted behavior."""

    def __init__(self, host: str = "127.0.0.1", partition_id: int = 0):
        self.partition_id = partition_id
        self.requests: List[Tuple[str, dict]] = []
        self._responders: Dict[str, Callable[[dict], Optional[bytes]]] = {}
        self._scripted: Dict[str, List[Callable[[dict], Optional[bytes]]]] = {}
        self._delay_ms: Dict[str, int] = {}
        self._lock = threading.Lock()
        self._keys = itertools.count(100)
        self._conns: List = []
        self._conn_ids: set = set()
        # subscriber key → the connection that subscribed (pushes go to
        # the subscriber's own connection, like SubscribedRecordWriter)
        self._subscriber_conns: Dict[int, object] = {}
        self.server = ServerTransport(
            host=host, request_handler=self._on_request
        )

    # -- scripting API -----------------------------------------------------
    def on(self, rtype: str, responder: Callable[[dict], Optional[bytes]]) -> None:
        """Replace the default responder for ``rtype``. Return None to
        swallow the request (client times out)."""
        self._responders[rtype] = responder

    def script_next(self, rtype: str, responder: Callable[[dict], Optional[bytes]]) -> None:
        """One-shot scripted response consumed before default handling."""
        with self._lock:
            self._scripted.setdefault(rtype, []).append(responder)

    def drop_next(self, rtype: str) -> None:
        """Swallow the next ``rtype`` request — the client sees a timeout
        (reference StubBrokerRule's doNotRespond)."""
        self.script_next(rtype, lambda msg: None)

    def reject_next(
        self,
        rtype: str = "command",
        reason: str = "scripted rejection",
        rejection_type: RejectionType = RejectionType.BAD_VALUE,
    ) -> None:
        """The next command is answered with a COMMAND_REJECTION."""

        def responder(msg):
            record, _ = codec.decode_record(bytes(msg["frame"]))
            record.metadata.record_type = RecordType.COMMAND_REJECTION
            record.metadata.rejection_type = rejection_type
            record.metadata.rejection_reason = reason
            return msgpack.pack(
                {"t": "command-rsp", "frame": codec.encode_record(record)}
            )

        self.script_next(rtype, responder)

    def redirect_next(self, rtype: str = "command") -> None:
        """The next command is answered NOT_LEADER (leader-change window)."""
        self.script_next(
            rtype,
            lambda msg: msgpack.pack({"t": "error", "code": "NOT_LEADER"}),
        )

    def delay(self, rtype: str, delay_ms: int) -> None:
        """Latency injection for every ``rtype`` request."""
        self._delay_ms[rtype] = delay_ms

    def requests_of(self, rtype: str) -> List[dict]:
        with self._lock:
            return [m for t, m in self.requests if t == rtype]

    # -- push (job/topic subscription) --------------------------------------
    def push_job(
        self,
        subscriber_key: int,
        record: Record,
        partition: Optional[int] = None,
    ) -> None:
        """Push an ACTIVATED job record to connected subscribers (the
        worker-side push path without a real engine)."""
        payload = msgpack.pack(
            {
                "t": "pushed-record",
                "partition": self.partition_id if partition is None else partition,
                "subscriber_key": subscriber_key,
                "frame": codec.encode_record(record),
            }
        )
        conn = self._subscriber_conns.get(subscriber_key)
        targets = [conn] if conn is not None else list(self._conns)
        for target in targets:
            try:
                target.push(payload)
            except Exception:  # noqa: BLE001 - dead test connection
                pass

    # -- wiring -------------------------------------------------------------
    @property
    def address(self):
        return self.server.address

    def _on_request(self, payload: bytes, conn):
        try:
            msg = msgpack.unpack(payload)
        except Exception:  # noqa: BLE001
            return None
        rtype = str(msg.get("t"))
        with self._lock:
            self.requests.append((rtype, msg))
            queue = self._scripted.get(rtype)
            scripted = queue.pop(0) if queue else None
        if conn is not None:
            # ServerTransport hands a FRESH handle per request; dedupe by
            # the underlying connection so broadcast pushes fire once
            sock_id = id(getattr(conn, "_conn", conn))
            if sock_id not in self._conn_ids:
                self._conn_ids.add(sock_id)
                self._conns.append(conn)
        if (
            conn is not None
            and rtype == "job-subscription"
            and msg.get("action") == "add"
            and "subscriber_key" in msg
        ):
            self._subscriber_conns[int(msg["subscriber_key"])] = conn
        def respond():
            if scripted is not None:
                return scripted(msg)
            responder = self._responders.get(rtype)
            if responder is not None:
                return responder(msg)
            return self._default(rtype, msg)

        delay = self._delay_ms.get(rtype)
        if delay:
            # latency injection OFF the transport IO thread: other request
            # types and queued pushes must keep flowing during the delay
            from zeebe_tpu.runtime.actors import ActorFuture

            future = ActorFuture()

            def later():
                time.sleep(delay / 1000.0)
                future.complete(respond())

            threading.Thread(target=later, daemon=True).start()
            return future
        return respond()

    # -- default behaviors (the happy-path canned broker) -------------------
    def _default(self, rtype: str, msg: dict) -> Optional[bytes]:
        if rtype == "topology":
            return msgpack.pack(
                {
                    "t": "topology-rsp",
                    "leaders": {
                        str(self.partition_id): {
                            "node": "stub-0",
                            "addr": [self.address.host, self.address.port],
                            "term": 1,
                        }
                    },
                }
            )
        if rtype == "command":
            # echo the command back as the accepted event (intent + 1 — the
            # usual CREATE→CREATED / COMPLETE→COMPLETED pairing)
            record, _ = codec.decode_record(bytes(msg["frame"]))
            record.metadata.record_type = RecordType.EVENT
            record.metadata.intent = int(record.metadata.intent) + 1
            if record.key < 0:
                record.key = next(self._keys)
            if int(record.metadata.value_type) == int(ValueType.WORKFLOW_INSTANCE):
                record.value.workflow_instance_key = record.key
            return msgpack.pack(
                {"t": "command-rsp", "frame": codec.encode_record(record)}
            )
        if rtype == "job-subscription":
            return msgpack.pack({"t": "ok"})
        if rtype == "topic-subscription":
            return msgpack.pack({"t": "ok"})
        if rtype in ("list-workflows",):
            return msgpack.pack({"t": "ok", "workflows": []})
        if rtype == "get-workflow":
            return msgpack.pack({"t": "error", "code": "NOT_FOUND"})
        return msgpack.pack({"t": "error", "code": "UNSUPPORTED"})

    def close(self) -> None:
        self.server.close()
