"""Deterministic chaos plane: seeded fault injection for cluster tests.

The paper's core claim — state is always reconstructible by replay over a
fault-tolerant replicated log — is only a claim until faults are actually
injected. This module makes fault schedules a first-class, REPRODUCIBLE
test input (reference analogue: the reference's ClusteringRule kills real
brokers; Jepsen-style nemeses do the same for network faults — here both
run in-process and deterministically):

- :class:`FaultPlane` — network faults (drop, delay, duplicate, symmetric/
  asymmetric partitions) installed into ``ClientTransport``/
  ``ServerTransport`` via their ``fault_hook`` injection point. All
  randomness comes from per-edge RNGs derived from one seed, so the same
  seed over the same per-edge traffic produces the same decision sequence;
  every decision is appended to ``plane.trace`` for replay/debugging.
- :class:`DiskFaults` — disk-level crash simulation: torn segment-tail
  writes, failing fsync, and a crash at any point inside the snapshot
  storage's two-rename commit (``_swap_in``).
- :class:`ChaosHarness` — crash-stops and restarts in-process
  ``ClusterBroker`` nodes (data dirs survive, sockets and schedulers do
  not), re-wiring raft membership to the restarted node's fresh ephemeral
  addresses.
- :func:`replay_oracle` — replays a committed record sequence through a
  fresh host oracle engine with side effects suppressed (the recovery
  contract of ``StreamProcessorController`` reprocessing): the parity
  baseline for the "replay reconstructs the same state" invariant.

The six invariants chaos runs assert (see ``tests/test_chaos.py``,
``tests/test_snapshot_delta.py`` and ``docs/CHAOS.md``):

1. no acked (committed) append is ever lost,
2. at most one raft leader per term,
3. replay of the surviving committed log is bit-identical across
   independent oracle replays and structurally equal to the live engine,
4. snapshot-restore after a mid-commit crash converges to the same state,
5. a delta-chain snapshot restores bit-identically to a from-scratch
   full take of the same state,
6. a crash mid-delta-commit never orphans the previous snapshot's
   referenced segments (it stays restorable across salvage sweep + GC).
"""

from __future__ import annotations

import os
import random
import threading
import zlib
from typing import Callable, Dict, List, Optional, Tuple

from zeebe_tpu.transport import RemoteAddress
from zeebe_tpu.tracing.recorder import (
    FLIGHT,
    dump_flight_recorder,
    record_event,
)

WILDCARD = "*"


def forensics_dump(reason: str) -> str:
    """Dump the process flight recorder for a chaos failure; returns the
    dump path. Every invariant-failure path goes through here so the
    next flake comes with the broker-side event history attached."""
    return dump_flight_recorder(reason=reason)


def invariant(condition, message: str) -> None:
    """Chaos-invariant assert: on failure, dump the flight recorder to
    disk and attach the dump path (plus the recent event slice) to the
    raised AssertionError — a failing chaos run must carry its own
    forensics, not require a re-run under instrumentation."""
    if condition:
        return
    path = forensics_dump("invariant-failure")
    raise AssertionError(
        f"{message}\n[flight recorder dump: {path}]\n"
        f"recent events:\n{FLIGHT.format_slice(last=30)}"
    )


class FaultPlane:
    """Seeded network-fault injector for the TCP transports.

    Install with :meth:`install_client` / :meth:`install_server` (sets the
    transport's ``fault_hook``) and :meth:`register_endpoint` (maps a
    listening address to a node label so destinations resolve). Faults are
    configured either as hard partitions (:meth:`partition`,
    :meth:`isolate`) or probabilistic per-edge rules (:meth:`set_rule`).

    Determinism contract: each directed edge ``src → dst`` draws from its
    own ``random.Random`` seeded by ``(seed, src, dst)``, so the decision
    SEQUENCE per edge depends only on the seed and how many frames crossed
    that edge — not on cross-edge thread interleaving. ``trace`` records
    every decision as ``(edge_seq, src, dst, verb, n_bytes)``.

    Scope: REQUEST/MESSAGE frames on the client side, RESPONSE frames on
    the server side. Server-initiated pushes (``ConnectionHandle.push``)
    bypass the plane — partitions sever RPC by blocking the request
    direction, which starves pushes of the subscriptions that feed them.
    """

    def __init__(self, seed: int = 0):
        self.seed = seed
        self.trace: List[tuple] = []
        self._lock = threading.Lock()
        self._endpoints: Dict[Tuple[str, int], str] = {}
        self._blocked: set = set()  # directed (src, dst); WILDCARD allowed
        # (src, dst) → rule dict; WILDCARD allowed on either side
        self._rules: Dict[Tuple[str, str], dict] = {}
        self._edge_rngs: Dict[Tuple[str, str], random.Random] = {}
        self._edge_seq: Dict[Tuple[str, str], int] = {}

    # -- wiring ------------------------------------------------------------
    def register_endpoint(self, node: str, addr: RemoteAddress) -> None:
        """Teach the plane that ``addr`` (a listening address) belongs to
        ``node`` so outbound frames resolve their destination label."""
        with self._lock:
            self._endpoints[(addr.host, addr.port)] = node

    def install_client(self, transport, node: str) -> None:
        """Intercept ``transport``'s outbound REQUEST/MESSAGE frames as
        traffic originating at ``node``."""
        transport.fault_hook = self._make_hook(node)

    def install_server(self, transport, node: str) -> None:
        """Intercept ``transport``'s outbound RESPONSE frames as traffic
        originating at ``node`` (destination resolves to the wildcard —
        responses ride the requester's connection)."""
        transport.fault_hook = self._make_hook(node)

    def _make_hook(self, src: str) -> Callable:
        def hook(peer: Optional[RemoteAddress], data: bytes):
            return self.decide(src, self._node_of(peer), data)

        return hook

    def _node_of(self, peer: Optional[RemoteAddress]) -> Optional[str]:
        if peer is None:
            return None
        with self._lock:
            return self._endpoints.get((peer.host, peer.port))

    # -- fault configuration ----------------------------------------------
    def partition(self, a: str, b: str, symmetric: bool = True) -> None:
        """Block all traffic ``a → b`` (and ``b → a`` when symmetric)."""
        with self._lock:
            self._blocked.add((a, b))
            if symmetric:
                self._blocked.add((b, a))

    def isolate(self, node: str) -> None:
        """Full isolation: nothing in, nothing out."""
        with self._lock:
            self._blocked.add((node, WILDCARD))
            self._blocked.add((WILDCARD, node))

    def heal(self, a: Optional[str] = None, b: Optional[str] = None) -> None:
        """Remove partitions: ``heal()`` clears all, ``heal(a)`` clears
        every edge touching ``a``, ``heal(a, b)`` clears that pair only."""
        with self._lock:
            if a is None:
                self._blocked.clear()
            elif b is None:
                self._blocked = {
                    e for e in self._blocked if a not in e
                }
            else:
                self._blocked -= {(a, b), (b, a)}

    def set_rule(
        self,
        src: str = WILDCARD,
        dst: str = WILDCARD,
        drop: float = 0.0,
        duplicate: float = 0.0,
        delay_ms: int = 0,
        delay_jitter_ms: int = 0,
    ) -> None:
        """Probabilistic faults on an edge (wildcards match any node):
        ``drop``/``duplicate`` are per-frame probabilities; every delivered
        frame is deferred ``delay_ms`` plus a seeded jitter draw from
        ``[0, delay_jitter_ms]`` (jitter across frames IS reordering —
        frames overtake each other)."""
        with self._lock:
            self._rules[(src, dst)] = {
                "drop": drop,
                "duplicate": duplicate,
                "delay_ms": delay_ms,
                "delay_jitter_ms": delay_jitter_ms,
            }

    def clear_rules(self) -> None:
        with self._lock:
            self._rules.clear()

    # -- the decision point -------------------------------------------------
    def _edge_rng(self, src: str, dst: str) -> random.Random:
        key = (src, dst)
        rng = self._edge_rngs.get(key)
        if rng is None:
            # string seeding is stable across processes (unlike hash());
            # crc32 keeps the derived seed integral and readable in traces
            rng = random.Random(zlib.crc32(f"{self.seed}|{src}|{dst}".encode()))
            self._edge_rngs[key] = rng
        return rng

    def _find_rule(self, src: str, dst: Optional[str]) -> Optional[dict]:
        for key in (
            (src, dst),
            (src, WILDCARD),
            (WILDCARD, dst),
            (WILDCARD, WILDCARD),
        ):
            if key[1] is None and key != (WILDCARD, WILDCARD):
                continue
            rule = self._rules.get(key)  # type: ignore[arg-type]
            if rule is not None:
                return rule
        return None

    def decide(
        self, src: str, dst: Optional[str], data: bytes
    ) -> Optional[List[Tuple[float, bytes]]]:
        """Fault decision for one frame. Returns ``None`` (deliver
        normally), ``[]`` (drop), or a list of ``(delay_s, payload)``
        deliveries (delay/duplicate/reorder)."""
        with self._lock:
            blocked = (
                (src, dst) in self._blocked
                or (src, WILDCARD) in self._blocked
                or (WILDCARD, dst) in self._blocked
            )
            rule = self._find_rule(src, dst)
            edge = (src, dst or WILDCARD)
            seq = self._edge_seq.get(edge, 0)
            self._edge_seq[edge] = seq + 1
            if blocked:
                self.trace.append((seq, src, dst, "drop-partition", len(data)))
                return []
            if rule is None:
                self.trace.append((seq, src, dst, "pass", len(data)))
                return None
            rng = self._edge_rng(*edge)
            if rule["drop"] > 0 and rng.random() < rule["drop"]:
                self.trace.append((seq, src, dst, "drop", len(data)))
                return []
            delay = rule["delay_ms"]
            if rule["delay_jitter_ms"]:
                delay += rng.randrange(rule["delay_jitter_ms"] + 1)
            deliveries = [(delay / 1000.0, data)]
            verb = "delay" if delay else "pass"
            if rule["duplicate"] > 0 and rng.random() < rule["duplicate"]:
                deliveries.append((delay / 1000.0, data))
                verb = "duplicate"
            self.trace.append((seq, src, dst, verb, len(data)))
            return deliveries


class DiskFaults:
    """Disk-level crash simulation for ``SegmentedLogStorage`` and
    ``SnapshotStorage``. All methods operate on CLOSED/QUIESCENT state —
    they simulate what a kernel crash leaves behind, then the normal open
    path must recover."""

    # -- log storage --------------------------------------------------------
    @staticmethod
    def tear_log_tail(directory: str, nbytes: int = 7) -> str:
        """Cut ``nbytes`` off the last segment file — the on-disk state a
        crash mid-append leaves (a partial record frame at the tail).
        Returns the path of the torn segment."""
        segments = sorted(
            name for name in os.listdir(directory)
            if name.startswith("segment-") and name.endswith(".log")
        )
        if not segments:
            raise FileNotFoundError(f"no segments in {directory}")
        path = os.path.join(directory, segments[-1])
        size = os.path.getsize(path)
        with open(path, "r+b") as f:
            f.truncate(max(0, size - nbytes))
        return path

    @staticmethod
    def break_fsync(storage, times: int = 1) -> None:
        """Make the next ``times`` calls of ``storage.flush`` raise
        ``OSError`` (fsync failure), then restore the real flush."""
        real_flush = storage.flush
        state = {"left": times}

        def failing_flush():
            if state["left"] > 0:
                state["left"] -= 1
                raise OSError("injected fsync failure")
            storage.flush = real_flush
            real_flush()

        storage.flush = failing_flush

    # -- snapshot storage ---------------------------------------------------
    # crash points inside SnapshotStorage._swap_in's two-rename commit
    CRASH_TMP_WRITTEN = "tmp-written"    # tmp dir durable, no rename ran
    CRASH_OLD_ASIDE = "old-aside"        # old final moved aside, tmp not in
    CRASH_SWAPPED = "swapped"            # new final in, set-aside not deleted

    # additional crash point for MANIFEST (delta) snapshots: the new
    # segments are durable in segments/ but the manifest commit never ran —
    # they are orphans until GC'd, and the PREVIOUS snapshot must stay
    # fully restorable
    CRASH_SEGMENTS_WRITTEN = "segments-written"

    @classmethod
    def crash_snapshot_commit(
        cls, storage, metadata, payload: bytes, point: str
    ) -> None:
        """Replay ``SnapshotStorage.write(metadata, payload)`` but crash at
        ``point`` inside the two-rename commit, leaving exactly the on-disk
        state a real crash leaves. The next ``SnapshotStorage(root)`` open
        must salvage (restore the set-aside or delete the orphans)."""
        tmp = os.path.join(storage.root, metadata.dirname + ".tmp")
        final = os.path.join(storage.root, metadata.dirname)
        # the real writer populates the tmp dir (same files, same fsyncs) —
        # only the commit renames are simulated here
        storage.populate_blob_dir(tmp, payload)
        cls._crash_commit_renames(tmp, final, point)

    @classmethod
    def crash_manifest_commit(
        cls, storage, metadata, parts, reused, point: str
    ) -> None:
        """Replay ``SnapshotStorage.write_parts_delta`` (a delta/manifest
        snapshot take) but crash at ``point``: after the new segments are
        durable (``CRASH_SEGMENTS_WRITTEN``) or inside the manifest dir's
        two-rename commit. The previous snapshot's referenced segments must
        survive the crash AND the subsequent open+GC."""
        from zeebe_tpu.log.snapshot import _pack_manifest, part_hash
        import zlib as _zlib

        entries = []
        for name, data in parts:
            h = part_hash(data)
            if not storage.has_segment(h):
                storage._write_segment(h, _zlib.compress(data, 1))
            entries.append({"n": name, "h": h, "l": len(data)})
        for e in reused:
            entries.append({"n": str(e["n"]), "h": str(e["h"]), "l": int(e["l"])})
        if point == cls.CRASH_SEGMENTS_WRITTEN:
            return
        entries.sort(key=lambda e: e["n"])
        manifest = _pack_manifest(entries)
        tmp = os.path.join(storage.root, metadata.dirname + ".tmp")
        final = os.path.join(storage.root, metadata.dirname)
        if os.path.exists(tmp):
            import shutil as _shutil

            _shutil.rmtree(tmp)
        os.makedirs(tmp)
        with open(os.path.join(tmp, "manifest.bin"), "wb") as f:
            f.write(manifest)
            f.flush()
            os.fsync(f.fileno())
        with open(os.path.join(tmp, "checksum.crc32"), "w") as f:
            f.write(str(_zlib.crc32(manifest)))
            f.flush()
            os.fsync(f.fileno())
        cls._crash_commit_renames(tmp, final, point)

    @classmethod
    def _crash_commit_renames(cls, tmp: str, final: str, point: str) -> None:
        if point == cls.CRASH_TMP_WRITTEN:
            return
        aside = final + ".aside"
        if os.path.exists(final):
            os.rename(final, aside)
        if point == cls.CRASH_OLD_ASIDE:
            return
        os.rename(tmp, final)
        if point == cls.CRASH_SWAPPED:
            return
        raise ValueError(f"unknown crash point {point!r}")


def replay_oracle(records, partition_id: int = 0, num_partitions: int = 1):
    """Replay committed ``records`` through a fresh host oracle engine with
    side effects suppressed (results are discarded — every follow-up they
    would produce is already IN the committed sequence), exactly the
    recovery replay contract. Returns the engine for state comparison."""
    from zeebe_tpu.engine.interpreter import PartitionEngine, WorkflowRepository

    engine = PartitionEngine(
        partition_id=partition_id,
        num_partitions=num_partitions,
        repository=WorkflowRepository(),
        clock=lambda: 0,
    )
    for record in records:
        engine.process(record)
    return engine


def oracle_state_bytes(engine) -> bytes:
    """The engine's snapshot state under the data-only codec — the
    bit-identity witness for invariant 3."""
    from zeebe_tpu.log import stateser

    return stateser.encode_state(engine.snapshot_state())


class ChaosHarness:
    """In-process ``ClusterBroker`` cluster with crash/restart and fault-
    plane wiring (the chaos analogue of the tests' ClusteringRule).

    ``crash(node)`` stops a broker (transports, scheduler, actors die; the
    data dir survives). ``restart(node)`` brings it back on fresh ephemeral
    ports and re-installs raft membership everywhere with the new
    addresses — the same re-bootstrap a deployment's service discovery
    performs. Combine with :class:`DiskFaults` between crash and restart
    to simulate torn writes.
    """

    def __init__(
        self,
        data_root: str,
        n_brokers: int = 3,
        partitions: int = 1,
        plane: Optional[FaultPlane] = None,
        engine_factory=None,
        cfg_tweaks: Optional[Callable] = None,
    ):
        from zeebe_tpu.runtime.cluster_broker import ClusterBroker

        self._broker_cls = ClusterBroker
        self.data_root = data_root
        self.partitions = partitions
        self.plane = plane
        self.engine_factory = engine_factory
        self.cfg_tweaks = cfg_tweaks
        self.crashed: set = set()
        self.brokers: Dict[str, object] = {}
        for i in range(n_brokers):
            node = f"b{i}"
            self.brokers[node] = self._make_broker(node)
        nodes = list(self.brokers.values())
        for broker in nodes[1:]:
            broker.join([nodes[0].gossip_address]).join(10)
        for pid in range(partitions):
            addrs = {
                node: broker.open_partition(pid).join(10)
                for node, broker in self.brokers.items()
            }
            for node, broker in self.brokers.items():
                members = {n: a for n, a in addrs.items() if n != node}
                broker.bootstrap_partition(pid, members)
        if self.plane is not None:
            for node in self.brokers:
                self._adopt(node)

    def _make_cfg(self, node: str):
        from zeebe_tpu.runtime.config import BrokerCfg

        cfg = BrokerCfg()
        cfg.network.client_port = 0
        cfg.network.management_port = 0
        cfg.network.subscription_port = 0
        cfg.metrics.port = 0
        cfg.metrics.enabled = False
        cfg.cluster.node_id = node
        cfg.cluster.partitions = self.partitions
        cfg.raft.heartbeat_interval_ms = 30
        cfg.raft.election_timeout_ms = 200
        cfg.gossip.probe_interval_ms = 50
        cfg.gossip.probe_timeout_ms = 250
        cfg.gossip.sync_interval_ms = 500
        cfg.data.snapshot_replication_period_ms = 300
        if self.cfg_tweaks is not None:
            self.cfg_tweaks(cfg)
        return cfg

    def _make_broker(self, node: str):
        return self._broker_cls(
            self._make_cfg(node),
            os.path.join(self.data_root, node),
            engine_factory=self.engine_factory,
        )

    def _adopt(self, node: str) -> None:
        """Wire one broker's transports into the fault plane."""
        broker = self.brokers[node]
        plane = self.plane
        plane.register_endpoint(node, broker.client_address)
        plane.register_endpoint(node, broker.subscription_server.address)
        plane.install_client(broker.client_transport, node)
        plane.install_server(broker.client_server, node)
        for server in broker.partitions.values():
            plane.register_endpoint(node, server.raft.address)
            plane.install_client(server.raft.client, node)
            plane.install_server(server.raft.server, node)

    # -- cluster queries ----------------------------------------------------
    def leader_of(self, pid: int = 0):
        for node, broker in self.brokers.items():
            if node in self.crashed:
                continue  # a closed broker's stale is_leader flag is a corpse
            server = broker.partitions.get(pid)
            if server is not None and server.is_leader:
                return broker
        return None

    def await_leaders(self, timeout: float = 60.0) -> None:
        import time

        deadline = time.monotonic() + timeout
        while time.monotonic() < deadline:
            if all(
                self.leader_of(pid) is not None for pid in range(self.partitions)
            ):
                return
            time.sleep(0.02)
        raise AssertionError(
            "no leader within timeout: "
            + str({
                node: {
                    pid: p.is_leader for pid, p in broker.partitions.items()
                }
                for node, broker in self.brokers.items()
            })
        )

    def client(self, **kw):
        from zeebe_tpu.gateway.cluster_client import ClusterClient

        return ClusterClient(
            [b.client_address for b in self.brokers.values()],
            num_partitions=self.partitions,
            **kw,
        )

    def partition_data_dir(self, node: str, pid: int = 0) -> str:
        return os.path.join(self.data_root, node, f"partition-{pid}")

    # -- chaos actions ------------------------------------------------------
    def crash(self, node: str) -> None:
        """Crash-stop a broker: transports, raft actors and scheduler die;
        the data dir stays for a later restart. (File buffers are flushed
        on close — use :class:`DiskFaults` on the data dir afterwards to
        simulate torn writes.)"""
        record_event("chaos", "crash-stop broker", node=node)
        self.crashed.add(node)
        self.brokers[node].close()

    def restart(self, node: str) -> None:
        """Bring a crashed broker back (fresh ephemeral ports) and re-
        install raft membership cluster-wide with the new addresses."""
        record_event("chaos", "restart broker", node=node)
        broker = self._make_broker(node)
        self.brokers[node] = broker
        self.crashed.discard(node)
        contact = next(
            (
                b.gossip_address
                for n, b in self.brokers.items()
                if n != node and n not in self.crashed
            ),
            None,
        )
        if contact is not None:
            broker.join([contact]).join(10)
        for pid in range(self.partitions):
            broker.open_partition(pid).join(10)
        for pid in range(self.partitions):
            addrs = {
                n: b.partitions[pid].raft.address
                for n, b in self.brokers.items()
                if n not in self.crashed and pid in b.partitions
            }
            for n, b in self.brokers.items():
                if n not in self.crashed and pid in b.partitions:
                    members = {m: a for m, a in addrs.items() if m != n}
                    b.bootstrap_partition(pid, members)
        if self.plane is not None:
            self._adopt(node)

    def close(self) -> None:
        for broker in self.brokers.values():
            try:
                broker.close()
            except Exception:  # noqa: BLE001 - already-crashed nodes
                pass
