"""Raft consensus: per-partition log replication.

Reference parity: ``raft/`` — one raft actor per partition replicating the
partition's log stream (``Raft.java:85``), follower/candidate/leader states
(``raft/.../state/``), poll-before-vote elections (``RaftPollService`` —
the pre-vote that avoids term inflation from partitioned nodes), leader
replication via per-member controllers walking the log and shipping
``AppendRequest``s (``MemberReplicateLogController.java:46-199``), quorum
commit = sorted match positions at index ``n - quorum``
(``LeaderState.java:171-199`` keeps ``positions[n+1-quorum]`` of n+1
members), persistent term/votedFor/members (``RaftPersistentStorage``),
and membership change via configuration events on the log
(``RaftConfigurationEvent``; single-step here instead of joint consensus —
one config change may be in flight at a time).

Re-design: messages are msgpack maps over the shared TCP transport (no SBE
schema); log entries travel as the codec's record frames. All state
mutation is single-writer on the raft actor.

Wire (msgpack maps, all request/response):
  poll / vote: {t, term, candidate, last_position, last_term}
               → {granted: bool, term}
  append:      {t: "append", term, leader, prev_position, prev_term,
                commit, frames: bytes}
               → {t: "append-rsp", term, success, match_position}
"""

from __future__ import annotations

import dataclasses
import enum
import json
import os
import random
import threading
from typing import Callable, Dict, List, Optional

from zeebe_tpu._events import count_event as _count_event
from zeebe_tpu.tracing.recorder import record_event as _flight
from zeebe_tpu.log.logstream import LogStream
from zeebe_tpu.protocol import codec, msgpack
from zeebe_tpu.runtime.actors import Actor, ActorFuture, ActorScheduler
from zeebe_tpu.transport import ClientTransport, RemoteAddress, ServerTransport


class RaftState(enum.Enum):
    FOLLOWER = "follower"
    CANDIDATE = "candidate"
    LEADER = "leader"


@dataclasses.dataclass
class RaftConfig:
    """Reference: the [raft] section of zeebe.cfg.toml (250ms heartbeat,
    1s election timeout)."""

    heartbeat_interval_ms: int = 100
    election_timeout_ms: int = 400
    election_jitter_ms: int = 400
    replication_batch_records: int = 128
    # per-peer RPC backoff: after a failed append/poll/vote exchange the
    # peer is not re-contacted for base * 2^(failures-1) ms (+ jitter),
    # capped at max — a dead or partitioned-away peer must not be hammered
    # at the full heartbeat rate (bare re-sends amplified exactly when the
    # cluster was least healthy)
    rpc_backoff_base_ms: int = 50
    rpc_backoff_max_ms: int = 2000
    # commit-latency watchdog: a leader holding appends un-COMMITTED for
    # longer than this logs + counts + flight-records the stall (the
    # "commit stuck at the no-op" failure class)
    commit_stall_ms: int = 5000


class RaftPersistentStorage:
    """Durable (term, voted_for, members) — reference RaftPersistentStorage
    writes a small metadata file per partition."""

    def __init__(self, path: Optional[str]):
        self.path = path
        self.term = 0
        self.voted_for: Optional[str] = None
        self.members: Dict[str, List] = {}  # member id → [host, port]
        if path and os.path.exists(path):
            with open(path) as f:
                data = json.load(f)
            self.term = data.get("term", 0)
            self.voted_for = data.get("voted_for")
            self.members = data.get("members", {})

    def save(self) -> None:
        if not self.path:
            return
        tmp = self.path + ".tmp"
        with open(tmp, "w") as f:
            json.dump(
                {"term": self.term, "voted_for": self.voted_for, "members": self.members},
                f,
            )
            f.flush()
            # raft safety: term/vote must be durable before answering any
            # RPC — an async fsync would reintroduce the double-vote window
            # zblint: disable=actor-thread-blocking (deliberate sync fsync)
            os.fsync(f.fileno())
        os.replace(tmp, self.path)


class Raft(Actor):
    """One node's raft endpoint for one partition."""

    def __init__(
        self,
        node_id: str,
        log: LogStream,
        scheduler: ActorScheduler,
        config: Optional[RaftConfig] = None,
        host: str = "127.0.0.1",
        port: int = 0,
        storage_path: Optional[str] = None,
        rng: Optional[random.Random] = None,
    ):
        super().__init__(f"raft-{node_id}")
        self.node_id = node_id
        self.log = log
        self.scheduler = scheduler
        self.config = config or RaftConfig()
        self.rng = rng or random.Random(hash(node_id) & 0xFFFFFFFF)

        self.persistent = RaftPersistentStorage(storage_path)
        self.state = RaftState.FOLLOWER
        self.leader_id: Optional[str] = None
        self.votes: set = set()
        self.polls: set = set()
        # leader replication state: member id → next position to ship
        self.next_position: Dict[str, int] = {}
        self.match_position: Dict[str, int] = {}
        self._last_heartbeat_ms = 0
        self._election_deadline_ms = 0
        # per-peer RPC backoff state: member id → (consecutive_failures,
        # earliest retry time in scheduler ms); see RaftConfig.rpc_backoff_*
        self._peer_backoff: Dict[str, tuple] = {}
        # set when the leader probes us with snapshot_needed (we are below
        # its compaction floor); the snapshot-replication service reads it
        # to decide a log fast-forward is legitimate
        self.snapshot_needed = False
        # applied config entries (position, members) for truncate rollback:
        # single-step membership applies ON APPEND, so removing the entry
        # from the log must revert to the previous configuration
        self._config_log: List[tuple] = []
        self._self_removal_position: Optional[int] = None
        self._state_listeners: List[Callable[[RaftState, int], None]] = []
        self._stopped = False
        # group-commit queue: append() calls enqueue here and one drain job
        # on the raft actor appends EVERYTHING queued as one log append +
        # one durability flush (see append)
        self._append_queue: List[tuple] = []
        self._append_lock = threading.Lock()
        # appended-but-uncommitted caller futures: (first, last, enq_ms,
        # future), resolved when the commit position covers them and
        # FAILED when a new leader's replication truncates them — acked
        # means COMMITTED (see append()). Guarded by _append_lock (the
        # drain registers on the raft actor; close() may fail them from
        # another thread).
        self._pending_commits: List[tuple] = []
        self._commit_stall_warned = False
        # log positions THIS raft bound sampled spans to (as leader, in
        # _stamp_traced_appends): truncation cleanup touches only these,
        # because the tracer is process-global and an in-process peer's
        # follower-side truncate must not finish the real leader's live
        # spans. Raft-actor-only state (append/resolve/truncate all run
        # there); pruned as commits cover it, so it stays sampled-sized.
        self._traced_bound: set = set()

        self.server = ServerTransport(host=host, port=port, request_handler=self._on_request)
        self.client = ClientTransport(default_timeout_ms=1000)
        scheduler.submit_actor(self)  # zblint: disable=unobserved-actor-future (boot submit; start failures land in the scheduler failure ring)

    # -- public API --------------------------------------------------------
    @property
    def address(self) -> RemoteAddress:
        return self.server.address

    @property
    def term(self) -> int:
        return self.persistent.term

    def bootstrap(self, members: Dict[str, RemoteAddress]) -> None:
        """Install the initial static membership (reference: persisted
        configuration from partition creation). Includes self."""

        def do():
            self.persistent.members = {
                mid: [a.host, a.port] for mid, a in members.items()
            }
            self.persistent.save()
            self._reset_election_timer()

        self.actor.run(do)

    def on_state_change(self, listener: Callable[[RaftState, int], None]) -> None:
        """listener(new_state, term); fires on this node's transitions
        (reference onStateChange → PartitionInstallService)."""
        self._state_listeners.append(listener)

    def append(self, records: List) -> ActorFuture:
        """Leader-only: append records to the replicated log. Completes
        with the last position once the records are COMMITTED (quorum-
        replicated), and completes exceptionally when they are lost —
        deposed before the drain ran, or truncated off this node's log by
        a new leader's replication.

        Acked-means-committed is the liveness contract the old
        acked-on-local-durability version broke: an append landing on a
        leader that was already deposed (but had not yet heard the new
        term) returned success for records the new leader then truncated,
        so a caller retrying only on FAILURE hung forever waiting for a
        commit that could never come (the recorded
        ``test_appends_replicate_and_commit`` flake — commit stuck at the
        no-op). Now that window resolves the future exceptionally and the
        caller's retry lands on the real leader. Retries are
        at-least-once: a failed future's records MAY still commit if the
        new leader already replicated them (standard raft "leadership
        lost" ambiguity; the client-level cid dedup covers commands).

        GROUP COMMIT: calls that queue while the raft actor is busy drain
        as ONE ``log.append`` + ONE durability flush (fsync) + one
        replication fan-out, in call order. Frames stay byte-identical to
        individual appends (per-record codec framing is unchanged) — only
        the fsync/replication round-trip count amortizes, which is the
        serving path's per-command floor."""
        future = ActorFuture()
        with self._append_lock:
            self._append_queue.append((records, future))
            first = len(self._append_queue) == 1
        if first:  # one drain job per burst; later calls ride it
            self.actor.run(self._drain_appends)
        return future

    def _drain_appends(self) -> None:
        with self._append_lock:
            batch, self._append_queue = self._append_queue, []
        if not batch:
            return
        if self._stopped:
            # close() already swept _pending_commits; a drain landing
            # after that sweep must not append or register new pending
            # entries — nothing would ever resolve them
            for _records, future in batch:
                future.complete_exceptionally(RuntimeError("raft closed"))
            return
        if self.state != RaftState.LEADER:
            for _records, future in batch:
                future.complete_exceptionally(RuntimeError("not leader"))
            return
        from zeebe_tpu.protocol.columnar import ColumnarBatch, MixedBatch

        term = self.persistent.term
        columnar = False
        for records, _future in batch:
            if isinstance(records, ColumnarBatch):
                # device-emission follow-ups arrive as a lazy batch: the
                # term stamps onto the COLUMN (lazy rows pick it up at
                # materialization), never forcing a row build here
                records.set_raft_term(term)
                columnar = True
            else:
                for record in records:
                    record.raft_term = term
        if not columnar:
            merged: List = []
            for records, _future in batch:
                merged.extend(records)
        elif len(batch) == 1:
            merged = batch[0][0]
        else:
            # a coalesced group with a columnar member: merge the groups'
            # tail ENTRIES (real rows + lazy refs) in call order — the
            # combined batch still encodes in one pass, rows stay lazy
            entries: List = []
            for records, _future in batch:
                if isinstance(records, ColumnarBatch):
                    entries.extend(records.log_entries())
                else:
                    entries.extend(records)
            merged = MixedBatch(entries)
        group_sizes = [len(records) for records, _future in batch]
        try:
            last = self.log.append(merged, commit=False)
            self.log.flush()  # ONE durable fsync for the whole group
        except Exception as e:
            # storage failure (e.g. closed mid-shutdown): fail every
            # queued caller instead of leaving futures to hang
            for _records, future in batch:
                future.complete_exceptionally(e)
            raise
        if len(batch) > 1:
            _count_event(
                "log_group_commit_coalesced",
                "append() calls that shared another call's fsync",
                delta=len(batch) - 1,
            )
        # positions are dense over the merged group: each caller's last
        # position derives from its slice, with no row materialization
        first = last - len(merged) + 1 if len(merged) else last + 1
        end = 0
        now = self.scheduler.now_ms()
        with self._append_lock:
            # close() flips _stopped before sweeping under this lock, so
            # re-checking here is race-free: registering after the sweep
            # would leave the futures with no resolver
            stopped = self._stopped
            for (records, future), size in zip(batch, group_sizes):
                end += size
                if stopped:
                    future.complete_exceptionally(RuntimeError("raft closed"))
                elif size:
                    self._pending_commits.append(
                        (first + end - size, first + end - 1, now, future)
                    )
                else:  # nothing to commit-wait on
                    future.complete(last)
        self._stamp_traced_appends(batch)
        self.match_position[self.node_id] = last
        self._maybe_commit()
        self._replicate_all()

    def _stamp_traced_appends(self, batch) -> None:
        """Record-lifecycle tracing: bind sampled client commands to the
        log positions this group commit just assigned (stamps RAFT_FSYNC).
        One global read when tracing is off; one dict-truthiness read when
        no request spans are live."""
        from zeebe_tpu import tracing

        tracer = tracing.TRACER
        if tracer is None or not tracer.tracking_requests():
            return
        pid = getattr(self.log, "partition_id", 0)
        for records, _future in batch:
            if not isinstance(records, list):
                continue  # columnar emissions carry no client request ids
            for record in records:
                rid = getattr(record.metadata, "request_id", -1)
                if rid is not None and rid >= 0:
                    if tracer.bind_append(rid, pid, record.position):
                        self._traced_bound.add(record.position)

    def _resolve_pending_commits(self) -> None:
        """Complete append futures whose spans the commit position now
        covers (acked means committed). Runs on the raft actor — both the
        leader's quorum commit and a deposed leader learning the new
        leader's commit resolve here."""
        commit = self.log.commit_position
        if self._traced_bound:
            # committed positions can never be truncated ("commit is
            # final"): stop tracking them for truncation cleanup
            self._traced_bound = {
                p for p in self._traced_bound if p > commit
            }
        done: List[tuple] = []
        with self._append_lock:
            if not self._pending_commits:
                return
            keep = []
            for entry in self._pending_commits:
                (done if entry[1] <= commit else keep).append(entry)
            self._pending_commits = keep
            if done or not keep:
                # progress ends a stall episode even when newer pendings
                # remain (sustained load never drains to empty): a later
                # wedge must warn and count again
                self._commit_stall_warned = False
        for _first, last_pos, _enq, future in done:
            future.complete(last_pos)

    def on_snapshot_fast_forward(self) -> None:
        """Snapshot catch-up reset the log underneath raft (fast_forward
        discards everything below the snapshot boundary and jumps the
        commit position without going through set_commit_position): every
        pending append future references superseded positions and would
        otherwise hang forever. Fail them all — the records MAY have
        committed cluster-wide (the snapshot covers them; standard
        leadership-lost at-least-once ambiguity) — so callers retry on
        the real leader, and finish their bound spans before the
        positions are re-served."""
        self._fail_pending_from(0, "snapshot fast-forward")

    def _fail_pending_from(self, position: int, reason: str) -> None:
        """A truncate removed everything from ``position`` on: append
        futures whose span intersects the cut lost records — fail them so
        callers retry on the real leader instead of waiting forever."""
        from zeebe_tpu import tracing

        tracer = tracing.TRACER
        if tracer is not None and self._traced_bound:
            # the cut records no longer exist and their positions will be
            # reused by the new leader: finish the bound spans so a later
            # commit over a reused position cannot mis-stamp a dead trace.
            # BEFORE the empty-pendings return — a second truncate walking
            # further back can arrive with no pendings left but live spans
            # still bound in the newly-cut range. Restricted to positions
            # THIS raft bound: the tracer is process-global, and a
            # follower-side truncate must not finish the in-process
            # leader's live spans
            mine = {p for p in self._traced_bound if p >= position}
            if mine:
                tracer.truncate_positions_from(
                    getattr(self.log, "partition_id", 0), position,
                    only=mine,
                )
                self._traced_bound -= mine
        failed: List[tuple] = []
        with self._append_lock:
            if not self._pending_commits:
                return
            keep = []
            for entry in self._pending_commits:
                (failed if entry[1] >= position else keep).append(entry)
            self._pending_commits = keep
            if failed or not keep:
                # the stall episode (if any) ended with the cut pendings:
                # re-arm the watchdog for the next one
                self._commit_stall_warned = False
        if failed:
            _count_event(
                "raft_appends_truncated",
                "Acked-pending append futures failed because a new "
                "leader's replication truncated their records",
                delta=len(failed),
            )
            _flight(
                "raft", "pending appends truncated", node=self.node_id,
                term=self.persistent.term, position=position,
                futures=len(failed), reason=reason,
            )
        for _first, _last, _enq, future in failed:
            future.complete_exceptionally(
                RuntimeError(f"not leader: {reason}")
            )

    # membership ops retry/forward for this long before giving up — a
    # leadership flap mid-call must not surface "not leader" to callers
    # (reference RaftJoinService retries joins until a leader accepts)
    MEMBERSHIP_TIMEOUT_MS = 10_000
    _MEMBERSHIP_RETRY_MS = 150

    def add_member(self, member_id: str, addr: RemoteAddress) -> ActorFuture:
        """Single-step membership change: appends a configuration entry
        with the new member set; the configuration takes effect ON APPEND
        (reference RaftConfigurationEvent / RaftJoinService; raft
        dissertation §4.1 — one change in flight at a time is the caller's
        responsibility). May be called on ANY node: a non-leader forwards
        the op to the current leader and retries across leadership flaps
        until ``MEMBERSHIP_TIMEOUT_MS``."""
        return self._change_membership(
            {"op": "add", "member": member_id, "addr": [addr.host, addr.port]}
        )

    def remove_member(self, member_id: str) -> ActorFuture:
        return self._change_membership({"op": "remove", "member": member_id})

    @staticmethod
    def _membership_mutation(op: dict):
        if op["op"] == "add":
            return lambda m: {**m, op["member"]: list(op["addr"])}
        return lambda m: {k: v for k, v in m.items() if k != op["member"]}

    def _change_membership(self, op: dict) -> ActorFuture:
        future = ActorFuture()
        deadline = self.scheduler.now_ms() + self.MEMBERSHIP_TIMEOUT_MS

        def attempt():
            if future.is_done():
                return
            if self._stopped:
                future.complete_exceptionally(RuntimeError("raft closed"))
                return
            if self.state == RaftState.LEADER:
                try:
                    future.complete(self._apply_membership_as_leader(op))
                except Exception as e:  # noqa: BLE001
                    future.complete_exceptionally(e)
                return
            # not the leader: forward to the leader we know of, or wait
            # out the election and retry
            target = self._membership_forward_target()
            if target is None:
                retry_later()
                return
            request = msgpack.pack({"t": "membership", **op})

            def on_response(msg):
                if future.is_done():
                    return
                if msg is not None and msg.get("ok"):
                    future.complete(int(msg.get("position", -1)))
                elif msg is not None and msg.get("error"):
                    # the leader ACCEPTED leadership of the op but failed
                    # applying it (e.g. log write error) — that is a real
                    # failure, not a redirect; surface it instead of
                    # retrying into the same error for 10s
                    future.complete_exceptionally(
                        RuntimeError(f"membership change failed: {msg['error']}")
                    )
                else:
                    retry_later()

            self._ask(target, request, on_response)

        def retry_later():
            if self.scheduler.now_ms() >= deadline:
                future.complete_exceptionally(
                    RuntimeError(
                        f"membership change {op['op']} {op['member']!r} "
                        f"timed out after {self.MEMBERSHIP_TIMEOUT_MS}ms "
                        "(no leader accepted it)"
                    )
                )
                return
            self.actor.run_delayed(self._MEMBERSHIP_RETRY_MS, attempt)

        self.actor.run(attempt)
        return future

    def _membership_forward_target(self) -> Optional[RemoteAddress]:
        """Address of the node to forward a membership op to: the current
        leader if known, else None (caller retries after the election)."""
        if self.leader_id is None or self.leader_id == self.node_id:
            return None
        entry = self.persistent.members.get(self.leader_id)
        if entry is None:
            return None
        return RemoteAddress(entry[0], int(entry[1]))

    def _apply_membership_as_leader(self, op: dict) -> int:
        """Leader-side config append (must run on the raft actor while
        leader). Returns the config entry's position."""
        from zeebe_tpu.protocol.enums import RecordType, ValueType
        from zeebe_tpu.protocol.metadata import RecordMetadata
        from zeebe_tpu.protocol.records import RaftConfigurationRecord, Record

        mutate = self._membership_mutation(op)
        new_members = mutate(dict(self.persistent.members))
        record = Record(
            metadata=RecordMetadata(
                record_type=RecordType.EVENT,
                value_type=ValueType.RAFT,
                intent=0,
            ),
            value=RaftConfigurationRecord(members=new_members),
        )
        record.raft_term = self.persistent.term
        last = self.log.append([record], commit=False)
        self.log.flush()
        self._config_log.append((last, dict(self.persistent.members)))
        self._apply_config(new_members)
        if self.node_id not in new_members:
            self._self_removal_position = last
        self.match_position[self.node_id] = last
        self._maybe_commit()
        self._replicate_all()
        return last

    def _apply_config(self, members: Dict[str, list]) -> None:
        self.persistent.members = dict(members)
        self.persistent.save()
        if self.state == RaftState.LEADER:
            last, _ = self._last_entry()
            for mid in self._other_members():
                self.next_position.setdefault(mid, last + 1)
                self.match_position.setdefault(mid, -1)
            for mid in list(self.next_position):
                if mid not in self.persistent.members:
                    self.next_position.pop(mid, None)
                    self.match_position.pop(mid, None)
            # a leader removing ITSELF keeps leading until the removal
            # entry COMMITS (dissertation §4.2.2: it manages the cluster
            # through the transition, not counting itself toward quorum —
            # _maybe_commit already iterates only current members), then
            # steps aside. Stepping down immediately would orphan the
            # un-replicated entry.

    def _maybe_apply_config(self, record) -> None:
        from zeebe_tpu.protocol.enums import ValueType

        if int(record.metadata.value_type) == int(ValueType.RAFT):
            members = getattr(record.value, "members", None)
            if isinstance(members, dict) and members:
                self._config_log.append(
                    (record.position, dict(self.persistent.members))
                )
                self._apply_config(members)

    def _rollback_config(self, position: int) -> None:
        """Truncating a suffix that contained configuration entries must
        revert to the configuration in force before them (raft dissertation
        §4.1: config-on-append implies config-rollback-on-truncate)."""
        reverted = None
        while self._config_log and self._config_log[-1][0] >= position:
            _pos, previous = self._config_log.pop()
            reverted = previous
        if reverted is not None:
            self._apply_config(reverted)

    def close(self) -> None:
        self._stopped = True
        with self._append_lock:
            pending, self._pending_commits = self._pending_commits, []
            self._commit_stall_warned = False
        for _first, _last, _enq, future in pending:
            future.complete_exceptionally(RuntimeError("raft closed"))
        self.server.close()
        self.client.close()

    # -- lifecycle ---------------------------------------------------------
    def on_actor_started(self) -> None:
        self._reset_election_timer()
        self.actor.run_at_fixed_rate(
            self.config.heartbeat_interval_ms, self._tick
        )

    def _members(self) -> Dict[str, RemoteAddress]:
        return {
            mid: RemoteAddress(a[0], int(a[1]))
            for mid, a in self.persistent.members.items()
        }

    def _quorum(self) -> int:
        return len(self.persistent.members) // 2 + 1

    def _other_members(self) -> Dict[str, RemoteAddress]:
        members = self._members()
        members.pop(self.node_id, None)
        return members

    def _reset_election_timer(self) -> None:
        self._election_deadline_ms = (
            self.scheduler.now_ms()
            + self.config.election_timeout_ms
            + self.rng.randrange(self.config.election_jitter_ms + 1)
        )

    # -- per-peer RPC backoff ----------------------------------------------
    # Scope: the backoff gates only the APPEND path (_replicate_one), which
    # re-sends at the heartbeat rate. Election poll/vote sends are NOT
    # gated — they are already paced and jittered by the election timer
    # (one send per member per timeout), and skipping a just-healed peer
    # there would stretch the leaderless window by up to the max backoff.
    # Poll/vote responses still feed the failure accounting, so a dead
    # peer discovered during an election is backed off on the append path.
    def _peer_backed_off(self, member_id: str) -> bool:
        entry = self._peer_backoff.get(member_id)
        return entry is not None and self.scheduler.now_ms() < entry[1]

    def _note_peer_failure(self, member_id: str) -> None:
        """A request to this peer failed (no/undecodable response): back off
        exponentially with jitter before contacting it again.

        Failures landing while the peer is ALREADY backed off don't
        escalate: one outage kills every in-flight request at once (several
        heartbeat-interval appends share the request-timeout window), and
        counting that burst as N failures would jump the delay straight to
        the max instead of ramping 1x, 2x, 4x per retry round."""
        entry = self._peer_backoff.get(member_id, (0, 0))
        if self.scheduler.now_ms() < entry[1]:
            return
        failures = entry[0] + 1
        delay = min(
            self.config.rpc_backoff_max_ms,
            self.config.rpc_backoff_base_ms * (1 << min(failures - 1, 16)),
        )
        delay += self.rng.randrange(delay // 2 + 1)  # jitter: desynchronize
        self._peer_backoff[member_id] = (
            failures, self.scheduler.now_ms() + delay
        )

    def _note_peer_ok(self, member_id: str) -> None:
        self._peer_backoff.pop(member_id, None)

    def _become(self, state: RaftState) -> None:
        if self.state == state:
            return
        self.state = state
        _flight(
            "raft", f"state -> {state.value}", node=self.node_id,
            term=self.persistent.term, partition=getattr(
                self.log, "partition_id", 0
            ),
        )
        for listener in self._state_listeners:
            listener(state, self.persistent.term)

    def _tick(self) -> None:
        if self._stopped or not self.persistent.members:
            return
        if self.state == RaftState.LEADER:
            self._check_commit_stall()
            self._replicate_all()
            return
        if self.scheduler.now_ms() >= self._election_deadline_ms:
            self._start_poll()

    def _check_commit_stall(self) -> None:
        """Commit-latency watchdog: a leader sitting on appends that never
        commit is exactly the silent failure mode the recorded replication
        flake had — warn ONCE per stall episode with the flight-recorder
        slice, count it, and leave forensics in the ring."""
        with self._append_lock:
            if not self._pending_commits:
                return
            oldest = self._pending_commits[0]
            stalled = (
                self.scheduler.now_ms() - oldest[2]
                > self.config.commit_stall_ms
            )
            if not stalled:
                return
            warned = self._commit_stall_warned
            self._commit_stall_warned = True
            pending = len(self._pending_commits)
        # count EVERY stalled tick (the log line stays once-per-episode):
        # a permanently wedged partition keeps the counter growing, which
        # is what the documented "sustained growth" alert watches
        _count_event(
            "raft_commit_stalls",
            "Ticks a leader spent with appends held uncommitted past the "
            "commit-latency watchdog threshold",
        )
        if warned:
            return
        _flight(
            "raft", "commit stall", node=self.node_id,
            term=self.persistent.term,
            commit=self.log.commit_position,
            oldest_pending=oldest[0], pending_futures=pending,
            match={m: p for m, p in self.match_position.items()},
        )
        from zeebe_tpu.tracing.recorder import FLIGHT
        import logging

        logging.getLogger(__name__).warning(
            "raft %s: appends pending past %dms without commit "
            "(commit=%d, oldest pending position %d, %d futures); "
            "recent flight-recorder events:\n%s",
            self.node_id, self.config.commit_stall_ms,
            self.log.commit_position, oldest[0], pending,
            FLIGHT.format_slice(last=25),
        )

    # -- election: poll (pre-vote) then vote -------------------------------
    def _last_entry(self):
        pos = self.log.next_position - 1
        if pos < 0:
            return -1, -1
        return pos, self.log.term_at(pos)

    def _start_poll(self) -> None:
        """Reference RaftPollService: ask peers whether they would grant a
        vote for term+1 WITHOUT bumping terms; only a poll majority starts a
        real election."""
        self._reset_election_timer()
        others = self._other_members()
        if not others:
            # single-node partition: immediate self-election
            self._start_election()
            return
        self.polls = {self.node_id}
        last_position, last_term = self._last_entry()
        request = msgpack.pack(
            {
                "t": "poll",
                "term": self.persistent.term + 1,
                "candidate": self.node_id,
                "last_position": last_position,
                "last_term": last_term,
            }
        )
        for mid, addr in others.items():
            self._ask(addr, request, lambda msg, mid=mid: self._on_poll_response(mid, msg))

    def _on_poll_response(self, member_id: str, msg: Optional[dict]) -> None:
        if msg is None:
            self._note_peer_failure(member_id)
            return
        self._note_peer_ok(member_id)
        if self.state == RaftState.LEADER:
            return
        if msg.get("granted"):
            self.polls.add(msg.get("from", len(self.polls)))
            if len(self.polls) >= self._quorum():
                self.polls = set()
                self._start_election()

    def _start_election(self) -> None:
        _count_event("raft_elections_started")
        _flight(
            "raft", "election started", node=self.node_id,
            term=self.persistent.term + 1,
        )
        self._become(RaftState.CANDIDATE)
        self.persistent.term += 1
        self.persistent.voted_for = self.node_id
        self.persistent.save()
        self.leader_id = None
        self.votes = {self.node_id}
        self._reset_election_timer()
        if len(self.persistent.members) <= 1 or self._quorum() == 1:
            self._become_leader()
            return
        last_position, last_term = self._last_entry()
        request = msgpack.pack(
            {
                "t": "vote",
                "term": self.persistent.term,
                "candidate": self.node_id,
                "last_position": last_position,
                "last_term": last_term,
            }
        )
        for mid, addr in self._other_members().items():
            self._ask(addr, request, lambda msg, mid=mid: self._on_vote_response(mid, msg))

    def _on_vote_response(self, member_id: str, msg: Optional[dict]) -> None:
        if msg is None:
            self._note_peer_failure(member_id)
            return
        self._note_peer_ok(member_id)
        if self.state != RaftState.CANDIDATE:
            return
        if msg.get("term", 0) > self.persistent.term:
            self._step_down(msg["term"])
            return
        if msg.get("granted") and msg.get("term") == self.persistent.term:
            self.votes.add(member_id)
            if len(self.votes) >= self._quorum():
                self._become_leader()

    def _become_leader(self) -> None:
        _count_event("raft_elections_won")
        self.leader_id = self.node_id
        last, _ = self._last_entry()
        for mid in self._other_members():
            self.next_position[mid] = last + 1
            self.match_position[mid] = -1
        self.match_position[self.node_id] = last
        self._become(RaftState.LEADER)
        # initial event: commit an entry of the new term to establish
        # leadership over prior-term entries (reference
        # LeaderCommitInitialEvent; raft §5.4.2 no-op entry)
        from zeebe_tpu.protocol.enums import RecordType, ValueType
        from zeebe_tpu.protocol.metadata import RecordMetadata
        from zeebe_tpu.protocol.records import NoopRecord, Record

        initial = Record(
            metadata=RecordMetadata(
                record_type=RecordType.EVENT,
                value_type=ValueType.NOOP,
                intent=0,
            ),
            value=NoopRecord(),
        )
        initial.raft_term = self.persistent.term
        last = self.log.append([initial], commit=False)
        self.log.flush()
        self.match_position[self.node_id] = last
        self._maybe_commit()
        self._replicate_all()

    def _step_down(self, term: int) -> None:
        if term > self.persistent.term:
            _flight(
                "raft", "term bump", node=self.node_id,
                old_term=self.persistent.term, new_term=term,
            )
            self.persistent.term = term
            self.persistent.voted_for = None
            self.persistent.save()
        if self.state != RaftState.FOLLOWER:
            self._become(RaftState.FOLLOWER)
        self._reset_election_timer()

    # -- leader replication -------------------------------------------------
    def _replicate_all(self) -> None:
        if self._stopped:
            return
        for mid, addr in self._other_members().items():
            self._replicate_one(mid, addr)

    def _replicate_one(self, member_id: str, addr: RemoteAddress) -> None:
        if self._peer_backed_off(member_id):
            return  # unreachable peer: exponential backoff, not bare re-sends
        next_pos = self.next_position.get(member_id, 0)
        if next_pos < self.log.base_position:
            # the member is behind the compaction floor: the records it
            # needs are gone. It catches up out-of-band via snapshot
            # replication (SnapshotReplicationService analogue) and its
            # next append-response log_end hint fast-forwards next_position.
            self._ask(
                addr,
                msgpack.pack(
                    {
                        "t": "append",
                        "term": self.persistent.term,
                        "leader": self.node_id,
                        "prev_position": self.log.next_position - 1,
                        "prev_term": self.log.term_at(self.log.next_position - 1),
                        "commit": self.log.commit_position,
                        "frames": b"",
                        "snapshot_needed": True,
                    }
                ),
                lambda msg, mid=member_id: self._on_append_response(
                    mid, -1, msg
                ),
            )
            return
        prev_pos = next_pos - 1
        prev_term = self.log.term_at(prev_pos) if prev_pos >= 0 else -1
        # one locked slice + ONE codec pass for the whole replication
        # batch (was a per-record record_at lock + encode + bytes concat)
        batch = self.log.slice_records(
            next_pos, limit=self.config.replication_batch_records
        )
        buf, _offsets = codec.encode_records(batch)
        frames = bytes(buf)
        count = len(batch)
        request = msgpack.pack(
            {
                "t": "append",
                "term": self.persistent.term,
                "leader": self.node_id,
                "prev_position": prev_pos,
                "prev_term": prev_term,
                "commit": self.log.commit_position,
                "frames": frames,
            }
        )
        self._ask(
            addr,
            request,
            lambda msg, mid=member_id, sent=count, base=next_pos: self._on_append_response(
                mid, base + sent - 1, msg
            ),
        )

    def _on_append_response(
        self, member_id: str, last_sent: int, msg: Optional[dict]
    ) -> None:
        if msg is None:
            self._note_peer_failure(member_id)
            return
        self._note_peer_ok(member_id)
        if self.state != RaftState.LEADER:
            return
        term = msg.get("term", 0)
        if term > self.persistent.term:
            self._step_down(term)
            return
        if msg.get("success"):
            match = int(msg.get("match_position", -1))
            self.match_position[member_id] = max(
                self.match_position.get(member_id, -1), match
            )
            self.next_position[member_id] = self.match_position[member_id] + 1
            self._maybe_commit()
        else:
            # follower diverged: resume from ITS log end (skips the classic
            # one-at-a-time walk-back). The hint may also JUMP FORWARD —
            # a follower that installed a snapshot past our compaction
            # floor reports its fast-forwarded end, and replication must
            # resume there rather than stay pinned below the floor.
            # Clamp the forward jump to our own log end: a follower with a
            # longer stale-term uncommitted suffix reports a log_end past
            # anything we hold, and probing beyond our log would degrade
            # into a one-record-per-round walk-back.
            hint = int(msg.get("log_end", self.next_position.get(member_id, 1)))
            cur = self.next_position.get(member_id, 1)
            if hint > cur:
                self.next_position[member_id] = min(hint, self.log.next_position)
            else:
                self.next_position[member_id] = max(0, min(hint, cur - 1))

    def _maybe_commit(self) -> None:
        """Quorum commit (reference LeaderState.commit:171-199): sort match
        positions of all members, take the quorum-th highest — but never
        commit entries of a previous term (raft §5.4.2)."""
        positions = sorted(
            self.match_position.get(mid, -1) for mid in self.persistent.members
        )
        candidate = positions[len(positions) - self._quorum()]
        if candidate <= self.log.commit_position:
            return
        if self.log.term_at(candidate) != self.persistent.term:
            return
        self.log.set_commit_position(candidate)
        self._resolve_pending_commits()
        if (
            self._self_removal_position is not None
            and candidate >= self._self_removal_position
        ):
            # our own removal is committed: step aside now
            self._self_removal_position = None
            self._become(RaftState.FOLLOWER)

    # -- request handling (IO thread → actor hop) ---------------------------
    def _ask(self, addr: RemoteAddress, payload: bytes, callback) -> None:
        future = self.client.send_request(addr, payload)

        def on_complete(f: ActorFuture):
            msg = None
            if f._exception is None:
                try:
                    msg = msgpack.unpack(f._value)
                except Exception:  # noqa: BLE001
                    msg = None
            self.actor.run(lambda: callback(msg))

        future.on_complete(on_complete)

    def _on_request(self, payload: bytes):
        """IO thread: decode only; handlers run on the raft actor and the
        response future is completed there (the IO loop never blocks behind
        a slow append — heartbeats and votes keep flowing)."""
        try:
            msg = msgpack.unpack(payload)
        except Exception:  # noqa: BLE001
            return None
        t = msg.get("t")
        if t == "poll":
            return self.actor.call(lambda: self._handle_poll(msg))
        if t == "vote":
            return self.actor.call(lambda: self._handle_vote(msg))
        if t == "append":
            return self.actor.call(lambda: self._handle_append(msg))
        if t == "membership":
            return self.actor.call(lambda: self._handle_membership(msg))
        return None

    def _log_up_to_date(self, msg: dict) -> bool:
        last_position, last_term = self._last_entry()
        return (msg.get("last_term", -1), msg.get("last_position", -1)) >= (
            last_term,
            last_position,
        )

    def _handle_membership(self, msg: dict) -> bytes:
        """Forwarded membership op (reference RaftJoinService: the leader
        accepts joins; non-leaders answer with a redirect hint and the
        caller retries)."""
        if self.state != RaftState.LEADER:
            return msgpack.pack({"ok": False, "leader": self.leader_id})
        try:
            position = self._apply_membership_as_leader(
                {k: msg[k] for k in ("op", "member", "addr") if k in msg}
            )
        except Exception as e:  # noqa: BLE001
            return msgpack.pack({"ok": False, "error": str(e)})
        return msgpack.pack({"ok": True, "position": position})

    def _handle_poll(self, msg: dict) -> bytes:
        # inbound traffic proves the peer is back (a backed-off healed
        # follower times out and polls — without this, the leader would sit
        # out the rest of the backoff before resuming its appends)
        self._note_peer_ok(msg.get("candidate"))
        # A current leader never grants pre-votes: _last_heartbeat_ms is
        # only refreshed by incoming appends, which a leader does not
        # receive, so without this guard a rejoining up-to-date node could
        # poll-quorum a healthy leader into stepping aside (the exact churn
        # pre-vote exists to prevent — reference RaftPollService).
        granted = (
            self.state != RaftState.LEADER
            and msg.get("term", 0) > self.persistent.term
            and self._log_up_to_date(msg)
            and self.scheduler.now_ms() >= self._last_heartbeat_ms
            + self.config.election_timeout_ms
        )
        return msgpack.pack(
            {"granted": granted, "term": self.persistent.term, "from": self.node_id}
        )

    def _handle_vote(self, msg: dict) -> bytes:
        self._note_peer_ok(msg.get("candidate"))  # see _handle_poll
        term = msg.get("term", 0)
        if term > self.persistent.term:
            self._step_down(term)
        granted = (
            term == self.persistent.term
            and self.persistent.voted_for in (None, msg.get("candidate"))
            and self._log_up_to_date(msg)
        )
        if granted:
            self.persistent.voted_for = msg.get("candidate")
            self.persistent.save()
            self._reset_election_timer()
        return msgpack.pack(
            {"granted": granted, "term": self.persistent.term, "from": self.node_id}
        )

    def _handle_append(self, msg: dict) -> bytes:
        self._note_peer_ok(msg.get("leader"))  # see _handle_poll
        term = msg.get("term", 0)
        if term < self.persistent.term:
            return msgpack.pack(
                {"t": "append-rsp", "term": self.persistent.term, "success": False}
            )
        if term > self.persistent.term or self.state != RaftState.FOLLOWER:
            self._step_down(term)
        self.leader_id = msg.get("leader")
        self._last_heartbeat_ms = self.scheduler.now_ms()
        self._reset_election_timer()

        self.snapshot_needed = bool(msg.get("snapshot_needed", False))
        prev_position = int(msg.get("prev_position", -1))
        prev_term = int(msg.get("prev_term", -1))
        if prev_position >= 0:
            if prev_position >= self.log.next_position:
                return msgpack.pack(
                    {
                        "t": "append-rsp",
                        "term": self.persistent.term,
                        "success": False,
                        "log_end": self.log.next_position,
                    }
                )
            if prev_position >= self.log.base_position and (
                self.log.term_at(prev_position) != prev_term
            ):
                # conflicting suffix: truncate it (uncommitted by definition)
                self.log.truncate(prev_position)
                self._rollback_config(prev_position)
                self._fail_pending_from(
                    prev_position, "suffix truncated by new leader"
                )
                return msgpack.pack(
                    {
                        "t": "append-rsp",
                        "term": self.persistent.term,
                        "success": False,
                        "log_end": self.log.next_position,
                    }
                )

        frames = msg.get("frames", b"") or b""
        offset = 0
        records = []
        while offset < len(frames):
            record, offset = codec.decode_record(frames, offset)
            records.append(record)
        appended = False
        for record in records:
            if record.position < self.log.next_position:
                existing = self.log.record_at(record.position)
                if existing is None or existing.raft_term == record.raft_term:
                    continue  # duplicate delivery (or compacted-away)
                self.log.truncate(record.position)
                self._rollback_config(record.position)
                self._fail_pending_from(
                    record.position, "suffix truncated by new leader"
                )
            if record.position != self.log.next_position:
                return msgpack.pack(
                    {
                        "t": "append-rsp",
                        "term": self.persistent.term,
                        "success": False,
                        "log_end": self.log.next_position,
                    }
                )
            self.log.append_replicated(record)
            self._maybe_apply_config(record)
            appended = True
        if appended:
            self.log.flush()  # durable before acking (commit-is-final)

        commit = int(msg.get("commit", -1))
        if commit > self.log.commit_position:
            self.log.set_commit_position(min(commit, self.log.next_position - 1))
            # a deposed leader's surviving pending appends resolve here:
            # the new leader replicated them before the election, so they
            # committed — acked-means-committed holds across the flap
            self._resolve_pending_commits()
        return msgpack.pack(
            {
                "t": "append-rsp",
                "term": self.persistent.term,
                "success": True,
                "match_position": self.log.next_position - 1,
            }
        )
