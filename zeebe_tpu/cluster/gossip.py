"""SWIM gossip: membership, failure detection, dissemination.

Reference parity: ``gossip/`` — probe loop (``PingController``), indirect
probes (``PingReqEventHandler``), suspicion with timeout
(``SuspicionController`` semantics via the suspicion multiplier), alive
refutation by incarnation ("gossip term") bump, join + periodic anti-entropy
sync (``JoinController``, ``SyncController``), piggybacked membership and
custom events with a retransmission budget (``DisseminationComponent``,
``GossipMath.gossipPeriodsToSpread``), and custom-event listeners (how the
broker broadcasts partition/leader info; ``GossipCustomEventEncoding``).

Re-design: messages are msgpack maps over the shared TCP transport — PING /
PING-REQ / SYNC are request/response (the response doubles as the ACK with
piggyback), no bespoke SBE schema. The probe loop runs on the actor
scheduler; all state mutation is single-writer on the gossip actor.

Wire messages (msgpack maps):
  {t: "ping",     from: id, events: [...]}                → {t: "ack", from, events}
  {t: "ping-req", from: id, target: id, events: [...]}    → {t: "ack", ...} | {t: "nack"}
  {t: "sync",     from: id, addr: [h,p], events: [...]}   → {t: "sync-rsp", members: [...], events}
Events piggybacked everywhere:
  {e: "alive"|"suspect"|"confirm"|"custom", id, term, addr?, type?, payload?, seq?}
"""

from __future__ import annotations

import dataclasses
import enum
import math
import random
from typing import Any, Callable, Dict, List, Optional, Tuple

from zeebe_tpu.protocol import msgpack
from zeebe_tpu.runtime.actors import Actor, ActorFuture, ActorScheduler
from zeebe_tpu.transport import ClientTransport, RemoteAddress, ServerTransport


class MemberStatus(enum.Enum):
    ALIVE = "alive"
    SUSPECT = "suspect"
    DEAD = "dead"


@dataclasses.dataclass
class Member:
    member_id: str
    address: RemoteAddress
    status: MemberStatus = MemberStatus.ALIVE
    gossip_term: int = 0  # SWIM incarnation number
    suspect_since_ms: int = -1


@dataclasses.dataclass
class GossipConfig:
    """Reference: GossipConfiguration + the [gossip] section of
    zeebe.cfg.toml (probe interval/timeout, suspicion multiplier, sync)."""

    probe_interval_ms: int = 250
    probe_timeout_ms: int = 500
    probe_indirect_nodes: int = 2
    probe_indirect_timeout_ms: int = 1000
    suspicion_multiplier: int = 5
    sync_interval_ms: int = 10_000
    retransmission_multiplier: int = 3

    def suspicion_timeout_ms(self, cluster_size: int) -> int:
        return (
            self.suspicion_multiplier
            * max(1, math.ceil(math.log2(max(cluster_size, 2))))
            * self.probe_interval_ms
        )

    def retransmission_budget(self, cluster_size: int) -> int:
        # reference GossipMath.gossipPeriodsToSpread
        return self.retransmission_multiplier * max(
            1, math.ceil(math.log2(max(cluster_size, 2)))
        )


@dataclasses.dataclass
class _QueuedEvent:
    payload: dict
    remaining: int  # retransmission budget


class Gossip(Actor):
    """One node's gossip endpoint."""

    def __init__(
        self,
        member_id: str,
        scheduler: ActorScheduler,
        config: Optional[GossipConfig] = None,
        host: str = "127.0.0.1",
        port: int = 0,
        rng: Optional[random.Random] = None,
    ):
        super().__init__(f"gossip-{member_id}")
        self.member_id = member_id
        self.config = config or GossipConfig()
        self.scheduler = scheduler
        self.rng = rng or random.Random(hash(member_id) & 0xFFFFFFFF)

        self.members: Dict[str, Member] = {}
        self._event_queue: List[_QueuedEvent] = []
        self._custom_seq = 0
        # highest custom-event seq seen per (sender id, type): dedup on relay
        self._custom_seen: Dict[Tuple[str, str], int] = {}
        self._custom_listeners: Dict[str, List[Callable[[str, Any], None]]] = {}
        self._membership_listeners: List[Callable[[Member], None]] = []
        self._probe_cursor = 0
        self._stopped = False

        self.server = ServerTransport(host=host, port=port, request_handler=self._on_request)
        self.client = ClientTransport(default_timeout_ms=self.config.probe_timeout_ms)
        self.self_member = Member(member_id, self.server.address)
        scheduler.submit_actor(self)  # zblint: disable=unobserved-actor-future (boot submit; start failures land in the scheduler failure ring)

    @property
    def address(self) -> RemoteAddress:
        return self.server.address

    # -- lifecycle ---------------------------------------------------------
    def on_actor_started(self) -> None:
        self.actor.run_at_fixed_rate(self.config.probe_interval_ms, self._probe_round)
        self.actor.run_at_fixed_rate(self.config.sync_interval_ms, self._sync_round)

    def close(self) -> None:
        self._stopped = True
        self.server.close()
        self.client.close()

    # -- public API --------------------------------------------------------
    def join(
        self, contact_points: List[RemoteAddress], max_rounds: int = 10
    ) -> ActorFuture:
        """Sync with the first reachable contact point; the whole list is
        retried with backoff before giving up (reference JoinController
        retries contact points on a timer)."""
        done = ActorFuture()

        def attempt(points: List[RemoteAddress], rounds_left: int):
            if not points:
                if rounds_left <= 0:
                    done.complete_exceptionally(
                        RuntimeError("no contact point reachable")
                    )
                    return
                self.actor.run_delayed(
                    self.config.probe_interval_ms,
                    lambda: attempt(list(contact_points), rounds_left - 1),
                )
                return
            addr, rest = points[0], points[1:]
            future = self.client.send_request(
                addr, self._encode_sync(), timeout_ms=self.config.probe_timeout_ms
            )

            def on_response(f: ActorFuture):
                if f._exception is not None:
                    self.actor.run(lambda: attempt(rest, rounds_left))
                    return
                self.actor.run(lambda: (self._apply_sync_response(f._value), done.complete()))

            future.on_complete(on_response)

        self.actor.run(lambda: attempt(list(contact_points), max_rounds))
        return done

    def leave(self) -> None:
        """Broadcast own death (graceful shutdown; reference Gossip.leave)."""

        def do_leave():
            self._enqueue_event(
                {
                    "e": "confirm",
                    "id": self.member_id,
                    "term": self.self_member.gossip_term,
                }
            )

        self.actor.run(do_leave)

    def publish_custom_event(self, event_type: str, payload: Any) -> None:
        """Disseminate an application event (reference publishEvent — the
        broker's topology broadcasts ride on this)."""

        def do_publish():
            self._custom_seq += 1
            event = {
                "e": "custom",
                "id": self.member_id,
                "type": event_type,
                "payload": payload,
                "seq": self._custom_seq,
            }
            self._custom_seen[(self.member_id, event_type)] = self._custom_seq
            self._enqueue_event(event)

        self.actor.run(do_publish)

    def on_custom_event(self, event_type: str, listener: Callable[[str, Any], None]) -> None:
        """listener(sender_id, payload); fires once per (sender, seq)."""
        self._custom_listeners.setdefault(event_type, []).append(listener)

    def on_membership_change(self, listener: Callable[[Member], None]) -> None:
        self._membership_listeners.append(listener)

    def alive_members(self) -> List[str]:
        out = [self.member_id]
        out += [m.member_id for m in self.members.values() if m.status == MemberStatus.ALIVE]
        return sorted(out)

    # -- wire encoding -----------------------------------------------------
    def _addr_list(self, addr: RemoteAddress) -> list:
        return [addr.host, addr.port]

    def _encode_msg(self, t: str, **fields) -> bytes:
        msg = {"t": t, "from": self.member_id, "events": self._drain_events()}
        msg.update(fields)
        return msgpack.pack(msg)

    def _encode_sync(self) -> bytes:
        return msgpack.pack(
            {
                "t": "sync",
                "from": self.member_id,
                "addr": self._addr_list(self.address),
                "events": [],
            }
        )

    # -- dissemination -----------------------------------------------------
    def _enqueue_event(self, payload: dict) -> None:
        budget = self.config.retransmission_budget(len(self.members) + 1)
        self._event_queue.append(_QueuedEvent(payload, budget))
        self._apply_event(payload, from_self=True)

    def _drain_events(self, limit: int = 16) -> List[dict]:
        """Piggyback up to ``limit`` queued events, decrementing budgets
        (reference DisseminationComponent.drainTo)."""
        out = []
        for qe in list(self._event_queue[:limit]):
            out.append(qe.payload)
            qe.remaining -= 1
            if qe.remaining <= 0:
                self._event_queue.remove(qe)
        return out

    # -- event application (membership state machine) ----------------------
    def _apply_events(self, events: List[dict]) -> None:
        for event in events or []:
            self._apply_event(event)

    def _apply_event(self, event: dict, from_self: bool = False) -> None:
        kind = event.get("e")
        member_id = event.get("id")
        if member_id is None:
            return
        if kind == "custom":
            self._apply_custom(event, from_self)
            return
        term = int(event.get("term", 0))
        if member_id == self.member_id:
            if kind in ("suspect", "confirm") and not from_self:
                # refute: bump incarnation, re-announce aliveness
                # (reference: alive-confirm on self suspicion)
                if term >= self.self_member.gossip_term:
                    self.self_member.gossip_term = term + 1
                    self._enqueue_event(
                        {
                            "e": "alive",
                            "id": self.member_id,
                            "term": self.self_member.gossip_term,
                            "addr": self._addr_list(self.address),
                        }
                    )
            return

        member = self.members.get(member_id)
        if kind == "alive":
            addr = event.get("addr")
            if member is None:
                if addr is None:
                    return
                member = Member(
                    member_id, RemoteAddress(addr[0], int(addr[1])), MemberStatus.ALIVE, term
                )
                self.members[member_id] = member
                self._relay(event)
                self._notify_membership(member)
            elif term > member.gossip_term or (
                term == member.gossip_term and member.status == MemberStatus.DEAD
            ):
                member.gossip_term = term
                changed = member.status != MemberStatus.ALIVE
                member.status = MemberStatus.ALIVE
                member.suspect_since_ms = -1
                self._relay(event)
                if changed:
                    self._notify_membership(member)
        elif kind == "suspect":
            if member is None or member.status == MemberStatus.DEAD:
                return
            if term >= member.gossip_term and member.status == MemberStatus.ALIVE:
                member.gossip_term = term
                member.status = MemberStatus.SUSPECT
                member.suspect_since_ms = self.scheduler.now_ms()
                self._relay(event)
                self._notify_membership(member)
        elif kind == "confirm":
            if member is None or member.status == MemberStatus.DEAD:
                return
            # a confirm is authoritative: only a LATER alive term refutes it
            member.status = MemberStatus.DEAD
            member.gossip_term = max(member.gossip_term, term)
            self._relay(event)
            self._notify_membership(member)

    def _apply_custom(self, event: dict, from_self: bool) -> None:
        sender = event["id"]
        if sender == self.member_id and not from_self:
            return
        key = (sender, event.get("type", ""))
        seq = int(event.get("seq", 0))
        if not from_self:
            if seq <= self._custom_seen.get(key, 0):
                return
            self._custom_seen[key] = seq
            self._relay(event)
        for listener in self._custom_listeners.get(event.get("type", ""), []):
            listener(sender, event.get("payload"))

    def _relay(self, event: dict) -> None:
        budget = self.config.retransmission_budget(len(self.members) + 1)
        self._event_queue.append(_QueuedEvent(dict(event), budget))

    def _notify_membership(self, member: Member) -> None:
        for listener in self._membership_listeners:
            listener(member)

    # -- probe loop (failure detection) ------------------------------------
    def _probe_targets(self) -> List[Member]:
        return [m for m in self.members.values() if m.status != MemberStatus.DEAD]

    def _probe_round(self) -> None:
        if self._stopped:
            return
        self._expire_suspects()
        targets = self._probe_targets()
        if not targets:
            return
        self._probe_cursor = (self._probe_cursor + 1) % len(targets)
        target = targets[self._probe_cursor]
        ping = self._encode_msg("ping")
        future = self.client.send_request(
            target.address, ping, timeout_ms=self.config.probe_timeout_ms
        )

        def on_ack(f: ActorFuture):
            if f._exception is None:
                self.actor.run(lambda: self._on_ack(target, f._value))
            else:
                self.actor.run(lambda: self._indirect_probe(target))

        future.on_complete(on_ack)

    def _on_ack(self, member: Member, payload: bytes) -> None:
        try:
            msg = msgpack.unpack(payload)
        except Exception:  # noqa: BLE001
            return
        self._apply_events(msg.get("events"))

    def _indirect_probe(self, target: Member) -> None:
        """Reference PingReqEventHandler: ask k peers to probe on our
        behalf before suspecting."""
        if self._stopped or target.status == MemberStatus.DEAD:
            return
        peers = [m for m in self._probe_targets() if m.member_id != target.member_id]
        self.rng.shuffle(peers)
        peers = peers[: self.config.probe_indirect_nodes]
        if not peers:
            self._suspect(target)
            return
        pending = [len(peers)]
        confirmed = [False]

        def on_result(f: ActorFuture):
            def apply():
                pending[0] -= 1
                ok = False
                if f._exception is None:
                    try:
                        ok = msgpack.unpack(f._value).get("t") == "ack"
                    except Exception:  # noqa: BLE001
                        ok = False
                if ok:
                    confirmed[0] = True
                if pending[0] == 0 and not confirmed[0]:
                    self._suspect(target)

            self.actor.run(apply)

        request = self._encode_msg("ping-req", target=target.member_id)
        for peer in peers:
            self.client.send_request(
                peer.address, request, timeout_ms=self.config.probe_indirect_timeout_ms
            ).on_complete(on_result)

    def _suspect(self, member: Member) -> None:
        if member.status != MemberStatus.ALIVE:
            return
        self._apply_event(
            {"e": "suspect", "id": member.member_id, "term": member.gossip_term}
        )

    def _expire_suspects(self) -> None:
        timeout = self.config.suspicion_timeout_ms(len(self.members) + 1)
        now = self.scheduler.now_ms()
        for member in list(self.members.values()):
            if (
                member.status == MemberStatus.SUSPECT
                and now - member.suspect_since_ms >= timeout
            ):
                self._apply_event(
                    {"e": "confirm", "id": member.member_id, "term": member.gossip_term}
                )

    # -- sync (anti-entropy) ----------------------------------------------
    def _sync_round(self) -> None:
        if self._stopped:
            return
        targets = self._probe_targets()
        if not targets:
            return
        target = self.rng.choice(targets)
        future = self.client.send_request(
            target.address, self._encode_sync(), timeout_ms=self.config.probe_timeout_ms
        )

        def on_response(f: ActorFuture):
            if f._exception is None:
                self.actor.run(lambda: self._apply_sync_response(f._value))

        future.on_complete(on_response)

    def _member_snapshot(self) -> List[dict]:
        out = [
            {
                "id": self.member_id,
                "term": self.self_member.gossip_term,
                "status": MemberStatus.ALIVE.value,
                "addr": self._addr_list(self.address),
            }
        ]
        for m in self.members.values():
            out.append(
                {
                    "id": m.member_id,
                    "term": m.gossip_term,
                    "status": m.status.value,
                    "addr": self._addr_list(m.address),
                }
            )
        return out

    def _apply_sync_response(self, payload: bytes) -> None:
        try:
            msg = msgpack.unpack(payload)
        except Exception:  # noqa: BLE001
            return
        for entry in msg.get("members", []):
            status = entry.get("status")
            event = {
                "e": "alive" if status == "alive" else ("suspect" if status == "suspect" else "confirm"),
                "id": entry["id"],
                "term": int(entry.get("term", 0)),
                "addr": entry.get("addr"),
            }
            self._apply_event(event)
        self._apply_events(msg.get("events"))

    # -- request handling (IO thread: decode only, then hop to the actor;
    # responses are async futures so the IO loop never blocks) -------------
    def _on_request(self, payload: bytes):
        try:
            msg = msgpack.unpack(payload)
        except Exception:  # noqa: BLE001
            return None
        t = msg.get("t")
        if t == "ping":
            return self.actor.call(lambda: self._handle_ping(msg))
        if t == "ping-req":
            result = ActorFuture()
            self.actor.run(lambda: self._handle_ping_req(msg, result))
            return result
        if t == "sync":
            return self.actor.call(lambda: self._handle_sync(msg))
        return None

    def _handle_ping(self, msg: dict) -> bytes:
        self._apply_events(msg.get("events"))
        return self._encode_msg("ack")

    def _handle_ping_req(self, msg: dict, result: ActorFuture) -> None:
        """Probe ``target`` on behalf of the requester (reference
        PingReqEventHandler); runs on the gossip actor, completes the
        response future when the relayed probe answers."""
        self._apply_events(msg.get("events"))
        target = self.members.get(msg.get("target"))
        if target is None:
            result.complete(msgpack.pack({"t": "nack", "from": self.member_id}))
            return
        relay = self.client.send_request(
            target.address, self._encode_msg("ping"),
            timeout_ms=self.config.probe_timeout_ms,
        )

        def on_relay(f: ActorFuture):
            def apply():
                if f._exception is not None:
                    result.complete(
                        msgpack.pack({"t": "nack", "from": self.member_id})
                    )
                    return
                self._on_ack(target, f._value)
                result.complete(self._encode_msg("ack"))

            self.actor.run(apply)

        relay.on_complete(on_relay)

    def _handle_sync(self, msg: dict) -> bytes:
        self._apply_events(msg.get("events"))
        addr = msg.get("addr")
        sender = msg.get("from")
        if sender and addr and sender != self.member_id:
            self._apply_event(
                {"e": "alive", "id": sender, "term": 0, "addr": addr}
            )
        return msgpack.pack(
            {
                "t": "sync-rsp",
                "from": self.member_id,
                "members": self._member_snapshot(),
                "events": self._drain_events(),
            }
        )
