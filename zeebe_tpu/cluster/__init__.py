from zeebe_tpu.cluster.gossip import Gossip, GossipConfig, Member, MemberStatus
from zeebe_tpu.cluster.raft import Raft, RaftConfig, RaftState

__all__ = [
    "Gossip",
    "GossipConfig",
    "Member",
    "MemberStatus",
    "Raft",
    "RaftConfig",
    "RaftState",
]
