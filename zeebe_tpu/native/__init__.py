"""ctypes bindings for the native runtime layer (native/*.cc).

Components (reference parity cited in the .cc files):
- ``RingBuffer`` — many-producer claim/commit ring buffer (the dispatcher,
  ``dispatcher/.../Dispatcher.java``).
- ``NativeLogStorage`` — segmented append-only storage, on-disk compatible
  with the Python backend (``FsLogStorage``).
- ``frame_scan`` / ``crc32`` — recovery-path frame validation.
- ``KvStore`` — keyed cold-state store with checkpoint/restore (zb-map +
  RocksDB ``StateController`` analogue).

The shared library is built on demand with ``g++`` (no pip deps); call
``available()`` to gate features on the toolchain being present.
"""

from __future__ import annotations

import ctypes
import os
import subprocess
import threading
from typing import List, Optional, Tuple

_LIB_PATH = os.path.join(os.path.dirname(__file__), "libzbtpu.so")
_SRC_DIR = os.path.join(os.path.dirname(__file__), "..", "..", "native")
_lock = threading.Lock()
_lib: Optional[ctypes.CDLL] = None
_build_error: Optional[str] = None


def _build() -> Optional[str]:
    """Build libzbtpu.so from native/. Returns an error string or None."""
    try:
        proc = subprocess.run(
            ["make", "-C", os.path.abspath(_SRC_DIR)],
            capture_output=True,
            text=True,
            timeout=120,
        )
    except (OSError, subprocess.TimeoutExpired) as e:
        return f"native build failed to run: {e}"
    if proc.returncode != 0:
        return f"native build failed:\n{proc.stdout}\n{proc.stderr}"
    return None


def _load() -> Optional[ctypes.CDLL]:
    global _lib, _build_error
    with _lock:
        if _lib is not None:
            return _lib
        if _build_error is not None:
            return None
        src_newer = False
        if os.path.exists(_LIB_PATH) and os.path.isdir(_SRC_DIR):
            lib_mtime = os.path.getmtime(_LIB_PATH)
            src_newer = any(
                os.path.getmtime(os.path.join(_SRC_DIR, f)) > lib_mtime
                for f in os.listdir(_SRC_DIR)
                if f.endswith((".cc", ".h"))
            )
        if not os.path.exists(_LIB_PATH) or src_newer:
            _build_error = _build()
            if _build_error is not None:
                return None
        try:
            lib = ctypes.CDLL(_LIB_PATH)
        except OSError:
            # stale or wrong-arch binary (e.g. left over from another
            # machine): force a full rebuild — the binary must go first,
            # else make's mtime check would skip compiling it again
            try:
                os.remove(_LIB_PATH)
            except OSError:
                pass
            _build_error = _build()
            if _build_error is not None:
                return None
            try:
                lib = ctypes.CDLL(_LIB_PATH)
            except OSError as e:
                _build_error = f"built library failed to load: {e}"
                return None
        _bind(lib)
        _lib = lib
        return _lib


def _bind(lib: ctypes.CDLL) -> None:
    c = ctypes
    lib.rb_create.restype = c.c_void_p
    lib.rb_create.argtypes = [c.c_int64]
    lib.rb_destroy.argtypes = [c.c_void_p]
    lib.rb_capacity.restype = c.c_int64
    lib.rb_capacity.argtypes = [c.c_void_p]
    lib.rb_claim.restype = c.c_int64
    lib.rb_claim.argtypes = [c.c_void_p, c.c_int32]
    lib.rb_buffer_ptr.restype = c.POINTER(c.c_uint8)
    lib.rb_buffer_ptr.argtypes = [c.c_void_p, c.c_int64]
    lib.rb_commit.argtypes = [c.c_void_p, c.c_int64]
    lib.rb_abort.argtypes = [c.c_void_p, c.c_int64]
    lib.rb_peek.restype = c.c_int32
    lib.rb_peek.argtypes = [c.c_void_p, c.POINTER(c.c_int64)]
    lib.rb_consume.argtypes = [c.c_void_p, c.c_int64, c.c_int32]
    lib.rb_offer.restype = c.c_int64
    lib.rb_offer.argtypes = [c.c_void_p, c.c_char_p, c.c_int32]
    lib.rb_poll.restype = c.c_int32
    lib.rb_poll.argtypes = [c.c_void_p, c.c_char_p, c.c_int32]

    lib.ls_open.restype = c.c_void_p
    lib.ls_open.argtypes = [c.c_char_p, c.c_int64]
    lib.ls_close.argtypes = [c.c_void_p]
    lib.ls_append.restype = c.c_int64
    lib.ls_append.argtypes = [c.c_void_p, c.c_char_p, c.c_int64]
    lib.ls_flush.restype = c.c_int
    lib.ls_flush.argtypes = [c.c_void_p]
    lib.ls_read.restype = c.c_int64
    lib.ls_read.argtypes = [c.c_void_p, c.c_int64, c.c_char_p, c.c_int64]
    lib.ls_segment_count.restype = c.c_int32
    lib.ls_segment_count.argtypes = [c.c_void_p]
    lib.ls_segment_id.restype = c.c_int32
    lib.ls_segment_id.argtypes = [c.c_void_p, c.c_int32]
    lib.ls_segment_data_size.restype = c.c_int64
    lib.ls_segment_data_size.argtypes = [c.c_void_p, c.c_int32]
    lib.ls_first_address.restype = c.c_int64
    lib.ls_first_address.argtypes = [c.c_void_p]
    lib.ls_truncate.restype = c.c_int
    lib.ls_truncate.argtypes = [c.c_void_p, c.c_int64]
    lib.ls_delete_before.restype = c.c_int32
    lib.ls_delete_before.argtypes = [c.c_void_p, c.c_int32]
    lib.ls_reset.restype = c.c_int
    lib.ls_reset.argtypes = [c.c_void_p]

    lib.frame_scan.restype = c.c_int64
    lib.frame_scan.argtypes = [
        c.c_char_p, c.c_int64, c.POINTER(c.c_int64), c.c_int64,
        c.POINTER(c.c_int64),
    ]
    lib.zb_crc32.restype = c.c_uint32
    lib.zb_crc32.argtypes = [c.c_char_p, c.c_int64, c.c_uint32]

    lib.kv_create.restype = c.c_void_p
    lib.kv_destroy.argtypes = [c.c_void_p]
    lib.kv_put.restype = c.c_int
    lib.kv_put.argtypes = [c.c_void_p, c.c_char_p, c.c_int64, c.c_char_p, c.c_int64]
    lib.kv_get.restype = c.POINTER(c.c_uint8)
    lib.kv_get.argtypes = [c.c_void_p, c.c_char_p, c.c_int64, c.POINTER(c.c_int64)]
    lib.kv_del.restype = c.c_int
    lib.kv_del.argtypes = [c.c_void_p, c.c_char_p, c.c_int64]
    lib.kv_count.restype = c.c_int64
    lib.kv_count.argtypes = [c.c_void_p]
    lib.kv_iter_next.restype = c.c_int64
    lib.kv_iter_next.argtypes = [
        c.c_void_p, c.POINTER(c.c_int64), c.POINTER(c.POINTER(c.c_uint8)),
        c.POINTER(c.c_int64), c.POINTER(c.POINTER(c.c_uint8)),
    ]
    lib.kv_checkpoint.restype = c.c_int
    lib.kv_checkpoint.argtypes = [c.c_void_p, c.c_char_p]
    lib.kv_restore.restype = c.c_void_p
    lib.kv_restore.argtypes = [c.c_char_p]


def available() -> bool:
    return _load() is not None


def build_error() -> Optional[str]:
    _load()
    return _build_error


class RingBuffer:
    """Dispatcher-equivalent claim/commit ring buffer (many producers, one
    consumer)."""

    def __init__(self, capacity: int = 1 << 20):
        lib = _load()
        if lib is None:
            raise RuntimeError(f"native layer unavailable: {_build_error}")
        self._lib = lib
        self._h = lib.rb_create(capacity)
        if not self._h:
            raise ValueError("capacity must be a power of two >= 64")

    @property
    def capacity(self) -> int:
        return self._lib.rb_capacity(self._h)

    def offer(self, data: bytes) -> bool:
        """Publish one fragment; False on backpressure (ring full)."""
        result = self._lib.rb_offer(self._h, data, len(data))
        if result == -2:
            raise ValueError("fragment too large for ring")
        return result >= 0

    def poll(self) -> Optional[bytes]:
        """Consume one fragment; None when empty. Payloads are contiguous in
        the ring (claims never wrap — padding frames fill the tail), so the
        copy-out reads the exact committed length."""
        pos = ctypes.c_int64(0)
        n = self._lib.rb_peek(self._h, ctypes.byref(pos))
        if n == 0:
            return None
        data = ctypes.string_at(self._lib.rb_buffer_ptr(self._h, pos.value), n)
        self._lib.rb_consume(self._h, pos.value, n)
        return data

    def drain(self) -> List[bytes]:
        out = []
        while (item := self.poll()) is not None:
            out.append(item)
        return out

    def close(self) -> None:
        if self._h:
            self._lib.rb_destroy(self._h)
            self._h = None

    def __del__(self):
        try:
            self.close()
        except Exception:
            pass


class NativeLogStorage:
    """C++ segmented log storage — drop-in for
    ``zeebe_tpu.log.storage.SegmentedLogStorage`` (same disk format)."""

    SEGMENT_HEADER_SIZE = 16

    def __init__(self, directory: str, segment_size: int = 64 * 1024 * 1024):
        lib = _load()
        if lib is None:
            raise RuntimeError(f"native layer unavailable: {_build_error}")
        self._lib = lib
        self.directory = directory
        self.segment_size = segment_size
        os.makedirs(directory, exist_ok=True)
        self._h = lib.ls_open(directory.encode(), segment_size)
        if not self._h:
            raise OSError(f"cannot open log storage at {directory}")

    # address packing (same as the Python backend)
    @staticmethod
    def address(segment_id: int, offset: int) -> int:
        return (segment_id << 32) | offset

    @staticmethod
    def segment_of(address: int) -> int:
        return address >> 32

    @staticmethod
    def offset_of(address: int) -> int:
        return address & 0xFFFFFFFF

    def append(self, block) -> int:
        if not isinstance(block, bytes):
            # the batch codec hands the wave's single bytearray straight
            # through; the ctypes signature wants an immutable buffer
            block = bytes(block)
        addr = self._lib.ls_append(self._h, block, len(block))
        if addr < 0:
            raise OSError("append failed")
        return addr

    def flush(self) -> None:
        self._lib.ls_flush(self._h)

    def read(self, address: int, length: int) -> bytes:
        buf = ctypes.create_string_buffer(length)
        n = self._lib.ls_read(self._h, address, buf, length)
        if n < 0:
            raise OSError(f"read failed at {address:#x}")
        return buf.raw[:n]

    def read_segment(self, segment_id: int) -> bytes:
        size = self._lib.ls_segment_data_size(self._h, segment_id)
        if size < 0:
            raise OSError(f"no segment {segment_id}")
        return self.read(self.address(segment_id, self.SEGMENT_HEADER_SIZE), size)

    def iter_blocks(self):
        for i in range(self._lib.ls_segment_count(self._h)):
            sid = self._lib.ls_segment_id(self._h, i)
            data = self.read_segment(sid)
            yield self.address(sid, self.SEGMENT_HEADER_SIZE), data

    def first_address(self) -> Optional[int]:
        addr = self._lib.ls_first_address(self._h)
        return None if addr < 0 else addr

    @property
    def _segments(self) -> List[int]:
        """Sorted live segment ids (same bookkeeping view as the Python
        backend exposes; tests and compaction assertions read it)."""
        return [
            self._lib.ls_segment_id(self._h, i)
            for i in range(self._lib.ls_segment_count(self._h))
        ]

    def delete_segments_before(self, segment_id: int) -> int:
        return self._lib.ls_delete_before(self._h, segment_id)

    def reset(self) -> None:
        if self._lib.ls_reset(self._h) != 0:
            raise OSError("reset failed")

    def truncate(self, address: int) -> None:
        if self._lib.ls_truncate(self._h, address) != 0:
            raise OSError(f"truncate failed at {address:#x}")

    def close(self) -> None:
        if self._h:
            self._lib.ls_close(self._h)
            self._h = None

    def __del__(self):
        try:
            self.close()
        except Exception:
            pass


def frame_scan(data: bytes, max_frames: int = 1 << 20) -> Tuple[List[int], int]:
    """Validate frames in ``data``; returns (frame offsets, valid prefix
    length). Native recovery-scan fast path."""
    lib = _load()
    if lib is None:
        raise RuntimeError(f"native layer unavailable: {_build_error}")
    # frames are 8-aligned and >8 bytes, so a buffer holds < len/16 + 1
    cap = min(max_frames, len(data) // 16 + 1)
    offsets = (ctypes.c_int64 * cap)()
    valid_len = ctypes.c_int64(0)
    n = lib.frame_scan(data, len(data), offsets, cap, ctypes.byref(valid_len))
    return list(offsets[:n]), valid_len.value


def crc32(data: bytes, seed: int = 0) -> int:
    lib = _load()
    if lib is None:
        raise RuntimeError(f"native layer unavailable: {_build_error}")
    return lib.zb_crc32(data, len(data), seed)


class KvStore:
    """Keyed cold-state store with checkpoint/restore."""

    def __init__(self, _handle=None):
        lib = _load()
        if lib is None:
            raise RuntimeError(f"native layer unavailable: {_build_error}")
        self._lib = lib
        self._h = _handle if _handle is not None else lib.kv_create()

    def put(self, key: bytes, value: bytes) -> None:
        if self._lib.kv_put(self._h, key, len(key), value, len(value)) != 0:
            raise MemoryError("kv_put failed")

    def get(self, key: bytes) -> Optional[bytes]:
        vlen = ctypes.c_int64(0)
        ptr = self._lib.kv_get(self._h, key, len(key), ctypes.byref(vlen))
        if not ptr:
            return None
        return ctypes.string_at(ptr, vlen.value)

    def delete(self, key: bytes) -> bool:
        return bool(self._lib.kv_del(self._h, key, len(key)))

    def __len__(self) -> int:
        return self._lib.kv_count(self._h)

    def items(self) -> List[Tuple[bytes, bytes]]:
        cursor = ctypes.c_int64(0)
        key_ptr = ctypes.POINTER(ctypes.c_uint8)()
        klen = ctypes.c_int64(0)
        val_ptr = ctypes.POINTER(ctypes.c_uint8)()
        out = []
        while True:
            vlen = self._lib.kv_iter_next(
                self._h, ctypes.byref(cursor), ctypes.byref(key_ptr),
                ctypes.byref(klen), ctypes.byref(val_ptr),
            )
            if vlen < 0:
                break
            out.append(
                (ctypes.string_at(key_ptr, klen.value), ctypes.string_at(val_ptr, vlen))
            )
        return out

    def checkpoint(self, path: str) -> None:
        if self._lib.kv_checkpoint(self._h, path.encode()) != 0:
            raise OSError(f"checkpoint to {path} failed")

    @classmethod
    def restore(cls, path: str) -> "KvStore":
        lib = _load()
        if lib is None:
            raise RuntimeError(f"native layer unavailable: {_build_error}")
        h = lib.kv_restore(path.encode())
        if not h:
            raise OSError(f"restore from {path} failed (missing or corrupt)")
        return cls(_handle=h)

    def close(self) -> None:
        if self._h:
            self._lib.kv_destroy(self._h)
            self._h = None

    def __del__(self):
        try:
            self.close()
        except Exception:
            pass
