"""Compiled workflow graphs: ExecutableWorkflow set → device tensor tables.

This is the deploy-time "BPMN compiler" of the TPU engine. The reference
binds a per-element map lifecycle-state → BpmnStep at transform time
(``broker-core/.../workflow/model/ExecutableFlowElement.java:44``,
``ServiceTaskHandler.java:65-67``); here that binding becomes a dense
``step_table[workflow, element, intent]`` tensor the kernel gathers from,
plus flat adjacency/attribute tables:

- sequence-flow targets, first-outgoing-flow, container start events
- parallel-gateway fan-out lists (fork) and incoming arity/positions (join)
- exclusive-gateway conditioned-flow lists + compiled predicate programs
- job type/retries, payload io-mappings as column moves, timer durations

Workflows whose features the device cannot execute (nested payload paths,
messages in round 1, …) raise DeviceIneligible — the partition falls back
to the host oracle engine for them.
"""

from __future__ import annotations

import dataclasses
from functools import partial
from typing import Dict, List, Optional, Sequence, Tuple

import jax
import jax.numpy as jnp

from zeebe_tpu.models.bpmn.model import ElementType, Mapping, OutputBehavior
from zeebe_tpu.models.el.ast import compile_json_path
from zeebe_tpu.models.transform.executable import (
    ExecutableFlowElement,
    ExecutableWorkflow,
)
from zeebe_tpu.models.transform.steps import BpmnStep
from zeebe_tpu.protocol.intents import WorkflowInstanceIntent as WI
from zeebe_tpu.tpu.conditions import DeviceIneligible, ProgramPool
from zeebe_tpu.tpu.intern import InternTable

NUM_WI_INTENTS = 17  # includes the BOUNDARY_EVENT_OCCURRED extension

_DEVICE_ELEMENT_TYPES = {
    ElementType.PROCESS,
    ElementType.START_EVENT,
    ElementType.END_EVENT,
    ElementType.SERVICE_TASK,
    ElementType.EXCLUSIVE_GATEWAY,
    ElementType.PARALLEL_GATEWAY,
    ElementType.SEQUENCE_FLOW,
    ElementType.SUB_PROCESS,
    ElementType.INTERMEDIATE_CATCH_EVENT,  # timer + message catch
    ElementType.RECEIVE_TASK,              # message catch (round 4)
    ElementType.BOUNDARY_EVENT,            # on tasks (round 4)
}

# device multi-instance: cardinality-based fan-out through the emission
# slots; larger cardinalities (or collection-driven MI — collections have
# no columnar form) run on the host oracle
MAX_DEVICE_MI_CARDINALITY = 16


def _device_boundary_reason(el) -> Optional[str]:
    """None when el's attached boundary events can run on device."""
    from zeebe_tpu.models.bpmn.model import ElementType as ET

    if not el.boundary_events:
        return None
    if el.element_type not in (ET.SERVICE_TASK, ET.RECEIVE_TASK):
        return (
            f"boundary events on {el.element_type.name} ({el.id}) — "
            "host-only (contained-instance termination)"
        )
    return None


def _device_mi_reason(el) -> Optional[str]:
    """None when el's multi-instance shape can run on device."""
    if not el.is_multi_instance:
        return None
    # mi_output_element has a default value; it only matters when an
    # output collection is actually collected
    if el.mi_input_collection or el.mi_output_collection:
        return (
            f"collection-driven multi-instance ({el.id}) — host-only "
            "(collections have no device column form)"
        )
    card = el.mi_cardinality or 0
    if not (0 < card <= MAX_DEVICE_MI_CARDINALITY):
        return (
            f"multi-instance cardinality {card} ({el.id}) exceeds the "
            f"device fan-out budget ({MAX_DEVICE_MI_CARDINALITY})"
        )
    return None


class VarSpace:
    """Payload variable name → device column."""

    def __init__(self, names: Sequence[str] = ()):
        self._cols: Dict[str, int] = {}
        for name in names:
            self.column(name)

    def column(self, name: str) -> int:
        col = self._cols.get(name)
        if col is None:
            col = len(self._cols)
            self._cols[name] = col
        return col

    def lookup(self, name: str) -> Optional[int]:
        return self._cols.get(name)

    @property
    def names(self) -> List[str]:
        return list(self._cols)

    def __len__(self) -> int:
        return len(self._cols)


# elem_meta column layout (keep in sync with the stack in compile_graph)
(EM_TYPE, EM_FIRST_OUT, EM_FLOW_TGT, EM_START_EV, EM_OUT_COUNT,
 EM_DEFAULT_FLOW, EM_JOIN_NIN, EM_JOIN_POS, EM_JOB_TYPE, EM_JOB_RETRIES,
 EM_OUT_BEHAVIOR, EM_MSG_NAME, EM_CORR_VAR, EM_BD_COUNT, EM_MI_CARD,
 EM_IN_MAP_N, EM_IN_ROOT, EM_OUT_MAP_N, EM_OUT_ROOT) = range(19)

_DATA = [
    "step_table", "elem_type", "first_out_flow", "flow_target", "start_event",
    "elem_meta",
    "out_flows", "out_count", "cond_flows", "cond_prog", "default_flow",
    "join_nin", "join_pos", "job_type", "job_retries",
    "in_map_src", "in_map_dst", "in_map_n", "in_root",
    "out_map_src", "out_map_dst", "out_map_n", "out_root", "out_behavior",
    "timer_dur", "msg_name", "corr_var",
    "bd_elem", "bd_timer", "bd_msg", "bd_corr", "bd_interrupt", "bd_count",
    "bd_is_boundary", "bd_host_interrupt", "mi_cardinality",
    "progs", "lit_nums",
]


@partial(
    jax.tree_util.register_dataclass,
    data_fields=_DATA,
    meta_fields=["num_vars", "emit_width", "max_join_in", "has_conditions",
                 "has_parallel_joins", "has_timers", "has_mappings",
                 "has_messages", "has_boundaries", "has_multi_instance",
                 "mi_loop_var"],
)
@dataclasses.dataclass
class DeviceGraph:
    # all [W, E] i32 unless noted
    step_table: jax.Array  # [W, E, NUM_WI_INTENTS]
    elem_type: jax.Array
    # the hot-path per-element scalars packed into ONE [W, E, EM_COLS]
    # table, so phase B/C reads are a single [B, EM_COLS] row gather
    # instead of a dozen [B] gathers (the per-gather cost is fixed-ish,
    # dominated by per-index issue, not bytes)
    elem_meta: jax.Array
    first_out_flow: jax.Array        # outgoing[0] element idx, -1 none
    flow_target: jax.Array           # sequence flow → target element idx
    start_event: jax.Array           # container → its start event idx
    out_flows: jax.Array             # [W, E, F] parallel fork fan-out, -1 pad
    out_count: jax.Array
    cond_flows: jax.Array            # [W, E, F] conditioned flows, in order
    cond_prog: jax.Array             # [W, E, F] program ids, -1 pad
    default_flow: jax.Array
    join_nin: jax.Array              # gateway: len(incoming)
    join_pos: jax.Array              # flow: its index in target.incoming
    job_type: jax.Array              # interned job type id
    job_retries: jax.Array
    in_map_src: jax.Array            # [W, E, K] source var column, -1 pad
    in_map_dst: jax.Array            # [W, E, K] target var column
    in_map_n: jax.Array
    in_root: jax.Array               # bool: lone "$→$" mapping
    out_map_src: jax.Array
    out_map_dst: jax.Array
    out_map_n: jax.Array
    out_root: jax.Array
    out_behavior: jax.Array
    timer_dur: jax.Array             # i64, -1 = no timer
    msg_name: jax.Array              # interned message name, 0 = none
    corr_var: jax.Array              # correlation-key payload column, -1 none
    # boundary events attached per host element (round 4: device-served
    # for tasks; reference model BoundaryEvent.java — the reference engine
    # never executes it)
    bd_elem: jax.Array               # [W, E, BD] boundary element idx, -1 pad
    bd_timer: jax.Array              # [W, E, BD] i64 duration, -1 = message
    bd_msg: jax.Array                # [W, E, BD] interned message name
    bd_corr: jax.Array               # [W, E, BD] correlation payload column
    bd_interrupt: jax.Array          # [W, E, BD] bool
    bd_count: jax.Array              # [W, E]
    bd_is_boundary: jax.Array        # [W, E] bool: element IS a boundary event
    bd_host_interrupt: jax.Array     # [W, E] bool: boundary elem interrupts
    mi_cardinality: jax.Array        # [W, E] i32, 0 = not multi-instance
    progs: jax.Array                 # [P, L, 6] predicate programs
    lit_nums: jax.Array              # [Q] f32
    # static meta
    num_vars: int
    emit_width: int                  # max emissions per record (≥2)
    max_join_in: int
    # deploy-time kernel specialization: features absent from the whole
    # deployed set are compiled out of the step entirely (the reference
    # binds steps per element at transform time — ServiceTaskHandler:65 —
    # the batched analogue specializes the fused program)
    has_conditions: bool = True
    has_parallel_joins: bool = True
    has_timers: bool = True
    has_mappings: bool = True
    has_messages: bool = False
    has_boundaries: bool = False
    has_multi_instance: bool = False
    mi_loop_var: int = -1  # payload column of loopCounter, -1 when no MI


@dataclasses.dataclass
class GraphMeta:
    """Host-side companions of a DeviceGraph."""

    workflows: List[ExecutableWorkflow]
    slot_by_key: Dict[int, int]
    interns: InternTable
    varspace: VarSpace
    # per workflow slot: element idx → id and id → idx
    elem_ids: List[List[str]]
    elem_idx: List[Dict[str, int]]

    def slot(self, workflow_key: int) -> int:
        return self.slot_by_key.get(workflow_key, -1)

    def element_id(self, wf_slot: int, elem: int) -> str:
        if 0 <= wf_slot < len(self.elem_ids) and 0 <= elem < len(self.elem_ids[wf_slot]):
            return self.elem_ids[wf_slot][elem]
        return ""


def _flat_var(varspace: VarSpace, path: str, what: str) -> int:
    steps = compile_json_path(path)
    if len(steps) != 1 or not isinstance(steps[0], str):
        raise DeviceIneligible(f"non-flat JSONPath in {what}: {path}")
    return varspace.column(steps[0])


def _compile_mappings(
    varspace: VarSpace, mappings: List[Mapping], what: str
) -> Tuple[List[int], List[int], bool]:
    if len(mappings) == 1 and mappings[0].source == "$" and mappings[0].target == "$":
        return [], [], True
    srcs, dsts = [], []
    for m in mappings:
        if m.source == "$" or m.target == "$":
            raise DeviceIneligible(f"root mapping mixed with others in {what}")
        srcs.append(_flat_var(varspace, m.source, what))
        dsts.append(_flat_var(varspace, m.target, what))
    return srcs, dsts, False


def check_device_compatible(workflow: ExecutableWorkflow) -> Optional[str]:
    """None when the workflow can run on device; else the reason."""
    varspace, interns = VarSpace(), InternTable()
    pool = ProgramPool(varspace=varspace, interns=interns)
    try:
        for el in workflow.elements:
            if el.element_type not in _DEVICE_ELEMENT_TYPES:
                return f"element type {el.element_type.name} ({el.id})"
            if el.message_name:
                # message catch runs on device (round 4); the correlation
                # key must be a flat payload variable (same contract as
                # io-mappings — nested documents never live in columns)
                _flat_var(
                    varspace, el.correlation_key_path,
                    f"correlation key of {el.id}",
                )
            reason = _device_mi_reason(el)
            if reason:
                return reason
            reason = _device_boundary_reason(el)
            if reason:
                return reason
            for boundary in el.boundary_events:
                if boundary.message_name:
                    _flat_var(
                        varspace, boundary.correlation_key_path,
                        f"correlation key of {boundary.id}",
                    )
            _compile_mappings(varspace, el.input_mappings, f"input mapping of {el.id}")
            _compile_mappings(varspace, el.output_mappings, f"output mapping of {el.id}")
            if el.condition is not None:
                pool.compile(el.condition)
    except DeviceIneligible as e:
        return str(e)
    return None


def compile_graph(
    workflows: List[ExecutableWorkflow],
    interns: Optional[InternTable] = None,
    extra_variables: Sequence[str] = (),
) -> Tuple[DeviceGraph, GraphMeta]:
    """Compile a deployed workflow set into one device graph.

    Recompiled on each deployment (deployments are rare and workflows small;
    the jit cache keys on shapes, which only change when tables grow).
    """
    interns = interns if interns is not None else InternTable()
    varspace = VarSpace(extra_variables)
    pool = ProgramPool(varspace=varspace, interns=interns)

    def _pad(n: int, mult: int) -> int:
        return ((max(n, 1) + mult - 1) // mult) * mult

    # Shapes are padded to coarse grid sizes so the step kernel's jit cache
    # is shared across deployments of similar size (a retrace happens only
    # when a table genuinely outgrows its padding).
    num_wf = _pad(len(workflows), 4)
    num_elems = _pad(max((len(w.elements) for w in workflows), default=1), 16)
    fan = 2
    join_in = 2
    num_maps = 2
    max_bd = 1
    for w in workflows:
        for el in w.elements:
            fan = max(fan, len(el.outgoing), len(el.outgoing_with_condition))
            if el.is_multi_instance and not _device_mi_reason(el):
                fan = max(fan, int(el.mi_cardinality or 0))
            join_in = max(join_in, len(el.incoming))
            num_maps = max(num_maps, len(el.input_mappings), len(el.output_mappings))
            max_bd = max(max_bd, len(el.boundary_events))

    shape = (num_wf, num_elems)
    import numpy as np

    step_table = np.zeros(shape + (NUM_WI_INTENTS,), np.int32)
    elem_type = np.zeros(shape, np.int32)
    first_out_flow = np.full(shape, -1, np.int32)
    flow_target = np.full(shape, -1, np.int32)
    start_event = np.full(shape, -1, np.int32)
    out_flows = np.full(shape + (fan,), -1, np.int32)
    out_count = np.zeros(shape, np.int32)
    cond_flows = np.full(shape + (fan,), -1, np.int32)
    cond_prog = np.full(shape + (fan,), -1, np.int32)
    default_flow = np.full(shape, -1, np.int32)
    join_nin = np.zeros(shape, np.int32)
    join_pos = np.full(shape, -1, np.int32)
    job_type = np.zeros(shape, np.int32)
    job_retries = np.zeros(shape, np.int32)
    in_map_src = np.full(shape + (num_maps,), -1, np.int32)
    in_map_dst = np.full(shape + (num_maps,), -1, np.int32)
    in_map_n = np.zeros(shape, np.int32)
    in_root = np.zeros(shape, bool)
    out_map_src = np.full(shape + (num_maps,), -1, np.int32)
    out_map_dst = np.full(shape + (num_maps,), -1, np.int32)
    out_map_n = np.zeros(shape, np.int32)
    out_root = np.zeros(shape, bool)
    out_behavior = np.zeros(shape, np.int32)
    timer_dur = np.full(shape, -1, np.int64)
    msg_name = np.zeros(shape, np.int32)
    corr_var = np.full(shape, -1, np.int32)
    bd_elem = np.full(shape + (max_bd,), -1, np.int32)
    bd_timer = np.full(shape + (max_bd,), -1, np.int64)
    bd_msg = np.zeros(shape + (max_bd,), np.int32)
    bd_corr = np.full(shape + (max_bd,), -1, np.int32)
    bd_interrupt = np.zeros(shape + (max_bd,), bool)
    bd_count = np.zeros(shape, np.int32)
    bd_is_boundary = np.zeros(shape, bool)
    bd_host_interrupt = np.zeros(shape, bool)
    mi_cardinality = np.zeros(shape, np.int32)

    slot_by_key: Dict[int, int] = {}
    elem_ids: List[List[str]] = []
    elem_idx: List[Dict[str, int]] = []

    for w, wf in enumerate(workflows):
        slot_by_key[wf.key] = w
        elem_ids.append([el.id for el in wf.elements])
        elem_idx.append({el.id: el.index for el in wf.elements})
        for el in wf.elements:
            e = el.index
            elem_type[w, e] = int(el.element_type)
            for intent, step in el.steps.items():
                step_table[w, e, int(intent)] = int(step)
            if el.outgoing:
                first_out_flow[w, e] = el.outgoing[0].index
                out_count[w, e] = len(el.outgoing)
                for i, f in enumerate(el.outgoing):
                    out_flows[w, e, i] = f.index
            if el.target is not None:
                flow_target[w, e] = el.target.index
                join_pos[w, e] = [f.index for f in el.target.incoming].index(e)
            if el.start_event is not None:
                start_event[w, e] = el.start_event.index
            if el.incoming:
                join_nin[w, e] = len(el.incoming)
            for i, f in enumerate(el.outgoing_with_condition):
                cond_flows[w, e, i] = f.index
                cond_prog[w, e, i] = pool.compile(f.condition)
            if el.default_flow is not None:
                default_flow[w, e] = el.default_flow.index
            if el.job_type:
                job_type[w, e] = interns.intern(el.job_type)
                job_retries[w, e] = el.job_retries
            srcs, dsts, root = _compile_mappings(
                varspace, el.input_mappings, f"input mapping of {el.id}"
            )
            in_map_n[w, e] = len(srcs)
            in_root[w, e] = root
            for i, (s, d) in enumerate(zip(srcs, dsts)):
                in_map_src[w, e, i] = s
                in_map_dst[w, e, i] = d
            srcs, dsts, root = _compile_mappings(
                varspace, el.output_mappings, f"output mapping of {el.id}"
            )
            out_map_n[w, e] = len(srcs)
            out_root[w, e] = root
            for i, (s, d) in enumerate(zip(srcs, dsts)):
                out_map_src[w, e, i] = s
                out_map_dst[w, e, i] = d
            out_behavior[w, e] = int(el.output_behavior)
            if el.timer_duration_ms is not None:
                timer_dur[w, e] = int(el.timer_duration_ms)
            if el.message_name:
                msg_name[w, e] = interns.intern(el.message_name)
                corr_var[w, e] = _flat_var(
                    varspace, el.correlation_key_path,
                    f"correlation key of {el.id}",
                )
            if el.element_type == ElementType.BOUNDARY_EVENT:
                bd_is_boundary[w, e] = True
                bd_host_interrupt[w, e] = bool(el.cancel_activity)
            bd_count[w, e] = len(el.boundary_events)
            for i, boundary in enumerate(el.boundary_events):
                bd_elem[w, e, i] = boundary.index
                if boundary.timer_duration_ms is not None:
                    bd_timer[w, e, i] = int(boundary.timer_duration_ms)
                if boundary.message_name:
                    bd_msg[w, e, i] = interns.intern(boundary.message_name)
                    bd_corr[w, e, i] = _flat_var(
                        varspace, boundary.correlation_key_path,
                        f"correlation key of {boundary.id}",
                    )
                bd_interrupt[w, e, i] = bool(boundary.cancel_activity)
            if el.is_multi_instance and not _device_mi_reason(el):
                mi_cardinality[w, e] = int(el.mi_cardinality or 0)
                varspace.column("loopCounter")

    progs, lit_nums = pool.tensors()
    emit_width = max(2, int(out_count.max()) if workflows else 2)
    if (msg_name > 0).any() or (bd_msg > 0).any():
        # a CORRELATE arrival emits CORRELATED + ELEMENT_COMPLETING + CLOSE
        emit_width = max(emit_width, 3)
    if (bd_count > 0).any():
        # rows on boundary-carrying elements mirror the oracle's written
        # order: arms/disarm-cancels (slots 0..BD-1), closes (BD..2BD-1),
        # the row's own step output (2BD), terminate-catch re-scan cancels
        # (2BD+1..3BD), TERMINATED (3BD+1)
        emit_width = max(emit_width, 3 * int(bd_count.max()) + 2)
    if (mi_cardinality > 0).any():
        # multi-instance fan-out rides the fork slots
        emit_width = max(emit_width, int(mi_cardinality.max()))

    import numpy as _np

    elem_meta = _np.stack(
        [_np.asarray(a, _np.int32) for a in (
            elem_type, first_out_flow, flow_target, start_event, out_count,
            default_flow, join_nin, join_pos, job_type, job_retries,
            out_behavior, msg_name, corr_var, bd_count, mi_cardinality,
            in_map_n, in_root, out_map_n, out_root,
        )], axis=-1,
    )
    graph = DeviceGraph(
        step_table=jnp.asarray(step_table),
        elem_type=jnp.asarray(elem_type),
        elem_meta=jnp.asarray(elem_meta),
        first_out_flow=jnp.asarray(first_out_flow),
        flow_target=jnp.asarray(flow_target),
        start_event=jnp.asarray(start_event),
        out_flows=jnp.asarray(out_flows),
        out_count=jnp.asarray(out_count),
        cond_flows=jnp.asarray(cond_flows),
        cond_prog=jnp.asarray(cond_prog),
        default_flow=jnp.asarray(default_flow),
        join_nin=jnp.asarray(join_nin),
        join_pos=jnp.asarray(join_pos),
        job_type=jnp.asarray(job_type),
        job_retries=jnp.asarray(job_retries),
        in_map_src=jnp.asarray(in_map_src),
        in_map_dst=jnp.asarray(in_map_dst),
        in_map_n=jnp.asarray(in_map_n),
        in_root=jnp.asarray(in_root),
        out_map_src=jnp.asarray(out_map_src),
        out_map_dst=jnp.asarray(out_map_dst),
        out_map_n=jnp.asarray(out_map_n),
        out_root=jnp.asarray(out_root),
        out_behavior=jnp.asarray(out_behavior),
        timer_dur=jnp.asarray(timer_dur),
        msg_name=jnp.asarray(msg_name),
        corr_var=jnp.asarray(corr_var),
        bd_elem=jnp.asarray(bd_elem),
        bd_timer=jnp.asarray(bd_timer),
        bd_msg=jnp.asarray(bd_msg),
        bd_corr=jnp.asarray(bd_corr),
        bd_interrupt=jnp.asarray(bd_interrupt),
        bd_count=jnp.asarray(bd_count),
        bd_is_boundary=jnp.asarray(bd_is_boundary),
        bd_host_interrupt=jnp.asarray(bd_host_interrupt),
        mi_cardinality=jnp.asarray(mi_cardinality),
        progs=progs,
        lit_nums=lit_nums,
        num_vars=max(len(varspace), 1),
        emit_width=emit_width,
        max_join_in=join_in,
        has_conditions=bool((cond_prog >= 0).any()),
        has_parallel_joins=bool((join_nin >= 2).any()),
        has_timers=bool((timer_dur >= 0).any() or (bd_timer >= 0).any()),
        has_mappings=bool(
            (in_map_n > 0).any() or (out_map_n > 0).any()
            or in_root.any() or out_root.any()
        ),
        has_messages=bool((msg_name > 0).any() or (bd_msg > 0).any()),
        has_boundaries=bool((bd_count > 0).any()),
        has_multi_instance=bool((mi_cardinality > 0).any()),
        mi_loop_var=(
            varspace.lookup("loopCounter") if (mi_cardinality > 0).any()
            else -1
        ),
    )
    meta = GraphMeta(
        workflows=list(workflows),
        slot_by_key=slot_by_key,
        interns=interns,
        varspace=varspace,
        elem_ids=elem_ids,
        elem_idx=elem_idx,
    )
    return graph, meta
