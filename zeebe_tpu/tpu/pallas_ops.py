"""Pallas TPU primitives for the step kernel's table operations.

XLA lowers general scatters/gathers and the hashmap probe loops to SERIAL
per-index programs on TPU (~70ns-1.4ms per op at wave 2^14 — see
PERF_NOTES.md); the whole round is a dependent chain of ~70 such ops, so
op count × batch dominates. These kernels replace each op family with one
serial pallas pass whose per-record cost is a handful of VPU/scalar-core
instructions (~1.5-5ns/record measured, benchmarks/pallas_probe.py):

- ``masked_row_update`` / ``masked_row_accum``: ``tbl[slot[i]] =
  where(lane_mask[i], vals[i], old)`` for active records, serial in batch
  order (= the XLA chain's last-writer-wins rank order).
- ``masked_lane_update`` / ``masked_lane_accum``: the 1D-table variant;
  the table is viewed as [T/128, 128] and the dynamic lane is modified by
  vector select (TPU has no scalar VMEM stores).
- ``lookup`` / ``insert`` / ``delete``: the hashmap ops
  (zeebe_tpu.tpu.hashmap semantics). Bucket LAYOUT may differ from the
  XLA path when colliding keys race (XLA claims are round-synchronous,
  this path is serial) — the key→slot mapping and probe invariants are
  identical, so tables from either path are interchangeable.

Addressing rules (load-bearing, measured):
- per-record control scalars (slots, flags, hashes, key halves) MUST live
  in SMEM — extracting a scalar from a VMEM vector costs ~300x;
- the batch is grid-chunked so each chunk's scalars fit SMEM;
- int64 never enters a kernel: i64 arrays are bitcast to (lo, hi) i32
  planes at the boundary (TPU i64 is emulated anyway).

Everything falls back to the XLA implementations off-TPU (tests run on
the CPU mesh; the TPU path is exercised by bench.py and the device parity
check in benchmarks/).
"""

from __future__ import annotations

import contextlib
import dataclasses
import functools
from typing import List, Optional, Sequence, Tuple

import jax
import jax.numpy as jnp
from jax import lax

from zeebe_tpu.tpu import hashmap
from zeebe_tpu.tpu.hashmap import EMPTY, HashTable, MAX_PROBES, TOMBSTONE

LANES = 128
# lane extraction = max(where(sel, row, INT32_MIN)): exact for every value
# (jnp.sum's 1D reduce does not lower under x64; max does), and the weak
# python literal adopts i32 from the row instead of promoting
_CHUNK = 2048  # records per grid step; scalars per chunk must fit SMEM

# ---------------------------------------------------------------------------
# per-family dispatch: pallas vs XLA is BUILD-dependent (PERF_NOTES round 4:
# libtpu builds with the serial per-index scatter lowering need the pallas
# passes, builds with the DMA-pipelined lowering are faster through plain
# XLA — and the winner flipped between builds). The engine-boot autotune
# (zeebe_tpu.tpu.autotune) measures both paths per op family on the actual
# build and writes the winners here; ZB_PALLAS=0/1 remains the manual
# override for A/B benchmarking.
# ---------------------------------------------------------------------------

FAMILIES = (
    "row_update", "row_max", "row_add", "lane", "vec64",
    "lookup", "insert", "delete", "fused", "gather", "emit",
)

# family -> use pallas?  Written once by autotune.set_dispatch; until then
# every family defaults to pallas-on-TPU (the pre-autotune behavior).
_DECISIONS: dict = {}
_FORCED: Optional[str] = None  # "pallas" | "xla" | None (autotune probes)


def set_dispatch(decisions: dict) -> None:
    """Install autotuned per-family decisions ({family: bool})."""
    _DECISIONS.clear()
    _DECISIONS.update({k: bool(v) for k, v in decisions.items()})


def get_dispatch() -> dict:
    return dict(_DECISIONS)


@contextlib.contextmanager
def forced(mode: Optional[str]):
    """Force every op onto one path regardless of env/autotune decisions
    (``"pallas"`` / ``"xla"``). Used by the autotune microbenches and the
    parity checks; traces taken inside the context bake the forced path
    into the compiled program."""
    global _FORCED
    prev = _FORCED
    _FORCED = mode
    try:
        yield
    finally:
        _FORCED = prev


def env_override() -> Optional[bool]:
    """The ``ZB_PALLAS`` manual override, or None when unset/unrecognized
    (one parser shared with the autotune, so an unrecognized value can
    never disable tuning while also failing to force a path)."""
    import os

    env = os.environ.get("ZB_PALLAS", "").strip().lower()
    if env in ("0", "false", "off", "no"):
        return False
    if env in ("1", "true", "on", "yes"):
        return True
    return None


def use_pallas(family: str = "row_update") -> bool:
    """Pallas path for this op family? Priority: forced() context >
    ZB_PALLAS env override > autotuned per-family decision > default
    (pallas on TPU). Always False off-TPU (Mosaic is TPU-only)."""
    if jax.default_backend() != "tpu":
        return False
    if _FORCED == "pallas":
        return True
    if _FORCED == "xla":
        return False
    env = env_override()
    if env is not None:
        return env
    return _DECISIONS.get(family, True)


def _use_pallas(family: str = "row_update") -> bool:
    return use_pallas(family)


def _chunk(b: int) -> int:
    c = min(b, _CHUNK)
    while b % c:
        c //= 2
    return max(c, 1)


def _pallas_call(kernel, grid, in_specs, out_specs, out_shape, aliases, vmem_mb=110):
    from jax.experimental import pallas as pl
    from jax.experimental.pallas import tpu as pltpu

    return pl.pallas_call(
        kernel,
        grid=grid,
        in_specs=in_specs,
        out_specs=out_specs,
        out_shape=out_shape,
        input_output_aliases=aliases,
        compiler_params=pltpu.CompilerParams(
            vmem_limit_bytes=vmem_mb * 1024 * 1024,
            dimension_semantics=("arbitrary",),
        ),
    )


def _smem_spec(c):
    from jax.experimental import pallas as pl
    from jax.experimental.pallas import tpu as pltpu

    return pl.BlockSpec((c,), lambda g: (g,), memory_space=pltpu.SMEM)


def _vmem_rows_spec(c, k):
    from jax.experimental import pallas as pl
    from jax.experimental.pallas import tpu as pltpu

    return pl.BlockSpec((c, k), lambda g: (g, jnp.int32(0)), memory_space=pltpu.VMEM)


def _vmem_full_spec(shape):
    from jax.experimental import pallas as pl
    from jax.experimental.pallas import tpu as pltpu

    return pl.BlockSpec(
        shape, lambda g: tuple(jnp.int32(0) for _ in shape),
        memory_space=pltpu.VMEM,
    )


# ---------------------------------------------------------------------------
# 2D-table row updates
# ---------------------------------------------------------------------------


def masked_row_update(
    table: jax.Array,  # [T, K] i32
    slots: jax.Array,  # [B] i32 (any value; inactive rows ignored)
    active: jax.Array,  # [B] bool
    vals: jax.Array,  # [B, K] i32
    lane_mask: Optional[jax.Array] = None,  # [B, K] bool; None = full row
) -> jax.Array:
    """Serial batch-order row writes: for i in range(B): if active[i]:
    row = table[slots[i]]; table[slots[i]] = where(lane_mask[i], vals[i], row).

    Equivalent to the XLA ``table.at[where(active, slots, T)].set(vals,
    mode="drop")`` chain (last writer in batch order wins)."""
    if not _use_pallas("row_update"):
        idx = jnp.where(active, slots, table.shape[0])
        if lane_mask is None:
            return table.at[idx].set(vals, mode="drop")
        # element-wise scatter: two active records may target DISJOINT
        # lanes of the same row (parallel-join arrivals) — a row-level
        # read-merge-write would drop one of them
        k = table.shape[1]
        rows = jnp.where(
            active[:, None] & lane_mask, slots[:, None], table.shape[0]
        )
        cols = jnp.broadcast_to(
            jnp.arange(k, dtype=jnp.int32)[None, :], lane_mask.shape
        )
        return table.at[rows, cols].set(vals, mode="drop")

    b = slots.shape[0]
    t, k = table.shape
    c = _chunk(b)
    blind = lane_mask is None
    if blind:
        lane_mask = jnp.ones((1, 1), jnp.int32)  # placeholder operand

    def kernel(slots_ref, active_ref, vals_ref, mask_ref, tbl_ref, out_ref):
        _init_out(out_ref, tbl_ref)

        def body(i, _):
            @functools.partial(_when, active_ref[i] != 0)
            def _():
                s = slots_ref[i]
                if blind:
                    out_ref[s, :] = vals_ref[i, :]
                else:
                    row = out_ref[s, :]
                    out_ref[s, :] = jnp.where(
                        mask_ref[i, :] != 0, vals_ref[i, :], row
                    )
            return jnp.int32(0)

        lax.fori_loop(jnp.int32(0), jnp.int32(c), body, jnp.int32(0))

    mask_spec = (
        _vmem_full_spec((1, 1)) if blind else _vmem_rows_spec(c, k)
    )
    return _pallas_call(
        kernel,
        grid=(b // c,),
        in_specs=[
            _smem_spec(c),
            _smem_spec(c),
            _vmem_rows_spec(c, k),
            mask_spec,
            _vmem_full_spec((t, k)),
        ],
        out_specs=_vmem_full_spec((t, k)),
        out_shape=jax.ShapeDtypeStruct((t, k), table.dtype),
        aliases={4: 0},
    )(
        slots.astype(jnp.int32),
        active.astype(jnp.int32),
        vals.astype(table.dtype),
        (lane_mask if blind else lane_mask.astype(jnp.int32)),
        table,
    )


def _when(cond, fn):
    from jax.experimental import pallas as pl

    return pl.when(cond)(fn)


def _init_out(out_ref, in_ref):
    """Copy the aliased input block into the output block on grid step 0.

    ``input_output_aliases`` donates the HBM buffer but does NOT guarantee
    the output VMEM window starts with the input's contents (observed on
    this jax/libtpu build: it reads back zeros). Every RMW kernel must
    seed its output window explicitly; the window then persists across
    grid steps (constant index_map + arbitrary semantics)."""
    from jax.experimental import pallas as pl

    @pl.when(pl.program_id(0) == 0)
    def _():
        out_ref[...] = in_ref[...]


def masked_row_max(
    table: jax.Array,  # [T, K] i32
    slots: jax.Array,  # [B] i32
    active: jax.Array,  # [B] bool
    vals: jax.Array,  # [B, K] i32
) -> jax.Array:
    """Serial ``table[slot[i]] = maximum(old, vals[i])`` for active records
    (the ``.at[slots].max(vals, mode="drop")`` analogue; max commutes, so
    batch order does not matter)."""
    if not _use_pallas("row_max"):
        idx = jnp.where(active, slots, table.shape[0])
        return table.at[idx].max(vals.astype(table.dtype), mode="drop")

    b = slots.shape[0]
    t, k = table.shape
    c = _chunk(b)

    def kernel(slots_ref, active_ref, vals_ref, tbl_ref, out_ref):
        _init_out(out_ref, tbl_ref)

        def body(i, _):
            @functools.partial(_when, active_ref[i] != 0)
            def _():
                s = slots_ref[i]
                row = out_ref[s, :]
                out_ref[s, :] = jnp.maximum(row, vals_ref[i, :])
            return jnp.int32(0)

        lax.fori_loop(jnp.int32(0), jnp.int32(c), body, jnp.int32(0))

    return _pallas_call(
        kernel,
        grid=(b // c,),
        in_specs=[
            _smem_spec(c),
            _smem_spec(c),
            _vmem_rows_spec(c, k),
            _vmem_full_spec((t, k)),
        ],
        out_specs=_vmem_full_spec((t, k)),
        out_shape=jax.ShapeDtypeStruct((t, k), table.dtype),
        aliases={3: 0},
    )(
        slots.astype(jnp.int32),
        active.astype(jnp.int32),
        vals.astype(table.dtype),
        table,
    )


def masked_row_add(
    table: jax.Array,  # [T, K] i32
    slots: jax.Array,  # [B] i32
    active: jax.Array,  # [B] bool
    vals: jax.Array,  # [B, K] i32
    lane_mask: Optional[jax.Array] = None,  # [B, K] bool; None = full row
) -> jax.Array:
    """Serial ``table[slot[i], lane] += vals[i, lane]`` for active records
    and masked lanes (integer addition commutes, so batch order does not
    matter; duplicates accumulate like ``.at[].add(..., mode="drop")``)."""
    if not _use_pallas("row_add"):
        idx = jnp.where(active, slots, table.shape[0])
        add = vals if lane_mask is None else jnp.where(lane_mask, vals, 0)
        return table.at[idx].add(add.astype(table.dtype), mode="drop")

    b = slots.shape[0]
    t, k = table.shape
    c = _chunk(b)
    blind = lane_mask is None
    if blind:
        lane_mask = jnp.ones((1, 1), jnp.int32)  # placeholder operand

    def kernel(slots_ref, active_ref, vals_ref, mask_ref, tbl_ref, out_ref):
        _init_out(out_ref, tbl_ref)

        def body(i, _):
            @functools.partial(_when, active_ref[i] != 0)
            def _():
                s = slots_ref[i]
                row = out_ref[s, :]
                if blind:
                    out_ref[s, :] = row + vals_ref[i, :]
                else:
                    out_ref[s, :] = jnp.where(
                        mask_ref[i, :] != 0, row + vals_ref[i, :], row
                    )
            return jnp.int32(0)

        lax.fori_loop(jnp.int32(0), jnp.int32(c), body, jnp.int32(0))

    mask_spec = _vmem_full_spec((1, 1)) if blind else _vmem_rows_spec(c, k)
    return _pallas_call(
        kernel,
        grid=(b // c,),
        in_specs=[
            _smem_spec(c),
            _smem_spec(c),
            _vmem_rows_spec(c, k),
            mask_spec,
            _vmem_full_spec((t, k)),
        ],
        out_specs=_vmem_full_spec((t, k)),
        out_shape=jax.ShapeDtypeStruct((t, k), table.dtype),
        aliases={4: 0},
    )(
        slots.astype(jnp.int32),
        active.astype(jnp.int32),
        vals.astype(table.dtype),
        (lane_mask if blind else lane_mask.astype(jnp.int32)),
        table,
    )


# ---------------------------------------------------------------------------
# 1D-table lane updates (table viewed as [T/128, 128])
# ---------------------------------------------------------------------------


def _lane_kernel(accumulate: bool):
    def kernel(slots_ref, active_ref, vals_ref, tbl_ref, out_ref):
        _init_out(out_ref, tbl_ref)
        lane_iota = lax.broadcasted_iota(jnp.int32, (LANES,), 0)

        def body(i, _):
            @functools.partial(_when, active_ref[i] != 0)
            def _():
                s = slots_ref[i]
                r = s >> 7
                lane = s & (LANES - 1)
                row = out_ref[r, :]
                v = vals_ref[i]
                hit = lane_iota == lane
                if accumulate:
                    out_ref[r, :] = jnp.where(hit, row + v, row)
                else:
                    out_ref[r, :] = jnp.where(hit, v, row)
            return jnp.int32(0)

        c = slots_ref.shape[0]
        lax.fori_loop(jnp.int32(0), jnp.int32(c), body, jnp.int32(0))

    return kernel


def _lane_op(table1d, slots, active, vals, accumulate):
    t = table1d.shape[0]
    b = slots.shape[0]
    if not _use_pallas("lane") or t % LANES:
        idx = jnp.where(active, slots, t)
        if accumulate:
            return table1d.at[idx].add(vals.astype(table1d.dtype), mode="drop")
        return table1d.at[idx].set(vals.astype(table1d.dtype), mode="drop")
    c = _chunk(b)
    folded = table1d.reshape(t // LANES, LANES)
    out = _pallas_call(
        _lane_kernel(accumulate),
        grid=(b // c,),
        in_specs=[
            _smem_spec(c),
            _smem_spec(c),
            _smem_spec(c),
            _vmem_full_spec((t // LANES, LANES)),
        ],
        out_specs=_vmem_full_spec((t // LANES, LANES)),
        out_shape=jax.ShapeDtypeStruct((t // LANES, LANES), table1d.dtype),
        aliases={3: 0},
    )(
        slots.astype(jnp.int32),
        active.astype(jnp.int32),
        vals.astype(table1d.dtype),
        folded,
    )
    return out.reshape(t)


def masked_lane_update(table1d, slots, active, vals):
    """1D analogue of masked_row_update (i32 tables only)."""
    return _lane_op(table1d, slots, active, vals, accumulate=False)


def masked_lane_accum(table1d, slots, active, deltas):
    """Serial ``table[slot] += delta`` (i32), batch order."""
    return _lane_op(table1d, slots, active, deltas, accumulate=True)


# ---------------------------------------------------------------------------
# int64 plane helpers (TPU i64 is emulated; tables convert to i32 planes at
# the pallas boundary and back — a cheap layout bitcast, not element math)
# ---------------------------------------------------------------------------


def i64_to_planes(x: jax.Array) -> jax.Array:
    """[N, C] i64 → [N, 2C] i32 (little-endian lo/hi pairs per column)."""
    n, cdim = x.shape
    return lax.bitcast_convert_type(x, jnp.int32).reshape(n, 2 * cdim)


def planes_to_i64(p: jax.Array) -> jax.Array:
    """[N, 2C] i32 → [N, C] i64."""
    n, c2 = p.shape
    return lax.bitcast_convert_type(
        p.reshape(n, c2 // 2, 2), jnp.int64
    )


def vec64_to_planes(x: jax.Array) -> jax.Array:
    """[B] i64 → [B, 2] i32."""
    return lax.bitcast_convert_type(x, jnp.int32)


def masked_vec64_update(table1d, slots, active, vals64):
    """1D i64 table scatter: ``table[slot[i]] = vals64[i]`` via planes."""
    if not _use_pallas("vec64"):
        idx = jnp.where(active, slots, table1d.shape[0])
        return table1d.at[idx].set(vals64.astype(table1d.dtype), mode="drop")
    planes = i64_to_planes(table1d[:, None])
    # force the inner row update onto the pallas path: this call must be
    # exactly what the autotune's "vec64" pallas arm measured — letting it
    # re-consult the independent "row_update" decision could install a
    # planes-conversion + XLA-scatter hybrid neither A/B arm ever timed
    with forced("pallas"):
        out = masked_row_update(planes, slots, active, vec64_to_planes(vals64))
    return planes_to_i64(out)[:, 0]


# ---------------------------------------------------------------------------
# fused phase-E mega-pass
# ---------------------------------------------------------------------------
#
# The step kernel's phase-E tail is a dependent chain of ~20 masked table
# writes (element-instance rows, job rows, timer bookkeeping, free-slot
# rings, direct-mapped indexes). Profiled on-chip, EVERY one of those ops
# costs ~20ns/record in per-index DMA issue — the chain, not the math, is
# the round's floor (PERF_NOTES round-4 cost model). ``fused_table_commit``
# collapses the whole tail into ONE pallas launch: the tables live in VMEM
# for the duration, each op is a serial register-resident RMW loop, and the
# per-record cost of the entire tail is a handful of VPU instructions.
#
# Ordering contract: ops apply in list order per batch chunk (chunk-major,
# op-minor). This equals the XLA chain's global op-major order whenever
# cross-record conflicts between DIFFERENT ops are confined to commutative
# kinds ("add"/"max") — which the step kernel guarantees: its guards make
# record kinds disjoint per row, so two records never hit the same (row,
# lane) through different non-commutative ops in one round. Within one op,
# serial batch order = the XLA chain's last-writer-wins rank order.


@dataclasses.dataclass
class TableOp:
    """One masked table write inside a fused commit.

    ``table`` indexes into the commit's table list. 2D [T, K] tables take
    ``vals`` [B, K] (+ optional ``mask`` [B, K]); 1D [T] tables (free
    rings, direct-mapped indexes) take scalar ``vals`` [B] and no mask.
    ``kind``: "set" (masked row write, serial last-writer-wins), "add"
    (commutative accumulate), "max" (commutative monotonic merge).
    """

    table: int
    kind: str
    slots: jax.Array
    active: jax.Array
    vals: jax.Array
    mask: Optional[jax.Array] = None


def _apply_op_unfused(tbl: jax.Array, op: TableOp) -> jax.Array:
    """One TableOp through the standalone per-family ops (exact XLA-chain
    semantics off-TPU; per-family autotuned dispatch on-TPU)."""
    if tbl.ndim == 1:
        if op.kind == "add":
            return masked_lane_accum(tbl, op.slots, op.active, op.vals)
        return masked_lane_update(tbl, op.slots, op.active, op.vals)
    if op.kind == "max":
        return masked_row_max(tbl, op.slots, op.active, op.vals)
    if op.kind == "add":
        return masked_row_add(tbl, op.slots, op.active, op.vals, op.mask)
    return masked_row_update(tbl, op.slots, op.active, op.vals, op.mask)


def fused_table_commit(
    tables: Sequence[jax.Array], ops: Sequence[TableOp], vmem_mb: int = 128
) -> List[jax.Array]:
    """Apply ``ops`` to ``tables`` (all i32; i64 state enters as planes) as
    ONE pallas serial pass — or, when the fused family lost the autotune
    A/B (or off-TPU), as the equivalent unfused op chain. Returns the new
    tables in input order.
    """
    ops = list(ops)
    if not ops:
        return list(tables)
    b = ops[0].slots.shape[0]
    fusable = (
        use_pallas("fused")
        and all(t.ndim == 1 or t.ndim == 2 for t in tables)
        and all(t.shape[0] % LANES == 0 for t in tables if t.ndim == 1)
        and all(op.slots.shape[0] == b for op in ops)
    )
    if not fusable:
        out = list(tables)
        for op in ops:
            out[op.table] = _apply_op_unfused(out[op.table], op)
        return out

    c = _chunk(b)
    ntab = len(tables)
    folded = [
        t.reshape(t.shape[0] // LANES, LANES) if t.ndim == 1 else t
        for t in tables
    ]
    is1d = [t.ndim == 1 for t in tables]

    # static operand layout: per op (slots, active, vals[, mask]) then the
    # tables; refs arrive in the same flat order, outputs one per table
    operands: List[jax.Array] = []
    in_specs = []
    meta = []  # (kind, table, one_d, masked, base ref index)
    for op in ops:
        one_d = is1d[op.table]
        base = len(operands)
        operands.append(op.slots.astype(jnp.int32))
        in_specs.append(_smem_spec(c))
        operands.append(op.active.astype(jnp.int32))
        in_specs.append(_smem_spec(c))
        if one_d:
            operands.append(op.vals.astype(tables[op.table].dtype))
            in_specs.append(_smem_spec(c))
        else:
            k = tables[op.table].shape[1]
            operands.append(op.vals.astype(tables[op.table].dtype))
            in_specs.append(_vmem_rows_spec(c, k))
        masked = (not one_d) and op.mask is not None
        if masked:
            operands.append(op.mask.astype(jnp.int32))
            in_specs.append(_vmem_rows_spec(c, k))
        meta.append((op.kind, op.table, one_d, masked, base))
    n_operands = len(operands)
    for f in folded:
        in_specs.append(_vmem_full_spec(f.shape))

    def kernel(*refs):
        in_tab = refs[n_operands : n_operands + ntab]
        out_tab = refs[n_operands + ntab :]
        for j in range(ntab):
            _init_out(out_tab[j], in_tab[j])
        lane_iota = lax.broadcasted_iota(jnp.int32, (LANES,), 0)

        for kind, tab, one_d, masked, base in meta:
            s_ref = refs[base]
            a_ref = refs[base + 1]
            v_ref = refs[base + 2]
            m_ref = refs[base + 3] if masked else None
            o_ref = out_tab[tab]

            def body(i, _, s_ref=s_ref, a_ref=a_ref, v_ref=v_ref,
                     m_ref=m_ref, o_ref=o_ref, kind=kind, one_d=one_d,
                     masked=masked):
                @functools.partial(_when, a_ref[i] != 0)
                def _():
                    s = s_ref[i]
                    if one_d:
                        r = s >> 7
                        hit = lane_iota == (s & (LANES - 1))
                        row = o_ref[r, :]
                        v = v_ref[i]
                        if kind == "add":
                            o_ref[r, :] = jnp.where(hit, row + v, row)
                        else:
                            o_ref[r, :] = jnp.where(hit, v, row)
                    else:
                        row = o_ref[s, :]
                        v = v_ref[i, :]
                        if kind == "max":
                            o_ref[s, :] = jnp.maximum(row, v)
                        elif kind == "add":
                            if masked:
                                o_ref[s, :] = jnp.where(
                                    m_ref[i, :] != 0, row + v, row
                                )
                            else:
                                o_ref[s, :] = row + v
                        else:
                            if masked:
                                o_ref[s, :] = jnp.where(
                                    m_ref[i, :] != 0, v, row
                                )
                            else:
                                o_ref[s, :] = v
                return jnp.int32(0)

            lax.fori_loop(jnp.int32(0), jnp.int32(c), body, jnp.int32(0))

    out = _pallas_call(
        kernel,
        grid=(b // c,),
        in_specs=in_specs,
        out_specs=tuple(_vmem_full_spec(f.shape) for f in folded),
        out_shape=tuple(
            jax.ShapeDtypeStruct(f.shape, f.dtype) for f in folded
        ),
        aliases={n_operands + j: j for j in range(ntab)},
        vmem_mb=vmem_mb,
    )(*operands, *folded)
    return [
        o.reshape(tables[j].shape) if is1d[j] else o
        for j, o in enumerate(out)
    ]


# ---------------------------------------------------------------------------
# fused phase-B/C mega-gather
# ---------------------------------------------------------------------------
#
# The read side of the round mirrors the write side: phases B/C open with
# one row gather per (role, table) pair — element-instance rows for the
# record/scope/activity keys, job rows, timer columns, payload rows — and
# each XLA gather costs the same ~20ns/record per-index DMA issue as the
# scatters fused_table_commit absorbed. ``fused_gather_rows`` collapses
# every read of a wave into ONE pallas launch: the tables sit in VMEM, a
# serial loop copies each requested row into a register-composed output
# block, and the per-record cost of the whole read tail is one row copy.
#
# The XLA fallback is where the op-census win lives: reads commute, so
# gathers against the SAME table concatenate their index vectors (one
# gather + static splits replaces N gathers, elementwise-identical), and
# 1D tables of one dtype concatenate along axis 0 with per-table index
# offsets. The fallback is pure data movement — no masking, no RMW — so
# fused-vs-unfused results are bit-identical by construction.


@dataclasses.dataclass
class GatherOp:
    """One row (2D table) or lane (1D table) read inside a fused gather.

    ``table`` indexes into the pass's table list; ``slots`` [B] i32 must
    already be clipped into range (the step kernel clips every slot
    vector once, right after the lookups).
    """

    table: int
    slots: jax.Array


def _gather_unfused(
    tables: Sequence[jax.Array], ops: Sequence[GatherOp]
) -> List[jax.Array]:
    """XLA gather chain with per-table index concatenation: one gather per
    2D table touched, one per 1D-table dtype group."""
    results: List[Optional[jax.Array]] = [None] * len(ops)
    by_table: dict = {}
    for i, op in enumerate(ops):
        by_table.setdefault(op.table, []).append(i)
    oned: List[int] = []
    for t_idx, op_ids in by_table.items():
        tbl = tables[t_idx]
        if tbl.ndim == 1:
            oned.extend(op_ids)
            continue
        if len(op_ids) == 1:
            i = op_ids[0]
            results[i] = tbl[ops[i].slots]
            continue
        cat = jnp.concatenate([ops[i].slots for i in op_ids])
        rows = tbl[cat]
        off = 0
        for i in op_ids:
            n = ops[i].slots.shape[0]
            results[i] = rows[off : off + n]
            off += n
    by_dtype: dict = {}
    for i in oned:
        by_dtype.setdefault(tables[ops[i].table].dtype, []).append(i)
    for op_ids in by_dtype.values():
        if len(op_ids) == 1:
            i = op_ids[0]
            results[i] = tables[ops[i].table][ops[i].slots]
            continue
        tbl_ids: List[int] = []
        for i in op_ids:
            if ops[i].table not in tbl_ids:
                tbl_ids.append(ops[i].table)
        offs = {}
        off = 0
        for t in tbl_ids:
            offs[t] = off
            off += tables[t].shape[0]
        cat_tbl = (
            tables[tbl_ids[0]] if len(tbl_ids) == 1
            else jnp.concatenate([tables[t] for t in tbl_ids])
        )
        cat_idx = jnp.concatenate(
            [ops[i].slots + offs[ops[i].table] for i in op_ids]
        )
        vals = cat_tbl[cat_idx]
        off = 0
        for i in op_ids:
            n = ops[i].slots.shape[0]
            results[i] = vals[off : off + n]
            off += n
    return results  # type: ignore[return-value]


def fused_gather_rows(
    tables: Sequence[jax.Array],
    ops: Sequence[GatherOp],
    family: str = "gather",
    vmem_mb: int = 110,
) -> List[jax.Array]:
    """``[tables[op.table][op.slots] for op in ops]`` as ONE pallas serial
    pass — or, off the pallas path, as one concatenated XLA gather per
    table group. Tables may be i32/i64/f32/i8/bool, 1D or 2D; i64 crosses
    the pallas boundary as (lo, hi) i32 planes, f32 as a bitcast, i8/bool
    widened to i32 — all exact round-trips. Every result is elementwise
    equal to direct indexing on both paths.

    ``family`` selects the dispatch row ("gather" for the phase-B/C state
    reads, "emit" for the output-queue compaction takes) so the autotuner
    can pick per-shape winners.
    """
    ops = list(ops)
    if not ops:
        return []
    b = ops[0].slots.shape[0]
    fusable = (
        use_pallas(family)
        and all(op.slots.shape[0] == b for op in ops)
        and all(t.ndim in (1, 2) for t in tables)
        and all(t.shape[0] % LANES == 0 for t in tables if t.ndim == 1)
        # every table must be VMEM-resident for the whole pass
        and sum(t.size * 4 for t in tables) <= vmem_mb * 1024 * 1024 * 3 // 4
    )
    if not fusable:
        return _gather_unfused(tables, ops)

    c = _chunk(b)
    ntab = len(tables)
    n_ops = len(ops)

    # normalize every table to i32 — 2D stays [T, K'] (i64 → planes, f32 →
    # bitcast, i8 → widened), 1D folds to [T/128, 128] for lane extraction
    # except 1D i64, which becomes a [T, 2] plane-row table
    norm: List[jax.Array] = []
    decode: List[Tuple[str, object]] = []  # per-table (mode, dtype)
    for t in tables:
        if t.ndim == 2:
            if t.dtype == jnp.int64:
                norm.append(i64_to_planes(t))
                decode.append(("planes", t.dtype))
            elif t.dtype == jnp.float32:
                norm.append(lax.bitcast_convert_type(t, jnp.int32))
                decode.append(("bitcast", t.dtype))
            elif t.dtype == jnp.int32:
                norm.append(t)
                decode.append(("rows", t.dtype))
            else:
                norm.append(t.astype(jnp.int32))
                decode.append(("widen", t.dtype))
        else:
            if t.dtype == jnp.int64:
                norm.append(i64_to_planes(t[:, None]))
                decode.append(("planes1d", t.dtype))
            elif t.dtype == jnp.float32:
                norm.append(
                    lax.bitcast_convert_type(t, jnp.int32).reshape(
                        t.shape[0] // LANES, LANES
                    )
                )
                decode.append(("lane_bitcast", t.dtype))
            else:
                norm.append(
                    t.astype(jnp.int32).reshape(t.shape[0] // LANES, LANES)
                )
                decode.append(("lane", t.dtype))

    lane_modes = ("lane", "lane_bitcast")
    in_specs = [_smem_spec(c) for _ in ops]
    in_specs += [_vmem_full_spec(nt.shape) for nt in norm]
    out_specs = []
    out_shape = []
    for op in ops:
        mode = decode[op.table][0]
        if mode in lane_modes:
            out_specs.append(_smem_spec(c))
            out_shape.append(jax.ShapeDtypeStruct((b,), jnp.int32))
        else:
            k = norm[op.table].shape[1]
            out_specs.append(_vmem_rows_spec(c, k))
            out_shape.append(jax.ShapeDtypeStruct((b, k), jnp.int32))

    meta = [(op.table, decode[op.table][0] in lane_modes) for op in ops]

    def kernel(*refs):
        t_refs = refs[n_ops : n_ops + ntab]
        o_refs = refs[n_ops + ntab :]
        lane_iota = lax.broadcasted_iota(jnp.int32, (LANES,), 0)
        for j, (tab, is_lane) in enumerate(meta):
            s_ref = refs[j]
            t_ref = t_refs[tab]
            o_ref = o_refs[j]

            def body(i, _, s_ref=s_ref, t_ref=t_ref, o_ref=o_ref,
                     is_lane=is_lane):
                s = s_ref[i]
                if is_lane:
                    r = s >> 7
                    sel = lane_iota == (s & (LANES - 1))
                    o_ref[i] = jnp.max(
                        jnp.where(sel, t_ref[r, :], jnp.int32(-(2**31)))
                    )
                else:
                    o_ref[i, :] = t_ref[s, :]
                return jnp.int32(0)

            lax.fori_loop(jnp.int32(0), jnp.int32(c), body, jnp.int32(0))

    out = _pallas_call(
        kernel,
        grid=(b // c,),
        in_specs=in_specs,
        out_specs=tuple(out_specs),
        out_shape=tuple(out_shape),
        aliases={},
        vmem_mb=vmem_mb,
    )(*[op.slots.astype(jnp.int32) for op in ops], *norm)

    results: List[jax.Array] = []
    for j, op in enumerate(ops):
        mode, dt = decode[op.table]
        o = out[j]
        if mode == "planes":
            results.append(planes_to_i64(o))
        elif mode == "planes1d":
            results.append(planes_to_i64(o)[:, 0])
        elif mode == "bitcast":
            results.append(lax.bitcast_convert_type(o, dt))
        elif mode == "widen":
            results.append(o.astype(dt))
        elif mode == "lane_bitcast":
            results.append(lax.bitcast_convert_type(o, dt))
        elif mode == "lane":
            results.append(o.astype(dt))
        else:
            results.append(o)
    return results


# ---------------------------------------------------------------------------
# hashmap ops (int64 keys as (lo, hi) i32 planes)
# ---------------------------------------------------------------------------


def _split_keys(keys64: jax.Array) -> Tuple[jax.Array, jax.Array]:
    planes = lax.bitcast_convert_type(keys64, jnp.int32)  # [..., 2] LE
    return planes[..., 0], planes[..., 1]


def _join_keys(lo: jax.Array, hi: jax.Array) -> jax.Array:
    return lax.bitcast_convert_type(
        jnp.stack([lo, hi], axis=-1), jnp.int64
    )


def _hash_i32(lo, hi, table_size):
    # must match hashmap._hash exactly (tables move between backends)
    c1 = jnp.uint32(0x9E3779B1).astype(jnp.int32)
    c2 = jnp.uint32(0x85EBCA77).astype(jnp.int32)
    h = (lo * c1) ^ (hi * c2)
    h = h ^ lax.shift_right_logical(h, jnp.int32(15))
    return h & jnp.int32(table_size - 1)


# sentinel planes: EMPTY = -1 → (lo, hi) = (-1, -1); TOMBSTONE = -2 →
# (-2, -1). Real keys are non-negative, so neither collides.


def _fold_table(table: HashTable):
    t = table.keys.shape[0]
    lo, hi = _split_keys(table.keys)
    return (
        lo.reshape(t // LANES, LANES),
        hi.reshape(t // LANES, LANES),
        table.vals.reshape(t // LANES, LANES),
    )


def lookup(table: HashTable, keys: jax.Array, valid: jax.Array):
    """Batched probe; identical results to hashmap.lookup."""
    t = table.keys.shape[0]
    b = keys.shape[0]
    if not _use_pallas("lookup") or t % LANES:
        return hashmap.lookup(table, keys, valid)
    c = _chunk(b)
    lo, hi = _split_keys(keys)
    h0 = _hash_i32(lo, hi, t)
    tlo, thi, _tv = _fold_table(table)
    tvals = table.vals.reshape(t // LANES, LANES)

    def kernel(h0_ref, lo_ref, hi_ref, valid_ref, tlo_ref, thi_ref, tv_ref,
               found_ref, slot_ref):
        lane_iota = lax.broadcasted_iota(jnp.int32, (LANES,), 0)

        def body(i, _):
            # validity folds into the loop condition (done starts True for
            # invalid records): one less conditional nesting level — the
            # cond→while→masked-op tower otherwise exceeds the tracer's
            # Python recursion budget
            klo = lo_ref[i]
            khi = hi_ref[i]
            h = h0_ref[i]
            invalid = jnp.where(valid_ref[i] == 0, jnp.int32(1), jnp.int32(0))

            # all carries are i32: mosaic's scalar bool conversions recurse
            def probe(carry):
                j, found, slot, done = carry
                idx = (h + j) & (t - 1)
                r = idx >> 7
                lane = idx & (LANES - 1)
                sel = lane_iota == lane
                blo = jnp.max(jnp.where(sel, tlo_ref[r, :], jnp.int32(-(2**31))))
                bhi = jnp.max(jnp.where(sel, thi_ref[r, :], jnp.int32(-(2**31))))
                bval = jnp.max(jnp.where(sel, tv_ref[r, :], jnp.int32(-(2**31))))
                hit = (blo == klo) & (bhi == khi)
                empty = (blo == -1) & (bhi == -1)
                return (
                    j + 1,
                    jnp.where(hit, jnp.int32(1), found),
                    jnp.where(hit, bval, slot),
                    jnp.where(hit | empty, jnp.int32(1), done),
                )

            _, found, slot, _ = lax.while_loop(
                lambda cy: (cy[0] < MAX_PROBES) & (cy[3] == 0),
                probe,
                (jnp.int32(0), jnp.int32(0), jnp.int32(-1), invalid),
            )
            found_ref[i] = found
            slot_ref[i] = slot
            return jnp.int32(0)

        lax.fori_loop(jnp.int32(0), jnp.int32(c), body, jnp.int32(0))

    found, slot = _pallas_call(
        kernel,
        grid=(b // c,),
        in_specs=[_smem_spec(c)] * 4
        + [_vmem_full_spec((t // LANES, LANES))] * 3,
        out_specs=(_smem_spec(c), _smem_spec(c)),
        out_shape=(
            jax.ShapeDtypeStruct((b,), jnp.int32),
            jax.ShapeDtypeStruct((b,), jnp.int32),
        ),
        aliases={},
    )(h0, lo, hi, valid.astype(jnp.int32), tlo, thi, tvals)
    return found.astype(bool), slot


def insert(table: HashTable, keys: jax.Array, vals: jax.Array, valid: jax.Array):
    """Batched insert of unique keys (hashmap.insert semantics; bucket
    layout may differ on collisions — see module docstring)."""
    t = table.keys.shape[0]
    b = keys.shape[0]
    if not _use_pallas("insert") or t % LANES:
        return hashmap.insert(table, keys, vals, valid)
    c = _chunk(b)
    lo, hi = _split_keys(keys)
    h0 = _hash_i32(lo, hi, t)
    tlo, thi, tvals = _fold_table(table)

    def kernel(h0_ref, lo_ref, hi_ref, vals_ref, valid_ref,
               tlo_in, thi_in, tv_in,
               tlo_ref, thi_ref, tv_ref, ok_ref):
        _init_out(tlo_ref, tlo_in)
        _init_out(thi_ref, thi_in)
        _init_out(tv_ref, tv_in)
        lane_iota = lax.broadcasted_iota(jnp.int32, (LANES,), 0)

        def body(i, _):
            klo = lo_ref[i]
            khi = hi_ref[i]
            h = h0_ref[i]
            v = vals_ref[i]
            invalid = jnp.where(valid_ref[i] == 0, jnp.int32(1), jnp.int32(0))

            # find the first EMPTY bucket; no ref writes inside the loop,
            # validity folded into the condition, i32 carries only
            def probe(carry):
                j, target, placed = carry
                idx = (h + j) & (t - 1)
                r = idx >> 7
                lane = idx & (LANES - 1)
                sel = lane_iota == lane
                blo = jnp.max(jnp.where(sel, tlo_ref[r, :], jnp.int32(-(2**31))))
                bhi = jnp.max(jnp.where(sel, thi_ref[r, :], jnp.int32(-(2**31))))
                # claim EMPTY (-1) or TOMBSTONE (-2) buckets, mirroring
                # the XLA insert (delete-heavy tables fill with
                # tombstones otherwise): hi plane is -1 for both
                free = ((blo == -1) | (blo == -2)) & (bhi == -1)
                return (
                    j + 1,
                    jnp.where(free, idx, target),
                    jnp.where(free, jnp.int32(1), placed),
                )

            _, target, placed = lax.while_loop(
                lambda cy: (cy[0] < MAX_PROBES) & (cy[2] == 0) & (invalid == 0),
                probe,
                (jnp.int32(0), jnp.int32(-1), jnp.int32(0)),
            )

            @functools.partial(_when, placed != 0)
            def _():
                r = target >> 7
                sel = lane_iota == (target & (LANES - 1))
                tlo_ref[r, :] = jnp.where(sel, klo, tlo_ref[r, :])
                thi_ref[r, :] = jnp.where(sel, khi, thi_ref[r, :])
                tv_ref[r, :] = jnp.where(sel, v, tv_ref[r, :])

            ok_ref[i] = placed
            return jnp.int32(0)

        lax.fori_loop(jnp.int32(0), jnp.int32(c), body, jnp.int32(0))

    shape2d = jax.ShapeDtypeStruct((t // LANES, LANES), jnp.int32)
    tlo2, thi2, tv2, ok = _pallas_call(
        kernel,
        grid=(b // c,),
        in_specs=[_smem_spec(c)] * 5
        + [_vmem_full_spec((t // LANES, LANES))] * 3,
        out_specs=(
            _vmem_full_spec((t // LANES, LANES)),
            _vmem_full_spec((t // LANES, LANES)),
            _vmem_full_spec((t // LANES, LANES)),
            _smem_spec(c),
        ),
        out_shape=(shape2d, shape2d, shape2d,
                   jax.ShapeDtypeStruct((b,), jnp.int32)),
        aliases={5: 0, 6: 1, 7: 2},
    )(h0, lo, hi, vals.astype(jnp.int32), valid.astype(jnp.int32),
      tlo, thi, tvals)
    new_keys = _join_keys(tlo2.reshape(t), thi2.reshape(t))
    return HashTable(new_keys, tv2.reshape(t)), ok.astype(bool)


def delete(table: HashTable, keys: jax.Array, valid: jax.Array) -> HashTable:
    """Batched delete (tombstones); identical to hashmap.delete."""
    t = table.keys.shape[0]
    b = keys.shape[0]
    if not _use_pallas("delete") or t % LANES:
        return hashmap.delete(table, keys, valid)
    c = _chunk(b)
    lo, hi = _split_keys(keys)
    h0 = _hash_i32(lo, hi, t)
    tlo, thi, tvals = _fold_table(table)

    def kernel(h0_ref, lo_ref, hi_ref, valid_ref, tlo_in, thi_in,
               tlo_ref, thi_ref):
        _init_out(tlo_ref, tlo_in)
        _init_out(thi_ref, thi_in)
        lane_iota = lax.broadcasted_iota(jnp.int32, (LANES,), 0)

        def body(i, _):
            klo = lo_ref[i]
            khi = hi_ref[i]
            h = h0_ref[i]
            invalid = jnp.where(valid_ref[i] == 0, jnp.int32(1), jnp.int32(0))

            def probe(carry):
                j, target, done = carry
                idx = (h + j) & (t - 1)
                r = idx >> 7
                lane = idx & (LANES - 1)
                sel = lane_iota == lane
                blo = jnp.max(jnp.where(sel, tlo_ref[r, :], jnp.int32(-(2**31))))
                bhi = jnp.max(jnp.where(sel, thi_ref[r, :], jnp.int32(-(2**31))))
                hit = (blo == klo) & (bhi == khi)
                empty = (blo == -1) & (bhi == -1)
                return (
                    j + 1,
                    jnp.where(hit, idx, target),
                    jnp.where(hit | empty, jnp.int32(1), done),
                )

            _, target, _ = lax.while_loop(
                lambda cy: (cy[0] < MAX_PROBES) & (cy[2] == 0) & (invalid == 0),
                probe,
                (jnp.int32(0), jnp.int32(-1), jnp.int32(0)),
            )

            @functools.partial(_when, target >= 0)
            def _():
                # TOMBSTONE = -2 → planes (-2, -1)
                r = target >> 7
                sel = lane_iota == (target & (LANES - 1))
                tlo_ref[r, :] = jnp.where(sel, jnp.int32(-2), tlo_ref[r, :])
                thi_ref[r, :] = jnp.where(sel, jnp.int32(-1), thi_ref[r, :])

            return jnp.int32(0)

        lax.fori_loop(jnp.int32(0), jnp.int32(c), body, jnp.int32(0))

    shape2d = jax.ShapeDtypeStruct((t // LANES, LANES), jnp.int32)
    tlo2, thi2 = _pallas_call(
        kernel,
        grid=(b // c,),
        in_specs=[_smem_spec(c)] * 4
        + [_vmem_full_spec((t // LANES, LANES))] * 2,
        out_specs=(
            _vmem_full_spec((t // LANES, LANES)),
            _vmem_full_spec((t // LANES, LANES)),
        ),
        out_shape=(shape2d, shape2d),
        aliases={4: 0, 5: 1},
    )(h0, lo, hi, valid.astype(jnp.int32), tlo, thi)
    new_keys = _join_keys(tlo2.reshape(t), thi2.reshape(t))
    return HashTable(new_keys, tvals.reshape(t))


# ----------------------------------------------------------------------
# startup self-check
# ----------------------------------------------------------------------
_SELFCHECK_PASSED = False


def selfcheck() -> None:
    """On-chip pallas-vs-XLA parity smoke, run ONCE before a TPU-backed
    broker serves traffic (round-3 advisor: the full parity gate in
    ``benchmarks/pallas_ops_check.py`` had never completed on hardware,
    yet ``_use_pallas()`` enabled these kernels unconditionally for
    production serving). Small shapes keep the extra boot cost to a few
    compiles; raises ``RuntimeError`` on any divergence so a broken
    Mosaic lowering refuses to serve instead of corrupting state.

    No-op off-TPU (the CPU suite pins semantics through the XLA
    fallbacks, which are the same code path).
    """
    global _SELFCHECK_PASSED
    if _SELFCHECK_PASSED or not _use_pallas():
        return

    import numpy as np

    rng = np.random.default_rng(11)
    t, b, k = 1 << 10, 1 << 8, 16

    def _fail(name, a, b_):
        raise RuntimeError(
            f"pallas selfcheck MISMATCH [{name}]: refusing to serve "
            f"({np.asarray(a).ravel()[:4]} vs {np.asarray(b_).ravel()[:4]})"
        )

    def _eq(name, a, b_):
        if not (np.asarray(a) == np.asarray(b_)).all():
            _fail(name, a, b_)

    table = hashmap.make(t)
    keys = jnp.asarray(
        rng.choice(np.arange(1, 8 * t, 3, dtype=np.int64), b, replace=False)
    )
    vals = jnp.arange(b, dtype=jnp.int32)
    valid = jnp.asarray(rng.random(b) < 0.8)
    t_x, ok_x = hashmap.insert(table, keys, vals, valid)
    t_p, ok_p = insert(table, keys, vals, valid)
    _eq("insert keyset", np.sort(np.asarray(t_x.keys)), np.sort(np.asarray(t_p.keys)))
    _eq("insert ok", ok_x, ok_p)
    fx, sx = hashmap.lookup(t_p, keys, valid)
    fp, sp = lookup(t_p, keys, valid)
    _eq("lookup found", fx, fp)
    _eq("lookup slots", np.where(np.asarray(fx), np.asarray(sx), -1),
        np.where(np.asarray(fp), np.asarray(sp), -1))
    d_x = hashmap.delete(t_x, keys, valid)
    d_p = delete(t_p, keys, valid)
    _eq("delete keyset", np.sort(np.asarray(d_x.keys)), np.sort(np.asarray(d_p.keys)))

    tbl = jnp.asarray(rng.integers(0, 100, (t, k)), jnp.int32)
    slots = jnp.asarray(rng.choice(t, b, replace=False), jnp.int32)
    active = jnp.asarray(rng.random(b) < 0.7)
    rows = jnp.asarray(rng.integers(0, 1000, (b, k)), jnp.int32)
    x = tbl.at[jnp.where(active, slots, t)].set(rows, mode="drop")
    p = masked_row_update(tbl, slots, active, rows)
    _eq("row update", x, p)

    t1 = jnp.asarray(rng.integers(0, 100, (t,)), jnp.int32)
    lvals = jnp.asarray(rng.integers(0, 9, (b,)), jnp.int32)
    _eq("lane update",
        t1.at[jnp.where(active, slots, t)].set(lvals, mode="drop"),
        masked_lane_update(t1, slots, active, lvals))
    _eq("lane accum",
        t1.at[jnp.where(active, slots, t)].add(lvals, mode="drop"),
        masked_lane_accum(t1, slots, active, lvals))

    _SELFCHECK_PASSED = True
