"""Device-resident record queue + drive loop.

The broker's hot loop (``StreamProcessorController.java:296-399``) reads
committed records and feeds follow-ups back into the log. On device, that
feedback must not cross the host boundary: emissions are appended to an
HBM FIFO (the dispatcher/"write buffer" analogue,
``dispatcher/.../Dispatcher.java:222``) and dequeued as the next fixed-size
input batch. One host sync per wave (the totals dict) drives the loop;
everything else stays on device.

Queue design (TPU-specific): XLA lowers general scatters/gathers to
SERIAL per-index loops on TPU (~10ns/row), so a classic ring buffer —
one scatter per record field per enqueue — dominated the whole round
(~50 serial ops x 32k rows). This queue instead keeps the FIFO front at
index 0:

- dequeue  = static slice ``rows[:B]`` + one contiguous shift-down copy
  per field (vectorized copies, no per-index work),
- enqueue  = one ``dynamic_update_slice`` per field at the tail
  (requires the incoming batch to be PREFIX-COMPACTED: valid rows form a
  contiguous prefix, which the kernel's emission compaction guarantees).

Rows at index >= count are always invalid (valid=False padding), so block
writes past the tail never clobber live records. FIFO order — the replay
determinism contract — is bit-identical to the ring design.

The bench and the batched broker path both run on this driver; the
durability path drains the same emissions to the host log asynchronously.
"""

from __future__ import annotations

import dataclasses
from functools import partial
from typing import Tuple

import jax
import jax.numpy as jnp
from jax import lax

from zeebe_tpu.tpu import batch as rb
from zeebe_tpu.tpu import jit_registry
from zeebe_tpu.tpu.batch import RecordBatch
from zeebe_tpu.tpu.graph import DeviceGraph
from zeebe_tpu.tpu.kernel import step_kernel
from zeebe_tpu.tpu.state import EngineState


@partial(
    jax.tree_util.register_dataclass,
    data_fields=["rows", "count", "overflow"],
    meta_fields=[],
)
@dataclasses.dataclass
class RecordQueue:
    rows: RecordBatch   # [Q] storage; live rows are exactly [0, count)
    count: jax.Array    # i32 scalar
    overflow: jax.Array  # bool scalar, sticky: an enqueue didn't fit

    @property
    def capacity(self) -> int:
        return self.rows.size


def make_queue(capacity: int, num_vars: int) -> RecordQueue:
    """``capacity`` must budget for block writes: an enqueue needs the whole
    (padded) incoming block to fit, so the usable record count is
    ``capacity - largest_enqueued_block`` (the kernel's emission block is
    ``batch_size * graph.emit_width`` rows). Size generously — storage is
    cheap, the shift copy is bandwidth-bound, and overflow is a hard abort."""
    return RecordQueue(
        rows=rb.empty(capacity, num_vars),
        count=jnp.zeros((), jnp.int32),
        overflow=jnp.zeros((), bool),
    )


def enqueue(queue: RecordQueue, batch: RecordBatch) -> RecordQueue:
    """Append a prefix-compacted batch in row order (FIFO).

    ``batch`` must have its valid rows as a contiguous prefix (the kernel's
    output compaction and host staging both guarantee this); the whole
    block lands at the tail with one dynamic_update_slice per field — the
    invalid padding rows fall beyond the new count where they are inert.
    Sets the sticky overflow flag (and leaves the queue corrupt) if the
    block doesn't fit; callers abort the drive loop on overflow.
    """
    qcap = queue.capacity
    ob = batch.size
    add = jnp.sum(batch.valid, dtype=jnp.int32)
    tail = queue.count
    # dynamic_update_slice clamps the start index; past qcap-ob the block
    # would land over live rows, so that is the (sticky) overflow line
    overflow = queue.overflow | (tail > qcap - ob)
    start = jnp.minimum(tail, qcap - ob)
    rows = jax.tree.map(
        lambda store, b: lax.dynamic_update_slice_in_dim(store, b, start, axis=0),
        queue.rows,
        batch,
    )
    return RecordQueue(rows=rows, count=tail + add, overflow=overflow)


def dequeue(queue: RecordQueue, batch_size: int) -> Tuple[RecordQueue, RecordBatch]:
    """Take the first ``batch_size`` rows (static slice) and shift the
    remainder down (contiguous per-field copies). Valid flags in storage
    already mask the sub-batch tail when fewer than ``batch_size`` rows
    are pending."""
    take = jnp.minimum(queue.count, batch_size)
    batch = jax.tree.map(lambda a: a[:batch_size], queue.rows)
    blanks = rb.empty(batch_size, queue.rows.num_vars)
    rows = jax.tree.map(
        lambda a, z: jnp.concatenate([a[batch_size:], z], axis=0),
        queue.rows,
        blanks,
    )
    return (
        RecordQueue(rows=rows, count=queue.count - take, overflow=queue.overflow),
        batch,
    )


def drive_round(
    graph: DeviceGraph,
    state: EngineState,
    queue: RecordQueue,
    now,
    batch_size: int,
    synthetic_workers: bool = False,
):
    """Dequeue one batch, step the kernel, enqueue the emissions.

    Returns (state, queue, stats). jit-compiled per (batch_size, shapes).
    ``synthetic_workers`` makes the kernel emit an instant COMPLETE after
    every ACTIVATED push (bench-only; see kernel.step_kernel).
    """
    queue, batch = dequeue(queue, batch_size)
    state, out, stats = step_kernel(
        graph, state, batch, now, synthetic_workers=synthetic_workers
    )
    queue = enqueue(queue, out)
    stats = dict(stats)
    stats["overflow"] = stats["overflow"] | queue.overflow
    return state, queue, stats


drive_jit = jit_registry.register_jit(
    "drive.round",
    drive_round,
    state_args=(1,),
    static_argnames=("batch_size", "synthetic_workers"),
    donate_argnums=(1, 2),
    max_signatures=4,
    notes="one signature per (batch_size, synthetic_workers) a process "
    "drives; batch_size is fixed per bench/serving config",
)


def _quiesce_device_fn(graph, state, queue, now, batch_size, synthetic_workers, max_rounds):
    """The whole drive-to-quiescence loop as ONE device program
    (``lax.while_loop``): no host round-trips between rounds. Off a local
    chip every per-round scalar sync is a full network round trip (the
    broker may sit across a tunnel/DCN from the device), and even locally
    dispatch latency dwarfs the step kernel."""
    totals0 = {
        "processed": jnp.zeros((), jnp.int64),
        "emitted": jnp.zeros((), jnp.int64),
        "completed_roots": jnp.zeros((), jnp.int64),
        "rounds": jnp.zeros((), jnp.int32),
        "overflow": jnp.zeros((), bool),
    }

    def cond(carry):
        _, q, t = carry
        return (q.count > 0) & (t["rounds"] < max_rounds) & (~t["overflow"])

    def body(carry):
        s, q, t = carry
        q, batch = dequeue(q, batch_size)
        s, out, stats = step_kernel(
            graph, s, batch, now, synthetic_workers=synthetic_workers
        )
        q = enqueue(q, out)
        t = {
            "processed": t["processed"] + stats["processed"].astype(jnp.int64),
            "emitted": t["emitted"] + stats["emitted"].astype(jnp.int64),
            "completed_roots": t["completed_roots"]
            + stats["completed_roots"].astype(jnp.int64),
            "rounds": t["rounds"] + 1,
            "overflow": t["overflow"]
            | stats["overflow"].astype(bool)
            | q.overflow,
        }
        return s, q, t

    return jax.lax.while_loop(cond, body, (state, queue, totals0))


_quiesce_device = jit_registry.register_jit(
    "drive.quiesce",
    _quiesce_device_fn,
    state_args=(1,),
    static_argnames=("batch_size", "synthetic_workers", "max_rounds"),
    donate_argnums=(1, 2),
    max_signatures=4,
    notes="one signature per (batch_size, synthetic_workers, max_rounds) "
    "combination a process drives",
)


# NOTE: an earlier revision compiled this program with
# ``xla_tpu_scoped_vmem_limit_kib=65536`` to get XLA's reduce-window cumsum
# lowering past a scoped-vmem allocation failure. The MXU-matmul prefix sums
# (kernel._mxu_cumsum_i32) removed those programs, and plain compilation is
# both sufficient and faster.
_quiesce_cache: dict = {}


def _quiesce_executable(graph, state, queue, now, batch_size, synthetic_workers, max_rounds):
    shapes = tuple(
        (tuple(leaf.shape), str(leaf.dtype))
        for leaf in jax.tree.leaves((graph, state, queue, now))
    )
    # the treedef must be part of the key: graphs with optional tables
    # absent (None) can have the same leaf list as graphs with a different
    # structure, and an AOT executable rejects a mismatched pytree
    treedef = jax.tree.structure((graph, state, queue, now))
    key = (treedef, shapes, batch_size, synthetic_workers, max_rounds)
    compiled = _quiesce_cache.get(key)
    if compiled is None:
        lowered = _quiesce_device.lower(
            graph, state, queue, now, batch_size, synthetic_workers, max_rounds
        )
        compiled = lowered.compile()
        _quiesce_cache[key] = compiled
    return compiled


def run_to_quiescence(
    graph: DeviceGraph,
    state: EngineState,
    queue: RecordQueue,
    now,
    batch_size: int,
    synthetic_workers: bool = False,
    max_rounds: int = 10_000,
    sync: bool = True,
):
    """Drive rounds until the queue drains — one device dispatch, one host
    sync for the totals. Returns (state, queue, totals dict).

    ``sync=False`` returns the totals as device scalars without any host
    round trip (callers accumulating across many waves fetch once at the
    end; overflow/quiescence checking is then the caller's job)."""
    now = jnp.asarray(now, jnp.int64)
    if jax.default_backend() == "tpu":
        compiled = _quiesce_executable(
            graph, state, queue, now, batch_size, synthetic_workers, max_rounds
        )
        state, queue, dev_totals = compiled(graph, state, queue, now)
    else:
        state, queue, dev_totals = _quiesce_device(
            graph, state, queue, now, batch_size, synthetic_workers, max_rounds
        )
    if not sync:
        return state, queue, dev_totals
    # ONE host transfer for all scalars — per-scalar syncs each cost a full
    # round trip to the device (networked tunnel: ~150ms apiece)
    host_totals = jax.device_get(dev_totals)
    if bool(host_totals.pop("overflow")):
        raise RuntimeError("device table or queue overflow during drive loop")
    totals = {k: int(v) for k, v in host_totals.items()}
    if totals["rounds"] >= max_rounds and int(queue.count) > 0:
        raise RuntimeError("drive loop did not quiesce")
    return state, queue, totals
