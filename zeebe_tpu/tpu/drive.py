"""Device-resident record queue + drive loop.

The broker's hot loop (``StreamProcessorController.java:296-399``) reads
committed records and feeds follow-ups back into the log. On device, that
feedback must not cross the host boundary: emissions are enqueued into an
HBM ring buffer (the dispatcher/"write buffer" analogue,
``dispatcher/.../Dispatcher.java:222``) and dequeued as the next fixed-size
input batch. One host sync per round (the pending-record count scalar)
drives the loop; everything else stays on device.

The bench and the (future) batched broker path both run on this driver; the
durability path drains the same emissions to the host log asynchronously.
"""

from __future__ import annotations

import dataclasses
from functools import partial
from typing import Tuple

import jax
import jax.numpy as jnp

from zeebe_tpu.protocol.enums import RecordType, ValueType
from zeebe_tpu.protocol.intents import JobIntent as JI
from zeebe_tpu.tpu import batch as rb
from zeebe_tpu.tpu.batch import RecordBatch
from zeebe_tpu.tpu.graph import DeviceGraph
from zeebe_tpu.tpu.kernel import step_kernel
from zeebe_tpu.tpu.state import EngineState


@partial(
    jax.tree_util.register_dataclass,
    data_fields=["rows", "head", "count"],
    meta_fields=[],
)
@dataclasses.dataclass
class RecordQueue:
    rows: RecordBatch  # capacity Q storage; only [head, head+count) live
    head: jax.Array    # i32 scalar
    count: jax.Array   # i32 scalar

    @property
    def capacity(self) -> int:
        return self.rows.size


def make_queue(capacity: int, num_vars: int) -> RecordQueue:
    return RecordQueue(
        rows=rb.empty(capacity, num_vars),
        head=jnp.zeros((), jnp.int32),
        count=jnp.zeros((), jnp.int32),
    )


def _rows_at(store: RecordBatch, idx) -> RecordBatch:
    return jax.tree.map(lambda a: a[idx], store)


def _store_rows(store: RecordBatch, idx, rows: RecordBatch, mask) -> RecordBatch:
    cap = store.size
    widx = jnp.where(mask, idx, cap)
    return jax.tree.map(
        lambda a, r: a.at[widx].set(r, mode="drop"), store, rows
    )


def enqueue(queue: RecordQueue, batch: RecordBatch) -> RecordQueue:
    """Append the valid rows of ``batch`` (already compacted: valid rows form
    a prefix) to the queue."""
    cap = queue.capacity
    n = batch.size
    add = jnp.sum(batch.valid, dtype=jnp.int32)
    idx = (queue.head + queue.count + jnp.arange(n, dtype=jnp.int32)) % cap
    rows = _store_rows(queue.rows, idx, batch, batch.valid)
    return RecordQueue(rows=rows, head=queue.head, count=queue.count + add)


def dequeue(queue: RecordQueue, batch_size: int) -> Tuple[RecordQueue, RecordBatch]:
    cap = queue.capacity
    take = jnp.minimum(queue.count, batch_size)
    idx = (queue.head + jnp.arange(batch_size, dtype=jnp.int32)) % cap
    batch = _rows_at(queue.rows, idx)
    live = jnp.arange(batch_size, dtype=jnp.int32) < take
    batch = dataclasses.replace(batch, valid=batch.valid & live)
    return (
        RecordQueue(
            rows=queue.rows,
            head=(queue.head + take) % cap,
            count=queue.count - take,
        ),
        batch,
    )


def _synthetic_complete(out: RecordBatch) -> RecordBatch:
    """Bench-only instant worker: turn pushed ACTIVATED job events into
    COMPLETE commands (models the external worker round-trip of
    ``gateway/.../impl/subscription/job/JobSubscriber.java:51`` without
    leaving the device)."""
    is_act = (
        out.valid
        & (out.vtype == int(ValueType.JOB))
        & (out.intent == int(JI.ACTIVATED))
        & out.push
    )
    return dataclasses.replace(
        out,
        valid=is_act,
        rtype=jnp.where(is_act, int(RecordType.COMMAND), out.rtype),
        intent=jnp.where(is_act, int(JI.COMPLETE), out.intent),
        push=jnp.zeros_like(out.push),
        resp=jnp.zeros_like(out.resp),
        req=jnp.full_like(out.req, -1),
        src=jnp.full_like(out.src, -1),
    )


def drive_round(
    graph: DeviceGraph,
    state: EngineState,
    queue: RecordQueue,
    now,
    batch_size: int,
    synthetic_workers: bool = False,
):
    """Dequeue one batch, step the kernel, enqueue the emissions.

    Returns (state, queue, stats). jit-compiled per (batch_size, shapes).
    """
    queue, batch = dequeue(queue, batch_size)
    state, out, stats = step_kernel(graph, state, batch, now)
    queue = enqueue(queue, out)
    if synthetic_workers:
        queue = enqueue(queue, _synthetic_complete(out))
    return state, queue, stats


drive_jit = jax.jit(
    drive_round,
    static_argnames=("batch_size", "synthetic_workers"),
    donate_argnums=(1, 2),
)


def run_to_quiescence(
    graph: DeviceGraph,
    state: EngineState,
    queue: RecordQueue,
    now,
    batch_size: int,
    synthetic_workers: bool = False,
    max_rounds: int = 10_000,
):
    """Host loop: drive rounds until the queue drains. Returns
    (state, queue, totals dict)."""
    totals = {"processed": 0, "emitted": 0, "completed_roots": 0, "rounds": 0}
    for _ in range(max_rounds):
        if int(queue.count) == 0:
            break
        state, queue, stats = drive_jit(
            graph, state, queue, jnp.asarray(now, jnp.int64),
            batch_size, synthetic_workers,
        )
        if bool(stats["overflow"]):
            raise RuntimeError("device table overflow during drive loop")
        totals["processed"] += int(stats["processed"])
        totals["emitted"] += int(stats["emitted"])
        totals["completed_roots"] += int(stats["completed_roots"])
        totals["rounds"] += 1
    else:
        raise RuntimeError("drive loop did not quiesce")
    return state, queue, totals
