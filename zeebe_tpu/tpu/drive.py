"""Device-resident record queue + drive loop.

The broker's hot loop (``StreamProcessorController.java:296-399``) reads
committed records and feeds follow-ups back into the log. On device, that
feedback must not cross the host boundary: emissions are enqueued into an
HBM ring buffer (the dispatcher/"write buffer" analogue,
``dispatcher/.../Dispatcher.java:222``) and dequeued as the next fixed-size
input batch. One host sync per round (the pending-record count scalar)
drives the loop; everything else stays on device.

The bench and the (future) batched broker path both run on this driver; the
durability path drains the same emissions to the host log asynchronously.
"""

from __future__ import annotations

import dataclasses
from functools import partial
from typing import Tuple

import jax
import jax.numpy as jnp

from zeebe_tpu.protocol.enums import RecordType, ValueType
from zeebe_tpu.protocol.intents import JobIntent as JI
from zeebe_tpu.tpu import batch as rb
from zeebe_tpu.tpu.batch import RecordBatch
from zeebe_tpu.tpu.graph import DeviceGraph
from zeebe_tpu.tpu.kernel import step_kernel
from zeebe_tpu.tpu.state import EngineState


@partial(
    jax.tree_util.register_dataclass,
    data_fields=["rows", "head", "count"],
    meta_fields=[],
)
@dataclasses.dataclass
class RecordQueue:
    rows: RecordBatch  # capacity Q storage; only [head, head+count) live
    head: jax.Array    # i32 scalar
    count: jax.Array   # i32 scalar

    @property
    def capacity(self) -> int:
        return self.rows.size


def make_queue(capacity: int, num_vars: int) -> RecordQueue:
    return RecordQueue(
        rows=rb.empty(capacity, num_vars),
        head=jnp.zeros((), jnp.int32),
        count=jnp.zeros((), jnp.int32),
    )


def _rows_at(store: RecordBatch, idx) -> RecordBatch:
    return jax.tree.map(lambda a: a[idx], store)


def _store_rows(store: RecordBatch, idx, rows: RecordBatch, mask) -> RecordBatch:
    cap = store.size
    widx = jnp.where(mask, idx, cap)
    return jax.tree.map(
        lambda a, r: a.at[widx].set(r, mode="drop"), store, rows
    )


def enqueue(queue: RecordQueue, batch: RecordBatch) -> RecordQueue:
    """Append the valid rows of ``batch`` to the queue, in row order. The
    mask may be arbitrary (not just a compacted prefix): each valid row is
    scattered to its prefix-sum slot, preserving record order — the
    determinism contract replay depends on."""
    cap = queue.capacity
    valid = batch.valid.astype(jnp.int32)
    add = jnp.sum(valid, dtype=jnp.int32)
    # rank of each valid row among valid rows
    offs = jnp.cumsum(valid, dtype=jnp.int32) - 1
    idx = (queue.head + queue.count + offs) % cap
    rows = _store_rows(queue.rows, idx, batch, batch.valid)
    return RecordQueue(rows=rows, head=queue.head, count=queue.count + add)


def dequeue(queue: RecordQueue, batch_size: int) -> Tuple[RecordQueue, RecordBatch]:
    cap = queue.capacity
    take = jnp.minimum(queue.count, batch_size)
    idx = (queue.head + jnp.arange(batch_size, dtype=jnp.int32)) % cap
    batch = _rows_at(queue.rows, idx)
    live = jnp.arange(batch_size, dtype=jnp.int32) < take
    batch = dataclasses.replace(batch, valid=batch.valid & live)
    return (
        RecordQueue(
            rows=queue.rows,
            head=(queue.head + take) % cap,
            count=queue.count - take,
        ),
        batch,
    )


def _synthetic_complete(out: RecordBatch) -> RecordBatch:
    """Bench-only instant worker: turn pushed ACTIVATED job events into
    COMPLETE commands (models the external worker round-trip of
    ``gateway/.../impl/subscription/job/JobSubscriber.java:51`` without
    leaving the device)."""
    is_act = (
        out.valid
        & (out.vtype == int(ValueType.JOB))
        & (out.intent == int(JI.ACTIVATED))
        & out.push
    )
    return dataclasses.replace(
        out,
        valid=is_act,
        rtype=jnp.where(is_act, int(RecordType.COMMAND), out.rtype),
        intent=jnp.where(is_act, int(JI.COMPLETE), out.intent),
        push=jnp.zeros_like(out.push),
        resp=jnp.zeros_like(out.resp),
        req=jnp.full_like(out.req, -1),
        src=jnp.full_like(out.src, -1),
    )


def drive_round(
    graph: DeviceGraph,
    state: EngineState,
    queue: RecordQueue,
    now,
    batch_size: int,
    synthetic_workers: bool = False,
):
    """Dequeue one batch, step the kernel, enqueue the emissions.

    Returns (state, queue, stats). jit-compiled per (batch_size, shapes).
    """
    queue, batch = dequeue(queue, batch_size)
    state, out, stats = step_kernel(graph, state, batch, now)
    queue = enqueue(queue, out)
    if synthetic_workers:
        queue = enqueue(queue, _synthetic_complete(out))
    return state, queue, stats


drive_jit = jax.jit(
    drive_round,
    static_argnames=("batch_size", "synthetic_workers"),
    donate_argnums=(1, 2),
)


@partial(
    jax.jit,
    static_argnames=("batch_size", "synthetic_workers", "max_rounds"),
    donate_argnums=(1, 2),
)
def _quiesce_device(graph, state, queue, now, batch_size, synthetic_workers, max_rounds):
    """The whole drive-to-quiescence loop as ONE device program
    (``lax.while_loop``): no host round-trips between rounds. Off a local
    chip every per-round scalar sync is a full network round trip (the
    broker may sit across a tunnel/DCN from the device), and even locally
    dispatch latency dwarfs the sub-ms step kernel."""
    totals0 = {
        "processed": jnp.zeros((), jnp.int64),
        "emitted": jnp.zeros((), jnp.int64),
        "completed_roots": jnp.zeros((), jnp.int64),
        "rounds": jnp.zeros((), jnp.int32),
        "overflow": jnp.zeros((), bool),
    }

    def cond(carry):
        _, q, t = carry
        return (q.count > 0) & (t["rounds"] < max_rounds) & (~t["overflow"])

    def body(carry):
        s, q, t = carry
        q, batch = dequeue(q, batch_size)
        s, out, stats = step_kernel(graph, s, batch, now)
        q = enqueue(q, out)
        if synthetic_workers:
            q = enqueue(q, _synthetic_complete(out))
        t = {
            "processed": t["processed"] + stats["processed"].astype(jnp.int64),
            "emitted": t["emitted"] + stats["emitted"].astype(jnp.int64),
            "completed_roots": t["completed_roots"]
            + stats["completed_roots"].astype(jnp.int64),
            "rounds": t["rounds"] + 1,
            "overflow": t["overflow"] | stats["overflow"].astype(bool),
        }
        return s, q, t

    return jax.lax.while_loop(cond, body, (state, queue, totals0))


# NOTE: an earlier revision compiled this program with
# ``xla_tpu_scoped_vmem_limit_kib=65536`` to get XLA's reduce-window cumsum
# lowering past a scoped-vmem allocation failure. The MXU-matmul prefix sums
# (kernel._mxu_cumsum_i32) removed those programs — and the raised limit
# turned out to force the in-loop scatter operands into scoped vmem, making
# every scatter ~100x slower (87ms/round vs 11ms without the flag on v5e).
# Plain compilation is both sufficient and much faster now.
_quiesce_cache: dict = {}


def _quiesce_executable(graph, state, queue, now, batch_size, synthetic_workers, max_rounds):
    shapes = tuple(
        (tuple(leaf.shape), str(leaf.dtype))
        for leaf in jax.tree.leaves((graph, state, queue, now))
    )
    key = (shapes, batch_size, synthetic_workers, max_rounds)
    compiled = _quiesce_cache.get(key)
    if compiled is None:
        lowered = _quiesce_device.lower(
            graph, state, queue, now, batch_size, synthetic_workers, max_rounds
        )
        compiled = lowered.compile()
        _quiesce_cache[key] = compiled
    return compiled


def run_to_quiescence(
    graph: DeviceGraph,
    state: EngineState,
    queue: RecordQueue,
    now,
    batch_size: int,
    synthetic_workers: bool = False,
    max_rounds: int = 10_000,
    sync: bool = True,
):
    """Drive rounds until the queue drains — one device dispatch, one host
    sync for the totals. Returns (state, queue, totals dict).

    ``sync=False`` returns the totals as device scalars without any host
    round trip (callers accumulating across many waves fetch once at the
    end; overflow/quiescence checking is then the caller's job)."""
    now = jnp.asarray(now, jnp.int64)
    if jax.default_backend() == "tpu":
        compiled = _quiesce_executable(
            graph, state, queue, now, batch_size, synthetic_workers, max_rounds
        )
        state, queue, dev_totals = compiled(graph, state, queue, now)
    else:
        state, queue, dev_totals = _quiesce_device(
            graph, state, queue, now, batch_size, synthetic_workers, max_rounds
        )
    if not sync:
        return state, queue, dev_totals
    # ONE host transfer for all scalars — per-scalar syncs each cost a full
    # round trip to the device (networked tunnel: ~150ms apiece)
    host_totals = jax.device_get(dev_totals)
    if bool(host_totals.pop("overflow")):
        raise RuntimeError("device table overflow during drive loop")
    totals = {k: int(v) for k, v in host_totals.items()}
    if totals["rounds"] >= max_rounds and int(queue.count) > 0:
        raise RuntimeError("drive loop did not quiesce")
    return state, queue, totals
