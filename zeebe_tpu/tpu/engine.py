"""Host wrapper: a batched partition stream processor over the step kernel.

``TpuPartitionEngine`` is the device-backed drop-in for the host oracle
``PartitionEngine`` (``zeebe_tpu/engine/interpreter.py``): the broker feeds
it committed records (in log order) and gets back written follow-ups,
responses, cross-partition sends, and worker pushes — but processing runs
as SIMD batches on the accelerator.

Routing: WORKFLOW_INSTANCE / JOB / TIMER records run on device; DEPLOYMENT,
MESSAGE, MESSAGE_SUBSCRIPTION and INCIDENT records are delegated to an
embedded host oracle engine (they are rare control-plane work — the
reference likewise runs deployments on the system partition only,
``DeploymentCreateEventProcessor``). Emissions are merged back in source
order, which preserves the oracle's append order (each record's follow-ups
appended after the whole committed batch, record-major).

Device-incompatible workflows (``graph.check_device_compatible``) fall
back per-workflow: their instance records route to the embedded host
oracle, so a TPU-backed partition serves every deployed workflow — the
device graph simply covers the compatible subset.
"""

from __future__ import annotations

import dataclasses
import os
from typing import Callable, Dict, List, Optional, Tuple

import numpy as np

import jax
import jax.numpy as jnp

from zeebe_tpu.engine.interpreter import (
    JobSubscription,
    PartitionEngine,
    ProcessingResult,
    WorkflowRepository,
)
from zeebe_tpu.engine.mappings import MappingError, extract, merge
from zeebe_tpu.models.el.interpreter import ConditionEvalError, evaluate_condition
from zeebe_tpu.protocol.enums import ErrorType, RecordType, RejectionType, ValueType
from zeebe_tpu.protocol.intents import (
    JobIntent as JI,
    WorkflowInstanceIntent as WI,
)
from zeebe_tpu.protocol.metadata import RecordMetadata
from zeebe_tpu.protocol.records import (
    IncidentRecord,
    JobHeaders,
    JobRecord,
    Record,
    TimerRecord,
    WorkflowInstanceRecord,
)
from zeebe_tpu.tpu import batch as rb
from zeebe_tpu.tpu import graph as graph_mod
from zeebe_tpu.tpu import jit_registry
from zeebe_tpu.tpu import kernel, state as state_mod
from zeebe_tpu.tpu.batch import PayloadError, RecordBatch
from zeebe_tpu.tpu.conditions import DeviceIneligible
from zeebe_tpu.tpu.intern import InternTable

_DEVICE_VALUE_TYPES = {
    int(ValueType.WORKFLOW_INSTANCE),
    int(ValueType.JOB),
    int(ValueType.TIMER),
}

# device-served when the compiled graph has message elements (round 4):
# the message store side is chosen per deployment set — see
# TpuPartitionEngine._recompile
_MESSAGE_VALUE_TYPES = {
    int(ValueType.MESSAGE),
    int(ValueType.MESSAGE_SUBSCRIPTION),
    int(ValueType.WORKFLOW_INSTANCE_SUBSCRIPTION),
}

_ERR_NO_RETRIES = 105  # kernel's JOB_NO_RETRIES incident code


PROBE_DEADLINES = 1  # bit0: some job/timer/message deadline is due
PROBE_JOB_BACKLOG = 2  # bit1: assignable jobs exist AND credits are free


def _due_probe_kernel(
    state: "state_mod.EngineState", now: jax.Array
) -> jax.Array:
    """i32 bitmask scalar (PROBE_*): is ANY device-side deadline due at
    ``now``, and is there job backlog a free credit could assign? One
    fused reduction over the relevant columns — launched asynchronously
    by the broker tick and polled with ``is_ready()`` so the tick never
    blocks on a device→host sync. The deadline predicates mirror the
    host sweeps below exactly (check_job_deadlines /
    check_timer_deadlines / check_message_ttls). The backlog predicate
    TYPE-MATCHES jobs against credited subscriptions: the earlier
    over-approximation (any assignable job AND any credited sub) kept
    the bit set whenever one orphan job of an unserved type coexisted
    with any credited subscription, paying a ~150 ms device→host
    backlog pull every tick for nothing. [M, S] broadcast over the small
    subscription table — still one fused reduction, no host round trip."""
    job_due = jnp.any(
        (state.job_state == int(JI.ACTIVATED))
        & (state.job_deadline >= 0)
        & (state.job_deadline <= now)
    )
    timer_due = jnp.any((state.timer_key >= 0) & (state.timer_due <= now))
    msg_due = jnp.any((state.msg_key >= 0) & (state.msg_deadline <= now))
    assignable = (
        (state.job_state == int(JI.CREATED))
        | (state.job_state == int(JI.TIMED_OUT))
        | (state.job_state == int(JI.FAILED))
    ) & (state.job_i32[:, state_mod.JB_RETRIES] > 0)
    credited = state.sub_valid & (state.sub_credits > 0)
    backlog = jnp.any(
        assignable[:, None]
        & credited[None, :]
        & (state.job_i32[:, state_mod.JB_TYPE, None] == state.sub_type[None, :])
    )
    return (
        (job_due | timer_due | msg_due).astype(jnp.int32) * PROBE_DEADLINES
        + backlog.astype(jnp.int32) * PROBE_JOB_BACKLOG
    )


def _due_probe_entry(
    state: "state_mod.EngineState", now: jax.Array
) -> Tuple["state_mod.EngineState", jax.Array]:
    """Donating entry for the probe: the reduction only READS state, so it
    passes the tables through and declares the input donated — without the
    alias, every async probe launch kept a full second copy of the ~50
    state tables resident until the poll completed (zbaudit boundary
    pass). Callers must rebind: ``state, mask = _due_probe_jit(state, now)``."""
    return state, _due_probe_kernel(state, now)


_due_probe_jit = jit_registry.register_jit(
    "engine.due_probe",
    _due_probe_entry,
    state_args=(0,),
    donate_argnums=(0,),
    max_signatures=2,
    notes="state shape is fixed per engine; one extra signature allowed "
    "for a capacity-resized engine in the same process",
)


def _host_unpack_payload(pay: np.ndarray):
    """Host-side view of one packed payload row ([3V] i32 — see
    state.pack_payload): returns (vt, num, sid) for columns_to_payload."""
    v = pay.shape[-1] // 3
    vt = pay[..., :v]
    sid = pay[..., v : 2 * v]
    num = np.ascontiguousarray(pay[..., 2 * v : 3 * v]).view(np.float32)
    return vt, num, sid


def _pow2(n: int) -> int:
    p = 64
    while p < n:
        p *= 2
    return p


def _as_record(entry) -> Record:
    """Materialize a tail entry (real ``Record`` or lazy ``(batch, idx)``
    ref) — the slow-path escape hatch for host-side inspection."""
    if type(entry) is tuple:
        return entry[0].row(entry[1])
    return entry


# frame-field defaults of a fresh Record/metadata (producer_id,
# incident_key, rejection_type) — the lazy emission batch pre-fills its
# frame columns with these so encode-from-columns matches what a
# materialized row would encode
_FRAME_DEFAULTS = None


def _frame_defaults():
    global _FRAME_DEFAULTS
    if _FRAME_DEFAULTS is None:
        md = RecordMetadata()
        probe = Record(metadata=md)
        _FRAME_DEFAULTS = (
            probe.producer_id, md.incident_key, int(md.rejection_type),
        )
    return _FRAME_DEFAULTS


# rows staged for the device STRAIGHT from readback columns (no Record
# build) — the counterpart of serving_rows_materialized_total; cached
# handle, this sits on the staging hot loop
_staged_columnar_counter = None


def _count_staged_columnar(n: int = 1) -> None:
    global _staged_columnar_counter
    if _staged_columnar_counter is None:
        from zeebe_tpu.runtime.metrics import GLOBAL_REGISTRY

        _staged_columnar_counter = GLOBAL_REGISTRY.counter(
            "serving_rows_staged_columnar_total",
            "Device rows re-staged straight from emission-batch columns "
            "(no Record object ever materialized for them)",
        )
    _staged_columnar_counter.inc(n)


@dataclasses.dataclass
class _PendingSegment:
    """One dispatched (not yet collected) device segment of a wave."""

    results: List[ProcessingResult]
    positions: List[int]
    live: List[int]           # indices (into the segment's records) staged
    suppress: set             # segment-record indices with host-emitted
                              # job-incident follow-ups (kernel copy drops)
    rows: List[int] = dataclasses.field(default_factory=list)
    out: Optional[RecordBatch] = None   # device emission batch (unfetched)
    stats: Optional[dict] = None        # device stats (unfetched)
    route_owner: Optional[int] = None   # routed wave's owner shard (v2)
    seq: int = -1                       # dispatch order (residency ordering)
    fb_pop: bool = False                # gathered fallback under routing:
                                        # collect pops residency from emissions
    blind: bool = False                 # fallback carried rows whose instance
                                        # key the host could not prove


@dataclasses.dataclass
class PendingWave:
    """A wave in flight: dispatched to the device, results not yet
    materialized. The serving loop double-buffers on this — stage/dispatch
    wave N+1 and materialize wave N−1 while the device computes wave N
    (JAX async dispatch carries the state dependency device-side).

    ``records`` may be a plain list or a lazy columnar view; ``positions``
    carries every record's log position so collection never materializes
    a row just to read it. ``partition_id`` tags the wave's owner — the
    cross-partition scheduler packs SHARED waves whose per-partition
    segments each arrive here tagged."""

    records: List[Record]
    per_record: List[Optional[ProcessingResult]]
    segments: List[_PendingSegment] = dataclasses.field(default_factory=list)
    positions: List[int] = dataclasses.field(default_factory=list)
    partition_id: int = -1
    host_seconds: float = 0.0    # staging + host-routed records + readback
    device_seconds: float = 0.0  # blocked on device outputs at collect
    collected: Optional[List[ProcessingResult]] = None  # one-shot cache


class TpuPartitionEngine:
    """Batched device stream processor for one partition."""

    def __init__(
        self,
        partition_id: int = 0,
        num_partitions: int = 1,
        repository: Optional[WorkflowRepository] = None,
        clock: Optional[Callable[[], int]] = None,
        capacity: int = 1 << 12,
        num_vars: int = 16,
        sub_capacity: int = 16,
        device=None,
        device_index: int = -1,
        state_shards: int = 1,
        shard_devices=None,
        device_indices=None,
        routing: str = "gathered",
        routed_lane_slots: int = 512,
    ):
        self.partition_id = partition_id
        self.num_partitions = num_partitions
        # mesh placement (scheduler/placement.DevicePlan): this engine's
        # state lives COMMITTED on `device`, batches stage onto it, and the
        # step program executes there — so several partitions' waves
        # compute concurrently across the mesh. None = default device (the
        # single-device baseline). `device_index` is the plan's index,
        # used only as the per-device metrics label.
        self.device = device
        self.device_index = device_index
        # sharded state mode (ROADMAP item 2, mesh-sharded partition
        # state): with state_shards > 1 this ONE partition's row tables
        # live block-sharded on dim 0 over a `shards` mesh axis spanning
        # `shard_devices` (DevicePlan hands the span; defaults to the
        # first N local devices). The step runs through
        # shard.build_state_step — gather-for-compute, keep-local-on-write
        # — and replays bit-identical to the single-device program by
        # construction. Mutually exclusive with single-device placement.
        self._state_shards = max(int(state_shards), 1)
        self._mesh = None
        self._state_step = None
        self._shard_exchange_bytes = 0
        self.sharded_waves = 0
        # sharded-state v2 (ROADMAP item 2, second half): routing mode.
        # "gathered" = v1 gather-for-compute every wave; "resident" =
        # residency-routed staging — single-owner waves stage into the
        # owner shard's batch lane and step ONLY local rows (no table
        # gather), everything else takes the gathered fallback program.
        # Both modes replay bit-identical to the single-device engine.
        if routing not in ("gathered", "resident"):
            raise ValueError(f"unknown mesh routing mode: {routing!r}")
        self.routing = routing if self._state_shards > 1 else "gathered"
        self._routed_lane_slots = max(int(routed_lane_slots), 1)
        self._state_step_routed = None
        self._state_step_fallback = None
        self._fallback_exchange_bytes = 0
        # residency map: workflow_instance_key → shard whose row block
        # holds the ENTIRE instance (learned from routed-segment
        # emissions; popped on fallback dispatch/collect, demotion,
        # completion)
        self._resident: Dict[int, int] = {}
        # instance_key → dispatch seq whose fallback/demotion broke the
        # single-owner proof. Collects run after LATER dispatches
        # (pipelining), so an earlier-dispatched routed segment's
        # _note_residency must not re-add a key a later fallback popped —
        # the seq ordering decides which knowledge is newer.
        self._residency_invalid: Dict[int, int] = {}
        self._dispatch_seq = 0
        # dispatched-but-uncollected fallback segments that stepped rows
        # whose instance key the host could not prove: until their
        # emissions name those instances (collect), ANY residency entry
        # may be stale, so routing holds off
        self._blind_fb_inflight = 0
        self.routed_waves = 0
        self.fallback_waves = 0
        self.routed_overflows = 0
        # per-shard staged-row counts of the last dispatched wave (owner
        # lane fill in resident mode, advisory hash split otherwise) —
        # read by the broker feed for scheduler/wave fill accounting
        self.last_shard_fill: tuple = ()
        self._last_stage_split = None
        self._last_stage_valid = 0
        self.device_indices = (
            list(device_indices) if device_indices is not None else []
        )
        if self._state_shards > 1:
            if device is not None:
                raise ValueError(
                    "state_shards > 1 shards over a mesh span; a single "
                    "`device` placement cannot also be pinned"
                )
            from zeebe_tpu.tpu import shard as shard_mod

            devs = (
                list(shard_devices)
                if shard_devices is not None
                else list(jax.devices())[: self._state_shards]
            )
            if len(devs) < self._state_shards:
                raise ValueError(
                    f"state_shards={self._state_shards} needs that many "
                    f"devices; have {len(devs)}"
                )
            self._mesh = shard_mod.Mesh(
                np.asarray(devs[: self._state_shards]),
                (shard_mod.STATE_AXIS,),
            )
            if not self.device_indices:
                self.device_indices = list(range(self._state_shards))
        self.repository = repository if repository is not None else WorkflowRepository()
        self.clock = clock or (lambda: 0)
        # pallas-vs-XLA dispatch is BUILD-dependent (PERF_NOTES round 4):
        # measure once per process on the actual libtpu build (disk-cached
        # per build fingerprint) instead of trusting a static env default.
        # No-op off-TPU; ZB_PALLAS stays the manual override.
        from zeebe_tpu.tpu import autotune

        autotune.ensure_autotuned()
        self.capacity = capacity
        self.num_vars = num_vars
        self.interns = InternTable()

        # host oracle engine for control-plane records (deployment, messages,
        # incidents); shares the repository and the workflow keyspace via
        # explicit counter sync after each batch
        self._host = PartitionEngine(
            partition_id=partition_id,
            num_partitions=num_partitions,
            repository=self.repository,
            clock=self.clock,
        )

        self.graph: Optional[graph_mod.DeviceGraph] = None
        self.meta: Optional[graph_mod.GraphMeta] = None
        self.state = self._place(
            state_mod.make_state(
                capacity=capacity, num_vars=num_vars, sub_capacity=sub_capacity
            )
        )
        if self._mesh is not None:
            from zeebe_tpu.tpu import shard as shard_mod

            if self.routing == "resident":
                bad = shard_mod.unshardable_state_leaves(
                    self.state, self._state_shards
                )
                if bad:
                    raise ValueError(
                        "resident routing needs every shardable table "
                        "divisible by the span; replicated-fallback "
                        f"leaves: {bad} (use routing='gathered' or a "
                        "divisible capacity)"
                    )
                self._state_step_routed = shard_mod.build_state_step_routed(
                    self._mesh, self.state
                )
                self._state_step_fallback = (
                    shard_mod.build_state_step_fallback(self._mesh, self.state)
                )
                self._fallback_exchange_bytes = (
                    shard_mod.state_exchange_bytes(
                        self.state, self._state_shards, include_lookup=False
                    )
                )
            else:
                self._state_step = shard_mod.build_state_step(
                    self._mesh, self.state
                )
            self._shard_exchange_bytes = shard_mod.state_exchange_bytes(
                self.state, self._state_shards
            )
        # key watermark of the last rebuild_lookup_state run: the direct-
        # mapped indexes are collision-free only within a window of index-
        # capacity consecutive keys, so the serving path re-derives the
        # fallback maps before the window can wrap (process_batch)
        self._keys_at_rebuild = 0
        self._compiled_count = 0
        self._host_only_keys: set = set()
        # device-residency observability (fuzzers/tests assert the routing
        # split instead of trusting eligibility rules not to drift)
        self.device_records_processed = 0
        self.host_records_processed = 0
        self._device_keys_dirty = False
        # message store side (see _recompile): True = device tables serve
        # this partition's MESSAGE-partition role
        self._messages_on_device = False
        self._restoring = False
        # ONE position→record cache shared with the embedded host oracle:
        # the broker fills it during recovery, host-side incident
        # resolution reads it (reference TypedStreamReader by position)
        self.records_by_position: Dict[int, Record] = self._host.records_by_position
        self.last_processed_position = -1
        # delta-snapshot dirty tracking over the device table families
        # (log/stateser.DEVICE_ARRAY_FAMILIES); None = cold (everything
        # dirty). Marking is conservative at wave granularity: one kernel
        # step may write any table (a job COMPLETE activates follow-on
        # elements), so a dispatched device segment dirties every family —
        # the win is that an idle partition's takes skip ALL device→host
        # readback, and host-side control traffic (subscriptions, acks,
        # ticks) dirties only the families it touches.
        self._dirty_device: Optional[set] = None
        # array part names materialized (device→host) by the last
        # snapshot_state call — the zero-readback proof for tests
        self.last_snapshot_readback: List[str] = []
        # lazy columnar emissions (ROADMAP item 4, device-path slice):
        # plain follow-up rows flow to the log as lazy refs into the
        # readback batch and re-STAGE from its columns — no Record builds
        # on the hot path. ZB_LAZY_EMISSIONS=0 restores eager rows (A/B)
        self.lazy_emissions = os.environ.get("ZB_LAZY_EMISSIONS", "1") != "0"
        # bumped by _recompile: workflow SLOTS in older emission batches
        # are stale after a redeploy — the staging fast path checks this
        self._meta_epoch = 0

    # -- mesh placement ----------------------------------------------------
    def _place(self, tree):
        """Commit a pytree's arrays to this engine's mesh device (no-op for
        the default single-device engine). Committed placement is what
        makes the jit programs EXECUTE there; uncommitted companions
        (clock scalars, migration rows) follow the committed operands."""
        if self._mesh is not None:
            # sharded mode: state tables commit block-sharded over the
            # mesh span (dim 0), everything else replicated across it —
            # both are NamedShardings, so the step program executes on
            # the whole span without per-call resharding
            from jax.sharding import NamedSharding, PartitionSpec
            from zeebe_tpu.tpu import shard as shard_mod

            if isinstance(tree, state_mod.EngineState):
                return jax.device_put(
                    tree, shard_mod.state_shardings(self._mesh, tree)
                )
            return jax.device_put(
                tree, NamedSharding(self._mesh, PartitionSpec())
            )
        if self.device is None:
            return tree
        return jax.device_put(tree, self.device)

    def place_on(self, device, device_index: int = -1) -> None:
        """Migrate this engine's live device state onto another mesh device
        (DevicePlan rebalance after a device exclusion or leadership
        change). Content is unchanged — snapshot dirty-tracking is
        untouched — and the next dispatched wave compiles/executes on the
        new device. Call between waves (the brokers do: placement changes
        happen on the broker actor, serialized with the drain)."""
        if self._mesh is not None:
            raise RuntimeError(
                "sharded-state engine is pinned to its mesh span; rebuild "
                "the engine (snapshot → restore) to move it"
            )
        self.device = device
        self.device_index = device_index
        if device is not None:
            self.state = jax.device_put(self.state, device)
            if self.graph is not None:
                self.graph = jax.device_put(self.graph, device)

    # -- routing ----------------------------------------------------------
    def partition_for_correlation_key(self, correlation_key: str) -> int:
        return self._host.partition_for_correlation_key(correlation_key)

    # topic orchestration + subscription-ack state live on the embedded
    # host oracle (system-partition control plane); the cluster broker
    # reads them through the engine interface
    @property
    def topics(self):
        return self._host.topics

    @property
    def topic_sub_acks(self):
        return self._host.topic_sub_acks

    @property
    def exporter_positions(self):
        return self._host.exporter_positions

    # -- deployment → graph recompile -------------------------------------
    def _recompile(self, extra_variables=None) -> None:
        """Split the deployed set: device-compatible workflows compile into
        the graph; incompatible ones (exotic conditions, non-flat JSONPath,
        …) run their instances on the embedded host oracle instead — the
        per-workflow fallback that makes a TPU-backed partition a drop-in
        for the host engine (reference bar: every deployed workflow keeps
        executing; where is an implementation detail)."""
        workflows = []
        host_only = set()
        for key in sorted(self.repository.by_key):
            wf = self.repository.by_key[key]
            if graph_mod.check_device_compatible(wf) is not None:
                host_only.add(key)
            else:
                workflows.append(wf)
        self._host_only_keys = host_only
        self._meta_epoch += 1  # older emission batches' wf slots are stale
        if not workflows:
            self.graph = None
            self._compiled_count = 0
            self._set_message_store_side(False)
            return
        if extra_variables is not None:
            var_names = list(extra_variables)
        else:
            var_names = list(self.meta.varspace.names) if self.meta else []
        self.graph, self.meta = graph_mod.compile_graph(
            workflows, interns=self.interns, extra_variables=var_names
        )
        # the graph is replicated per engine: committed next to the state
        # so a step never re-transfers it from the default device per call
        self.graph = self._place(self.graph)
        if self.graph.num_vars > self.num_vars:
            raise PayloadError(
                f"workflow variables ({self.graph.num_vars}) exceed engine "
                f"num_vars={self.num_vars}; raise num_vars"
            )
        self._compiled_count = len(workflows)
        # The message store (this partition's MESSAGE-partition role: stored
        # messages + open subscriptions) lives on EXACTLY one side. Device
        # iff the deployed set compiles with message elements and has no
        # host-only workflows — a mixed store would let a publish see only
        # half the subscriptions. Flipping sides migrates the store.
        self._set_message_store_side(
            self.graph.has_messages and not host_only
        )

    def _set_message_store_side(self, on_device: bool) -> None:
        prev = self._messages_on_device
        self._messages_on_device = on_device
        if self._restoring:
            return
        if on_device and not prev:
            self._migrate_message_store_to_device()
        elif prev and not on_device:
            self._migrate_message_store_to_host()

    def _migrate_message_store_to_device(self) -> None:
        """Host oracle message store → device tables (a deployment flipped
        the store side; rare control-plane event, plain host loop)."""
        from zeebe_tpu.tpu import hashmap as hm
        from zeebe_tpu.tpu.conditions import VT_NUM, VT_STR

        host = self._host
        if not host.messages and not host.message_subscriptions:
            return
        self._mark_device_dirty("msg", "msub")
        host.snapshot_mark_dirty(("h/messages",))
        s = self.state

        def corr_cols(value) -> tuple:
            if isinstance(value, str):
                return int(VT_STR), self.interns.intern(value)
            return (
                int(VT_NUM),
                int(np.float32(float(value)).view(np.int32)),
            )

        def composite(name: str, cvt: int, cbits: int) -> int:
            nid = self.interns.intern(name)
            return (nid << 35) | (cvt << 32) | (cbits & 0xFFFFFFFF)

        msub_ckey = np.asarray(s.msub_ckey).copy()
        msub_i32 = np.asarray(s.msub_i32).copy()
        msub_i64 = np.asarray(s.msub_i64).copy()
        mkeys, mslots = [], []
        free = list(np.nonzero(msub_ckey < 0)[0])
        if len(host.message_subscriptions) > len(free):
            raise RuntimeError(
                f"message-store migration needs "
                f"{len(host.message_subscriptions)} subscription slots but "
                f"the device table has {len(free)} free — raise the "
                "engine's msub capacity"
            )
        for sub in host.message_subscriptions:
            cvt, cbits = corr_cols(sub.correlation_key)
            ck = composite(sub.message_name, cvt, cbits)
            slot = int(free.pop(0))
            msub_ckey[slot] = ck
            msub_i32[slot] = (
                self.interns.intern(sub.message_name), cvt, cbits,
                sub.workflow_instance_partition_id,
            )
            msub_i64[slot] = (sub.workflow_instance_key, sub.activity_instance_key)
            mkeys.append(ck)
            mslots.append(slot)
        host.message_subscriptions = []

        msg_key = np.asarray(s.msg_key).copy()
        msg_ckey = np.asarray(s.msg_ckey).copy()
        msg_i32 = np.asarray(s.msg_i32).copy()
        msg_deadline = np.asarray(s.msg_deadline).copy()
        msg_pay = np.asarray(s.msg_pay).copy()
        gkeys, gslots = [], []
        gfree = list(np.nonzero(msg_key < 0)[0])
        if len(host.messages) > len(gfree):
            raise RuntimeError(
                f"message-store migration needs {len(host.messages)} stored-"
                f"message slots but the device table has {len(gfree)} free "
                "— raise the engine's msg capacity"
            )
        for key, message in sorted(host.messages.items()):
            cvt, cbits = corr_cols(message.correlation_key)
            ck = composite(message.name, cvt, cbits)
            slot = int(gfree.pop(0))
            msg_key[slot] = key
            msg_ckey[slot] = ck
            msg_i32[slot] = (
                self.interns.intern(message.name), cvt, cbits,
                self.interns.intern(message.message_id)
                if message.message_id else 0,
            )
            msg_deadline[slot] = message.deadline
            vt, num, sid = rb.payload_to_columns(
                message.payload, self._var_column, self.interns, self.num_vars
            )
            msg_pay[slot] = np.concatenate(
                [vt.astype(np.int32), sid,
                 np.ascontiguousarray(num).view(np.int32)]
            )
            gkeys.append(ck)
            gslots.append(slot)
        host.messages = {}

        state = dataclasses.replace(
            self.state,
            msub_ckey=jnp.asarray(msub_ckey),
            msub_i32=jnp.asarray(msub_i32),
            msub_i64=jnp.asarray(msub_i64),
            msg_key=jnp.asarray(msg_key),
            msg_ckey=jnp.asarray(msg_ckey),
            msg_i32=jnp.asarray(msg_i32),
            msg_deadline=jnp.asarray(msg_deadline),
            msg_pay=jnp.asarray(msg_pay),
        )
        if mkeys:
            m, _ = hm.insert(
                state.msub_map, jnp.asarray(mkeys, jnp.int64),
                jnp.asarray(mslots, jnp.int32),
                jnp.ones((len(mkeys),), bool),
            )
            state = dataclasses.replace(state, msub_map=m)
        if gkeys:
            g, _ = hm.insert(
                state.msg_map, jnp.asarray(gkeys, jnp.int64),
                jnp.asarray(gslots, jnp.int32),
                jnp.ones((len(gkeys),), bool),
            )
            state = dataclasses.replace(state, msg_map=g)
        self.state = state

    def _migrate_message_store_to_host(self) -> None:
        """Device message tables → host oracle store (a host-only workflow
        arrived; the store moves so every subscription sees every publish)."""
        from zeebe_tpu.engine.interpreter import StoredMessage, StoredSubscription
        from zeebe_tpu.tpu import hashmap as hm

        self._mark_device_dirty("msg", "msub")
        self._host.snapshot_mark_dirty(("h/messages",))
        s = self.state
        names = self.meta.varspace.names if self.meta else []
        corr_value = self._corr_string

        msub_ckey = np.asarray(s.msub_ckey)
        msub_i32 = np.asarray(s.msub_i32)
        msub_i64 = np.asarray(s.msub_i64)
        for slot in np.nonzero(msub_ckey >= 0)[0]:
            slot = int(slot)
            self._host.message_subscriptions.append(
                StoredSubscription(
                    message_name=self.interns.string(int(msub_i32[slot, 0])) or "",
                    correlation_key=corr_value(
                        int(msub_i32[slot, 1]), int(msub_i32[slot, 2])
                    ),
                    workflow_instance_partition_id=int(msub_i32[slot, 3]),
                    workflow_instance_key=int(msub_i64[slot, 0]),
                    activity_instance_key=int(msub_i64[slot, 1]),
                )
            )
        msg_key = np.asarray(s.msg_key)
        msg_i32 = np.asarray(s.msg_i32)
        msg_deadline = np.asarray(s.msg_deadline)
        msg_pay = np.asarray(s.msg_pay)
        for slot in np.nonzero(msg_key >= 0)[0]:
            slot = int(slot)
            key = int(msg_key[slot])
            self._host.messages[key] = StoredMessage(
                key=key,
                name=self.interns.string(int(msg_i32[slot, 0])) or "",
                correlation_key=corr_value(
                    int(msg_i32[slot, 1]), int(msg_i32[slot, 2])
                ),
                time_to_live=0,
                payload=rb.columns_to_payload(
                    *_host_unpack_payload(msg_pay[slot]), names, self.interns
                ),
                message_id=self.interns.string(int(msg_i32[slot, 3])) or "",
                deadline=int(msg_deadline[slot]),
            )
        v = self.num_vars
        self.state = dataclasses.replace(
            s,
            msub_ckey=jnp.full_like(s.msub_ckey, -1),
            msub_i64=jnp.full_like(s.msub_i64, -1),
            msub_map=hm.make(s.msub_map.keys.shape[0]),
            msg_key=jnp.full_like(s.msg_key, -1),
            msg_ckey=jnp.full_like(s.msg_ckey, -1),
            msg_deadline=jnp.full_like(s.msg_deadline, -1),
            msg_map=hm.make(s.msg_map.keys.shape[0]),
        )

    # -- instance demotion: rare imperative ops take the host path ---------
    def _live_device_instance_slot(self, key: int) -> int:
        """Slot of a live root element instance in the device table, -1
        when absent (completed, unknown, or host-side)."""
        if key < 0:
            return -1
        keys = np.asarray(self.state.ei_i64[:, 0])
        states = np.asarray(self.state.ei_i32[:, state_mod.EI_STATE])
        hits = np.nonzero((keys == key) & (states != -1))[0]
        return int(hits[0]) if len(hits) else -1

    def _demote_instance(self, root_key: int) -> None:
        """Migrate a live instance's scope tree (+ its jobs and timers)
        from the device SoA tables into the embedded host oracle.

        CANCEL and UPDATE_PAYLOAD are rare imperative control operations;
        running them host-side preserves the oracle's exact record cascade
        (CancelWorkflowInstanceProcessor's termination order, child-by-key
        sorting, job CANCEL commands) without teaching the SIMD kernel a
        cold path. The device keeps the hot lifecycle; a demoted instance
        finishes on the oracle — semantically invisible, since the oracle
        IS the semantics."""
        from zeebe_tpu.tpu import hashmap

        # demotion rewrites device tables AND inserts instances/jobs/timers
        # straight into the oracle's maps (outside any record dispatch)
        self._mark_device_dirty()
        self._host.snapshot_mark_dirty(None)
        # a demoted instance leaves the device tables — it is no longer
        # block-resident anywhere (resident routing, sharded-state v2).
        # The invalidation also blocks in-flight collects (all dispatched
        # before this point) from noting the key back in.
        self._resident.pop(int(root_key), None)
        self._residency_invalid[int(root_key)] = self._dispatch_seq
        s = self.state
        ei_i32 = np.asarray(s.ei_i32)
        ei_i64 = np.asarray(s.ei_i64)
        ei_pay = np.asarray(s.ei_pay)
        states = ei_i32[:, state_mod.EI_STATE]
        live = states != -1

        root_slot = self._live_device_instance_slot(root_key)
        if root_slot < 0:
            return
        # collect the scope tree (parent-slot pointers, bounded depth)
        tree = {root_slot}
        changed = True
        while changed:
            changed = False
            for slot in np.nonzero(live)[0]:
                parent = int(ei_i32[slot, state_mod.EI_SCOPE])
                if parent in tree and int(slot) not in tree:
                    tree.add(int(slot))
                    changed = True
        slots_sorted = sorted(tree, key=lambda sl: int(ei_i64[sl, 0]))

        names = self.meta.varspace.names if self.meta else []
        by_slot: Dict[int, object] = {}
        for slot in slots_sorted:
            key = int(ei_i64[slot, 0])
            parent_slot = int(ei_i32[slot, state_mod.EI_SCOPE])
            parent = by_slot.get(parent_slot)
            wf_slot = int(ei_i32[slot, state_mod.EI_WF])
            workflow = (
                self.meta.workflows[wf_slot]
                if self.meta and 0 <= wf_slot < len(self.meta.workflows)
                else None
            )
            value = WorkflowInstanceRecord(
                bpmn_process_id=workflow.id if workflow else "",
                version=workflow.version if workflow else -1,
                workflow_key=workflow.key if workflow else -1,
                workflow_instance_key=int(ei_i64[slot, 1]),
                activity_id=(
                    self.meta.element_id(
                        wf_slot, int(ei_i32[slot, state_mod.EI_ELEM])
                    )
                    if self.meta else ""
                ),
                payload=rb.columns_to_payload(
                    *_host_unpack_payload(ei_pay[slot]), names, self.interns
                ),
                scope_instance_key=(
                    int(ei_i64[parent_slot, 0]) if parent_slot in tree else -1
                ),
            )
            inst = self._host.element_instances.new_instance(
                key, value, WI(int(states[slot])), parent=parent
            )
            inst.job_key = int(ei_i64[slot, 2])
            inst.active_tokens = int(ei_i32[slot, state_mod.EI_TOKENS])
            pending_elem = int(ei_i32[slot, state_mod.EI_PENDING_BD])
            if pending_elem >= 0 and self.meta:
                # in-flight interrupting-boundary continuation migrates to
                # the oracle's _pending_boundary (ei_pay holds the trigger
                # payload by construction)
                self._host._pending_boundary[key] = (
                    self.meta.element_id(wf_slot, pending_elem),
                    dict(value.payload),
                )
            by_slot[slot] = inst

        tree_keys = {int(ei_i64[sl, 0]) for sl in tree}

        # migrate this tree's jobs
        job_i64 = np.asarray(s.job_i64)
        job_i32 = np.asarray(s.job_i32)
        job_slots = [
            int(sl)
            for sl in np.nonzero(job_i32[:, state_mod.JB_STATE] != -1)[0]
            if int(job_i64[sl, state_mod.JBL_AIK]) in tree_keys
        ]
        from zeebe_tpu.engine.interpreter import JobState

        for sl in job_slots:
            jkey = int(job_i64[sl, state_mod.JBL_KEY])
            self._host.jobs[jkey] = JobState(
                state=int(job_i32[sl, state_mod.JB_STATE]),
                record=self._job_value_from_slot(sl),
                deadline=int(job_i64[sl, state_mod.JBL_DEADLINE]),
            )

        # migrate this tree's timers
        from zeebe_tpu.engine.interpreter import TimerState

        timer_keys = np.asarray(s.timer_key)
        timer_aik = np.asarray(s.timer_aik)
        timer_slots = [
            int(sl)
            for sl in np.nonzero(timer_keys >= 0)[0]
            if int(timer_aik[sl]) in tree_keys
        ]
        for sl in timer_slots:
            tkey = int(timer_keys[sl])
            wf_slot = int(np.asarray(s.timer_wf)[sl])
            self._host.timers[tkey] = TimerState(
                due_date=int(np.asarray(s.timer_due)[sl]),
                activity_instance_key=int(timer_aik[sl]),
                record=TimerRecord(
                    activity_instance_key=int(timer_aik[sl]),
                    workflow_instance_key=int(
                        np.asarray(s.timer_instance_key)[sl]
                    ),
                    due_date=int(np.asarray(s.timer_due)[sl]),
                    handler_element_id=self.meta.element_id(
                        wf_slot, int(np.asarray(s.timer_elem)[sl])
                    ) if self.meta else "",
                ),
            )

        # migrate in-flight parallel joins: device join rows are keyed by
        # (scope_key << 10 | gateway element). The device merges arrival
        # payloads eagerly (flow-position-stamped), so the reconstructed
        # per-flow arrival map carries the merged payload for every arrived
        # position — exact for termination (which discards it) and for
        # joins that complete after demotion with the merged document.
        join_keys = np.asarray(s.join_key)
        join_arr = np.asarray(s.join_arrived)
        join_pay_np = np.asarray(s.join_pay)
        join_slots = [
            int(sl)
            for sl in np.nonzero(join_keys >= 0)[0]
            if int(join_keys[sl]) >> 10 in tree_keys
        ]
        for sl in join_slots:
            scope_key = int(join_keys[sl]) >> 10
            gw_elem = int(join_keys[sl]) & ((1 << 10) - 1)
            scope = self._host.element_instances.get(scope_key)
            if scope is None:
                continue
            merged = rb.columns_to_payload(
                *_host_unpack_payload(join_pay_np[sl]), names, self.interns
            )
            arrivals = {
                int(pos): dict(merged)
                for pos in np.nonzero(join_arr[sl])[0]
            }
            if arrivals:
                scope.join_arrivals[gw_elem] = arrivals

        # clear the migrated rows from the device tables + hash maps
        ei_idx = jnp.asarray(sorted(tree), jnp.int32)
        ei_del_keys = jnp.asarray(
            [int(ei_i64[sl, 0]) for sl in sorted(tree)], jnp.int64
        )
        new_state = dataclasses.replace(
            s,
            ei_i32=s.ei_i32.at[ei_idx, state_mod.EI_STATE].set(-1),
            ei_i64=s.ei_i64.at[ei_idx, 0].set(-1),
            ei_map=hashmap.delete(
                s.ei_map, ei_del_keys, jnp.ones(ei_del_keys.shape, bool)
            ),
        )
        if job_slots:
            j_idx = jnp.asarray(job_slots, jnp.int32)
            j_keys = jnp.asarray(
                [int(job_i64[sl, state_mod.JBL_KEY]) for sl in job_slots],
                jnp.int64,
            )
            new_state = dataclasses.replace(
                new_state,
                job_i32=new_state.job_i32.at[j_idx, state_mod.JB_STATE].set(-1),
                job_i64=new_state.job_i64.at[j_idx, state_mod.JBL_KEY].set(-1),
                job_map=hashmap.delete(
                    new_state.job_map, j_keys, jnp.ones(j_keys.shape, bool)
                ),
            )
        if timer_slots:
            t_idx = jnp.asarray(timer_slots, jnp.int32)
            t_keys = jnp.asarray(
                [int(timer_keys[sl]) for sl in timer_slots], jnp.int64
            )
            new_state = dataclasses.replace(
                new_state,
                timer_key=new_state.timer_key.at[t_idx].set(-1),
                timer_due=new_state.timer_due.at[t_idx].set(-1),
                timer_map=hashmap.delete(
                    new_state.timer_map, t_keys, jnp.ones(t_keys.shape, bool)
                ),
            )
        if join_slots:
            jo_idx = jnp.asarray(join_slots, jnp.int32)
            jo_keys = jnp.asarray(
                [int(join_keys[sl]) for sl in join_slots], jnp.int64
            )
            new_state = dataclasses.replace(
                new_state,
                join_key=new_state.join_key.at[jo_idx].set(-1),
                join_nin=new_state.join_nin.at[jo_idx].set(0),
                join_arrived=new_state.join_arrived.at[jo_idx].set(False),
                join_pos_stamp=new_state.join_pos_stamp.at[jo_idx].set(-1),
                join_map=hashmap.delete(
                    new_state.join_map, jo_keys, jnp.ones(jo_keys.shape, bool)
                ),
            )
        # the host-side frees above bypass the kernel's free-slot ring —
        # re-derive it (and the lookup structures) NOW, or near capacity
        # the ring runs dry and inserts report spurious table overflow
        # while the freed rows sit unused until the next cadence rebuild
        self.state = state_mod.rebuild_lookup_state(new_state)
        self._keys_at_rebuild = 0

    def _routes_to_host(self, record: Record) -> bool:
        """True when a device-value-type record belongs to a host-only
        workflow or a host-side (possibly demoted) instance and must run on
        the oracle. Pure — no side effects: process_batch performs the
        demotion for CANCEL / UPDATE_PAYLOAD after flushing the pending
        device segment, so demotion always sees up-to-date state."""
        vt = int(record.metadata.value_type)
        value = record.value
        if vt == int(ValueType.WORKFLOW_INSTANCE):
            wf_key = value.workflow_key
            intent = int(record.metadata.intent)
            if wf_key <= 0 and intent == int(WI.CREATE):
                wf = self._resolve_workflow(value)
                wf_key = wf.key if wf is not None else -1
            if wf_key in self._host_only_keys:
                return True
            if self._nonscalar_payload(record):
                # nested/list payload values have no device column form —
                # the instance is born (and lives) host-side; the oracle
                # supports arbitrary documents
                return True
            if int(record.metadata.record_type) == int(RecordType.COMMAND) and (
                intent in (int(WI.CANCEL), int(WI.UPDATE_PAYLOAD))
            ):
                # rare imperative ops always take the host path (with
                # demotion of their live device instance, done by
                # process_batch at the segment boundary)
                return True
            # EVENTS of host-side (host-only or demoted) instances route
            # by instance ownership in the oracle's element-instance index
            instances = self._host.element_instances.instances
            return (
                record.key in instances
                or value.workflow_instance_key in instances
            )
        if vt == int(ValueType.JOB):
            if self._nonscalar_payload(record):
                # e.g. a worker completing with a list-valued result:
                # process_batch demotes the owning instance first (for
                # commands; job events with such payloads are host-born)
                return True
            return (
                value.headers.workflow_key in self._host_only_keys
                or record.key in self._host.jobs
                or value.headers.workflow_instance_key
                in self._host.element_instances.instances
            )
        if vt == int(ValueType.TIMER):
            # host-side instances own their timers
            return (
                record.key in self._host.timers
                or value.activity_instance_key
                in self._host.element_instances.instances
            )
        if vt in (
            int(ValueType.MESSAGE), int(ValueType.MESSAGE_SUBSCRIPTION)
        ):
            # the message store lives on exactly one side (see _recompile)
            return not self._messages_on_device
        if vt == int(ValueType.WORKFLOW_INSTANCE_SUBSCRIPTION):
            # CORRELATE routes by where the TARGET INSTANCE lives: demoted
            # and host-only instances correlate on the oracle, device
            # instances in the kernel
            return (
                value.activity_instance_key
                in self._host.element_instances.instances
            )
        return False

    def _var_column(self, name: str) -> int:
        if self.meta is None:
            raise PayloadError("no workflows deployed")
        col = self.meta.varspace.column(name)
        if col >= self.num_vars:
            raise PayloadError(f"variable space overflow at {name!r}")
        return col

    # -- worker subscriptions (host-managed device table) ------------------
    def add_job_subscription(self, sub: JobSubscription) -> List[Record]:
        """Idempotent per subscriber key (same contract as the interpreter
        engine): a re-subscribe replaces the previous slot rather than
        double-registering it.

        Returns ACTIVATE commands for the backlog of already-created
        matching jobs (reference: ActivateJobStreamProcessor reads the log
        from the start, so pre-existing CREATED / failed-with-retries /
        timed-out jobs get assigned too — this is what lets workers find
        their jobs again after a failover/restart). The caller appends the
        returned commands to the partition log, exactly like the host
        oracle's add_job_subscription.

        The subscription registers in BOTH engines: the device table serves
        device-workflow jobs, the embedded host oracle serves jobs of
        host-only workflows. Each side draws on its own credit counter, so
        the per-subscription in-flight bound is per-engine."""
        self.remove_job_subscription(sub.subscriber_key)
        host_backlog = self._host.add_job_subscription(dataclasses.replace(sub))
        s = self.state
        valid = np.asarray(s.sub_valid)
        free = int(np.argmin(valid)) if not valid.all() else -1
        if free < 0 or valid[free]:
            raise RuntimeError("subscription table full")

        # backlog scan over the device job table (host-side; not hot path).
        # JB_STATE only ever holds CREATED/ACTIVATED/FAILED/TIMED_OUT (the
        # kernel keeps state FAILED on UPDATE_RETRIES and bumps only the
        # retries column), so FAILED + retries>0 covers retries-updated jobs
        activatable = {int(JI.CREATED), int(JI.TIMED_OUT), int(JI.FAILED)}
        type_id = self.interns.intern(sub.job_type)
        job_i32 = np.asarray(s.job_i32)
        job_keys = np.asarray(s.job_key)
        backlog: List[Record] = []
        credits = sub.credits
        candidates = [
            (int(job_keys[slot]), slot)
            for slot in np.nonzero(
                (job_i32[:, state_mod.JB_STATE] != -1)
                & (job_i32[:, state_mod.JB_TYPE] == type_id)
                & (job_i32[:, state_mod.JB_RETRIES] > 0)
            )[0]
            if int(job_i32[slot, state_mod.JB_STATE]) in activatable
        ]
        for key, slot in sorted(candidates):
            if credits <= 0:
                break
            activated = self._job_value_from_slot(int(slot))
            activated.deadline = self.clock() + sub.timeout
            activated.worker = sub.worker
            backlog.append(
                Record(
                    key=key,
                    value=activated,
                    metadata=RecordMetadata(
                        record_type=RecordType.COMMAND,
                        value_type=ValueType.JOB,
                        intent=int(JI.ACTIVATE),
                        request_stream_id=sub.subscriber_key,
                    ),
                )
            )
            credits -= 1

        self._mark_device_dirty("sub")
        self.state = dataclasses.replace(
            s,
            sub_key=s.sub_key.at[free].set(sub.subscriber_key),
            sub_type=s.sub_type.at[free].set(type_id),
            sub_worker=s.sub_worker.at[free].set(self.interns.intern(sub.worker)),
            # backlog activations consumed credits up front; the kernel
            # returns them on ACTIVATE rejection like pool assignments
            sub_credits=s.sub_credits.at[free].set(credits),
            sub_timeout=s.sub_timeout.at[free].set(sub.timeout),
            sub_valid=s.sub_valid.at[free].set(True),
        )
        return host_backlog + backlog

    def remove_job_subscription(self, subscriber_key: int) -> None:
        self._host.remove_job_subscription(subscriber_key)
        self._mark_device_dirty("sub")
        s = self.state
        match = np.asarray(s.sub_key) == subscriber_key
        self.state = dataclasses.replace(
            s, sub_valid=s.sub_valid & jnp.asarray(~match)
        )

    def increase_job_credits(self, subscriber_key: int, credits: int) -> None:
        self._host.increase_job_credits(subscriber_key, credits)
        self._mark_device_dirty("sub")
        s = self.state
        match = jnp.asarray(np.asarray(s.sub_key) == subscriber_key)
        self.state = dataclasses.replace(
            s, sub_credits=s.sub_credits + jnp.where(match, credits, 0)
        )

    # -- deadline scans (broker tick) --------------------------------------
    def deadlines_due_probe(self):
        """Device i32 bitmask scalar (PROBE_DEADLINES | PROBE_JOB_BACKLOG):
        is any device-side job/timer/message deadline due now, and is
        there unassigned job backlog a free credit could serve? The
        broker launches this and polls ``is_ready()`` without blocking —
        the full column sweeps below each cost a device→host sync
        (~150ms+ over a tunneled chip) and would starve the broker actor
        at the tick rate. Host-oracle deadlines are NOT covered: the
        broker sweeps those (cheap dict scans) every tick via
        ``host_deadline_commands``."""
        now = jnp.asarray(self.clock(), jnp.int64)
        self.state, mask = _due_probe_jit(self.state, now)
        return mask

    def backlog_activations(self) -> List[Record]:
        """Host-oracle side only (cheap dict scans — call freely). The
        DEVICE job backlog is served by ``device_backlog_activations``,
        gated behind the async probe's PROBE_JOB_BACKLOG bit so the tick
        only pays the device→host pull when something is assignable."""
        return self._host.backlog_activations()

    def device_backlog_activations(self) -> List[Record]:
        """ACTIVATE commands for device-table jobs that became activatable
        while every subscription was out of credits (same stranding class
        as the host engine's backlog_activations; the kernel only assigns
        jobs when it processes a job event with credits available).
        Credits are consumed up front, exactly like add_job_subscription's
        backlog scan — the kernel returns them on ACTIVATE rejection."""
        s = self.state
        valid = np.asarray(s.sub_valid)
        if not valid.any():
            return []
        sub_keys = np.asarray(s.sub_key)
        sub_types = np.asarray(s.sub_type)
        sub_credits = np.asarray(s.sub_credits).copy()
        sub_timeouts = np.asarray(s.sub_timeout)
        sub_workers = np.asarray(s.sub_worker)
        if not (sub_credits[valid] > 0).any():
            return []
        activatable = {int(JI.CREATED), int(JI.TIMED_OUT), int(JI.FAILED)}
        job_i32 = np.asarray(s.job_i32)
        job_keys = np.asarray(s.job_key)
        candidates = [
            (int(job_keys[slot]), slot)
            for slot in np.nonzero(
                (job_i32[:, state_mod.JB_STATE] != -1)
                & (job_i32[:, state_mod.JB_RETRIES] > 0)
            )[0]
            if int(job_i32[slot, state_mod.JB_STATE]) in activatable
        ]
        out: List[Record] = []
        now = self.clock()
        sub_slots = [int(i) for i in np.nonzero(valid)[0]]
        # the round-robin cursor persists in state.sub_rr across calls
        # (and across snapshot/restore): a fresh `rr = 0` every tick made
        # the first credited subscription win every drain, starving the
        # rest — the oracle's _job_rr_cursor is global, so this is also
        # host-oracle parity
        rr = int(np.asarray(s.sub_rr)) % len(sub_slots)
        for key, slot in sorted(candidates):
            type_id = int(job_i32[slot, state_mod.JB_TYPE])
            target = None
            for j in range(len(sub_slots)):
                cand = sub_slots[(rr + j) % len(sub_slots)]
                if sub_credits[cand] > 0 and int(sub_types[cand]) == type_id:
                    target = cand
                    rr = (rr + j + 1) % len(sub_slots)
                    break
            if target is None:
                continue  # no credits for this type; try other jobs' types
            sub_credits[target] -= 1
            activated = self._job_value_from_slot(int(slot))
            activated.deadline = now + int(sub_timeouts[target])
            activated.worker = self.interns.string(int(sub_workers[target])) or ""
            out.append(
                Record(
                    key=key,
                    value=activated,
                    metadata=RecordMetadata(
                        record_type=RecordType.COMMAND,
                        value_type=ValueType.JOB,
                        intent=int(JI.ACTIVATE),
                        request_stream_id=int(sub_keys[target]),
                    ),
                )
            )
        if out:  # rr only advances on an assignment, which also appends
            self._mark_device_dirty("sub")
            self.state = dataclasses.replace(
                s, sub_credits=jnp.asarray(sub_credits),
                sub_rr=jnp.asarray(rr, jnp.int32),
            )
        return out

    def host_deadline_commands(self) -> List[Record]:
        """The embedded oracle's due commands only (same per-family key
        order the merged sweeps produce when the device side is empty).
        The broker tick calls this UNCONDITIONALLY every tick — host
        sweeps are cheap dict scans — and pairs it with
        ``device_deadline_commands`` gated by the async probe."""
        return (
            sorted(self._host.check_job_deadlines(), key=lambda r: r.key)
            + sorted(self._host.check_timer_deadlines(), key=lambda r: r.key)
            + sorted(self._host.check_message_ttls(), key=lambda r: r.key)
        )

    def device_deadline_commands(self) -> List[Record]:
        """Device-side due commands only (jobs, timers, message TTLs — each
        family key-sorted, same per-family order as host_deadline_commands).
        Callers that already swept the host oracle this tick use this to
        avoid double-emitting host commands (which would append duplicate
        TIME_OUT/TRIGGER/DELETE commands and surface as rejections)."""
        return (
            self._device_job_deadlines()
            + self._device_timer_deadlines()
            + self._device_message_ttls()
        )

    def check_job_deadlines(self) -> List[Record]:
        # jobs of host-only/demoted workflows live in the embedded oracle;
        # merge key-sorted so mixed device+host populations emit the same
        # global order the pure oracle would (log order IS the contract)
        return sorted(
            self._device_job_deadlines() + self._host.check_job_deadlines(),
            key=lambda r: r.key,
        )

    def _device_job_deadlines(self) -> List[Record]:
        now = self.clock()
        s = self.state
        keys = np.asarray(s.job_key)
        states = np.asarray(s.job_state)
        deadlines = np.asarray(s.job_deadline)
        due = (states == int(JI.ACTIVATED)) & (deadlines >= 0) & (deadlines <= now)
        out = []
        for slot in np.nonzero(due)[0][np.argsort(keys[np.nonzero(due)[0]])]:
            out.append(
                Record(
                    key=int(keys[slot]),
                    metadata=RecordMetadata(
                        record_type=RecordType.COMMAND,
                        value_type=ValueType.JOB,
                        intent=int(JI.TIME_OUT),
                    ),
                    value=self._job_value_from_slot(int(slot)),
                )
            )
        return out

    def check_timer_deadlines(self) -> List[Record]:
        # timers of host-only/demoted workflows (incl. boundary-event
        # timers) live in the embedded oracle and must be swept too;
        # key-sorted merge = the pure oracle's global order
        return sorted(
            self._device_timer_deadlines() + self._host.check_timer_deadlines(),
            key=lambda r: r.key,
        )

    def _device_timer_deadlines(self) -> List[Record]:
        now = self.clock()
        s = self.state
        keys = np.asarray(s.timer_key)
        due = (keys >= 0) & (np.asarray(s.timer_due) <= now)
        slots = np.nonzero(due)[0]
        out = []
        for slot in slots[np.argsort(keys[slots])]:
            slot = int(slot)
            out.append(
                Record(
                    key=int(keys[slot]),
                    metadata=RecordMetadata(
                        record_type=RecordType.COMMAND,
                        value_type=ValueType.TIMER,
                        intent=2,  # TimerIntent.TRIGGER
                    ),
                    value=TimerRecord(
                        workflow_instance_key=int(
                            np.asarray(s.timer_instance_key)[slot]
                        ),
                        activity_instance_key=int(np.asarray(s.timer_aik)[slot]),
                        due_date=int(np.asarray(s.timer_due)[slot]),
                        handler_element_id=self.meta.element_id(
                            int(np.asarray(s.timer_wf)[slot]),
                            int(np.asarray(s.timer_elem)[slot]),
                        ),
                    ),
                )
            )
        return out

    def check_message_ttls(self) -> List[Record]:
        return sorted(
            self._device_message_ttls() + self._host.check_message_ttls(),
            key=lambda r: r.key,
        )

    def _device_message_ttls(self) -> List[Record]:
        from zeebe_tpu.protocol.intents import MessageIntent as MI
        from zeebe_tpu.protocol.records import MessageRecord

        now = self.clock()
        s = self.state
        keys = np.asarray(s.msg_key)
        due = (keys >= 0) & (np.asarray(s.msg_deadline) <= now)
        slots = np.nonzero(due)[0]
        names = self.meta.varspace.names if self.meta else []
        msg_i32 = np.asarray(s.msg_i32)
        msg_pay = np.asarray(s.msg_pay)
        out = []
        for slot in slots[np.argsort(keys[slots])]:
            slot = int(slot)
            out.append(
                Record(
                    key=int(keys[slot]),
                    metadata=RecordMetadata(
                        record_type=RecordType.COMMAND,
                        value_type=ValueType.MESSAGE,
                        intent=int(MI.DELETE),
                    ),
                    value=MessageRecord(
                        name=self.interns.string(int(msg_i32[slot, 0])) or "",
                        correlation_key=self._corr_string(
                            int(msg_i32[slot, 1]), int(msg_i32[slot, 2])
                        ),
                        payload=rb.columns_to_payload(
                            *_host_unpack_payload(msg_pay[slot]),
                            names, self.interns,
                        ),
                        message_id=(
                            self.interns.string(int(msg_i32[slot, 3])) or ""
                        ),
                    ),
                )
            )
        return out

    def compaction_floor(self) -> int:
        """See PartitionEngine.compaction_floor — incident state lives on
        the embedded host oracle."""
        return min(
            self.last_processed_position + 1, self._host.compaction_floor()
        )

    # -- snapshot / restore (reference StateSnapshotController: RocksDB
    # checkpoint keyed by last-processed position; here the SoA tables are
    # device_get into the data-only device envelope of log/stateser.py,
    # alongside the intern/varspace sidecars and the embedded host oracle's
    # state. Restore + replay is the same contract as the host engine:
    # the broker replays committed records after last_processed_position
    # with side effects suppressed.) --------------------------------------
    # every device table family (kept in sync with
    # stateser.DEVICE_ARRAY_FAMILIES; pinned by a test) — module-local so
    # the per-wave mark pays no import lookup
    _ALL_DEVICE_FAMILIES = (
        "ei", "job", "join", "keys", "msg", "msub", "sub", "timer",
    )

    def _mark_device_dirty(self, *families: str) -> None:
        """Record device-table mutations for delta snapshots; no args =
        every device family (a kernel step may write any table) — host
        family tracking stays live, so clean host parts (e.g. workflows)
        still reuse their previous segments on the next take."""
        if self._dirty_device is None:
            return
        self._dirty_device.update(families or self._ALL_DEVICE_FAMILIES)

    def snapshot_dirty_families(self):
        """Union of device ("d/<family>") and embedded-oracle ("h/...")
        dirty families since the last mark_clean; None when either side's
        tracking is cold (forces a full take)."""
        host = self._host.snapshot_dirty_families()
        if self._dirty_device is None or host is None:
            return None
        return frozenset({"d/" + f for f in self._dirty_device} | set(host))

    def snapshot_mark_clean(self) -> None:
        self._dirty_device = set()
        self._host.snapshot_mark_clean()

    def snapshot_mark_dirty(self, families=None) -> None:
        if families is None:
            self._dirty_device = None
            self._host.snapshot_mark_dirty(None)
            return
        dev = [f[2:] for f in families if f.startswith("d/")]
        if dev:  # empty would mean mark-ALL in _mark_device_dirty's varargs
            self._mark_device_dirty(*dev)
        host = [f for f in families if f.startswith("h/")]
        if host:
            self._host.snapshot_mark_dirty(host)

    def snapshot_state(self, families=None) -> dict:
        from zeebe_tpu.log import stateser

        dirty_dev = None
        if families is not None:
            dirty_dev = {f[2:] for f in families if f.startswith("d/")}
        arrays: Dict[str, Optional[np.ndarray]] = {}
        read: List[str] = []

        def put(name: str, value, skip: bool) -> None:
            if skip:
                # clean family: the caller reuses the previous manifest's
                # segment — NO device→host transfer, no encode, no hash
                arrays[name] = None
            else:
                arrays[name] = np.asarray(value)
                read.append(name)

        for f in dataclasses.fields(self.state):
            skip = (
                dirty_dev is not None
                and stateser.device_array_family(f.name) not in dirty_dev
            )
            v = getattr(self.state, f.name)
            if hasattr(v, "keys") and hasattr(v, "vals"):  # HashTable
                put(f.name + ".keys", v.keys, skip)
                put(f.name + ".vals", v.vals, skip)
            else:
                put(f.name, v, skip)
        self.last_snapshot_readback = read
        return {
            "fmt": stateser.FORMAT_DEVICE_V1,
            "arrays": arrays,
            "meta": {
                # interned strings in id order (id 0 is reserved NIL);
                # restoring in order reproduces identical ids, which the
                # table columns (job types, workers, string payloads) hold
                "interns": [s or "" for s in self.interns._by_id[1:]],
                "variables": (
                    list(self.meta.varspace.names) if self.meta else []
                ),
                "last_processed_position": self.last_processed_position,
            },
            "host": self._host.snapshot_state(),
        }

    def restore_state(self, snap: dict) -> None:
        from zeebe_tpu.log import stateser
        from zeebe_tpu.tpu import hashmap

        if snap.get("fmt") != stateser.FORMAT_DEVICE_V1:
            raise ValueError("not a device-engine snapshot")
        self._dirty_device = None  # restored engine: next take is full
        # host oracle first: restores the shared repository (workflows) and
        # the control-plane state families
        self._host.restore_state(snap["host"])
        meta = snap.get("meta", {})
        self.interns = InternTable()
        for s in meta.get("interns", []):
            self.interns.intern(s)
        # recompile through the SAME path as deployments (_recompile):
        # it re-derives the host-only split and compiles only the
        # device-compatible subset, so workflow slot numbering matches the
        # run that wrote the snapshot; the snapshot's variable-column order
        # is forced (column ids live in the payload matrices, so order is
        # part of the state)
        self.meta = None
        self.graph = None
        if self.repository.by_key:
            # no store migration during restore: the snapshot arrays below
            # already carry the message store on whichever side the gate
            # computes (the gate is a pure function of the restored repo)
            self._restoring = True
            try:
                self._recompile(extra_variables=list(meta.get("variables", [])))
            finally:
                self._restoring = False
        arrays = snap["arrays"]
        kwargs = {}
        pre_round4_arrays = False
        for f in dataclasses.fields(self.state):
            if f.name + ".keys" in arrays:
                kwargs[f.name] = hashmap.HashTable(
                    keys=jnp.asarray(arrays[f.name + ".keys"]),
                    vals=jnp.asarray(arrays[f.name + ".vals"]),
                )
            elif f.name == "ei_i32" and arrays[f.name].shape[1] == 5:
                # pre-round-4 snapshot: pad the pending-boundary column
                kwargs[f.name] = jnp.concatenate(
                    [jnp.asarray(arrays[f.name]),
                     jnp.full((arrays[f.name].shape[0], 1), -1, jnp.int32)],
                    axis=1,
                )
                pre_round4_arrays = True
            elif f.name in arrays:
                kwargs[f.name] = jnp.asarray(arrays[f.name])
            else:
                # snapshot written before this state family existed (e.g.
                # message tables added in round 4): keep the fresh empty
                # table; any live state of that family sits on the host
                # side of the snapshot and migrates below
                kwargs[f.name] = getattr(self.state, f.name)
                pre_round4_arrays = True
        st = state_mod.EngineState(**kwargs)
        # job-worker subscriptions are transient client-session state: the
        # reference drops them across failover (workers re-subscribe); the
        # snapshot carries the columns but a restored partition starts with
        # an empty subscription table
        st = dataclasses.replace(
            st,
            sub_key=jnp.full_like(st.sub_key, -1),
            sub_credits=jnp.zeros_like(st.sub_credits),
            sub_valid=jnp.zeros_like(st.sub_valid),
        )
        # derive the lookup structures from the restored rows: an old
        # snapshot has no index arrays, a cross-backend snapshot may carry
        # a bucket layout the local builder would not produce, and the
        # fallback maps must cover every restored live instance
        st = state_mod.rebuild_lookup_state(st)
        self.state = self._place(st)
        if self._mesh is not None:
            # the restored capacity may differ from the ctor template's,
            # which changes the spec tree (divisibility) and the program's
            # traced shapes — rebuild both (register_jit: latest wins)
            from zeebe_tpu.tpu import shard as shard_mod

            self._state_step = shard_mod.build_state_step(self._mesh, st)
            self._shard_exchange_bytes = shard_mod.state_exchange_bytes(
                st, self._state_shards
            )
        self._keys_at_rebuild = 0
        self.capacity = st.capacity
        self.num_vars = st.num_vars
        self.last_processed_position = int(
            meta.get("last_processed_position", -1)
        )
        if pre_round4_arrays and self._messages_on_device:
            # the old snapshot's message store lives host-side (flat-key
            # message workflows were host-only before round 4) but the
            # restored deployment now computes a device store — migrate so
            # publishes see the restored subscriptions
            self._migrate_message_store_to_device()

    def _job_value_from_slot(self, slot: int) -> JobRecord:
        s = self.state
        wf_slot = int(np.asarray(s.job_wf)[slot])
        elem = int(np.asarray(s.job_elem)[slot])
        workflow = (
            self.meta.workflows[wf_slot]
            if self.meta and 0 <= wf_slot < len(self.meta.workflows)
            else None
        )
        return JobRecord(
            type=self.interns.string(int(np.asarray(s.job_type)[slot])) or "",
            retries=int(np.asarray(s.job_retries)[slot]),
            deadline=int(np.asarray(s.job_deadline)[slot]),
            worker=self.interns.string(int(np.asarray(s.job_worker)[slot])) or "",
            payload=rb.columns_to_payload(
                *_host_unpack_payload(np.asarray(s.job_pay)[slot]),
                self.meta.varspace.names if self.meta else [],
                self.interns,
            ),
            headers=JobHeaders(
                workflow_instance_key=int(np.asarray(s.job_instance_key)[slot]),
                bpmn_process_id=workflow.id if workflow else "",
                workflow_definition_version=workflow.version if workflow else -1,
                workflow_key=workflow.key if workflow else -1,
                activity_id=self.meta.element_id(wf_slot, elem) if self.meta else "",
                activity_instance_key=int(np.asarray(s.job_aik)[slot]),
            ),
        )

    # ------------------------------------------------------------------
    # batch processing
    # ------------------------------------------------------------------
    def process(self, record: Record) -> ProcessingResult:
        """Single-record convenience (tests); real throughput uses
        process_batch / the dispatch_wave+collect_wave pipeline."""
        return self.process_batch([record])

    def process_batch(self, records: List[Record]) -> ProcessingResult:
        """Synchronous wave: dispatch + collect, merged record-major (the
        cluster drain's non-pipelined entry)."""
        return ProcessingResult.merged(self.process_wave(records))

    def process_wave(self, records: List[Record]) -> List[ProcessingResult]:
        """Per-record results of one wave (same contract as the host
        oracle's process_wave; one device dispatch per contiguous device
        segment)."""
        return self.collect_wave(self.dispatch_wave(records))

    def dispatch_wave(self, records) -> PendingWave:
        """Stage + launch a wave WITHOUT reading device outputs back.
        Host-routed records process inline (they mutate host state in
        strict log order); device segments dispatch through the kernel and
        stay pending until ``collect_wave``. The caller may dispatch the
        next wave before collecting this one — the state dependency chains
        on device, so host staging of wave N+1 overlaps device compute of
        wave N.

        ``records`` may be a plain list of ``Record`` objects or a lazy
        columnar view (``RecordsView`` — the drains' ``committed_view``
        spans). Routing reads the COLUMNS; a lazy entry that is a device
        EVENT of a device-resident instance enters its segment as a ref
        and later stages straight from the emission batch's columns — no
        ``Record`` ever materializes for it (the columnar plane's
        device-path slice)."""
        import time as _time

        t0 = _time.perf_counter()
        view = records if hasattr(records, "entries") else None
        entries = list(view.entries()) if view is not None else records
        n = len(entries)
        if view is not None:
            col_vts = view.value_types()
            col_rts = view.record_types()
            col_its = view.intents()
            col_pos = view.positions()
            col_keys = view.keys()
        else:
            col_vts = None
            col_rts = col_its = col_pos = col_keys = None

        per_record: List[Optional[ProcessingResult]] = [None] * n
        wave = PendingWave(
            records=records, per_record=per_record,
            partition_id=self.partition_id,
        )
        positions = wave.positions
        # segment processing: device rows batch up, but whenever a
        # host-routed record appears the pending device segment DISPATCHES
        # through the kernel first — state mutates in strict log order,
        # exactly like the oracle's per-record loop (a host record may
        # depend on state a preceding device record writes, e.g. a job
        # COMPLETE followed by the instance's CANCEL)
        pending: List[int] = []
        # resident routing: the pending segment carries ONE route class —
        # ("create",) all-CREATE, ("ik", shard) proven-resident, ("fb",)
        # unknown/mixed — and a record of a different class flushes first
        # (single-owner waves are what make the routed program exact).
        # None everywhere when routing is inactive: no split, no change.
        pending_route: List = [None]
        # the two engines allocate from ONE keyspace; their counters sync
        # at segment boundaries so keys never collide across the
        # host/device split. Device→host pulls cost a device read and only
        # happen when a device segment has run since the last pull — the
        # flag lives on SELF because the boundary usually falls BETWEEN
        # process_batch calls; host→device pushes are device-side maxima
        # (no read).
        host_allocated = [False]

        def push_host_keys() -> None:
            if not host_allocated[0]:
                return
            self._mark_device_dirty("keys")
            # device-side maxima: no host↔device round trip
            self.state = dataclasses.replace(
                self.state,
                next_wf_key=jnp.maximum(
                    self.state.next_wf_key,
                    jnp.asarray(self._host.wf_keys.peek, jnp.int64),
                ),
                next_job_key=jnp.maximum(
                    self.state.next_job_key,
                    jnp.asarray(self._host.job_keys.peek, jnp.int64),
                ),
            )
            host_allocated[0] = False

        def seg_meta(i: int):
            if col_vts is not None:
                return col_vts[i], col_rts[i], col_its[i]
            md = entries[i].metadata
            return (
                int(md.value_type), int(md.record_type), int(md.intent),
            )

        def flush() -> None:
            if not pending:
                return
            push_host_keys()  # device allocations continue after the host's
            seg = self._dispatch_device(
                [entries[i] for i in pending],
                [positions[i] for i in pending],
                [seg_meta(i) for i in pending],
                route=pending_route[0],
            )
            seg.rows = list(pending)
            wave.segments.append(seg)
            self.device_records_processed += len(pending)
            pending.clear()
            self._device_keys_dirty = True

        for i in range(n):
            entry = entries[i]
            lazy = type(entry) is tuple
            if col_vts is not None:
                vt, rt, intent = col_vts[i], col_rts[i], col_its[i]
                pos, key = col_pos[i], col_keys[i]
            else:
                md = entry.metadata
                vt = int(md.value_type)
                rt = int(md.record_type)
                intent = int(md.intent)
                pos, key = entry.position, entry.key
            positions.append(pos)
            device_vt = vt in _DEVICE_VALUE_TYPES or (
                vt in _MESSAGE_VALUE_TYPES
                and self.graph is not None
                and self.graph.has_messages
            )
            eligible = (
                device_vt and self.meta is not None and self.graph is not None
            )
            if lazy and eligible and self._lazy_device_row(
                entry, vt, rt, intent, key
            ):
                # device EVENT of a device-resident instance, born from a
                # readback batch with current workflow slots: the row
                # stages from columns; no Record materializes, and the
                # log-backed position cache covers any later re-read
                rc = self._wave_route_class(entry, True, vt, rt, intent)
                if pending and rc != pending_route[0]:
                    flush()
                pending_route[0] = rc
                pending.append(i)
                continue
            if lazy:
                record = entry[0].row(entry[1])
                entries[i] = record
            else:
                record = entry
            # records_by_position aliases the host oracle's cache (one
            # shared dict) — a single write covers both readers
            self.records_by_position[pos] = record
            md = record.metadata
            if eligible and not self._routes_to_host(record):
                # data contract of TPU-backed partitions: payload numbers
                # must be exactly representable in float32 (device payload
                # columns are f32). Commands violating it are REJECTED at
                # the boundary — the reference likewise validates msgpack
                # documents at the client API (ClientApiMessageHandler) —
                # instead of silently rounding. Events are engine-produced
                # and therefore exact by induction.
                bad = self._inexact_payload_value(record)
                if bad is not None:
                    per_record[i] = self._reject_payload(record, bad)
                    continue
                rc = self._wave_route_class(record, False, vt, rt, intent)
                if pending and rc != pending_route[0]:
                    flush()
                pending_route[0] = rc
                pending.append(i)
            else:
                flush()  # earlier device rows execute BEFORE this record
                if self._device_keys_dirty:
                    self._pull_device_keys_into_host()
                    self._device_keys_dirty = False
                wf_peek = self._host.wf_keys.peek
                job_peek = self._host.job_keys.peek
                if (
                    vt == int(ValueType.WORKFLOW_INSTANCE)
                    and int(md.record_type) == int(RecordType.COMMAND)
                ):
                    # rare imperative ops demote their live device instance
                    # to the host oracle, which then runs the exact
                    # reference cascade (see _demote_instance)
                    if int(md.intent) == int(WI.CANCEL):
                        self._demote_instance(record.key)
                    elif int(md.intent) == int(WI.UPDATE_PAYLOAD):
                        self._demote_instance(
                            record.value.workflow_instance_key
                        )
                elif (
                    vt == int(ValueType.JOB)
                    and int(md.record_type) == int(RecordType.COMMAND)
                    and self._nonscalar_payload(record)
                ):
                    # a non-columnar job result drags the owning instance
                    # to the host path before the command applies. Client
                    # commands may omit headers — resolve the owner from
                    # the device job table by job key then.
                    owner = record.value.headers.workflow_instance_key
                    if owner < 0 and record.key >= 0:
                        slots = np.nonzero(
                            np.asarray(self.state.job_key) == record.key
                        )[0]
                        if len(slots):
                            owner = int(
                                np.asarray(self.state.job_instance_key)[
                                    int(slots[0])
                                ]
                            )
                    self._demote_instance(owner)
                deployed_before = len(self.repository.by_key)
                self.host_records_processed += 1
                try:
                    per_record[i] = self._host.process(record)
                except Exception as e:  # noqa: BLE001 - poison isolation,
                    # same contract as the oracle's process_batch: skip and
                    # record, never wedge the drain loop
                    self._host.processing_failures.append(
                        (record.position, repr(e)[:300])
                    )
                if len(self.repository.by_key) != deployed_before:
                    self._recompile()
                # key-sync check runs even for a poisoned record: a handler
                # may allocate keys before raising
                if (
                    self._host.wf_keys.peek != wf_peek
                    or self._host.job_keys.peek != job_peek
                ):
                    host_allocated[0] = True
        flush()
        push_host_keys()
        if positions:
            self.last_processed_position = positions[-1]
        wave.host_seconds += _time.perf_counter() - t0
        return wave

    def _lazy_device_row(self, entry, vt, rt, intent, key) -> bool:
        """True when a LAZY tail ref (``(batch, idx)``) can enter a device
        segment straight from its backing readback columns. Conservative:
        anything this cannot prove device-resident from columns alone
        materializes and takes the exact per-record path.

        Mirrors ``_routes_to_host`` for the EVENT cases it admits —
        device-born events are f32-exact and scalar by induction, so the
        payload-contract checks are vacuous for them."""
        if rt != int(RecordType.EVENT):
            return False
        ref = entry[0].device_ref(entry[1])
        if ref is None:
            return False
        src, j = ref
        _o, scols, epoch = src.device_source
        if epoch != self._meta_epoch or self.meta is None:
            # a redeploy recompiled the graph: workflow SLOTS in this
            # batch are stale — rebuild through the record path
            return False
        if vt == int(ValueType.JOB):
            if intent in (
                int(JI.FAILED), int(JI.RETRIES_UPDATED), int(JI.CANCELED)
            ):
                # host-side job-incident bookkeeping reads these records
                return False
        elif vt != int(ValueType.WORKFLOW_INSTANCE):
            return False
        wf_slot = scols["wf"][j]
        workflow = (
            self.meta.workflows[wf_slot]
            if 0 <= wf_slot < len(self.meta.workflows) else None
        )
        if workflow is not None and workflow.key in self._host_only_keys:
            return False
        instances = self._host.element_instances.instances
        if scols["instance_key"][j] in instances:
            return False
        if vt == int(ValueType.JOB):
            return key not in self._host.jobs
        return key not in instances

    # -- resident routing policy (sharded-state v2) ------------------------
    @property
    def _resident_mode(self) -> bool:
        return self.routing == "resident" and self._mesh is not None

    def _routing_active(self) -> bool:
        """Resident routing applies per wave: message-correlation graphs
        probe subscription tables across the whole keyspace, which the
        single-owner contract cannot cover — such partitions run every
        wave through the gathered fallback (still correct, still
        bit-identical; the routed win simply does not apply)."""
        return (
            self._resident_mode
            and self.graph is not None
            and not self.graph.has_messages
        )

    def _instance_key_of(self, entry, lazy: bool, vt: int):
        """The workflow_instance_key a device record belongs to — the
        residency-map key (the ROOT instance key, shared by every row of
        the instance's scope tree). None = not provable from the entry."""
        if lazy:
            ref = entry[0].device_ref(entry[1])
            if ref is None:
                return None
            src, j = ref
            _o, scols, _epoch = src.device_source
            return int(scols["instance_key"][j])
        value = getattr(entry, "value", None)
        if value is None:
            return None
        if vt == int(ValueType.JOB):
            headers = getattr(value, "headers", None)
            ik = getattr(headers, "workflow_instance_key", None)
        else:
            ik = getattr(value, "workflow_instance_key", None)
        return int(ik) if ik is not None else None

    def _wave_route_class(self, entry, lazy: bool, vt, rt, intent):
        """Route class of one device-eligible record: ``("create",)``
        (WI CREATE commands — the root key is the NEXT counter value, so
        the whole instance births in one predictable block),
        ``("ik", shard)`` (instance proven block-resident), ``("fb",)``
        (unknown residency → gathered fallback). None = routing inactive."""
        if not self._routing_active():
            return None
        if (
            vt == int(ValueType.WORKFLOW_INSTANCE)
            and rt == int(RecordType.COMMAND)
            and intent == int(WI.CREATE)
        ):
            return ("create",)
        ik = self._instance_key_of(entry, lazy, vt)
        if ik is None or ik < 0:
            return ("fb",)
        if self._blind_fb_inflight:
            # an uncollected fallback segment stepped rows whose instance
            # the host could not identify — possibly THIS one, and the
            # gathered kernel may have allocated its rows outside the
            # home block. Until that segment's emissions resolve the
            # keys, no residency entry is trustworthy. (CREATEs above
            # stay routable: their keys are freshly allocated.)
            return ("fb",)
        s = self._resident.get(int(ik))
        return ("ik", s) if s is not None else ("fb",)

    def _routed_lane_cap(self) -> int:
        """Max rows a routed wave may carry. Beyond the lane size, the
        binding constraint is the shard-local direct-mapped index window:
        rows born in one wave resolve through ei_index/job_index until the
        next rebuild (wave start), and the direct maps are collision-free
        only across a window of local-capacity consecutive keys — the
        same invariant `_keys_at_rebuild` maintains globally, here per
        wave with the v1 safety factor (4) because local capacity is
        1/D of the global one."""
        fanout = max(
            1, self.graph.emit_width if self.graph is not None else 1
        )
        window = (
            self.state.ei_index.shape[0] // self._state_shards
        ) // (4 * fanout)
        return max(1, min(self._routed_lane_slots, window))

    def _pop_residency_fallback(self, o, seq: int) -> None:
        """Retire residency for every instance a collected FALLBACK
        segment's emissions name: the gathered step allocates at GLOBAL
        free slots, so each touched instance may now own rows outside
        its home block. This is the collect-time complement of the
        dispatch-time pop — it covers the rows whose instance key the
        host could not prove (the kernel's emissions resolve them)."""
        valid = np.asarray(o.valid)
        ik = np.asarray(o.instance_key)
        for k in np.unique(ik[valid & (ik >= 0)]).tolist():
            self._resident.pop(int(k), None)
            self._residency_invalid[int(k)] = seq

    def _note_residency(self, o, owner: int, seq: int) -> None:
        """Learn residency from a collected ROUTED segment's emissions:
        every instance the wave touched has all its rows in ``owner``'s
        block (single-owner staging + local allocation), and instances
        whose root completed/terminated leave the map (their rows are
        freed; a later reuse of the key would be a different instance).

        ``seq`` is the segment's dispatch order: a key invalidated by a
        LATER-dispatched fallback (or a demotion) is skipped — this
        collect reflects older device state and must not reinstate an
        entry that newer knowledge already retired."""
        valid = np.asarray(o.valid)
        ik = np.asarray(o.instance_key)
        live = valid & (ik >= 0)
        inv = self._residency_invalid
        for k in np.unique(ik[live]).tolist():
            if inv.get(int(k), -1) >= seq:
                continue
            self._resident[int(k)] = owner
        vt = np.asarray(o.vtype)
        it = np.asarray(o.intent)
        key = np.asarray(o.key)
        done = (
            live
            & (vt == int(ValueType.WORKFLOW_INSTANCE))
            & (key == ik)
            & (
                (it == int(WI.ELEMENT_COMPLETED))
                | (it == int(WI.ELEMENT_TERMINATED))
            )
        )
        for k in np.unique(ik[done]).tolist():
            self._resident.pop(int(k), None)

    def collect_wave(self, wave: PendingWave) -> List[ProcessingResult]:
        """Materialize a dispatched wave: one bulk device fetch per
        segment, columnar emission decode, per-record source stamping.
        Returns per-record results in log order (a record with no output
        yields an empty result)."""
        import time as _time

        from zeebe_tpu.protocol.records import stamp_source_positions

        if wave.collected is not None:  # collection is one-shot
            return wave.collected
        t0 = _time.perf_counter()
        device_s = 0.0
        for seg in wave.segments:
            device_s += self._collect_device(seg)
            for i, res in zip(seg.rows, seg.results):
                wave.per_record[i] = res
        results: List[ProcessingResult] = []
        for pos, res in zip(wave.positions, wave.per_record):
            if res is None:  # poisoned host record: contained, no output
                res = ProcessingResult()
            stamp_source_positions(res.written, pos)
            results.append(res)
        wave.device_seconds += device_s
        wave.host_seconds += (_time.perf_counter() - t0) - device_s
        # (host, device) seconds of the last collected wave — read by the
        # brokers' wave metrics (same attribute as the host oracle's)
        self.last_wave_seconds = (wave.host_seconds, wave.device_seconds)
        wave.collected = results
        return results

    def _pull_device_keys_into_host(self) -> None:
        """Advance the embedded oracle's key generators past the device
        counters (one device→host scalar read; called only at
        device-segment → host-record boundaries)."""
        from zeebe_tpu.engine import keyspace

        # .item() extracts the scalar for any size-1 array; plain int() on a
        # ndim>0 array is deprecated NumPy behavior that will hard-error
        dev_wf = int(np.asarray(self.state.next_wf_key).item())
        dev_job = int(np.asarray(self.state.next_job_key).item())
        if self._host.wf_keys.peek < dev_wf or self._host.job_keys.peek < dev_job:
            self._host.snapshot_mark_dirty(("h/control",))
        if self._host.wf_keys.peek < dev_wf:
            self._host.wf_keys.set_key(dev_wf - keyspace.STEP_SIZE)
        if self._host.job_keys.peek < dev_job:
            self._host.job_keys.set_key(dev_job - keyspace.STEP_SIZE)

    @staticmethod
    def _nonscalar_payload(record: Record) -> bool:
        """True when the record payload holds values with no device column
        form (lists/nested documents) — such records take the host path.
        Device-born events are scalar by induction, so a non-scalar
        payload implies host ownership even before the oracle's
        element-instance index has the entry (e.g. the CREATED event of an
        instance whose CREATE was host-routed for this same reason)."""
        payload = getattr(record.value, "payload", None)
        if not payload:
            return False
        return any(
            not isinstance(v, (type(None), bool, int, float, str))
            for v in payload.values()
        )

    def _inexact_payload_value(self, record: Record):
        """Name of the first payload entry not exactly representable in
        f32 on a COMMAND record, else None."""
        from zeebe_tpu.tpu.conditions import f32_exact

        if int(record.metadata.record_type) != int(RecordType.COMMAND):
            return None
        payload = getattr(record.value, "payload", None)
        if not payload:
            return None
        for name, value in payload.items():
            if (
                isinstance(value, (int, float))
                and not isinstance(value, bool)
                and not f32_exact(value)
            ):
                return name
        return None

    def _reject_payload(self, record: Record, field: str) -> ProcessingResult:
        out = ProcessingResult()
        md = record.metadata
        rejection = Record(
            key=record.key,
            value=record.value.copy(),
            metadata=RecordMetadata(
                record_type=RecordType.COMMAND_REJECTION,
                value_type=md.value_type,
                intent=md.intent,
                rejection_type=RejectionType.BAD_VALUE,
                rejection_reason=(
                    f"payload value {field!r} is not exactly representable "
                    "in float32 (TPU partition payload contract)"
                ),
                request_id=md.request_id,
                request_stream_id=md.request_stream_id,
            ),
            source_record_position=record.position,
        )
        out.written.append(rejection)
        if md.request_id >= 0:
            out.responses.append(rejection)
        return out

    # -- host record → batch row -------------------------------------------
    _TPU_BATCH = 512  # one canonical staged shape on TPU (= drain chunk)

    # dtype families for the packed host→device transfer: one bulk
    # device_put per family (6 total) instead of one per column (24) —
    # each transfer is a round trip over a tunneled chip
    _I64_COLS = ("key", "instance_key", "scope_key", "req", "aux_key",
                 "aux2_key", "deadline")
    _I32_COLS = ("rtype", "vtype", "intent", "elem", "wf", "req_stream",
                 "type_id", "retries", "worker", "src", "rej")
    _BOOL_COLS = ("valid", "resp", "push")
    _COL_DEFAULTS = {
        "valid": False, "rtype": 0, "vtype": 0, "intent": 0, "key": -1,
        "elem": -1, "wf": -1, "instance_key": -1, "scope_key": -1,
        "req": -1, "req_stream": -1, "aux_key": -1, "aux2_key": -1,
        "type_id": 0, "retries": 0, "deadline": -1, "worker": 0,
        "src": -1, "resp": False, "push": False, "rej": 0,
    }

    def _stage(
        self, records: List[Record], pad_to: int = 0, lane_owner=None
    ) -> RecordBatch:
        n = len(records)
        # on TPU every batch pads to ONE canonical shape: invalid rows are
        # SIMD-masked and near-free, while each distinct pow2 bucket would
        # be its own multi-minute cold compile through the remote-compile
        # tunnel, serialized on the broker actor. CPU (tests) keeps tight
        # pow2 buckets — small batches there are latency-bound.
        if lane_owner is not None:
            # routed lanes stage at ONE fixed shape ([D, lane_slots] per
            # column) — one compiled routed program regardless of fill
            pad_to = max(pad_to, self._routed_lane_slots)
        if jax.default_backend() == "tpu":
            pad_to = max(pad_to, self._TPU_BATCH)
        size = max(_pow2(n), pad_to)
        v = self.num_vars
        # columnar fill: scalar columns are plain Python lists (C-speed
        # setitem per row, ONE numpy conversion per column at pack time)
        # — per-element numpy scalar writes were the measured host cost of
        # staging a serving wave. Payload matrices stay numpy: their rows
        # assign vectorized.
        cols: Dict[str, object] = {
            name: [default] * size
            for name, default in self._COL_DEFAULTS.items()
        }
        cols["v_vt"] = np.zeros((size, v), np.int8)
        cols["v_num"] = np.zeros((size, v), np.float32)
        cols["v_str"] = np.zeros((size, v), np.int32)
        staged_lazy = 0
        for i, record in enumerate(records):
            if type(record) is tuple:
                # lazy emission ref (_lazy_device_row admitted it): copy
                # the device columns straight from the readback batch —
                # payloads skip the columns→payload→columns round trip
                src, j = record[0].device_ref(record[1])
                self._stage_from_emission(cols, i, src, j)
                staged_lazy += 1
            else:
                self._stage_row(cols, i, record)
        if staged_lazy:
            _count_staged_columnar(staged_lazy)
        return self._pack_batch(cols, size, lane_owner=lane_owner)

    def _stage_from_emission(self, cols, i, src, j) -> None:
        """Stage one row by COPYING the backing emission batch's columns
        (the kernel emitted them; re-deriving via a materialized Record is
        the identity — pinned by the lazy-vs-eager log bit-identity test).
        Only the columns ``_stage_row`` would set for the value type are
        copied; everything else keeps the staging defaults (``src``,
        ``resp``, ``push`` are per-staging flags, never carried over)."""
        o, s, _epoch = src.device_source
        vt = s["vtype"][j]
        cols["valid"][i] = True
        cols["rtype"][i] = s["rtype"][j]
        cols["vtype"][i] = vt
        cols["intent"][i] = s["intent"][j]
        cols["key"][i] = s["key"][j]
        cols["req"][i] = s["req"][j]
        cols["req_stream"][i] = s["req_stream"][j]
        wf = s["wf"][j]
        if vt == int(ValueType.WORKFLOW_INSTANCE):
            cols["wf"][i] = wf
            cols["elem"][i] = s["elem"][j] if wf >= 0 else -1
            cols["instance_key"][i] = s["instance_key"][j]
            cols["scope_key"][i] = s["scope_key"][j]
        elif vt == int(ValueType.JOB):
            cols["type_id"][i] = s["type_id"][j]
            cols["retries"][i] = s["retries"][j]
            cols["deadline"][i] = s["deadline"][j]
            cols["worker"][i] = s["worker"][j]
            cols["aux_key"][i] = s["aux_key"][j]
            cols["instance_key"][i] = s["instance_key"][j]
            cols["wf"][i] = wf
            cols["elem"][i] = s["elem"][j] if wf >= 0 else -1
        # payload columns copy MASKED by the type column: zeros where no
        # variable is set — exactly what payload_to_columns(
        # columns_to_payload(...)) would produce (unset lanes must not
        # carry junk)
        vt_row = o["v_vt"][j]
        mask = vt_row != 0
        cols["v_vt"][i] = vt_row
        cols["v_num"][i] = np.where(mask, o["v_num"][j], 0)
        cols["v_str"][i] = np.where(mask, o["v_str"][j], 0)

    def _pack_batch(
        self, cols: Dict[str, object], size: int, lane_owner=None
    ) -> RecordBatch:
        """Scalar columns → one matrix per dtype family → one device_put
        each; the batch's per-column views are device slices (safe: the
        step program donates only the state argument, never the batch).

        ``lane_owner`` (resident routing, sharded-state v2) packs the same
        family matrices into a ``[num_shards, size]`` laned layout — the
        owner shard's lane carries the staged rows, every other lane holds
        the all-invalid staging defaults — and the put is lane-sharded
        over the mesh axis, so each device receives ONLY its own routed
        rows while the transfer count stays one per dtype family."""
        i64 = np.empty((size, len(self._I64_COLS)), np.int64)
        for j, name in enumerate(self._I64_COLS):
            i64[:, j] = cols[name]
        i32 = np.empty((size, len(self._I32_COLS)), np.int32)
        for j, name in enumerate(self._I32_COLS):
            i32[:, j] = cols[name]
        bools = np.empty((size, len(self._BOOL_COLS)), bool)
        for j, name in enumerate(self._BOOL_COLS):
            bools[:, j] = cols[name]
        # sharded-state routing accounting: record the staged row split
        # (residency basis: instance_key in resident mode, advisory key
        # hash otherwise) and the valid count — _run_step observes them
        # together with the wave's ACTUAL exchange volume, so idle waves
        # that dispatch zero records no longer inflate the exchange
        # counter (they still count as sharded waves).
        if self._mesh is not None:
            from zeebe_tpu.tpu import shard as shard_mod

            basis = (
                cols["instance_key"] if self._resident_mode else cols["key"]
            )
            self._last_stage_split = shard_mod.shard_row_counts_host(
                basis, cols["valid"], self._state_shards
            )
            self._last_stage_valid = int(
                np.count_nonzero(np.asarray(cols["valid"], bool))
            )
        # staged columns commit to THIS engine's mesh device (placement is
        # what routes the step program to it); sharded mode replicates
        # them over the span via _place-style NamedSharding (lane-sharded
        # in routed staging); default device otherwise
        kw: Dict[str, jax.Array] = {}
        if self._mesh is not None and lane_owner is not None:
            from jax.sharding import NamedSharding, PartitionSpec
            from zeebe_tpu.tpu import shard as shard_mod

            D = self._state_shards
            lane_spec = NamedSharding(
                self._mesh, PartitionSpec(shard_mod.STATE_AXIS)
            )
            put = lambda a: jax.device_put(a, lane_spec)  # noqa: E731
            i64_def = np.array(
                [self._COL_DEFAULTS[n] for n in self._I64_COLS], np.int64
            )
            i32_def = np.array(
                [self._COL_DEFAULTS[n] for n in self._I32_COLS], np.int32
            )
            i64_l = np.broadcast_to(i64_def, (D, size, i64_def.size)).copy()
            i32_l = np.broadcast_to(i32_def, (D, size, i32_def.size)).copy()
            bool_l = np.zeros((D, size, len(self._BOOL_COLS)), bool)
            i64_l[lane_owner] = i64
            i32_l[lane_owner] = i32
            bool_l[lane_owner] = bools
            i64_dev = put(i64_l)
            i32_dev = put(i32_l)
            bool_dev = put(bool_l)
            for j, name in enumerate(self._I64_COLS):
                kw[name] = i64_dev[:, :, j]
            for j, name in enumerate(self._I32_COLS):
                kw[name] = i32_dev[:, :, j]
            for j, name in enumerate(self._BOOL_COLS):
                kw[name] = bool_dev[:, :, j]
            for name in ("v_vt", "v_num", "v_str"):
                mat = cols[name]
                lanes = np.zeros((D,) + mat.shape, mat.dtype)
                lanes[lane_owner] = mat
                kw[name] = put(lanes)
            return RecordBatch(**kw)
        if self._mesh is not None:
            from jax.sharding import NamedSharding, PartitionSpec

            _repl = NamedSharding(self._mesh, PartitionSpec())
            put = lambda a: jax.device_put(a, _repl)  # noqa: E731
        else:
            put = (
                jnp.asarray if self.device is None
                else (lambda a: jax.device_put(a, self.device))
            )
        i64_dev = put(i64)
        i32_dev = put(i32)
        bool_dev = put(bools)
        for j, name in enumerate(self._I64_COLS):
            kw[name] = i64_dev[:, j]
        for j, name in enumerate(self._I32_COLS):
            kw[name] = i32_dev[:, j]
        for j, name in enumerate(self._BOOL_COLS):
            kw[name] = bool_dev[:, j]
        kw["v_vt"] = put(cols["v_vt"])
        kw["v_num"] = put(cols["v_num"])
        kw["v_str"] = put(cols["v_str"])
        return RecordBatch(**kw)

    def warm(self, sizes=(512,)) -> None:
        """Pre-compile the step program for the hot batch shapes BEFORE the
        partition serves: a cold kernel compile on the first drained batch
        otherwise blocks the broker actor for the whole compile (minutes
        over a remote-compile tunnel), and every client request meanwhile
        times out. The empty deployed set compiles to the same padded
        graph shapes as small real deployments, so these cache entries
        serve production traffic."""
        if self.graph is None:
            self._recompile()
        if self.graph is None:
            return
        now = jnp.asarray(self.clock(), jnp.int64)
        for n in sizes:
            batch = self._stage([], pad_to=n)
            # zero valid rows: a semantic no-op step that only compiles
            _out, _stats = self._run_step(batch, now)
        if self._resident_mode:
            # resident mode serves through TWO programs: the fallback just
            # warmed above (it takes the same flat batch shapes); warm the
            # routed program at its one laned shape too
            batch = self._stage([], lane_owner=0)
            _out, _stats = self._run_step(batch, now, lane_owner=0)
        jax.block_until_ready(self.state.ei_i32)

    def _run_step(self, batch: RecordBatch, now, lane_owner=None) -> tuple:
        """Launch ONE wave through the active step program — routed or
        fallback in resident mode (``lane_owner`` picks; the choice is
        host-side so the routed lowering never contains the fallback's
        gather), the v1 gathered program in sharded mode, kernel.step_jit
        otherwise — rebinding ``self.state`` and returning ``(out,
        stats)``. All programs are bit-identical by construction, so
        callers never branch on the mode."""
        pid = jnp.asarray(self.partition_id, jnp.int32)
        if self._resident_mode:
            program = (
                self._state_step_routed
                if lane_owner is not None
                else self._state_step_fallback
            )
            self.state, out, stats = program(
                self.graph, self.state, batch, now, pid
            )
        elif self._state_step is not None:
            self.state, out, stats = self._state_step(
                self.graph, self.state, batch, now, pid
            )
        else:
            self.state, out, stats = kernel.step_jit(
                self.graph, self.state, batch, now, partition_id=pid
            )
        if self._mesh is not None:
            from zeebe_tpu.runtime import metrics as metrics_mod
            from zeebe_tpu.tpu import shard as shard_mod

            n_valid = self._last_stage_valid
            # exchange model per wave KIND — and zero when the wave
            # dispatched zero records: an idle/warm step moves no table
            # or boundary data worth accounting (satellite fix; the
            # gathered program still lowers its gathers, but capacity
            # planning reads demand, not compilation artifacts)
            if not n_valid:
                xb = 0
            elif self._resident_mode and lane_owner is not None:
                xb = shard_mod.routed_exchange_bytes(out, self._state_shards)
            elif self._resident_mode:
                xb = self._fallback_exchange_bytes
            else:
                xb = self._shard_exchange_bytes
            split = self._last_stage_split
            single_lane = self._resident_mode and lane_owner is not None
            if single_lane:
                split = np.zeros(self._state_shards, np.int64)
                split[int(lane_owner)] = n_valid
            metrics_mod.observe_sharded_wave(
                split, xb, single_lane=single_lane
            )
            self.sharded_waves += 1
            self.last_shard_fill = tuple(int(x) for x in split)
            if self._resident_mode and n_valid:
                if lane_owner is not None:
                    self.routed_waves += 1
                else:
                    self.fallback_waves += 1
        return out, stats

    def _stage_row(self, cols, i, record: Record) -> None:
        md = record.metadata
        vt = int(md.value_type)
        cols["valid"][i] = True
        cols["rtype"][i] = int(md.record_type)
        cols["vtype"][i] = vt
        cols["intent"][i] = int(md.intent)
        cols["key"][i] = record.key
        cols["req"][i] = md.request_id
        cols["req_stream"][i] = md.request_stream_id
        value = record.value
        if vt == int(ValueType.WORKFLOW_INSTANCE):
            wf_slot = self.meta.slot(value.workflow_key)
            if (
                int(md.record_type) == int(RecordType.COMMAND)
                and int(md.intent) == int(WI.CREATE)
            ):
                workflow = self._resolve_workflow(value)
                wf_slot = self.meta.slot(workflow.key) if workflow else -1
            cols["wf"][i] = wf_slot
            if wf_slot >= 0 and value.activity_id:
                cols["elem"][i] = self.meta.elem_idx[wf_slot].get(
                    value.activity_id, -1
                )
            cols["instance_key"][i] = value.workflow_instance_key
            cols["scope_key"][i] = value.scope_instance_key
            self._stage_payload(cols, i, value.payload)
        elif vt == int(ValueType.JOB):
            cols["type_id"][i] = self.interns.intern(value.type) if value.type else 0
            cols["retries"][i] = value.retries
            cols["deadline"][i] = value.deadline
            cols["worker"][i] = (
                self.interns.intern(value.worker) if value.worker else 0
            )
            headers = value.headers
            cols["aux_key"][i] = headers.activity_instance_key
            cols["instance_key"][i] = headers.workflow_instance_key
            wf_slot = self.meta.slot(headers.workflow_key)
            cols["wf"][i] = wf_slot
            if wf_slot >= 0 and headers.activity_id:
                cols["elem"][i] = self.meta.elem_idx[wf_slot].get(
                    headers.activity_id, -1
                )
            self._stage_payload(cols, i, value.payload)
        elif vt == int(ValueType.TIMER):
            cols["aux_key"][i] = value.activity_instance_key
            cols["instance_key"][i] = value.workflow_instance_key
            cols["deadline"][i] = value.due_date
            # the handler element (a boundary event or the catch element
            # itself) re-resolves from the owning instance's workflow —
            # TimerRecord carries no workflow reference
            if value.handler_element_id and self.meta is not None:
                wf_slot = self._wf_slot_of_instance(
                    value.activity_instance_key
                )
                if wf_slot >= 0:
                    cols["wf"][i] = wf_slot
                    cols["elem"][i] = self.meta.elem_idx[wf_slot].get(
                        value.handler_element_id, -1
                    )
        elif vt == int(ValueType.MESSAGE):
            self._stage_corr(cols, i, value.name, value.correlation_key)
            cols["deadline"][i] = value.time_to_live
            cols["aux2_key"][i] = (
                self.interns.intern(value.message_id) if value.message_id else 0
            )
            self._stage_payload(cols, i, value.payload)
        elif vt == int(ValueType.MESSAGE_SUBSCRIPTION):
            self._stage_corr(cols, i, value.message_name, value.correlation_key)
            cols["wf"][i] = value.workflow_instance_partition_id
            cols["instance_key"][i] = value.workflow_instance_key
            cols["aux_key"][i] = value.activity_instance_key
        elif vt == int(ValueType.WORKFLOW_INSTANCE_SUBSCRIPTION):
            self._stage_corr(
                cols, i, value.message_name, value.correlation_key
            )
            cols["wf"][i] = value.message_partition_id
            cols["aux2_key"][i] = value.message_partition_id
            cols["instance_key"][i] = value.workflow_instance_key
            cols["aux_key"][i] = value.activity_instance_key
            self._stage_payload(cols, i, value.payload)

    def _wf_slot_of_instance(self, key: int) -> int:
        """Workflow slot of a live device element instance (host-side scan;
        timer creates are rare control records)."""
        if key < 0:
            return -1
        keys = np.asarray(self.state.ei_i64[:, 0])
        hits = np.nonzero(keys == key)[0]
        if not len(hits):
            return -1
        return int(np.asarray(self.state.ei_i32)[int(hits[0]), state_mod.EI_WF])

    def _stage_corr(self, cols, i, name: str, correlation_key) -> None:
        """Message-family correlation columns: type_id = interned name,
        retries = correlation value type, worker = correlation bits."""
        from zeebe_tpu.tpu.conditions import VT_NUM, VT_STR

        cols["type_id"][i] = self.interns.intern(name) if name else 0
        if isinstance(correlation_key, str) and correlation_key:
            cols["retries"][i] = int(VT_STR)
            cols["worker"][i] = self.interns.intern(correlation_key)
        elif isinstance(correlation_key, (int, float)):
            cols["retries"][i] = int(VT_NUM)
            cols["worker"][i] = int(
                np.float32(float(correlation_key)).view(np.int32)
            )

    def _stage_payload(self, cols, i, payload) -> None:
        if not payload:
            return
        try:
            vt, num, sid = rb.payload_to_columns(
                payload, self._var_column, self.interns, self.num_vars
            )
        except rb.PayloadError:
            if int(cols["rtype"][i]) == int(RecordType.COMMAND_REJECTION):
                # a rejection record echoes the offending command's payload
                # (e.g. a non-f32-exact number) — it is terminal for the
                # kernel, so it stages with an empty payload instead of
                # re-tripping the payload contract it reported
                return
            raise
        cols["v_vt"][i] = vt
        cols["v_num"][i] = num
        cols["v_str"][i] = sid

    def _resolve_workflow(self, value: WorkflowInstanceRecord):
        if value.workflow_key > 0:
            return self.repository.by_key.get(value.workflow_key)
        if value.version > 0:
            return self.repository.by_id_and_version(
                value.bpmn_process_id, value.version
            )
        return self.repository.latest(value.bpmn_process_id)

    # -- device round -------------------------------------------------------
    def _dispatch_device(
        self, records: List, positions: List[int],
        metas: "Optional[List[tuple]]" = None,
        route=None,
    ) -> _PendingSegment:
        """Host pre-work + staging + kernel launch for one device segment;
        returns the pending segment WITHOUT synchronizing on the device
        (overflow check and emission fetch happen in ``_collect_device``).

        ``records`` entries may be lazy ``(batch, idx)`` refs (admitted by
        ``_lazy_device_row``); ``metas`` carries each entry's
        ``(value_type, record_type, intent)`` so the host-side scans below
        never materialize a row just to filter on it."""
        if metas is None:
            metas = []
            for record in records:
                md = record.metadata
                metas.append(
                    (int(md.value_type), int(md.record_type), int(md.intent))
                )
        results = [ProcessingResult() for _ in records]
        # Job-incident bookkeeping lives in the host engine (incident records
        # are host-processed); run the oracle's _incident_on_job_event for
        # the corresponding JOB events flowing through the device. For
        # FAILED-with-no-retries the HOST emits the follow-up — either the
        # incident CREATE (stamped with the failure event's position) or,
        # when the failure event was re-written by an incident RESOLVE
        # (metadata.incident_key set), the RESOLVE_FAILED event. The
        # kernel's own unconditional incident-CREATE emission for these
        # rows is suppressed below (it cannot see the incident_key).
        # (Lazy refs never match: _lazy_device_row excludes these intents.)
        suppress_incident_create: set = set()
        for i, (vt, rt, intent) in enumerate(metas):
            if vt != int(ValueType.JOB) or rt != int(RecordType.EVENT):
                continue
            if intent == int(JI.FAILED):
                record = _as_record(records[i])
                if record.value.retries <= 0:
                    # mutates the oracle's incident maps outside
                    # host.process
                    self._host.snapshot_mark_dirty(
                        ("h/incidents", "h/control")
                    )
                    self._host._incident_on_job_event(record, results[i])
                    suppress_incident_create.add(i)
            elif intent in (int(JI.RETRIES_UPDATED), int(JI.CANCELED)):
                record = _as_record(records[i])
                self._host.snapshot_mark_dirty(("h/incidents", "h/control"))
                self._host._incident_on_job_event(record, results[i])
        # CREATE commands with unknown workflows are rejected host-side,
        # mirroring CreateWorkflowInstanceEventProcessor's rejection
        rejected = set()
        for i, (vt, rt, intent) in enumerate(metas):
            if (
                vt == int(ValueType.WORKFLOW_INSTANCE)
                and rt == int(RecordType.COMMAND)
                and intent == int(WI.CREATE)
            ):
                record = _as_record(records[i])
                if self._resolve_workflow(record.value) is None:
                    md = record.metadata
                    value = record.value.copy()
                    value.workflow_instance_key = self._next_wf_key_host()
                    rejection = Record(
                        key=record.key,
                        source_record_position=record.position,
                        metadata=RecordMetadata(
                            record_type=RecordType.COMMAND_REJECTION,
                            value_type=ValueType.WORKFLOW_INSTANCE,
                            intent=int(WI.CREATE),
                            rejection_type=RejectionType.BAD_VALUE,
                            rejection_reason="Workflow is not deployed",
                            request_id=md.request_id,
                            request_stream_id=md.request_stream_id,
                        ),
                        value=value,
                    )
                    results[i].written.append(rejection)
                    results[i].responses.append(rejection)
                    rejected.add(i)

        seg = _PendingSegment(
            results=results,
            positions=positions,
            live=[i for i in range(len(records)) if i not in rejected],
            suppress=suppress_incident_create,
        )
        live = seg.live
        if not live:
            return seg
        seg.seq = self._dispatch_seq
        self._dispatch_seq += 1
        lane_owner = None
        if self._routing_active():
            if route is not None and route[0] == "ik":
                lane_owner = route[1]
            elif route is not None and route[0] == "create":
                # all-CREATE segment: the first live CREATE allocates the
                # NEXT counter value as its root key (the rejection scan
                # above already advanced the counter for rejected rows),
                # and every follow-on allocation of the segment lands in
                # the same owner's block. One blocking scalar read — the
                # cost of making CREATE waves routable without a mirror
                # of the kernel's allocation arithmetic.
                from zeebe_tpu.tpu import shard as shard_mod

                key0 = int(np.asarray(self.state.next_wf_key))
                lane_owner = int(
                    shard_mod.shard_of_key_host(key0, self._state_shards)
                )
            if lane_owner is not None and len(live) > self._routed_lane_cap():
                lane_owner = None
                self.routed_overflows += 1
            if lane_owner is None:
                # gathered fallback allocates follow-up rows at GLOBAL
                # free slots — the instances it steps can no longer be
                # proven block-resident. Host-provable keys pop at
                # dispatch so later segments never route on them; rows
                # whose key the host CANNOT prove (e.g. client job
                # commands with default headers — exactly what forced
                # the fallback) resolve at collect, when the kernel's
                # emissions name them (seg.fb_pop), and routing holds
                # off until then (seg.blind). CREATE rows are exempt
                # from blindness: their keys are freshly allocated, so
                # no pre-existing residency entry can go stale.
                seg.fb_pop = True
                for i in live:
                    vt_i, rt_i, it_i = metas[i]
                    ik = self._instance_key_of(
                        records[i], type(records[i]) is tuple, vt_i
                    )
                    if ik is not None and ik >= 0:
                        self._resident.pop(int(ik), None)
                        self._residency_invalid[int(ik)] = seg.seq
                    elif not (
                        vt_i == int(ValueType.WORKFLOW_INSTANCE)
                        and rt_i == int(RecordType.COMMAND)
                        and it_i == int(WI.CREATE)
                    ):
                        seg.blind = True
                if seg.blind:
                    self._blind_fb_inflight += 1
        seg.route_owner = lane_owner
        batch = self._stage(
            [records[i] for i in live], lane_owner=lane_owner
        )
        now = jnp.asarray(self.clock(), jnp.int64)
        # re-derive the fallback maps before the key window can wrap past
        # the direct-mapped index capacity (see rebuild_lookup_state).
        # Conservative host-side bound — one record can allocate up to
        # emit_width keys (parallel split / multi-instance fan-out), each
        # advancing the counter by the stride (5) — so the serving path
        # pays no device sync. Resident mode skips the cadence entirely:
        # BOTH its step programs rebuild the lookup structures in-program
        # every wave, so no at-rest window can go stale.
        if not self._resident_mode:
            fanout = max(
                1, self.graph.emit_width if self.graph is not None else 1
            )
            self._keys_at_rebuild += 5 * fanout * len(live)
            if self._keys_at_rebuild > self.state.ei_index.shape[0] // 4:
                self.state = state_mod.rebuild_lookup_state(self.state)
                self._keys_at_rebuild = 0
        self._mark_device_dirty()  # a kernel step may write any table
        out, stats = self._run_step(batch, now, lane_owner=lane_owner)
        seg.out = out
        seg.stats = stats
        return seg

    def _collect_device(self, seg: _PendingSegment) -> float:
        """Synchronize on one dispatched segment: overflow check + ONE
        bulk device→host fetch of the whole emission batch, then columnar
        decode into the segment's per-record results. Returns the seconds
        spent blocked on the device (the host/device time-split metric)."""
        import time as _time

        if seg.out is None:
            return 0.0
        t0 = _time.perf_counter()
        if bool(seg.stats["overflow"]):
            raise RuntimeError(
                "device table overflow — raise TpuPartitionEngine capacity"
            )
        o = jax.device_get(seg.out)
        # collection is one-shot: clear the device refs BEFORE decoding so
        # a re-collect of this wave (the drain's finally path after an
        # exception elsewhere) can never append duplicate emissions into
        # seg.results
        seg.out = None
        seg.stats = None
        waited = _time.perf_counter() - t0
        if seg.route_owner is not None:
            self._note_residency(o, seg.route_owner, seg.seq)
        elif seg.fb_pop:
            self._pop_residency_fallback(o, seg.seq)
            if seg.blind:
                self._blind_fb_inflight -= 1
        if self._residency_invalid:
            # collects run in dispatch order: an invalidation at/before
            # this seq can no longer suppress any future note
            self._residency_invalid = {
                k: s
                for k, s in self._residency_invalid.items()
                if s > seg.seq
            }
        self._emit_records(
            o, [seg.positions[i] for i in seg.live], seg.results, seg.live,
            seg.suppress,
        )
        return waited

    def _next_wf_key_host(self) -> int:
        """Allocate a workflow key host-side, keeping the device counter in
        sync (rejections consume a key in the oracle too)."""
        key = int(np.asarray(self.state.next_wf_key))
        self._mark_device_dirty("keys")
        self.state = dataclasses.replace(
            self.state,
            next_wf_key=self.state.next_wf_key + 5,
        )
        return key

    # -- emission → host records -------------------------------------------
    def _emit_records(
        self,
        out: RecordBatch,
        src_positions: List[int],
        results: List[ProcessingResult],
        live_rows: List[int],
        suppress_incident_create: "set | None" = None,
    ) -> None:
        """Decode one emission batch (``out``: np-array RecordBatch — the
        caller's single bulk ``device_get``) into Record objects. Columnar:
        scalar columns convert to Python lists ONCE (`.tolist()`); rows
        materialize lazily from those lists only up to the valid count."""
        from zeebe_tpu.protocol.intents import (
            IncidentIntent,
            MessageSubscriptionIntent as MS,
            WorkflowInstanceSubscriptionIntent as WS,
        )

        from zeebe_tpu.protocol.columnar import ColumnarBatch

        o = {f.name: np.asarray(getattr(out, f.name)) for f in dataclasses.fields(out)}
        count = int(o["valid"].sum())
        if not count:
            return
        # per-row int(np_scalar) dominated readback CPU at serving wave
        # sizes; one C-level tolist per column replaces them all
        cols = {
            k: v[:count].tolist() for k, v in o.items() if v.ndim == 1
        }
        # bind THIS compile's meta into the lazy closures: a later
        # redeploy replaces self.meta, but the slots in these columns
        # index the graph that emitted them
        meta = self.meta
        names = meta.varspace.names
        srcs = cols["src"]
        sources = [
            src_positions[s] if 0 <= s < len(src_positions) else -1
            for s in srcs
        ]
        producer_d, incident_d, rejtype_d = _frame_defaults()
        # the readback decodes into a COLUMNAR batch carrying the FULL
        # frame-column set plus a value-only builder: plain follow-up rows
        # flow to LogStream.append as lazy refs and encode from columns +
        # built values — no Record/metadata objects on the append edge —
        # and later re-STAGE from these very columns (_stage_from_
        # emission). Only rows that need objects now (sends, responses,
        # pushes, rejections, incident fixups) materialize here.
        emission = ColumnarBatch(
            count,
            {
                "key": cols["key"],
                "record_type": cols["rtype"],
                "value_type": cols["vtype"],
                "intent": cols["intent"],
                "request_id": cols["req"],
                "request_stream_id": cols["req_stream"],
                "source_record_position": sources,
                "producer_id": [producer_d] * count,
                "incident_key": [incident_d] * count,
                "rejection_type": [rejtype_d] * count,
                "rejection_reason": [""] * count,
                "raft_term": [0] * count,
            },
            materializer=lambda r: self._materialize(
                o, cols, r, names, sources, meta
            ),
            value_builder=lambda r: self._materialize_value(
                o, cols, r, names, meta
            ),
        )
        emission.device_source = (o, cols, self._meta_epoch)
        lazy_ok = self.lazy_emissions
        rt_cmd = int(RecordType.COMMAND)
        rt_rej = int(RecordType.COMMAND_REJECTION)
        for r in range(count):
            src = srcs[r]
            res = results[live_rows[src]] if 0 <= src < len(live_rows) else results[0]
            # cross-partition subscription commands are SENDS, not appended
            # records — exactly the oracle's out.sends channel
            # (SubscriptionCommandSender.java:96-108)
            vt = cols["vtype"][r]
            rt = cols["rtype"][r]
            intent = cols["intent"][r]
            if rt == rt_cmd and vt == int(
                ValueType.MESSAGE_SUBSCRIPTION
            ) and intent in (int(MS.OPEN), int(MS.CLOSE)):
                record = emission.row(r)
                target = self.partition_for_correlation_key(
                    record.value.correlation_key
                )
                record.source_record_position = -1  # sends are unstamped
                res.sends.append((target, record))
                continue
            if rt == rt_cmd and vt == int(
                ValueType.WORKFLOW_INSTANCE_SUBSCRIPTION
            ) and intent == int(WS.CORRELATE):
                record = emission.row(r)
                record.source_record_position = -1
                res.sends.append((cols["wf"][r], record))
                continue
            if (
                rt == rt_cmd
                and vt == int(ValueType.INCIDENT)
                and intent == int(IncidentIntent.CREATE)
            ):
                if (
                    suppress_incident_create
                    and 0 <= src < len(live_rows)
                    and live_rows[src] in suppress_incident_create
                ):
                    # job incidents are host-emitted (see _process_device:
                    # the host branches on metadata.incident_key, which
                    # the kernel cannot see) — drop the kernel's copy
                    continue
                record = emission.row(r)
                if (
                    record.value is not None
                    and record.value.failure_event_position < 0
                ):
                    # the oracle stamps the failing event's position into
                    # the CREATE command (it re-reads that record on
                    # RESOLVE and compaction pins it); the kernel only
                    # ships an error code, but the failing event IS this
                    # emission's source record
                    record.value.failure_event_position = (
                        record.source_record_position
                    )
                res.written.append(record)
                if cols["resp"][r] and cols["req"][r] >= 0:
                    res.responses.append(record)
                if cols["push"][r]:
                    res.pushes.append((cols["req_stream"][r], record))
                continue
            resp = cols["resp"][r] and cols["req"][r] >= 0
            push = cols["push"][r]
            if lazy_ok and not resp and not push and rt != rt_rej:
                # plain append: the row stays COLUMNS all the way into
                # the log tail (a (batch, idx) ref) — materialized only
                # if something later reads it as an object
                res.written.append((emission, r))
                continue
            record = emission.row(r)
            res.written.append(record)
            if resp:
                res.responses.append(record)
            if push:
                res.pushes.append((cols["req_stream"][r], record))

    def _materialize(self, o, cols, r, names, sources, meta) -> Record:
        """One emission row → Record. ``cols`` holds the scalar columns as
        Python lists (see _emit_records); ``o`` the 2D payload matrices;
        ``meta`` is the graph meta bound AT EMIT (slots in these columns
        index it, not whatever self.meta later becomes)."""
        vt = cols["vtype"][r]
        rt = cols["rtype"][r]
        rej = cols["rej"][r]
        value = self._materialize_value(o, cols, r, names, meta)

        md = RecordMetadata(
            record_type=RecordType(rt),
            value_type=ValueType(vt),
            intent=cols["intent"][r],
            request_id=cols["req"][r],
            request_stream_id=cols["req_stream"][r],
        )
        if rt == int(RecordType.COMMAND_REJECTION):
            md.rejection_type = (
                RejectionType.BAD_VALUE
                if rej == rb.REJ_RETRIES_NOT_POSITIVE
                else RejectionType.NOT_APPLICABLE
            )
            md.rejection_reason = rb.REJECTION_REASONS.get(rej, "")
            if vt == int(ValueType.MESSAGE) and rej == rb.REJ_MSG_DUP:
                md.rejection_type = RejectionType.BAD_VALUE
                md.rejection_reason = (
                    f"message with id '{value.message_id}' is already "
                    "published"
                )
        record = Record(key=cols["key"][r], metadata=md, value=value)
        record.source_record_position = sources[r]
        return record

    def _materialize_value(self, o, cols, r, names, meta):
        """One emission row → its typed ``RecordValue`` only (no
        Record/metadata wrapper) — the append-edge encode path for lazy
        rows builds exactly this and nothing more."""
        vt = cols["vtype"][r]
        rej = cols["rej"][r]
        wf_slot = cols["wf"][r]
        elem = cols["elem"][r]
        payload = rb.columns_to_payload(
            o["v_vt"][r], o["v_num"][r], o["v_str"][r], names, self.interns
        )
        workflow = (
            meta.workflows[wf_slot]
            if 0 <= wf_slot < len(meta.workflows)
            else None
        )
        elem_id = meta.element_id(wf_slot, elem)
        element = (
            workflow.elements[elem] if workflow and 0 <= elem < len(workflow.elements)
            else None
        )

        if vt == int(ValueType.WORKFLOW_INSTANCE):
            value = WorkflowInstanceRecord(
                bpmn_process_id=workflow.id if workflow else "",
                version=workflow.version if workflow else -1,
                workflow_key=workflow.key if workflow else -1,
                workflow_instance_key=cols["instance_key"][r],
                activity_id=elem_id,
                payload=payload,
                scope_instance_key=cols["scope_key"][r],
            )
        elif vt == int(ValueType.JOB):
            value = JobRecord(
                type=self.interns.string(cols["type_id"][r]) or "",
                retries=cols["retries"][r],
                deadline=cols["deadline"][r],
                worker=self.interns.string(cols["worker"][r]) or "",
                payload=payload,
                custom_headers=dict(element.job_headers) if element else {},
                headers=JobHeaders(
                    workflow_instance_key=cols["instance_key"][r],
                    bpmn_process_id=workflow.id if workflow else "",
                    workflow_definition_version=workflow.version if workflow else -1,
                    workflow_key=workflow.key if workflow else -1,
                    activity_id=elem_id,
                    activity_instance_key=cols["aux_key"][r],
                ),
            )
        elif vt == int(ValueType.INCIDENT):
            error_type, message = self._incident_error(o, r, element, payload, rej)
            value = IncidentRecord(
                error_type=int(error_type),
                error_message=message,
                bpmn_process_id=workflow.id if workflow else "",
                workflow_instance_key=cols["instance_key"][r],
                activity_id=elem_id,
                activity_instance_key=cols["aux_key"][r],
                job_key=cols["aux2_key"][r],
                payload=payload,
            )
        elif vt == int(ValueType.TIMER):
            value = TimerRecord(
                workflow_instance_key=cols["instance_key"][r],
                activity_instance_key=cols["aux_key"][r],
                due_date=cols["deadline"][r],
                handler_element_id=elem_id,
            )
        elif vt == int(ValueType.MESSAGE):
            from zeebe_tpu.protocol.records import MessageRecord

            value = MessageRecord(
                name=self.interns.string(cols["type_id"][r]) or "",
                correlation_key=self._corr_string(
                    cols["retries"][r], cols["worker"][r]
                ),
                time_to_live=max(cols["deadline"][r], 0),
                payload=payload,
                message_id=self.interns.string(cols["aux2_key"][r]) or "",
            )
        elif vt == int(ValueType.MESSAGE_SUBSCRIPTION):
            from zeebe_tpu.protocol.records import MessageSubscriptionRecord

            value = MessageSubscriptionRecord(
                workflow_instance_partition_id=cols["wf"][r],
                workflow_instance_key=cols["instance_key"][r],
                activity_instance_key=cols["aux_key"][r],
                message_name=self.interns.string(cols["type_id"][r]) or "",
                correlation_key=self._corr_string(
                    cols["retries"][r], cols["worker"][r]
                ),
            )
        elif vt == int(ValueType.WORKFLOW_INSTANCE_SUBSCRIPTION):
            from zeebe_tpu.protocol.records import (
                WorkflowInstanceSubscriptionRecord,
            )

            value = WorkflowInstanceSubscriptionRecord(
                workflow_instance_key=cols["instance_key"][r],
                activity_instance_key=cols["aux_key"][r],
                message_name=self.interns.string(cols["type_id"][r]) or "",
                payload=payload,
                message_partition_id=cols["aux2_key"][r],
                correlation_key=self._corr_string(
                    cols["retries"][r], cols["worker"][r]
                ),
            )
        else:
            value = None
        return value

    def _corr_string(self, cvt: int, cbits: int) -> str:
        """Correlation columns → the oracle's string form (numeric keys
        normalize to ``str(int(...))`` exactly like the oracle's
        ``str(corr_value)`` on an int payload value; bools to
        ``str(True/False)``)."""
        from zeebe_tpu.tpu.conditions import VT_BOOL, VT_STR

        if cvt == int(VT_STR):
            return self.interns.string(cbits) or ""
        if cvt == int(VT_BOOL):
            return str(bool(np.int32(cbits).view(np.float32)))
        if cvt == 0:
            return ""
        f = float(np.int32(cbits).view(np.float32))
        return str(int(f)) if f == int(f) else str(f)

    def _incident_error(self, o, r, element, payload, rej):
        """Reconstruct the oracle's exact incident error message by
        re-running the failing host evaluation (incidents are rare; the
        device only ships an error code)."""
        if rej == rb.ERR_CONDITION_NO_FLOW:
            return (
                ErrorType.CONDITION_ERROR,
                "All conditions evaluated to false and no default flow is set.",
            )
        if rej == rb.ERR_CONDITION_EVAL and element is not None:
            try:
                for flow in element.outgoing_with_condition:
                    evaluate_condition(flow.condition, payload)
            except ConditionEvalError as e:
                return ErrorType.CONDITION_ERROR, str(e)
            return ErrorType.CONDITION_ERROR, "condition evaluation failed"
        if rej in (rb.ERR_IO_MAPPING_IN, rb.ERR_IO_MAPPING_OUT) and element is not None:
            mappings = (
                element.input_mappings
                if rej == rb.ERR_IO_MAPPING_IN
                else element.output_mappings
            )
            try:
                if rej == rb.ERR_IO_MAPPING_IN:
                    extract(payload, mappings)
                else:
                    merge(payload, {}, mappings)
            except MappingError as e:
                return ErrorType.IO_MAPPING_ERROR, str(e)
            return ErrorType.IO_MAPPING_ERROR, "io mapping failed"
        if rej == _ERR_NO_RETRIES:
            return ErrorType.JOB_NO_RETRIES, "No more retries left."
        if rej == rb.ERR_CORRELATION_KEY:
            path = getattr(element, "correlation_key_path", "") if element else ""
            return (
                ErrorType.IO_MAPPING_ERROR,
                f"Failed to extract the correlation-key by '{path}'",
            )
        return ErrorType.UNKNOWN, ""
