"""HBM-resident open-addressing hash table: int64 key → int32 slot.

TPU-native analogue of the reference's off-heap hash maps
(``zb-map/src/main/java/io/zeebe/map/ZbMap.java:37`` — Long2Long maps over
bucket buffer arrays): the table is a pair of device arrays (keys, vals),
capacity a power of two, linear probing, batched vectorized operations:

- ``lookup``: gather-probe loop, all queries in parallel.
- ``insert``: deterministic parallel claims — per probe round, each pending
  insert scatters its batch rank onto its candidate bucket with
  ``scatter-min``; the unique winner writes, losers advance their probe.
  Assumes batch keys are unique (engine keys are monotone counters).
- ``delete``: probe to the key's bucket, write a tombstone.

Tombstones keep probe chains intact; the engine rebuilds the table
(``rebuild_from``) when live+dead load crosses ``REBUILD_LOAD`` — the
analogue of ZbMap's block splitting/shrinking (``ZbMap.java:45``).

All ops are jit-compatible and deterministic (scatter conflicts resolved by
batch rank, never by scheduling).
"""

from __future__ import annotations

import dataclasses
from functools import partial

import jax
import jax.numpy as jnp
from jax import lax

EMPTY = -1
TOMBSTONE = -2
MAX_PROBES = 32
REBUILD_LOAD = 0.45

_BIG = jnp.iinfo(jnp.int32).max


@partial(jax.tree_util.register_dataclass, data_fields=["keys", "vals"], meta_fields=[])
@dataclasses.dataclass
class HashTable:
    keys: jax.Array  # [T] int64; EMPTY / TOMBSTONE sentinels
    vals: jax.Array  # [T] int32


def make(capacity: int) -> HashTable:
    assert capacity & (capacity - 1) == 0, "capacity must be a power of two"
    return HashTable(
        keys=jnp.full((capacity,), EMPTY, dtype=jnp.int64),
        vals=jnp.zeros((capacity,), dtype=jnp.int32),
    )


def _hash(keys: jax.Array, table_size: int) -> jax.Array:
    # Multiplicative hash over the two 32-bit halves. TPUs have no native
    # 64-bit multiply (XLA emulates it with 32-bit mul chains — it showed
    # up in every probe-loop fusion); two u32 multiplies are native-cheap
    # and mix just as well for monotone-counter keys.
    lo = keys.astype(jnp.uint32)
    hi = (keys >> jnp.int64(32)).astype(jnp.uint32)
    h = lo * jnp.uint32(0x9E3779B1) ^ hi * jnp.uint32(0x85EBCA77)
    h = h ^ (h >> jnp.uint32(15))
    return (h & jnp.uint32(table_size - 1)).astype(jnp.int32)


def lookup(table: HashTable, keys: jax.Array, valid: jax.Array):
    """Batched lookup. Returns (found [B] bool, vals [B] i32)."""
    table_size = table.keys.shape[0]
    h0 = _hash(keys, table_size)

    def cond(carry):
        i, _, _, done = carry
        # early exit: at sane load factors chains are 1-3 buckets long, and
        # each probe round is a full gather pass — don't run all MAX_PROBES
        return (i < MAX_PROBES) & jnp.any(~done)

    def body(carry):
        i, found, vals, done = carry
        idx = (h0 + i) & (table_size - 1)
        k = table.keys[idx]
        hit = (~done) & (k == keys)
        found = found | hit
        vals = jnp.where(hit, table.vals[idx], vals)
        # an EMPTY bucket terminates the chain; TOMBSTONE does not
        done = done | hit | (k == EMPTY)
        return i + 1, found, vals, done

    found = jnp.zeros(keys.shape, dtype=bool)
    vals = jnp.full(keys.shape, -1, dtype=jnp.int32)
    done = ~valid
    _, found, vals, _ = lax.while_loop(
        cond, body, (jnp.zeros((), jnp.int32), found, vals, done)
    )
    return found, vals


def insert(table: HashTable, keys: jax.Array, vals: jax.Array, valid: jax.Array):
    """Batched insert of unique keys. Returns (table', inserted [B] bool).

    ``inserted`` is False for entries that could not be placed within
    MAX_PROBES (over-full table) — the engine must rebuild larger then.
    """
    table_size = table.keys.shape[0]
    batch = keys.shape[0]
    vals = vals.astype(jnp.int32)
    h0 = _hash(keys, table_size)
    rank = jnp.arange(batch, dtype=jnp.int32)

    def cond(carry):
        i, _, _, pending, _ = carry
        return (i < MAX_PROBES) & jnp.any(pending)

    def body(carry):
        i, tkeys, tvals, pending, probe = carry
        idx = (h0 + probe) & (table_size - 1)
        # claim EMPTY *or* TOMBSTONE buckets (standard open addressing):
        # delete-heavy tables (parallel joins insert+delete per instance)
        # otherwise fill with tombstones until no bucket is claimable and
        # inserts silently fail mid-workload
        free = tkeys[idx] < 0
        attempt = pending & free
        # deterministic bucket claim: lowest batch rank wins
        order = jnp.where(attempt, rank, _BIG)
        claims = jnp.full((table_size,), _BIG, dtype=jnp.int32).at[idx].min(
            order, mode="drop"
        )
        win = attempt & (claims[idx] == rank)
        widx = jnp.where(win, idx, table_size)
        tkeys = tkeys.at[widx].set(keys, mode="drop")
        tvals = tvals.at[widx].set(vals, mode="drop")
        pending = pending & ~win
        probe = jnp.where(pending, probe + 1, probe)
        return i + 1, tkeys, tvals, pending, probe

    probe = jnp.zeros((batch,), dtype=jnp.int32)
    _, tkeys, tvals, pending, _ = lax.while_loop(
        cond, body,
        (jnp.zeros((), jnp.int32), table.keys, table.vals, valid, probe),
    )
    return HashTable(tkeys, tvals), valid & ~pending


def delete(table: HashTable, keys: jax.Array, valid: jax.Array) -> HashTable:
    """Batched delete: the key's bucket becomes a tombstone."""
    table_size = table.keys.shape[0]
    h0 = _hash(keys, table_size)

    def cond(carry):
        i, _, done = carry
        return (i < MAX_PROBES) & jnp.any(~done)

    def body(carry):
        i, slot, done = carry
        idx = (h0 + i) & (table_size - 1)
        k = table.keys[idx]
        hit = (~done) & (k == keys)
        slot = jnp.where(hit, idx, slot)
        done = done | hit | (k == EMPTY)
        return i + 1, slot, done

    slot = jnp.full(keys.shape, table_size, dtype=jnp.int32)
    _, slot, _ = lax.while_loop(
        cond, body, (jnp.zeros((), jnp.int32), slot, ~valid)
    )
    tkeys = table.keys.at[slot].set(TOMBSTONE, mode="drop")
    return HashTable(tkeys, table.vals)


def rebuild_from(capacity: int, keys: jax.Array, vals: jax.Array, valid: jax.Array):
    """Fresh table from live entries (tombstone purge / growth).

    Returns (table, all_inserted bool scalar).
    """
    table = make(capacity)
    table, inserted = insert(table, keys, vals, valid)
    return table, jnp.all(inserted == valid)


def fill_counts(table: HashTable):
    """(live, dead) bucket counts — host uses these to decide on rebuilds."""
    live = jnp.sum(table.keys >= 0)
    dead = jnp.sum(table.keys == TOMBSTONE)
    return live, dead
