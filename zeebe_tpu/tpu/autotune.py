"""Engine-boot autotune: pick pallas vs XLA per table-op family on the
RUNNING libtpu build.

PERF_NOTES round 4: the fast path for the step kernel's table ops is
BUILD-dependent — libtpu builds that lower general scatters to serial
per-index programs need the pallas serial passes, builds with the
DMA-pipelined scatter/gather lowering are faster through plain XLA, and
the winner has flipped between builds. A static env default (the old
``ZB_PALLAS`` switch) is therefore wrong half the time; this module A/Bs
both paths per op family with a dependent-chain microbench ONCE at engine
boot and installs the winners in ``pallas_ops``' dispatch table.

Rules that shape the measurement (all empirical, see PERF_NOTES):
- chains must be DEPENDENT (each op consumes the previous op's output) —
  isolated op timing is pipelined and lies ~20x;
- decisions cache on disk keyed by a build fingerprint (jax/jaxlib
  versions + device kind + platform version), so a fleet restart pays the
  microbench once per build, not once per boot;
- ``ZB_PALLAS=0/1`` remains the manual override (checked inside
  ``pallas_ops.use_pallas``, so a tuned table never shadows it), and
  ``ZB_AUTOTUNE=0`` skips tuning entirely (keeps the defaults);
- off-TPU this is a no-op: Mosaic is TPU-only and ``use_pallas`` already
  pins every family to the XLA fallbacks there.
"""

from __future__ import annotations

import hashlib
import json
import os
import time
from typing import Callable, Dict, Optional

import numpy as np

import jax
import jax.numpy as jnp

from zeebe_tpu.tpu import hashmap, jit_registry, pallas_ops as pops

_CHAIN = 8   # dependent ops per timed call (amortizes dispatch overhead)
_REPS = 5    # timed repetitions; min is the reported cost
_MARGIN = 1.05  # pallas must beat XLA by >5% to win (ties keep XLA: one
# fewer Mosaic program to trust on an unproven build)

_T = 1 << 12  # table rows for the probes
_B = 1 << 11  # batch per op
_K = 16       # row width

_state: Dict[str, object] = {"done": False, "source": "default"}


def dispatch_source() -> str:
    """Where the current dispatch came from: ``default`` (never tuned),
    ``env`` (ZB_PALLAS override), ``cache`` (fingerprint hit), or
    ``measured`` (microbench ran this boot)."""
    return str(_state["source"])


def build_fingerprint() -> str:
    """Identity of the (jax, jaxlib, libtpu/device) combination a cached
    decision table is valid for."""
    import jaxlib

    try:
        dev = jax.devices()[0]
        kind = f"{dev.platform}:{getattr(dev, 'device_kind', '?')}"
    except Exception:  # noqa: BLE001 - no backend at all
        kind = "none"
    parts = f"{jax.__version__}|{jaxlib.__version__}|{kind}"
    try:
        parts += f"|{jax.extend.backend.get_backend().platform_version}"
    except Exception:  # noqa: BLE001 - platform_version is best-effort
        pass
    return parts


def _cache_path() -> str:
    root = os.environ.get(
        "ZB_AUTOTUNE_CACHE",
        os.path.join(os.path.expanduser("~"), ".cache", "zbtpu"),
    )
    digest = hashlib.sha256(build_fingerprint().encode()).hexdigest()[:16]
    return os.path.join(root, f"autotune-{digest}.json")


def _load_cache() -> Optional[dict]:
    try:
        with open(_cache_path()) as f:
            doc = json.load(f)
    except (OSError, ValueError):
        return None
    if doc.get("fingerprint") != build_fingerprint():
        return None
    decisions = doc.get("decisions")
    if not isinstance(decisions, dict):
        return None
    return doc


def _save_cache(decisions: dict, timings: dict) -> None:
    path = _cache_path()
    try:
        os.makedirs(os.path.dirname(path), exist_ok=True)
        with open(path, "w") as f:
            json.dump(
                {
                    "fingerprint": build_fingerprint(),
                    "decisions": decisions,
                    "timings_us": timings,
                },
                f,
                indent=2,
            )
    except OSError:
        pass  # cache is an optimization, never fatal


def _time(fn: Callable[[], object]) -> float:
    """Best-of-N wall time of ``fn`` (compiles on the first call)."""
    jax.block_until_ready(fn())
    best = float("inf")
    for _ in range(_REPS):
        t0 = time.perf_counter()
        jax.block_until_ready(fn())
        best = min(best, time.perf_counter() - t0)
    return best


def _operands(rng: np.random.Generator):
    tbl = jnp.asarray(rng.integers(0, 100, (_T, _K)), jnp.int32)
    t1 = jnp.asarray(rng.integers(0, 100, (_T,)), jnp.int32)
    t64 = jnp.asarray(rng.integers(0, 1 << 40, (_T,)), jnp.int64)
    slots = jnp.asarray(rng.integers(0, _T, (_B,)), jnp.int32)
    active = jnp.asarray(rng.random(_B) < 0.7)
    vals = jnp.asarray(rng.integers(0, 1000, (_B, _K)), jnp.int32)
    mask = jnp.asarray(rng.random((_B, _K)) < 0.3)
    keys = jnp.asarray(
        rng.choice(np.arange(1, 10 * _T, 5, dtype=np.int64), _B, replace=False)
    )
    return tbl, t1, t64, slots, active, vals, mask, keys


def _benches() -> Dict[str, Callable[[], object]]:
    """family -> jit-able dependent-chain workload. Each chain feeds the
    previous op's output table into the next op, so per-op cost cannot
    hide behind pipelining."""
    rng = np.random.default_rng(23)
    tbl, t1, t64, slots, active, vals, mask, keys = _operands(rng)
    lvals = jnp.asarray(rng.integers(0, 9, (_B,)), jnp.int32)
    v64 = keys + 7

    def row_update(t=tbl):
        for i in range(_CHAIN):
            t = pops.masked_row_update(t, slots, active, vals + i, mask)
        return t

    def row_max(t=tbl):
        for i in range(_CHAIN):
            t = pops.masked_row_max(t, slots, active, vals + i)
        return t

    def row_add(t=tbl):
        for i in range(_CHAIN):
            t = pops.masked_row_add(t, slots, active, vals + i, mask)
        return t

    def lane(t=t1):
        for i in range(_CHAIN):
            t = pops.masked_lane_update(t, slots, active, lvals + i)
        return t

    def vec64(t=t64):
        for i in range(_CHAIN):
            t = pops.masked_vec64_update(t, slots, active, v64 + i)
        return t

    def lookup():
        table, _ = hashmap.insert(
            hashmap.make(_T * 2), keys, jnp.arange(_B, dtype=jnp.int32),
            jnp.ones((_B,), bool),
        )
        probe = keys
        acc = jnp.int32(0)
        for _ in range(_CHAIN):
            found, slot = pops.lookup(table, probe + acc, active)
            acc = jnp.max(jnp.where(found, slot, 0))
        return acc

    def insert():
        table = hashmap.make(_T * 4)
        for i in range(_CHAIN):
            table, ok = pops.insert(
                table, keys + i, jnp.arange(_B, dtype=jnp.int32), active
            )
        return table.keys

    def delete():
        table, _ = hashmap.insert(
            hashmap.make(_T * 4), keys, jnp.arange(_B, dtype=jnp.int32),
            jnp.ones((_B,), bool),
        )
        for i in range(_CHAIN):
            table = pops.delete(table, keys + i, active)
        return table.keys

    def gather(t=tbl, r=t1):
        # representative phase-B shape: several row reads off 2D tables
        # plus lane reads off a 1D table, chained through the gathered rows
        s = slots
        for _ in range(_CHAIN // 2):
            rows_a, rows_b, lanes = pops.fused_gather_rows(
                [t, r],
                [pops.GatherOp(0, s),
                 pops.GatherOp(0, (s + 1) % _T),
                 pops.GatherOp(1, s)],
            )
            s = (jnp.max(rows_a, axis=1) + jnp.max(rows_b, axis=1)
                 + lanes) % _T
        return s

    def emit(t=tbl):
        # representative phase-C shape: queue compaction — one packed row
        # take at a data-dependent permutation, chained through the output
        for _ in range(_CHAIN):
            order = jnp.argsort(t[:_B, 0], stable=True).astype(jnp.int32)
            (taken,) = pops.fused_gather_rows(
                [t], [pops.GatherOp(0, order)], family="emit"
            )
            t = t.at[:_B].set(taken + 1)
        return t

    def fused(t=tbl, r=t1):
        # representative phase-E shape: mixed set/add/max rows + a lane
        # write, chained through the output tables
        for i in range(_CHAIN // 2):
            ops = [
                pops.TableOp(0, "add", slots, active, vals + i, mask),
                pops.TableOp(0, "set", slots, active, vals + i, mask),
                pops.TableOp(0, "max", slots, active, vals + i),
                pops.TableOp(1, "set", slots, active, lvals + i),
            ]
            t, r = pops.fused_table_commit([t, r], ops)
        return t

    return {
        "row_update": row_update,
        "row_max": row_max,
        "row_add": row_add,
        "lane": lane,
        "vec64": vec64,
        "lookup": lookup,
        "insert": insert,
        "delete": delete,
        "fused": fused,
        "gather": gather,
        "emit": emit,
    }


def audit_candidates() -> Dict[str, Callable]:
    """Register and return one jitted program per microbench family, for
    ``tools/zbaudit`` to lower and audit. ``measure()`` registers the
    ``.xla``/``.pallas`` timing arms only when it actually runs; this
    enumerates the same workloads without timing anything."""
    return {
        family: jit_registry.register_jit(
            f"autotune.{family}",
            fn,
            max_signatures=1,
            notes="boot microbench candidate; carries no engine state",
        )
        for family, fn in _benches().items()
    }


def measure(progress: Optional[Callable[[str], None]] = None):
    """Run the per-family A/B microbench on the current backend. Returns
    (decisions, timings_us) — decisions maps family -> use pallas."""
    decisions: Dict[str, bool] = {}
    timings: Dict[str, dict] = {}
    benches = _benches()
    for family, fn in benches.items():
        # two jit instances so each dispatch arm traces (and caches) its
        # own program — a shared cache would reuse the first arm's trace
        jitted_x = jit_registry.register_jit(
            f"autotune.{family}.xla", fn, max_signatures=1,
            notes="boot microbench candidate (XLA arm); no state args",
        )
        jitted_p = jit_registry.register_jit(
            f"autotune.{family}.pallas", fn, max_signatures=1,
            notes="boot microbench candidate (pallas arm); no state args",
        )
        if family == "fused":
            # the fused baseline is the UNFUSED chain under the already-
            # tuned per-family winners — with the fused family pinned OFF
            # explicitly: a missing "fused" key defaults to pallas, which
            # would time the mega-pass against itself and silently lose
            # every A/B
            prev = pops.get_dispatch()
            pops.set_dispatch({**decisions, "fused": False})
            try:
                t_xla = _time(jitted_x)
            finally:
                pops.set_dispatch(prev)
        else:
            with pops.forced("xla"):
                t_xla = _time(jitted_x)
        with pops.forced("pallas"):
            try:
                t_pal = _time(jitted_p)
            except Exception as e:  # noqa: BLE001 - a Mosaic lowering that
                # fails to compile on this build simply loses the A/B
                t_pal = float("inf")
                timings.setdefault(family, {})["pallas_error"] = repr(e)[:200]
        win = t_pal * _MARGIN < t_xla
        decisions[family] = bool(win)
        timings.setdefault(family, {}).update(
            xla_us=round(t_xla * 1e6, 1),
            pallas_us=(None if t_pal == float("inf")
                       else round(t_pal * 1e6, 1)),
        )
        if progress:
            progress(
                f"autotune {family}: xla {t_xla*1e6:.0f}us "
                f"pallas {t_pal*1e6:.0f}us -> "
                f"{'pallas' if win else 'xla'}"
            )
    return decisions, timings


def ensure_autotuned(
    progress: Optional[Callable[[str], None]] = None, force: bool = False
) -> dict:
    """Idempotent boot hook: install per-family dispatch decisions for the
    running build (cache hit or fresh measurement). Called from
    ``TpuPartitionEngine.__init__`` and bench.py; cheap no-op off-TPU and
    on every call after the first."""
    if _state["done"] and not force:
        return pops.get_dispatch()
    if pops.env_override() is not None:
        # manual override active: the dispatch table is shadowed anyway
        _state.update(done=True, source="env")
        return pops.get_dispatch()
    if os.environ.get("ZB_AUTOTUNE", "").strip() in ("0", "false", "off"):
        _state.update(done=True, source="disabled")
        return pops.get_dispatch()
    if jax.default_backend() != "tpu":
        _state.update(done=True, source="off-tpu")
        return pops.get_dispatch()
    cached = None if force else _load_cache()
    if cached is not None:
        pops.set_dispatch(cached["decisions"])
        _state.update(done=True, source="cache")
        if progress:
            progress(f"autotune: cached decisions {cached['decisions']}")
        return pops.get_dispatch()
    decisions, timings = measure(progress)
    pops.set_dispatch(decisions)
    _save_cache(decisions, timings)
    _state.update(done=True, source="measured")
    return pops.get_dispatch()


def get_decisions_json() -> str:
    """Current per-family dispatch as a JSON string (logging helper)."""
    return json.dumps(pops.get_dispatch(), sort_keys=True)


def main() -> None:
    """Self-check CLI: run the microbench (ignoring the cache), print the
    per-family table, and verify the chosen dispatch still passes the
    pallas selfcheck. Skips cleanly off-TPU (CI wires this as a
    skip-on-no-TPU step)."""
    import sys

    if jax.default_backend() != "tpu":
        print("autotune self-check skipped: no TPU backend")
        return
    decisions = ensure_autotuned(progress=lambda m: print(m, flush=True),
                                 force=True)
    print(f"dispatch ({dispatch_source()}): {json.dumps(decisions)}")
    pops.selfcheck()
    print("autotune self-check OK")
    sys.stdout.flush()


if __name__ == "__main__":
    main()
