"""SoA record batches: the device form of log records.

A batch is the columnar image of a contiguous log range (the unit the kernel
processes per invocation), mirroring the logical record layout of the
reference protocol (``protocol/src/main/resources/protocol.xml`` metadata +
value fields): record type / value type / intent / key plus the value
columns the kernel needs. Payloads are columnarized over the graph's
variable space; strings are interned ids.

Emissions reuse the same layout — the kernel's output batch IS the next
input batch (plus host bookkeeping columns: source row, response/push
flags, rejection codes).
"""

from __future__ import annotations

import dataclasses
from functools import partial
from typing import Any, Dict, Optional, Tuple

import jax
import jax.numpy as jnp
import numpy as np

from zeebe_tpu.tpu.conditions import (
    VT_ABSENT,
    VT_BOOL,
    VT_FLOAT,
    VT_NIL,
    VT_NUM,
    VT_STR,
    f32_exact,
)
from zeebe_tpu.tpu.intern import InternTable

# ---------------------------------------------------------------------------
# rejection / incident codes (device → host reason strings)
# ---------------------------------------------------------------------------

REJ_NONE = 0
REJ_JOB_NOT_ACTIVATABLE = 1
REJ_JOB_NOT_COMPLETABLE = 2
REJ_JOB_NOT_ACTIVATED = 3
REJ_JOB_NOT_FAILED = 4
REJ_RETRIES_NOT_POSITIVE = 5
REJ_JOB_NOT_EXIST = 6
REJ_TIMER_NOT_EXIST = 7
REJ_SUB_NOT_ACTIVE = 8   # correlate arrival for a gone activity instance
REJ_MSG_DUP = 9          # duplicate (name, correlation, message id) publish
# the device message store keys ONE live slot per (name, correlation)
# composite — a second open subscription / stored message on an occupied
# composite rejects per-record instead of crashing the partition
REJ_SUB_OCCUPIED = 10
REJ_MSG_STORE_OCCUPIED = 11

# incident error codes (emitted on INCIDENT CREATE commands)
ERR_CONDITION_NO_FLOW = 101
ERR_CONDITION_EVAL = 102
ERR_IO_MAPPING_IN = 103
ERR_IO_MAPPING_OUT = 104
ERR_CORRELATION_KEY = 106  # 105 = job-no-retries (engine.py)

# reason strings match the oracle engine exactly (interpreter.py)
REJECTION_REASONS = {
    REJ_JOB_NOT_ACTIVATABLE: "Job is not in one of these states: CREATED, FAILED, TIMED_OUT",
    REJ_JOB_NOT_COMPLETABLE: "Job is not in state: ACTIVATED, TIMED_OUT",
    REJ_JOB_NOT_ACTIVATED: "Job is not in state ACTIVATED",
    REJ_JOB_NOT_FAILED: "Job is not in state FAILED",
    REJ_RETRIES_NOT_POSITIVE: "Retries must be greater than 0",
    REJ_JOB_NOT_EXIST: "Job does not exist",
    REJ_TIMER_NOT_EXIST: "timer does not exist",
    REJ_SUB_NOT_ACTIVE: "activity is not active anymore",
    # REJ_MSG_DUP's reason embeds the message id — formatted in
    # engine._materialize from the interned id
    REJ_SUB_OCCUPIED: (
        "a subscription for this (message name, correlation key) is already "
        "open on this TPU-backed partition (one live subscription per key)"
    ),
    REJ_MSG_STORE_OCCUPIED: (
        "a message with this (name, correlation key) is already stored on "
        "this TPU-backed partition (one buffered message per key)"
    ),
}

_FIELDS = [
    "valid", "rtype", "vtype", "intent", "key", "elem", "wf",
    "instance_key", "scope_key", "v_vt", "v_num", "v_str",
    "req", "req_stream", "aux_key", "aux2_key", "type_id", "retries",
    "deadline", "worker", "src", "resp", "push", "rej",
]


@partial(jax.tree_util.register_dataclass, data_fields=_FIELDS, meta_fields=[])
@dataclasses.dataclass
class RecordBatch:
    valid: jax.Array        # [B] bool
    rtype: jax.Array        # [B] i32 RecordType
    vtype: jax.Array        # [B] i32 ValueType
    intent: jax.Array       # [B] i32
    key: jax.Array          # [B] i64
    elem: jax.Array         # [B] i32 element index (-1 n/a)
    wf: jax.Array           # [B] i32 workflow slot (-1 n/a)
    instance_key: jax.Array # [B] i64 workflowInstanceKey
    scope_key: jax.Array    # [B] i64 scopeInstanceKey
    v_vt: jax.Array         # [B, V] i8 payload types
    v_num: jax.Array        # [B, V] f32 (f32-exact by construction; see
                            # payload_to_columns — inexact values take the
                            # host-oracle path)
    v_str: jax.Array        # [B, V] i32
    req: jax.Array          # [B] i64 request id (-1 none)
    req_stream: jax.Array   # [B] i32 request stream / subscriber key
    aux_key: jax.Array      # [B] i64 job activityInstanceKey / incident aik / timer aik
    aux2_key: jax.Array     # [B] i64 incident jobKey / timer dueDate
    type_id: jax.Array      # [B] i32 job type (interned)
    retries: jax.Array      # [B] i32
    deadline: jax.Array     # [B] i64
    worker: jax.Array       # [B] i32 interned worker name
    src: jax.Array          # [B] i32 source row in the previous batch (-1 host)
    resp: jax.Array         # [B] bool respond to req at append
    push: jax.Array         # [B] bool push to req_stream subscriber
    rej: jax.Array          # [B] i32 rejection / incident code

    @property
    def size(self) -> int:
        return self.valid.shape[0]

    @property
    def num_vars(self) -> int:
        return self.v_vt.shape[1]


def empty(size: int, num_vars: int) -> RecordBatch:
    i64, i32, i8, f32 = jnp.int64, jnp.int32, jnp.int8, jnp.float32
    z64 = lambda: jnp.full((size,), -1, i64)  # noqa: E731
    z32 = lambda: jnp.full((size,), -1, i32)  # noqa: E731
    return RecordBatch(
        valid=jnp.zeros((size,), bool),
        rtype=jnp.zeros((size,), i32),
        vtype=jnp.zeros((size,), i32),
        intent=jnp.zeros((size,), i32),
        key=z64(),
        elem=z32(),
        wf=z32(),
        instance_key=z64(),
        scope_key=z64(),
        v_vt=jnp.zeros((size, num_vars), i8),
        v_num=jnp.zeros((size, num_vars), f32),
        v_str=jnp.zeros((size, num_vars), i32),
        req=z64(),
        req_stream=z32(),
        aux_key=z64(),
        aux2_key=z64(),
        type_id=jnp.zeros((size,), i32),
        retries=jnp.zeros((size,), i32),
        deadline=z64(),
        worker=jnp.zeros((size,), i32),
        src=z32(),
        resp=jnp.zeros((size,), bool),
        push=jnp.zeros((size,), bool),
        rej=jnp.zeros((size,), i32),
    )


# ---------------------------------------------------------------------------
# host payload conversion
# ---------------------------------------------------------------------------


class PayloadError(ValueError):
    """Payload not columnarizable (nested document / unknown type) — the
    caller must fall back to the host oracle engine."""


def payload_to_columns(
    doc: Dict[str, Any],
    column_of,          # name -> column (VarSpace.column, growable)
    interns: InternTable,
    num_vars: int,
) -> Tuple[np.ndarray, np.ndarray, np.ndarray]:
    vt = np.zeros((num_vars,), np.int8)
    num = np.zeros((num_vars,), np.float32)
    sid = np.zeros((num_vars,), np.int32)
    for name, value in doc.items():
        col = column_of(name)
        if col >= num_vars:
            raise PayloadError(f"variable space overflow: {name}")
        if value is None:
            vt[col] = VT_NIL
        elif isinstance(value, bool):
            vt[col] = VT_BOOL
            num[col] = 1.0 if value else 0.0
        elif isinstance(value, (int, float)):
            if not f32_exact(value):
                raise PayloadError(
                    f"payload number not f32-exact for {name!r}: {value!r}"
                )
            vt[col] = VT_NUM if isinstance(value, int) else VT_FLOAT
            num[col] = value
        elif isinstance(value, str):
            vt[col] = VT_STR
            sid[col] = interns.intern(value)
        else:
            raise PayloadError(f"non-scalar payload value for {name!r}: {value!r}")
    return vt, num, sid


def columns_to_payload(
    vt: np.ndarray, num: np.ndarray, sid: np.ndarray, names, interns: InternTable
) -> Dict[str, Any]:
    doc: Dict[str, Any] = {}
    for col, name in enumerate(names):
        t = int(vt[col])
        if t == VT_ABSENT:
            continue
        if t == VT_NIL:
            doc[name] = None
        elif t == VT_BOOL:
            doc[name] = bool(num[col])
        elif t == VT_NUM:
            doc[name] = int(num[col])
        elif t == VT_FLOAT:
            doc[name] = float(num[col])
        elif t == VT_STR:
            doc[name] = interns.string(int(sid[col]))
    return doc


# row-take packing groups (schema-derived so a new field fails loudly here
# instead of silently dropping from the packed takes)
_I32_SCALARS = ["rtype", "vtype", "intent", "elem", "wf", "req_stream",
                "type_id", "retries", "worker", "src", "rej"]
_I64_SCALARS = ["key", "instance_key", "scope_key", "req", "aux_key",
                "aux2_key", "deadline"]
_I8_SCALARS = ["valid", "resp", "push"]
assert set(_I32_SCALARS + _I64_SCALARS + _I8_SCALARS
           + ["v_vt", "v_num", "v_str"]) == set(_FIELDS)


def take_rows(batch: RecordBatch, idx: jax.Array) -> RecordBatch:
    """``batch[idx]`` (row take along axis 0) as TWO packed row gathers
    instead of one per field: an i32 mega-matrix (i32 scalars + v_str +
    bitcast v_num + i64 lo/hi planes) and an i8 matrix (bool flags + v_vt).
    A gather costs per-index issue, not bytes (PERF_NOTES round-4 cost
    model), so the naive per-field tree.map paid ~24 serial gathers where
    2 suffice. Bitcast/widen round-trips are exact — the result is
    bit-identical to ``jax.tree.map(lambda a: a[idx], batch)`` — and the
    takes route through the "emit" fused-gather family so the pallas
    mega-pass picks them up on TPU."""
    from zeebe_tpu.tpu import pallas_ops as pops

    v = batch.num_vars
    i32_mat = jnp.concatenate(
        [jnp.stack([getattr(batch, n) for n in _I32_SCALARS], axis=-1),
         batch.v_str,
         jax.lax.bitcast_convert_type(batch.v_num, jnp.int32),
         pops.i64_to_planes(
             jnp.stack([getattr(batch, n) for n in _I64_SCALARS], axis=-1)
         )],
        axis=1,
    )
    i8_mat = jnp.concatenate(
        [jnp.stack([getattr(batch, n).astype(jnp.int8) for n in _I8_SCALARS],
                   axis=-1),
         batch.v_vt],
        axis=1,
    )
    t32, t8 = pops.fused_gather_rows(
        [i32_mat, i8_mat],
        [pops.GatherOp(0, idx), pops.GatherOp(1, idx)],
        family="emit",
    )
    n32 = len(_I32_SCALARS)
    i64_mat = pops.planes_to_i64(t32[:, n32 + 2 * v :])
    out = {n: t32[:, i] for i, n in enumerate(_I32_SCALARS)}
    out.update({n: i64_mat[:, i] for i, n in enumerate(_I64_SCALARS)})
    out.update(
        valid=t8[:, 0].astype(bool),
        resp=t8[:, 1].astype(bool),
        push=t8[:, 2].astype(bool),
        v_vt=t8[:, 3:],
        v_str=t32[:, n32 : n32 + v],
        v_num=jax.lax.bitcast_convert_type(
            t32[:, n32 + v : n32 + 2 * v], jnp.float32
        ),
    )
    return RecordBatch(**out)


def compact(batch: RecordBatch) -> RecordBatch:
    """Stable-reorder a batch so valid rows form a contiguous prefix
    (drive.enqueue's precondition). Used for batches whose valid rows are
    interleaved — e.g. the all_to_all exchange output, which groups rows by
    source shard. The reorder is ``take_rows``' two packed gathers."""
    order = jnp.argsort(~batch.valid, stable=True)
    return take_rows(batch, order)
