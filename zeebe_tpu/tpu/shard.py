"""Multi-partition sharding over a device mesh.

The reference scales by splitting topics into partitions, each an
independent ordered log + state machine, with hash-routed cross-partition
messaging over the subscription transport
(``docs/src/basics/clustering.md``, ``SubscriptionCommandSender.java:96-108``).
Here partitions ARE mesh shards: each device owns one partition's engine
state and record queue; the step kernel runs under ``shard_map`` with

- partition-disjoint keyspaces (partition id in the key's high bits, the
  Protocol.java partition-key encoding),
- an ``all_to_all`` exchange slot for hash-routed cross-partition commands
  (message correlation — the subscription-transport data plane moved onto
  ICI),
- ``psum`` for global control-plane aggregates (processed counts,
  quiescence detection).
"""

from __future__ import annotations

import dataclasses
import re
from functools import partial
from typing import Tuple

import jax
import jax.numpy as jnp
import numpy as np
from jax.sharding import Mesh, PartitionSpec as P

# jax promoted shard_map out of experimental at different versions; this
# build only ships the experimental name (and spells the replication-check
# kwarg ``check_rep`` instead of ``check_vma``). Resolve once here so the
# two shard_map call sites below work on either build.
_shard_map = getattr(jax, "shard_map", None)
if _shard_map is None:
    from jax.experimental.shard_map import shard_map as _exp_shard_map

    def _shard_map(f, **kw):
        if "check_vma" in kw:
            kw["check_rep"] = kw.pop("check_vma")
        return _exp_shard_map(f, **kw)

from zeebe_tpu.engine import keyspace
from zeebe_tpu.protocol.enums import RecordType, ValueType
from zeebe_tpu.tpu import batch as rb
from zeebe_tpu.tpu import jit_registry
from zeebe_tpu.tpu import state as state_mod
from zeebe_tpu.tpu.batch import RecordBatch
from zeebe_tpu.tpu.graph import DeviceGraph
from zeebe_tpu.tpu.kernel import step_kernel
from zeebe_tpu.tpu.state import EngineState, corr_composite

# partition id lives in the key's high bits (reference Protocol.java keeps
# partition-local key spaces; 13 bits of partition, 51 bits of counter)
PARTITION_KEY_SHIFT = 51


def correlation_route(out: RecordBatch, nparts: int, my_pid):
    """Destination partition per emission row.

    Message-subscription commands (OPEN/CLOSE) hash their correlation
    composite — the device mesh's analogue of the oracle's
    ``partition_for_correlation_key`` (``SubscriptionCommandSender.java:
    96-108``; the hash FUNCTION differs from the host's string hash, which
    only matters when comparing partition assignments across engine kinds
    — the mesh is self-consistent). CORRELATE commands carry their
    destination (the subscribing instance's partition) in the ``wf``
    column. Everything else stays local."""
    rt_cmd = out.rtype == int(RecordType.COMMAND)
    is_msub = out.valid & rt_cmd & (
        out.vtype == int(ValueType.MESSAGE_SUBSCRIPTION)
    )
    is_corr = out.valid & rt_cmd & (
        out.vtype == int(ValueType.WORKFLOW_INSTANCE_SUBSCRIPTION)
    )
    ckey = corr_composite(out.type_id, out.retries, out.worker)
    # Fibonacci multiplicative hash on the composite (wraps mod 2^64)
    h = ((ckey * jnp.int64(-7046029254386353131)) >> 33) & jnp.int64(
        0x7FFFFFFF
    )
    hash_target = (h % nparts).astype(jnp.int32)
    return jnp.where(
        is_msub, hash_target,
        jnp.where(is_corr, jnp.clip(out.wf, 0, nparts - 1), my_pid),
    )


def _first_true_indices_local(mask, k):
    """Indices of the first ``k`` True entries (kernel._first_true_indices
    without the MXU scan — exchange blocks are small and this runs inside
    shard_map where odd lengths are common)."""
    n = mask.shape[0]
    rank = jnp.cumsum(mask.astype(jnp.int32)) - mask.astype(jnp.int32)
    tgt = jnp.where(mask & (rank < k), rank, k)
    return (
        jnp.full((k,), n, jnp.int32)
        .at[tgt]
        .set(jnp.arange(n, dtype=jnp.int32), mode="drop")
    )


def make_partitioned_state(
    num_partitions: int, capacity: int, num_vars: int, **kw
) -> EngineState:
    """Stacked per-partition state: every leaf gains a leading partition
    axis; key counters start at partition-disjoint bases."""
    shards = []
    for pid in range(num_partitions):
        st = state_mod.make_state(capacity=capacity, num_vars=num_vars, **kw)
        base = jnp.int64(pid) << PARTITION_KEY_SHIFT
        st = dataclasses.replace(
            st,
            next_wf_key=base + keyspace.WF_OFFSET,
            next_job_key=base + keyspace.JOB_OFFSET,
        )
        shards.append(st)
    return jax.tree.map(lambda *xs: jnp.stack(xs, axis=0), *shards)


def make_partitioned_batch(num_partitions: int, size: int, num_vars: int) -> RecordBatch:
    shards = [rb.empty(size, num_vars) for _ in range(num_partitions)]
    return jax.tree.map(lambda *xs: jnp.stack(xs, axis=0), *shards)


def _squeeze(tree):
    return jax.tree.map(lambda a: jnp.squeeze(a, axis=0), tree)


def _unsqueeze(tree):
    return jax.tree.map(lambda a: a[None], tree)


def build_sharded_step(mesh: Mesh, exchange_slots: int = 128):
    """A jit-compiled multi-partition step:

      (graph, state[P,...], batch[P,B,...], sends[P,P,S,...], now)
        → (state', emissions[P,...], sends_in[P,...], global_processed)

    ``sends`` carries hash-routed cross-partition command rows (row p,q =
    rows partition p addresses to partition q); the all_to_all delivers
    ``sends_in`` (rows arriving at each partition), which the caller
    enqueues into the destination partition's queue next round (after
    prefix-compaction: drive.enqueue requires valid rows contiguous at the
    front, and all_to_all output interleaves them by source shard) — exactly
    the reference's subscription-transport hop, but over ICI.
    """
    axis = mesh.axis_names[0]
    nparts = mesh.devices.shape[0]

    def shard_fn(graph, state, batch, sends, now):
        state = _squeeze(state)
        batch = _squeeze(batch)
        sends = _squeeze(sends)  # [P, S, ...] rows addressed per destination
        state, out, stats = step_kernel(graph, state, batch, now)
        # subscription-transport hop: deliver each partition its inbound rows
        sends_in = jax.tree.map(
            lambda a: jax.lax.all_to_all(a, axis, 0, 0), sends
        )
        total = jax.lax.psum(stats["processed"], axis)
        pending = jax.lax.psum(
            jnp.sum(out.valid, dtype=jnp.int32)
            + jnp.sum(sends_in.valid, dtype=jnp.int32),
            axis,
        )
        return (
            _unsqueeze(state),
            _unsqueeze(out),
            _unsqueeze(sends_in),
            total[None],
            pending[None],
        )

    spec_sharded = P(axis)
    spec_repl = P()

    def specs(tree, spec):
        return jax.tree.map(lambda _: spec, tree)

    def sharded_step(graph, state, batch, sends, now):
        fn = _shard_map(
            shard_fn,
            mesh=mesh,
            in_specs=(
                specs(graph, spec_repl),
                specs(state, spec_sharded),
                specs(batch, spec_sharded),
                specs(sends, spec_sharded),
                spec_repl,
            ),
            out_specs=(
                specs(state, spec_sharded),
                specs(batch, spec_sharded),
                specs(sends, spec_sharded),
                spec_sharded,
                spec_sharded,
            ),
            check_vma=False,
        )
        return fn(graph, state, batch, sends, now)

    return (
        jit_registry.register_jit(
            "shard.sharded_step",
            sharded_step,
            state_args=(1,),
            collective=True,
            max_signatures=2,
            suppress=("boundary-donation",),
            notes="state donation deferred: mesh A/B harnesses reuse the "
            "pre-step state for parity runs (ROADMAP item 3 picks this up "
            "when tables carry sharding specs natively)",
        ),
        nparts,
    )


def build_frame_exchange(mesh: Mesh, slots: int, frame_bytes: int):
    """The subscription-transport hop for the SERVING plane, as a mesh
    collective: encoded record frames ride the same per-destination
    ``all_to_all`` exchange-slot pattern ``build_sharded_step`` uses for
    staged record rows — but as raw wire bytes, so the destination decodes
    EXACTLY what the host transport would have carried (bit-identical
    appends by construction; see scheduler/placement.MeshExchange).

    Returns ``exchange(buf[D,D,S,B] u8, lens[D,D,S] i32, pids[D,D,S] i32)
    → (buf', lens', pids')`` where row ``d`` of each output carries the
    frames addressed TO device ``d``, indexed [source device, slot].
    """
    axis = mesh.axis_names[0]

    def shard_fn(buf, lens, pids):
        buf = jnp.squeeze(buf, axis=0)    # [D, S, B] rows per destination
        lens = jnp.squeeze(lens, axis=0)  # [D, S]
        pids = jnp.squeeze(pids, axis=0)
        out_buf = jax.lax.all_to_all(buf, axis, 0, 0)
        out_lens = jax.lax.all_to_all(lens, axis, 0, 0)
        out_pids = jax.lax.all_to_all(pids, axis, 0, 0)
        return out_buf[None], out_lens[None], out_pids[None]

    spec = P(axis)
    fn = jit_registry.register_jit(
        "shard.frame_exchange",
        _shard_map(
            shard_fn,
            mesh=mesh,
            in_specs=(spec, spec, spec),
            out_specs=(spec, spec, spec),
            check_vma=False,
        ),
        collective=True,
        max_signatures=2,
        notes="pure permutation of wire frames; carries no engine state",
    )
    n = mesh.devices.shape[0]

    def exchange(buf, lens, pids):
        # the builder's geometry IS the contract: a mismatched caller
        # would otherwise shard garbage silently
        if buf.shape != (n, n, slots, frame_bytes):
            raise ValueError(
                f"frame exchange built for buf shape "
                f"{(n, n, slots, frame_bytes)}, got {buf.shape}"
            )
        if lens.shape != (n, n, slots) or pids.shape != (n, n, slots):
            raise ValueError(
                f"frame exchange built for lane shape {(n, n, slots)}, "
                f"got {lens.shape} / {pids.shape}"
            )
        return fn(buf, lens, pids)

    return exchange


def make_exchange(num_partitions: int, slots: int, num_vars: int) -> RecordBatch:
    """The cross-partition send buffer: [P, P, S] record rows (source,
    destination, slot)."""
    shards = [
        jax.tree.map(
            lambda a: jnp.stack([a] * num_partitions, axis=0),
            rb.empty(slots, num_vars),
        )
        for _ in range(num_partitions)
    ]
    return jax.tree.map(lambda *xs: jnp.stack(xs, axis=0), *shards)


def build_sharded_drive(
    mesh: Mesh, batch_size: int, synthetic_workers: bool = False,
    max_rounds: int = 10_000, exchange_slots: int = 0,
):
    """The multi-partition drive-to-quiescence loop as ONE device program:
    per-partition record queues feed the step kernel under ``shard_map``,
    with a ``psum`` of pending counts deciding GLOBAL quiescence (all
    shards iterate in lockstep; a partition with an empty queue simply
    processes empty batches until every partition drains — the sharded
    analogue of ``drive.run_to_quiescence``).

    Cross-partition message correlation rides the ICI every round: emission
    rows whose route (``correlation_route``) is another partition are
    bucketed into per-destination blocks of ``exchange_slots`` rows and
    delivered by ``all_to_all`` — the reference's subscription transport
    (``SubscriptionCommandSender``) as a mesh collective. Arrivals enqueue
    after local emissions; a block overflow aborts the drive loudly.

    Queue sizing: ``drive.enqueue`` needs the whole PADDED incoming block
    to fit, so with messages each per-partition queue must hold at least
    ``batch_size * graph.emit_width + nparts * exchange_slots`` rows of
    headroom above its backlog.

    Staging contract: the mesh never materializes rows to host records, so
    correlation VALUE-TYPE TAGS must agree between what the subscribe step
    extracts from instance payloads and what staged publishes carry — a
    publish staged with a VT_STR intern of "42" will NOT match a
    subscription whose payload variable was numeric 42 (the serving path
    normalizes through record materialization; the mesh path by staging
    discipline).

    Returns ``drive(graph, state[P], queue[P], now) →
    (state', queue', totals[P])`` where totals carries per-shard processed/
    emitted/completed counts plus the shared overflow flag.
    """
    from zeebe_tpu.tpu import drive as drive_mod

    axis = mesh.axis_names[0]
    nparts = mesh.devices.shape[0]
    exchange_slots = exchange_slots or batch_size

    def shard_fn(graph, state, queue, now):
        state = _squeeze(state)
        queue = _squeeze(queue)
        my_pid = jax.lax.axis_index(axis).astype(jnp.int32)

        totals0 = {
            "processed": jnp.zeros((), jnp.int64),
            "emitted": jnp.zeros((), jnp.int64),
            "completed_roots": jnp.zeros((), jnp.int64),
            "rounds": jnp.zeros((), jnp.int32),
            "overflow": jnp.zeros((), bool),
        }
        pending0 = jax.lax.psum(queue.count, axis)

        def cond(carry):
            _s, _q, t, pending = carry
            return (
                (pending > 0)
                & (t["rounds"] < max_rounds)
                & (~t["overflow"])
            )

        def body(carry):
            s, q, t, _pending = carry
            q, batch = drive_mod.dequeue(q, batch_size)
            s, out, stats = step_kernel(
                graph, s, batch, now, synthetic_workers=synthetic_workers,
                partition_id=my_pid,
            )
            xover = jnp.zeros((), bool)
            if graph.has_messages and nparts > 1:
                target = correlation_route(out, nparts, my_pid)
                stay = out.valid & (target == my_pid)
                # per-destination blocks (own-destination block is empty by
                # construction: target == my_pid rows are 'stay')
                be = out.size
                blocks = []
                for p in range(nparts):
                    m = out.valid & (target == p) & (target != my_pid)
                    xover = xover | (
                        jnp.sum(m, dtype=jnp.int32) > exchange_slots
                    )
                    idx = jnp.clip(
                        _first_true_indices_local(m, exchange_slots),
                        0, be - 1,
                    )
                    n_p = jnp.sum(m, dtype=jnp.int32)
                    # two packed row gathers instead of a per-field tree.map
                    # (batch.take_rows, PERF_NOTES round-4 cost model)
                    block = rb.take_rows(out, idx)
                    block = dataclasses.replace(
                        block,
                        valid=jnp.arange(exchange_slots, dtype=jnp.int32)
                        < n_p,
                        # arrivals are fresh log entries at the destination
                        src=jnp.full((exchange_slots,), -1, jnp.int32),
                    )
                    blocks.append(block)
                sends = jax.tree.map(
                    lambda *xs: jnp.stack(xs, axis=0), *blocks
                )  # [P, S, ...]
                arrivals = jax.tree.map(
                    lambda a: jax.lax.all_to_all(a, axis, 0, 0), sends
                )
                flat = jax.tree.map(
                    lambda a: a.reshape((nparts * exchange_slots,)
                                        + a.shape[2:]),
                    arrivals,
                )
                # local rows keep their emission order; exchanged arrivals
                # append after (both prefix-compacted for enqueue)
                local = rb.compact(dataclasses.replace(out, valid=stay))
                q = drive_mod.enqueue(q, local)
                q = drive_mod.enqueue(q, rb.compact(flat))
            else:
                q = drive_mod.enqueue(q, out)
            t = {
                "processed": t["processed"] + stats["processed"].astype(jnp.int64),
                "emitted": t["emitted"] + stats["emitted"].astype(jnp.int64),
                "completed_roots": t["completed_roots"]
                + stats["completed_roots"].astype(jnp.int64),
                "rounds": t["rounds"] + 1,
                # overflow anywhere aborts everywhere (lockstep)
                "overflow": t["overflow"]
                | (jax.lax.psum(
                    (stats["overflow"] | q.overflow | xover).astype(jnp.int32),
                    axis,
                ) > 0),
            }
            pending = jax.lax.psum(q.count, axis)
            return s, q, t, pending

        state, queue, totals, _ = jax.lax.while_loop(
            cond, body, (state, queue, totals0, pending0)
        )
        return _unsqueeze(state), _unsqueeze(queue), _unsqueeze(totals)

    spec_sharded = P(axis)
    spec_repl = P()

    def specs(tree, spec):
        return jax.tree.map(lambda _: spec, tree)

    def drive(graph, state, queue, now):
        fn = _shard_map(
            shard_fn,
            mesh=mesh,
            in_specs=(
                specs(graph, spec_repl),
                specs(state, spec_sharded),
                specs(queue, spec_sharded),
                spec_repl,
            ),
            out_specs=(
                specs(state, spec_sharded),
                specs(queue, spec_sharded),
                {k: spec_sharded for k in (
                    "processed", "emitted", "completed_roots", "rounds",
                    "overflow",
                )},
            ),
            check_vma=False,
        )
        return fn(graph, state, queue, now)

    return jit_registry.register_jit(
        "shard.sharded_drive",
        drive,
        state_args=(1,),
        collective=True,
        max_signatures=2,
        suppress=("boundary-donation",),
        notes="state donation deferred with shard.sharded_step (parity "
        "A/B harnesses reuse the pre-drive state)",
    )


def make_partitioned_queue(num_partitions: int, capacity: int, num_vars: int):
    from zeebe_tpu.tpu import drive as drive_mod

    shards = [drive_mod.make_queue(capacity, num_vars) for _ in range(num_partitions)]
    return jax.tree.map(lambda *xs: jnp.stack(xs, axis=0), *shards)


# ---------------------------------------------------------------------------
# mesh-sharded SINGLE-partition state (ROADMAP item 2)
# ---------------------------------------------------------------------------
# Everything above shards ACROSS partitions (each device owns one whole
# partition). This section shards ONE partition's state tables over the
# mesh axis, so a single hot tenant's resident rows scale with the mesh
# instead of being capped at one chip's HBM: the row tables carry
# ``match_partition_rules``-style sharding specs (the pjit shard/gather
# pattern), live sharded at rest between waves, and are gathered over ICI
# for each step — the cross-shard reads (message correlation, scope-parent
# resolution, key sync) are ONE budgeted ``all_gather`` per table family
# per wave, modeled by zbaudit's collective-volume pass. The write side is
# collective-free: every device computes the identical full-table update
# (the batch is replicated), then keeps only its own row block. Running
# the UNMODIFIED step kernel on the gathered view is what makes the
# sharded engine replay bit-identical to the single-device one by
# construction.

# default mesh axis name for sharded-state programs
STATE_AXIS = "shards"

# (regex over the state leaf's dotted key-path, shard?) — first match
# wins, like SNIPPETS' match_partition_rules over a parameter pytree.
# Row tables (leading dim = a table capacity) shard on dim 0; host-managed
# worker-subscription tables, ring cursors, and key counters replicate
# (tiny, scalar, or mutated host-side between waves).
STATE_PARTITION_RULES = (
    (r"ei_(i32|i64|pay|index)$", True),
    (r"ei_map\.", True),
    (r"free_ei$", True),
    (r"job_(i32|i64|pay|index)$", True),
    (r"job_map\.", True),
    (r"free_job$", True),
    (r"join_(key|nin|arrived|pay|pos_stamp)$", True),
    (r"join_map\.", True),
    (r"timer_(key|due|aik|instance_key|elem|wf)$", True),
    (r"timer_map\.", True),
    (r"msub_(ckey|i32|i64)$", True),
    (r"msub_map\.", True),
    (r"msg_(key|ckey|i32|deadline|pay)$", True),
    (r"msg_map\.", True),
    (r".*", False),
)


def _path_str(path) -> str:
    parts = []
    for k in path:
        name = getattr(k, "name", None)
        if name is None:
            name = getattr(k, "key", None)
        if name is None:
            name = getattr(k, "idx", None)
        parts.append(str(name))
    return ".".join(parts)


def match_partition_rules(
    rules, tree, num_shards: int, axis: str = STATE_AXIS
):
    """PartitionSpec pytree for ``tree``: each leaf's dotted key-path is
    matched against ``rules`` (first match wins); a shard rule puts
    ``P(axis)`` on dim 0 when the leaf has rows divisible by
    ``num_shards``, else the leaf stays replicated (``P()``) — a
    non-divisible table silently falling back is safe (correctness never
    depends on WHICH leaves shard), and the HBM model reads the spec tree
    rather than assuming."""
    leaves, treedef = jax.tree_util.tree_flatten_with_path(tree)
    spec_leaves = []
    for path, leaf in leaves:
        name = _path_str(path)
        spec = P()
        for pat, want in rules:
            if re.search(pat, name):
                shape = getattr(leaf, "shape", ())
                if (
                    want
                    and len(shape) >= 1
                    and shape[0] > 0
                    and shape[0] % num_shards == 0
                ):
                    spec = P(axis)
                break
        spec_leaves.append(spec)
    return jax.tree_util.tree_unflatten(treedef, spec_leaves)


def state_partition_specs(
    state: EngineState, num_shards: int, axis: str = STATE_AXIS
):
    """The sharded-state spec tree for an :class:`EngineState`."""
    return match_partition_rules(STATE_PARTITION_RULES, state, num_shards, axis)


def state_shardings(mesh: Mesh, state: EngineState):
    """NamedSharding pytree for committing a state to a sharded mesh
    (``jax.device_put(state, state_shardings(mesh, state))``)."""
    from jax.sharding import NamedSharding

    specs = state_partition_specs(
        state, int(mesh.devices.size), mesh.axis_names[0]
    )
    return jax.tree.map(
        lambda s: NamedSharding(mesh, s), specs,
        is_leaf=lambda x: isinstance(x, P),
    )


def shard_of_key(key, num_shards: int):
    """Owning shard of an entity key — the same Fibonacci multiplicative
    hash ``correlation_route`` uses for message routing, so one routing
    function covers both planes. Deterministic in the key alone; the wave
    stager (engine ``_pack_batch``) and the routing tests both call this."""
    k = jnp.asarray(key, jnp.int64)
    h = ((k * jnp.int64(-7046029254386353131)) >> 33) & jnp.int64(0x7FFFFFFF)
    return (h % num_shards).astype(jnp.int32)


def shard_row_counts(keys, valid, num_shards: int):
    """Rows per owning shard for one staged wave ([num_shards] i32) — the
    ``mesh_shard_rows{device}`` gauge feed."""
    tgt = jnp.where(
        jnp.asarray(valid, bool), shard_of_key(keys, num_shards), num_shards
    )
    return (
        jnp.zeros((num_shards,), jnp.int32)
        .at[tgt]
        .add(1, mode="drop")
    )


def shard_of_key_host(keys, num_shards: int) -> np.ndarray:
    """numpy twin of :func:`shard_of_key` for host-side wave staging —
    the engine accounts routing per wave without a device round-trip.
    Tests pin the two implementations equal (routing determinism)."""
    k = np.asarray(keys, np.int64)
    with np.errstate(over="ignore"):
        h = (
            (k * np.int64(-7046029254386353131)) >> np.int64(33)
        ) & np.int64(0x7FFFFFFF)
    return (h % num_shards).astype(np.int32)


def shard_row_counts_host(keys, valid, num_shards: int) -> np.ndarray:
    """Host twin of :func:`shard_row_counts` ([num_shards] counts)."""
    tgt = shard_of_key_host(keys, num_shards)
    v = np.asarray(valid, bool)
    return np.bincount(tgt[v], minlength=num_shards).astype(np.int64)


def state_exchange_bytes(
    state: EngineState,
    num_shards: int,
    axis: str = STATE_AXIS,
    include_lookup: bool = True,
) -> int:
    """Aggregate cross-shard bytes ONE wave's table gathers move: each of
    the D devices receives the (D-1)/D fraction of every sharded table it
    does not hold, so the interconnect carries ``sharded_bytes * (D-1)``
    per wave. Pure shape arithmetic (no tracing) — the engine stamps it
    on the ``mesh_shard_exchange_bytes_total`` counter per wave, and the
    zbaudit collective pass independently measures the same gathers at
    the jaxpr level. ``include_lookup=False`` models resident mode's
    fallback leg, which rebuilds the lookup structures in-program instead
    of gathering them (only the row tables cross the interconnect)."""
    specs = state_partition_specs(state, num_shards, axis)
    leaves, _ = jax.tree_util.tree_flatten_with_path(state)
    spec_leaves = jax.tree_util.tree_leaves(
        specs, is_leaf=lambda x: isinstance(x, P)
    )
    total = 0
    for (path, a), s in zip(leaves, spec_leaves):
        if tuple(s) != (axis,):
            continue
        if not include_lookup and is_lookup_leaf(_path_str(path)):
            continue
        total += int(np.dtype(a.dtype).itemsize) * int(np.prod(a.shape))
    return total * (num_shards - 1)


def _zip_specs(fn, tree, specs):
    """Map ``fn(leaf, spec)`` over aligned (tree, spec-tree) leaves."""
    leaves, treedef = jax.tree_util.tree_flatten(tree)
    spec_leaves = jax.tree_util.tree_leaves(
        specs, is_leaf=lambda x: isinstance(x, P)
    )
    return jax.tree_util.tree_unflatten(
        treedef, [fn(a, s) for a, s in zip(leaves, spec_leaves)]
    )


def build_state_step(mesh: Mesh, state_template: EngineState):
    """The sharded-state step program:

      (graph, state, batch, now, partition_id) → (state', out, stats)

    ``state`` row tables arrive sharded per ``state_partition_specs``
    (dim 0 over the mesh axis); the batch, graph, and scalars are
    replicated. Each wave all_gathers the sharded tables (the budgeted
    cross-shard read), runs the UNMODIFIED ``step_kernel`` on the gathered
    view — identical on every device, so emissions and stats are
    replicated and bit-identical to the single-device program — and keeps
    only the local row block of the updated tables (the write side is a
    local slice, no collective). Registered as ``shard.state_step`` so
    zbaudit traces, lowers, and gates it like the other entries.
    """
    axis = mesh.axis_names[0]
    nshards = int(mesh.devices.size)
    specs_tree = state_partition_specs(state_template, nshards, axis)

    def _sharded(spec) -> bool:
        return tuple(spec) == (axis,)

    def shard_fn(graph, state, batch, now, partition_id):
        idx = jax.lax.axis_index(axis)

        def gather(a, s):
            if not _sharded(s):
                return a
            return jax.lax.all_gather(a, axis, axis=0, tiled=True)

        def keep(a, s):
            if not _sharded(s):
                return a
            rows = a.shape[0] // nshards
            return jax.lax.dynamic_slice_in_dim(a, idx * rows, rows, axis=0)

        full = _zip_specs(gather, state, specs_tree)
        new_state, out, stats = step_kernel(
            graph, full, batch, now, partition_id=partition_id
        )
        return _zip_specs(keep, new_state, specs_tree), out, stats

    fn = _shard_map(
        shard_fn,
        mesh=mesh,
        in_specs=(P(), specs_tree, P(), P(), P()),
        out_specs=(specs_tree, P(), P()),
        check_vma=False,
    )
    return jit_registry.register_jit(
        "shard.state_step",
        fn,
        state_args=(1,),
        donate_argnums=(1,),
        collective=True,
        max_signatures=2,
        suppress=("boundary-alias",),
        notes="one partition's tables sharded over the mesh axis "
        "(gather-for-compute / keep-local-on-write); aliasing of the "
        "donated sharded blocks is layout-dependent under shard_map, so "
        "the alias materialization check is waived — donation itself "
        "stays asserted",
    )


# ---------------------------------------------------------------------------
# sharded-state v2: residency-routed staging (ROADMAP item 2, second half)
# ---------------------------------------------------------------------------
# ``build_state_step`` above is gather-for-compute: resident HBM divides by
# the span but every wave gathers every sharded table, so neither the
# compute term nor the per-wave collective volume divides. The routed
# programs below make the key-hash routing plane PHYSICAL: the engine
# stages each wave into per-shard batch lanes (``_pack_batch``'s laned
# path), every shard rebuilds its lookup structures from its OWN row block
# in-program (``rebuild_lookup_state`` — pow2 capacities stay pow2 under
# the block split) and steps the unmodified kernel on local rows + its
# routed batch lane. No per-wave table ``all_gather`` exists in the routed
# lowering; the only collectives are ``psum`` reductions of the (single-
# owner, hence exact) emissions, stats, and replicated-leaf deltas — the
# boundary traffic, scaling with the BATCH, not the tables.
#
# Residency contract (enforced by the engine's routing policy, not here):
# a routed wave is SINGLE-OWNER — all rows belong to instances wholly
# resident in one shard's row block — so key allocation from the
# replicated counters happens on exactly one lane (no cross-lane key
# collisions) and parent-slot references never leave the block. Waves the
# policy cannot prove single-owner (unknown residency, lane overflow,
# message-correlation graphs) run ``build_state_step_fallback``: the v1
# gathered shape but with the lookup structures rebuilt GLOBALLY in-program
# from the gathered rows — in resident mode the lookup leaves are per-wave
# derived scratch in BOTH legs, which is what lets the two interleave
# freely on the same sharded tables. Both legs replay bit-identical to the
# single-device engine: emissions depend on keys and batch-row order, never
# on which table slot a row occupies.

# state leaves DERIVED from live rows (direct-mapped indexes, fallback
# hashmaps, free-slot rings + their cursors): in resident mode these are
# per-wave scratch — rebuilt inside the step programs — never gathered,
# never trusted across waves.
LOOKUP_LEAF_PATTERNS = (
    r"ei_map\.", r"job_map\.", r"join_map\.", r"timer_map\.",
    r"msub_map\.", r"msg_map\.",
    r"ei_index$", r"job_index$",
    r"free_(ei|job)$", r"free_(ei|job)_(pop|push)$",
)

_CURSOR_RE = re.compile(r"free_(ei|job)_(pop|push)$")


def is_lookup_leaf(name: str) -> bool:
    """True when a dotted state-leaf path names a row-derived lookup
    structure (rebuilt per wave by the resident-mode step programs)."""
    return any(re.search(p, name) for p in LOOKUP_LEAF_PATTERNS)


def unshardable_state_leaves(state: EngineState, num_shards: int) -> list:
    """Leaf paths the partition rules WANT sharded but whose leading dim
    is not divisible by ``num_shards`` (they silently replicate in v1).
    Resident mode refuses such a configuration outright: a replicated row
    table would put its slots in the global space while sharded tables use
    block-local spaces, and the owner lane's writes to it would diverge
    from the other lanes' no-ops."""
    leaves, _ = jax.tree_util.tree_flatten_with_path(state)
    bad = []
    for path, leaf in leaves:
        name = _path_str(path)
        for pat, want in STATE_PARTITION_RULES:
            if re.search(pat, name):
                if want:
                    shape = getattr(leaf, "shape", ())
                    if not (
                        len(shape) >= 1
                        and shape[0] > 0
                        and shape[0] % num_shards == 0
                    ):
                        bad.append(name)
                break
    return bad


def routed_exchange_bytes(out_tree, num_shards: int) -> int:
    """Cross-shard bytes ONE routed wave moves: the emission batch (and
    stats/replicated-leaf deltas, which it dominates) reduces over the mesh
    axis via ``psum``, so the interconnect carries ``reduced_bytes *
    (D-1)`` — the same receive-volume convention as
    :func:`state_exchange_bytes`, now a function of the BATCH instead of
    the tables. bool/int8 leaves reduce in i32 (4 B/element)."""
    total = 0
    for a in jax.tree_util.tree_leaves(out_tree):
        dt = np.dtype(a.dtype)
        item = 4 if dt in (np.dtype(bool), np.dtype(np.int8)) else dt.itemsize
        total += item * int(np.prod(a.shape))
    return total * (num_shards - 1)


def _psum_masked(leaf, mine, axis):
    """Exact single-owner reduction of a per-lane value: non-owner lanes
    contribute zeros, so the sum IS the owner's value (f32 included — one
    nonzero term). bool/int8 reduce in i32."""
    if leaf.dtype == jnp.bool_:
        z = jnp.where(mine, leaf, False).astype(jnp.int32)
        return jax.lax.psum(z, axis) != 0
    if leaf.dtype == jnp.int8:
        z = jnp.where(mine, leaf, jnp.zeros_like(leaf)).astype(jnp.int32)
        return jax.lax.psum(z, axis).astype(jnp.int8)
    z = jnp.where(mine, leaf, jnp.zeros_like(leaf))
    return jax.lax.psum(z, axis)


def _delta_psum(new, old, mine, axis):
    """Replicated-leaf reconciliation: every lane holds the same ``old``;
    only the owner lane's kernel produced a real ``new`` — apply exactly
    its delta on all lanes (bools via i32 space)."""
    if new.dtype == jnp.bool_:
        o = old.astype(jnp.int32)
        d = jnp.where(mine, new.astype(jnp.int32) - o, 0)
        return (o + jax.lax.psum(d, axis)) != 0
    d = jnp.where(mine, new - old, jnp.zeros_like(new))
    return old + jax.lax.psum(d, axis)


def build_state_step_routed(mesh: Mesh, state_template: EngineState):
    """The residency-routed sharded-state step program:

      (graph, state, lanes, now, partition_id) → (state', out, stats)

    ``state`` arrives sharded per ``state_partition_specs`` exactly like
    ``shard.state_step``; ``lanes`` is a RecordBatch with a leading
    ``[num_shards]`` lane dim, sharded over the mesh axis, so each device
    receives ONLY its own routed rows (one host→device put per dtype
    family covers all lanes). Each shard translates the parent-slot column
    into its local row space, rebuilds the lookup structures from its own
    block, and steps the UNMODIFIED kernel on local rows + local lane —
    no table gather anywhere in the lowering. Emissions, stats, and the
    deltas of replicated leaves (key counters, worker-subscription
    tables) reduce with ``psum``; single-owner waves make every reduction
    exact, so outputs are replicated and bit-identical to the
    single-device program. Registered as ``shard.state_step_routed`` with
    its own zbaudit collective budget (boundary traffic only)."""
    axis = mesh.axis_names[0]
    nshards = int(mesh.devices.size)
    specs_tree = state_partition_specs(state_template, nshards, axis)
    spec_leaves = jax.tree_util.tree_leaves(
        specs_tree, is_leaf=lambda x: isinstance(x, P)
    )

    def _sharded(spec) -> bool:
        return tuple(spec) == (axis,)

    def shard_fn(graph, state, lanes, now, partition_id):
        from zeebe_tpu.tpu.kernel import scope_to_global, scope_to_local

        idx = jax.lax.axis_index(axis)
        batch = _squeeze(lanes)
        mine = jnp.any(batch.valid)
        lrows = state.ei_i32.shape[0]
        prev_scope = state.ei_i32[:, state_mod.EI_SCOPE]
        local = dataclasses.replace(
            state, ei_i32=scope_to_local(state.ei_i32, idx, lrows)
        )
        # lookup structures are per-wave derived scratch: rebuild them
        # from THIS block's rows (local capacities — pow2/D stays pow2)
        local = state_mod.rebuild_lookup_state(local)
        new_state, out, stats = step_kernel(
            graph, local, batch, now, partition_id=partition_id
        )
        new_state = dataclasses.replace(
            new_state,
            ei_i32=scope_to_global(
                new_state.ei_i32, prev_scope, idx, lrows
            ),
        )
        new_leaves, treedef = jax.tree_util.tree_flatten_with_path(new_state)
        old_leaves = jax.tree_util.tree_leaves(state)
        rec = []
        for (path, nl), ol, sp in zip(new_leaves, old_leaves, spec_leaves):
            if _sharded(sp):
                rec.append(nl)  # local block stays local
            elif _CURSOR_RE.search(_path_str(path)):
                # free-ring cursors are lane-local rebuild scratch: pass
                # the replicated input through (next rebuild resets them)
                rec.append(ol)
            else:
                rec.append(_delta_psum(nl, ol, mine, axis))
        new_state = jax.tree_util.tree_unflatten(treedef, rec)
        out = jax.tree.map(lambda a: _psum_masked(a, mine, axis), out)
        stats = {
            k: _psum_masked(v, mine, axis) for k, v in stats.items()
        }
        return new_state, out, stats

    fn = _shard_map(
        shard_fn,
        mesh=mesh,
        in_specs=(P(), specs_tree, P(axis), P(), P()),
        out_specs=(specs_tree, P(), P()),
        check_vma=False,
    )
    return jit_registry.register_jit(
        "shard.state_step_routed",
        fn,
        state_args=(1,),
        donate_argnums=(1,),
        collective=True,
        max_signatures=2,
        suppress=("boundary-alias",),
        notes="residency-routed sharded state: local rows + routed batch "
        "lane per shard, lookup structures rebuilt in-program, psum-only "
        "boundary exchange (no table all_gather in the lowering); alias "
        "materialization waived as for shard.state_step",
    )


def build_state_step_fallback(mesh: Mesh, state_template: EngineState):
    """Resident mode's gathered fallback step (same signature as
    ``shard.state_step``): waves the routing policy cannot prove
    single-owner (unknown residency, lane overflow, message graphs) gather
    the ROW tables and step the replicated global view like v1 — but the
    lookup structures are NOT gathered: they are per-wave scratch in
    resident mode, so this leg substitutes global-shaped placeholders and
    rebuilds them in-program from the gathered rows (strictly fresher than
    v1's cadence invariant, and it sheds the map/index/ring gather volume
    from the wave). Sharded lookup leaves return the local slice of the
    rebuilt global scratch so at-rest shapes stay identical to v1."""
    axis = mesh.axis_names[0]
    nshards = int(mesh.devices.size)
    specs_tree = state_partition_specs(state_template, nshards, axis)
    spec_leaves = jax.tree_util.tree_leaves(
        specs_tree, is_leaf=lambda x: isinstance(x, P)
    )
    template_leaves = [
        leaf
        for _, leaf in jax.tree_util.tree_flatten_with_path(state_template)[0]
    ]

    def _sharded(spec) -> bool:
        return tuple(spec) == (axis,)

    def shard_fn(graph, state, batch, now, partition_id):
        idx = jax.lax.axis_index(axis)
        leaves, treedef = jax.tree_util.tree_flatten_with_path(state)
        full_leaves = []
        for (path, a), t, sp in zip(leaves, template_leaves, spec_leaves):
            name = _path_str(path)
            if is_lookup_leaf(name):
                if _sharded(sp):
                    # global-shaped scratch; rebuild overwrites it below
                    full_leaves.append(
                        jnp.zeros(tuple(t.shape), dtype=t.dtype)
                    )
                else:
                    full_leaves.append(a)
            elif _sharded(sp):
                full_leaves.append(
                    jax.lax.all_gather(a, axis, axis=0, tiled=True)
                )
            else:
                full_leaves.append(a)
        full = jax.tree_util.tree_unflatten(treedef, full_leaves)
        full = state_mod.rebuild_lookup_state(full)
        new_state, out, stats = step_kernel(
            graph, full, batch, now, partition_id=partition_id
        )

        def keep(a, s):
            if not _sharded(s):
                return a
            rows = a.shape[0] // nshards
            return jax.lax.dynamic_slice_in_dim(a, idx * rows, rows, axis=0)

        return _zip_specs(keep, new_state, specs_tree), out, stats

    fn = _shard_map(
        shard_fn,
        mesh=mesh,
        in_specs=(P(), specs_tree, P(), P(), P()),
        out_specs=(specs_tree, P(), P()),
        check_vma=False,
    )
    return jit_registry.register_jit(
        "shard.state_step_fallback",
        fn,
        state_args=(1,),
        donate_argnums=(1,),
        collective=True,
        max_signatures=4,
        suppress=("boundary-alias",),
        notes="resident mode's gathered fallback: row tables gather, "
        "lookup structures rebuild in-program (sheds the map/index/ring "
        "gather volume vs shard.state_step); overflow waves add pow2 "
        "batch buckets, hence the wider signature allowance",
    )
