"""Engine state: struct-of-arrays tables in HBM.

The reference keeps per-partition state in heap object maps / RocksDB
(``broker-core/.../workflow/index/ElementInstanceIndex.java:25``,
``broker-core/.../job/state/JobInstanceStateController.java:28``); here
each state family is a fixed-capacity SoA table plus an HBM hash index
(``zeebe_tpu.tpu.hashmap``) mapping entity key → slot:

- element instances: lifecycle state, element, scope linkage, token counts,
  columnar payload (the ElementInstanceIndex analogue)
- jobs: the short job state machine + stored job record
- joins: in-flight parallel-gateway joins keyed by (scope, gateway), with
  flow-position-stamped payload merge (matches the oracle's flow-order merge)
- timers: due-date table scanned by the tick kernel
- job subscriptions: small table mutated host-side (credits, workers)
- key counters (reference KeyGenerator strides: workflow ≡1, job ≡2 mod 5)

Capacities are static (jit shapes); the host engine grows tables by
re-padding when occupancy crosses a threshold.
"""

from __future__ import annotations

import dataclasses
from functools import partial

import jax
import jax.numpy as jnp

from zeebe_tpu.engine import keyspace
from zeebe_tpu.tpu import hashmap

_STATE_FIELDS = [
    "ei_key", "ei_elem", "ei_state", "ei_wf", "ei_scope_slot", "ei_instance_key",
    "ei_tokens", "ei_job_key", "ei_vt", "ei_num", "ei_str", "ei_map",
    "job_key", "job_state", "job_elem", "job_wf", "job_instance_key",
    "job_aik", "job_type", "job_retries", "job_deadline", "job_worker",
    "job_vt", "job_num", "job_str", "job_map",
    "join_key", "join_nin", "join_arrived", "join_vt", "join_num", "join_str",
    "join_pos_stamp", "join_map",
    "timer_key", "timer_due", "timer_aik", "timer_instance_key", "timer_elem",
    "timer_wf", "timer_map",
    "sub_key", "sub_type", "sub_worker", "sub_credits", "sub_timeout", "sub_valid",
    "sub_rr",
    "next_wf_key", "next_job_key",
]


@partial(jax.tree_util.register_dataclass, data_fields=_STATE_FIELDS, meta_fields=[])
@dataclasses.dataclass
class EngineState:
    # element instances [N]
    ei_key: jax.Array          # i64, -1 free
    ei_elem: jax.Array         # i32
    ei_state: jax.Array        # i32 lifecycle intent, -1 free
    ei_wf: jax.Array           # i32 workflow slot
    ei_scope_slot: jax.Array   # i32 parent slot, -1 root
    ei_instance_key: jax.Array # i64 workflowInstanceKey
    ei_tokens: jax.Array       # i32 active tokens in this scope
    ei_job_key: jax.Array      # i64
    ei_vt: jax.Array           # [N, V] i8 payload value types
    ei_num: jax.Array          # [N, V] f64
    ei_str: jax.Array          # [N, V] i32
    ei_map: hashmap.HashTable  # key → slot

    # jobs [M]
    job_key: jax.Array         # i64, -1 free
    job_state: jax.Array       # i32 (JobIntent of last state event), -1 free
    job_elem: jax.Array        # i32 (headers.activityId element)
    job_wf: jax.Array          # i32
    job_instance_key: jax.Array# i64
    job_aik: jax.Array         # i64 headers.activityInstanceKey
    job_type: jax.Array        # i32 interned
    job_retries: jax.Array     # i32
    job_deadline: jax.Array    # i64
    job_worker: jax.Array      # i32 interned
    job_vt: jax.Array          # [M, V]
    job_num: jax.Array
    job_str: jax.Array
    job_map: hashmap.HashTable

    # parallel joins [J]
    join_key: jax.Array        # i64 composite (scope_key<<8 | gateway), -1 free
    join_nin: jax.Array        # i32
    join_arrived: jax.Array    # [J, F_in] bool
    join_vt: jax.Array         # [J, V] merged payload
    join_num: jax.Array
    join_str: jax.Array
    join_pos_stamp: jax.Array  # [J, V] i32: flow position that wrote each var
    join_map: hashmap.HashTable

    # timers [TM]
    timer_key: jax.Array       # i64, -1 free
    timer_due: jax.Array       # i64
    timer_aik: jax.Array       # i64
    timer_instance_key: jax.Array  # i64
    timer_elem: jax.Array      # i32 handler element
    timer_wf: jax.Array        # i32
    timer_map: hashmap.HashTable

    # job worker subscriptions [S] (host-managed)
    sub_key: jax.Array         # i64 subscriber key
    sub_type: jax.Array        # i32 interned job type
    sub_worker: jax.Array      # i32 interned worker name
    sub_credits: jax.Array     # i32
    sub_timeout: jax.Array     # i64
    sub_valid: jax.Array       # bool
    sub_rr: jax.Array          # i32 round-robin cursor (global, like the oracle)

    # key counters (i64 scalars)
    next_wf_key: jax.Array
    next_job_key: jax.Array

    @property
    def capacity(self) -> int:
        return self.ei_key.shape[0]

    @property
    def num_vars(self) -> int:
        return self.ei_vt.shape[1]


def _pow2(n: int) -> int:
    p = 1
    while p < n:
        p *= 2
    return p


def make_state(
    capacity: int = 1 << 12,
    num_vars: int = 8,
    job_capacity: int = 0,
    join_capacity: int = 0,
    timer_capacity: int = 0,
    sub_capacity: int = 64,
    max_join_in: int = 4,
) -> EngineState:
    n = capacity
    m = job_capacity or capacity
    j = join_capacity or max(capacity // 8, 256)
    tm = timer_capacity or max(capacity // 8, 256)
    v = num_vars
    i64, i32, i8, f64 = jnp.int64, jnp.int32, jnp.int8, jnp.float64

    return EngineState(
        ei_key=jnp.full((n,), -1, i64),
        ei_elem=jnp.zeros((n,), i32),
        ei_state=jnp.full((n,), -1, i32),
        ei_wf=jnp.zeros((n,), i32),
        ei_scope_slot=jnp.full((n,), -1, i32),
        ei_instance_key=jnp.full((n,), -1, i64),
        ei_tokens=jnp.zeros((n,), i32),
        ei_job_key=jnp.full((n,), -1, i64),
        ei_vt=jnp.zeros((n, v), i8),
        ei_num=jnp.zeros((n, v), f64),
        ei_str=jnp.zeros((n, v), i32),
        ei_map=hashmap.make(_pow2(4 * n)),
        job_key=jnp.full((m,), -1, i64),
        job_state=jnp.full((m,), -1, i32),
        job_elem=jnp.zeros((m,), i32),
        job_wf=jnp.zeros((m,), i32),
        job_instance_key=jnp.full((m,), -1, i64),
        job_aik=jnp.full((m,), -1, i64),
        job_type=jnp.zeros((m,), i32),
        job_retries=jnp.zeros((m,), i32),
        job_deadline=jnp.full((m,), -1, i64),
        job_worker=jnp.zeros((m,), i32),
        job_vt=jnp.zeros((m, v), i8),
        job_num=jnp.zeros((m, v), f64),
        job_str=jnp.zeros((m, v), i32),
        job_map=hashmap.make(_pow2(4 * m)),
        join_key=jnp.full((j,), -1, i64),
        join_nin=jnp.zeros((j,), i32),
        join_arrived=jnp.zeros((j, max_join_in), bool),
        join_vt=jnp.zeros((j, v), i8),
        join_num=jnp.zeros((j, v), f64),
        join_str=jnp.zeros((j, v), i32),
        join_pos_stamp=jnp.full((j, v), -1, i32),
        join_map=hashmap.make(_pow2(4 * j)),
        timer_key=jnp.full((tm,), -1, i64),
        timer_due=jnp.full((tm,), -1, i64),
        timer_aik=jnp.full((tm,), -1, i64),
        timer_instance_key=jnp.full((tm,), -1, i64),
        timer_elem=jnp.zeros((tm,), i32),
        timer_wf=jnp.zeros((tm,), i32),
        timer_map=hashmap.make(_pow2(4 * tm)),
        sub_key=jnp.full((sub_capacity,), -1, i64),
        sub_type=jnp.zeros((sub_capacity,), i32),
        sub_worker=jnp.zeros((sub_capacity,), i32),
        sub_credits=jnp.zeros((sub_capacity,), i32),
        sub_timeout=jnp.zeros((sub_capacity,), i64),
        sub_valid=jnp.zeros((sub_capacity,), bool),
        sub_rr=jnp.zeros((), i32),
        next_wf_key=jnp.array(keyspace.WF_OFFSET, i64),
        next_job_key=jnp.array(keyspace.JOB_OFFSET, i64),
    )
