"""Engine state: struct-of-arrays tables in HBM.

The reference keeps per-partition state in heap object maps / RocksDB
(``broker-core/.../workflow/index/ElementInstanceIndex.java:25``,
``broker-core/.../job/state/JobInstanceStateController.java:28``); here
each state family is a fixed-capacity SoA table plus an HBM hash index
(``zeebe_tpu.tpu.hashmap``) mapping entity key → slot:

- element instances: lifecycle state, element, scope linkage, token counts,
  columnar payload (the ElementInstanceIndex analogue)
- jobs: the short job state machine + stored job record
- joins: in-flight parallel-gateway joins keyed by (scope, gateway), with
  flow-position-stamped payload merge (matches the oracle's flow-order merge)
- timers: due-date table scanned by the tick kernel
- job subscriptions: small table mutated host-side (credits, workers)
- key counters (reference KeyGenerator strides: workflow ≡1, job ≡2 mod 5)

Capacities are static (jit shapes); the host engine grows tables by
re-padding when occupancy crosses a threshold.

Write-path note: the step kernel commits each table GROUP (ei_i32 +
ei_i64-as-planes + ei_pay + free ring + index; likewise jobs and timers)
through ONE fused pallas mega-pass (``pallas_ops.fused_table_commit``) on
builds where the boot autotune picked fusion — the packed same-dtype
layout below is what makes those groups commit as whole-row writes.
"""

from __future__ import annotations

import dataclasses
from functools import partial

import jax
import jax.numpy as jnp

from zeebe_tpu.engine import keyspace
from zeebe_tpu.tpu import hashmap

# packed column layouts: same-dtype scalar fields of a table live in one
# [cap, K] matrix so inserts/updates touching many fields are ONE row
# scatter instead of one scatter fusion per field
EI_ELEM, EI_STATE, EI_WF, EI_SCOPE, EI_TOKENS = 0, 1, 2, 3, 4
# pending interrupting-boundary continuation: the boundary element whose
# BOUNDARY_EVENT_OCCURRED fires when this instance's ELEMENT_TERMINATED
# processes (-1 none) — the oracle's _pending_boundary dict as a column
EI_PENDING_BD = 5
EIL_KEY, EIL_IKEY, EIL_JOB_KEY = 0, 1, 2
JB_STATE, JB_ELEM, JB_WF, JB_TYPE, JB_RETRIES, JB_WORKER = 0, 1, 2, 3, 4, 5
JBL_KEY, JBL_IKEY, JBL_AIK, JBL_DEADLINE = 0, 1, 2, 3
# message subscriptions (message-partition role): i32 cols = (name id,
# correlation vt, correlation bits, workflow-instance partition);
# i64 cols = (workflowInstanceKey, activityInstanceKey)
MS_NAME, MS_CVT, MS_CBITS, MS_PART = 0, 1, 2, 3
MSL_WIKEY, MSL_AIK = 0, 1
# stored messages (TTL > 0): i32 cols = (name id, correlation vt,
# correlation bits, interned message id)
MG_NAME, MG_CVT, MG_CBITS, MG_MSGID = 0, 1, 2, 3


def corr_composite(name_id, corr_vt, corr_bits):
    """Injective i64 composite of (message name, correlation value) — the
    hashmap key for subscription and stored-message lookups. The oracle
    keys correlation on ``(message name, str(correlation key))``
    (interpreter ``StoredSubscription``); on device the value is an
    interned-string id or the f32 bit pattern, tagged by its value type so
    numeric and string keys can never alias. Non-negative by construction
    (intern ids are ≥ 0), so it never collides with the hashmap's
    EMPTY/TOMBSTONE sentinels."""
    import jax.numpy as _jnp

    return (
        (name_id.astype(_jnp.int64) << 35)
        | (corr_vt.astype(_jnp.int64) << 32)
        | corr_bits.astype(_jnp.uint32).astype(_jnp.int64)
    )

_STATE_FIELDS = [
    "ei_i32", "ei_i64", "ei_pay", "ei_map", "ei_index",
    "free_ei", "free_ei_pop", "free_ei_push",
    "job_i32", "job_i64", "job_pay", "job_map", "job_index",
    "free_job", "free_job_pop", "free_job_push",
    "join_key", "join_nin", "join_arrived", "join_pay",
    "join_pos_stamp", "join_map",
    "timer_key", "timer_due", "timer_aik", "timer_instance_key", "timer_elem",
    "timer_wf", "timer_map",
    "msub_ckey", "msub_i32", "msub_i64", "msub_map",
    "msg_key", "msg_ckey", "msg_i32", "msg_deadline", "msg_pay", "msg_map",
    "sub_key", "sub_type", "sub_worker", "sub_credits", "sub_timeout", "sub_valid",
    "sub_rr",
    "next_wf_key", "next_job_key",
]


# ---------------------------------------------------------------------------
# packed payload columns
# ---------------------------------------------------------------------------
# A table's payload (per-variable value type, interned string id, numeric
# value) is ONE [cap, 3V] i32 matrix: cols [0,V) = value types, [V,2V) =
# string ids, [2V,3V) = float32 numbers bitcast to i32. XLA lowers general
# scatters to SERIAL per-index loops on TPU, so a payload write must be one
# scatter, not three — and float32 (not 64) halves the emulated-64-bit op
# cost throughout the kernel. Values that are not exactly representable in
# f32 never reach the device: ``batch.payload_to_columns`` rejects them
# into the host-oracle fallback path.


def pack_payload(vt, sid, num):
    """[..., V] (vt int, sid i32, num f32) → [..., 3V] i32."""
    return jnp.concatenate(
        [
            vt.astype(jnp.int32),
            sid.astype(jnp.int32),
            jax.lax.bitcast_convert_type(num.astype(jnp.float32), jnp.int32),
        ],
        axis=-1,
    )


def unpack_payload(pay):
    """[..., 3V] i32 → (vt i32, sid i32, num f32), each [..., V]."""
    v = pay.shape[-1] // 3
    vt = pay[..., :v]
    sid = pay[..., v : 2 * v]
    num = jax.lax.bitcast_convert_type(pay[..., 2 * v : 3 * v], jnp.float32)
    return vt, sid, num


@partial(jax.tree_util.register_dataclass, data_fields=_STATE_FIELDS, meta_fields=[])
@dataclasses.dataclass
class EngineState:
    # element instances [N] (ElementInstanceIndex analogue), packed:
    # ei_i32 cols = (elem, lifecycle state[-1 free], wf slot, scope slot,
    # token count, pending boundary elem[-1 none]);
    # ei_i64 cols = (key[-1 free], workflowInstanceKey, jobKey)
    ei_i32: jax.Array          # [N, 6] i32
    ei_i64: jax.Array          # [N, 3] i64
    ei_pay: jax.Array          # [N, 3V] i32 packed payload (vt | sid | f32 bits)
    ei_map: hashmap.HashTable  # key → slot (FALLBACK; see ei_index)
    # Direct-mapped key → slot accelerator: keys are allocated
    # sequentially with stride 5 by this engine (keyspace residue
    # classes), so ``index[(key // 5) & (cap-1)]`` is collision-free
    # within any window of ``5 * cap`` consecutive keys. A hit
    # is verified against the row's own key column; misses (an old live
    # instance whose congruent-mod-cap successor overwrote the entry)
    # fall back to the hashmap probe, which is rebuilt from live rows at
    # wave boundaries rather than maintained per round — the per-round
    # probe/insert/delete machinery was the largest profiled cost class.
    ei_index: jax.Array        # [8N] i32 slot, -1 empty
    # free-slot ring (replaces the per-round full-table free scan): pop
    # cursor hands out ring[(pop+rank) % N], frees append at push; both
    # cursors are monotonic i64, free count = push - pop. Rebuilt with the
    # lookup state (host-side frees — demotions — re-enter the ring then).
    free_ei: jax.Array         # [N] i32 ring of free slots
    free_ei_pop: jax.Array     # i64 scalar
    free_ei_push: jax.Array    # i64 scalar

    # jobs [M], packed: job_i32 cols = (state[-1 free], elem, wf, type,
    # retries, worker); job_i64 cols = (key[-1 free], instanceKey, aik,
    # deadline)
    job_i32: jax.Array         # [M, 6] i32
    job_i64: jax.Array         # [M, 4] i64
    job_pay: jax.Array         # [M, 3V] i32 packed payload
    job_map: hashmap.HashTable  # fallback (see ei_index)
    job_index: jax.Array       # [8M] i32 slot, -1 empty
    free_job: jax.Array        # [M] i32
    free_job_pop: jax.Array    # i64
    free_job_push: jax.Array   # i64

    # parallel joins [J]
    join_key: jax.Array        # i64 composite (scope_key<<8 | gateway), -1 free
    join_nin: jax.Array        # i32
    join_arrived: jax.Array    # [J, F_in] bool
    join_pay: jax.Array        # [J, 3V] i32 packed merged payload
    join_pos_stamp: jax.Array  # [J, V] i32: flow position that wrote each var
    join_map: hashmap.HashTable

    # timers [TM]
    timer_key: jax.Array       # i64, -1 free
    timer_due: jax.Array       # i64
    timer_aik: jax.Array       # i64
    timer_instance_key: jax.Array  # i64
    timer_elem: jax.Array      # i32 handler element
    timer_wf: jax.Array        # i32
    timer_map: hashmap.HashTable

    # message subscriptions [MS] (this partition as MESSAGE partition —
    # reference broker-core message correlation state; device redesign of
    # the oracle's StoredSubscription list). One open subscription per
    # (name, correlation) composite; a second OPEN on a live composite is
    # a loud overflow (kernel stat), not silent data loss.
    msub_ckey: jax.Array       # [MS] i64 corr_composite, -1 free
    msub_i32: jax.Array        # [MS, 4] (name, cvt, cbits, wi partition)
    msub_i64: jax.Array        # [MS, 2] (workflowInstanceKey, activityInstanceKey)
    msub_map: hashmap.HashTable  # composite → slot

    # stored messages with TTL [MG] (oracle StoredMessage dict)
    msg_key: jax.Array         # [MG] i64 message key, -1 free
    msg_ckey: jax.Array        # [MG] i64 corr_composite
    msg_i32: jax.Array         # [MG, 4] (name, cvt, cbits, interned msg id)
    msg_deadline: jax.Array    # [MG] i64 expiry timestamp
    msg_pay: jax.Array         # [MG, 3V] packed payload
    msg_map: hashmap.HashTable  # composite → slot

    # job worker subscriptions [S] (host-managed)
    sub_key: jax.Array         # i64 subscriber key
    sub_type: jax.Array        # i32 interned job type
    sub_worker: jax.Array      # i32 interned worker name
    sub_credits: jax.Array     # i32
    sub_timeout: jax.Array     # i64
    sub_valid: jax.Array       # bool
    # i32 round-robin cursor (global, like the oracle's _job_rr_cursor);
    # persisted by engine.device_backlog_activations across calls and
    # across snapshot/restore so drain fairness survives ticks and leaders
    sub_rr: jax.Array

    # key counters (i64 scalars)
    next_wf_key: jax.Array
    next_job_key: jax.Array

    # unpacked read views (lazy column slices — free inside jit; host code
    # and the kernel's read paths keep the original field names)
    @property
    def ei_key(self): return self.ei_i64[:, EIL_KEY]
    @property
    def ei_instance_key(self): return self.ei_i64[:, EIL_IKEY]
    @property
    def ei_job_key(self): return self.ei_i64[:, EIL_JOB_KEY]
    @property
    def ei_elem(self): return self.ei_i32[:, EI_ELEM]
    @property
    def ei_state(self): return self.ei_i32[:, EI_STATE]
    @property
    def ei_wf(self): return self.ei_i32[:, EI_WF]
    @property
    def ei_scope_slot(self): return self.ei_i32[:, EI_SCOPE]
    @property
    def ei_tokens(self): return self.ei_i32[:, EI_TOKENS]
    @property
    def job_key(self): return self.job_i64[:, JBL_KEY]
    @property
    def job_instance_key(self): return self.job_i64[:, JBL_IKEY]
    @property
    def job_aik(self): return self.job_i64[:, JBL_AIK]
    @property
    def job_deadline(self): return self.job_i64[:, JBL_DEADLINE]
    @property
    def job_state(self): return self.job_i32[:, JB_STATE]
    @property
    def job_elem(self): return self.job_i32[:, JB_ELEM]
    @property
    def job_wf(self): return self.job_i32[:, JB_WF]
    @property
    def job_type(self): return self.job_i32[:, JB_TYPE]
    @property
    def job_retries(self): return self.job_i32[:, JB_RETRIES]
    @property
    def job_worker(self): return self.job_i32[:, JB_WORKER]

    @property
    def capacity(self) -> int:
        return self.ei_i32.shape[0]

    @property
    def num_vars(self) -> int:
        return self.ei_pay.shape[1] // 3


def _pow2(n: int) -> int:
    p = 1
    while p < n:
        p *= 2
    return p


def make_state(
    capacity: int = 1 << 12,
    num_vars: int = 8,
    job_capacity: int = 0,
    join_capacity: int = 0,
    timer_capacity: int = 0,
    sub_capacity: int = 64,
    max_join_in: int = 4,
    msub_capacity: int = 0,
    msg_capacity: int = 0,
) -> EngineState:
    n = capacity
    m = job_capacity or capacity
    j = join_capacity or max(capacity // 8, 256)
    tm = timer_capacity or max(capacity // 8, 256)
    ms = msub_capacity or max(capacity // 2, 256)
    mg = msg_capacity or max(capacity // 4, 256)
    v = num_vars
    i64, i32 = jnp.int64, jnp.int32

    return EngineState(
        # ei_i32: elem=0, state=-1, wf=0, scope=-1, tokens=0, pending_bd=-1
        ei_i32=jnp.tile(jnp.array([[0, -1, 0, -1, 0, -1]], i32), (n, 1)),
        ei_i64=jnp.full((n, 3), -1, i64),
        ei_pay=jnp.zeros((n, 3 * v), i32),
        ei_map=hashmap.make(_pow2(8 * n)),
        ei_index=jnp.full((_pow2(8 * n),), -1, i32),
        free_ei=jnp.arange(n, dtype=i32),
        free_ei_pop=jnp.zeros((), i64),
        free_ei_push=jnp.asarray(n, i64),
        # job_i32: state=-1, elem/wf/type/retries/worker=0
        job_i32=jnp.tile(jnp.array([[-1, 0, 0, 0, 0, 0]], i32), (m, 1)),
        job_i64=jnp.full((m, 4), -1, i64),
        job_pay=jnp.zeros((m, 3 * v), i32),
        job_map=hashmap.make(_pow2(8 * m)),
        job_index=jnp.full((_pow2(8 * m),), -1, i32),
        free_job=jnp.arange(m, dtype=i32),
        free_job_pop=jnp.zeros((), i64),
        free_job_push=jnp.asarray(m, i64),
        join_key=jnp.full((j,), -1, i64),
        join_nin=jnp.zeros((j,), i32),
        join_arrived=jnp.zeros((j, max_join_in), bool),
        join_pay=jnp.zeros((j, 3 * v), i32),
        join_pos_stamp=jnp.full((j, v), -1, i32),
        join_map=hashmap.make(_pow2(4 * j)),
        timer_key=jnp.full((tm,), -1, i64),
        timer_due=jnp.full((tm,), -1, i64),
        timer_aik=jnp.full((tm,), -1, i64),
        timer_instance_key=jnp.full((tm,), -1, i64),
        timer_elem=jnp.zeros((tm,), i32),
        timer_wf=jnp.zeros((tm,), i32),
        timer_map=hashmap.make(_pow2(4 * tm)),
        msub_ckey=jnp.full((ms,), -1, i64),
        msub_i32=jnp.zeros((ms, 4), i32),
        msub_i64=jnp.full((ms, 2), -1, i64),
        msub_map=hashmap.make(_pow2(4 * ms)),
        msg_key=jnp.full((mg,), -1, i64),
        msg_ckey=jnp.full((mg,), -1, i64),
        msg_i32=jnp.zeros((mg, 4), i32),
        msg_deadline=jnp.full((mg,), -1, i64),
        msg_pay=jnp.zeros((mg, 3 * v), i32),
        msg_map=hashmap.make(_pow2(4 * mg)),
        sub_key=jnp.full((sub_capacity,), -1, i64),
        sub_type=jnp.zeros((sub_capacity,), i32),
        sub_worker=jnp.zeros((sub_capacity,), i32),
        sub_credits=jnp.zeros((sub_capacity,), i32),
        sub_timeout=jnp.zeros((sub_capacity,), i64),
        sub_valid=jnp.zeros((sub_capacity,), bool),
        sub_rr=jnp.zeros((), i32),
        next_wf_key=jnp.array(keyspace.WF_OFFSET, i64),
        next_job_key=jnp.array(keyspace.JOB_OFFSET, i64),
    )


def rebuild_lookup_state(state: EngineState) -> EngineState:
    """Recompute the key→slot indexes and fallback hashmaps from live
    table rows.

    Run at wave boundaries (drive entry), at snapshot restore, and on the
    engine's key-advance cadence — NOT per round: in-round lookups resolve
    through the direct-mapped index (rows created this wave are always
    index-hits, the index is collision-free within a window of 8N
    consecutive keys), and stale map/index entries are harmless because
    every lookup verifies the row's own key column. The invariant this
    maintains: the fallback map covers every instance live at the last
    rebuild."""
    import dataclasses as _dc

    import jax.numpy as _jnp

    n = state.ei_i32.shape[0]
    m = state.job_i32.shape[0]
    icap = state.ei_index.shape[0]
    jcap = state.job_index.shape[0]
    ei_live = state.ei_state >= 0
    job_live = state.job_state >= 0
    ei_idx = (
        _jnp.full((icap,), -1, _jnp.int32)
        .at[_jnp.where(ei_live, (state.ei_key // 5) & (icap - 1), icap).astype(_jnp.int32)]
        .set(_jnp.arange(n, dtype=_jnp.int32), mode="drop")
    )
    job_idx = (
        _jnp.full((jcap,), -1, _jnp.int32)
        .at[_jnp.where(job_live, (state.job_key // 5) & (jcap - 1), jcap).astype(_jnp.int32)]
        .set(_jnp.arange(m, dtype=_jnp.int32), mode="drop")
    )
    ei_map, _ = hashmap.rebuild_from(
        state.ei_map.keys.shape[0], state.ei_key,
        _jnp.arange(n, dtype=_jnp.int32), ei_live,
    )
    job_map, _ = hashmap.rebuild_from(
        state.job_map.keys.shape[0], state.job_key,
        _jnp.arange(m, dtype=_jnp.int32), job_live,
    )
    ei_free_mask = ~ei_live
    job_free_mask = ~job_live
    ei_rank = _jnp.cumsum(ei_free_mask.astype(_jnp.int32)) - ei_free_mask
    job_rank = _jnp.cumsum(job_free_mask.astype(_jnp.int32)) - job_free_mask
    free_ei = (
        _jnp.full((n,), n, _jnp.int32)
        .at[_jnp.where(ei_free_mask, ei_rank, n)]
        .set(_jnp.arange(n, dtype=_jnp.int32), mode="drop")
    )
    free_job = (
        _jnp.full((m,), m, _jnp.int32)
        .at[_jnp.where(job_free_mask, job_rank, m)]
        .set(_jnp.arange(m, dtype=_jnp.int32), mode="drop")
    )
    # the remaining maps are maintained in-round (tombstone churn);
    # rebuilding them here compacts the churn away on the same cadence
    def _iota(a):
        return _jnp.arange(a.shape[0], dtype=_jnp.int32)

    join_map, _ = hashmap.rebuild_from(
        state.join_map.keys.shape[0], state.join_key,
        _iota(state.join_key), state.join_key >= 0,
    )
    timer_map, _ = hashmap.rebuild_from(
        state.timer_map.keys.shape[0], state.timer_key,
        _iota(state.timer_key), state.timer_key >= 0,
    )
    msub_map, _ = hashmap.rebuild_from(
        state.msub_map.keys.shape[0], state.msub_ckey,
        _iota(state.msub_ckey), state.msub_ckey >= 0,
    )
    msg_map, _ = hashmap.rebuild_from(
        state.msg_map.keys.shape[0], state.msg_ckey,
        _iota(state.msg_ckey), state.msg_key >= 0,
    )
    return _dc.replace(
        state, ei_index=ei_idx, job_index=job_idx,
        ei_map=ei_map, job_map=job_map,
        join_map=join_map, timer_map=timer_map,
        msub_map=msub_map, msg_map=msg_map,
        free_ei=free_ei,
        free_ei_pop=_jnp.zeros((), _jnp.int64),
        free_ei_push=_jnp.sum(ei_free_mask, dtype=_jnp.int64),
        free_job=free_job,
        free_job_pop=_jnp.zeros((), _jnp.int64),
        free_job_push=_jnp.sum(job_free_mask, dtype=_jnp.int64),
    )

