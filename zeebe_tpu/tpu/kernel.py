"""THE step kernel: one jit'd application of all stream processors to a
record batch.

This replaces the reference's per-record hot loop
(``logstreams/.../processor/StreamProcessorController.java:296-399`` driving
``BpmnStepProcessor.processRecord`` and the job/incident processors) with a
single SIMD pass: every record in the batch is routed, guarded, and stepped
in parallel; follow-up records are produced into fixed emission slots and
compacted; state lands via deterministic scatters (conflicts resolved by
batch rank or flow position, never by scheduling). Feeding emissions back
as the next batch reproduces the oracle's serial log exactly — a batch is a
contiguous log range, and slot order (record-major, then emission slot)
equals the oracle's append order.

Kernel phases:
  A. hash lookups (record key / scope key / job aik → table slots)
  B. routing + step guards (BpmnStepProcessor.java:127-151 semantics)
  C. masked per-step compute: payload mappings, condition programs,
     parallel-join arrival merge, job state machine, timers
  D. key assignment (strided counters + prefix sums — KeyGenerator parity)
  E. emissions → compaction; state scatters; table insert/delete
"""

from __future__ import annotations

from typing import Dict, Tuple

import jax
import jax.numpy as jnp

from zeebe_tpu.engine import keyspace
from zeebe_tpu.models.transform.steps import BpmnStep as BS
from zeebe_tpu.protocol.enums import RecordType, ValueType
from zeebe_tpu.protocol.intents import (
    JobIntent as JI,
    MessageIntent as MI,
    MessageSubscriptionIntent as MS,
    TimerIntent as TI,
    WorkflowInstanceIntent as WI,
    WorkflowInstanceSubscriptionIntent as WS,
)
from zeebe_tpu.tpu import batch as rb
from zeebe_tpu.tpu import graph as graph_mod
from zeebe_tpu.tpu import hashmap
from zeebe_tpu.tpu import jit_registry
from zeebe_tpu.tpu import pallas_ops as pops
from zeebe_tpu.tpu.batch import RecordBatch
from zeebe_tpu.tpu.conditions import ERROR as TRI_ERROR
from zeebe_tpu.tpu.conditions import TRUE as TRI_TRUE
from zeebe_tpu.tpu.conditions import (
    VT_ABSENT,
    VT_BOOL as COND_VT_BOOL,
    VT_NUM as COND_VT_NUM,
    VT_STR as COND_VT_STR,
    eval_programs,
)
from zeebe_tpu.tpu.graph import DeviceGraph
from zeebe_tpu.tpu.state import (
    EngineState,
    corr_composite,
    pack_payload, unpack_payload,
    EI_ELEM, EI_STATE, EI_WF, EI_SCOPE, EI_TOKENS, EI_PENDING_BD,
    EIL_KEY, EIL_IKEY, EIL_JOB_KEY,
    JB_STATE, JB_ELEM, JB_WF, JB_TYPE, JB_RETRIES, JB_WORKER,
    JBL_KEY, JBL_IKEY, JBL_AIK, JBL_DEADLINE,
    MS_NAME, MS_CVT, MS_CBITS, MS_PART, MSL_WIKEY, MSL_AIK,
    MG_NAME, MG_CVT, MG_CBITS, MG_MSGID,
)

RT_EVENT = int(RecordType.EVENT)
RT_CMD = int(RecordType.COMMAND)
RT_REJ = int(RecordType.COMMAND_REJECTION)
VT_WI = int(ValueType.WORKFLOW_INSTANCE)
VT_JOB = int(ValueType.JOB)
VT_INCIDENT = int(ValueType.INCIDENT)
VT_TIMER = int(ValueType.TIMER)
VT_MSG = int(ValueType.MESSAGE)
VT_MSUB = int(ValueType.MESSAGE_SUBSCRIPTION)
VT_WISUB = int(ValueType.WORKFLOW_INSTANCE_SUBSCRIPTION)

_KEY_STEP = keyspace.STEP_SIZE


def _mxu_cumsum_i32(x):
    """Inclusive scan of small-int vectors via triangular matmuls on the
    MXU. XLA's TPU cumsum lowering (reduce-window) serializes badly at
    these lengths; two tiny matmuls are ~free. Exact while the running sum
    stays below 2^24 (batch sizes here are ≤ 2^20 of 0/1 counts) — which
    requires full f32 accumulation: the TPU matmul default feeds the MXU
    bf16 inputs (8 mantissa bits), so Precision.HIGHEST is load-bearing,
    not a nicety (row totals above 256 would round)."""
    n = x.shape[0]
    tile = 128
    if n % tile != 0:  # fall back off the fast path for odd sizes
        return jnp.cumsum(x)
    rows = n // tile
    hi = jax.lax.Precision.HIGHEST
    xf = x.astype(jnp.float32).reshape(rows, tile)
    upper = jnp.triu(jnp.ones((tile, tile), jnp.float32))
    lower_strict = jnp.tril(jnp.ones((rows, rows), jnp.float32), k=-1)
    within = jnp.matmul(xf, upper, precision=hi)  # [rows, tile] row-wise scan
    row_tot = within[:, -1]                       # [rows]
    row_off = jnp.matmul(lower_strict, row_tot, precision=hi)
    return (within + row_off[:, None]).reshape(n).astype(x.dtype)


def _excl_cumsum(x):
    c = _mxu_cumsum_i32(x)
    return c - x


def _first_true_indices(avail, k):
    """Indices of the first ``k`` True entries of ``avail`` (padded with
    ``len(avail)``) — the free-slot scan. ``jnp.nonzero`` lowers to a slow
    serialized cumsum+scatter on TPU; this uses the MXU scan + one bounded
    scatter."""
    cap = avail.shape[0]
    rank = _excl_cumsum(avail.astype(jnp.int32))
    tgt = jnp.where(avail & (rank < k), rank, k)
    return (
        jnp.full((k,), cap, jnp.int32)
        .at[tgt]
        .set(jnp.arange(cap, dtype=jnp.int32), mode="drop")
    )


def _last_writer(slots, mask, size):
    """True for the highest-batch-rank writer per target slot (deterministic
    conflict resolution for duplicate scatters). Small batches (the serving
    wave) use an O(B²) comparison triangle — no gather/scatter pair per
    call site; large drive-loop batches keep the scatter-max + read-back
    form (same split as ``_first_per_key``)."""
    n = slots.shape[0]
    if n <= 2048:
        later_same = (
            (slots[:, None] == slots[None, :])
            & mask[None, :]
            & jnp.triu(jnp.ones((n, n), bool), 1)
        )
        return mask & ~jnp.any(later_same, axis=1)
    rank = jnp.arange(n, dtype=jnp.int32)
    tgt = jnp.where(mask, slots, size)
    best = jnp.full((size + 1,), -1, jnp.int32).at[tgt].max(
        jnp.where(mask, rank, -1), mode="drop"
    )
    return mask & (best[jnp.clip(tgt, 0, size)] == rank)


def _first_per_key(keys, mask):
    """[B] bool: row i is the FIRST masked row carrying its key (row order
    = log order; intra-batch duplicate commands on one entity serialize
    to first-wins, matching the oracle's sequential pop-then-no-op).
    Small batches (the serving wave) use an O(B²) comparison triangle —
    cheap, no extra gathers/scatters; large drive-loop batches switch to
    a stable two-key sort to avoid the B² blowup."""
    b = keys.shape[0]
    if b <= 2048:
        earlier_same = (
            (keys[:, None] == keys[None, :])
            & mask[None, :]
            & jnp.tril(jnp.ones((b, b), bool), -1)
        )
        return ~jnp.any(earlier_same, axis=1)
    idx = jnp.arange(b, dtype=jnp.int64)
    # unmasked rows get unique sentinels so they never collide
    k = jnp.where(mask, keys, jnp.int64(-1) - idx)
    k_sorted, idx_sorted = jax.lax.sort((k, idx), num_keys=2)
    first_sorted = jnp.concatenate(
        [jnp.ones((1,), bool), k_sorted[1:] != k_sorted[:-1]]
    )
    return jnp.zeros((b,), bool).at[idx_sorted].set(first_sorted)


def _indexed_lookup_multi(lookups):
    """N parallel key → (found, slot) resolutions via the direct-mapped
    indexes with hashmap fallback; both paths verify against the table's
    own key column, so stale index/map entries (deleted rows, reused
    slots) resolve to not-found without any per-round index maintenance.

    Each lookup is ``(index, key_col, fallback_map, keys, want, cap)``;
    returns ``[(found, slot), ...]`` in input order. The index probes and
    the two key-column verifies run through ``pops.fused_gather_rows``,
    so the N lookups share one gather per stage (the indexes are all i32,
    the key columns all i64 — each stage's tables concatenate) instead of
    issuing 3 gathers apiece."""
    # keys are stride-5 (keyspace: one residue class per entity family),
    # so indexing on key // 5 packs them densely — the collision-free
    # window is icap * 5 consecutive keys, not icap (a parallel-split /
    # multi-instance wave can allocate hundreds of thousands of keys;
    # indexing on the raw key wrapped the window within ONE wave and
    # silently dropped ~4% of fork-join completions at bench scale)
    cands = pops.fused_gather_rows(
        [index for index, *_ in lookups],
        [
            pops.GatherOp(
                i, ((keys // 5) & (index.shape[0] - 1)).astype(jnp.int32)
            )
            for i, (index, _kc, _fb, keys, _w, _c) in enumerate(lookups)
        ],
    )
    cand_clips = [
        jnp.clip(cand, 0, lk[5] - 1) for cand, lk in zip(cands, lookups)
    ]
    key_cols = [kc for _i, kc, *_ in lookups]
    kc_hit = pops.fused_gather_rows(
        key_cols, [pops.GatherOp(i, cc) for i, cc in enumerate(cand_clips)]
    )
    hits = [
        lk[4] & (cand >= 0) & (kc == lk[3])
        for lk, cand, kc in zip(lookups, cands, kc_hit)
    ]
    misses = [lk[4] & ~hit for lk, hit in zip(lookups, hits)]
    # fallback probe for clobbered index entries and genuinely absent
    # keys; with no misses the probe's while_loop exits after its first
    # condition check (cheaper than a lax.cond, whose operand copies cost
    # more than the empty loop — measured)
    fbs = [
        pops.lookup(lk[2], lk[3], miss) for lk, miss in zip(lookups, misses)
    ]
    fb_clips = [
        jnp.clip(fb_slot, 0, lk[5] - 1)
        for (_f, fb_slot), lk in zip(fbs, lookups)
    ]
    kc_fb = pops.fused_gather_rows(
        key_cols, [pops.GatherOp(i, fc) for i, fc in enumerate(fb_clips)]
    )
    out = []
    for lk, hit, miss, (fb_found, _s), fb_clip, kc, cand_clip in zip(
        lookups, hits, misses, fbs, fb_clips, kc_fb, cand_clips
    ):
        fb_ok = miss & fb_found & (kc == lk[3])
        out.append((hit | fb_ok, jnp.where(hit, cand_clip, fb_clip)))
    return out


def _indexed_lookup(index, key_col, fallback_map, keys, want, cap):
    """Single-lookup form of ``_indexed_lookup_multi`` (tests, tools)."""
    return _indexed_lookup_multi(
        [(index, key_col, fallback_map, keys, want, cap)]
    )[0]


def _apply_mappings(graph, wf, elem, src_vt, src_num, src_sid, is_input):
    """Vectorized MappingProcessor.extract (input) source selection.

    Returns (dst_from [B, V] source column per target column or -1,
    has_mappings [B], root [B], err [B] — any listed source absent).
    """
    b = wf.shape[0]
    v = src_vt.shape[1]
    if is_input:
        m_src, m_dst, m_n, m_root = (
            graph.in_map_src, graph.in_map_dst, graph.in_map_n, graph.in_root
        )
    else:
        m_src, m_dst, m_n, m_root = (
            graph.out_map_src, graph.out_map_dst, graph.out_map_n, graph.out_root
        )
    k_max = m_src.shape[2]
    rows = jnp.arange(b, dtype=jnp.int32)
    dst_from = jnp.full((b, v), -1, jnp.int32)
    err = jnp.zeros((b,), bool)
    for k in range(k_max):
        src = m_src[wf, elem, k]
        dst = m_dst[wf, elem, k]
        active = src >= 0
        src_c = jnp.clip(src, 0, v - 1)
        err = err | (active & (src_vt[rows, src_c] == VT_ABSENT))
        dst_c = jnp.where(active, dst, v)
        dst_from = dst_from.at[rows, dst_c].set(src, mode="drop")
    has = m_n[wf, elem] > 0
    root = m_root[wf, elem]
    return dst_from, has, root, err


def _select_by_map(dst_from, vt, num, sid):
    """payload'[v] = payload[dst_from[v]] (absent where dst_from = -1)."""
    c = jnp.clip(dst_from, 0, vt.shape[1] - 1)
    got = dst_from >= 0
    take = lambda a, fill: jnp.where(got, jnp.take_along_axis(a, c, axis=1), fill)  # noqa: E731
    return (
        take(vt, jnp.int8(VT_ABSENT)),
        take(num, 0.0),
        take(sid, 0),
    )


def scope_to_local(ei_i32, shard_index, local_rows):
    """Translate the EI_SCOPE parent-slot column from the GLOBAL row space
    into one shard's LOCAL row space (residency-routed sharded state).

    At rest the sharded tables store parent slots globally so host readers
    (``_demote_instance``'s scope-tree walk, snapshots) see one coherent
    space. The routed step runs the kernel on a local row block, whose
    slot arithmetic is local — so in-block parents shift down by the block
    base, sentinels (< 0) pass through, and out-of-block parents become the
    POISON slot ``local_rows`` (one past the last local row: never equal
    to any real slot, so parent-slot comparisons can't alias). A gather
    through the POISON slot clamps to the LAST local row — JAX clamps
    out-of-range indices to the valid edge, not to row 0 — so the read
    itself returns real (wrong-parent) data; it is harmless only because
    the routing policy routes exclusively instances wholly resident in
    the block: poisoned parents belong to instances the wave does not
    step, their lanes stay masked, and :func:`scope_to_global` restores
    the original global slot afterwards."""
    base = shard_index * local_rows
    g = ei_i32[:, EI_SCOPE]
    local = jnp.where(
        g < 0,
        g,
        jnp.where(
            (g >= base) & (g < base + local_rows), g - base, local_rows
        ),
    )
    return ei_i32.at[:, EI_SCOPE].set(local.astype(ei_i32.dtype))


def scope_to_global(ei_i32, prev_global_scope, shard_index, local_rows):
    """Inverse of :func:`scope_to_local` after the kernel ran on the local
    block: local slots shift up by the block base, sentinels pass through,
    and rows still carrying the POISON slot were untouched by the wave —
    their original global parent (``prev_global_scope``) is restored."""
    base = shard_index * local_rows
    loc = ei_i32[:, EI_SCOPE]
    back = jnp.where(
        loc < 0,
        loc,
        jnp.where(loc == local_rows, prev_global_scope, loc + base),
    )
    return ei_i32.at[:, EI_SCOPE].set(back.astype(ei_i32.dtype))


def step_kernel(
    graph: DeviceGraph, state: EngineState, batch: RecordBatch, now,
    synthetic_workers: bool = False, partition_id=0,
) -> Tuple[EngineState, RecordBatch, dict]:
    """Process one committed-record batch; returns (state', emissions, stats).

    Emissions are compacted in oracle append order; ``emissions.src`` links
    each emission to its source row (host assigns positions/responses).

    ``synthetic_workers`` (static, bench-only): every ACTIVATED push also
    emits an instant COMPLETE command — the worker round-trip of
    ``gateway/.../impl/subscription/job/JobSubscriber.java:51`` without
    leaving the device.
    """
    b = batch.size
    v = state.num_vars
    e_w = graph.emit_width
    n_cap = state.capacity
    m_cap = state.job_key.shape[0]
    j_cap = state.join_key.shape[0]
    t_cap = state.timer_key.shape[0]
    s_cap = state.sub_key.shape[0]
    rows = jnp.arange(b, dtype=jnp.int32)

    valid = batch.valid
    rt, vt_, it = batch.rtype, batch.vtype, batch.intent
    wf_c = jnp.clip(batch.wf, 0, graph.elem_type.shape[0] - 1)
    el_c = jnp.clip(batch.elem, 0, graph.elem_type.shape[1] - 1)
    # hot-path per-element graph reads (round 9a): the meta scalar row,
    # the step table, the conditioned/parallel flow fans and the timer
    # duration all index by the same (workflow, element) pair — flattened
    # to one [W*E, K] i32 table (timer_dur rides as two bitcast planes)
    # they collapse into ONE row gather instead of five
    n_elems = graph.elem_type.shape[1]
    n_intents = graph.step_table.shape[2]
    cond_fan = graph.cond_flows.shape[2]
    fork_fan = graph.out_flows.shape[2]
    em_cols = graph.elem_meta.shape[2]
    with jax.named_scope("zb_gather"):
        g_flat = jnp.concatenate(
            [
                graph.elem_meta.reshape(-1, em_cols),
                graph.step_table.reshape(-1, n_intents),
                graph.cond_flows.reshape(-1, cond_fan),
                graph.cond_prog.reshape(-1, cond_fan),
                graph.out_flows.reshape(-1, fork_fan),
                pops.vec64_to_planes(graph.timer_dur.reshape(-1)),
            ],
            axis=1,
        )
        (g_row,) = pops.fused_gather_rows(
            [g_flat], [pops.GatherOp(0, wf_c * n_elems + el_c)]
        )
    _go = 0
    emeta = g_row[:, _go : _go + em_cols]; _go += em_cols
    step_row = g_row[:, _go : _go + n_intents]; _go += n_intents
    cflow = g_row[:, _go : _go + cond_fan]; _go += cond_fan
    cprog = g_row[:, _go : _go + cond_fan]; _go += cond_fan
    fork_flows = g_row[:, _go : _go + fork_fan]; _go += fork_fan
    timer_dur_rec = pops.planes_to_i64(g_row[:, _go : _go + 2])[:, 0]

    # ---------------- A. lookups ----------------
    is_wi = valid & (vt_ == VT_WI)
    wi_ev = is_wi & (rt == RT_EVENT)
    wi_cmd = is_wi & (rt == RT_CMD)
    is_job = valid & (vt_ == VT_JOB)
    job_cmd = is_job & (rt == RT_CMD)
    job_ev = is_job & (rt == RT_EVENT)
    timer_cmd = valid & (vt_ == VT_TIMER) & (rt == RT_CMD)
    # message family (reference broker-core message correlation — the
    # MESSAGE/MESSAGE_SUBSCRIPTION processors on the message partition and
    # CorrelateWorkflowInstanceSubscription on the workflow partition;
    # correlation columns ride type_id=name, retries=corr vt, worker=corr
    # bits — fields the message rows never use for their job meanings)
    msg_pub = valid & (vt_ == VT_MSG) & (rt == RT_CMD) & (it == int(MI.PUBLISH))
    msg_del = valid & (vt_ == VT_MSG) & (rt == RT_CMD) & (it == int(MI.DELETE))
    ms_open = valid & (vt_ == VT_MSUB) & (rt == RT_CMD) & (it == int(MS.OPEN))
    ms_close = valid & (vt_ == VT_MSUB) & (rt == RT_CMD) & (it == int(MS.CLOSE))
    wisub_corr = (
        valid & (vt_ == VT_WISUB) & (rt == RT_CMD) & (it == int(WS.CORRELATE))
    )

    # the three element-instance lookups (record key / scope key / job
    # activity key) resolve through the direct-mapped index: keys are
    # engine-allocated and sequential, so index[key & (cap-1)] hits for
    # everything created within the last 8N keys; a hit is verified
    # against the row's own key column, and the rare miss (congruent-key
    # clobber) falls back to the per-wave-rebuilt hashmap. No per-record
    # probe loop on the hot path (reference: ElementInstanceIndex is a
    # Long2ObjectHashMap — this is its O(1) vectorized analogue).
    keys3 = jnp.concatenate([batch.key, batch.scope_key, batch.aux_key])
    want3 = jnp.concatenate(
        [wi_ev, wi_ev & (batch.scope_key >= 0),
         job_ev | timer_cmd | wisub_corr]
    )
    with jax.named_scope("zb_lookups"):
        (ei3_found, ei3_slot), (jb_found, jb_slot) = _indexed_lookup_multi([
            (state.ei_index, state.ei_key, state.ei_map, keys3, want3, n_cap),
            (state.job_index, state.job_key, state.job_map,
             batch.key, job_cmd & (batch.key >= 0), m_cap),
        ])
    ei_found, ei_slot = ei3_found[:b], ei3_slot[:b]
    sc_found, sc_slot = ei3_found[b : 2 * b], ei3_slot[b : 2 * b]
    aik_found, aik_slot = ei3_found[2 * b :], ei3_slot[2 * b :]
    if graph.has_timers:
        tm_found, tm_slot = pops.lookup(
            state.timer_map, batch.key, timer_cmd & (batch.key >= 0)
        )
    else:
        tm_found = jnp.zeros((b,), bool)
        tm_slot = jnp.zeros((b,), jnp.int32)
    ms_cap = state.msub_ckey.shape[0]
    mg_cap = state.msg_key.shape[0]
    if graph.has_messages:
        # composite (message name, correlation value) — the store key for
        # both subscription and stored-message probes
        ckey = corr_composite(batch.type_id, batch.retries, batch.worker)
        msub_probe = msg_pub | ms_open | ms_close
        msub_found, msub_slot = pops.lookup(state.msub_map, ckey, msub_probe)
        mmsg_probe = msg_pub | ms_open | msg_del
        mmsg_found, mmsg_slot = pops.lookup(state.msg_map, ckey, mmsg_probe)
    else:
        ckey = jnp.full((b,), -1, jnp.int64)
        msub_found = jnp.zeros((b,), bool)
        msub_slot = jnp.zeros((b,), jnp.int32)
        mmsg_found = jnp.zeros((b,), bool)
        mmsg_slot = jnp.zeros((b,), jnp.int32)
    msub_clip = jnp.clip(msub_slot, 0, ms_cap - 1)
    mmsg_clip = jnp.clip(mmsg_slot, 0, mg_cap - 1)
    ei_clip = jnp.clip(ei_slot, 0, n_cap - 1)
    sc_clip = jnp.clip(sc_slot, 0, n_cap - 1)
    aik_clip = jnp.clip(aik_slot, 0, n_cap - 1)
    jb_clip = jnp.clip(jb_slot, 0, m_cap - 1)
    tm_clip = jnp.clip(tm_slot, 0, t_cap - 1)

    # ONE fused gather pass feeds every phase-B/C read: each role's rows
    # (element-instance i32/i64, payload, job, timer columns, message
    # store) are pulled once per wave through pops.fused_gather_rows — on
    # the pallas path a single serial launch with the tables VMEM-resident,
    # on the XLA path one concatenated gather per table (a [B, 6] row
    # gather costs the same as a [B] column gather: the cost is per-index
    # issue, not bytes). Every per-role read below slices these gathered
    # rows instead of issuing its own gather.
    with jax.named_scope("zb_gather"):
        g_tables = [
            state.ei_i32, state.ei_i64, state.ei_pay,
            state.job_i32, state.job_i64, state.job_pay,
            state.timer_elem, state.timer_wf,
        ]
        g_ops = [
            pops.GatherOp(0, ei_clip), pops.GatherOp(0, sc_clip),
            pops.GatherOp(0, aik_clip),
            pops.GatherOp(1, aik_clip), pops.GatherOp(1, ei_clip),
            pops.GatherOp(2, sc_clip), pops.GatherOp(2, aik_clip),
            pops.GatherOp(2, ei_clip),
            pops.GatherOp(3, jb_clip), pops.GatherOp(4, jb_clip),
            pops.GatherOp(5, jb_clip),
            pops.GatherOp(6, tm_clip), pops.GatherOp(7, tm_clip),
        ]
        if graph.has_messages:
            gm = len(g_tables)
            g_tables += [
                state.msg_i32, state.msg_key, state.msg_pay,
                state.msub_i32, state.msub_i64,
            ]
            g_ops += [
                pops.GatherOp(gm, mmsg_clip),
                pops.GatherOp(gm + 1, mmsg_clip),
                pops.GatherOp(gm + 2, mmsg_clip),
                pops.GatherOp(gm + 3, msub_clip),
                pops.GatherOp(gm + 4, msub_clip),
            ]
        g = pops.fused_gather_rows(g_tables, g_ops)
    (ei_rows, sc_rows, aik_rows, aik_i64_rows, ei_i64_rows,
     sc_pay_rows, aik_pay_rows, ei_pay_rows,
     jb_i32_rows, jb_i64_rows, jb_pay_rows,
     tm_elem_rows, tm_wf_rows) = g[:13]
    if graph.has_messages:
        (mmsg_i32_rows, mmsg_key_rows, mmsg_pay_rows,
         msub_i32_rows, msub_i64_rows) = g[13:]
    inst_state = jnp.where(ei_found, ei_rows[:, EI_STATE], -1)
    scope_state = jnp.where(sc_found, sc_rows[:, EI_STATE], -1)

    # second-level reads: scope-of-scope keys resolve through slots that
    # only exist after the first gather pass lands (a row's parent slot is
    # a COLUMN of its gathered row) — one more fused pass, one gather
    scope_parent = jnp.where(sc_found, sc_rows[:, EI_SCOPE], -1)
    inst_scope_slot = aik_rows[:, EI_SCOPE]
    with jax.named_scope("zb_gather"):
        sp_key_g, is_key_g = pops.fused_gather_rows(
            [state.ei_key],
            [pops.GatherOp(0, jnp.clip(scope_parent, 0, n_cap - 1)),
             pops.GatherOp(0, jnp.clip(inst_scope_slot, 0, n_cap - 1))],
        )
    scope_parent_key = jnp.where(scope_parent >= 0, sp_key_g, -1)
    inst_scope_key = jnp.where(inst_scope_slot >= 0, is_key_g, -1)

    # ---------------- B. routing + guards ----------------
    m_create = wi_cmd & (it == int(WI.CREATE)) & (batch.wf >= 0)
    m_created_ev = wi_ev & (it == int(WI.CREATED))

    g_own = (
        (it == int(WI.ELEMENT_READY))
        | (it == int(WI.ELEMENT_ACTIVATED))
        | (it == int(WI.ELEMENT_COMPLETING))
    )
    g_flow = (
        (it == int(WI.END_EVENT_OCCURRED))
        | (it == int(WI.GATEWAY_ACTIVATED))
        | (it == int(WI.START_EVENT_OCCURRED))
        | (it == int(WI.SEQUENCE_FLOW_TAKEN))
        | (it == int(WI.BOUNDARY_EVENT_OCCURRED))
    )
    # pending interrupting-boundary continuation (the oracle's
    # _pending_boundary dict as the instance column EI_PENDING_BD):
    # ELEMENT_TERMINATED with a pending boundary processes while the scope
    # stays ACTIVATED (the token moves to the boundary event)
    pending_bd = jnp.where(
        ei_found, ei_rows[:, EI_PENDING_BD], -1
    )
    guard = jnp.where(
        g_own,
        ei_found & (inst_state == it),
        jnp.where(
            it == int(WI.ELEMENT_COMPLETED),
            sc_found & (scope_state == int(WI.ELEMENT_ACTIVATED)),
            jnp.where(
                it == int(WI.ELEMENT_TERMINATED),
                sc_found & jnp.where(
                    pending_bd >= 0,
                    (scope_state == int(WI.ELEMENT_ACTIVATED))
                    | (scope_state == int(WI.ELEMENT_TERMINATING)),
                    scope_state == int(WI.ELEMENT_TERMINATING),
                ),
                jnp.where(
                    g_flow, sc_found & (scope_state == int(WI.ELEMENT_ACTIVATED)), True
                ),
            ),
        ),
    )
    shall = ei_found | sc_found
    stepped = wi_ev & ~m_created_ev & shall & guard & (batch.wf >= 0) & (batch.elem >= 0)
    # per-row intent select from the gathered step row: a one-hot
    # multiply-sum over the (small, static) intent axis — no second gather
    step_id = jnp.where(
        stepped,
        jnp.sum(
            jnp.where(
                jnp.arange(n_intents, dtype=jnp.int32)[None, :]
                == jnp.clip(it, 0, n_intents - 1)[:, None],
                step_row,
                0,
            ),
            axis=1,
        ),
        int(BS.NONE),
    )

    def m_step(s):
        return stepped & (step_id == int(s))

    m_take = m_step(BS.TAKE_SEQUENCE_FLOW)
    m_consume = m_step(BS.CONSUME_TOKEN)
    m_xsplit = m_step(BS.EXCLUSIVE_SPLIT)
    m_createjob = m_step(BS.CREATE_JOB)
    m_inmap = m_step(BS.APPLY_INPUT_MAPPING)
    m_outmap = m_step(BS.APPLY_OUTPUT_MAPPING)
    m_actgw = m_step(BS.ACTIVATE_GATEWAY)
    m_startst = m_step(BS.START_STATEFUL_ELEMENT)
    m_trigend = m_step(BS.TRIGGER_END_EVENT)
    m_trigstart = m_step(BS.TRIGGER_START_EVENT)
    m_complete_proc = m_step(BS.COMPLETE_PROCESS)
    m_psplit = m_step(BS.PARALLEL_SPLIT)
    m_pmerge = m_step(BS.PARALLEL_MERGE)
    m_timer_step = m_step(BS.CREATE_TIMER)
    m_subscribe = m_step(BS.SUBSCRIBE_TO_INTERMEDIATE_MESSAGE)
    m_term_job = m_step(BS.TERMINATE_JOB_TASK)
    m_term_catch = m_step(BS.TERMINATE_CATCH_EVENT)
    m_term_elem = m_step(BS.TERMINATE_ELEMENT)
    m_mi = m_step(BS.MULTI_INSTANCE_SPLIT)

    # job commands
    job_state = jnp.where(jb_found, jb_i32_rows[:, JB_STATE], -1)
    m_jcreate = job_cmd & (it == int(JI.CREATE))
    m_jactivate = job_cmd & (it == int(JI.ACTIVATE))
    m_jcomplete = job_cmd & (it == int(JI.COMPLETE))
    m_jfail = job_cmd & (it == int(JI.FAIL))
    m_jtimeout = job_cmd & (it == int(JI.TIME_OUT))
    m_jretries = job_cmd & (it == int(JI.UPDATE_RETRIES))
    m_jcancel = job_cmd & (it == int(JI.CANCEL))

    activatable = (
        (job_state == int(JI.CREATED))
        | (job_state == int(JI.FAILED))
        | (job_state == int(JI.TIMED_OUT))
    )
    completable = (job_state == int(JI.ACTIVATED)) | (job_state == int(JI.TIMED_OUT))
    jact_ok = m_jactivate & jb_found & activatable
    jact_rej = m_jactivate & ~(jb_found & activatable)
    jcomp_ok = m_jcomplete & jb_found & completable
    jcomp_rej = m_jcomplete & ~(jb_found & completable)
    jfail_ok = m_jfail & jb_found & (job_state == int(JI.ACTIVATED))
    jfail_rej = m_jfail & ~(jb_found & (job_state == int(JI.ACTIVATED)))
    jtime_ok = m_jtimeout & jb_found & (job_state == int(JI.ACTIVATED))
    jtime_rej = m_jtimeout & ~(jb_found & (job_state == int(JI.ACTIVATED)))
    jret_ok = m_jretries & jb_found & (job_state == int(JI.FAILED)) & (batch.retries > 0)
    jret_badv = m_jretries & jb_found & (job_state == int(JI.FAILED)) & (batch.retries <= 0)
    jret_rej = m_jretries & ~(jb_found & (job_state == int(JI.FAILED)))
    jcan_ok = m_jcancel & jb_found
    jcan_rej = m_jcancel & ~jb_found

    # job events (workflow-side processors + activation pool + incidents)
    jev_created = job_ev & (it == int(JI.CREATED))
    jev_completed = job_ev & (it == int(JI.COMPLETED)) & aik_found
    m_actpool = job_ev & (
        (it == int(JI.CREATED))
        | (it == int(JI.TIMED_OUT))
        | (it == int(JI.FAILED))
        | (it == int(JI.RETRIES_UPDATED))
    ) & (batch.retries > 0)
    jev_fail_noretry = job_ev & (it == int(JI.FAILED)) & (batch.retries <= 0)

    # timer commands
    m_tcreate = timer_cmd & (it == int(TI.CREATE))
    ttrig_ok = timer_cmd & (it == int(TI.TRIGGER)) & tm_found
    ttrig_rej = timer_cmd & (it == int(TI.TRIGGER)) & ~tm_found
    # two CANCELs for ONE timer key legitimately share a batch (the engine
    # emits a disarm cancel AND a terminate-catch-scan cancel for the same
    # armed timer; under the wave drain both land in one step). The oracle
    # pops the timer on the first and the second is a silent no-op —
    # tm_found alone sees the PRE-step table and would emit CANCELED
    # twice, so only the FIRST cancel row per key stays eligible.
    m_tcancel = timer_cmd & (it == int(TI.CANCEL))
    tcan_ok = m_tcancel & tm_found & _first_per_key(batch.key, m_tcancel)
    # timer trigger resumes the catch event when still active
    ttrig_inst = ttrig_ok & aik_found & (
        jnp.where(aik_found, aik_rows[:, EI_STATE], -1) == int(WI.ELEMENT_ACTIVATED)
    )
    # boundary-event triggers: the timer's handler element is a BOUNDARY
    # event attached to the instance's element (oracle _boundary_for +
    # _fire_boundary_event); interrupting boundaries terminate the host
    # and continue at the boundary when ELEMENT_TERMINATED processes
    # the trigger's handler element comes from the TIMER TABLE (a
    # host-staged TRIGGER command does not carry element columns)
    trig_elem = jnp.where(tm_found, tm_elem_rows, batch.elem)
    trig_wf = jnp.where(tm_found, tm_wf_rows, 0)
    if graph.has_boundaries:
        trig_elem_c = jnp.clip(trig_elem, 0, graph.elem_type.shape[1] - 1)
        trig_wf_c = jnp.clip(trig_wf, 0, graph.elem_type.shape[0] - 1)
        trig_is_bd = graph.bd_is_boundary[trig_wf_c, trig_elem_c]
        ttrig_catch = ttrig_inst & ~trig_is_bd
        ttrig_bd = ttrig_inst & trig_is_bd
        ttrig_bd_int = ttrig_bd & graph.bd_host_interrupt[trig_wf_c, trig_elem_c]
        ttrig_bd_non = ttrig_bd & ~graph.bd_host_interrupt[trig_wf_c, trig_elem_c]
        # arming/disarming rides the host element's lifecycle events
        # (oracle _arm_boundary_events / _disarm_boundary_events)
        lifecycle_ok = (
            wi_ev & ~m_created_ev & shall & guard
            & (batch.wf >= 0) & (batch.elem >= 0)
        )
        bd_n = emeta[:, graph_mod.EM_BD_COUNT]
        m_arm = lifecycle_ok & (it == int(WI.ELEMENT_ACTIVATED)) & (bd_n > 0)
        m_disarm_bd = lifecycle_ok & (
            (it == int(WI.ELEMENT_COMPLETING))
            | (it == int(WI.ELEMENT_TERMINATING))
        ) & (bd_n > 0)
        # TERMINATE_CATCH_EVENT re-scans timers by aik (the oracle's
        # _h_terminate_catch_event scan — a SECOND cancel for timers the
        # disarm already canceled, since state only mutates when the
        # commands process)
        m_cancel_timers = m_term_catch
        # TERMINATED with a pending boundary: continue the token at the
        # boundary element with the stored trigger payload
        m_bd_continue = (
            lifecycle_ok & (it == int(WI.ELEMENT_TERMINATED)) & (pending_bd >= 0)
        )
    else:
        zbb = jnp.zeros((b,), bool)
        ttrig_catch = ttrig_inst
        ttrig_bd = ttrig_bd_int = ttrig_bd_non = zbb
        m_arm = m_disarm_bd = m_bd_continue = zbb
        m_cancel_timers = m_term_catch
        bd_n = jnp.zeros((b,), jnp.int32)
    # rows on boundary-carrying elements re-slot their own step output
    # AFTER the arm/disarm records (the oracle writes arms/cancels first)
    has_bd = bd_n > 0

    # message correlation guards (oracle: _process_message_command /
    # _process_message_subscription / _process_wi_subscription)
    if graph.has_messages:
        msgid = batch.aux2_key.astype(jnp.int32)  # interned message id, 0 none
        pub_dup = (
            msg_pub & mmsg_found & (msgid > 0)
            & (mmsg_i32_rows[:, MG_MSGID] == msgid)
        )
        # one live slot per composite (the device store is hashmap-keyed):
        # a second TTL-store or OPEN on an occupied composite REJECTS that
        # record with an explicit reason — a legal-but-unsupported workload
        # degrades per-record, never crashes the partition
        pub_chain = msg_pub & ~pub_dup & (batch.deadline > 0) & mmsg_found
        pub_ok = msg_pub & ~pub_dup & ~pub_chain
        pub_store = pub_ok & (batch.deadline > 0)   # TTL rides the deadline col
        pub_nostore = pub_ok & ~(batch.deadline > 0)
        pub_corr = pub_ok & msub_found
        open_dup = ms_open & msub_found
        open_ok = ms_open & ~msub_found
        open_corr = open_ok & mmsg_found
        close_ok = (
            ms_close & msub_found
            & (msub_i64_rows[:, MSL_AIK] == batch.aux_key)
            & (msub_i64_rows[:, MSL_WIKEY] == batch.instance_key)
        )
        del_ok = msg_del & mmsg_found & (mmsg_key_rows == batch.key)
        corr_live = wisub_corr & aik_found & (
            jnp.where(aik_found, aik_rows[:, EI_STATE], -1)
            == int(WI.ELEMENT_ACTIVATED)
        )
        corr_rej = wisub_corr & ~corr_live
        # boundary-message correlate: the message name matches one of the
        # instance element's attached boundary events (oracle
        # _process_wi_subscription -> _boundary_for by message name)
        ci_elem = jnp.where(aik_found, aik_rows[:, EI_ELEM], 0)
        ci_wf = jnp.where(aik_found, aik_rows[:, EI_WF], 0)
        ci_elem_c = jnp.clip(ci_elem, 0, graph.elem_type.shape[1] - 1)
        ci_wf_c = jnp.clip(ci_wf, 0, graph.elem_type.shape[0] - 1)
        if graph.has_boundaries:
            bd_cnt_i = graph.bd_count[ci_wf_c, ci_elem_c]
            corr_bd_elem = jnp.full((b,), -1, jnp.int32)
            corr_bd_interrupt = jnp.zeros((b,), bool)
            for bslot in range(graph.bd_elem.shape[2]):
                match_b = (
                    (bslot < bd_cnt_i)
                    & (graph.bd_msg[ci_wf_c, ci_elem_c, bslot] == batch.type_id)
                    & (graph.bd_msg[ci_wf_c, ci_elem_c, bslot] > 0)
                    & (corr_bd_elem < 0)
                )
                corr_bd_elem = jnp.where(
                    match_b, graph.bd_elem[ci_wf_c, ci_elem_c, bslot],
                    corr_bd_elem,
                )
                corr_bd_interrupt = jnp.where(
                    match_b,
                    graph.bd_interrupt[ci_wf_c, ci_elem_c, bslot],
                    corr_bd_interrupt,
                )
            corr_is_bd = corr_live & (corr_bd_elem >= 0)
        else:
            corr_bd_elem = jnp.full((b,), -1, jnp.int32)
            corr_bd_interrupt = jnp.zeros((b,), bool)
            corr_is_bd = jnp.zeros((b,), bool)
        corr_inst_ok = corr_live & ~corr_is_bd
        corr_bd_int = corr_is_bd & corr_bd_interrupt
        corr_bd_non = corr_is_bd & ~corr_bd_interrupt
        # subscribe step: correlation key extracted from the payload column.
        # Accepted types mirror the oracle's isinstance(corr, (str, int)):
        # strings, ints, and bools (a Python bool IS an int); floats raise
        # the same IO_MAPPING incident the oracle does
        cvar = emeta[:, graph_mod.EM_CORR_VAR]
        cvar_c = jnp.clip(cvar, 0, v - 1)
        corr_vt_ext = batch.v_vt[rows, cvar_c].astype(jnp.int32)
        corr_bits_ext = jnp.where(
            corr_vt_ext == int(COND_VT_STR),
            batch.v_str[rows, cvar_c],
            jax.lax.bitcast_convert_type(batch.v_num[rows, cvar_c], jnp.int32),
        )
        corr_extractable = (
            (cvar >= 0)
            & (
                (corr_vt_ext == int(COND_VT_STR))
                | (corr_vt_ext == int(COND_VT_NUM))
                | (corr_vt_ext == int(COND_VT_BOOL))
            )
        )
        sub_ok = m_subscribe & corr_extractable
        sub_err = m_subscribe & ~corr_extractable
    else:
        zb = jnp.zeros((b,), bool)
        pub_dup = pub_chain = pub_ok = pub_store = pub_nostore = pub_corr = zb
        open_dup = open_ok = open_corr = close_ok = del_ok = zb
        corr_inst_ok = corr_rej = sub_ok = sub_err = zb
        corr_bd_int = corr_bd_non = corr_is_bd = zb
        corr_bd_elem = jnp.full((b,), -1, jnp.int32)
        corr_vt_ext = jnp.zeros((b,), jnp.int32)
        corr_bits_ext = jnp.zeros((b,), jnp.int32)

    # ---------------- C. per-step compute ----------------
    # exclusive split: evaluate conditioned flows in order
    fan = cond_fan
    # cflow / cprog [B, F] rows ride the phase-A fused graph gather
    has_cond = cprog >= 0
    if graph.has_conditions:
        tri = eval_programs(
            graph.progs,
            graph.lit_nums,
            cprog,
            jnp.broadcast_to(batch.v_vt[:, None, :], (b, fan, v)),
            jnp.broadcast_to(batch.v_num[:, None, :], (b, fan, v)),
            jnp.broadcast_to(batch.v_str[:, None, :], (b, fan, v)),
        )
        tri = jnp.where(has_cond, tri, -1)
    else:
        # deploy-time specialization: no conditioned flow in the whole
        # deployed set — the predicate machine is compiled out
        tri = jnp.full((b, fan), -1, jnp.int32)
    is_true = tri == TRI_TRUE
    is_err = tri == TRI_ERROR
    fidx = jnp.arange(fan, dtype=jnp.int32)
    first_true = jnp.min(jnp.where(is_true, fidx, fan), axis=1)
    first_err = jnp.min(jnp.where(is_err, fidx, fan), axis=1)
    cond_errored = first_err < first_true
    default_f = emeta[:, graph_mod.EM_DEFAULT_FLOW]
    # select the first-true flow by one-hot multiply-sum over the (small,
    # static) fan axis instead of a per-row gather
    taken_flow = jnp.where(
        first_true < fan,
        jnp.sum(
            jnp.where(
                fidx[None, :] == jnp.clip(first_true, 0, fan - 1)[:, None],
                cflow,
                0,
            ),
            axis=1,
        ),
        default_f,
    )
    xs_ok = m_xsplit & ~cond_errored & (taken_flow >= 0)
    xs_nofl = m_xsplit & ~cond_errored & (taken_flow < 0)
    xs_err = m_xsplit & cond_errored

    # input mapping (compiled out when the deployed set has no mappings:
    # identity pass-through is the default behavior)
    if graph.has_mappings:
        in_from, in_has, in_root, in_err = _apply_mappings(
            graph, wf_c, el_c, batch.v_vt, batch.v_num, batch.v_str, True
        )
        im_vt, im_num, im_sid = _select_by_map(
            in_from, batch.v_vt, batch.v_num, batch.v_str
        )
        sel_in = (in_has & ~in_root)[:, None]
        in_vt = jnp.where(sel_in, im_vt, batch.v_vt)
        in_num = jnp.where(sel_in, im_num, batch.v_num)
        in_sid = jnp.where(sel_in, im_sid, batch.v_str)
        inmap_ok = m_inmap & ~(in_has & in_err)
        inmap_err = m_inmap & in_has & in_err
    else:
        in_vt, in_num, in_sid = batch.v_vt, batch.v_num, batch.v_str
        inmap_ok = m_inmap
        inmap_err = jnp.zeros((b,), bool)

    # output mapping: merge(record payload → scope payload)
    scope_vt, scope_sid, scope_num = unpack_payload(sc_pay_rows)
    scope_vt = scope_vt.astype(jnp.int8)
    no_scope = ~sc_found
    scope_vt = jnp.where(no_scope[:, None], VT_ABSENT, scope_vt)
    if graph.has_mappings:
        out_from, out_has, out_root, out_err = _apply_mappings(
            graph, wf_c, el_c, batch.v_vt, batch.v_num, batch.v_str, False
        )
        om_vt, om_num, om_sid = _select_by_map(
            out_from, batch.v_vt, batch.v_num, batch.v_str
        )
    else:
        out_from = jnp.full((b, v), -1, jnp.int32)
        out_has = jnp.zeros((b,), bool)
        out_root = jnp.zeros((b,), bool)
        out_err = jnp.zeros((b,), bool)
        om_vt, om_num, om_sid = batch.v_vt, batch.v_num, batch.v_str
    behavior = emeta[:, graph_mod.EM_OUT_BEHAVIOR]
    B_MERGE, B_OVERWRITE, B_NONE = 0, 1, 2
    src_present = batch.v_vt != VT_ABSENT

    def _merge_one(scope_a, src_a, mapped_a, fill):
        base = jnp.where((behavior == B_OVERWRITE)[:, None], fill, scope_a)
        with_maps = jnp.where(out_from >= 0, mapped_a, base)
        without = jnp.where(
            (behavior == B_OVERWRITE)[:, None],
            src_a,
            jnp.where(src_present, src_a, scope_a),
        )
        merged = jnp.where((out_has & ~out_root)[:, None], with_maps, jnp.where(
            out_root[:, None], src_a, without))
        return jnp.where((behavior == B_NONE)[:, None], scope_a, merged)

    out_vt = _merge_one(scope_vt, batch.v_vt, om_vt, jnp.int8(VT_ABSENT))
    out_num = _merge_one(scope_num, batch.v_num, om_num, 0.0)
    out_sid = _merge_one(scope_sid, batch.v_str, om_sid, 0)
    outmap_ok = m_outmap & ~(out_has & out_err)
    outmap_err = m_outmap & out_has & out_err

    # parallel join: composite key (scope_key, gateway element). Compiled
    # out for deployed sets without a joining parallel gateway.
    gw_elem = emeta[:, graph_mod.EM_FLOW_TGT]
    gw_clip = jnp.clip(gw_elem, 0, graph.elem_type.shape[1] - 1)
    if graph.has_parallel_joins:
        join_key = jnp.where(
            m_pmerge, (batch.scope_key << jnp.int64(10)) | gw_clip.astype(jnp.int64), -1
        )
        jn_found, jn_slot = pops.lookup(state.join_map, join_key, m_pmerge)
        # leaders: first batch occurrence of each missing join key (sort-dedup)
        missing = m_pmerge & ~jn_found
        sort_k = jnp.where(missing, join_key, jnp.int64(2**62))
        order = jnp.argsort(sort_k, stable=True)
        sorted_k = sort_k[order]
        first_occ = jnp.concatenate(
            [jnp.ones((1,), bool), sorted_k[1:] != sorted_k[:-1]]
        )
        leader = jnp.zeros((b,), bool).at[order].set(first_occ) & missing
        # allocate join slots for leaders
        join_free = _first_true_indices(state.join_key < 0, b)
        l_rank = _excl_cumsum(leader.astype(jnp.int32))
        l_slot = join_free[jnp.clip(l_rank, 0, b - 1)]
        join_overflow = jnp.any(leader & (l_slot >= j_cap))
        join_key_arr = pops.masked_vec64_update(
            state.join_key, l_slot, leader, join_key
        )
        nin_here = graph.join_nin[wf_c, gw_clip]
        join_nin_arr = pops.masked_lane_update(
            state.join_nin, l_slot, leader, nin_here
        )
        jmap, jins = pops.insert(state.join_map, join_key, l_slot, leader)
        # re-lookup so every arrival sees its slot
        jn_found2, jn_slot2 = pops.lookup(jmap, join_key, m_pmerge)
        arr_slot = jnp.clip(jn_slot2, 0, j_cap - 1)
        my_pos = emeta[:, graph_mod.EM_JOIN_POS]
        arrival = m_pmerge & jn_found2
        aw = jnp.where(arrival, arr_slot, j_cap)
        # dynamic column one-hot; arrivals are monotonic so a row MAX
        # composes concurrent arrivals at the same join slot
        fcols = jnp.arange(state.join_arrived.shape[1], dtype=jnp.int32)
        pos_hot = fcols[None, :] == jnp.clip(
            my_pos, 0, state.join_arrived.shape[1] - 1
        )[:, None]
        arrived = pops.masked_row_max(
            state.join_arrived.astype(jnp.int32), arr_slot, arrival,
            pos_hot.astype(jnp.int32),
        ).astype(bool)
        # flow-position-stamped payload merge: higher flow pos wins per variable
        stamp = pops.masked_row_max(
            state.join_pos_stamp, arr_slot, arrival,
            jnp.where(src_present, my_pos[:, None], -1),
        )
        win_var = m_pmerge[:, None] & src_present & (
            stamp[jnp.clip(aw, 0, j_cap - 1)] == my_pos[:, None]
        )
        win3 = jnp.concatenate([win_var, win_var, win_var], axis=1)
        b_pay_join = pack_payload(batch.v_vt, batch.v_str, batch.v_num)
        join_pay = pops.masked_row_update(
            state.join_pay, arr_slot, arrival, b_pay_join, win3
        )
        # completion: all incoming arrived; completer = last arrival in batch
        arr_count = jnp.sum(arrived, axis=1).astype(jnp.int32)
        complete_slot = (join_nin_arr > 0) & (arr_count >= join_nin_arr)
        my_complete = m_pmerge & jn_found2 & complete_slot[arr_slot]
        completer = _last_writer(arr_slot, my_complete, j_cap)
        # merged payload for the completer
        mg_vt, mg_sid, mg_num = unpack_payload(join_pay[arr_slot])
        mg_vt = mg_vt.astype(jnp.int8)
    else:
        join_key = jnp.full((b,), -1, jnp.int64)
        arr_slot = jnp.zeros((b,), jnp.int32)
        my_complete = jnp.zeros((b,), bool)
        completer = jnp.zeros((b,), bool)
        join_overflow = jnp.zeros((), bool)
        join_key_arr = state.join_key
        join_nin_arr = state.join_nin
        arrived = state.join_arrived
        stamp = state.join_pos_stamp
        join_pay = state.join_pay
        jmap = state.join_map
        mg_vt, mg_num, mg_sid = batch.v_vt, batch.v_num, batch.v_str

    # ---------------- D. key assignment ----------------
    out_count = emeta[:, graph_mod.EM_OUT_COUNT]
    single_key = (
        m_create | m_take | xs_ok | m_actgw | m_startst | m_trigend
        | m_trigstart | completer | m_tcreate | pub_ok | open_ok
        | ttrig_bd_non | m_bd_continue | corr_bd_non
    )
    n_wf = jnp.where(
        single_key, 1,
        jnp.where(
            m_psplit, out_count,
            jnp.where(m_mi, emeta[:, graph_mod.EM_MI_CARD], 0),
        ),
    )
    wf_base = state.next_wf_key + _KEY_STEP * _excl_cumsum(n_wf).astype(jnp.int64)
    key0 = wf_base  # key for single-allocation steps
    n_job = m_jcreate.astype(jnp.int32)
    job_base = state.next_job_key + _KEY_STEP * _excl_cumsum(n_job).astype(jnp.int64)
    next_wf_key = state.next_wf_key + _KEY_STEP * jnp.sum(n_wf, dtype=jnp.int64)
    next_job_key = state.next_job_key + _KEY_STEP * jnp.sum(n_job, dtype=jnp.int64)

    # ---------------- job activation pool ----------------
    # candidate subscription: first valid sub of the job's type (oracle
    # round-robin degenerates to this for one subscription per type)
    sub_match = (
        state.sub_valid[None, :]
        & (state.sub_type[None, :] == batch.type_id[:, None])
        & (state.sub_credits[None, :] > 0)
    )  # [B, S]
    cand = jnp.argmax(sub_match, axis=1).astype(jnp.int32)
    has_sub = jnp.any(sub_match, axis=1)
    pool = m_actpool & has_sub
    sub_credits = state.sub_credits
    activated = jnp.zeros((b,), bool)
    for s in range(s_cap):
        mask_s = pool & (cand == s)
        rank_s = _excl_cumsum(mask_s.astype(jnp.int32))
        act_s = mask_s & (rank_s < sub_credits[s])
        activated = activated | act_s
        sub_credits = sub_credits.at[s].add(-jnp.sum(act_s, dtype=jnp.int32))
    cand_c = jnp.clip(cand, 0, s_cap - 1)
    # the sub tables are tiny ([S] with S = sub_capacity): read the
    # candidate's columns by one-hot multiply-sum instead of three gathers
    cand_oh = jnp.arange(s_cap, dtype=jnp.int32)[None, :] == cand_c[:, None]
    act_deadline = now + jnp.sum(
        jnp.where(cand_oh, state.sub_timeout[None, :], 0), axis=1
    )
    act_worker = jnp.sum(
        jnp.where(cand_oh, state.sub_worker[None, :], 0), axis=1
    )
    act_stream = jnp.sum(
        jnp.where(cand_oh, state.sub_key[None, :], 0), axis=1
    ).astype(jnp.int32)
    # credit return on activate rejection
    ret_idx = jnp.argmax(
        state.sub_key[None, :] == batch.req_stream[:, None].astype(jnp.int64), axis=1
    ).astype(jnp.int32)
    ret_has = jnp.any(
        state.sub_key[None, :] == batch.req_stream[:, None].astype(jnp.int64), axis=1
    )
    ret_w = jnp.where(jact_rej & ret_has, ret_idx, s_cap)
    sub_credits = sub_credits.at[ret_w].add(1, mode="drop")

    # ---------------- E. emissions ----------------
    zero_vt = jnp.zeros((b, v), jnp.int8)
    zero_num = jnp.zeros((b, v), jnp.float32)
    zero_sid = jnp.zeros((b, v), jnp.int32)

    def blank():
        return {
            "valid": jnp.zeros((b,), bool),
            "rtype": jnp.zeros((b,), jnp.int32),
            "vtype": jnp.zeros((b,), jnp.int32),
            "intent": jnp.zeros((b,), jnp.int32),
            "key": jnp.full((b,), -1, jnp.int64),
            "elem": jnp.full((b,), -1, jnp.int32),
            "wf": batch.wf,
            "instance_key": batch.instance_key,
            "scope_key": batch.scope_key,
            "v_vt": batch.v_vt,
            "v_num": batch.v_num,
            "v_str": batch.v_str,
            "req": jnp.full((b,), -1, jnp.int64),
            "req_stream": jnp.full((b,), -1, jnp.int32),
            "aux_key": jnp.full((b,), -1, jnp.int64),
            "aux2_key": jnp.full((b,), -1, jnp.int64),
            "type_id": jnp.zeros((b,), jnp.int32),
            "retries": jnp.zeros((b,), jnp.int32),
            "deadline": jnp.full((b,), -1, jnp.int64),
            "worker": jnp.zeros((b,), jnp.int32),
            "src": rows,
            "resp": jnp.zeros((b,), bool),
            "push": jnp.zeros((b,), bool),
            "rej": jnp.zeros((b,), jnp.int32),
        }

    def put(em, mask, **kw):
        for name, val in kw.items():
            em[name] = jnp.where(mask, val, em[name])
        return em

    e0 = blank()
    e1 = blank()
    # emission slots ≥ 2 materialize lazily (messages, boundary arm/disarm
    # fan-out); rows claiming the same slot index always have disjoint
    # masks — compaction keeps slot order = the oracle's append order
    extra_slots: Dict[int, dict] = {}

    def eslot(i: int) -> dict:
        if i == 0:
            return e0
        if i == 1:
            return e1
        if i not in extra_slots:
            extra_slots[i] = blank()
        return extra_slots[i]

    pid_col = jnp.broadcast_to(jnp.asarray(partition_id, jnp.int32), (b,))

    # --- slot 0: workflow-instance emissions
    # (scope_parent / scope_parent_key resolved in the phase-A fused pass)
    scope_elem = jnp.where(sc_found, sc_rows[:, EI_ELEM], -1)

    e0 = put(
        e0, m_create,
        valid=True, rtype=RT_EVENT, vtype=VT_WI, intent=int(WI.CREATED),
        key=key0, elem=0, instance_key=key0, scope_key=jnp.int64(-1),
        req=batch.req, req_stream=batch.req_stream, resp=batch.req >= 0,
    )
    e1 = put(
        e1, m_create,
        valid=True, rtype=RT_EVENT, vtype=VT_WI, intent=int(WI.ELEMENT_READY),
        key=key0, elem=0, instance_key=key0, scope_key=jnp.int64(-1),
    )

    first_out = emeta[:, graph_mod.EM_FIRST_OUT]
    e0 = put(
        e0, m_take,
        valid=True, rtype=RT_EVENT, vtype=VT_WI,
        intent=int(WI.SEQUENCE_FLOW_TAKEN), key=key0, elem=first_out,
    )
    # consume token: the last consumed token completes the scope
    tokens_after = jnp.zeros((n_cap,), jnp.int32).at[
        jnp.where(m_consume, sc_clip, n_cap)
    ].add(-1, mode="drop") + state.ei_tokens
    # round-9a fused read pass: the remaining 1D i32 state reads — the
    # post-consume token count per scope, the parallel-join fan-in, and
    # the two free-slot ring pops (whose index math is pure, so the
    # phase-E pops hoist here) — share ONE gather
    ins_replay = m_created_ev & ~ei_found
    ins = m_create | m_startst | ins_replay
    ins_rank = _excl_cumsum(ins.astype(jnp.int32))
    ei_pop_idx = state.free_ei_pop + ins_rank.astype(jnp.int64)
    ei_ring_ok = ei_pop_idx < state.free_ei_push
    job_ins = m_jcreate
    j_rank = _excl_cumsum(job_ins.astype(jnp.int32))
    job_pop_idx = state.free_job_pop + j_rank.astype(jnp.int64)
    job_ring_ok = job_pop_idx < state.free_job_push
    with jax.named_scope("zb_gather"):
        tok_after_sc, nin_rec, ei_pop_slot, job_pop_slot = (
            pops.fused_gather_rows(
                [tokens_after, join_nin_arr, state.free_ei, state.free_job],
                [
                    pops.GatherOp(0, sc_clip),
                    pops.GatherOp(1, arr_slot),
                    pops.GatherOp(2, (ei_pop_idx % n_cap).astype(jnp.int32)),
                    pops.GatherOp(3, (job_pop_idx % m_cap).astype(jnp.int32)),
                ],
            )
        )
    consume_done = m_consume & (tok_after_sc <= 0)
    consume_completer = _last_writer(sc_clip, consume_done, n_cap)
    e0 = put(
        e0, consume_completer,
        valid=True, rtype=RT_EVENT, vtype=VT_WI,
        intent=int(WI.ELEMENT_COMPLETING), key=batch.scope_key, elem=scope_elem,
        scope_key=scope_parent_key,
    )
    if graph.has_multi_instance:
        # a completing multi-instance container keeps ITS OWN payload (the
        # oracle never copies iteration payloads into an MI scope)
        sc_elem_c = jnp.clip(scope_elem, 0, graph.elem_type.shape[1] - 1)
        sc_wf_c = jnp.clip(
            jnp.where(sc_found, sc_rows[:, EI_WF], 0),
            0, graph.elem_type.shape[0] - 1,
        )
        mi_completer = (
            consume_completer
            & (graph.mi_cardinality[sc_wf_c, sc_elem_c] > 0)
        )
        sc_vt, sc_sid, sc_num = unpack_payload(sc_pay_rows)
        e0["v_vt"] = jnp.where(
            mi_completer[:, None], sc_vt.astype(jnp.int8), e0["v_vt"]
        )
        e0["v_num"] = jnp.where(mi_completer[:, None], sc_num, e0["v_num"])
        e0["v_str"] = jnp.where(mi_completer[:, None], sc_sid, e0["v_str"])
    e0 = put(
        e0, xs_ok,
        valid=True, rtype=RT_EVENT, vtype=VT_WI,
        intent=int(WI.SEQUENCE_FLOW_TAKEN), key=key0, elem=taken_flow,
    )
    e0 = put(
        e0, xs_nofl | xs_err,
        valid=True, rtype=RT_CMD, vtype=VT_INCIDENT, intent=0,  # IncidentIntent.CREATE
        key=jnp.int64(-1), elem=batch.elem, aux_key=batch.key,
        rej=jnp.where(xs_nofl, rb.ERR_CONDITION_NO_FLOW, rb.ERR_CONDITION_EVAL),
    )
    e0 = put(
        e0, m_createjob & ~has_bd,
        valid=True, rtype=RT_CMD, vtype=VT_JOB, intent=int(JI.CREATE),
        key=jnp.int64(-1), elem=batch.elem, aux_key=batch.key,
        type_id=emeta[:, graph_mod.EM_JOB_TYPE], retries=emeta[:, graph_mod.EM_JOB_RETRIES],
    )
    e0 = put(
        e0, inmap_ok,
        valid=True, rtype=RT_EVENT, vtype=VT_WI,
        intent=int(WI.ELEMENT_ACTIVATED), key=batch.key, elem=batch.elem,
    )
    e0["v_vt"] = jnp.where(inmap_ok[:, None], in_vt, e0["v_vt"])
    e0["v_num"] = jnp.where(inmap_ok[:, None], in_num, e0["v_num"])
    e0["v_str"] = jnp.where(inmap_ok[:, None], in_sid, e0["v_str"])
    e0 = put(
        e0, outmap_ok & ~has_bd,
        valid=True, rtype=RT_EVENT, vtype=VT_WI,
        intent=int(WI.ELEMENT_COMPLETED), key=batch.key, elem=batch.elem,
    )
    e0["v_vt"] = jnp.where((outmap_ok & ~has_bd)[:, None], out_vt, e0["v_vt"])
    e0["v_num"] = jnp.where((outmap_ok & ~has_bd)[:, None], out_num, e0["v_num"])
    e0["v_str"] = jnp.where((outmap_ok & ~has_bd)[:, None], out_sid, e0["v_str"])
    e0 = put(
        e0, inmap_err | outmap_err,
        valid=True, rtype=RT_CMD, vtype=VT_INCIDENT, intent=0,
        key=jnp.int64(-1), elem=batch.elem, aux_key=batch.key,
        rej=jnp.where(inmap_err, rb.ERR_IO_MAPPING_IN, rb.ERR_IO_MAPPING_OUT),
    )
    ftarget = emeta[:, graph_mod.EM_FLOW_TGT]
    e0 = put(
        e0, m_actgw,
        valid=True, rtype=RT_EVENT, vtype=VT_WI,
        intent=int(WI.GATEWAY_ACTIVATED), key=key0, elem=ftarget,
    )
    e0 = put(
        e0, m_startst,
        valid=True, rtype=RT_EVENT, vtype=VT_WI,
        intent=int(WI.ELEMENT_READY), key=key0, elem=ftarget,
    )
    e0 = put(
        e0, m_trigend,
        valid=True, rtype=RT_EVENT, vtype=VT_WI,
        intent=int(WI.END_EVENT_OCCURRED), key=key0, elem=ftarget,
    )
    start_ev = emeta[:, graph_mod.EM_START_EV]
    e0 = put(
        e0, m_trigstart,
        valid=True, rtype=RT_EVENT, vtype=VT_WI,
        intent=int(WI.START_EVENT_OCCURRED), key=key0, elem=start_ev,
        scope_key=batch.key,
    )
    e0 = put(
        e0, m_complete_proc,
        valid=True, rtype=RT_EVENT, vtype=VT_WI,
        intent=int(WI.ELEMENT_COMPLETED), key=batch.key, elem=batch.elem,
    )
    e0 = put(
        e0, completer,
        valid=True, rtype=RT_EVENT, vtype=VT_WI,
        intent=int(WI.GATEWAY_ACTIVATED), key=key0, elem=gw_elem,
    )
    e0["v_vt"] = jnp.where(completer[:, None], mg_vt, e0["v_vt"])
    e0["v_num"] = jnp.where(completer[:, None], mg_num, e0["v_num"])
    e0["v_str"] = jnp.where(completer[:, None], mg_sid, e0["v_str"])
    e0 = put(
        e0, m_timer_step,
        valid=True, rtype=RT_CMD, vtype=VT_TIMER, intent=int(TI.CREATE),
        key=jnp.int64(-1), elem=batch.elem, aux_key=batch.key,
        deadline=now + timer_dur_rec,
    )

    # --- slot 0: job command results
    jrej = jact_rej | jcomp_rej | jfail_rej | jtime_rej | jret_rej | jret_badv | jcan_rej
    e0 = put(
        e0, m_jcreate,
        valid=True, rtype=RT_EVENT, vtype=VT_JOB, intent=int(JI.CREATED),
        key=job_base, elem=batch.elem, aux_key=batch.aux_key,
        type_id=batch.type_id, retries=batch.retries,
        req=batch.req, req_stream=batch.req_stream, resp=batch.req >= 0,
    )
    e0 = put(
        e0, jact_ok,
        valid=True, rtype=RT_EVENT, vtype=VT_JOB, intent=int(JI.ACTIVATED),
        key=batch.key, elem=batch.elem, aux_key=batch.aux_key,
        type_id=batch.type_id, retries=batch.retries, deadline=batch.deadline,
        worker=batch.worker, push=True, req_stream=batch.req_stream,
    )
    if synthetic_workers:
        # bench-only instant worker: the COMPLETE lands in the slot right
        # after its ACTIVATED, riding the normal emission compaction (e1 is
        # never used by job-command rows, so the slot is free here)
        e1 = put(
            e1, jact_ok,
            valid=True, rtype=RT_CMD, vtype=VT_JOB, intent=int(JI.COMPLETE),
            key=batch.key, elem=batch.elem, aux_key=batch.aux_key,
            type_id=batch.type_id, retries=batch.retries,
            worker=batch.worker, src=jnp.full((b,), -1, jnp.int32),
        )
    # completed value = stored job record + command payload (columns of
    # the phase-A jb row gathers — no per-column gathers here)
    st_elem = jb_i32_rows[:, JB_ELEM]
    st_wf = jb_i32_rows[:, JB_WF]
    st_ik = jb_i64_rows[:, JBL_IKEY]
    st_aik = jb_i64_rows[:, JBL_AIK]
    st_type = jb_i32_rows[:, JB_TYPE]
    st_retries = jb_i32_rows[:, JB_RETRIES]
    st_worker = jb_i32_rows[:, JB_WORKER]
    st_deadline = jb_i64_rows[:, JBL_DEADLINE]
    e0 = put(
        e0, jcomp_ok,
        valid=True, rtype=RT_EVENT, vtype=VT_JOB, intent=int(JI.COMPLETED),
        key=batch.key, elem=st_elem, wf=st_wf, instance_key=st_ik,
        aux_key=st_aik, type_id=st_type, retries=st_retries,
        worker=st_worker, deadline=st_deadline,
        req=batch.req, req_stream=batch.req_stream, resp=batch.req >= 0,
    )
    payload_nonempty = jnp.any(batch.v_vt != VT_ABSENT, axis=1)
    jb_vt, jb_sid, jb_num = unpack_payload(jb_pay_rows)
    jb_vt = jb_vt.astype(jnp.int8)
    fail_vt = jnp.where(payload_nonempty[:, None], batch.v_vt, jb_vt)
    fail_num = jnp.where(payload_nonempty[:, None], batch.v_num, jb_num)
    fail_sid = jnp.where(payload_nonempty[:, None], batch.v_str, jb_sid)
    e0 = put(
        e0, jfail_ok,
        valid=True, rtype=RT_EVENT, vtype=VT_JOB, intent=int(JI.FAILED),
        key=batch.key, elem=st_elem, wf=st_wf, instance_key=st_ik,
        aux_key=st_aik, type_id=st_type, retries=batch.retries,
        worker=st_worker, deadline=st_deadline,
        req=batch.req, req_stream=batch.req_stream, resp=batch.req >= 0,
    )
    e0["v_vt"] = jnp.where(jfail_ok[:, None], fail_vt, e0["v_vt"])
    e0["v_num"] = jnp.where(jfail_ok[:, None], fail_num, e0["v_num"])
    e0["v_str"] = jnp.where(jfail_ok[:, None], fail_sid, e0["v_str"])
    e0 = put(
        e0, jtime_ok,
        valid=True, rtype=RT_EVENT, vtype=VT_JOB, intent=int(JI.TIMED_OUT),
        key=batch.key, elem=batch.elem, aux_key=batch.aux_key,
        type_id=batch.type_id, retries=batch.retries,
        deadline=batch.deadline, worker=batch.worker,
        req=batch.req, req_stream=batch.req_stream, resp=batch.req >= 0,
    )
    ret_vt = jb_vt
    ret_num = jb_num
    ret_sid = jb_sid
    e0 = put(
        e0, jret_ok,
        valid=True, rtype=RT_EVENT, vtype=VT_JOB, intent=int(JI.RETRIES_UPDATED),
        key=batch.key, elem=st_elem, wf=st_wf, instance_key=st_ik,
        aux_key=st_aik, type_id=st_type, retries=batch.retries,
        worker=st_worker, deadline=st_deadline,
        req=batch.req, req_stream=batch.req_stream, resp=batch.req >= 0,
    )
    e0["v_vt"] = jnp.where(jret_ok[:, None], ret_vt, e0["v_vt"])
    e0["v_num"] = jnp.where(jret_ok[:, None], ret_num, e0["v_num"])
    e0["v_str"] = jnp.where(jret_ok[:, None], ret_sid, e0["v_str"])
    e0 = put(
        e0, jcan_ok,
        valid=True, rtype=RT_EVENT, vtype=VT_JOB, intent=int(JI.CANCELED),
        key=batch.key, elem=batch.elem, aux_key=batch.aux_key,
        type_id=batch.type_id, retries=batch.retries,
        deadline=batch.deadline, worker=batch.worker,
        req=batch.req, req_stream=batch.req_stream, resp=batch.req >= 0,
    )
    rej_code = jnp.select(
        [jact_rej, jcomp_rej, jfail_rej, jtime_rej, jret_badv, jret_rej, jcan_rej],
        [
            rb.REJ_JOB_NOT_ACTIVATABLE, rb.REJ_JOB_NOT_COMPLETABLE,
            rb.REJ_JOB_NOT_ACTIVATED, rb.REJ_JOB_NOT_ACTIVATED,
            rb.REJ_RETRIES_NOT_POSITIVE, rb.REJ_JOB_NOT_FAILED,
            rb.REJ_JOB_NOT_EXIST,
        ],
        0,
    )
    e0 = put(
        e0, jrej,
        valid=True, rtype=RT_REJ, vtype=vt_, intent=it, key=batch.key,
        elem=batch.elem, aux_key=batch.aux_key, type_id=batch.type_id,
        retries=batch.retries, deadline=batch.deadline, worker=batch.worker,
        rej=rej_code, req=batch.req, req_stream=batch.req_stream,
        resp=batch.req >= 0,
    )

    # --- slot 0: job events → workflow / activation / incident
    wi_of_inst_vt, wi_of_inst_sid, wi_of_inst_num = unpack_payload(
        aik_pay_rows
    )
    wi_of_inst_vt = wi_of_inst_vt.astype(jnp.int8)
    inst_elem = aik_rows[:, EI_ELEM]
    inst_wf = aik_rows[:, EI_WF]
    # (inst_scope_slot / inst_scope_key resolved in the phase-A fused pass)
    e0 = put(
        e0, jev_completed,
        valid=True, rtype=RT_EVENT, vtype=VT_WI,
        intent=int(WI.ELEMENT_COMPLETING), key=batch.aux_key,
        elem=inst_elem, wf=inst_wf, scope_key=inst_scope_key,
    )
    act_pool_win = activated
    e0 = put(
        e0, act_pool_win,
        valid=True, rtype=RT_CMD, vtype=VT_JOB, intent=int(JI.ACTIVATE),
        key=batch.key, elem=batch.elem, aux_key=batch.aux_key,
        type_id=batch.type_id, retries=batch.retries,
        deadline=act_deadline, worker=act_worker, req_stream=act_stream,
    )
    e0 = put(
        e0, jev_fail_noretry,
        valid=True, rtype=RT_CMD, vtype=VT_INCIDENT, intent=0,
        key=jnp.int64(-1), elem=batch.elem, aux_key=batch.aux_key,
        aux2_key=batch.key, rej=0,  # JOB_NO_RETRIES handled host-side by code 0? no:
    )
    # job-no-retries uses a dedicated code so the host maps the error type
    e0["rej"] = jnp.where(jev_fail_noretry, 105, e0["rej"])

    # --- slot 0/1: timer commands
    e0 = put(
        e0, m_tcreate,
        valid=True, rtype=RT_EVENT, vtype=VT_TIMER, intent=int(TI.CREATED),
        key=key0, elem=batch.elem, aux_key=batch.aux_key, deadline=batch.deadline,
    )
    e0 = put(
        e0, ttrig_ok,
        valid=True, rtype=RT_EVENT, vtype=VT_TIMER, intent=int(TI.TRIGGERED),
        key=batch.key, elem=trig_elem, wf=trig_wf, aux_key=batch.aux_key,
        deadline=batch.deadline,
    )
    e1 = put(
        e1, ttrig_catch,
        valid=True, rtype=RT_EVENT, vtype=VT_WI,
        intent=int(WI.ELEMENT_COMPLETING), key=batch.aux_key,
        elem=inst_elem, wf=inst_wf, scope_key=inst_scope_key,
    )
    # interrupting boundary: terminate the host (the continuation fires at
    # ELEMENT_TERMINATED); non-interrupting: the token appears at the
    # boundary event, the host keeps running (oracle _fire_boundary_event)
    e1 = put(
        e1, ttrig_bd_int,
        valid=True, rtype=RT_EVENT, vtype=VT_WI,
        intent=int(WI.ELEMENT_TERMINATING), key=batch.aux_key,
        elem=inst_elem, wf=inst_wf, scope_key=inst_scope_key,
    )
    e1 = put(
        e1, ttrig_bd_non,
        valid=True, rtype=RT_EVENT, vtype=VT_WI,
        intent=int(WI.BOUNDARY_EVENT_OCCURRED), key=key0,
        elem=trig_elem, wf=inst_wf, scope_key=inst_scope_key,
    )
    ttrig_any_inst = ttrig_catch | ttrig_bd_int | ttrig_bd_non
    e1["v_vt"] = jnp.where(ttrig_any_inst[:, None], wi_of_inst_vt, e1["v_vt"])
    e1["v_num"] = jnp.where(ttrig_any_inst[:, None], wi_of_inst_num, e1["v_num"])
    e1["v_str"] = jnp.where(ttrig_any_inst[:, None], wi_of_inst_sid, e1["v_str"])
    e1["instance_key"] = jnp.where(
        ttrig_any_inst, aik_i64_rows[:, EIL_IKEY], e1["instance_key"]
    )
    e0 = put(
        e0, ttrig_rej,
        valid=True, rtype=RT_REJ, vtype=vt_, intent=it, key=batch.key,
        rej=rb.REJ_TIMER_NOT_EXIST, req=batch.req, req_stream=batch.req_stream,
        resp=batch.req >= 0,
    )
    e0 = put(
        e0, tcan_ok,
        valid=True, rtype=RT_EVENT, vtype=VT_TIMER, intent=int(TI.CANCELED),
        key=batch.key, elem=batch.elem, aux_key=batch.aux_key,
        deadline=batch.deadline,
    )

    # --- message correlation emissions
    if graph.has_messages:
        e2 = eslot(2)
        # subscribe step → OPEN sent to the message partition (oracle
        # _h_subscribe_to_message); correlation-key failure → incident
        e0 = put(
            e0, sub_ok & ~has_bd,
            valid=True, rtype=RT_CMD, vtype=VT_MSUB, intent=int(MS.OPEN),
            key=jnp.int64(-1), elem=batch.elem,
            type_id=emeta[:, graph_mod.EM_MSG_NAME],
            retries=corr_vt_ext, worker=corr_bits_ext,
            instance_key=batch.instance_key, aux_key=batch.key,
            wf=pid_col,
        )
        e0 = put(
            e0, sub_err & ~has_bd,
            valid=True, rtype=RT_CMD, vtype=VT_INCIDENT, intent=0,
            key=jnp.int64(-1), elem=batch.elem, aux_key=batch.key,
            rej=rb.ERR_CORRELATION_KEY,
        )
        # message partition: PUBLISH
        e0 = put(
            e0, pub_dup | pub_chain | open_dup,
            valid=True, rtype=RT_REJ, vtype=vt_, intent=it, key=batch.key,
            type_id=batch.type_id, retries=batch.retries, worker=batch.worker,
            instance_key=batch.instance_key, aux_key=batch.aux_key,
            aux2_key=batch.aux2_key,
            rej=jnp.where(
                pub_dup, rb.REJ_MSG_DUP,
                jnp.where(pub_chain, rb.REJ_MSG_STORE_OCCUPIED,
                          rb.REJ_SUB_OCCUPIED),
            ),
            req=batch.req, req_stream=batch.req_stream, resp=batch.req >= 0,
        )
        e0 = put(
            e0, pub_ok,
            valid=True, rtype=RT_EVENT, vtype=VT_MSG, intent=int(MI.PUBLISHED),
            key=key0, type_id=batch.type_id, retries=batch.retries,
            worker=batch.worker, deadline=batch.deadline,
            aux2_key=batch.aux2_key,
            req=batch.req, req_stream=batch.req_stream, resp=batch.req >= 0,
        )
        e1 = put(
            e1, pub_nostore,
            valid=True, rtype=RT_EVENT, vtype=VT_MSG, intent=int(MI.DELETED),
            key=key0, type_id=batch.type_id, retries=batch.retries,
            worker=batch.worker, aux2_key=batch.aux2_key,
        )
        e2 = put(
            e2, pub_corr,
            valid=True, rtype=RT_CMD, vtype=VT_WISUB, intent=int(WS.CORRELATE),
            key=jnp.int64(-1),
            wf=msub_i32_rows[:, MS_PART],
            instance_key=msub_i64_rows[:, MSL_WIKEY],
            aux_key=msub_i64_rows[:, MSL_AIK],
            type_id=batch.type_id, retries=batch.retries, worker=batch.worker,
            aux2_key=pid_col.astype(jnp.int64),  # message partition id
        )
        # message partition: OPEN / CLOSE
        e0 = put(
            e0, open_ok,
            valid=True, rtype=RT_EVENT, vtype=VT_MSUB, intent=int(MS.OPENED),
            key=key0, type_id=batch.type_id, retries=batch.retries,
            worker=batch.worker, instance_key=batch.instance_key,
            aux_key=batch.aux_key,
        )
        stored_vt, stored_sid, stored_num = unpack_payload(mmsg_pay_rows)
        e1 = put(
            e1, open_corr,
            valid=True, rtype=RT_CMD, vtype=VT_WISUB, intent=int(WS.CORRELATE),
            key=jnp.int64(-1), wf=batch.wf,
            instance_key=batch.instance_key, aux_key=batch.aux_key,
            type_id=batch.type_id, retries=batch.retries, worker=batch.worker,
            aux2_key=pid_col.astype(jnp.int64),
        )
        e1["v_vt"] = jnp.where(
            open_corr[:, None], stored_vt.astype(jnp.int8), e1["v_vt"]
        )
        e1["v_num"] = jnp.where(open_corr[:, None], stored_num, e1["v_num"])
        e1["v_str"] = jnp.where(open_corr[:, None], stored_sid, e1["v_str"])
        e0 = put(
            e0, close_ok,
            valid=True, rtype=RT_EVENT, vtype=VT_MSUB, intent=int(MS.CLOSED),
            key=batch.key, type_id=batch.type_id, retries=batch.retries,
            worker=batch.worker, instance_key=batch.instance_key,
            aux_key=batch.aux_key,
        )
        e0 = put(
            e0, del_ok,
            valid=True, rtype=RT_EVENT, vtype=VT_MSG, intent=int(MI.DELETED),
            key=batch.key, type_id=batch.type_id, retries=batch.retries,
            worker=batch.worker, aux2_key=batch.aux2_key,
        )
        # workflow partition: CORRELATE arrival (oracle
        # _process_wi_subscription) — CORRELATED, then either the element
        # completes with the message payload (own catch), a boundary event
        # fires (non-interrupting keeps the subscription open), or the
        # host terminates (interrupting); CLOSE goes back to the message
        # partition except for non-interrupting boundaries
        e0 = put(
            e0, corr_live,
            valid=True, rtype=RT_EVENT, vtype=VT_WISUB,
            intent=int(WS.CORRELATED), key=batch.key,
            type_id=batch.type_id, retries=batch.retries, worker=batch.worker,
            instance_key=batch.instance_key, aux_key=batch.aux_key,
        )
        e1 = put(
            e1, corr_inst_ok,
            valid=True, rtype=RT_EVENT, vtype=VT_WI,
            intent=int(WI.ELEMENT_COMPLETING), key=batch.aux_key,
            elem=inst_elem, wf=inst_wf, scope_key=inst_scope_key,
        )
        e1 = put(
            e1, corr_bd_non,
            valid=True, rtype=RT_EVENT, vtype=VT_WI,
            intent=int(WI.BOUNDARY_EVENT_OCCURRED), key=key0,
            elem=corr_bd_elem, wf=inst_wf, scope_key=inst_scope_key,
        )
        e1 = put(
            e1, corr_bd_int,
            valid=True, rtype=RT_EVENT, vtype=VT_WI,
            intent=int(WI.ELEMENT_TERMINATING), key=batch.aux_key,
            elem=inst_elem, wf=inst_wf, scope_key=inst_scope_key,
        )
        # interrupting-boundary TERMINATING carries the INSTANCE payload
        # (oracle terminates with host_value); completion and boundary
        # firing carry the MESSAGE payload (batch defaults)
        e1["v_vt"] = jnp.where(corr_bd_int[:, None], wi_of_inst_vt, e1["v_vt"])
        e1["v_num"] = jnp.where(corr_bd_int[:, None], wi_of_inst_num, e1["v_num"])
        e1["v_str"] = jnp.where(corr_bd_int[:, None], wi_of_inst_sid, e1["v_str"])
        corr_any_inst = corr_inst_ok | corr_bd_non | corr_bd_int
        e1["instance_key"] = jnp.where(
            corr_any_inst, aik_i64_rows[:, EIL_IKEY], e1["instance_key"]
        )
        e2 = put(
            e2, corr_inst_ok | corr_bd_int,
            valid=True, rtype=RT_CMD, vtype=VT_MSUB, intent=int(MS.CLOSE),
            key=jnp.int64(-1), wf=pid_col,
            type_id=batch.type_id, retries=batch.retries, worker=batch.worker,
            instance_key=batch.instance_key, aux_key=batch.aux_key,
        )
        e0 = put(
            e0, corr_rej,
            valid=True, rtype=RT_REJ, vtype=vt_, intent=it, key=batch.key,
            type_id=batch.type_id, retries=batch.retries, worker=batch.worker,
            instance_key=batch.instance_key, aux_key=batch.aux_key,
            rej=rb.REJ_SUB_NOT_ACTIVE,
            req=batch.req, req_stream=batch.req_stream, resp=batch.req >= 0,
        )
    # --- boundary events: arm / disarm / terminate / continue.
    # Slot plan for rows on boundary-carrying elements (written order
    # mirrors the oracle: arms/cancels BEFORE the row's own step output):
    #   slots 0..BD-1   arm records (ACTIVATED) / timer cancels (disarm)
    #   slots BD..2BD-1 subscription closes (disarm; sends)
    #   slot 2BD        the row's own step output (job CREATE / OPEN /
    #                   COMPLETED / job CANCEL / own CLOSE)
    #   slot 2BD+1      ELEMENT_TERMINATED (terminating rows)
    if graph.has_boundaries:
        bdw = graph.bd_elem.shape[2]
        step_slot = eslot(2 * bdw)
        t_iota = jnp.arange(t_cap, dtype=jnp.int32)
        # disarm scan: this instance's armed timers by activityInstanceKey
        # (oracle _disarm_boundary_events' self.timers scan)
        cancel_mask = (
            m_disarm_bd[:, None]
            & (state.timer_key >= 0)[None, :]
            & (state.timer_aik[None, :] == batch.key[:, None])
        )
        for bslot in range(bdw):
            arm_b = m_arm & (bslot < bd_n)
            b_elem = graph.bd_elem[wf_c, el_c, bslot]
            b_tdur = graph.bd_timer[wf_c, el_c, bslot]
            b_mname = graph.bd_msg[wf_c, el_c, bslot]
            b_cvar = graph.bd_corr[wf_c, el_c, bslot]
            es = eslot(bslot)
            # timer boundary arm (oracle writes TimerIntent.CREATE)
            es = put(
                es, arm_b & (b_tdur >= 0),
                valid=True, rtype=RT_CMD, vtype=VT_TIMER, intent=int(TI.CREATE),
                key=jnp.int64(-1), elem=b_elem, aux_key=batch.key,
                deadline=now + jnp.maximum(b_tdur, 0),
            )
            # message boundary arm: correlation key from this row's payload
            b_cvar_c = jnp.clip(b_cvar, 0, v - 1)
            b_cvt = batch.v_vt[rows, b_cvar_c].astype(jnp.int32)
            b_cbits = jnp.where(
                b_cvt == int(COND_VT_STR),
                batch.v_str[rows, b_cvar_c],
                jax.lax.bitcast_convert_type(
                    batch.v_num[rows, b_cvar_c], jnp.int32
                ),
            )
            b_extractable = (b_cvar >= 0) & (
                (b_cvt == int(COND_VT_STR))
                | (b_cvt == int(COND_VT_NUM))
                | (b_cvt == int(COND_VT_BOOL))
            )
            es = put(
                es, arm_b & (b_mname > 0) & b_extractable,
                valid=True, rtype=RT_CMD, vtype=VT_MSUB, intent=int(MS.OPEN),
                key=jnp.int64(-1), elem=b_elem, type_id=b_mname,
                retries=b_cvt, worker=b_cbits,
                instance_key=batch.instance_key, aux_key=batch.key,
                wf=pid_col,
            )
            es = put(
                es, arm_b & (b_mname > 0) & ~b_extractable,
                valid=True, rtype=RT_CMD, vtype=VT_INCIDENT, intent=0,
                key=jnp.int64(-1), elem=b_elem, aux_key=batch.key,
                rej=rb.ERR_CORRELATION_KEY,
            )
            # disarm: bslot-th armed timer cancel
            c_idx = jnp.min(
                jnp.where(cancel_mask, t_iota[None, :], t_cap), axis=1
            ).astype(jnp.int32)
            c_found = c_idx < t_cap
            c_clipd = jnp.clip(c_idx, 0, t_cap - 1)
            with jax.named_scope("zb_gather"):
                c_key, c_due, c_ik, c_elem = pops.fused_gather_rows(
                    [state.timer_key, state.timer_due,
                     state.timer_instance_key, state.timer_elem],
                    [pops.GatherOp(0, c_clipd), pops.GatherOp(1, c_clipd),
                     pops.GatherOp(2, c_clipd), pops.GatherOp(3, c_clipd)],
                )
            es = put(
                es, c_found,
                valid=True, rtype=RT_CMD, vtype=VT_TIMER, intent=int(TI.CANCEL),
                key=c_key, elem=c_elem,
                aux_key=batch.key, deadline=c_due,
                instance_key=c_ik,
            )
            cancel_mask = cancel_mask & (t_iota[None, :] != c_clipd[:, None])
            # disarm: message-boundary subscription closes (sends)
            es2 = eslot(bdw + bslot)
            es2 = put(
                es2, m_disarm_bd & (bslot < bd_n) & (b_mname > 0) & b_extractable,
                valid=True, rtype=RT_CMD, vtype=VT_MSUB, intent=int(MS.CLOSE),
                key=jnp.int64(-1), type_id=b_mname,
                retries=b_cvt, worker=b_cbits,
                instance_key=batch.instance_key, aux_key=batch.key,
                wf=pid_col,
            )

        # re-slotted step outputs for boundary-carrying rows
        step_slot = put(
            step_slot, m_createjob & has_bd,
            valid=True, rtype=RT_CMD, vtype=VT_JOB, intent=int(JI.CREATE),
            key=jnp.int64(-1), elem=batch.elem, aux_key=batch.key,
            type_id=emeta[:, graph_mod.EM_JOB_TYPE],
            retries=emeta[:, graph_mod.EM_JOB_RETRIES],
        )
        step_slot = put(
            step_slot, outmap_ok & has_bd,
            valid=True, rtype=RT_EVENT, vtype=VT_WI,
            intent=int(WI.ELEMENT_COMPLETED), key=batch.key, elem=batch.elem,
        )
        step_slot["v_vt"] = jnp.where(
            (outmap_ok & has_bd)[:, None], out_vt, step_slot["v_vt"]
        )
        step_slot["v_num"] = jnp.where(
            (outmap_ok & has_bd)[:, None], out_num, step_slot["v_num"]
        )
        step_slot["v_str"] = jnp.where(
            (outmap_ok & has_bd)[:, None], out_sid, step_slot["v_str"]
        )
        if graph.has_messages:
            step_slot = put(
                step_slot, sub_ok & has_bd,
                valid=True, rtype=RT_CMD, vtype=VT_MSUB, intent=int(MS.OPEN),
                key=jnp.int64(-1), elem=batch.elem,
                type_id=emeta[:, graph_mod.EM_MSG_NAME],
                retries=corr_vt_ext, worker=corr_bits_ext,
                instance_key=batch.instance_key, aux_key=batch.key,
                wf=pid_col,
            )
            step_slot = put(
                step_slot, sub_err & has_bd,
                valid=True, rtype=RT_CMD, vtype=VT_INCIDENT, intent=0,
                key=jnp.int64(-1), elem=batch.elem, aux_key=batch.key,
                rej=rb.ERR_CORRELATION_KEY,
            )
            # TERMINATE_CATCH_EVENT: close the element's own subscription
            step_slot = put(
                step_slot,
                m_term_catch & (emeta[:, graph_mod.EM_MSG_NAME] > 0)
                & corr_extractable,
                valid=True, rtype=RT_CMD, vtype=VT_MSUB, intent=int(MS.CLOSE),
                key=jnp.int64(-1), type_id=emeta[:, graph_mod.EM_MSG_NAME],
                retries=corr_vt_ext, worker=corr_bits_ext,
                instance_key=batch.instance_key, aux_key=batch.key,
                wf=pid_col,
            )
        # TERMINATE_JOB_TASK: cancel the instance's job, then TERMINATED
        job_key_inst = jnp.where(ei_found, ei_i64_rows[:, EIL_JOB_KEY], -1)
        tj_found, tj_slot = pops.lookup(
            state.job_map, job_key_inst, m_term_job & (job_key_inst > 0)
        )
        tj_clip = jnp.clip(tj_slot, 0, m_cap - 1)
        mask_jcancel = m_term_job & (job_key_inst > 0)
        step_slot = put(
            step_slot, mask_jcancel,
            valid=True, rtype=RT_CMD, vtype=VT_JOB, intent=int(JI.CANCEL),
            key=job_key_inst, elem=batch.elem, aux_key=batch.key,
            type_id=jnp.where(tj_found, state.job_type[tj_clip], 0),
            retries=jnp.int32(-1),  # JobRecord default — oracle sends a
            # bare record: type + headers only, no payload
        )
        step_slot["v_vt"] = jnp.where(
            mask_jcancel[:, None], jnp.int8(0), step_slot["v_vt"]
        )
        step_slot["v_num"] = jnp.where(
            mask_jcancel[:, None], jnp.float32(0), step_slot["v_num"]
        )
        step_slot["v_str"] = jnp.where(
            mask_jcancel[:, None], jnp.int32(0), step_slot["v_str"]
        )
        # TERMINATE_CATCH_EVENT's own timer scan (slots 2BD+1..3BD): the
        # oracle writes these cancels between the step output and
        # TERMINATED; a timer both disarmed and terminate-scanned cancels
        # TWICE, exactly like the oracle's two passes over self.timers
        tc_mask = (
            m_cancel_timers[:, None]
            & (state.timer_key >= 0)[None, :]
            & (state.timer_aik[None, :] == batch.key[:, None])
        )
        for t in range(bdw):
            tc_idx = jnp.min(
                jnp.where(tc_mask, t_iota[None, :], t_cap), axis=1
            ).astype(jnp.int32)
            tc_found = tc_idx < t_cap
            tc_clipd = jnp.clip(tc_idx, 0, t_cap - 1)
            with jax.named_scope("zb_gather"):
                tc_key, tc_due, tc_ik, tc_elem = pops.fused_gather_rows(
                    [state.timer_key, state.timer_due,
                     state.timer_instance_key, state.timer_elem],
                    [pops.GatherOp(0, tc_clipd), pops.GatherOp(1, tc_clipd),
                     pops.GatherOp(2, tc_clipd), pops.GatherOp(3, tc_clipd)],
                )
            es3 = eslot(2 * bdw + 1 + t)
            es3 = put(
                es3, tc_found,
                valid=True, rtype=RT_CMD, vtype=VT_TIMER, intent=int(TI.CANCEL),
                key=tc_key, elem=tc_elem,
                aux_key=batch.key, deadline=tc_due,
                instance_key=tc_ik,
            )
            tc_mask = tc_mask & (t_iota[None, :] != tc_clipd[:, None])

        term_tail = eslot(3 * bdw + 1)
        term_tail = put(
            term_tail, m_term_job | m_term_catch,
            valid=True, rtype=RT_EVENT, vtype=VT_WI,
            intent=int(WI.ELEMENT_TERMINATED), key=batch.key, elem=batch.elem,
        )
        e0 = put(
            e0, m_term_elem,
            valid=True, rtype=RT_EVENT, vtype=VT_WI,
            intent=int(WI.ELEMENT_TERMINATED), key=batch.key, elem=batch.elem,
        )
        # ELEMENT_TERMINATED with a pending boundary: the token continues
        # at the boundary event with the stored trigger payload
        cont_vt, cont_sid, cont_num = unpack_payload(ei_pay_rows)
        e0 = put(
            e0, m_bd_continue,
            valid=True, rtype=RT_EVENT, vtype=VT_WI,
            intent=int(WI.BOUNDARY_EVENT_OCCURRED), key=key0,
            elem=pending_bd,
        )
        e0["v_vt"] = jnp.where(
            m_bd_continue[:, None], cont_vt.astype(jnp.int8), e0["v_vt"]
        )
        e0["v_num"] = jnp.where(m_bd_continue[:, None], cont_num, e0["v_num"])
        e0["v_str"] = jnp.where(m_bd_continue[:, None], cont_sid, e0["v_str"])

    # jev_completed payload = job payload (record payload already in columns)
    # (value defaults carry batch payload, which is the job's — correct)

    # --- fork slots (parallel split + multi-instance) + assemble [B, E]
    em = {}
    for name in e0:
        parts = [e0[name], e1[name]] + [
            extra_slots[i][name] if i in extra_slots
            else jnp.zeros_like(e0[name])
            for i in range(2, e_w)
        ]
        em[name] = jnp.stack(parts, axis=1)  # [B, E] or [B, E, V]

    # fork_flows [B, F<=E] rows rode the phase-A fused graph gather
    fan_out = fork_flows.shape[1]
    for f in range(min(fan_out, e_w)):
        mask_f = m_psplit & (f < out_count)
        em["valid"] = em["valid"].at[:, f].set(
            jnp.where(mask_f, True, em["valid"][:, f])
        )
        for name, val in (
            ("rtype", RT_EVENT), ("vtype", VT_WI),
            ("intent", int(WI.SEQUENCE_FLOW_TAKEN)),
        ):
            em[name] = em[name].at[:, f].set(
                jnp.where(mask_f, val, em[name][:, f])
            )
        em["key"] = em["key"].at[:, f].set(
            jnp.where(mask_f, wf_base + _KEY_STEP * f, em["key"][:, f])
        )
        em["elem"] = em["elem"].at[:, f].set(
            jnp.where(mask_f, fork_flows[:, f], em["elem"][:, f])
        )
        for name in ("wf", "instance_key", "scope_key"):
            em[name] = em[name].at[:, f].set(
                jnp.where(mask_f, getattr(batch, name), em[name][:, f])
            )
        for name in ("v_vt", "v_num", "v_str"):
            em[name] = em[name].at[:, f].set(
                jnp.where(mask_f[:, None], getattr(batch, name), em[name][:, f])
            )
        em["src"] = em["src"].at[:, f].set(rows)

    if graph.has_multi_instance:
        # multi-instance fan-out (oracle _h_multi_instance_split,
        # cardinality form): one body token per iteration, each carrying
        # loopCounter = i+1; the container completes when the last body
        # token is consumed (token counting, same as the parallel join)
        mi_card = emeta[:, graph_mod.EM_MI_CARD]
        lv = graph.mi_loop_var
        for f in range(e_w):  # emit_width covers the max cardinality
            mask_f = m_mi & (f < mi_card)
            em["valid"] = em["valid"].at[:, f].set(
                jnp.where(mask_f, True, em["valid"][:, f])
            )
            for name, val in (
                ("rtype", RT_EVENT), ("vtype", VT_WI),
                ("intent", int(WI.START_EVENT_OCCURRED)),
            ):
                em[name] = em[name].at[:, f].set(
                    jnp.where(mask_f, val, em[name][:, f])
                )
            em["key"] = em["key"].at[:, f].set(
                jnp.where(mask_f, wf_base + _KEY_STEP * f, em["key"][:, f])
            )
            em["elem"] = em["elem"].at[:, f].set(
                jnp.where(mask_f, start_ev, em["elem"][:, f])
            )
            em["wf"] = em["wf"].at[:, f].set(
                jnp.where(mask_f, batch.wf, em["wf"][:, f])
            )
            em["instance_key"] = em["instance_key"].at[:, f].set(
                jnp.where(mask_f, batch.instance_key, em["instance_key"][:, f])
            )
            em["scope_key"] = em["scope_key"].at[:, f].set(
                jnp.where(mask_f, batch.key, em["scope_key"][:, f])
            )
            mi_vt = batch.v_vt.at[:, lv].set(jnp.int8(COND_VT_NUM))
            mi_num = batch.v_num.at[:, lv].set(jnp.float32(f + 1))
            em["v_vt"] = em["v_vt"].at[:, f].set(
                jnp.where(mask_f[:, None], mi_vt, em["v_vt"][:, f])
            )
            em["v_num"] = em["v_num"].at[:, f].set(
                jnp.where(mask_f[:, None], mi_num, em["v_num"][:, f])
            )
            em["v_str"] = em["v_str"].at[:, f].set(
                jnp.where(mask_f[:, None], batch.v_str, em["v_str"][:, f])
            )
            em["src"] = em["src"].at[:, f].set(rows)

    # -------- state scatters: fused phase-E commits --------
    # Every table write below is expressed as a pops.TableOp and committed
    # through pops.fused_table_commit: ONE pallas mega-pass per table group
    # (element instances, jobs, timers) that keeps the tables VMEM-resident
    # and applies the whole ~20-op write tail in a single serial pass — the
    # per-record cost is a handful of VPU instructions instead of ~20ns of
    # per-index DMA issue PER OP (PERF_NOTES round-4 cost model). Where the
    # engine-boot autotune picked the unfused path (or off-TPU), the commit
    # degrades to the exact previous op chain, so the CPU parity suites pin
    # the semantics bit-for-bit. Op order matches the old op-major chain;
    # the only cross-op row sharing between records is through commutative
    # "add" ops (token counters), so the mega-pass's chunk-major execution
    # is observationally identical.
    ei_i64_pl = pops.i64_to_planes(state.ei_i64)
    ei_k32 = state.ei_i32.shape[1]
    T_EI32, T_EI64, T_EIPAY, T_EIFREE, T_EIIDX = range(5)
    ei_ops = []

    def _col_op(k, col, val):
        """([B, k] vals, [B, k] mask) pair writing ``val`` into one column."""
        if jnp.ndim(val) == 0:
            val = jnp.full((b,), val, jnp.int32)
        vals = jnp.zeros((b, k), jnp.int32).at[:, col].set(
            val.astype(jnp.int32)
        )
        mask = jnp.zeros((b, k), bool).at[:, col].set(True)
        return vals, mask

    # token counters: one select-by-kind accumulate on the scope row (a
    # record is exactly one of consume / parallel-split / join-complete,
    # so the old per-kind accumulate chain merges into one commutative op)
    # nin_rec (join fan-in per record) rode the round-9a fused read pass
    tok_m = m_consume | m_psplit | completer
    tok_v = jnp.where(
        m_consume, jnp.int32(-1),
        jnp.where(m_psplit, out_count - 1, -(nin_rec - 1)),
    )
    tok_vals, tok_mask = _col_op(ei_k32, EI_TOKENS, tok_v)
    ei_ops.append(pops.TableOp(T_EI32, "add", sc_clip, tok_m, tok_vals, tok_mask))
    if graph.has_boundaries:
        # non-interrupting boundary fire: the host's scope gains a token
        # for the boundary path (oracle: scope.active_tokens += 1)
        bd_vals, bd_mask = _col_op(ei_k32, EI_TOKENS, jnp.ones((b,), jnp.int32))
        ei_ops.append(pops.TableOp(
            T_EI32, "add", jnp.clip(inst_scope_slot, 0, n_cap - 1),
            ttrig_bd_non | corr_bd_non, bd_vals, bd_mask,
        ))
    # start-trigger / multi-instance container token counts (own row; the
    # container holds one token per body iteration — disjoint step kinds)
    tokset_m = m_trigstart
    tokset_v = jnp.ones((b,), jnp.int32)
    if graph.has_multi_instance:
        tokset_m = tokset_m | m_mi
        tokset_v = jnp.where(m_mi, emeta[:, graph_mod.EM_MI_CARD], 1)
    ts_vals, ts_mask = _col_op(ei_k32, EI_TOKENS, tokset_v)
    ei_ops.append(pops.TableOp(T_EI32, "set", ei_clip, tokset_m, ts_vals, ts_mask))

    # scope payload on consume (oracle: scope value.payload = record
    # payload — EXCEPT multi-instance containers, whose iteration-local
    # variables must not leak into the container payload)
    b_pay = pack_payload(batch.v_vt, batch.v_str, batch.v_num)
    if graph.has_multi_instance:
        scope_elem_c = jnp.clip(
            jnp.where(sc_found, sc_rows[:, EI_ELEM], 0),
            0, graph.elem_type.shape[1] - 1,
        )
        scope_wf_c = jnp.clip(
            jnp.where(sc_found, sc_rows[:, EI_WF], 0),
            0, graph.elem_type.shape[0] - 1,
        )
        mi_scope = graph.mi_cardinality[scope_wf_c, scope_elem_c] > 0
        consume_pay_m = m_consume & ~mi_scope
    else:
        consume_pay_m = m_consume
    ei_ops.append(pops.TableOp(
        T_EIPAY, "set", sc_clip,
        _last_writer(sc_clip, consume_pay_m, n_cap), b_pay,
    ))
    # scope state transition by consume completer
    cc_vals, cc_mask = _col_op(
        ei_k32, EI_STATE, jnp.int32(int(WI.ELEMENT_COMPLETING))
    )
    ei_ops.append(pops.TableOp(
        T_EI32, "set", sc_clip, consume_completer, cc_vals, cc_mask
    ))
    # -- own-row transitions, ONE composed write per dtype family ---------
    # Every record is exactly one step kind (the guard predicates are
    # mutually exclusive per record, and the no-concurrent-transition
    # guards exclude two records transitioning the same instance row in
    # one round), so the per-kind column writes compose into a single
    # select-by-kind row write instead of one write per kind.
    if graph.has_boundaries:
        bd_int_any = ttrig_bd_int | corr_bd_int
        term_all = m_term_job | m_term_catch | m_term_elem
    else:
        bd_int_any = jnp.zeros((b,), bool)
        term_all = jnp.zeros((b,), bool)
    ei_remove = outmap_ok | m_complete_proc | m_bd_continue

    own_is_aik = jev_completed | ttrig_catch | bd_int_any
    own_slot = jnp.where(own_is_aik, aik_clip, ei_clip)
    completing = jev_completed | ttrig_catch
    own_state_m = inmap_ok | completing | bd_int_any | term_all | ei_remove
    own_state_v = jnp.where(
        ei_remove, jnp.int32(-1),                      # removal wins last
        jnp.where(
            term_all, jnp.int32(int(WI.ELEMENT_TERMINATED)),
            jnp.where(
                bd_int_any, jnp.int32(int(WI.ELEMENT_TERMINATING)),
                jnp.where(
                    completing, jnp.int32(int(WI.ELEMENT_COMPLETING)),
                    jnp.int32(int(WI.ELEMENT_ACTIVATED)),
                ),
            ),
        ),
    )
    own_vals = jnp.zeros((b, ei_k32), jnp.int32)
    own_mask = jnp.zeros((b, ei_k32), bool)
    own_vals = own_vals.at[:, EI_STATE].set(own_state_v)
    own_mask = own_mask.at[:, EI_STATE].set(own_state_m)
    if graph.has_boundaries:
        # pending boundary element recorded with the TERMINATING write
        own_vals = own_vals.at[:, EI_PENDING_BD].set(
            jnp.where(ttrig_bd_int, trig_elem, corr_bd_elem)
        )
        own_mask = own_mask.at[:, EI_PENDING_BD].set(bd_int_any)
    own_active = own_state_m
    ei_ops.append(pops.TableOp(
        T_EI32, "set", own_slot, own_active, own_vals, own_mask
    ))

    # own-row payloads: input mapping writes the mapped document, job
    # completion / message-boundary interruption write the record payload
    own_pay_m = inmap_ok | jev_completed | (corr_bd_int if graph.has_boundaries
                                            else jnp.zeros((b,), bool))
    inmap_pay = pack_payload(in_vt, in_sid, in_num)
    own_pay = jnp.where(inmap_ok[:, None], inmap_pay, b_pay)
    ei_ops.append(pops.TableOp(
        T_EIPAY, "set", own_slot,
        _last_writer(own_slot, own_pay_m, n_cap), own_pay,
    ))

    # own-row i64 columns (job-key attach/detach, removal key clear)
    jobkey_m = jev_completed | (jev_created & aik_found)
    jobkey_v = jnp.where(jev_completed, jnp.int64(-1), batch.key)
    ei64_slot = jnp.where(jobkey_m, aik_clip, ei_clip)
    v2 = pops.vec64_to_planes(jobkey_v)
    neg2 = pops.vec64_to_planes(jnp.full((b,), -1, jnp.int64))
    ei64_vals = jnp.zeros((b, ei_i64_pl.shape[1]), jnp.int32)
    ei64_mask = jnp.zeros((b, ei_i64_pl.shape[1]), bool)
    ei64_vals = ei64_vals.at[:, 2 * EIL_JOB_KEY].set(v2[:, 0])
    ei64_vals = ei64_vals.at[:, 2 * EIL_JOB_KEY + 1].set(v2[:, 1])
    ei64_mask = ei64_mask.at[:, 2 * EIL_JOB_KEY].set(jobkey_m)
    ei64_mask = ei64_mask.at[:, 2 * EIL_JOB_KEY + 1].set(jobkey_m)
    ei64_vals = jnp.where(
        (ei_remove & ~jobkey_m)[:, None],
        jnp.zeros_like(ei64_vals).at[:, 2 * EIL_KEY].set(neg2[:, 0])
        .at[:, 2 * EIL_KEY + 1].set(neg2[:, 1]),
        ei64_vals,
    )
    ei64_mask = jnp.where(
        (ei_remove & ~jobkey_m)[:, None],
        jnp.zeros_like(ei64_mask).at[:, 2 * EIL_KEY].set(True)
        .at[:, 2 * EIL_KEY + 1].set(True),
        ei64_mask,
    )
    ei_ops.append(pops.TableOp(
        T_EI64, "set", ei64_slot, jobkey_m | ei_remove, ei64_vals, ei64_mask
    ))
    # no map delete: the removed row's key column is cleared above, and
    # every lookup verifies against it — stale index/map entries are inert
    ei_map = state.ei_map

    # inserts: CREATE command roots + START_STATEFUL children (+ replayed
    # CREATED events whose instance is missing)
    ins_root = m_create
    ins_child = m_startst
    ins = ins_root | ins_child | ins_replay
    ins_key = jnp.where(ins_root, key0, jnp.where(ins_child, key0, batch.key))
    ins_elem = jnp.where(ins_root, 0, jnp.where(ins_child, ftarget, batch.elem))
    ins_parent = jnp.where(ins_child, sc_slot, -1)
    ins_ikey = jnp.where(ins_root, key0, batch.instance_key)
    # free-slot ring pop (replaces the full-table free scan): slots freed
    # this round enter at push and are never re-allocated in the same
    # round (matches the old scan, which read round-start state). The
    # ring read itself rode the round-9a fused read pass (ei_pop_slot).
    ins_slot = jnp.where(
        ins & ei_ring_ok, ei_pop_slot, n_cap
    ).astype(jnp.int32)
    ei_overflow = jnp.any(ins & ~ei_ring_ok)
    free_ei_pop_new = state.free_ei_pop + jnp.sum(ins, dtype=jnp.int64)
    # dedup pushes per slot: two removal records for the same row in one
    # batch (e.g. a client-retried command) must free the slot ONCE, or
    # the ring later hands the row to two inserts
    ei_push_m = _last_writer(ei_clip, ei_remove, n_cap)
    ei_rm_rank = _excl_cumsum(ei_push_m.astype(jnp.int32))
    ei_push_idx = state.free_ei_push + ei_rm_rank.astype(jnp.int64)
    ei_ops.append(pops.TableOp(
        T_EIFREE, "set", (ei_push_idx % n_cap).astype(jnp.int32),
        ei_push_m, ei_clip,
    ))
    free_ei_push_new = state.free_ei_push + jnp.sum(ei_push_m, dtype=jnp.int64)
    # one row write per dtype group (the point of the packed layout)
    ei_i32_rows = jnp.stack(
        [ins_elem,
         jnp.full((b,), int(WI.ELEMENT_READY), jnp.int32),
         batch.wf, ins_parent, jnp.zeros((b,), jnp.int32),
         jnp.full((b,), -1, jnp.int32)], axis=-1,  # no pending boundary
    )
    ei_ops.append(pops.TableOp(T_EI32, "set", ins_slot, ins, ei_i32_rows))
    ei_i64_rows = jnp.stack(
        [ins_key, ins_ikey, jnp.full((b,), -1, jnp.int64)], axis=-1
    )
    ei_ops.append(pops.TableOp(
        T_EI64, "set", ins_slot, ins, pops.i64_to_planes(ei_i64_rows)
    ))
    ei_ops.append(pops.TableOp(T_EIPAY, "set", ins_slot, ins, b_pay))
    ei_icap = state.ei_index.shape[0]
    ei_ops.append(pops.TableOp(
        T_EIIDX, "set", ((ins_key // 5) & (ei_icap - 1)).astype(jnp.int32),
        ins, ins_slot,
    ))
    if graph.has_messages:
        # correlate arrival → instance completes with the message payload
        corr_vals, corr_mask = _col_op(
            ei_k32, EI_STATE, jnp.int32(int(WI.ELEMENT_COMPLETING))
        )
        ei_ops.append(pops.TableOp(
            T_EI32, "set", aik_clip, corr_inst_ok, corr_vals, corr_mask
        ))
        ei_ops.append(pops.TableOp(
            T_EIPAY, "set", aik_clip,
            _last_writer(aik_clip, corr_inst_ok, n_cap), b_pay,
        ))

    ei_i32_arr, ei_i64_pl, ei_pay, free_ei_arr, ei_index_arr = (
        pops.fused_table_commit(
            [state.ei_i32, ei_i64_pl, state.ei_pay, state.free_ei,
             state.ei_index],
            ei_ops,
        )
    )
    ei_i64_arr = pops.planes_to_i64(ei_i64_pl)

    # ---------------- job table (fused commit) ----------------
    T_J32, T_J64, T_JPAY, T_JFREE, T_JIDX = range(5)
    job_i64_pl = pops.i64_to_planes(state.job_i64)
    job_k32 = state.job_i32.shape[1]
    job_ops = []
    # job ring pop indices + the ring read hoisted into the round-9a
    # fused read pass (job_pop_slot)
    j_slot = jnp.where(
        job_ins & job_ring_ok, job_pop_slot, m_cap
    ).astype(jnp.int32)
    job_overflow = jnp.any(job_ins & ~job_ring_ok)
    free_job_pop_new = state.free_job_pop + jnp.sum(job_ins, dtype=jnp.int64)
    job_i32_rows = jnp.stack(
        [jnp.full((b,), int(JI.CREATED), jnp.int32),
         batch.elem, batch.wf, batch.type_id, batch.retries,
         jnp.zeros((b,), jnp.int32)], axis=-1,
    )
    job_ops.append(pops.TableOp(T_J32, "set", j_slot, job_ins, job_i32_rows))
    job_i64_rows = jnp.stack(
        [job_base, batch.instance_key, batch.aux_key,
         jnp.full((b,), -1, jnp.int64)], axis=-1,
    )
    job_ops.append(pops.TableOp(
        T_J64, "set", j_slot, job_ins, pops.i64_to_planes(job_i64_rows)
    ))
    job_ops.append(pops.TableOp(T_JPAY, "set", j_slot, job_ins, b_pay))
    job_icap = state.job_index.shape[0]
    job_ops.append(pops.TableOp(
        T_JIDX, "set", ((job_base // 5) & (job_icap - 1)).astype(jnp.int32),
        job_ins, j_slot,
    ))
    job_map = state.job_map

    # transitions: every record is one job step kind and all kinds target
    # jb_clip, so the per-kind column writes compose into ONE row write
    # per dtype family (select-by-kind values)
    job_rm = jcomp_ok | jcan_ok
    jstate_m = jact_ok | jfail_ok | jtime_ok | job_rm
    jstate_v = jnp.where(
        job_rm, jnp.int32(-1),
        jnp.where(
            jtime_ok, jnp.int32(int(JI.TIMED_OUT)),
            jnp.where(
                jfail_ok, jnp.int32(int(JI.FAILED)),
                jnp.int32(int(JI.ACTIVATED)),
            ),
        ),
    )
    jretries_m = jact_ok | jfail_ok | jret_ok
    jb_vals = jnp.zeros((b, job_k32), jnp.int32)
    jb_mask = jnp.zeros((b, job_k32), bool)
    jb_vals = jb_vals.at[:, JB_STATE].set(jstate_v)
    jb_mask = jb_mask.at[:, JB_STATE].set(jstate_m)
    jb_vals = jb_vals.at[:, JB_RETRIES].set(batch.retries)
    jb_mask = jb_mask.at[:, JB_RETRIES].set(jretries_m)
    jb_vals = jb_vals.at[:, JB_WORKER].set(batch.worker)
    jb_mask = jb_mask.at[:, JB_WORKER].set(jact_ok)
    job_ops.append(pops.TableOp(
        T_J32, "set", jb_clip, jstate_m | jret_ok, jb_vals, jb_mask
    ))

    jd2 = pops.vec64_to_planes(batch.deadline)
    jneg2 = pops.vec64_to_planes(jnp.full((b,), -1, jnp.int64))
    j64_vals = jnp.zeros((b, job_i64_pl.shape[1]), jnp.int32)
    j64_mask = jnp.zeros((b, job_i64_pl.shape[1]), bool)
    j64_vals = j64_vals.at[:, 2 * JBL_DEADLINE].set(jd2[:, 0])
    j64_vals = j64_vals.at[:, 2 * JBL_DEADLINE + 1].set(jd2[:, 1])
    j64_mask = j64_mask.at[:, 2 * JBL_DEADLINE].set(jact_ok)
    j64_mask = j64_mask.at[:, 2 * JBL_DEADLINE + 1].set(jact_ok)
    j64_vals = jnp.where(
        job_rm[:, None],
        jnp.zeros_like(j64_vals).at[:, 2 * JBL_KEY].set(jneg2[:, 0])
        .at[:, 2 * JBL_KEY + 1].set(jneg2[:, 1]),
        j64_vals,
    )
    j64_mask = jnp.where(
        job_rm[:, None],
        jnp.zeros_like(j64_mask).at[:, 2 * JBL_KEY].set(True)
        .at[:, 2 * JBL_KEY + 1].set(True),
        j64_mask,
    )
    job_ops.append(pops.TableOp(
        T_J64, "set", jb_clip, jact_ok | job_rm, j64_vals, j64_mask
    ))

    jpay_m = jact_ok | jfail_ok
    jpay = jnp.where(
        jfail_ok[:, None], pack_payload(fail_vt, fail_sid, fail_num), b_pay
    )
    job_ops.append(pops.TableOp(T_JPAY, "set", jb_clip, jpay_m, jpay))
    # dedup per slot (see the ei ring push)
    job_push_m = _last_writer(jb_clip, job_rm, m_cap)
    job_rm_rank = _excl_cumsum(job_push_m.astype(jnp.int32))
    job_push_idx = state.free_job_push + job_rm_rank.astype(jnp.int64)
    job_ops.append(pops.TableOp(
        T_JFREE, "set", (job_push_idx % m_cap).astype(jnp.int32),
        job_push_m, jb_clip,
    ))
    free_job_push_new = state.free_job_push + jnp.sum(job_push_m, dtype=jnp.int64)

    job_i32_arr, job_i64_pl, job_pay_arr, free_job_arr, job_index_arr = (
        pops.fused_table_commit(
            [state.job_i32, job_i64_pl, state.job_pay, state.free_job,
             state.job_index],
            job_ops,
        )
    )
    job_i64_arr = pops.planes_to_i64(job_i64_pl)

    # ---------------- join cleanup ----------------
    if graph.has_parallel_joins:
        join_key_arr = pops.masked_vec64_update(
            join_key_arr, arr_slot, completer,
            jnp.full((b,), -1, jnp.int64),
        )
        join_nin_arr = pops.masked_lane_update(
            join_nin_arr, arr_slot, completer, jnp.zeros((b,), jnp.int32)
        )
        arrived = pops.masked_row_update(
            arrived.astype(jnp.int32), arr_slot, completer,
            jnp.zeros((b, arrived.shape[1]), jnp.int32),
        ).astype(bool)
        stamp = pops.masked_row_update(
            stamp, arr_slot, completer,
            jnp.full((b, stamp.shape[1]), -1, jnp.int32),
        )
        join_map = pops.delete(jmap, join_key, completer)
    else:
        join_map = jmap

    # ---------------- timer table ----------------
    if graph.has_timers:
        # fused commit over the timer bookkeeping columns (i64 columns as
        # [TM, 2] i32 planes, elem/wf as 1D lane tables): the 8 insert /
        # remove writes ride one mega-pass; the hashmap insert/delete stay
        # their own probe kernels
        t_ins = m_tcreate
        tfree = _first_true_indices(state.timer_key < 0, b)
        t_rank = _excl_cumsum(t_ins.astype(jnp.int32))
        t_slot = tfree[jnp.clip(t_rank, 0, b - 1)]
        timer_overflow = jnp.any(t_ins & (t_slot >= t_cap))
        t_rm = ttrig_ok | tcan_ok
        tneg_pl = pops.vec64_to_planes(jnp.full((b,), -1, jnp.int64))
        T_TK, T_TD, T_TA, T_TIK, T_TE, T_TW = range(6)
        timer_ops = [
            pops.TableOp(T_TK, "set", t_slot, t_ins, pops.vec64_to_planes(key0)),
            pops.TableOp(
                T_TD, "set", t_slot, t_ins, pops.vec64_to_planes(batch.deadline)
            ),
            pops.TableOp(
                T_TA, "set", t_slot, t_ins, pops.vec64_to_planes(batch.aux_key)
            ),
            pops.TableOp(
                T_TIK, "set", t_slot, t_ins,
                pops.vec64_to_planes(batch.instance_key),
            ),
            pops.TableOp(T_TE, "set", t_slot, t_ins, batch.elem),
            pops.TableOp(T_TW, "set", t_slot, t_ins, batch.wf),
            pops.TableOp(T_TK, "set", tm_clip, t_rm, tneg_pl),
            pops.TableOp(T_TD, "set", tm_clip, t_rm, tneg_pl),
        ]
        tk_pl, td_pl, ta_pl, tik_pl, timer_elem_arr, timer_wf_arr = (
            pops.fused_table_commit(
                [pops.i64_to_planes(state.timer_key[:, None]),
                 pops.i64_to_planes(state.timer_due[:, None]),
                 pops.i64_to_planes(state.timer_aik[:, None]),
                 pops.i64_to_planes(state.timer_instance_key[:, None]),
                 state.timer_elem, state.timer_wf],
                timer_ops,
            )
        )
        timer_key_arr = pops.planes_to_i64(tk_pl)[:, 0]
        timer_due_arr = pops.planes_to_i64(td_pl)[:, 0]
        timer_aik_arr = pops.planes_to_i64(ta_pl)[:, 0]
        timer_ik_arr = pops.planes_to_i64(tik_pl)[:, 0]
        timer_map, _t_ok = pops.insert(state.timer_map, key0, t_slot, t_ins)
        timer_map = pops.delete(timer_map, batch.key, t_rm)
    else:
        timer_overflow = jnp.zeros((), bool)
        timer_key_arr = state.timer_key
        timer_due_arr = state.timer_due
        timer_aik_arr = state.timer_aik
        timer_ik_arr = state.timer_instance_key
        timer_elem_arr = state.timer_elem
        timer_wf_arr = state.timer_wf
        timer_map = state.timer_map

    # ---------------- message tables ----------------
    if graph.has_messages:
        neg64 = jnp.full((b,), -1, jnp.int64)
        # subscription inserts (OPEN) / removals (CLOSE)
        msfree = _first_true_indices(state.msub_ckey < 0, b)
        ms_rank = _excl_cumsum(open_ok.astype(jnp.int32))
        ms_slot_new = msfree[jnp.clip(ms_rank, 0, b - 1)]
        msub_overflow = jnp.any(open_ok & (ms_slot_new >= ms_cap))
        msub_ckey_arr = pops.masked_vec64_update(
            state.msub_ckey, ms_slot_new, open_ok, ckey
        )
        msub_i32_arr = pops.masked_row_update(
            state.msub_i32, ms_slot_new, open_ok,
            jnp.stack(
                [batch.type_id, batch.retries, batch.worker, batch.wf], axis=-1
            ),
        )
        msub_i64_pl = pops.i64_to_planes(state.msub_i64)
        msub_i64_pl = pops.masked_row_update(
            msub_i64_pl, ms_slot_new, open_ok,
            pops.i64_to_planes(
                jnp.stack([batch.instance_key, batch.aux_key], axis=-1)
            ),
        )
        msub_map_arr, msub_ins_ok = pops.insert(
            state.msub_map, ckey, ms_slot_new, open_ok
        )
        msub_ckey_arr = pops.masked_vec64_update(
            msub_ckey_arr, msub_clip, close_ok, neg64
        )
        msub_map_arr = pops.delete(msub_map_arr, ckey, close_ok)
        msub_i64_arr = pops.planes_to_i64(msub_i64_pl)

        # stored messages (PUBLISH with TTL) / deletions
        mgfree = _first_true_indices(state.msg_key < 0, b)
        mg_rank = _excl_cumsum(pub_store.astype(jnp.int32))
        mg_slot_new = mgfree[jnp.clip(mg_rank, 0, b - 1)]
        msg_overflow = jnp.any(pub_store & (mg_slot_new >= mg_cap))
        msg_key_arr = pops.masked_vec64_update(
            state.msg_key, mg_slot_new, pub_store, key0
        )
        msg_ckey_arr = pops.masked_vec64_update(
            state.msg_ckey, mg_slot_new, pub_store, ckey
        )
        msg_i32_arr = pops.masked_row_update(
            state.msg_i32, mg_slot_new, pub_store,
            jnp.stack(
                [batch.type_id, batch.retries, batch.worker,
                 batch.aux2_key.astype(jnp.int32)], axis=-1,
            ),
        )
        msg_deadline_arr = pops.masked_vec64_update(
            state.msg_deadline, mg_slot_new, pub_store, now + batch.deadline
        )
        msg_pay_arr = pops.masked_row_update(
            state.msg_pay, mg_slot_new, pub_store, b_pay
        )
        msg_map_arr, msg_ins_ok = pops.insert(
            state.msg_map, ckey, mg_slot_new, pub_store
        )
        msg_key_arr = pops.masked_vec64_update(
            msg_key_arr, mmsg_clip, del_ok, neg64
        )
        msg_deadline_arr = pops.masked_vec64_update(
            msg_deadline_arr, mmsg_clip, del_ok, neg64
        )
        msg_map_arr = pops.delete(msg_map_arr, ckey, del_ok)

        message_overflow = (
            msub_overflow | msg_overflow
            | ~jnp.all(msub_ins_ok == open_ok)
            | ~jnp.all(msg_ins_ok == pub_store)
        )
    else:
        msub_ckey_arr = state.msub_ckey
        msub_i32_arr = state.msub_i32
        msub_i64_arr = state.msub_i64
        msub_map_arr = state.msub_map
        msg_key_arr = state.msg_key
        msg_ckey_arr = state.msg_ckey
        msg_i32_arr = state.msg_i32
        msg_deadline_arr = state.msg_deadline
        msg_pay_arr = state.msg_pay
        msg_map_arr = state.msg_map
        message_overflow = jnp.zeros((), bool)

    # ---------------- output compaction ----------------
    flat_valid = em["valid"].reshape(-1)
    be = b * e_w
    take_idx = _first_true_indices(flat_valid, be)
    count = jnp.sum(flat_valid, dtype=jnp.int32)

    idx = jnp.clip(take_idx, 0, be - 1)

    # the compaction packs the whole emission record into TWO row gathers
    # (an i32 mega-matrix: scalars + v_str + bitcast v_num + i64 planes;
    # an i8 matrix: flags + v_vt) routed through the "emit" fused-gather
    # family — the per-dtype-group takes before this dominated the
    # emission tail at ~20ns/record of per-index issue apiece. The
    # bitcast/widen round-trips are exact, so the packed take is
    # bit-identical to per-field takes.
    i32_names = ["rtype", "vtype", "intent", "elem", "wf", "req_stream",
                 "type_id", "retries", "worker", "src", "rej"]
    i64_names = ["key", "instance_key", "scope_key", "req", "aux_key",
                 "aux2_key", "deadline"]

    def _flat(n):
        return em[n].reshape((be,) + em[n].shape[2:])

    with jax.named_scope("zb_emit"):
        i32_mat = jnp.concatenate(
            [jnp.stack([_flat(n).astype(jnp.int32) for n in i32_names],
                       axis=-1),
             _flat("v_str"),
             jax.lax.bitcast_convert_type(_flat("v_num"), jnp.int32),
             pops.i64_to_planes(
                 jnp.stack([_flat(n) for n in i64_names], axis=-1)
             )],
            axis=1,
        )
        i8_mat = jnp.concatenate(
            [jnp.stack([_flat("resp").astype(jnp.int8),
                        _flat("push").astype(jnp.int8)], axis=-1),
             _flat("v_vt")],
            axis=1,
        )
        taken_i32, taken_i8 = pops.fused_gather_rows(
            [i32_mat, i8_mat],
            [pops.GatherOp(0, idx), pops.GatherOp(1, idx)],
            family="emit",
        )
    n32 = len(i32_names)
    i32 = {n: taken_i32[:, i] for i, n in enumerate(i32_names)}
    i64_mat = pops.planes_to_i64(
        taken_i32[:, n32 + 2 * v : n32 + 2 * v + 2 * len(i64_names)]
    )
    i64 = {n: i64_mat[:, i] for i, n in enumerate(i64_names)}
    flags = {"resp": taken_i8[:, 0], "push": taken_i8[:, 1]}

    out = RecordBatch(
        valid=jnp.arange(be, dtype=jnp.int32) < count,
        rtype=i32["rtype"],
        vtype=i32["vtype"],
        intent=i32["intent"],
        key=i64["key"],
        elem=i32["elem"],
        wf=i32["wf"],
        instance_key=i64["instance_key"],
        scope_key=i64["scope_key"],
        v_vt=taken_i8[:, 2:],
        v_num=jax.lax.bitcast_convert_type(
            taken_i32[:, n32 + v : n32 + 2 * v], jnp.float32
        ),
        v_str=taken_i32[:, n32 : n32 + v],
        req=i64["req"],
        req_stream=i32["req_stream"],
        aux_key=i64["aux_key"],
        aux2_key=i64["aux2_key"],
        type_id=i32["type_id"],
        retries=i32["retries"],
        deadline=i64["deadline"],
        worker=i32["worker"],
        src=i32["src"],
        resp=flags["resp"].astype(bool),
        push=flags["push"].astype(bool),
        rej=i32["rej"],
    )

    new_state = EngineState(
        ei_i32=ei_i32_arr, ei_i64=ei_i64_arr,
        ei_pay=ei_pay, ei_map=ei_map, ei_index=ei_index_arr,
        free_ei=free_ei_arr, free_ei_pop=free_ei_pop_new,
        free_ei_push=free_ei_push_new,
        job_i32=job_i32_arr, job_i64=job_i64_arr,
        job_pay=job_pay_arr, job_map=job_map, job_index=job_index_arr,
        free_job=free_job_arr, free_job_pop=free_job_pop_new,
        free_job_push=free_job_push_new,
        join_key=join_key_arr, join_nin=join_nin_arr, join_arrived=arrived,
        join_pay=join_pay, join_pos_stamp=stamp, join_map=join_map,
        timer_key=timer_key_arr, timer_due=timer_due_arr,
        timer_aik=timer_aik_arr, timer_instance_key=timer_ik_arr,
        timer_elem=timer_elem_arr, timer_wf=timer_wf_arr, timer_map=timer_map,
        msub_ckey=msub_ckey_arr, msub_i32=msub_i32_arr,
        msub_i64=msub_i64_arr, msub_map=msub_map_arr,
        msg_key=msg_key_arr, msg_ckey=msg_ckey_arr, msg_i32=msg_i32_arr,
        msg_deadline=msg_deadline_arr, msg_pay=msg_pay_arr,
        msg_map=msg_map_arr,
        sub_key=state.sub_key, sub_type=state.sub_type,
        sub_worker=state.sub_worker, sub_credits=sub_credits,
        sub_timeout=state.sub_timeout, sub_valid=state.sub_valid,
        sub_rr=state.sub_rr,
        next_wf_key=next_wf_key, next_job_key=next_job_key,
    )
    stats = {
        "processed": jnp.sum(valid, dtype=jnp.int32),
        "stepped": jnp.sum(stepped, dtype=jnp.int32)
        + jnp.sum(job_cmd | job_ev | timer_cmd | m_create | m_created_ev
                  | msg_pub | msg_del | ms_open | ms_close | wisub_corr,
                  dtype=jnp.int32),
        "emitted": count,
        "completed_roots": jnp.sum(
            m_complete_proc & (batch.elem == 0), dtype=jnp.int32
        ),
        "overflow": (
            ei_overflow | job_overflow | join_overflow | timer_overflow
            | message_overflow
        ),
    }
    return new_state, out, stats


step_jit = jit_registry.register_jit(
    "kernel.step",
    step_kernel,
    state_args=(1,),
    donate_argnums=(1,),
    static_argnames=("synthetic_workers",),
    max_signatures=4,
    notes="one signature per (synthetic_workers, wave shape) pair a "
    "serving process uses; the scheduler packs fixed-size waves",
)


def tick_kernel(state: EngineState, now) -> Tuple[RecordBatch, jax.Array]:
    """Due-timer and job-deadline scan → TIME_OUT / TRIGGER command batch
    (reference JobTimeOutStreamProcessor + the oracle's check_*_deadlines;
    ordered by key like the oracle's sorted iteration)."""
    t_cap = state.timer_key.shape[0]
    m_cap = state.job_key.shape[0]
    v = state.num_vars
    size = t_cap + m_cap

    timer_due = (state.timer_key >= 0) & (state.timer_due <= now)
    job_due = (
        (state.job_state == int(JI.ACTIVATED))
        & (state.job_deadline >= 0)
        & (state.job_deadline <= now)
    )
    keys = jnp.concatenate([state.timer_key, state.job_key])
    due = jnp.concatenate([timer_due, job_due])
    order = jnp.argsort(jnp.where(due, keys, jnp.int64(2**62)), stable=True)
    count = jnp.sum(due, dtype=jnp.int32)

    is_timer = jnp.concatenate(
        [jnp.ones((t_cap,), bool), jnp.zeros((m_cap,), bool)]
    )[order]
    tidx = jnp.clip(order, 0, t_cap - 1)
    jidx = jnp.clip(order - t_cap, 0, m_cap - 1)

    sel = jnp.arange(size, dtype=jnp.int32) < count
    tick_jb_vt, tick_jb_sid, tick_jb_num = unpack_payload(state.job_pay[jidx])
    out = RecordBatch(
        valid=sel,
        rtype=jnp.full((size,), RT_CMD, jnp.int32),
        vtype=jnp.where(
            is_timer, jnp.int32(VT_TIMER), jnp.int32(VT_JOB)
        ),
        intent=jnp.where(
            is_timer, jnp.int32(int(TI.TRIGGER)), jnp.int32(int(JI.TIME_OUT))
        ),
        key=keys[order],
        elem=jnp.where(is_timer, state.timer_elem[tidx], state.job_elem[jidx]),
        wf=jnp.where(is_timer, state.timer_wf[tidx], state.job_wf[jidx]),
        instance_key=jnp.where(
            is_timer, state.timer_instance_key[tidx], state.job_instance_key[jidx]
        ),
        scope_key=jnp.full((size,), -1, jnp.int64),
        v_vt=jnp.where(is_timer[:, None], 0, tick_jb_vt).astype(jnp.int8),
        v_num=jnp.where(is_timer[:, None], jnp.float32(0.0), tick_jb_num),
        v_str=jnp.where(is_timer[:, None], 0, tick_jb_sid),
        req=jnp.full((size,), -1, jnp.int64),
        req_stream=jnp.full((size,), -1, jnp.int32),
        aux_key=jnp.where(is_timer, state.timer_aik[tidx], state.job_aik[jidx]),
        aux2_key=jnp.full((size,), -1, jnp.int64),
        type_id=jnp.where(is_timer, 0, state.job_type[jidx]),
        retries=jnp.where(is_timer, 0, state.job_retries[jidx]),
        deadline=jnp.where(
            is_timer, state.timer_due[tidx], state.job_deadline[jidx]
        ),
        worker=jnp.where(is_timer, 0, state.job_worker[jidx]),
        src=jnp.full((size,), -1, jnp.int32),
        resp=jnp.zeros((size,), bool),
        push=jnp.zeros((size,), bool),
        rej=jnp.zeros((size,), jnp.int32),
    )
    return out, count


def _tick_entry(
    state: EngineState, now
) -> Tuple[EngineState, RecordBatch, jax.Array]:
    """Donating wrapper for ``tick_kernel``: the scan only READS state, so
    the entry passes it through unchanged and declares the input donated —
    XLA aliases the ~50 state tables input→output instead of keeping a
    second resident copy live across the tick (zbaudit boundary pass).
    Callers must rebind: ``state, out, count = tick_jit(state, now)``."""
    out, count = tick_kernel(state, now)
    return state, out, count


tick_jit = jit_registry.register_jit(
    "kernel.tick",
    _tick_entry,
    state_args=(0,),
    donate_argnums=(0,),
    max_signatures=2,
    notes="state shape is fixed per engine; one extra signature allowed "
    "for a capacity-resized engine in the same process",
)
