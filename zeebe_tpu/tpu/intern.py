"""Host-side string interning.

The device never sees strings: element ids, job types, worker names,
message names, string literals in conditions, and string-valued payload
variables are interned host-side to dense int32 ids. Equality on device is
id equality (exact, unlike hashing). The reference's analogue is the
garbage-free DirectBuffer string handling in msgpack-value
(``msgpack-value/.../value/StringValue.java``) — strings are compared as
bytes there; here they are compared as ids.
"""

from __future__ import annotations

from typing import Dict, List, Optional


NIL_ID = 0  # id 0 is reserved: "no string"


class InternTable:
    def __init__(self):
        self._by_str: Dict[str, int] = {}
        self._by_id: List[Optional[str]] = [None]  # id 0 reserved

    def intern(self, s: str) -> int:
        sid = self._by_str.get(s)
        if sid is None:
            sid = len(self._by_id)
            self._by_str[s] = sid
            self._by_id.append(s)
        return sid

    def lookup(self, s: str) -> int:
        """Id of ``s`` or NIL_ID when never interned (device compares will
        simply not match)."""
        return self._by_str.get(s, NIL_ID)

    def string(self, sid: int) -> Optional[str]:
        if 0 < sid < len(self._by_id):
            return self._by_id[sid]
        return None

    def __len__(self) -> int:
        return len(self._by_id)
