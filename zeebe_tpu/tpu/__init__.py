"""TPU-native execution engine.

This package is the device-side replacement for the reference's per-record
stream processors (``broker-core/.../logstreams/processor/TypedStreamProcessor.java``,
``broker-core/.../workflow/processor/BpmnStepProcessor.java``): committed
records are processed in batches by one ``jax.jit`` step kernel that applies
all BPMN/job state transitions as masked SIMD updates over struct-of-arrays
state resident in HBM, and emits follow-up records via fixed-slot emission +
prefix-sum compaction (replay-parity with the host oracle engine in
``zeebe_tpu.engine.interpreter``).

Module map:

- ``intern``    — host string interning (ids are what the device sees)
- ``hashmap``   — open-addressing i64→i32 hash table in HBM (zb-map analogue)
- ``conditions``— json-el condition compiler → device predicate programs
- ``graph``     — ExecutableWorkflow set → tensor tables (the "compiled BPMN")
- ``batch``     — SoA record batches + host<->device conversion
- ``state``     — engine state pytree (element instances, jobs, joins, subs)
- ``kernel``    — THE step kernel
- ``engine``    — host wrapper: partition processor API over the kernel

Keys are int64 (the reference's keyspace is 64-bit, KeyGenerator.java); the
package enables jax x64 at import.
"""

import jax

jax.config.update("jax_enable_x64", True)

from zeebe_tpu.tpu.engine import TpuPartitionEngine  # noqa: E402,F401
