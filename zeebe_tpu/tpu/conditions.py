"""json-el conditions → device predicate programs.

The reference evaluates exclusive-gateway conditions per record with a tree
interpreter over msgpack (``json-el/.../JsonConditionInterpreter.java``);
here each condition compiles once (at deployment) to a postfix program over
columnarized payload variables, and the kernel evaluates ALL records × ALL
outgoing flows in parallel with a fixed-depth stack machine (lax.scan over
instructions).

Tri-state logic preserves the oracle's short-circuit error semantics
(``zeebe_tpu/models/el/interpreter.py``): FALSE=0, TRUE=1, ERROR=2;
``and``: F→F, E→E, else right; ``or``: T→T, E→E, else right. A comparison
errors when a referenced variable is absent, types mismatch (int/float
widen), or ordering is applied to non-numbers — exactly the oracle's raise
conditions, so an ERROR result maps to the same CONDITION_ERROR incident.

Strings compare by interned id (exact); numbers compare as float32 —
sound because only f32-EXACT values reach the device (payload
columnarization and literal compilation both reject inexact values into
the host path, where the oracle compares float64), and f64→f32 is
order-preserving on exactly-representable values.
"""

from __future__ import annotations

import dataclasses
from typing import List, Optional, Tuple

import numpy as np

import jax
import jax.numpy as jnp
from jax import lax

from zeebe_tpu.models.el.ast import (
    Comparison,
    Condition,
    Conjunction,
    Disjunction,
    JsonPathLiteral,
    Literal,
    compile_json_path,
)
from zeebe_tpu.tpu.intern import InternTable

# tri-state
FALSE, TRUE, ERROR = 0, 1, 2

# opcodes
OP_NOP, OP_CMP, OP_AND, OP_OR = 0, 1, 2, 3

# comparison ops
CMP_OPS = {"==": 0, "!=": 1, "<": 2, "<=": 3, ">": 4, ">=": 5}

# operand kinds
K_VAR, K_NUM, K_STR, K_BOOL, K_NIL = 0, 1, 2, 3, 4

# variable value types (the ``vtype`` payload column); INTEGER and FLOAT are
# distinct for document round-trips but compare as one numeric class
# (the oracle's int/float widening, interpreter.py _coerce_same_type)
VT_ABSENT, VT_NIL, VT_BOOL, VT_NUM, VT_STR, VT_FLOAT = 0, 1, 2, 3, 4, 5

STACK_DEPTH = 8


def f32_exact(value: float) -> bool:
    """True when ``value`` survives a float32 round trip exactly. The
    device engine stores payload numerics as f32 (see state.pack_payload);
    BOTH gates — payload columnarization (batch.payload_to_columns) and
    condition-literal compilation below — must use this same predicate, or
    the "only f32-exact values reach the device" soundness argument of this
    module's header breaks."""
    f = np.float32(value)
    return bool(np.isfinite(f)) and float(f) == float(value)


class DeviceIneligible(ValueError):
    """Condition uses a feature the device path cannot evaluate (nested
    JSONPath, non-scalar literal) — the workflow falls back to the host
    oracle engine."""


@dataclasses.dataclass
class ProgramPool:
    """Host-side accumulator for compiled programs; ``tensors`` yields the
    device form."""

    varspace: "object"  # VarSpace (graph.py); needs .column(name)
    interns: InternTable
    programs: List[List[Tuple[int, int, int, int, int, int]]] = dataclasses.field(
        default_factory=list
    )
    lit_nums: List[float] = dataclasses.field(default_factory=list)

    def _num_literal(self, value: float) -> int:
        if not f32_exact(value):
            raise DeviceIneligible(
                f"condition literal not f32-exact: {value!r}"
            )
        self.lit_nums.append(float(value))
        return len(self.lit_nums) - 1

    def _operand(self, operand) -> Tuple[int, int]:
        if isinstance(operand, JsonPathLiteral):
            steps = compile_json_path(operand.path)
            if len(steps) != 1 or not isinstance(steps[0], str):
                raise DeviceIneligible(
                    f"non-flat JSONPath in condition: {operand.path}"
                )
            return K_VAR, self.varspace.column(steps[0])
        assert isinstance(operand, Literal)
        v = operand.value
        if v is None:
            return K_NIL, 0
        if isinstance(v, bool):
            return K_BOOL, 1 if v else 0
        if isinstance(v, (int, float)):
            return K_NUM, self._num_literal(v)
        if isinstance(v, str):
            return K_STR, self.interns.intern(v)
        raise DeviceIneligible(f"non-scalar literal in condition: {v!r}")

    def _emit(self, condition: Condition, out: list) -> None:
        if isinstance(condition, Comparison):
            lk, li = self._operand(condition.left)
            rk, ri = self._operand(condition.right)
            out.append((OP_CMP, CMP_OPS[condition.op], lk, li, rk, ri))
        elif isinstance(condition, Conjunction):
            self._emit(condition.left, out)
            self._emit(condition.right, out)
            out.append((OP_AND, 0, 0, 0, 0, 0))
        elif isinstance(condition, Disjunction):
            self._emit(condition.left, out)
            self._emit(condition.right, out)
            out.append((OP_OR, 0, 0, 0, 0, 0))
        else:
            raise DeviceIneligible(f"unknown condition node: {condition!r}")

    def compile(self, condition: Condition) -> int:
        """Compile one condition; returns its program id."""
        out: list = []
        self._emit(condition, out)
        self.programs.append(out)
        return len(self.programs) - 1

    def tensors(self):
        """(progs [P, L, 6] i32, lit_nums [Q] f32), padded to coarse sizes
        so kernel jit caches are shared across deployments."""

        def _pad(n: int, mult: int) -> int:
            return ((max(n, 1) + mult - 1) // mult) * mult

        max_len = _pad(max((len(p) for p in self.programs), default=0), 8)
        count = _pad(len(self.programs), 4)
        arr = [
            [list(ins) for ins in p] + [[OP_NOP] * 6] * (max_len - len(p))
            for p in self.programs
        ]
        arr += [[[OP_NOP] * 6] * max_len] * (count - len(arr))
        progs = jnp.array(arr, dtype=jnp.int32).reshape(count, max_len, 6)
        lits = list(self.lit_nums)
        lits += [0.0] * (_pad(len(lits), 8) - len(lits))
        lit_nums = jnp.array(lits, dtype=jnp.float32)
        return progs, lit_nums


def _resolve(kind, idx, v_vt, v_num, v_str, lit_nums):
    """Operand → (vtype, num, sid). ``kind``/``idx`` broadcast over the
    query shape; v_* are [..., V] payload columns."""
    var_vt = jnp.take_along_axis(v_vt, idx[..., None], axis=-1)[..., 0]
    var_num = jnp.take_along_axis(v_num, idx[..., None], axis=-1)[..., 0]
    var_str = jnp.take_along_axis(v_str, idx[..., None], axis=-1)[..., 0]
    lit_num = lit_nums[jnp.clip(idx, 0, lit_nums.shape[0] - 1)]

    vt = jnp.select(
        [kind == K_VAR, kind == K_NUM, kind == K_STR, kind == K_BOOL],
        [var_vt, VT_NUM, VT_STR, VT_BOOL],
        VT_NIL,
    )
    num = jnp.select(
        [kind == K_VAR, kind == K_NUM, kind == K_BOOL],
        [var_num, lit_num, idx.astype(jnp.float32)],
        jnp.float32(0.0),
    )
    sid = jnp.select(
        [kind == K_VAR, kind == K_STR],
        [var_str, idx],
        0,
    )
    return vt, num, sid


def _compare(op, lvt, lnum, lsid, rvt, rnum, rsid):
    """Tri-state comparison, oracle semantics."""
    absent = (lvt == VT_ABSENT) | (rvt == VT_ABSENT)
    any_nil = (lvt == VT_NIL) | (rvt == VT_NIL)
    both_nil = (lvt == VT_NIL) & (rvt == VT_NIL)
    l_num_t = (lvt == VT_NUM) | (lvt == VT_FLOAT)
    r_num_t = (rvt == VT_NUM) | (rvt == VT_FLOAT)
    same_type = (lvt == rvt) | (l_num_t & r_num_t)

    eq_raw = jnp.select(
        [lvt == VT_STR, lvt == VT_BOOL],
        [lsid == rsid, lnum == rnum],
        lnum == rnum,  # numeric
    )
    # equality: nil equals only nil (no error); else same type required
    eq_err = (~any_nil) & (~same_type)
    eq_val = jnp.where(any_nil, both_nil, eq_raw)
    eq_tri = jnp.where(eq_err, ERROR, eq_val.astype(jnp.int32))
    ne_tri = jnp.where(eq_err, ERROR, (~eq_val).astype(jnp.int32))

    # ordering: numbers only
    ord_err = ~(l_num_t & r_num_t)
    ord_raw = jnp.select(
        [op == 2, op == 3, op == 4],
        [lnum < rnum, lnum <= rnum, lnum > rnum],
        lnum >= rnum,
    )
    ord_tri = jnp.where(ord_err, ERROR, ord_raw.astype(jnp.int32))

    tri = jnp.select([op == 0, op == 1], [eq_tri, ne_tri], ord_tri)
    return jnp.where(absent, ERROR, tri)


def _combine_and(a, b):
    return jnp.where(a == FALSE, FALSE, jnp.where(a == ERROR, ERROR, b))


def _combine_or(a, b):
    return jnp.where(a == TRUE, TRUE, jnp.where(a == ERROR, ERROR, b))


def eval_programs(progs, lit_nums, prog_id, v_vt, v_num, v_str):
    """Evaluate programs for a batch of queries.

    prog_id: [...] i32 (clipped; callers mask out -1 themselves)
    v_vt/v_num/v_str: [..., V] payload columns (same leading shape)
    returns tri-state [...] i32
    """
    pid = jnp.clip(prog_id, 0, progs.shape[0] - 1)
    code = progs[pid]  # [..., L, 6]
    length = progs.shape[1]
    shape = prog_id.shape

    stack0 = jnp.zeros(shape + (STACK_DEPTH,), dtype=jnp.int32)
    sp0 = jnp.zeros(shape, dtype=jnp.int32)
    lanes = jnp.arange(STACK_DEPTH, dtype=jnp.int32)

    def step(carry, i):
        stack, sp = carry
        ins = code[..., i, :]  # [..., 6]
        opcode = ins[..., 0]
        is_cmp = opcode == OP_CMP
        is_and = opcode == OP_AND
        is_or = opcode == OP_OR

        lvt, lnum, lsid = _resolve(
            ins[..., 2], ins[..., 3], v_vt, v_num, v_str, lit_nums
        )
        rvt, rnum, rsid = _resolve(
            ins[..., 4], ins[..., 5], v_vt, v_num, v_str, lit_nums
        )
        cmp_tri = _compare(ins[..., 1], lvt, lnum, lsid, rvt, rnum, rsid)

        # pop two for AND/OR
        top = jnp.take_along_axis(
            stack, jnp.maximum(sp - 1, 0)[..., None], axis=-1
        )[..., 0]
        under = jnp.take_along_axis(
            stack, jnp.maximum(sp - 2, 0)[..., None], axis=-1
        )[..., 0]
        comb = jnp.where(is_and, _combine_and(under, top), _combine_or(under, top))

        is_bin = is_and | is_or
        push_val = jnp.where(is_cmp, cmp_tri, comb)
        push_pos = jnp.where(is_bin, jnp.maximum(sp - 2, 0), sp)
        write = (is_cmp | is_bin)[..., None] & (lanes == push_pos[..., None])
        stack = jnp.where(write, push_val[..., None], stack)
        sp = jnp.where(is_cmp, sp + 1, jnp.where(is_bin, sp - 1, sp))
        return (stack, sp), None

    (stack, _), _ = lax.scan(step, (stack0, sp0), jnp.arange(length))
    return stack[..., 0]
