"""Registry of the device plane's jit entry points, for IR-level audit.

Every ``jax.jit`` in ``zeebe_tpu/`` routes through :func:`register_jit`
(enforced by the ``jit-registry`` zblint rule) so ``tools/zbaudit`` can
enumerate the full set of compiled programs a serving run produces and
statically audit each one — HBM footprint, dtype flow, host boundary and
donation/aliasing, collective volume, recompile signatures — without
guessing at call sites. The registry records the audit-relevant contract
alongside the jitted callable:

- ``state_args``: positions carrying an ``EngineState`` (or other large
  resident pytree). The boundary pass asserts each is donated — an
  un-donated state arg doubles peak HBM for the duration of the step.
- ``collective``: the program is built under ``shard_map`` and is
  expected to contain collectives; the collective-volume pass models its
  per-round bytes, and non-collective entries are asserted collective-free.
- ``max_signatures``: ceiling on distinct compiled signatures a serving
  run may produce for this entry (the recompile-signature guard compares
  the live ``_cache_size()`` against it).
- ``suppress``: zbaudit pass names deliberately waived for this entry,
  with ``notes`` saying why — same contract as a zblint inline disable,
  but attached to the program rather than a source line.

Re-registering a name is allowed (per-mesh builders like
``shard.build_sharded_step`` construct a fresh program per topology);
the latest registration wins and ``instances`` counts how many times the
entry was built this process.
"""

from __future__ import annotations

import dataclasses
from typing import Any, Callable, Dict, Optional, Tuple

import jax

__all__ = ["JitEntry", "register_jit", "entries", "get", "signature_report"]


@dataclasses.dataclass
class JitEntry:
    """One registered jit entry point plus its audit contract."""

    name: str
    fn: Any  # the jitted callable (jax.stages.Wrapped)
    wrapped: Callable  # the underlying python function
    state_args: Tuple[int, ...] = ()
    donate_argnums: Tuple[int, ...] = ()
    static_argnames: Tuple[str, ...] = ()
    collective: bool = False
    max_signatures: int = 1
    suppress: Tuple[str, ...] = ()
    notes: str = ""
    instances: int = 1

    def cache_size(self) -> Optional[int]:
        """Live compiled-signature count, or None when jax doesn't expose
        one (API drift / freshly built entry)."""
        try:
            return int(self.fn._cache_size())
        except (AttributeError, TypeError):  # private API; absence is data
            return None


REGISTRY: Dict[str, JitEntry] = {}


def _as_tuple(v) -> tuple:
    if v is None:
        return ()
    if isinstance(v, (str, int)):
        return (v,)
    return tuple(v)


def register_jit(
    name: str,
    fn: Callable,
    *,
    state_args=(),
    collective: bool = False,
    max_signatures: int = 1,
    suppress=(),
    notes: str = "",
    **jit_kwargs,
):
    """``jax.jit`` with an audit registration — the only sanctioned way to
    create a jit entry point inside ``zeebe_tpu/`` (zblint ``jit-registry``).

    ``jit_kwargs`` pass through to ``jax.jit`` verbatim (``donate_argnums``,
    ``static_argnames``, ...). Returns the jitted callable.
    """
    jitted = jax.jit(fn, **jit_kwargs)
    prev = REGISTRY.get(name)
    REGISTRY[name] = JitEntry(
        name=name,
        fn=jitted,
        wrapped=fn,
        state_args=_as_tuple(state_args),
        donate_argnums=_as_tuple(jit_kwargs.get("donate_argnums")),
        static_argnames=_as_tuple(jit_kwargs.get("static_argnames")),
        collective=collective,
        max_signatures=int(max_signatures),
        suppress=_as_tuple(suppress),
        notes=notes,
        instances=(prev.instances + 1) if prev is not None else 1,
    )
    return jitted


def entries() -> Dict[str, JitEntry]:
    """Snapshot of the registry (name → entry)."""
    return dict(REGISTRY)


def get(name: str) -> Optional[JitEntry]:
    return REGISTRY.get(name)


def signature_report() -> Dict[str, dict]:
    """Per-entry live compile-cache occupancy vs the declared ceiling —
    the runtime leg of zbaudit's recompile-signature guard (the static leg
    lowers each entry; this one reads what the process actually compiled)."""
    out = {}
    for name, e in sorted(REGISTRY.items()):
        out[name] = {
            "cache_size": e.cache_size(),
            "max_signatures": e.max_signatures,
            "instances": e.instances,
        }
    return out
