"""Process-global event counting for low-level layers.

Transport, log storage, snapshot storage and raft have no broker metrics
registry in reach (they are constructed in many places, some several
layers from a broker), so chaos-relevant events count into the global
registry of :mod:`zeebe_tpu.runtime.metrics`. This module exists so those
layers share ONE shim: it is import-cycle-free (no imports at module
level) because ``zeebe_tpu.runtime`` initializes the broker — which
imports ``zeebe_tpu.log`` — at package-init time, and a top-level metrics
import from inside ``log`` would re-enter that cycle half-built.
"""

from __future__ import annotations


def count_event(name: str, help_text: str = "", delta: float = 1.0) -> None:
    """Bump a process-global event counter (allocate-on-first-use)."""
    from zeebe_tpu.runtime.metrics import count_event as _impl

    _impl(name, help_text, delta)


def set_gauge(name: str, value: float, help_text: str = "", **labels: str) -> None:
    """Set a process-global gauge (allocate-on-first-use); same shim rules
    as :func:`count_event` — merged into every /metrics dump."""
    from zeebe_tpu.runtime.metrics import global_gauge

    global_gauge(name, help_text, **labels).set(value)
