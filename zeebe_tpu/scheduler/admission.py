"""Gateway admission control: bounded in-flight, shed-before-collapse.

Under overload a broker that keeps accepting commands converts every new
request into queue time: clients see p99 latency grow without bound until
their own deadlines fire, at which point they retry and make it worse.
The reference behavior for a full broker is backpressure at the request
boundary (the client API rejects with RESOURCE_EXHAUSTED and the client
retries with backoff) — never queue-until-timeout.

:class:`AdmissionController` enforces two watermarks at the client-API
edge, BEFORE a command touches the broker actor:

- **per-connection in-flight bound** — one client connection may have at
  most ``max_inflight_per_connection`` commands awaiting responses; the
  excess is rejected retryably. This bounds what a single misbehaving
  client can queue regardless of aggregate load.
- **queue-depth watermark** — when the broker-wide backlog (committed
  records awaiting the drain + pending responses) crosses
  ``queue_depth_high``, NEW commands are shed until it recedes. The probe
  is supplied by the broker (the wave scheduler's ``backlog()`` plus its
  pending-response map).

Rejections are counted (``gateway_commands_shed``, labeled by reason) and
carry a ``retry_ms`` hint; ``gateway/cluster_client.py`` treats the
rejection as retryable with backoff. Checks run on the transport IO
thread and are lock-cheap (one dict op per command).
"""

from __future__ import annotations

import threading
from typing import Callable, Dict, Optional

from zeebe_tpu.runtime.metrics import GLOBAL_REGISTRY
from zeebe_tpu.tracing.recorder import RateLimitedEvent

# rejection reasons (the wire carries them for observability; the client
# treats any RESOURCE_EXHAUSTED identically — back off and retry)
REASON_CONNECTION_INFLIGHT = "CONNECTION_INFLIGHT"
REASON_QUEUE_DEPTH = "QUEUE_DEPTH"


class AdmissionConfig:
    """Knobs (see ``runtime/config.AdmissionCfg`` for the TOML surface)."""

    __slots__ = (
        "enabled", "max_inflight_per_connection", "queue_depth_high",
        "retry_after_ms",
    )

    def __init__(
        self,
        enabled: bool = True,
        max_inflight_per_connection: int = 1024,
        queue_depth_high: int = 8192,
        retry_after_ms: int = 50,
    ):
        self.enabled = enabled
        self.max_inflight_per_connection = max(1, max_inflight_per_connection)
        self.queue_depth_high = max(1, queue_depth_high)
        self.retry_after_ms = max(1, retry_after_ms)


class AdmissionController:
    """Per-broker admission state. Thread-safe: ``try_admit`` runs on
    transport IO threads, ``release`` on whatever thread completes the
    response future."""

    def __init__(
        self,
        config: Optional[AdmissionConfig] = None,
        queue_depth_probe: Optional[Callable[[], int]] = None,
    ):
        self.config = config or AdmissionConfig()
        self._queue_depth_probe = queue_depth_probe
        self._lock = threading.Lock()
        self._inflight: Dict[int, int] = {}  # conn key → commands awaiting
        g = GLOBAL_REGISTRY
        self._shed_conn = g.counter(
            "gateway_commands_shed",
            "Commands rejected retryably at the admission boundary",
            reason=REASON_CONNECTION_INFLIGHT,
        )
        self._shed_queue = g.counter(
            "gateway_commands_shed",
            "Commands rejected retryably at the admission boundary",
            reason=REASON_QUEUE_DEPTH,
        )
        self._inflight_gauge = g.gauge(
            "gateway_inflight_commands",
            "Client commands admitted and awaiting responses (all "
            "connections)",
        )
        self._depth_gauge = g.gauge(
            "gateway_queue_depth",
            "Broker backlog observed by the last admission check "
            "(committed records awaiting the drain + pending responses)",
        )
        self._probe_failures = g.counter(
            "gateway_depth_probe_failures",
            "Queue-depth probe calls that raised (admission fails open "
            "with depth 0)",
        )
        # sheds burst at per-command rate under exactly the overload a
        # flight dump wants to explain — rate-limit the ring entries so
        # they cannot evict the control-plane history (counters above
        # stay exact)
        self._shed_event = RateLimitedEvent("admission", "command shed")

    def set_queue_depth_probe(self, probe: Callable[[], int]) -> None:
        self._queue_depth_probe = probe

    # -- the admission decision --------------------------------------------
    def try_admit(self, conn_key: int) -> Optional[str]:
        """Admit one command from ``conn_key``. Returns None when admitted
        (caller MUST pair with ``release``), else the rejection reason."""
        cfg = self.config
        if not cfg.enabled:
            return None
        probe = self._queue_depth_probe
        if probe is not None:
            try:
                depth = int(probe())
            except Exception:  # noqa: BLE001 - a probe bug must not shed
                self._probe_failures.inc()
                depth = 0
            self._depth_gauge.set(depth)
            if depth >= cfg.queue_depth_high:
                self._shed_queue.inc()
                self._shed_event.record(
                    reason=REASON_QUEUE_DEPTH, depth=depth,
                )
                return REASON_QUEUE_DEPTH
        with self._lock:
            inflight = self._inflight.get(conn_key, 0)
            if inflight >= cfg.max_inflight_per_connection:
                self._shed_conn.inc()
                self._shed_event.record(
                    reason=REASON_CONNECTION_INFLIGHT, conn=conn_key,
                    inflight=inflight,
                )
                return REASON_CONNECTION_INFLIGHT
            self._inflight[conn_key] = inflight + 1
        self._inflight_gauge.inc()
        return None

    def release(self, conn_key: int) -> None:
        """One admitted command finished (response sent or failed)."""
        with self._lock:
            inflight = self._inflight.get(conn_key)
            if inflight is None:
                return
            if inflight <= 1:
                self._inflight.pop(conn_key, None)
            else:
                self._inflight[conn_key] = inflight - 1
        self._inflight_gauge.inc(-1)

    def forget_connection(self, conn_key: int) -> None:
        """The connection closed: drop its in-flight accounting (its
        pending responses can no longer be delivered anyway)."""
        with self._lock:
            dropped = self._inflight.pop(conn_key, 0)
        if dropped:
            self._inflight_gauge.inc(-dropped)

    def inflight(self, conn_key: Optional[int] = None) -> int:
        with self._lock:
            if conn_key is not None:
                return self._inflight.get(conn_key, 0)
            return sum(self._inflight.values())

    def rejection_body(self, reason: str) -> dict:
        """The wire response for a shed command (retryable by contract)."""
        return {
            "t": "error",
            "code": "RESOURCE_EXHAUSTED",
            "reason": reason,
            "retry_ms": self.config.retry_after_ms,
        }
