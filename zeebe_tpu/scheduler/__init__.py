"""Continuous-batching wave scheduler + gateway admission control.

``wave.WaveScheduler`` packs committed records from ALL leader partitions
on a broker into shared device waves (deficit-round-robin fairness,
per-partition backpressure); ``admission.AdmissionController`` bounds
client in-flight and sheds retryably before the broker collapses under
overload. See docs/SERVING.md ("The wave scheduler").
"""

from zeebe_tpu.scheduler.admission import (
    AdmissionConfig,
    AdmissionController,
    REASON_CONNECTION_INFLIGHT,
    REASON_QUEUE_DEPTH,
)
from zeebe_tpu.scheduler.placement import DevicePlan, MeshExchange
from zeebe_tpu.scheduler.wave import (
    PartitionFeed,
    SharedWave,
    WaveScheduler,
    WaveSegment,
)

__all__ = [
    "AdmissionConfig",
    "AdmissionController",
    "DevicePlan",
    "MeshExchange",
    "PartitionFeed",
    "REASON_CONNECTION_INFLIGHT",
    "REASON_QUEUE_DEPTH",
    "SharedWave",
    "WaveScheduler",
    "WaveSegment",
]
