"""Continuous-batching device-wave scheduler (Orca-style).

The serving plane's wave metrics (PR 4) exposed the structural gap: each
partition drained its OWN committed tail into its own wave, so under
sparse or skewed traffic wave fill collapsed and every partition paid a
full device round-trip for a handful of records. On a TPU, batch
occupancy is the difference between rated and realized throughput — the
"millions of users" regime is heavy AGGREGATE traffic from many small
tenants, which must pack as tightly as one synthetic firehose.

:class:`WaveScheduler` is the single place waves are formed. It keeps a
per-partition cursor into each partition's committed tail (the one-lock
``committed_view``/``slice_records`` spans are the feed), packs records
from ALL leader partitions on a broker into SHARED waves up to
``wave_size``, dispatches each partition's segment through that
partition's engine (the existing ``dispatch_wave``/``collect_wave``
double-buffered pipeline), and de-multiplexes results back to the owning
partition's apply/append/response path. Per-partition processing order is
cursor order, so every partition's log stays bit-identical to what the
unscheduled per-partition drain produces.

Packing policy is deficit round-robin (DRR) fairness: each feed earns
``quantum`` record credits per packing round and spends them against its
backlog, so a partition with a deep backlog cannot starve sparse ones —
it simply fills whatever room the others leave. Backpressure is per
partition: a feed with more than ``backpressure_limit`` records dispatched
but not yet collected/applied is skipped (counted) until its apply side
catches up, so one slow partition can neither starve the others nor
overrun itself.

The scheduler is deliberately broker-agnostic: a feed is anything that
implements the small :class:`PartitionFeed` surface. The cluster broker's
``PartitionServer`` and the in-process broker's partitions both adapt to
it, so tier-1 covers the exact packing/dispatch code the cluster runs.
"""

from __future__ import annotations

import logging
from typing import Dict, List, Optional

from zeebe_tpu import tracing
from zeebe_tpu.runtime.metrics import (
    count_event,
    observe_device_wave,
    observe_mesh_wave,
    observe_shard_fill,
    observe_shared_wave,
)
from zeebe_tpu.tracing.recorder import FLIGHT, record_event

logger = logging.getLogger(__name__)


class PartitionFeed:
    """One partition's drain surface, as the scheduler sees it.

    Implementations (``runtime/cluster_broker.PartitionServer``,
    ``runtime/broker._BrokerFeed``) adapt their partition plumbing to:

    - ``partition_id`` — the segment tag.
    - ``backlog()`` — committed-but-unconsumed record count (packing and
      admission hints; never negative).
    - ``take(limit)`` — CONSUME up to ``limit`` committed records at the
      cursor and advance it. Returns a sequence (list or ``RecordsView``);
      empty when nothing is available (also used for parking: a feed
      waiting on a workflow fetch returns nothing until unparked).
    - ``dispatch(records)`` — hand one wave segment to the engine.
      Returns ``(pending, host_seconds, device_seconds)``: ``pending`` is
      an opaque in-flight wave to pass to ``collect`` later (device
      pipeline), or None when the segment was processed AND applied
      inline (synchronous engines).
    - ``collect(pending)`` — materialize + apply one dispatched segment;
      returns ``(host_seconds, device_seconds)``.
    - ``rewind(position)`` — undo ``take``: reset the cursor to
      ``position`` (called when a dispatch raised before consuming the
      segment, so the records re-drain instead of being lost).
    - ``tick()`` — deadline/TTL sweep entry (probe + command append);
      optional.
    - ``device_index`` — the mesh device this partition's engine is placed
      on (scheduler/placement.DevicePlan index), -1 when unplaced. Used
      only for the per-device wave metrics; dispatch itself is routed by
      the ENGINE's committed state placement.
    - ``device_indices`` — EVERY plan index the partition occupies: the
      span of a mesh-sharded-state engine (its wave computes on all of
      them at once), else just ``[device_index]``. Feeds may leave it
      empty; the scheduler falls back to ``device_index``.
    """

    partition_id: int = -1
    device_index: int = -1
    device_indices: tuple = ()

    def backlog(self) -> int:  # pragma: no cover - interface default
        return 0

    def take(self, limit: int):  # pragma: no cover - interface default
        return []

    def dispatch(self, records):  # pragma: no cover - interface default
        raise NotImplementedError

    def collect(self, pending):  # pragma: no cover - interface default
        raise NotImplementedError

    def rewind(self, position: int) -> None:  # pragma: no cover - default
        pass

    def tick(self) -> None:  # pragma: no cover - interface default
        pass


def _first_position(records) -> int:
    """First log position of a taken span (list of Records or a columnar
    view) — the rewind target when a dispatch fails."""
    positions = getattr(records, "positions", None)
    if positions is not None:
        col = positions()
        return col[0] if col else -1
    first = records[0]
    # plain ints serve as positions in scheduler-core harness feeds
    return getattr(first, "position", first)


class WaveSegment:
    """One partition's contiguous slice of a shared wave."""

    __slots__ = ("feed", "records", "pending", "count", "trace",
                 "shard_fill")

    def __init__(self, feed: PartitionFeed, records):
        self.feed = feed
        self.records = records
        self.count = len(records)
        self.pending = None  # dispatched-but-uncollected engine wave
        self.trace = None  # wave-timeline segment entry (tracing on)
        self.shard_fill = None  # per-shard staged rows, stamped at dispatch


class SharedWave:
    """A wave packed from several partitions' committed tails."""

    __slots__ = ("segments", "total", "host_seconds", "device_seconds",
                 "dispatched", "trace")

    def __init__(self):
        self.segments: List[WaveSegment] = []
        self.total = 0
        self.host_seconds = 0.0
        self.device_seconds = 0.0
        self.dispatched = False
        self.trace = None  # wave-timeline event (tracing on)


class _FeedState:
    __slots__ = ("feed", "deficit", "inflight")

    def __init__(self, feed: PartitionFeed):
        self.feed = feed
        self.deficit = 0
        self.inflight = 0  # records dispatched but not collected/applied


class WaveScheduler:
    """Shared-wave scheduler over registered partition feeds."""

    def __init__(
        self,
        wave_size: int = 512,
        quantum: Optional[int] = None,
        backpressure_limit: Optional[int] = None,
        slow_wave_ms: Optional[int] = None,
    ):
        # slow-wave watchdog threshold: the [tracing] slowWaveMs knob,
        # honored even with spans disabled (the watchdog is sampling-
        # independent); None falls back to the tracer's value, then 5s
        self.slow_wave_ms = slow_wave_ms
        self.wave_size = max(1, wave_size)
        # DRR quantum: fairness granularity. Small enough that several
        # active partitions share one wave, large enough that a lone
        # partition fills the wave in a few rounds.
        self.quantum = quantum if quantum and quantum > 0 else max(
            1, self.wave_size // 8
        )
        # per-partition cap on dispatched-but-unapplied records (the
        # double-buffer depth in records); at the cap the feed is skipped
        self.backpressure_limit = (
            backpressure_limit if backpressure_limit and backpressure_limit > 0
            else 4 * self.wave_size
        )
        self._feeds: Dict[int, _FeedState] = {}
        self._order: List[int] = []  # sorted pids (deterministic packing)
        self._rr = 0  # rotating start index into _order
        # slow-wave watchdog: warn once per stall episode (every slow
        # wave still counts + flight-records; a fast wave re-arms)
        self._slow_wave_warned = False
        from zeebe_tpu.tracing.recorder import RateLimitedEvent

        self._backpressure_event = RateLimitedEvent(
            "scheduler", "backpressure skip"
        )

    # -- registration ------------------------------------------------------
    def register(self, feed: PartitionFeed) -> None:
        self._feeds[feed.partition_id] = _FeedState(feed)
        self._order = sorted(self._feeds)

    def unregister(self, partition_id: int) -> None:
        self._feeds.pop(partition_id, None)
        self._order = sorted(self._feeds)
        if self._order:
            self._rr %= len(self._order)
        else:
            self._rr = 0

    def feeds(self) -> List[PartitionFeed]:
        return [self._feeds[pid].feed for pid in self._order]

    def backlog(self) -> int:
        """Total committed-but-unconsumed records across feeds (the
        gateway admission queue-depth probe)."""
        total = 0
        for state in self._feeds.values():
            total += max(0, state.feed.backlog()) + state.inflight
        return total

    # -- packing (deficit round-robin) -------------------------------------
    def _pack(self) -> Optional[SharedWave]:
        order = self._order
        if not order:
            return None
        wave = SharedWave()
        room = self.wave_size
        start = self._rr
        rotated = order[start:] + order[:start]
        self._rr = (start + 1) % len(order)
        by_feed: Dict[int, WaveSegment] = {}
        # cycle DRR rounds until the wave is full or a whole round adds
        # nothing (every feed empty, parked, or backpressured)
        while room > 0:
            added = False
            for pid in rotated:
                if room <= 0:
                    break
                state = self._feeds.get(pid)
                if state is None:  # unregistered mid-drain (step-down)
                    continue
                state.deficit += self.quantum
                seg = by_feed.get(pid)
                # records already packed into THIS wave count against the
                # in-flight cap too: they dispatch together, so a feed
                # revisited across DRR rounds must not assemble a segment
                # larger than its configured apply-side bound
                packed = seg.count if seg is not None else 0
                budget = min(
                    state.deficit,
                    room,
                    self.backpressure_limit - state.inflight - packed,
                )
                if budget <= 0:
                    if state.feed.backlog() > 0:
                        count_event(
                            "scheduler_backpressure_skips",
                            "Feed visits skipped because the partition hit "
                            "its in-flight backpressure limit",
                        )
                        # skips repeat every DRR round while a partition
                        # is wedged — rate-limited like admission sheds,
                        # or the burst would wrap the flight ring
                        self._backpressure_event.record(
                            partition=pid, inflight=state.inflight,
                            backlog=state.feed.backlog(),
                        )
                    state.deficit = min(state.deficit, self.quantum)
                    continue
                records = state.feed.take(budget)
                taken = len(records)
                if not taken:
                    state.deficit = 0  # empty queue: DRR resets the credit
                    continue
                state.deficit -= taken
                room -= taken
                added = True
                seg = by_feed.get(pid)
                if seg is None:
                    seg = WaveSegment(state.feed, records)
                    by_feed[pid] = seg
                    wave.segments.append(seg)
                else:
                    # a feed revisited within one wave extends its single
                    # contiguous segment (cursor order is preserved)
                    seg.records = _concat(seg.records, records)
                    seg.count += taken
            if not added:
                break
        if not wave.segments:
            return None
        wave.total = sum(seg.count for seg in wave.segments)
        return wave

    # -- dispatch / collect ------------------------------------------------
    def _dispatch(self, wave: SharedWave) -> None:
        wave.dispatched = True
        tracer = tracing.TRACER
        if tracer is not None:
            waves = tracer.waves
            wave_id = next(waves.seq)
            if wave_id % waves.stride == 0:
                wave.trace = waves.begin(wave_id, self.wave_size)
        for i, seg in enumerate(wave.segments):
            state = self._feeds.get(seg.feed.partition_id)
            pid = seg.feed.partition_id
            device = getattr(seg.feed, "device_index", -1)
            if tracer is not None:
                if wave.trace is not None:  # this wave's timeline sampled
                    seg.trace = tracer.waves.segment(
                        wave.trace, pid, device, seg.count
                    )
                if tracer.by_position:
                    tracer.stamp_positions(
                        pid, tracing.positions_of(seg.records),
                        tracing.WAVE_DISPATCH, device=device,
                    )
            try:
                pending, host_s, device_s = seg.feed.dispatch(seg.records)
            except Exception:
                # this segment's records were consumed but never entered
                # the engine: rewind its cursor (and every not-yet-
                # dispatched segment's) so they re-drain — then surface
                # the failure like the per-partition drain would
                count_event(
                    "scheduler_dispatch_rewinds",
                    "Wave segments rewound because their dispatch raised",
                )
                record_event(
                    "scheduler", "dispatch raised; segments rewound",
                    partition=pid, segment_records=seg.count,
                )
                for later in wave.segments[i:]:
                    if later.pending is None and later.count:
                        try:
                            later.feed.rewind(_first_position(later.records))
                        except Exception:  # noqa: BLE001 - best effort
                            logger.exception(
                                "scheduler: rewind failed on partition %d",
                                later.feed.partition_id,
                            )
                    later.count = 0
                wave.total = sum(s.count for s in wave.segments)
                raise
            seg.pending = pending
            # snapshot the engine's per-shard fill NOW: the attribute is
            # mutable "last dispatched" state, and by collect time a later
            # segment's dispatch has overwritten it
            seg.shard_fill = getattr(seg.feed, "shard_fill", None)
            wave.host_seconds += host_s
            wave.device_seconds += device_s
            if pending is None:
                # synchronous engine: the segment processed+applied inline,
                # so its per-device accounting lands here (pipelined
                # segments report at collect, when their times are known)
                observe_device_wave(
                    getattr(seg.feed, "device_index", -1), seg.count,
                    wave.total, host_s, device_s,
                )
                if seg.trace is not None:
                    tracer.waves.segment_collected(
                        seg.trace, host_s, device_s
                    )
            if pending is not None and state is not None:
                state.inflight += seg.count

    def _collect(self, wave: SharedWave) -> None:
        """Materialize a dispatched shared wave's segments (apply appends/
        responses/sends/pushes per partition) and observe its metrics."""
        error = None
        tracer = tracing.TRACER
        for seg in wave.segments:
            if seg.pending is None:
                continue
            pending, seg.pending = seg.pending, None
            state = self._feeds.get(seg.feed.partition_id)
            try:
                host_s, device_s = seg.feed.collect(pending)
                wave.host_seconds += host_s
                wave.device_seconds += device_s
                observe_device_wave(
                    getattr(seg.feed, "device_index", -1), seg.count,
                    wave.total, host_s, device_s,
                )
                if tracer is not None and seg.trace is not None:
                    # DEVICE_COLLECT is stamped inside feed.collect()
                    # between device collect and apply, so stage order
                    # matches the baseline drain
                    tracer.waves.segment_collected(
                        seg.trace, host_s, device_s
                    )
            except Exception as e:  # noqa: BLE001 - one partition's
                # collect failure must not strand the other segments'
                # responses; re-raised after the loop
                error = e
            finally:
                if state is not None:
                    state.inflight = max(0, state.inflight - seg.count)
        if tracer is not None and wave.trace is not None:
            tracer.waves.end(wave.trace)
        self._check_slow_wave(wave)
        observe_shared_wave(
            wave.total, self.wave_size, len(wave.segments),
            wave.host_seconds, wave.device_seconds,
        )
        devices = set()
        for seg in wave.segments:
            if not seg.count:
                continue
            span = getattr(seg.feed, "device_indices", None)
            if span:
                # a sharded-state segment computes on its WHOLE span
                devices.update(span)
                # per-shard fill accounting (sharded-state v2): what each
                # plan device actually staged for this segment — under
                # resident routing a routed wave fills ONE lane, and this
                # is where that concentration becomes visible per device
                # (the fill was snapshotted at THIS segment's dispatch)
                if seg.shard_fill:
                    observe_shard_fill(span, seg.shard_fill)
            else:
                devices.add(getattr(seg.feed, "device_index", -1))
        devices.discard(-1)
        if devices:
            # >1 here means this wave's compute overlapped across the mesh
            observe_mesh_wave(len(devices))
        if error is not None:
            raise error

    def _check_slow_wave(self, wave: SharedWave) -> None:
        """Slow-wave watchdog: a wave whose host+device time exceeds the
        threshold is counted + flight-recorded, and the FIRST one of an
        episode logs the recorder slice (the next fast wave re-arms the
        warning). The threshold is the scheduler's own slowWaveMs when
        configured (honored even with [tracing] enabled=false), else the
        tracer's; with neither the watchdog defaults to 5s."""
        threshold_ms = self.slow_wave_ms
        if threshold_ms is None:
            tracer = tracing.TRACER
            threshold_ms = tracer.slow_wave_ms if tracer is not None else 5000
        threshold_s = threshold_ms / 1000.0
        duration = wave.host_seconds + wave.device_seconds
        if duration <= threshold_s:
            self._slow_wave_warned = False
            return
        count_event(
            "serving_slow_waves",
            "Waves whose host+device time exceeded the slow-wave "
            "watchdog threshold",
        )
        record_event(
            "stall", "slow wave", records=wave.total,
            segments=len(wave.segments),
            host_s=round(wave.host_seconds, 4),
            device_s=round(wave.device_seconds, 4),
        )
        if not self._slow_wave_warned:
            self._slow_wave_warned = True
            logger.warning(
                "slow wave: %d records across %d segments took %.2fs "
                "(host %.2fs / device %.2fs, threshold %.1fs); recent "
                "flight-recorder events:\n%s",
                wave.total, len(wave.segments), duration,
                wave.host_seconds, wave.device_seconds, threshold_s,
                FLIGHT.format_slice(last=25),
            )

    def drain(self, max_records: Optional[int] = None) -> int:
        """Pack + dispatch shared waves until every feed runs dry, double-
        buffering: wave N+1 dispatches (host staging overlaps device
        compute of wave N) before wave N collects. Returns records
        drained. The ``finally`` collects every in-flight wave even when a
        dispatch or collect raises — dispatched records are consumed into
        engine state and their responses must land."""
        total = 0
        inflight: List[SharedWave] = []
        try:
            while True:
                wave = self._pack()
                if wave is None:
                    if inflight:
                        # every feed empty OR backpressured: collecting
                        # the oldest in-flight wave frees its in-flight
                        # budget (and may commit follow-ups) — then retry
                        self._collect(inflight.pop(0))
                        continue
                    break
                inflight.append(wave)
                try:
                    self._dispatch(wave)
                finally:
                    total += wave.total
                while len(inflight) > 1:
                    self._collect(inflight.pop(0))
                if max_records is not None and total >= max_records:
                    break
        finally:
            while inflight:
                self._collect(inflight.pop(0))
        return total

    # -- time-driven sweeps -------------------------------------------------
    def tick(self) -> None:
        """Deadline-probe sweeps for every registered feed: the resulting
        commands append through each feed's own partition and re-enter the
        shared waves as committed records."""
        for pid in list(self._order):
            state = self._feeds.get(pid)
            if state is not None:
                state.feed.tick()


def _concat(a, b):  # noqa: D401
    """Concatenate two taken spans preserving laziness (RecordsView
    entries stay lazy; plain lists concatenate)."""
    from zeebe_tpu.protocol.columnar import RecordsView

    if isinstance(a, RecordsView) or isinstance(b, RecordsView):
        ea = a._entries if isinstance(a, RecordsView) else list(a)
        eb = b._entries if isinstance(b, RecordsView) else list(b)
        return RecordsView(ea + eb)
    return list(a) + list(b)
