"""Partition→device placement over the accelerator mesh + frame exchange.

The serving plane historically ran every leader partition's engine on the
default device: 8 healthy chips (MULTICHIP_r05) and one of them doing all
the work. :class:`DevicePlan` is the missing map — it assigns each LEADER
partition a device (least-loaded with round-robin tie-break, which
degenerates to plain round-robin for sequential installs), rebalances on
leadership change (a step-down releases the slot; the next install lands
on the emptiest device), and survives device loss (``exclude`` moves the
dead device's partitions onto the remaining healthy ones — the caller
migrates live engine state via ``TpuPartitionEngine.place_on``).

With the plan in place the PR-8 ``WaveScheduler`` drain needs no new
mechanics to go wide: it already dispatches every partition's wave
segment (async, no device sync) before collecting the previous wave, so
segments landing on DIFFERENT devices compute concurrently across the
whole mesh within one scheduling round.

:class:`MeshExchange` is the cross-partition data plane of the meshed
serving plane: instead of the host subscription-transport hop, the
message-correlation command frames of one scheduling round ride the
device mesh through the same ``all_to_all`` exchange-slot machinery
``build_sharded_step`` uses (``tpu/shard.build_frame_exchange``). The
slots carry the ENCODED WIRE FRAMES — exactly the bytes the transport
would carry — so the record appended at the destination partition is
bit-identical to the unmeshed path by construction.
"""

from __future__ import annotations

import logging
import threading
from typing import Callable, Dict, List, Optional

from zeebe_tpu.runtime.metrics import GLOBAL_REGISTRY, count_event

logger = logging.getLogger(__name__)


class DevicePlan:
    """Leader-partition → device placement over the visible mesh."""

    def __init__(self, devices=None, max_devices: int = 0):
        if devices is None:
            import jax

            devices = jax.devices()
        devices = list(devices)
        if max_devices > 0:
            devices = devices[:max_devices]
        if not devices:
            raise ValueError("DevicePlan needs at least one device")
        self.devices = devices
        self._lock = threading.Lock()
        self._assigned: Dict[int, int] = {}  # partition id → device index
        # sharded-state partitions occupy a SPAN of devices (their tables
        # block-shard over the span's mesh axis); the primary index also
        # lives in _assigned so single-device queries keep working
        self._spans: Dict[int, List[int]] = {}
        self._excluded: set = set()
        self._rr = 0  # round-robin tie-break cursor
        self._device_gauges: Dict[int, object] = {}  # cached metric handles

    # -- queries -----------------------------------------------------------
    def healthy_indices(self) -> List[int]:
        with self._lock:
            return [
                i for i in range(len(self.devices)) if i not in self._excluded
            ]

    def device_index(self, partition_id: int) -> int:
        with self._lock:
            return self._assigned.get(partition_id, -1)

    def device_for(self, partition_id: int):
        idx = self.device_index(partition_id)
        return self.devices[idx] if idx >= 0 else None

    def assignments(self) -> Dict[int, int]:
        with self._lock:
            return dict(self._assigned)

    def device_indices(self, partition_id: int) -> List[int]:
        """Every device index a partition occupies: its span when sharded,
        the single assignment otherwise, [] when unplaced."""
        with self._lock:
            sp = self._spans.get(partition_id)
            if sp is not None:
                return list(sp)
            idx = self._assigned.get(partition_id, -1)
            return [idx] if idx >= 0 else []

    def devices_for(self, partition_id: int) -> List:
        return [self.devices[i] for i in self.device_indices(partition_id)]

    def load(self) -> Dict[int, int]:
        """Partitions per device index (all devices, excluded included).
        A sharded partition counts on EVERY device of its span."""
        with self._lock:
            return self._load_locked(range(len(self.devices)))

    def _load_locked(self, indices) -> Dict[int, int]:
        counts = {i: 0 for i in indices}
        for pid, idx in self._assigned.items():
            sp = self._spans.get(pid)
            for i in (sp if sp is not None else (idx,)):
                if i in counts:
                    counts[i] += 1
        return counts

    # -- placement ---------------------------------------------------------
    def assign(self, partition_id: int) -> int:
        """Place a partition (sticky: re-assigning a placed partition keeps
        its device). Least-loaded healthy device wins; ties resolve
        round-robin so sequential leadership installs spread like a plain
        round-robin over the mesh. Returns the device index."""
        with self._lock:
            idx = self._assigned.get(partition_id)
            if idx is not None and idx not in self._excluded:
                return idx
            idx = self._pick_locked()
            self._assigned[partition_id] = idx
        count_event(
            "mesh_partition_assigns",
            "Leader partitions placed onto a mesh device",
        )
        self._publish_load()
        return idx

    def assign_span(self, partition_id: int, span: int) -> List[int]:
        """Place a SHARDED-state partition across ``span`` devices — the
        mesh span its tables block-shard over (engine ``state_shards``).
        Sticky like :meth:`assign`; picks the least-loaded healthy
        devices (index tie-break) and returns their indices in mesh
        order. The first is the primary that ``device_index`` reports."""
        if span <= 1:
            return [self.assign(partition_id)]
        with self._lock:
            got = self._spans.get(partition_id)
            if got is not None and not (set(got) & self._excluded):
                return list(got)
            chosen = self._pick_span_locked(span)
            self._spans[partition_id] = chosen
            self._assigned[partition_id] = chosen[0]
        count_event(
            "mesh_span_assigns",
            "Sharded-state partitions placed across a mesh device span",
        )
        self._publish_load()
        return list(chosen)

    def _pick_span_locked(self, span: int) -> List[int]:
        healthy = [
            i for i in range(len(self.devices)) if i not in self._excluded
        ]
        if len(healthy) < span:
            raise RuntimeError(
                f"DevicePlan: sharded span {span} exceeds the "
                f"{len(healthy)} healthy devices"
            )
        counts = self._load_locked(healthy)
        return sorted(sorted(healthy, key=lambda i: (counts[i], i))[:span])

    def _pick_locked(self) -> int:
        healthy = [
            i for i in range(len(self.devices)) if i not in self._excluded
        ]
        if not healthy:
            raise RuntimeError("DevicePlan: every device is excluded")
        counts = self._load_locked(healthy)
        low = min(counts.values())
        # rotate the tie-break start so equal-load devices fill in order
        n = len(healthy)
        for k in range(n):
            cand = healthy[(self._rr + k) % n]
            if counts[cand] == low:
                self._rr = (healthy.index(cand) + 1) % n
                return cand
        return healthy[0]  # unreachable

    def release(self, partition_id: int) -> None:
        """Leadership left this partition: free its slot so the next
        install (here or elsewhere) rebalances onto the emptiest device."""
        with self._lock:
            removed = self._assigned.pop(partition_id, None)
            self._spans.pop(partition_id, None)
        if removed is not None:
            count_event(
                "mesh_partition_releases",
                "Leader partitions released from their mesh device "
                "(step-down / close)",
            )
            self._publish_load()

    # -- device health -----------------------------------------------------
    def exclude(self, device_index: int) -> Dict[int, int]:
        """Mark a device dead/excluded and move its partitions onto the
        remaining healthy devices. Returns {partition_id: new device index}
        for the caller to migrate live engine state (``place_on``)."""
        moves: Dict[int, int] = {}
        with self._lock:
            self._excluded.add(device_index)
            victims = [
                pid for pid, idx in self._assigned.items()
                if idx == device_index
                or device_index in self._spans.get(pid, ())
            ]
            spans = {
                pid: len(self._spans[pid])
                for pid in victims if pid in self._spans
            }
            for pid in victims:
                del self._assigned[pid]
                self._spans.pop(pid, None)
            for pid in victims:
                if pid in spans:
                    # a sharded partition re-spans over the survivors; the
                    # caller rebuilds its engine on the new span (the
                    # sharded engine is pinned — no live place_on)
                    chosen = self._pick_span_locked(spans[pid])
                    self._spans[pid] = chosen
                    self._assigned[pid] = chosen[0]
                    moves[pid] = chosen[0]
                else:
                    moves[pid] = self._pick_locked()
                    self._assigned[pid] = moves[pid]
        if moves:
            count_event(
                "mesh_rebalance_moves",
                "Partitions moved to another device by a rebalance "
                "(device exclusion)",
                delta=len(moves),
            )
        self._publish_load()
        return moves

    def readmit(self, device_index: int) -> None:
        with self._lock:
            self._excluded.discard(device_index)
        self._publish_load()

    def _publish_load(self) -> None:
        load = self.load()
        for idx, n in load.items():
            handle = self._device_gauges.get(idx)
            if handle is None:
                handle = GLOBAL_REGISTRY.gauge(
                    "mesh_device_partitions",
                    "Leader partitions currently placed on each mesh device",
                    device=str(idx),
                )
                self._device_gauges[idx] = handle
            handle.set(n)
        GLOBAL_REGISTRY.gauge(
            "mesh_devices_healthy",
            "Mesh devices currently accepting partition placements",
        ).set(len(self.devices) - len(self._excluded))


class MeshExchange:
    """Cross-partition command frames over the mesh's ``all_to_all``.

    ``queue`` buffers one encoded record frame addressed from a source
    device to a destination device (and destination PARTITION — several
    partitions may share a device); ``flush`` runs ONE collective exchange
    for everything queued and hands each arrival to the caller's deliver
    callback in deterministic order (destination device → source device →
    slot, which per (src, dst) pair preserves queue order).

    Frames larger than ``frame_bytes`` or beyond the ``slots`` budget of
    their (src, dst) pair are REFUSED (``queue`` returns False) and the
    caller falls back to the host transport hop — counted, never dropped.
    """

    def __init__(self, devices, slots: int = 32, frame_bytes: int = 1024):
        import numpy as np  # noqa: F401 - verified importable at build

        from jax.sharding import Mesh

        from zeebe_tpu.tpu import shard

        self.devices = list(devices)
        if len(self.devices) < 2:
            raise ValueError("MeshExchange needs at least two devices")
        self.slots = int(slots)
        self.frame_bytes = int(frame_bytes)
        import numpy as _np

        mesh = Mesh(_np.asarray(self.devices), ("exchange",))
        self._step = shard.build_frame_exchange(
            mesh, self.slots, self.frame_bytes
        )
        self._n = len(self.devices)
        # queued[src][dst] = list of (dst_pid, frame)
        self._queued: Dict[int, Dict[int, List]] = {}
        self._count = 0
        # fallbacks can burst at per-frame rate under sustained slot
        # overflow — rate-limit the flight-ring entries so an overloaded
        # mesh cannot evict the control-plane history (the
        # mesh_exchange_fallbacks counter stays exact)
        from zeebe_tpu.tracing.recorder import RateLimitedEvent

        self._fallback_event = RateLimitedEvent(
            "mesh", "frames fell back to transport"
        )

    def pending(self) -> int:
        return self._count

    def queue(
        self, src_device: int, dst_device: int, dst_partition: int,
        frame: bytes,
    ) -> bool:
        if not (0 <= src_device < self._n and 0 <= dst_device < self._n):
            return False
        if len(frame) > self.frame_bytes:
            count_event(
                "mesh_exchange_fallbacks",
                "Cross-partition frames routed over the host transport "
                "because they did not fit the mesh exchange slots",
            )
            self._fallback_event.record(
                why="oversize", src=src_device, dst=dst_device,
                bytes=len(frame),
            )
            return False
        per_dst = self._queued.setdefault(src_device, {})
        block = per_dst.setdefault(dst_device, [])
        if len(block) >= self.slots:
            count_event(
                "mesh_exchange_fallbacks",
                "Cross-partition frames routed over the host transport "
                "because they did not fit the mesh exchange slots",
            )
            self._fallback_event.record(
                why="pair slots full", src=src_device, dst=dst_device,
                slots=self.slots,
            )
            return False
        block.append((dst_partition, frame))
        self._count += 1
        return True

    def flush(self, deliver: Callable[[int, bytes], None]) -> int:
        """Exchange everything queued; ``deliver(dst_partition, frame)``
        per arrival. Returns the number of frames delivered. The mesh hop
        is an OPTIMIZATION, never a durability boundary: the frames also
        sit in host memory, so a failing collective delivers them
        directly (counted) instead of dropping the round's commands — a
        lost subscription OPEN would wedge its instance forever, which
        the transport path this replaces never does."""
        import numpy as np

        if not self._count:
            return 0
        n, s, b = self._n, self.slots, self.frame_bytes
        buf = np.zeros((n, n, s, b), np.uint8)
        lens = np.full((n, n, s), -1, np.int32)
        pids = np.full((n, n, s), -1, np.int32)
        for src, per_dst in self._queued.items():
            for dst, block in per_dst.items():
                for slot, (pid, frame) in enumerate(block):
                    buf[src, dst, slot, : len(frame)] = np.frombuffer(
                        frame, np.uint8
                    )
                    lens[src, dst, slot] = len(frame)
                    pids[src, dst, slot] = pid
        queued, snapshot = self._count, self._queued
        self._queued = {}
        self._count = 0

        def safe_deliver(pid: int, frame: bytes) -> bool:
            try:
                deliver(pid, frame)
                return True
            except Exception:  # noqa: BLE001 - one bad frame must not
                # strand the rest of the round's arrivals
                count_event(
                    "mesh_exchange_flush_failures",
                    "Mesh exchange frame deliveries that raised",
                )
                logger.exception(
                    "mesh exchange delivery failed for partition %d", pid
                )
                return False

        try:
            out_buf, out_lens, out_pids = self._step(buf, lens, pids)
            out_buf = np.asarray(out_buf)
            out_lens = np.asarray(out_lens)
            out_pids = np.asarray(out_pids)
        except Exception:  # noqa: BLE001 - collective failed: fall back
            # to direct host delivery of the snapshot (per-pair order
            # preserved)
            count_event(
                "mesh_exchange_flush_failures",
                "Mesh exchange frame deliveries that raised",
            )
            logger.exception(
                "mesh exchange collective failed; delivering %d frames "
                "directly", queued,
            )
            delivered = 0
            for src in sorted(snapshot):
                for dst in sorted(snapshot[src]):
                    for pid, frame in snapshot[src][dst]:
                        if safe_deliver(pid, frame):
                            delivered += 1
            return delivered
        delivered = 0
        # arrivals per destination device, ordered by source device then
        # slot (all_to_all preserves slot order per pair)
        for dst in range(n):
            for src in range(n):
                for slot in range(s):
                    length = int(out_lens[dst, src, slot])
                    if length < 0:
                        continue
                    if safe_deliver(
                        int(out_pids[dst, src, slot]),
                        out_buf[dst, src, slot, :length].tobytes(),
                    ):
                        delivered += 1
        if delivered:
            count_event(
                "mesh_exchange_frames",
                "Cross-partition command frames delivered over the mesh "
                "all_to_all exchange (instead of the host transport hop)",
                delta=delivered,
            )
        if delivered != queued:  # pragma: no cover - exchange invariant
            logger.error(
                "mesh exchange delivered %d of %d queued frames",
                delivered, queued,
            )
        return delivered
