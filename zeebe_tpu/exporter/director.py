"""ExporterDirector: per-partition committed-stream fan-out to exporters.

Reference parity: ``broker-core/.../exporter/ExporterDirector`` (one
director per partition tails the committed log, dispatches to every
configured exporter, persists per-exporter positions, and bounds segment
deletion by their minimum). Differences here:

- **Batched dispatch** (``export_batch``) instead of per-record calls —
  the same batch-first shape as the device engine's drain loop.
- **Replicated positions**: acks are EXPORTER ACKNOWLEDGE records appended
  to the partition's own log (raft-replicated on clusters), folded into
  engine state by the interpreter, snapshotted with it, and recovered by
  the same snapshot+replay path as everything else. A new leader's
  director reads ``engine.exporter_positions`` and resumes without gaps.
- **Failure isolation**: each exporter has its own cursor, retry backoff
  and stall tracking; one failing exporter never blocks the others (it
  pins the compaction floor and fires a stall warning instead).

The director core is threading-agnostic (``pump()`` is a plain method);
the in-process ``Broker`` pumps it inside ``run_until_idle`` while the
cluster broker drives it from an actor (``ExporterDirectorActor``) hooked
to the log's commit signal.
"""

from __future__ import annotations

import logging
import time
from typing import Callable, Dict, List, Optional, Tuple

from zeebe_tpu.exporter.base import Exporter, ExporterContext, ExporterController
from zeebe_tpu.protocol.enums import RecordType, ValueType
from zeebe_tpu.protocol.intents import ExporterIntent
from zeebe_tpu.protocol.metadata import RecordMetadata
from zeebe_tpu.protocol.records import ExporterPositionRecord, Record
from zeebe_tpu.runtime.actors import Actor
from zeebe_tpu.runtime.metrics import GLOBAL_REGISTRY, count_event
from zeebe_tpu import tracing

logger = logging.getLogger(__name__)

# records exporters never see: the exporter plane's own ack traffic (a
# dispatched ack would ack itself forever); positions still advance past
# them
_HIDDEN_VALUE_TYPES = {int(ValueType.EXPORTER)}


def fold_tail_acks(positions: Dict[str, int], log, from_position: int) -> Dict[str, int]:
    """Recovered ``engine.exporter_positions`` + EXPORTER acks in the log
    tail the replay boundary has not folded in yet (acks produce no
    follow-ups, so they never extend the boundary; without this scan a
    restart re-opens at the last SNAPSHOTTED ack and re-exports the whole
    tail). The scan deliberately covers the WHOLE local tail, not just the
    committed prefix: right after a restart the leadership install can run
    before raft re-advances the commit position over the recovered log,
    and stopping there resumes from a stale snapshot ack (duplicate
    burst). Trusting a not-yet-recommitted ack is safe — its VALUE only
    attests records that were already committed and exported when the ack
    was written, so no gap can result even if raft later truncates the ack
    record itself (the next real ack re-persists a higher position)."""
    out = dict(positions)
    try:
        reader = log.reader(max(0, from_position))
    except Exception:  # noqa: BLE001 - scan is best-effort (at-least-once)
        return out
    for record in reader:
        md = record.metadata
        if (
            int(md.value_type) != int(ValueType.EXPORTER)
            or int(md.record_type) != int(RecordType.COMMAND)
            or record.value is None
            or not record.value.exporter_id
        ):
            continue
        if int(md.intent) == int(ExporterIntent.ACKNOWLEDGE):
            prior = out.get(record.value.exporter_id)
            if prior is None or record.value.position > prior:
                out[record.value.exporter_id] = record.value.position
        elif int(md.intent) == int(ExporterIntent.REMOVE):
            out.pop(record.value.exporter_id, None)
    return out


def ack_record(
    exporter_id: str, position: int,
    intent: ExporterIntent = ExporterIntent.ACKNOWLEDGE,
) -> Record:
    return Record(
        metadata=RecordMetadata(
            record_type=RecordType.COMMAND,
            value_type=ValueType.EXPORTER,
            intent=int(intent),
        ),
        value=ExporterPositionRecord(
            exporter_id=exporter_id, position=position
        ),
    )


def remove_stale_positions(
    positions: Dict[str, int], configured,
) -> List[Record]:
    """REMOVE records for recovered exporter ids no longer in the
    configured set — deconfiguring an exporter must actually release its
    compaction pin, INCLUDING when the last exporter was removed (the
    brokers call this with an empty ``configured`` set when no director
    is installed at all)."""
    return [
        ack_record(stale_id, -1, ExporterIntent.REMOVE)
        for stale_id in sorted(set(positions) - set(configured))
    ]


class ExporterHandle:
    """One exporter's dispatch state inside a director."""

    def __init__(self, exporter_id: str, exporter: Exporter, position: int):
        self.id = exporter_id
        self.exporter = exporter
        # last durably acked position (mirrors engine.exporter_positions)
        self.position = position
        # like .position but advanced only when the ack's append COMMITS
        # (_append_acks on_durable): tracing's EXPORT_ACK keys off this,
        # never off the optimistic in-flight value
        self.durable_position = position
        # next read position; >= position+1 (runs ahead over hidden/admin
        # records and, for MANUAL_ACK exporters, over delivered batches)
        self.cursor = position + 1
        self.failures = 0
        self.retry_at_ms = 0
        self.last_advance_ms: Optional[int] = None
        self.stall_warned = False
        self.broken: Optional[str] = None  # open/configure failed: reason
        # MANUAL_ACK exporters confirm through the controller
        self.manual_position = position
        self.controller: Optional[ExporterController] = None
        # registry handles resolved once (the pump is the hot loop — no
        # global-registry lock round-trip per batch)
        self.exported_counter = None
        self.failure_counter = None


class ExporterDirector:
    """Tails one partition's committed records into N exporters."""

    BATCH_SIZE = 512
    INITIAL_BACKOFF_MS = 100
    MAX_BACKOFF_MS = 10_000
    # a floor-pinning exporter that has not advanced for this long fires
    # the stall warning (once per stall episode)
    STALL_AFTER_MS = 10_000

    def __init__(
        self,
        partition_id: int,
        log,
        exporters: List[Tuple[str, Exporter]],
        append_fn: Callable[[List[Record]], object],
        clock: Optional[Callable[[], int]] = None,
        node_label: str = "",
    ):
        self.partition_id = partition_id
        self.log = log
        self.append_fn = append_fn
        self.clock = clock or (lambda: int(time.time() * 1000))
        self.node_label = node_label
        self.handles: List[ExporterHandle] = []
        self._exporters = list(exporters)
        self._scheduled: List[Tuple[int, Callable[[], None]]] = []
        self.closed = False
        self._lag_gauges: Dict[str, object] = {}
        # last visible committed position, cached per commit position (the
        # backwards scan only walks the trailing run of hidden ack records)
        self._lv_cache = -1
        self._lv_cache_commit = -1

    def can_ack(self) -> bool:
        """Whether ANY exporter can still advance an ack. A handle whose
        open/configure raised is broken for the life of the director —
        when every handle is, no ack will ever arrive, and tracing must
        treat the response/apply as a span's final stage (an unfinishable
        span keeps every per-record stamp path hot forever)."""
        return any(h.broken is None for h in self.handles)

    def dispatch_passed(self, position: int) -> bool:
        """Every live exporter's read cursor is already beyond
        ``position``: no future dispatch will stamp it, so a span that
        missed its dispatch window (bound after the pump raced past) can
        never be finished by an ack — ``ack_exported`` requires an
        EXPORT_DISPATCH stamp. The caller closes such a span instead of
        leaking it."""
        live = [h for h in self.handles if h.broken is None]
        return bool(live) and all(h.cursor > position for h in live)

    # -- lifecycle ----------------------------------------------------------
    def open(self, positions: Dict[str, int]) -> None:
        """Configure+open every exporter, resuming each at its recovered
        acked position (``engine.exporter_positions``); exporters never
        seen before are REGISTERED with an ack at -1 so the compaction
        floor pins the whole log until their first real ack commits."""
        now = self.clock()
        register: List[Record] = []
        # recovered ids no longer configured: append REMOVE so their stale
        # positions (possibly a -1 registration that never acked) stop
        # pinning the compaction floor — deconfiguring an exporter must
        # actually release its pin
        configured = {exporter_id for exporter_id, _ in self._exporters}
        register.extend(remove_stale_positions(positions, configured))
        for exporter_id, exporter in self._exporters:
            acked = positions.get(exporter_id)
            handle = ExporterHandle(
                exporter_id, exporter, -1 if acked is None else acked
            )
            handle.last_advance_ms = now
            self.handles.append(handle)
            if acked is None:
                register.append(self._ack_record(exporter_id, -1))
            try:
                context = ExporterContext(
                    exporter_id=exporter_id,
                    args=getattr(exporter, "_cfg_args", {}) or {},
                    partition_id=self.partition_id,
                    clock=self.clock,
                )
                exporter.configure(context)
                handle.controller = ExporterController(
                    update_position=lambda pos, h=handle: self._manual_ack(h, pos),
                    schedule=self._schedule,
                    acked_position=handle.position,
                )
                exporter.open(handle.controller)
            except Exception as e:  # noqa: BLE001 - isolation: a broken
                # exporter must not take down the partition; it pins the
                # floor (stall warning) until fixed or deconfigured
                handle.broken = repr(e)
                count_event(
                    "exporter_open_failures",
                    "Exporters whose configure/open raised",
                )
                logger.error(
                    "exporter %r on partition %d failed to open "
                    "(floor stays pinned at its last ack): %r",
                    exporter_id, self.partition_id, e,
                )
        if register:
            self._append_acks(register)
        # the director itself bounds LogStream.compact (second belt next
        # to the engine-state positions, and the only one covering the
        # window before a registration ack commits)
        if hasattr(self.log, "add_floor_provider"):
            self.log.add_floor_provider(self.compaction_floor)

    def close(self) -> None:
        if self.closed:
            return
        self.closed = True
        if hasattr(self.log, "remove_floor_provider"):
            self.log.remove_floor_provider(self.compaction_floor)
        # the lag gauges are process-global: left at their last value an
        # ex-leader's /metrics would report a stuck non-zero lag for a
        # partition it no longer serves (false alerts)
        for gauge in self._lag_gauges.values():
            gauge.set(0)
        for handle in self.handles:
            if handle.broken is not None:
                continue
            try:
                handle.exporter.close()
            except Exception as e:  # noqa: BLE001 - shutdown best effort
                logger.warning(
                    "exporter %r close failed: %r", handle.id, e
                )

    # -- position plumbing --------------------------------------------------
    def _ack_record(
        self, exporter_id: str, position: int,
        intent: ExporterIntent = ExporterIntent.ACKNOWLEDGE,
    ) -> Record:
        return ack_record(exporter_id, position, intent)

    def _append_acks(self, records: List[Record], on_durable=None) -> None:
        try:
            result = self.append_fn(records)
        except Exception as e:  # noqa: BLE001 - a deposed leader's append
            # fails; positions simply stay at the last committed ack and
            # the next leader re-exports from there (at-least-once)
            self._ack_append_failed(e)
            return
        # the cluster path (raft.append) reports failure through the
        # returned ActorFuture, never by raising here — observe it, or a
        # deposed leader's lost ack vanishes silently. The handle keeps
        # its optimistic position either way: the director closes on
        # step-down and the NEXT leader resumes from the replicated
        # (committed) state, so at-least-once is unaffected.
        # ``on_durable`` fires only once the ack actually committed
        # (raft futures resolve at commit) — tracing's EXPORT_ACK must
        # not stamp an ack a new leader is about to truncate
        on_complete = getattr(result, "on_complete", None)
        if on_complete is not None:
            on_complete(lambda f: (
                self._ack_append_failed(f._exception)
                if getattr(f, "_exception", None) is not None
                else (on_durable() if on_durable is not None else None)
            ))
        elif on_durable is not None:  # single-writer: append IS commit
            on_durable()

    def _ack_append_failed(self, exc) -> None:
        count_event(
            "exporter_ack_append_failures",
            "Exporter position acks whose log append failed "
            "(typically a deposed leader; re-export covers the gap)",
        )
        logger.debug(
            "exporter ack append failed on partition %d "
            "(re-export will cover the gap): %r", self.partition_id, exc,
        )

    def _manual_ack(self, handle: ExporterHandle, position: int) -> None:
        if position > handle.manual_position:
            handle.manual_position = position

    def _schedule(self, delay_ms: int, fn: Callable[[], None]) -> None:
        self._scheduled.append((self.clock() + max(0, delay_ms), fn))

    def compaction_floor(self) -> int:
        """First position still needed by some exporter (exclusive bound
        for ``LogStream.compact``): nothing above the minimum acked
        position may be dropped — a restart resumes there."""
        floor = None
        for handle in self.handles:
            pinned = handle.position + 1
            floor = pinned if floor is None else min(floor, pinned)
        return floor if floor is not None else (1 << 62)

    # -- the pump -----------------------------------------------------------
    def pump(self) -> bool:
        """One dispatch round over all exporters. Returns True when any
        exporter made durable progress (ack appended) — the in-process
        broker loops until quiescence on this signal."""
        if self.closed:
            return False
        now = self.clock()
        self._run_scheduled(now)
        progress = False
        for handle in self.handles:
            if handle.broken is not None:
                self._update_lag(handle)
                self._maybe_warn_stall(handle, now)
                continue
            if now < handle.retry_at_ms:
                # still refresh the gauge: lag grows fastest exactly when
                # the exporter is failing, and a frozen pre-failure value
                # underreports the backlog for the whole backoff window
                self._update_lag(handle)
                self._maybe_warn_stall(handle, now)
                continue
            progress = self._pump_one(handle, now) or progress
            self._update_lag(handle)
            self._maybe_warn_stall(handle, now)
        return progress

    def _pump_one(self, handle: ExporterHandle, now: int) -> bool:
        commit = self.log.commit_position
        base = self.log.base_position
        if handle.cursor < base:
            # only possible for an exporter configured AFTER compaction
            # already dropped the early log (the floor protects everything
            # else) — resume at the surviving base, count the skip
            # upper bound, not an exact record count: the compacted range
            # is gone, so the positions the plane's own hidden ack records
            # occupied (which this exporter never would have seen) cannot
            # be subtracted out
            count_event(
                "exporter_skipped_compacted",
                "Log positions an exporter could not see (compacted "
                "before it was configured; includes the plane's own "
                "hidden admin records)",
                delta=base - handle.cursor,
            )
            handle.cursor = base
        progress = False
        view_fn = getattr(self.log, "committed_view", None)
        while handle.cursor <= commit:
            if view_fn is not None:
                # columnar read: ONE lock acquisition for the whole batch,
                # hidden-record filtering over the value-type COLUMN — no
                # row materialization before the sink edge
                batch = view_fn(handle.cursor, self.BATCH_SIZE)
                if not len(batch):
                    break
                vts = batch.value_types()
                pos = handle.cursor + len(batch)
                visible = batch.select([
                    i for i, vt in enumerate(vts)
                    if vt not in _HIDDEN_VALUE_TYPES
                ])
            else:  # plain-log fallback (test doubles without the view API)
                plain: List[Record] = []
                pos = handle.cursor
                while pos <= commit and len(plain) < self.BATCH_SIZE:
                    record = self.log.record_at(pos)
                    if record is None:
                        break
                    plain.append(record)
                    pos += 1
                if not plain:
                    break
                visible = [
                    r for r in plain
                    if int(r.metadata.value_type) not in _HIDDEN_VALUE_TYPES
                ]
            if len(visible):
                try:
                    handle.exporter.export_batch(visible)
                except Exception as e:  # noqa: BLE001 - isolate + backoff
                    handle.failures += 1
                    backoff = min(
                        self.INITIAL_BACKOFF_MS * (2 ** (handle.failures - 1)),
                        self.MAX_BACKOFF_MS,
                    )
                    handle.retry_at_ms = now + backoff
                    if handle.failure_counter is None:
                        handle.failure_counter = GLOBAL_REGISTRY.counter(
                            "exporter_export_failures",
                            "export_batch calls that raised",
                            exporter=handle.id,
                            partition=str(self.partition_id),
                        )
                    handle.failure_counter.inc()
                    logger.warning(
                        "exporter %r partition %d failed at position %d "
                        "(retry in %dms, attempt %d): %r",
                        handle.id, self.partition_id, handle.cursor,
                        backoff, handle.failures, e,
                    )
                    return progress
                handle.failures = 0
                if handle.exported_counter is None:
                    handle.exported_counter = GLOBAL_REGISTRY.counter(
                        "exporter_records_exported",
                        "Records dispatched to exporters",
                        exporter=handle.id,
                        partition=str(self.partition_id),
                    )
                handle.exported_counter.inc(len(visible))
                tracer = tracing.TRACER
                if tracer is not None and tracer.by_position:
                    tracer.stamp_positions(
                        self.partition_id, tracing.positions_of(visible),
                        tracing.EXPORT_DISPATCH, exporter=handle.id,
                    )
            handle.cursor = pos
            ack_to = self._ack_target(handle, visible)
            if ack_to > handle.position:
                handle.position = ack_to
                handle.last_advance_ms = now
                handle.stall_warned = False
                self._append_acks(
                    [self._ack_record(handle.id, ack_to)],
                    on_durable=lambda h=handle, a=ack_to:
                        self._ack_durable(h, a),
                )
                progress = True
        # MANUAL_ACK exporters may confirm between pumps without new
        # committed records arriving
        if handle.exporter.MANUAL_ACK and handle.manual_position > handle.position:
            handle.position = handle.manual_position
            handle.last_advance_ms = now
            handle.stall_warned = False
            self._append_acks(
                [self._ack_record(handle.id, handle.position)],
                on_durable=lambda h=handle, a=handle.position:
                    self._ack_durable(h, a),
            )
            progress = True
        return progress

    def _ack_durable(self, handle: ExporterHandle, position: int) -> None:
        """An ack's append COMMITTED (raft future resolved, or the
        single-writer append that is its own commit): only now may
        tracing treat the position as acked — an optimistic in-flight
        ack could still be truncated by a new leader."""
        if position > handle.durable_position:
            handle.durable_position = position
        self._stamp_acked()

    def _stamp_acked(self) -> None:
        """Record-lifecycle tracing: EXPORT_ACK is the lifecycle's final
        stage, so a span finishes only once EVERY exporter's DURABLE ack
        covers its position — the min across handles. Finishing on the
        fastest exporter's ack would unindex the span before slower
        exporters dispatch it, and their egress would vanish from the
        trace."""
        tracer = tracing.TRACER
        if tracer is None or not tracer.by_position:
            return
        # broken exporters never dispatch again, so their frozen cursor
        # must not hold every span open forever; backoff handles recover
        # and DO count
        ack = min(
            (h.durable_position for h in self.handles if h.broken is None),
            default=-1,
        )
        if ack >= 0:
            tracer.ack_exported(self.partition_id, ack)

    def _ack_target(self, handle: ExporterHandle, visible) -> int:
        if handle.exporter.MANUAL_ACK:
            return handle.manual_position
        # auto-ack: a successful batch acks its last VISIBLE record, never
        # a trailing hidden admin position — the replicated ack must point
        # at a record the exporter actually saw (a file sink compares its
        # recovered tail against the ack on open, and an ack sitting on a
        # hidden record would false-report an audit hole after restart).
        # An admin-only batch advances the cursor without an ack (an ack
        # record acking only ack records would ping-pong forever). The
        # position comes from the view's COLUMN — no row materializes.
        if len(visible):
            positions = getattr(visible, "positions", None)
            if positions is not None:
                return positions()[-1]
            return visible[-1].position
        return handle.position

    def _run_scheduled(self, now: int) -> None:
        if not self._scheduled:
            return
        due = [fn for at, fn in self._scheduled if at <= now]
        self._scheduled = [(at, fn) for at, fn in self._scheduled if at > now]
        for fn in due:
            try:
                fn()
            except Exception as e:  # noqa: BLE001 - exporter callback
                logger.warning("scheduled exporter callback failed: %r", e)

    # -- observability ------------------------------------------------------
    def _last_visible_commit(self) -> int:
        """Position of the last committed record exporters can SEE (the
        commit position itself usually sits on this plane's own hidden ack
        records — measuring lag/stalls against it reads >=1 forever on a
        fully caught-up exporter and false-warns healthy MANUAL_ACK sinks
        that acked everything visible)."""
        commit = self.log.commit_position
        if commit == self._lv_cache_commit:
            return self._lv_cache
        pos = commit
        base = self.log.base_position
        while pos >= base:
            record = self.log.record_at(pos)
            if record is None or (
                int(record.metadata.value_type) not in _HIDDEN_VALUE_TYPES
            ):
                break
            pos -= 1
        self._lv_cache_commit = commit
        self._lv_cache = pos
        return pos

    def _update_lag(self, handle: ExporterHandle) -> None:
        # gauge resolved once per handle (pump runs on every commit signal
        # plus the retry tick — don't pay the registry lock each time)
        gauge = self._lag_gauges.get(handle.id)
        if gauge is None:
            gauge = GLOBAL_REGISTRY.gauge(
                "exporter_lag",
                "Records behind the commit position, per exporter",
                exporter=handle.id,
                partition=str(self.partition_id),
            )
            self._lag_gauges[handle.id] = gauge
        gauge.set(max(0, self._last_visible_commit() - handle.position))

    def _maybe_warn_stall(self, handle: ExporterHandle, now: int) -> None:
        # "stalled" means NOT advancing the durable position past records
        # it can see: broken, in failure backoff, or a MANUAL_ACK exporter
        # that consumes without confirming (its cursor runs ahead but
        # position stays put — the floor is pinned all the same). Measured
        # against the last VISIBLE record: an exporter acked there is
        # fully caught up even though the raw commit position sits on the
        # trailing hidden ack records.
        behind = self._last_visible_commit() - handle.position
        if behind <= 0:
            return
        floor = self.compaction_floor()
        if handle.position + 1 > floor:
            return  # not the exporter pinning the floor
        if handle.last_advance_ms is None:
            handle.last_advance_ms = now
            return
        if handle.stall_warned or now - handle.last_advance_ms < self.STALL_AFTER_MS:
            return
        handle.stall_warned = True
        count_event(
            "exporter_floor_stalls",
            "Stalled exporters pinning the compaction floor",
        )
        if handle.broken:
            cause = f"broken: {handle.broken}"
        elif handle.failures:
            cause = f"{handle.failures} consecutive failures"
        else:
            # MANUAL_ACK consuming without confirming (or an ack append
            # path that never lands): nothing "failed", progress just
            # never became durable
            cause = "positions never confirmed/durable"
        logger.warning(
            "exporter %r on partition %d is STALLED %d records behind the "
            "last exportable record (%s) and is pinning the compaction "
            "floor at %d — segments cannot be deleted until it recovers",
            handle.id, self.partition_id, behind, cause,
            handle.position + 1,
        )


class ExporterDirectorActor(Actor):
    """Cluster-broker driver: runs the director on its OWN actor, pumped
    on every commit signal plus a periodic retry tick (reference: the
    exporter stream processor runs in its own actor, decoupled from the
    engine's processing actor). Owning the actor is the isolation
    contract's last clause: a custom exporter whose ``export_batch``
    BLOCKS (rather than raises) stalls only this actor — record
    processing, raft, and the other partitions keep running."""

    RETRY_TICK_MS = 100

    def __init__(self, director: ExporterDirector, scheduler) -> None:
        super().__init__(
            f"exporter-{director.node_label or 'p'}-{director.partition_id}"
        )
        self.director = director
        self._scheduler = scheduler
        self._pump_scheduled = False
        self.can_ack = director.can_ack  # tracing's final-stage probe
        self._closing = False
        self._commit_listener = lambda _pos: self.schedule_pump()
        scheduler.submit_actor(self)  # zblint: disable=unobserved-actor-future (boot submit; start failures land in the scheduler failure ring)
        self.director.log.on_commit(self._commit_listener)

    def on_actor_started(self) -> None:
        self._tick()

    def schedule_pump(self) -> None:
        if self._closing or self._pump_scheduled or self.actor is None:
            return
        self._pump_scheduled = True
        self.actor.run(self._pump)

    def _pump(self) -> None:
        self._pump_scheduled = False
        if self._closing:
            return
        self.director.pump()

    def _tick(self) -> None:
        # periodic re-pump: retry backoffs and scheduled exporter
        # callbacks have no commit edge to ride
        if self._closing:
            return
        self.actor.run_delayed(self.RETRY_TICK_MS, self._tick)
        self.schedule_pump()

    def on_actor_closing(self) -> None:
        self.director.close()

    def close(self, wait_s: float = 2.0) -> None:
        """Stop pumping and close the director ON the actor, serialized
        after any in-flight export_batch. Waits briefly so the common
        step-down/shutdown path keeps synchronous close semantics, but a
        blocked exporter cannot hang it past ``wait_s``."""
        if self._closing:
            return
        self._closing = True
        if hasattr(self.director.log, "remove_commit_listener"):
            self.director.log.remove_commit_listener(self._commit_listener)
        done = self._scheduler.close_actor(self)
        try:
            done.join(wait_s)
        except TimeoutError:
            logger.warning(
                "exporter actor %s did not close within %.1fs (a blocked "
                "export_batch?); director close continues in background",
                self.name, wait_s,
            )
