"""In-memory debug exporter (tests / REPL).

Collects every dispatched record. Because cluster leadership churn creates
a FRESH exporter instance per install, records also accumulate into
class-level sinks keyed by exporter id — a chaos test can assert the
at-least-once/in-order/gap-free contract across crash-stop/restart and
leader failover by reading ``InMemoryExporter.sink(<id>)`` (all records
ever exported under that id, in dispatch order) and
``InMemoryExporter.episodes(<id>)`` (one ordered list per exporter
incarnation)."""

from __future__ import annotations

import threading
from typing import Dict, List, Optional

from zeebe_tpu.exporter.base import Exporter, ExporterContext


class InMemoryExporter(Exporter):
    """args: ``fail`` (optional bool: raise from export_batch until
    cleared — the stuck-exporter fixture for compaction-gating tests)."""

    _LOCK = threading.Lock()
    _SINKS: Dict[str, List] = {}
    _EPISODES: Dict[str, List[List]] = {}

    def __init__(self):
        self.exporter_id = ""
        self.records: List = []  # this incarnation's stream, in order
        self.fail = False
        self.opened = False
        self.closed = False
        self.controller = None

    # -- class-level sinks (survive incarnations) ---------------------------
    @classmethod
    def sink(cls, exporter_id: str) -> List:
        with cls._LOCK:
            return list(cls._SINKS.get(exporter_id, []))

    @classmethod
    def episodes(cls, exporter_id: str) -> List[List]:
        with cls._LOCK:
            return [list(e) for e in cls._EPISODES.get(exporter_id, [])]

    @classmethod
    def reset(cls, exporter_id: Optional[str] = None) -> None:
        with cls._LOCK:
            if exporter_id is None:
                cls._SINKS.clear()
                cls._EPISODES.clear()
            else:
                cls._SINKS.pop(exporter_id, None)
                cls._EPISODES.pop(exporter_id, None)

    # -- lifecycle ----------------------------------------------------------
    def configure(self, context: ExporterContext) -> None:
        self.exporter_id = context.exporter_id
        # default keeps a directly-set flag (tests hand the instance in)
        self.fail = bool((context.args or {}).get("fail", self.fail))

    def open(self, controller) -> None:
        self.opened = True
        self.controller = controller
        with self._LOCK:
            self._SINKS.setdefault(self.exporter_id, [])
            self._EPISODES.setdefault(self.exporter_id, []).append(self.records)

    def export_batch(self, records) -> None:
        if self.fail:
            raise RuntimeError(f"injected failure in exporter {self.exporter_id!r}")
        # debug sink = an API edge: iterating the (possibly columnar)
        # view materializes rows here, deliberately — the asserts need
        # real Record objects
        rows = list(records)
        self.records.extend(rows)
        with self._LOCK:
            self._SINKS.setdefault(self.exporter_id, []).extend(rows)

    def close(self) -> None:
        self.closed = True

    # -- test helpers -------------------------------------------------------
    def positions(self) -> List[int]:
        return [r.position for r in self.records]
