"""Metrics exporter: per-ValueType/intent record counts and commit→export
latency histograms, fed into the process-global metrics registry so they
appear on every broker's ``/metrics`` endpoint and metrics file
(``render_with_global``).

Reference analogue: the reference's metrics exporter feeding the
prometheus stack (docker/compose + MetricsFileWriter); here the exporter
IS the pipeline — no sidecar."""

from __future__ import annotations

from typing import Optional

from zeebe_tpu.exporter.base import Exporter, ExporterContext, intent_name
from zeebe_tpu.protocol.enums import RecordType, ValueType
from zeebe_tpu.runtime.metrics import GLOBAL_REGISTRY


class MetricsExporter(Exporter):
    """args: ``latency_buckets`` (optional list of upper bounds, ms)."""

    def __init__(self, registry=None):
        self.registry = registry or GLOBAL_REGISTRY
        self.partition_id = 0
        self.clock = None
        self.buckets: Optional[tuple] = None
        # metric handles cached per (record_type, value_type, intent):
        # resolving through the registry lock per RECORD would put two
        # mutex round-trips on the egress hot path (same fix as the
        # director's _lag_gauges)
        self._counters: dict = {}
        self._hists: dict = {}

    def configure(self, context: ExporterContext) -> None:
        self.partition_id = context.partition_id
        self.clock = context.clock
        raw = (context.args or {}).get("latency_buckets")
        if raw:
            self.buckets = tuple(float(b) for b in raw)

    def export_batch(self, records) -> None:
        from zeebe_tpu.runtime.metrics import Histogram

        now = self.clock() if self.clock is not None else None
        # columnar egress: this sink needs only the metadata scalar
        # columns — it never materializes a single Record object from a
        # columnar view (the wave stays the currency through this edge)
        if hasattr(records, "value_types"):
            vts = records.value_types()
            rts = records.record_types()
            intents = records.intents()
            timestamps = records.timestamps()
        else:  # plain record lists (tests, custom drivers)
            vts = [int(r.metadata.value_type) for r in records]
            rts = [int(r.metadata.record_type) for r in records]
            intents = [int(r.metadata.intent) for r in records]
            timestamps = [r.timestamp for r in records]
        for row in range(len(vts)):
            vt = vts[row]
            rt = rts[row]
            intent = intents[row]
            key = (rt, vt, intent)
            counter = self._counters.get(key)
            if counter is None:
                vt_name = ValueType(vt).name \
                    if vt in ValueType._value2member_map_ else str(vt)
                rt_name = RecordType(rt).name \
                    if rt in RecordType._value2member_map_ else str(rt)
                labels = {
                    "value_type": vt_name,
                    "intent": intent_name(vt, intent),
                    "partition": str(self.partition_id),
                }
                counter = self.registry.counter(
                    "exported_records_total",
                    "Committed records seen by the metrics exporter",
                    record_type=rt_name,
                    **labels,
                )
                self._counters[key] = counter
                self._hists[key] = self.registry.histogram(
                    "export_latency_ms",
                    "Record timestamp → export latency",
                    buckets=self.buckets or Histogram.DEFAULT_BUCKETS,
                    record_type=rt_name,
                    **labels,
                )
            counter.inc()
            ts = timestamps[row]
            if now is not None and ts >= 0:
                self._hists[key].observe(max(0, now - ts))
