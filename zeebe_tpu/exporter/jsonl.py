"""Rotating JSONL audit exporter.

One json document per committed record, append-only files rotated by size
(``audit-p<partition>-<first-position>.jsonl``). The file set is an exact,
replayable image of the partition's record stream:

- **Exactly-once in the file** despite at-least-once delivery: on open the
  exporter scans its newest file for the last durably written position and
  skips re-delivered records at or below it (the broker resumes export
  from the last *acked* position after a crash, which may be behind the
  file tail).
- **Torn-tail tolerant**: a crash mid-line leaves a trailing partial json
  line; open() truncates the file back to the last complete line before
  appending (the same recovery contract as the log storage's torn-tail
  scan).

``read_audit_docs`` replays a directory back into the document sequence —
used by the CI smoke step to assert file⇔log parity.
"""

from __future__ import annotations

import json
import logging
import os
from typing import Any, Dict, List, Optional

from zeebe_tpu.exporter.base import Exporter, ExporterContext, record_to_doc

DEFAULT_ROTATE_BYTES = 64 * 1024 * 1024


class JsonlExporter(Exporter):
    """args: ``path`` (directory, required), ``rotate_bytes`` (optional),
    ``fsync`` (optional bool, default false — flush-per-batch only)."""

    def __init__(self):
        self.directory: Optional[str] = None
        self.rotate_bytes = DEFAULT_ROTATE_BYTES
        self.fsync = False
        self.prefix = "audit"
        self.partition_id = 0
        self._file = None
        self._file_size = 0
        self._last_position = -1
        self._log = None

    # -- lifecycle ----------------------------------------------------------
    def configure(self, context: ExporterContext) -> None:
        args = context.args or {}
        path = args.get("path")
        if not path:
            raise ValueError(
                f"jsonl exporter {context.exporter_id!r}: args.path "
                "(audit directory) is required"
            )
        self.directory = str(path)
        self.rotate_bytes = int(args.get("rotate_bytes", DEFAULT_ROTATE_BYTES))
        self.fsync = bool(args.get("fsync", False))
        self.prefix = str(args.get("prefix", "audit"))
        self.partition_id = context.partition_id
        self._log = context.log()

    def open(self, controller) -> None:
        os.makedirs(self.directory, exist_ok=True)
        files = self._files()
        if files:
            self._recover(files)
        # else: first record opens the first file (named by its position),
        # and _last_position stays -1 — so a WIPED directory under an
        # acked position >= 0 is a hole exactly like a lost tail
        if (
            controller is not None
            and getattr(controller, "acked_position", -1) > self._last_position
        ):
            # the broker's ack (fsync'd raft log) outran the audit lines
            # it covers: the un-fsynced tail was lost with the page cache
            # to an OS/power crash, or the audit directory itself was
            # wiped/unmounted. The director resumes ABOVE the file tail
            # and will never re-deliver the gap — report it, do not
            # silently present a holed audit trail as complete
            from zeebe_tpu.runtime.metrics import count_event

            count_event(
                "exporter_audit_holes",
                "JSONL audit files missing records below the durable "
                "ack (un-fsynced tail lost to an OS crash, or audit "
                "directory lost)",
            )
            self._log.error(
                "audit trail HOLE: acked position %d but the recovered "
                "file tail is %d — records between were lost with the "
                "page cache or the audit directory (set args.fsync=true "
                "to make audit lines durable before they are acked)",
                controller.acked_position, self._last_position,
            )

    def _recover(self, files) -> None:
        # a crash between rotation and the new file's first flush leaves
        # the newest file empty (or torn down to empty) — walk back until
        # a complete line is found, else the dedup tail is -1 and
        # already-persisted records in older files re-write
        for path in reversed(files):
            self._last_position = _recover_file_tail(path)
            if self._last_position >= 0:
                break
        newest = files[-1]
        self._file = self._open_audit(newest)
        self._file_size = os.path.getsize(newest)

    def close(self) -> None:
        if self._file is not None:
            self._file.flush()
            try:
                os.fsync(self._file.fileno())
            except OSError:
                pass
            self._file.close()
            self._file = None

    # -- export -------------------------------------------------------------
    def export_batch(self, records) -> None:
        """Serialize the WHOLE batch into one buffer and issue ONE
        ``write`` + flush per batch (one per file when rotation splits
        it) — per-record writes were a syscall per record on the egress
        hot path. Re-delivered rows (crash resume below the recovered
        file tail) are skipped via the position COLUMN, before any row
        materializes; rotation byte-accounting is unchanged (a record
        lands in the current file whenever its pre-write size is below
        ``rotate_bytes``, exactly like the per-record path did)."""
        positions_col = getattr(records, "positions", None)
        positions = (
            positions_col() if positions_col is not None
            else [r.position for r in records]
        )
        last = self._last_position
        buffer: list = []

        def flush_buffer() -> None:
            if buffer:
                self._file.write("".join(buffer))
                buffer.clear()

        wrote = False
        try:
            for i, position in enumerate(positions):
                if position <= last:
                    continue  # re-delivery below the file tail (crash resume)
                if self._file is None or self._file_size >= self.rotate_bytes:
                    flush_buffer()  # lines belong to the file they sized into
                    self._rotate(position)
                line = json.dumps(
                    record_to_doc(records[i]), separators=(",", ":"),
                    sort_keys=True,
                )
                buffer.append(line + "\n")
                # default ensure_ascii escapes all non-ASCII, so len(line)
                # IS the on-disk byte count and rotate_bytes holds exactly
                self._file_size += len(line) + 1
                last = position
                wrote = True
        finally:
            # a mid-batch failure persists the lines already serialized,
            # exactly like the per-record path (the director re-delivers
            # from the last ack; the dedup tail skips these)
            flush_buffer()
            self._last_position = last
        if wrote:
            self._file.flush()
            if self.fsync:
                os.fsync(self._file.fileno())

    # -- files --------------------------------------------------------------
    def _file_name(self, first_position: int) -> str:
        return os.path.join(
            self.directory,
            f"{self.prefix}-p{self.partition_id}-{first_position:012d}.jsonl",
        )

    def _files(self) -> List[str]:
        return _audit_files(self.directory, self.partition_id, self.prefix)

    def _rotate(self, first_position: int) -> None:
        if self._file is not None:
            self._file.flush()
            try:
                os.fsync(self._file.fileno())
            except OSError:
                pass
            self._file.close()
        path = self._file_name(first_position)
        self._file = self._open_audit(path)
        self._file_size = os.path.getsize(path)

    def _open_audit(self, path: str):
        """Open an audit file for appending — the seam tests wrap to count
        syscall-level writes (the batched ``export_batch`` contract: one
        write per batch per file)."""
        return open(path, "a", encoding="utf-8")


def _audit_files(directory: str, partition_id: int, prefix: str) -> List[str]:
    """The partition's audit files, oldest → newest (one listing shared by
    the exporter and the replay verifier so the name scheme can't drift)."""
    want = f"{prefix}-p{partition_id}-"
    try:
        names = sorted(
            n for n in os.listdir(directory)
            if n.startswith(want) and n.endswith(".jsonl")
        )
    except OSError:
        return []
    return [os.path.join(directory, n) for n in names]


# tail-scan window: widened (doubled) until a valid line is found, so a
# leadership install reads KBs of a near-rotation-size file, not all of it
_TAIL_CHUNK = 64 * 1024


def _recover_file_tail(path: str) -> int:
    """Validate an audit file's tail: truncate torn/corrupt TRAILING lines
    (crash mid-write) and return the last complete line's position (-1
    when none survives). A corrupt line with content after it is NOT a
    torn tail — it is bitrot, and the valid lines following it are intact
    evidence that `read_audit_docs` is designed to detect and raise on:
    those are preserved (reported, never truncated). Scans backwards in
    chunks — the newest file can be ~rotate_bytes large, and slurping +
    json-parsing all of it on every leadership install costs seconds of
    CPU per partition."""
    size = os.path.getsize(path)
    chunk = _TAIL_CHUNK
    while True:
        start = max(0, size - chunk)
        with open(path, "rb") as f:
            f.seek(start)
            data = f.read()
        offset = 0
        if start > 0:
            # the window starts mid-line: lines before the first newline
            # boundary belong to the unscanned (assumed-valid) prefix
            nl = data.find(b"\n")
            if nl < 0:
                chunk *= 2
                continue
            offset = nl + 1
        keep = start + offset
        last_position = -1
        bitrot = False
        while offset < len(data):
            nl = data.find(b"\n", offset)
            if nl < 0:
                break  # trailing torn fragment: cut at `keep` below
            line = data[offset : nl]
            try:
                doc = json.loads(line.decode("utf-8"))
                last_position = int(doc["position"])
            except (ValueError, KeyError, TypeError, UnicodeDecodeError):
                if nl + 1 < len(data):
                    # complete corrupt line with content AFTER it: bitrot,
                    # not a torn tail — preserve it (and everything after)
                    # and keep scanning for the dedup tail
                    bitrot = True
                    keep = start + nl + 1
                    offset = nl + 1
                    continue
                break  # final complete-but-corrupt line: torn-tail, cut
            keep = start + nl + 1
            offset = nl + 1
        if last_position < 0 and start > 0:
            chunk *= 2  # no valid line in this window: widen
            continue
        if bitrot:
            from zeebe_tpu.runtime.metrics import count_event

            count_event(
                "exporter_audit_bitrot",
                "Audit files with a corrupt non-trailing line (bitrot "
                "preserved on disk; read_audit_docs raises on it)",
            )
            logging.getLogger(__name__).error(
                "audit file %s has a corrupt NON-trailing line (bitrot, "
                "not a torn tail) — preserved for forensics; replay via "
                "read_audit_docs will raise on it", os.path.basename(path),
            )
        if keep < size:
            with open(path, "r+b") as f:
                f.truncate(keep)
        return last_position


def read_audit_docs(directory: str, partition_id: int = 0,
                    prefix: str = "audit") -> List[Dict[str, Any]]:
    """Replay a JSONL audit directory into the ordered document list.
    Only the NEWEST file may end in a torn line (crash mid-write, skipped
    exactly like open()); a corrupt line anywhere else is bitrot, not a
    torn tail — raise rather than return a sequence with a silent hole."""
    docs: List[Dict[str, Any]] = []
    paths = _audit_files(directory, partition_id, prefix)
    for path in paths:
        with open(path, encoding="utf-8") as f:
            for line in f:
                line = line.strip()
                if not line:
                    continue
                try:
                    docs.append(json.loads(line))
                except ValueError:
                    # only a TRAILING partial line of the newest file is a
                    # torn tail; a corrupt line with anything after it (or
                    # in an older file) is bitrot — raise, don't return a
                    # silently truncated sequence
                    if path == paths[-1] and not any(l.strip() for l in f):
                        break
                    raise ValueError(
                        f"corrupt audit line in {os.path.basename(path)!r} "
                        "(content follows it or an older file: bitrot, "
                        "not a torn tail)"
                    )
    return docs
